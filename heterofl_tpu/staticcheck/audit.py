"""Front 1: the compiled-program auditor.

Lowers every flagship round-program variant -- masked + grouped engines x
replicated/sharded/streaming-cohort (masked) and span/slices/streaming
(grouped) placements x ``superstep_rounds`` in {1, 8} -- on a CPU mesh and
statically enforces:

(a) **no host callbacks** (``pure_callback``/``io_callback``/
    ``debug_callback``) and **no f64** anywhere in a round program;
(b) **donation coverage** -- every donated leaf is consumed by input-output
    aliasing in the optimized HLO, and JAX "donated buffers were not
    usable" warnings are promoted to audit failures (silent memory
    doubling);
(c) **collectives budget** -- psum binds are counted per program and the
    fused grouped round must perform EXACTLY ONE global psum (the PR 2
    invariant), with every collective axis resolvable in the mesh;
(d) **recompile hazard** -- two dispatches with fresh-but-identical host
    inputs leave ``engine.program_cache_size()`` unchanged (weak-type /
    python-scalar cache-key leaks recompile the ~40s flagship program);
(e) **FLOP budget** -- ``cost_analysis()`` FLOPs per level program are
    checked against the analytic shares from
    :func:`~..fed.core.level_flop_shares`;
(f) **wire budget** (ISSUE 7, :mod:`.wire`) -- every collective bind is
    priced from its operand avals and each fused training round must move
    EXACTLY one dense global reduction of the level-a footprint
    (``sum(param_bytes) + count_bytes``, per-level slices for the grouped
    K=1 programs), matched by equality against
    :func:`~..fed.core.level_byte_table`;
(g) **HBM budget** (ISSUE 7, :mod:`.memory`) -- ``memory_analysis()``
    temp/argument/output bytes are required fields held to analytic
    ceilings, with donation-savings accounting;
(h) **reshard detector** (ISSUE 7) -- zero data-movement collectives, in
    the jaxpr (``all_to_all``/``ppermute``) and in the optimized HLO
    (GSPMD-introduced ``all-to-all``/``collective-permute``);
(i) **wire codecs** (ISSUE 8, :mod:`..compress`) -- every lossy codec's
    fused superstep still binds EXACTLY one global psum, its compressed
    payload matches :func:`~..fed.core.level_codec_byte_table` by equality
    (the packed psum operand avals ARE the wire format), the error-feedback
    residual carry is the ONLY donated input (both engines pin resid-only
    donation around an XLA:CPU executable-serialization bug; see
    parallel.round_engine._WireCodecCarry), and the analytic flagship int8
    payload stays <= 25% of the dense baseline (``wire-frontier``);
(j) **telemetry** (ISSUE 10, :mod:`..obs`) -- the ``telemetry='on'``
    program variants carry the in-program health probes at ZERO wire cost:
    same single global psum, same wire bytes by equality, full donation,
    and the k1 step body inside the unchanged kernel budget;
(l) **cohort histograms** (ISSUE 12, :mod:`..obs.hist`) -- the
    ``telemetry='hist'`` variants carry the fixed-bucket cohort
    histograms next to the scalar probes at the SAME budgets: one global
    psum, wire bytes by equality (dense AND int8-codec), full/resid-only
    donation, unchanged k1 step body;
(k) **sampler** (ISSUE 11, :mod:`..fed.sampling`) -- both sampler kinds'
    in-jit draws audited as programs (the legacy ``perm`` superstep stays
    a pinned variant next to the default ``prp`` one, same psum/wire/
    donation/HBM budgets), plus the stream-consistency check
    (:func:`sampler_stream_check`: in-jit == host bitwise, all-ones
    availability == uniform cohort, exact PRP bijection) and sampler
    entries in the recompile-hazard matrix.

Widths: the default audit config keeps the flagship *structure* (5-level
a1-e1 fix mix, both engines, both placements, K in {1, 8}) at test-scale
widths so the whole matrix lowers+compiles in tens of seconds on a CPU --
every property above except the FLOP-share tolerance is width-independent.
``flagship=True`` swaps in the full CIFAR-10 ResNet-18 widths, where the
conv terms dominate and the share tolerance tightens to 2%
(``FLAGSHIP_FLOP_TOL``); at tiny widths the width-independent per-step
costs (RNG, data prep, slicing) are a large fraction of the smallest
levels, so the default tolerance is ``SMALL_FLOP_TOL`` and a strict
monotonicity check carries the regression-catching weight instead.
"""

from __future__ import annotations

import fnmatch
import math
import os
import warnings
from typing import Any, Dict, List, Optional, Set, Tuple

import numpy as np

from .jaxpr_walk import (aliased_outputs, count_collectives, count_psum_joint,
                         count_psum_over, donation_marks, find_callbacks,
                         find_f64, find_reshards, random_bind_files,
                         reshard_ops, scan_body_kernel_count)
from .memory import (analytic_budget, check_memory, collect_memory,
                     donation_accounting)
from .report import AuditReport, Finding, ProgramReport
from .wire import check_wire, program_wire

#: FLOP-share tolerance (max relative error of measured vs analytic level
#: shares).  2% holds where conv/matmul FLOPs dominate (flagship widths);
#: the tiny-width gate config runs the same check at a documented looser
#: bound plus strict share monotonicity.
FLAGSHIP_FLOP_TOL = 0.02
SMALL_FLOP_TOL = 0.45

#: the PR 2 invariant: one global psum per (fused) TRAINING round program
PSUM_BUDGET = 1

#: the ISSUE 4 eval-phase budget: the fused sBN moment reduction + the
#: Global metric reduction, each ONE joint (clients, data) psum bind per
#: eval point's trace (the per-user Local sums stay sharded -- no
#: collective)
EVAL_PSUM_BUDGET = 2

#: the ISSUE 5 hot-step kernel budget: max fusion launches per iteration of
#: the LOCAL-STEP scan body (optimized HLO, CPU-mesh lowering) for the two
#: programs on the level-a critical path.  Sized from the fused-epilogue
#: bodies (masked 55, grouped level-a 61 at the audit widths; the flagship
#: ResNet-18 body drops 415 -> 304) with headroom, and BELOW the
#: reference-op-chain bodies (72 / 76) -- so an op-soup regression
#: (un-hoisting the masks + un-fusing the epilogue, or any new per-leaf
#: chain of comparable size) fails the audit the same way a second psum
#: would.
#: (re-pinned with ISSUE 17: the current XLA:CPU build fuses the SAME
#: 159-instruction masked step body into 69 kernels where the previous
#: build produced 55 -- verified against the pristine pre-ISSUE tree, so
#: it is toolchain drift, not an op-soup regression.  Headroom stays +5
#: as before; the reference-op-chain bodies drift proportionally and
#: remain above the budget.)
STEP_BODY_FUSION_BUDGET = {
    "masked/replicated/k1": 74,
    "grouped/span/level-1/k1": 66,
    # ISSUE 10: the health probes live at ROUND level (post-psum), never
    # inside the local-step scan body -- the telemetry-on k1 program is
    # held to the SAME step-body budget as its dense twin
    "masked/replicated/k1-telemetry": 74,
    # ISSUE 12: the cohort histograms are round-level bucketing over the
    # already-emitted per-slot metric sums -- same unchanged step body
    "masked/replicated/k1-hist": 74,
    # ISSUE 15: the quarantine gate lives at ROUND level (after local
    # training, folded into the counted sums before the psum), never
    # inside the local-step scan body -- same unchanged step body
    "masked/replicated/k1-quarantine": 74,
}


def default_audit_cfg(flagship: bool = False) -> Dict[str, Any]:
    """The audit config: flagship federation structure (5-level a1-e1 fix
    mix over 10 users, iid, BN) at test widths (``flagship=True``: full
    CIFAR-10 ResNet-18 widths)."""
    from .. import config as C

    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name("1_10_0.5_iid_fix_a1-b1-c1-d1-e1_bn_1_1")
    cfg["data_name"] = "CIFAR10" if flagship else "MNIST"
    cfg["model_name"] = "resnet18" if flagship else "conv"
    cfg["synthetic"] = True
    cfg = C.process_control(cfg)
    if not flagship:
        cfg["conv"] = {"hidden_size": [8, 16]}
    cfg["classes_size"] = 10
    return cfg


def build_setup(flagship: bool = False, seed: int = 0) -> Dict[str, Any]:
    """cfg + synthetic client-stacked data + model/params + 8-row CPU mesh.

    Needs >= 5 mesh rows so the slices placement exists (tests/CLI force an
    8-device host platform before jax initialises)."""
    import jax

    from ..data import (fetch_dataset, label_split_masks, split_dataset,
                        stack_client_shards)
    from ..models import make_model
    from ..parallel import make_mesh

    cfg = default_audit_cfg(flagship)
    users = cfg["num_users"]
    n_train = 2000 if flagship else 400
    ds = fetch_dataset(cfg["data_name"], synthetic=True, seed=seed,
                       synthetic_sizes={"train": n_train, "test": 100})
    rng = np.random.default_rng(seed)
    split, lsplit = split_dataset(ds, users, "iid", rng, classes_size=10)
    x, y, m = stack_client_shards(ds["train"].data, ds["train"].target,
                                  split["train"], list(range(users)))
    lm = label_split_masks(lsplit, users, 10)
    data = (x, y, m, lm)
    model = make_model(cfg)
    params = model.init(jax.random.key(seed))
    n_dev = min(8, len(jax.devices()))
    if n_dev < 5:
        raise RuntimeError(
            f"staticcheck audit needs >= 5 devices for the slices placement "
            f"(have {n_dev}); set "
            f"XLA_FLAGS=--xla_force_host_platform_device_count=8 before jax "
            f"initialises (the CLI and tests/conftest.py both do)")
    mesh = make_mesh(n_dev, 1)

    # eval operands for the eval-fused superstep variants (ISSUE 4), staged
    # through the DRIVER'S OWN assembly so the audited operand layout is
    # exactly the one the driver commits
    from ..entry.common import stage_eval_operands

    sbn, local, glob = stage_eval_operands(cfg, ds["train"], ds["test"],
                                           split["test"], lm)
    eval_data = {"sbn": sbn, "local": local, "global": glob}

    # streaming population store (ISSUE 6): the same split as the eager
    # stacks, so the streamed audit variants stage bit-identical cohorts
    from ..parallel import ClientStore

    store = ClientStore.from_split(ds["train"].data, ds["train"].target,
                                   split["train"], lsplit, 10)

    # analytic per-level byte/shape table (ISSUE 7): the wire and HBM
    # budgets' source of truth -- the SAME table bench.py's extra.wire reads
    from ..fed.core import level_byte_table

    return {"cfg": cfg, "data": data, "model": model, "params": params,
            "mesh": mesh, "flagship": flagship, "key": jax.random.key(seed),
            "lr": np.float32(0.05), "users": users, "eval_data": eval_data,
            "store": store, "byte_table": level_byte_table(cfg)}


def fused_eval_for(setup):
    """One :class:`~..parallel.evaluation.FusedEval` per setup (memoised):
    the eval-fused audit targets and the recompile check share its committed
    operands, exactly like the driver does."""
    if "fused_eval" not in setup:
        from ..parallel.evaluation import Evaluator

        ev = Evaluator(setup["model"], setup["cfg"], setup["mesh"], seed=0)
        ed = setup["eval_data"]
        setup["fused_eval"] = ev.fused(sbn_batches=ed["sbn"],
                                       local_eval=ed["local"],
                                       global_eval=ed["global"])
    return setup["fused_eval"]


def _sds(shape: Tuple[int, ...], dtype=np.int32):
    import jax

    return jax.ShapeDtypeStruct(shape, dtype)


def _args_bytes(args) -> int:
    """Total byte footprint of a program's example arguments (arrays and
    ShapeDtypeStructs alike) -- the staged-operand term of the analytic HBM
    bound.  PRNG-key leaves have an extended dtype without an itemsize; a
    key is one (2,)-uint32 cell per element."""
    import jax

    total = 0
    for leaf in jax.tree_util.tree_leaves(args):
        shape = getattr(leaf, "shape", None)
        dt = getattr(leaf, "dtype", None)
        if shape is None or dt is None:
            continue
        try:
            total += int(np.prod(shape)) * np.dtype(dt).itemsize
        except TypeError:
            total += int(np.prod(shape)) * 8
    return total


def _mem_expect(byte_table: Dict[float, Dict[str, int]], rate: float,
                clients_per_device: int) -> Dict[str, int]:
    """The per-program analytic-HBM-bound inputs the target builders embed
    in ``expect['mem']``: the GLOBAL parameter footprint (the carry every
    program holds, donated or not), the program's own level activation
    bytes, and its per-device client concurrency."""
    top = max(byte_table)
    return {"param_bytes": byte_table[top]["param_bytes"],
            "activation_bytes": byte_table[rate]["activation_bytes"],
            "clients_per_device": int(clients_per_device)}


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


# ---------------------------------------------------------------------------
# the program matrix
# ---------------------------------------------------------------------------

def _masked_targets(setup) -> List[Tuple[str, Any, Tuple, Dict[str, Any]]]:
    """(name, jitted program, example args, expectations) for the masked
    engine: replicated + sharded placements x K in {1, 8}.  Arg shapes
    mirror the engines' own staging math (slot padding/bucketing)."""
    import jax

    from ..parallel import RoundEngine, shard_client_data
    from ..utils.optim import make_traced_lr_fn

    cfg, model, mesh = setup["cfg"], setup["model"], setup["mesh"]
    params, key, lr = setup["params"], setup["key"], setup["lr"]
    users = setup["users"]
    n_dev = mesh.shape["clients"]
    n_leaves = len(jax.tree_util.tree_leaves(params))
    k = 8
    targets = []

    # the masked engine trains the full global model under masks, so every
    # program's single reduction moves the LEVEL-A (global) footprint:
    # sums + count masks, both param-shaped f32 (ISSUE 7 wire budget)
    bt = setup["byte_table"]
    top = max(bt)
    wire = bt[top]["wire_bytes"]

    def mem(cpd: int) -> Dict[str, int]:
        return _mem_expect(bt, top, cpd)

    # replicated
    eng = RoundEngine(model, cfg, mesh)
    eng._lr_fn = make_traced_lr_fn(cfg)
    fix = (eng.fix_rates,) if eng.fix_rates is not None else ()
    data = tuple(setup["data"]) + fix
    slots = users + ((-users) % n_dev)
    targets.append((
        "masked/replicated/k1", eng._build_train(),
        (params, key, lr, _sds((slots,)), _sds((slots,))) + data,
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(_ceil_div(slots, n_dev))}))
    a = int(math.ceil(cfg["frac"] * users))
    targets.append((
        "masked/replicated/k8",
        eng._build_superstep(k, _ceil_div(a, n_dev), True, num_active=a),
        (params, key, np.int32(1)) + data,
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(_ceil_div(a, n_dev))}))
    # sampler variants (ISSUE 11): the default engine above draws its
    # cohort in-jit from the PRP index map (cfg default sampler='prp'); the
    # legacy full-permutation stream stays an audited program too -- same
    # psum/wire/donation/HBM budgets, because the draw is round-level
    # integer work that must never touch a collective or the step body
    eng_perm = RoundEngine(model, dict(cfg, sampler="perm"), mesh)
    eng_perm._lr_fn = make_traced_lr_fn(cfg)
    targets.append((
        "masked/replicated/k8-perm",
        eng_perm._build_superstep(k, _ceil_div(a, n_dev), True, num_active=a),
        (params, key, np.int32(1)) + data,
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(_ceil_div(a, n_dev))}))
    # eval-fused variants (ISSUE 4): the ACCEPTANCE cadence eval_interval=1
    # (every round evaluates; the eval core is traced once per eval point,
    # so the joint-psum budget scales with k) and the boundary cadence
    # eval_interval=K (one eval point)
    fe = fused_eval_for(setup)
    targets.append((
        "masked/replicated/k8-eval1",
        eng._build_superstep(k, _ceil_div(a, n_dev), True, num_active=a,
                             eval_mask=(True,) * k, fused_eval=fe),
        (params, key, np.int32(1)) + data + tuple(fe.ops),
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "psum_eval": EVAL_PSUM_BUDGET * k, "mem": mem(_ceil_div(a, n_dev))}))
    targets.append((
        "masked/replicated/k8-eval8",
        eng._build_superstep(k, _ceil_div(a, n_dev), True, num_active=a,
                             eval_mask=(False,) * (k - 1) + (True,),
                             fused_eval=fe),
        (params, key, np.int32(1)) + data + tuple(fe.ops),
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "psum_eval": EVAL_PSUM_BUDGET, "mem": mem(_ceil_div(a, n_dev))}))

    # streaming cohort superstep (ISSUE 6): the cohort's data stacks ride
    # the scan xs; the program never sees the population.  The staged
    # cohort's REAL committed arrays are the example args (audit only
    # traces/lowers), so the audited layout is the engine's own staging.
    from ..fed.core import superstep_user_schedule

    sched = superstep_user_schedule(key, 1, k, users, a)
    coh = eng.stage_cohort(setup["store"], sched)
    targets.append((
        "masked/stream/k8",
        eng._build_superstep(k, coh.per_dev, False, num_active=coh.a,
                             streaming=True),
        (params, key, np.int32(1), coh.sched) + tuple(coh.data) + fix,
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(coh.per_dev)}))
    targets.append((
        "masked/stream/k8-eval1",
        eng._build_superstep(k, coh.per_dev, False, num_active=coh.a,
                             eval_mask=(True,) * k, fused_eval=fe,
                             streaming=True),
        (params, key, np.int32(1), coh.sched) + tuple(coh.data) + fix
        + tuple(fe.ops),
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "psum_eval": EVAL_PSUM_BUDGET * k, "mem": mem(coh.per_dev)}))

    # sharded: per-user stacks device-sharded over the clients axis
    eng_sh = RoundEngine(model, dict(cfg, data_placement="sharded"), mesh)
    eng_sh._lr_fn = make_traced_lr_fn(cfg)
    data_sh = shard_client_data(mesh, setup["data"]) + fix
    per = _ceil_div(users, n_dev)
    slots_sh = per * n_dev  # every device owns at most `per` active users
    targets.append((
        "masked/sharded/k1", eng_sh._build_train(),
        (params, key, lr, _sds((slots_sh,)), _sds((slots_sh,))) + data_sh,
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(per)}))
    targets.append((
        "masked/sharded/k8", eng_sh._build_superstep(k, per, False),
        (params, key, np.int32(1), _sds((k, slots_sh)), _sds((k, slots_sh)))
        + data_sh,
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(per)}))
    targets.append((
        "masked/sharded/k8-eval1",
        eng_sh._build_superstep(k, per, False, eval_mask=(True,) * k,
                                fused_eval=fe),
        (params, key, np.int32(1), _sds((k, slots_sh)), _sds((k, slots_sh)))
        + data_sh + tuple(fe.ops),
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "psum_eval": EVAL_PSUM_BUDGET * k, "mem": mem(per)}))
    return targets


def _grouped_targets(setup) -> Tuple[List, Dict[str, float], Any]:
    """Targets for the grouped engine (span + slices x K in {1, 8} plus the
    combine), the span per-level program names by rate (the FLOP-budget
    check reads their measured flops), and the slices engine."""
    import jax

    from ..parallel import GroupedRoundEngine
    from ..parallel.grouped import _bucket_pow2
    from ..utils.optim import make_traced_lr_fn

    cfg, mesh = setup["cfg"], setup["mesh"]
    params, key, lr = setup["params"], setup["key"], setup["lr"]
    n_dev = mesh.shape["clients"]
    n_leaves = len(jax.tree_util.tree_leaves(params))
    data = tuple(setup["data"])
    k = 8
    per_level = 2  # 10 users over 5 levels, all active: 2 clients per level

    grp = GroupedRoundEngine(cfg, mesh)
    grp._lr_fn = make_traced_lr_fn(cfg)
    level_rates = sorted(grp.levels, reverse=True)
    targets, level_prog_names = [], {}

    # wire budgets (ISSUE 7): a per-level program psums its SLICED sums +
    # counts (the embed to global shape happens after the reduction), so its
    # payload is that level's 2 x param_bytes; the fused superstep joins the
    # embedded level partials in one GLOBAL (level-a footprint) reduction,
    # exactly like the masked engine
    bt = setup["byte_table"]
    top = max(bt)
    wire_top = bt[top]["wire_bytes"]

    slots = _bucket_pow2(_ceil_div(per_level, n_dev)) * n_dev
    for rate in level_rates:
        name = f"grouped/span/level-{rate:g}/k1"
        level_prog_names[rate] = name
        targets.append((
            name, grp._level_prog(rate, slots),
            (params, key, lr, _sds((slots,))) + data,
            {"donated": 0, "psum": PSUM_BUDGET,
             "wire_bytes": bt[rate]["wire_bytes"],
             "mem": _mem_expect(bt, rate, _ceil_div(slots, n_dev))}))
    psds = jax.tree_util.tree_map(
        lambda v: _sds(v.shape, v.dtype), dict(params))
    targets.append((
        "grouped/span/combine", grp._combine_prog(len(level_rates)),
        (params, [psds] * len(level_rates), [psds] * len(level_rates)),
        {"donated": n_leaves, "psum": 0, "wire_bytes": 0,
         "mem": _mem_expect(bt, top, 0)}))
    per_dev = _bucket_pow2(_ceil_div(per_level, n_dev))
    targets.append((
        "grouped/span/k8-fused", grp._superstep_prog(k, per_dev, "span"),
        (params, key, np.int32(1),
         _sds((k, len(level_rates), per_dev * n_dev))) + data,
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire_top,
         "mem": _mem_expect(bt, top, per_dev)}))
    fe = fused_eval_for(setup)
    targets.append((
        "grouped/span/k8-eval1-fused",
        grp._superstep_prog(k, per_dev, "span", eval_mask=(True,) * k,
                            fused_eval=fe),
        (params, key, np.int32(1),
         _sds((k, len(level_rates), per_dev * n_dev))) + data + tuple(fe.ops),
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire_top,
         "psum_eval": EVAL_PSUM_BUDGET * k,
         "mem": _mem_expect(bt, top, per_dev)}))

    # streaming cohort superstep (ISSUE 6): level-grouped cohort stacks as
    # scan xs, staged through the engine's own cohort pipeline
    from ..fed.core import superstep_rate_schedule, superstep_user_schedule

    a_stream = cfg["num_users"]  # every user active: all levels populated
    sched_st = superstep_user_schedule(key, 1, k, cfg["num_users"], a_stream)
    rates_st = superstep_rate_schedule(key, 1, k, cfg, sched_st)
    coh = grp.stage_cohort(setup["store"], sched_st, rates_st)
    targets.append((
        "grouped/stream/span/k8",
        grp._superstep_prog(k, coh.per_dev, "span", streaming=True),
        (params, key, np.int32(1), coh.sched) + tuple(coh.data),
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire_top,
         "mem": _mem_expect(bt, top, coh.per_dev)}))

    grp_sl = GroupedRoundEngine(dict(cfg, level_placement="slices"), mesh)
    grp_sl._lr_fn = make_traced_lr_fn(cfg)
    if grp_sl.level_placement == "slices":
        for rate in level_rates:
            srange = grp_sl._slices[rate]
            rows = srange[1] - srange[0]
            slots_l = _bucket_pow2(_ceil_div(per_level, rows)) * rows
            targets.append((
                f"grouped/slices/level-{rate:g}/k1",
                grp_sl._level_prog(rate, slots_l,
                                   grp_sl._staging.submesh(*srange), srange),
                (params, key, lr, _sds((slots_l,))) + data,
                {"donated": n_leaves, "psum": PSUM_BUDGET,
                 "wire_bytes": bt[rate]["wire_bytes"],
                 "mem": _mem_expect(bt, rate, _ceil_div(slots_l, rows))}))
        mode, _ = grp_sl._fused_layout()
        if mode == "slices":
            need = max(_ceil_div(per_level, grp_sl._slices[r][1] - grp_sl._slices[r][0])
                       for r in level_rates)
            per_dev_sl = _bucket_pow2(need)
            targets.append((
                "grouped/slices/k8-fused",
                grp_sl._superstep_prog(k, per_dev_sl, "slices"),
                (params, key, np.int32(1), _sds((k, per_dev_sl * n_dev))) + data,
                {"donated": n_leaves, "psum": PSUM_BUDGET,
                 "wire_bytes": wire_top,
                 "mem": _mem_expect(bt, top, per_dev_sl)}))
            targets.append((
                "grouped/slices/k8-eval1-fused",
                grp_sl._superstep_prog(k, per_dev_sl, "slices",
                                       eval_mask=(True,) * k, fused_eval=fe),
                (params, key, np.int32(1), _sds((k, per_dev_sl * n_dev)))
                + data + tuple(fe.ops),
                {"donated": n_leaves, "psum": PSUM_BUDGET,
                 "wire_bytes": wire_top,
                 "psum_eval": EVAL_PSUM_BUDGET * k,
                 "mem": _mem_expect(bt, top, per_dev_sl)}))
            coh_sl = grp_sl.stage_cohort(setup["store"], sched_st, rates_st)
            targets.append((
                "grouped/stream/slices/k8",
                grp_sl._superstep_prog(k, coh_sl.per_dev, "slices",
                                       streaming=True),
                (params, key, np.int32(1), coh_sl.sched) + tuple(coh_sl.data),
                {"donated": n_leaves, "psum": PSUM_BUDGET,
                 "wire_bytes": wire_top,
                 "mem": _mem_expect(bt, top, coh_sl.per_dev)}))

            # multi-host fake-mesh variants (ISSUE 17): the same fused
            # slices programs re-audited with the clients axis classified
            # as crossing process boundaries -- the host-aligned placement
            # puts levels on disjoint hosts, so every byte the training
            # round moves cross-host is the ONE dense level-a reduction
            # (DCN budget enforced by EQUALITY), with zero reshards.
            # wire_only: the compile-side checks already ran on the
            # single-process entries above (same program objects).
            mh = {"dcn_axes": ("clients",), "dcn_budget_bytes": wire_top,
                  "dcn_exact": True, "wire_only": True}
            targets.append((
                "grouped/slices/k8-fused/mh",
                grp_sl._superstep_prog(k, per_dev_sl, "slices"),
                (params, key, np.int32(1), _sds((k, per_dev_sl * n_dev))) + data,
                {"donated": n_leaves, "psum": PSUM_BUDGET,
                 "wire_bytes": wire_top, **mh,
                 "mem": _mem_expect(bt, top, per_dev_sl)}))
            targets.append((
                "grouped/stream/slices/k8/mh",
                grp_sl._superstep_prog(k, coh_sl.per_dev, "slices",
                                       streaming=True),
                (params, key, np.int32(1), coh_sl.sched) + tuple(coh_sl.data),
                {"donated": n_leaves, "psum": PSUM_BUDGET,
                 "wire_bytes": wire_top, **mh,
                 "mem": _mem_expect(bt, top, coh_sl.per_dev)}))
    return targets, level_prog_names, grp_sl


def _codec_targets(setup) -> List[Tuple[str, Any, Tuple, Dict[str, Any]]]:
    """Wire-codec variants (ISSUE 8): every lossy codec's fused superstep
    for both engines, plus the int8 placement/eval spread.

    The compressed payload rides the SAME single psum bind the dense
    programs are budgeted on, so ``psum`` stays at :data:`PSUM_BUDGET`; the
    wire budget switches to :func:`~..fed.core.level_codec_byte_table` --
    still enforced by EQUALITY, because the packed int32/f32 psum operand
    avals ARE the wire format.  Donation: every codec program donates ONLY
    the error-feedback residual -- donating the params carry alongside a
    params-sized resid output trips an XLA:CPU serialized-executable
    aliasing bug in BOTH engines (see parallel.round_engine._WireCodecCarry),
    so the audit pins codec programs at exactly 1 donated leaf with the
    residual's bytes in the savings accounting (a budgeted cost, not a
    silent shortfall)."""
    import jax

    from ..compress import LOSSY_CODECS, resid_slots
    from ..fed.core import level_codec_byte_table
    from ..ops.fused_update import FlatSpec
    from ..parallel import GroupedRoundEngine, RoundEngine, shard_client_data
    from ..utils.optim import make_traced_lr_fn

    cfg, model, mesh = setup["cfg"], setup["model"], setup["mesh"]
    params, key = setup["params"], setup["key"]
    users = setup["users"]
    n_dev = mesh.shape["clients"]
    n_leaves = len(jax.tree_util.tree_leaves(params))
    total = FlatSpec.of(params).total
    bt = setup["byte_table"]
    top = max(bt)
    k = 8
    a = int(math.ceil(cfg["frac"] * users))
    per_level = 2
    targets = []

    def mem(cpd: int) -> Dict[str, int]:
        return _mem_expect(bt, top, cpd)

    def resid_sds(codec: str):
        return _sds((n_dev, resid_slots(codec), total), np.float32)

    fe = fused_eval_for(setup)
    from ..parallel.grouped import _bucket_pow2

    per_dev_g = _bucket_pow2(_ceil_div(per_level, n_dev))
    for codec in LOSSY_CODECS:
        wire = level_codec_byte_table(cfg, codec, n_leaves=n_leaves)[top]
        # resid-only donation (see the docstring); the residual's global
        # footprint is what aliasing can save
        resid_bytes = n_dev * resid_slots(codec) * total * 4
        expect = {"donated": 1, "psum": PSUM_BUDGET, "wire_bytes": wire,
                  "donated_bytes": resid_bytes}
        ceng = RoundEngine(model, dict(cfg, wire_codec=codec), mesh)
        ceng._lr_fn = make_traced_lr_fn(cfg)
        fix = (ceng.fix_rates,) if ceng.fix_rates is not None else ()
        data = tuple(setup["data"]) + fix
        targets.append((
            f"masked/replicated/k8-{codec}",
            ceng._build_superstep(k, _ceil_div(a, n_dev), True, num_active=a),
            (params, resid_sds(codec), key, np.int32(1)) + data,
            {**expect, "mem": mem(_ceil_div(a, n_dev))}))
        cgrp = GroupedRoundEngine(dict(cfg, wire_codec=codec), mesh)
        cgrp._lr_fn = make_traced_lr_fn(cfg)
        targets.append((
            f"grouped/span/k8-fused-{codec}",
            cgrp._superstep_prog(k, per_dev_g, "span"),
            (params, resid_sds(codec), key, np.int32(1),
             _sds((k, len(cgrp.levels), per_dev_g * n_dev)))
            + tuple(setup["data"]),
            {**expect, "mem": mem(per_dev_g)}))
        if codec != "int8":
            continue
        # int8 carries the placement/eval spread: the sharded slot schedule,
        # the slices layout, and the eval-fused program whose EVAL phase
        # stays dense (only the training reduction compresses)
        eng_sh = RoundEngine(model, dict(cfg, data_placement="sharded",
                                         wire_codec=codec), mesh)
        eng_sh._lr_fn = make_traced_lr_fn(cfg)
        per = _ceil_div(users, n_dev)
        slots_sh = per * n_dev
        targets.append((
            f"masked/sharded/k8-{codec}",
            eng_sh._build_superstep(k, per, False),
            (params, resid_sds(codec), key, np.int32(1),
             _sds((k, slots_sh)), _sds((k, slots_sh)))
            + shard_client_data(mesh, setup["data"]) + fix,
            {**expect, "mem": mem(per)}))
        targets.append((
            f"masked/replicated/k8-eval8-{codec}",
            ceng._build_superstep(k, _ceil_div(a, n_dev), True, num_active=a,
                                  eval_mask=(False,) * (k - 1) + (True,),
                                  fused_eval=fe),
            (params, resid_sds(codec), key, np.int32(1)) + data
            + tuple(fe.ops),
            {**expect, "psum_eval": EVAL_PSUM_BUDGET,
             "mem": mem(_ceil_div(a, n_dev))}))
        grp_sl = GroupedRoundEngine(dict(cfg, level_placement="slices",
                                         wire_codec=codec), mesh)
        grp_sl._lr_fn = make_traced_lr_fn(cfg)
        mode, _ = grp_sl._fused_layout()
        if mode == "slices":
            need = max(_ceil_div(per_level,
                                 grp_sl._slices[r][1] - grp_sl._slices[r][0])
                       for r in grp_sl.levels)
            per_dev_sl = _bucket_pow2(need)
            targets.append((
                f"grouped/slices/k8-fused-{codec}",
                grp_sl._superstep_prog(k, per_dev_sl, "slices"),
                (params, resid_sds(codec), key, np.int32(1),
                 _sds((k, per_dev_sl * n_dev))) + tuple(setup["data"]),
                {**expect, "mem": mem(per_dev_sl)}))
    return targets


def _sched_targets(setup) -> List[Tuple[str, Any, Tuple, Dict[str, Any]]]:
    """Scheduler variants (ISSUE 9): the program matrix grows the scenario
    mechanisms so the standing gates cover them --

    * ``-trace``: the masked in-jit sampler with an availability trace
      riding as a replicated program argument (the selection arithmetic
      adds NO collective: one global psum, dense wire budget, full params
      donation, all unchanged);
    * ``-deadline``: per-client step truncation (pure in-scan arithmetic:
      same budgets as lockstep) for both engines;
    * ``-buffered``: the buffered-async staleness carry -- donation pins to
      the buffer ONLY (the codec programs' XLA:CPU serialization-bug
      policy), the wire budget stays the one dense reduction (buffering is
      post-psum), and the carry's bytes land in the donation-savings
      accounting;
    * ``-perlevel``: the grouped per-level codec map (level-a int8, rest
      dense): ONE psum bind whose payload is budgeted BY EQUALITY against
      :func:`~..fed.core.level_codec_map_byte_table`'s per-level sum.
    """
    import jax

    from ..fed.core import level_codec_map_byte_table
    from ..ops.fused_update import FlatSpec
    from ..parallel import GroupedRoundEngine, RoundEngine
    from ..parallel.grouped import _bucket_pow2
    from ..sched import markov_trace
    from ..utils.optim import make_traced_lr_fn

    cfg, model, mesh = setup["cfg"], setup["model"], setup["mesh"]
    params, key = setup["params"], setup["key"]
    users = setup["users"]
    n_dev = mesh.shape["clients"]
    n_leaves = len(jax.tree_util.tree_leaves(params))
    total = FlatSpec.of(params).total
    bt = setup["byte_table"]
    top = max(bt)
    wire = bt[top]["wire_bytes"]
    k = 8
    a = int(math.ceil(cfg["frac"] * users))
    per_dev = _ceil_div(a, n_dev)
    per_level = 2
    per_dev_g = _bucket_pow2(_ceil_div(per_level, n_dev))
    data = tuple(setup["data"])
    targets = []

    def mem(cpd: int) -> Dict[str, int]:
        return _mem_expect(bt, top, cpd)

    # availability trace, in-jit sampling (masked replicated)
    trace = markov_trace(users, k, 0.6, 0.4, seed=0)
    tcfg = dict(cfg, schedule={"kind": "trace", "trace": trace.tolist()})
    eng_tr = RoundEngine(model, tcfg, mesh)
    eng_tr._lr_fn = make_traced_lr_fn(cfg)
    fix = (eng_tr.fix_rates,) if eng_tr.fix_rates is not None else ()
    targets.append((
        "masked/replicated/k8-trace",
        eng_tr._build_superstep(k, per_dev, True, num_active=a),
        (params, key, np.int32(1), eng_tr._sched_spec.trace) + data + fix,
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(per_dev)}))

    # deadline stragglers: both engines
    dcfg = dict(cfg, schedule={"deadline": {"min_frac": 0.5}})
    eng_dl = RoundEngine(model, dcfg, mesh)
    eng_dl._lr_fn = make_traced_lr_fn(cfg)
    targets.append((
        "masked/replicated/k8-deadline",
        eng_dl._build_superstep(k, per_dev, True, num_active=a),
        (params, key, np.int32(1)) + data + fix,
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(per_dev)}))
    grp_dl = GroupedRoundEngine(dcfg, mesh)
    grp_dl._lr_fn = make_traced_lr_fn(cfg)
    targets.append((
        "grouped/span/k8-fused-deadline",
        grp_dl._superstep_prog(k, per_dev_g, "span"),
        (params, key, np.int32(1),
         _sds((k, len(grp_dl.levels), per_dev_g * n_dev))) + data,
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(per_dev_g)}))

    # buffered-async aggregation: both engines, buf-only donation
    bcfg = dict(cfg, schedule={"aggregation": "buffered"})
    buf_sds = _sds((2, total), np.float32)
    buf_bytes = 2 * total * 4
    eng_bf = RoundEngine(model, bcfg, mesh)
    eng_bf._lr_fn = make_traced_lr_fn(cfg)
    targets.append((
        "masked/replicated/k8-buffered",
        eng_bf._build_superstep(k, per_dev, True, num_active=a),
        (params, buf_sds, key, np.int32(1)) + data + fix,
        {"donated": 1, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "donated_bytes": buf_bytes, "mem": mem(per_dev)}))
    grp_bf = GroupedRoundEngine(bcfg, mesh)
    grp_bf._lr_fn = make_traced_lr_fn(cfg)
    targets.append((
        "grouped/span/k8-fused-buffered",
        grp_bf._superstep_prog(k, per_dev_g, "span"),
        (params, buf_sds, key, np.int32(1),
         _sds((k, len(grp_bf.levels), per_dev_g * n_dev))) + data,
        {"donated": 1, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "donated_bytes": buf_bytes, "mem": mem(per_dev_g)}))

    # per-level codec map (ISSUE 9 satellite): level-a int8, rest dense --
    # the single bind's payload equals the per-level byte-table sum
    level_rates = sorted(bt, reverse=True)
    codec_map = {r: ("int8" if r == top else "dense") for r in level_rates}
    # the per-level map is a grouped-superstep-only feature, and
    # resolve_codec_cfg (which the engine ctor re-applies) refuses it
    # elsewhere -- declare the strategy/K this target actually audits
    mcfg = dict(cfg, strategy="grouped", superstep_rounds=k,
                wire_codec={f"{r:g}": c for r, c in codec_map.items()})
    grp_pl = GroupedRoundEngine(mcfg, mesh)
    grp_pl._lr_fn = make_traced_lr_fn(cfg)
    lay = grp_pl._map_layout(params)
    wire_map = sum(level_codec_map_byte_table(
        cfg, codec_map, n_leaves=n_leaves).values())
    resid_bytes = n_dev * 2 * lay["total_lossy"] * 4
    targets.append((
        "grouped/span/k8-fused-perlevel",
        grp_pl._superstep_prog(k, per_dev_g, "span"),
        (params, _sds((n_dev, 2, lay["total_lossy"]), np.float32), key,
         np.int32(1), _sds((k, len(grp_pl.levels), per_dev_g * n_dev)))
        + data,
        {"donated": 1, "psum": PSUM_BUDGET, "wire_bytes": wire_map,
         "donated_bytes": resid_bytes, "mem": mem(per_dev_g)}))
    # per-level codec map x slices layout (ISSUE 14 satellite, retiring
    # the PR 9 refusal): every switch branch emits every level's payload
    # structure (identity payloads for non-owned levels), so the single
    # bind's operand bytes equal the SAME per-level byte-table sum as the
    # span map -- enforced by equality against the traced avals
    grp_pl_sl = GroupedRoundEngine(dict(mcfg, level_placement="slices"),
                                   mesh)
    grp_pl_sl._lr_fn = make_traced_lr_fn(cfg)
    mode_sl, _ = grp_pl_sl._fused_layout()
    if mode_sl == "slices":
        need = max(_ceil_div(per_level,
                             grp_pl_sl._slices[r][1] - grp_pl_sl._slices[r][0])
                   for r in grp_pl_sl.levels)
        per_dev_sl = _bucket_pow2(need)
        targets.append((
            "grouped/slices/k8-fused-perlevel",
            grp_pl_sl._superstep_prog(k, per_dev_sl, "slices"),
            (params, _sds((n_dev, 2, lay["total_lossy"]), np.float32), key,
             np.int32(1), _sds((k, per_dev_sl * n_dev))) + data,
            {"donated": 1, "psum": PSUM_BUDGET, "wire_bytes": wire_map,
             "donated_bytes": resid_bytes, "mem": mem(per_dev_sl)}))
    return targets


def _arms_targets(setup) -> List[Tuple[str, Any, Tuple, Dict[str, Any]]]:
    """Arms-multiplexer variants (ISSUE 14): the E-arm vmapped supersteps
    of both engines at ARMS-SCALED budgets.

    The batched counted-average reduction stays EXACTLY one psum bind per
    fused training round (a vmapped pytree psum is one bind -- the
    ``psum`` budget does NOT scale with E), while the bind's operand
    bytes scale linearly: the wire budget is ``E x`` the per-arm dense
    reduction, enforced by equality against the traced avals.  The HBM
    budget scales the params carry and the per-device client concurrency
    by E (each arm's slot cohort trains concurrently).  Program FLOPs are
    held to E-linearity by :func:`arms_flop_check` against the unbatched
    twin.  Donation pins to ZERO leaves: donating the E-stacked params
    carry trips the XLA:CPU deserialized-executable aliasing bug (see
    ``round_engine._build_superstep``), so the arms programs keep the
    carry undonated -- a budgeted extra params buffer, not a silent
    coverage shortfall."""
    import jax

    from ..fed.core import arm_stream_keys
    from ..multi import default_seeds
    from ..parallel import GroupedRoundEngine, RoundEngine
    from ..parallel.grouped import _bucket_pow2
    from ..utils.optim import make_traced_lr_fn

    cfg, model, mesh = setup["cfg"], setup["model"], setup["mesh"]
    params, key = setup["params"], setup["key"]
    users = setup["users"]
    n_dev = mesh.shape["clients"]
    bt = setup["byte_table"]
    top = max(bt)
    wire = bt[top]["wire_bytes"]
    k = 8
    a = int(math.ceil(cfg["frac"] * users))
    per_dev = _ceil_div(a, n_dev)
    per_level = 2
    per_dev_g = _bucket_pow2(_ceil_div(per_level, n_dev))
    targets = []

    def amem(cpd: int, e: int) -> Dict[str, int]:
        m = _mem_expect(bt, top, cpd)
        # the params carry (and its donated/output footprint) stacks E
        # arms; per-device client concurrency multiplies the same way
        return {"param_bytes": e * m["param_bytes"],
                "activation_bytes": m["activation_bytes"],
                "clients_per_device": e * cpd}

    def stacked_params(e: int):
        return jax.tree_util.tree_map(
            lambda v: _sds((e,) + tuple(v.shape), v.dtype), dict(params))

    for e in (2, 4):
        acfg = dict(cfg, arms=e)
        eng = RoundEngine(model, acfg, mesh)
        eng._lr_fn = make_traced_lr_fn(cfg)
        fix = (eng.fix_rates,) if eng.fix_rates is not None else ()
        data = tuple(setup["data"]) + fix
        keys_e = arm_stream_keys(key, default_seeds(e))
        scales_e = np.ones(e, np.float32)
        targets.append((
            f"masked/replicated/k8-arms{e}",
            eng._build_superstep(k, per_dev, True, num_active=a, arms=e),
            (stacked_params(e), keys_e, np.int32(1), scales_e) + data,
            {"donated": 0, "psum": PSUM_BUDGET,
             "wire_bytes": e * wire, "mem": amem(per_dev, e)}))
    grp = GroupedRoundEngine(dict(cfg, arms=2), mesh)
    grp._lr_fn = make_traced_lr_fn(cfg)
    keys_2 = arm_stream_keys(key, default_seeds(2))
    # grouped arms share the host user/rate schedule, so the count masks
    # are ARM-INVARIANT and vmap leaves them unbatched: the single bind
    # carries E sum payloads + ONE counts payload -- (E+1)/2 x the dense
    # wire, tighter than the masked engine's E x (whose per-arm cohorts
    # batch the counts too).  Still enforced by equality.
    targets.append((
        "grouped/span/k8-fused-arms2",
        grp._superstep_prog(k, per_dev_g, "span", arms=2),
        (stacked_params(2), keys_2, np.int32(1), np.ones(2, np.float32),
         _sds((k, len(grp.levels), per_dev_g * n_dev)))
        + tuple(setup["data"]),
        {"donated": 0, "psum": PSUM_BUDGET,
         "wire_bytes": (2 + 1) * wire // 2,
         "mem": amem(per_dev_g, 2)}))
    return targets


def arms_flop_check(report: "AuditReport") -> Dict[str, Any]:
    """FLOP linearity of the arms axis (ISSUE 14): the MARGINAL cost of an
    arm is constant -- ``flops(E=4) == 2 x flops(E=2)`` to 0.1% (each arm
    re-runs the identical per-arm math; doubling the batch doubles it) --
    and an E-arm program stays within a few percent of ``E x`` the
    unbatched twin (the small super-E offset is the per-arm in-jit cohort
    draw and LR scaling that the solo program binds only once; a blowout
    here means the vmap fell off the batched lowering).  Read from the
    per-program ``cost_analysis`` numbers already recorded by the audit
    (nothing recompiles here)."""
    out: Dict[str, Any] = {"ok": True, "pairs": {}}

    def flops_of(name):
        return getattr(report.programs.get(name), "flops", None)

    f2 = flops_of("masked/replicated/k8-arms2")
    f4 = flops_of("masked/replicated/k8-arms4")
    if f2 and f4:
        out["pairs"]["masked-arms4-vs-arms2"] = {
            "flops": f4, "half_flops": f2, "ratio": round(f4 / f2, 6)}
        if abs(f4 / f2 - 2.0) > 2e-3:
            report.fail(out, "arms-flop-linearity",
                        f"masked k8 arms4 compiled flops {f4:.4g} are "
                        f"{f4 / f2:.6f}x arms2's ({f2:.4g}); the marginal "
                        f"arm cost must be constant (2x to 0.1%)")
    for arms_name, solo_name, e in (
            ("masked/replicated/k8-arms2", "masked/replicated/k8", 2),
            ("masked/replicated/k8-arms4", "masked/replicated/k8", 4),
            ("grouped/span/k8-fused-arms2", "grouped/span/k8-fused", 2)):
        fa, fs = flops_of(arms_name), flops_of(solo_name)
        if not fa or not fs:
            continue  # cost analysis unavailable on this backend
        ratio = fa / fs
        out["pairs"][arms_name] = {"flops": fa, "solo_flops": fs,
                                   "ratio": round(ratio, 6), "expect": e}
        if not e <= ratio <= 1.1 * e:
            report.fail(out, "arms-flop-linearity",
                        f"{arms_name}: compiled flops {fa:.4g} are "
                        f"{ratio:.6f}x the unbatched {solo_name} "
                        f"({fs:.4g}), outside [{e}, {1.1 * e:g}]: the "
                        f"arms axis must scale FLOPs ~{e}x (per-arm draw "
                        f"overhead only)")
    return out


def _obs_targets(setup) -> List[Tuple[str, Any, Tuple, Dict[str, Any]]]:
    """Telemetry variants (ISSUE 10): ``telemetry='on'`` folds the health
    probes into the metrics pytree of every round core, and these targets
    pin the zero-cost contract statically -- SAME single global psum, SAME
    dense (or codec) wire bytes by equality (the probes derive from
    already-reduced values and per-device partials, never a new
    collective), full params donation, and the k1 program held to the
    unchanged step-body kernel budget (the probes live outside the
    local-step scan).  The int8 variant proves the probe of the
    error-feedback residual rides the codec programs without touching
    their resid-only donation policy or compressed payload."""
    import jax

    from ..compress import resid_slots
    from ..fed.core import level_codec_byte_table
    from ..ops.fused_update import FlatSpec
    from ..parallel import GroupedRoundEngine, RoundEngine
    from ..parallel.grouped import _bucket_pow2
    from ..utils.optim import make_traced_lr_fn

    cfg, model, mesh = setup["cfg"], setup["model"], setup["mesh"]
    params, key, lr = setup["params"], setup["key"], setup["lr"]
    users = setup["users"]
    n_dev = mesh.shape["clients"]
    n_leaves = len(jax.tree_util.tree_leaves(params))
    bt = setup["byte_table"]
    top = max(bt)
    wire = bt[top]["wire_bytes"]
    k = 8
    a = int(math.ceil(cfg["frac"] * users))
    per_dev = _ceil_div(a, n_dev)
    per_level = 2
    per_dev_g = _bucket_pow2(_ceil_div(per_level, n_dev))
    targets = []

    def mem(cpd: int) -> Dict[str, int]:
        return _mem_expect(bt, top, cpd)

    tcfg = dict(cfg, telemetry="on")
    eng = RoundEngine(model, tcfg, mesh)
    eng._lr_fn = make_traced_lr_fn(cfg)
    fix = (eng.fix_rates,) if eng.fix_rates is not None else ()
    data = tuple(setup["data"]) + fix
    slots = users + ((-users) % n_dev)
    targets.append((
        "masked/replicated/k1-telemetry", eng._build_train(),
        (params, key, lr, _sds((slots,)), _sds((slots,))) + data,
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(_ceil_div(slots, n_dev))}))
    targets.append((
        "masked/replicated/k8-telemetry",
        eng._build_superstep(k, per_dev, True, num_active=a),
        (params, key, np.int32(1)) + data,
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(per_dev)}))

    grp = GroupedRoundEngine(tcfg, mesh)
    grp._lr_fn = make_traced_lr_fn(cfg)
    targets.append((
        "grouped/span/k8-fused-telemetry",
        grp._superstep_prog(k, per_dev_g, "span"),
        (params, key, np.int32(1),
         _sds((k, len(grp.levels), per_dev_g * n_dev))) + data[:4],
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(per_dev_g)}))

    total = FlatSpec.of(params).total
    ceng = RoundEngine(model, dict(cfg, telemetry="on", wire_codec="int8"),
                       mesh)
    ceng._lr_fn = make_traced_lr_fn(cfg)
    wire_i8 = level_codec_byte_table(cfg, "int8", n_leaves=n_leaves)[top]
    resid_bytes = n_dev * resid_slots("int8") * total * 4
    targets.append((
        "masked/replicated/k8-telemetry-int8",
        ceng._build_superstep(k, per_dev, True, num_active=a),
        (params, _sds((n_dev, resid_slots("int8"), total), np.float32), key,
         np.int32(1)) + data,
        {"donated": 1, "psum": PSUM_BUDGET, "wire_bytes": wire_i8,
         "donated_bytes": resid_bytes, "mem": mem(per_dev)}))
    return targets


def _quarantine_targets(setup) -> List[Tuple[str, Any, Tuple,
                                             Dict[str, Any]]]:
    """Client-update quarantine variants (ISSUE 15 tentpole): the
    finiteness (+ norm) gate folds into the counted sums and counts BEFORE
    the single global psum, from values each device already holds -- so
    these targets pin quarantine='on' to the EXACT budgets of the dense
    twins: SAME one psum, SAME dense wire bytes by equality (the gate is
    elementwise math + the one [1]-shaped obs_quarantine metrics leaf,
    never a collective), full params donation, and the k1 program held to
    the unchanged step-body kernel budget (the gate lives at round level,
    outside the local-step scan).  The max_norm variant proves the
    masked-update-norm term also stays collective-free; telemetry stays
    OFF here, pinning the counter's ride-along contract on its own."""
    import jax

    from ..parallel import GroupedRoundEngine, RoundEngine
    from ..parallel.grouped import _bucket_pow2
    from ..utils.optim import make_traced_lr_fn

    cfg, model, mesh = setup["cfg"], setup["model"], setup["mesh"]
    params, key, lr = setup["params"], setup["key"], setup["lr"]
    users = setup["users"]
    n_dev = mesh.shape["clients"]
    n_leaves = len(jax.tree_util.tree_leaves(params))
    bt = setup["byte_table"]
    top = max(bt)
    wire = bt[top]["wire_bytes"]
    k = 8
    a = int(math.ceil(cfg["frac"] * users))
    per_dev = _ceil_div(a, n_dev)
    per_dev_g = _bucket_pow2(_ceil_div(2, n_dev))

    def mem(cpd: int) -> Dict[str, int]:
        return _mem_expect(bt, top, cpd)

    qcfg = dict(cfg, quarantine="on")
    eng = RoundEngine(model, qcfg, mesh)
    eng._lr_fn = make_traced_lr_fn(cfg)
    fix = (eng.fix_rates,) if eng.fix_rates is not None else ()
    data = tuple(setup["data"]) + fix
    slots = users + ((-users) % n_dev)
    targets = [(
        "masked/replicated/k1-quarantine", eng._build_train(),
        (params, key, lr, _sds((slots,)), _sds((slots,))) + data,
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(_ceil_div(slots, n_dev))}), (
        "masked/replicated/k8-quarantine",
        eng._build_superstep(k, per_dev, True, num_active=a),
        (params, key, np.int32(1)) + data,
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(per_dev)})]

    neng = RoundEngine(model, dict(cfg, quarantine={"max_norm": 100.0}),
                       mesh)
    neng._lr_fn = make_traced_lr_fn(cfg)
    targets.append((
        "masked/replicated/k8-quarantine-norm",
        neng._build_superstep(k, per_dev, True, num_active=a),
        (params, key, np.int32(1)) + data,
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(per_dev)}))

    grp = GroupedRoundEngine(qcfg, mesh)
    grp._lr_fn = make_traced_lr_fn(cfg)
    targets.append((
        "grouped/span/k8-fused-quarantine",
        grp._superstep_prog(k, per_dev_g, "span"),
        (params, key, np.int32(1),
         _sds((k, len(grp.levels), per_dev_g * n_dev))) + data[:4],
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(per_dev_g)}))
    return targets


def _obs_hist_targets(setup) -> List[Tuple[str, Any, Tuple, Dict[str, Any]]]:
    """Cohort-histogram telemetry variants (ISSUE 12): ``telemetry='hist'``
    folds the fixed-bucket cohort histograms (obs/hist.py: per-client
    loss, deadline step fraction, level membership, buffered staleness
    magnitude) into the metrics pytree NEXT TO the scalar probes -- and
    these targets pin the same zero-cost contract the ISSUE 10 variants
    pin: IDENTICAL single-global-psum, wire-byte (by equality), donation
    and step-body budgets as the scalar-probe/dense twins.  The bucketing
    is one searchsorted + scatter-add per histogram over per-slot values
    each device already holds -- per-device partials riding the metrics
    out-spec, never a collective.  The int8 variant proves the histograms
    ride the codec programs at the compressed wire budget and resid-only
    donation unchanged."""
    import jax

    from ..compress import resid_slots
    from ..fed.core import level_codec_byte_table
    from ..ops.fused_update import FlatSpec
    from ..parallel import GroupedRoundEngine, RoundEngine
    from ..parallel.grouped import _bucket_pow2
    from ..utils.optim import make_traced_lr_fn

    cfg, model, mesh = setup["cfg"], setup["model"], setup["mesh"]
    params, key, lr = setup["params"], setup["key"], setup["lr"]
    users = setup["users"]
    n_dev = mesh.shape["clients"]
    n_leaves = len(jax.tree_util.tree_leaves(params))
    bt = setup["byte_table"]
    top = max(bt)
    wire = bt[top]["wire_bytes"]
    k = 8
    a = int(math.ceil(cfg["frac"] * users))
    per_dev = _ceil_div(a, n_dev)
    per_level = 2
    per_dev_g = _bucket_pow2(_ceil_div(per_level, n_dev))
    targets = []

    def mem(cpd: int) -> Dict[str, int]:
        return _mem_expect(bt, top, cpd)

    hcfg = dict(cfg, telemetry="hist")
    eng = RoundEngine(model, hcfg, mesh)
    eng._lr_fn = make_traced_lr_fn(cfg)
    fix = (eng.fix_rates,) if eng.fix_rates is not None else ()
    data = tuple(setup["data"]) + fix
    slots = users + ((-users) % n_dev)
    targets.append((
        "masked/replicated/k1-hist", eng._build_train(),
        (params, key, lr, _sds((slots,)), _sds((slots,))) + data,
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(_ceil_div(slots, n_dev))}))
    targets.append((
        "masked/replicated/k8-hist",
        eng._build_superstep(k, per_dev, True, num_active=a),
        (params, key, np.int32(1)) + data,
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(per_dev)}))

    grp = GroupedRoundEngine(hcfg, mesh)
    grp._lr_fn = make_traced_lr_fn(cfg)
    targets.append((
        "grouped/span/k8-fused-hist",
        grp._superstep_prog(k, per_dev_g, "span"),
        (params, key, np.int32(1),
         _sds((k, len(grp.levels), per_dev_g * n_dev))) + data[:4],
        {"donated": n_leaves, "psum": PSUM_BUDGET, "wire_bytes": wire,
         "mem": mem(per_dev_g)}))

    total = FlatSpec.of(params).total
    ceng = RoundEngine(model, dict(cfg, telemetry="hist", wire_codec="int8"),
                       mesh)
    ceng._lr_fn = make_traced_lr_fn(cfg)
    wire_i8 = level_codec_byte_table(cfg, "int8", n_leaves=n_leaves)[top]
    resid_bytes = n_dev * resid_slots("int8") * total * 4
    targets.append((
        "masked/replicated/k8-hist-int8",
        ceng._build_superstep(k, per_dev, True, num_active=a),
        (params, _sds((n_dev, resid_slots("int8"), total), np.float32), key,
         np.int32(1)) + data,
        {"donated": 1, "psum": PSUM_BUDGET, "wire_bytes": wire_i8,
         "donated_bytes": resid_bytes, "mem": mem(per_dev)}))
    return targets


def codec_frontier_check(report: "AuditReport") -> Dict[str, Any]:
    """The analytic flagship compression frontier (ISSUE 8 acceptance): each
    codec's per-round payload at full CIFAR-10 ResNet-18 widths vs the
    dense 89.4 MB baseline, all numbers from the ONE byte formula
    (:func:`~..compress.codec_payload_bytes` via the fed.core tables, no
    lowering needed).  Enforced: the int8 payload is <= 25% of dense (the
    8-bit value lane + 8-bit count lane vs two f32 trees; the small slack
    absorbs the <= 1 padded lane word per packed stream).  The signsgd row
    excludes its per-leaf scale vector (a few hundred bytes against tens of
    MB -- the audited small-width programs DO price it exactly)."""
    from ..compress import LOSSY_CODECS
    from ..fed.core import level_byte_table, level_codec_byte_table

    fcfg = default_audit_cfg(flagship=True)
    bt = level_byte_table(fcfg)
    top = max(bt)
    dense = bt[top]["wire_bytes"]
    sec: Dict[str, Any] = {"ok": True, "flagship_dense_bytes": dense,
                           "source": "fed.core.level_codec_byte_table",
                           "codecs": {}}
    for name in LOSSY_CODECS:
        comp = level_codec_byte_table(fcfg, name)[top]
        sec["codecs"][name] = {
            "payload_bytes_per_round": comp,
            "ratio_vs_dense": round(comp / dense, 6),
            "reduction_x": round(dense / comp, 3),
        }
    int8 = sec["codecs"]["int8"]["payload_bytes_per_round"]
    if 4 * int8 > dense + 32:
        report.fail(sec, "wire-frontier",
                    f"flagship int8 payload {int8} B/round exceeds 25% of "
                    f"the dense baseline {dense} B/round "
                    f"({int8 / dense:.2%}): the compressed wire budget "
                    f"regressed past the ISSUE 8 acceptance line")
    return sec


# ---------------------------------------------------------------------------
# per-program checks
# ---------------------------------------------------------------------------

def audit_program(name: str, prog, args: Tuple, expect: Dict[str, Any],
                  mesh, bind_files: Optional[Set[str]] = None) -> ProgramReport:
    """Trace, lower and compile one program; run checks (a)-(c), the ISSUE 7
    wire/HBM/reshard passes, and record flops/memory for (e).  Never
    executes the program.

    ``bind_files`` (ISSUE 18): a shared set the caller passes to collect
    the package-relative source files of every PRNG bind in the traced
    jaxpr -- the key-stream audit cross-checks them against its modeled
    modules."""
    from ..analysis import cost_analysis_dict

    rep = ProgramReport(name=name, donation_expected=int(expect["donated"]))
    jaxpr = prog.trace(*args).jaxpr
    if bind_files is not None:
        bind_files.update(random_bind_files(
            jaxpr, os.path.dirname(os.path.dirname(os.path.abspath(__file__)))))
    for prim, prov in find_callbacks(jaxpr):
        rep.fail("no-host-callback",
                 f"host callback op `{prim}` inside the round program "
                 f"(bound at {prov}): one callback serialises the whole "
                 f"fused round on the host boundary")
    for what, prov in find_f64(jaxpr):
        rep.fail("no-f64", f"{what} (bound at {prov})")

    # explicit (jaxpr-level) reshards: data-movement collectives the round
    # programs never need -- the HLO half joins after compile
    jaxpr_reshards = find_reshards(jaxpr)
    for prim, prov in jaxpr_reshards:
        rep.fail("reshard",
                 f"explicit data-movement collective `{prim}` bound at "
                 f"{prov}: the round programs move bytes through the single "
                 f"reduction only")

    counts, axes = count_collectives(jaxpr)
    # the eval phase's reductions bind (clients, data) JOINTLY; every
    # training psum binds a single axis -- count them as separate budgets
    # (ISSUE 4: "one global psum per fused round" means per TRAINING round)
    rep.psum_eval = count_psum_joint(jaxpr, ("clients", "data"))
    rep.psum_clients = count_psum_over(jaxpr, "clients") - rep.psum_eval
    rep.all_gather = counts.get("all_gather", 0)
    rep.collective_axes = sorted(axes)
    mesh_axes = set(mesh.axis_names)
    bad_axes = axes - mesh_axes
    if bad_axes:
        rep.fail("collective-axis",
                 f"collective axes {sorted(bad_axes)} not resolvable in the "
                 f"mesh axes {sorted(mesh_axes)}")
    if rep.psum_clients != expect["psum"]:
        rep.fail("psum-budget",
                 f"{rep.psum_clients} global psum bind(s) over the clients "
                 f"axis, budget is exactly {expect['psum']}")
    if rep.psum_eval != expect.get("psum_eval", 0):
        rep.fail("eval-psum-budget",
                 f"{rep.psum_eval} joint (clients, data) psum bind(s), "
                 f"budget is exactly {expect.get('psum_eval', 0)} (sBN + "
                 f"Global reductions per traced eval point)")
    if rep.all_gather:
        rep.fail("collective-budget",
                 f"{rep.all_gather} all_gather bind(s); the round programs "
                 f"move aggregates through the single psum only")

    # wire model (ISSUE 7 tentpole): price every collective bind and hold
    # the training round to its dense-reduction byte budget.  Multi-host
    # variants (ISSUE 17) override the link classification with an
    # explicit dcn_axes (the fake-mesh audit: classify AS IF the clients
    # axis crossed processes) and hold DCN to EXACTLY one dense reduction
    rep.wire = program_wire(jaxpr, mesh, dcn_axes=expect.get("dcn_axes"))
    if "wire_bytes" in expect:
        check_wire(rep, rep.wire, expect["wire_bytes"],
                   n_eval_points=expect.get("psum_eval", 0) // EVAL_PSUM_BUDGET,
                   dcn_budget_bytes=expect.get("dcn_budget_bytes", 0),
                   dcn_exact=expect.get("dcn_exact", False))

    if any(f.rule == "no-host-callback" for f in rep.findings):
        # a host callback is fatal on its own AND may refuse to lower under
        # a mesh -- report what the jaxpr walk found and stop here
        rep.reshards = {"jaxpr": [list(t) for t in jaxpr_reshards],
                        "total": len(jaxpr_reshards)}
        return rep

    if expect.get("wire_only"):
        # multi-host fake-mesh variant (ISSUE 17): the SAME program object
        # as its single-process entry (lowered, compiled and budgeted
        # there); this entry re-audits the trace-level wire classification
        # under the multi-process link model -- dcn_axes forced onto the
        # clients axis, DCN held to exactly one dense train reduction --
        # so it skips the duplicate lower/compile
        rep.reshards = {"jaxpr": [list(t) for t in jaxpr_reshards],
                        "total": len(jaxpr_reshards)}
        return rep

    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        lowered = prog.lower(*args)
        compiled = lowered.compile()
    for w in caught:
        msg = str(w.message)
        if "donated" in msg.lower() or "donation" in msg.lower():
            rep.fail("donation-unused",
                     f"jax donation warning promoted to failure: {msg[:300]}")

    lowered_text = lowered.as_text()
    compiled_text = compiled.as_text()
    # reshard detector, HLO half (ISSUE 7): GSPMD-introduced data-movement
    # instructions the jaxpr never shows -- zero allowed, and the tripwire
    # the multi-host slices work must keep green
    hlo_reshards = reshard_ops(compiled_text)
    rep.reshards = {**hlo_reshards,
                    "jaxpr": [list(t) for t in jaxpr_reshards],
                    "total": hlo_reshards["total"] + len(jaxpr_reshards)}
    if hlo_reshards["total"]:
        rep.fail("reshard",
                 f"optimized HLO carries {hlo_reshards['total']} "
                 f"GSPMD-introduced data-movement instruction(s) "
                 f"({ {k: v for k, v in hlo_reshards.items() if k != 'total' and v} }): "
                 f"sharding propagation decided operands live on the wrong "
                 f"devices -- an implicit reshard crept into the program")
    # hot-step kernel count (ISSUE 5): recorded for EVERY program, budgeted
    # on the level-a critical-path bodies (STEP_BODY_FUSION_BUDGET)
    rep.step_body = scan_body_kernel_count(compiled_text)
    rep.step_body_budget = expect.get("step_body_fusions",
                                      STEP_BODY_FUSION_BUDGET.get(name))
    if rep.step_body_budget is not None \
            and rep.step_body["fusions"] > rep.step_body_budget:
        rep.fail("step-body-budget",
                 f"{rep.step_body['fusions']} fusion kernels per scan-body "
                 f"iteration (body {rep.step_body['body']}), budget is "
                 f"{rep.step_body_budget}: the per-step op soup has "
                 f"regressed (un-hoisted masks / un-fused epilogue / a new "
                 f"per-leaf chain)")
    rep.donated = donation_marks(lowered_text)
    rep.aliased = aliased_outputs(compiled_text)
    if rep.donated != expect["donated"]:
        rep.fail("donation-coverage",
                 f"{rep.donated} donated input leaves at lowering, expected "
                 f"{expect['donated']} (params/opt-state coverage)")
    if rep.aliased != expect["donated"]:
        rep.fail("donation-consumed",
                 f"only {rep.aliased}/{expect['donated']} donated leaves "
                 f"were consumed by input-output aliasing in the compiled "
                 f"program -- unconsumed donation is silent memory doubling")

    try:
        rep.flops = float(cost_analysis_dict(compiled).get("flops", float("nan")))
    except Exception as e:  # cost analysis availability varies by backend
        rep.flops = None
        rep.findings.append(Finding("cost-analysis", name,
                                    f"cost_analysis unavailable: {e!r} "
                                    f"(informational)"))

    # HBM footprint (ISSUE 7): memory_analysis() fields are REQUIRED now --
    # an absent field on a compiled flagship program is a loud
    # memory-analysis-missing finding, not the old getattr-skipped empty
    # record -- and each is held to the analytic bound, with the bytes that
    # donation actually saved accounted alongside
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    rep.memory, mem_findings = collect_memory(ma, name)
    if mem_findings:
        rep.ok = False
        rep.findings.extend(mem_findings)
    if "mem" in expect:
        mi = expect["mem"]
        budget = analytic_budget(mi["param_bytes"], mi["activation_bytes"],
                                 mi["clients_per_device"], _args_bytes(args),
                                 expect.get("wire_bytes", 0))
        budget["donation"] = donation_accounting(
            rep, expect.get("donated_bytes", mi["param_bytes"]))
        rep.memory_budget = budget
        check_memory(rep, rep.memory, budget)
    return rep


# ---------------------------------------------------------------------------
# cross-program checks: (d) recompile hazard, (e) FLOP budget
# ---------------------------------------------------------------------------

def recompile_hazard_check(setup) -> Dict[str, Any]:
    """Dispatch each engine twice with FRESH but value-identical host inputs
    (new numpy buffers, new python floats) and require
    ``engine.program_cache_size()`` to stay flat after the first call --
    the classic leaks (weak-typed scalars, python floats in cache keys,
    re-bucketed slots) all show up as growth here."""
    import jax

    from ..parallel import GroupedRoundEngine, RoundEngine, shard_client_data

    cfg, model, mesh = setup["cfg"], setup["model"], setup["mesh"]
    data = tuple(setup["data"])
    out: Dict[str, Any] = {"ok": True}

    def fresh_idx():
        return np.array([0, 2, 4, 6, 8, 1], dtype=np.int64)  # re-allocated

    def fresh_lr():
        return float("0.05")  # a NEW python float each dispatch

    eng = RoundEngine(model, cfg, mesh)
    p = model.init(jax.random.key(0))
    p, _ = eng.train_round(p, jax.random.key(1), fresh_lr(), fresh_idx(), data)
    size1 = eng.program_cache_size()
    p, _ = eng.train_round(p, jax.random.key(2), fresh_lr(), fresh_idx(), data)
    out["masked_round"] = {"after_warm": size1,
                           "after_repeat": eng.program_cache_size()}

    p, pend = eng.train_superstep(p, jax.random.key(3), 1, 2, data,
                                  num_active=4)
    pend.fetch()
    size1 = eng.program_cache_size()
    p, pend = eng.train_superstep(p, jax.random.key(3), 3, 2, data,
                                  num_active=4)
    pend.fetch()
    out["masked_superstep"] = {"after_warm": size1,
                               "after_repeat": eng.program_cache_size()}

    # sampler variants (ISSUE 11): the superstep above draws in-jit from
    # the default PRP index map; the legacy permutation engine must stay
    # recompile-free too (the sampler kind is an engine-construction
    # constant, never a per-dispatch cache key)
    eng_pm = RoundEngine(model, dict(cfg, sampler="perm"), mesh)
    ppm = model.init(jax.random.key(0))
    ppm, pend = eng_pm.train_superstep(ppm, jax.random.key(3), 1, 2, data,
                                       num_active=4)
    pend.fetch()
    size1 = eng_pm.program_cache_size()
    ppm, pend = eng_pm.train_superstep(ppm, jax.random.key(3), 3, 2, data,
                                       num_active=4)
    pend.fetch()
    out["masked_superstep_perm"] = {"after_warm": size1,
                                    "after_repeat": eng_pm.program_cache_size()}

    # eval-fused superstep (ISSUE 4): a fresh-but-identical eval mask (a NEW
    # tuple of the same booleans) must hit the cached program -- the mask is
    # part of the program key, so a tuple-identity (rather than equality)
    # key would recompile the flagship program every superstep
    fe = fused_eval_for(setup)
    p, pend = eng.train_superstep(p, jax.random.key(3), 5, 2, data,
                                  num_active=4, eval_mask=(True, True),
                                  fused_eval=fe)
    pend.fetch()
    size1 = eng.program_cache_size()
    p, pend = eng.train_superstep(p, jax.random.key(3), 7, 2, data,
                                  num_active=4,
                                  eval_mask=tuple([True] * 2), fused_eval=fe)
    pend.fetch()
    out["masked_superstep_eval"] = {"after_warm": size1,
                                    "after_repeat": eng.program_cache_size()}

    # sharded placement superstep: the host-packed slot schedule's ownership
    # density keys the K-round program -- fresh-but-identical schedules must
    # not recompile (per_dev bucketing regression, found by this very check)
    from ..fed.core import round_users

    eng_sh = RoundEngine(model, dict(cfg, data_placement="sharded"), mesh)
    data_sh = shard_client_data(mesh, data)
    base = jax.random.key(5)

    def fresh_sched():
        return np.stack([np.asarray(round_users(jax.random.fold_in(base, 1 + j),
                                                setup["users"], 4))
                         for j in range(2)])

    ps = model.init(jax.random.key(0))
    ps, pend = eng_sh.train_superstep(ps, base, 1, 2, data_sh,
                                      user_schedule=fresh_sched())
    pend.fetch()
    size1 = eng_sh.program_cache_size()
    ps, pend = eng_sh.train_superstep(ps, base, 3, 2, data_sh,
                                      user_schedule=fresh_sched())
    pend.fetch()
    out["masked_sharded_superstep"] = {"after_warm": size1,
                                       "after_repeat": eng_sh.program_cache_size()}

    # streaming cohort supersteps (ISSUE 6): every superstep restages a
    # FRESH cohort (new host buffers, new device arrays) -- the program key
    # is the static layout (k, per_dev, stream), so steady-state streaming
    # must stay one compiled specialization per engine
    from ..fed.core import superstep_rate_schedule, superstep_user_schedule

    store = setup["store"]
    eng_st = RoundEngine(model, cfg, mesh)
    pst = model.init(jax.random.key(0))

    def fresh_cohort(epoch0):
        sched = superstep_user_schedule(base, epoch0, 2, setup["users"], 4)
        return eng_st.stage_cohort(store, sched)

    pst, pend = eng_st.train_superstep(pst, base, 1, 2, cohort=fresh_cohort(1))
    pend.fetch()
    size1 = eng_st.program_cache_size()
    pst, pend = eng_st.train_superstep(pst, base, 3, 2, cohort=fresh_cohort(3))
    pend.fetch()
    out["masked_stream_superstep"] = {"after_warm": size1,
                                      "after_repeat": eng_st.program_cache_size()}

    grp_st = GroupedRoundEngine(cfg, mesh)
    gst = model.init(jax.random.key(0))

    def fresh_gcohort(epoch0):
        sched = superstep_user_schedule(base, epoch0, 2, setup["users"],
                                        setup["users"])
        rates = superstep_rate_schedule(base, epoch0, 2, cfg, sched)
        return grp_st.stage_cohort(store, sched, rates)

    gst, pend = grp_st.train_superstep(gst, base, 1, 2, cohort=fresh_gcohort(1))
    pend.fetch()
    size1 = grp_st.program_cache_size()
    gst, pend = grp_st.train_superstep(gst, base, 3, 2, cohort=fresh_gcohort(3))
    pend.fetch()
    out["grouped_stream_superstep"] = {"after_warm": size1,
                                       "after_repeat": grp_st.program_cache_size()}

    grp = GroupedRoundEngine(cfg, mesh)
    rates_vec = np.asarray(cfg["model_rate"], np.float32)
    g = model.init(jax.random.key(0))
    g, _ = grp.train_round(g, fresh_idx(), rates_vec[fresh_idx()], data,
                           fresh_lr(), jax.random.key(1))
    size1 = grp.program_cache_size()
    g, _ = grp.train_round(g, fresh_idx(), rates_vec[fresh_idx()], data,
                           fresh_lr(), jax.random.key(2))
    out["grouped_round"] = {"after_warm": size1,
                            "after_repeat": grp.program_cache_size()}

    # arms superstep (ISSUE 14): the stacked per-arm key roots and LR
    # scales are per-dispatch VALUES; the arms count is an engine
    # constant.  A fresh-but-identical dispatch (new key derivation, new
    # scale buffer) must hit the cached E-arm program.
    eng_ar = RoundEngine(model, dict(cfg, arms=2), mesh)
    par = jax.tree_util.tree_map(
        lambda v: jax.numpy.stack([v, v]), model.init(jax.random.key(0)))
    par, pend = eng_ar.train_superstep(par, jax.random.key(3), 1, 2, data,
                                       num_active=4)
    pend.fetch()
    size1 = eng_ar.program_cache_size()
    par, pend = eng_ar.train_superstep(par, jax.random.key(3), 3, 2, data,
                                       num_active=4)
    pend.fetch()
    out["masked_arms_superstep"] = {"after_warm": size1,
                                    "after_repeat":
                                        eng_ar.program_cache_size()}
    return out


def sampler_stream_check(report: AuditReport, setup) -> Dict[str, Any]:
    """Sampling-stream consistency (ISSUE 11): for BOTH sampler kinds the
    in-jit draw must equal the host draw bitwise (the one-stream contract
    behind superstep == sequential), an all-ones availability row must
    select exactly that sampler's uniform cohort (trace replay stays a
    strict generalisation of the uniform stream), a uniform cohort must be
    duplicate-free, and the PRP index map must be an exact bijection on
    ``[0, num_users)``.  Executes tiny draws, like the recompile check."""
    import jax

    from ..fed.core import round_users
    from ..fed.sampling import prp_map

    users = setup["users"]
    a = max(1, users // 2)
    key = jax.random.fold_in(setup["key"], 77)
    sec: Dict[str, Any] = {"ok": True, "num_users": users, "num_active": a,
                           "kinds": {}}
    for kind in ("perm", "prp"):
        host = np.asarray(round_users(key, users, a, sampler=kind))
        jitd = np.asarray(jax.jit(
            lambda kk, _kind=kind: round_users(kk, users, a,
                                               sampler=_kind))(key))
        ones = np.asarray(round_users(key, users, a,
                                      avail=np.ones(users, np.uint8),
                                      sampler=kind))
        rec = {"in_jit_equals_host": bool((host == jitd).all()),
               "all_ones_equals_uniform": bool((host == ones).all()),
               "cohort_distinct": len(set(host.tolist())) == a}
        sec["kinds"][kind] = rec
        if not rec["in_jit_equals_host"]:
            report.fail(sec, "sampler-stream",
                        f"sampler {kind!r}: in-jit draw differs from the "
                        f"host draw -- the superstep stream has forked "
                        f"(host {host.tolist()[:8]} vs jit "
                        f"{jitd.tolist()[:8]})")
        if not rec["all_ones_equals_uniform"]:
            report.fail(sec, "sampler-stream",
                        f"sampler {kind!r}: an all-ones availability row "
                        f"selects {ones.tolist()[:8]} instead of the "
                        f"uniform cohort {host.tolist()[:8]} -- trace "
                        f"replay is no longer a generalisation of the "
                        f"uniform stream")
        if not rec["cohort_distinct"]:
            report.fail(sec, "sampler-stream",
                        f"sampler {kind!r}: uniform cohort carries "
                        f"duplicate ids ({host.tolist()})")
    image = np.sort(np.asarray(prp_map(key, np.arange(users), users)))
    sec["prp_bijection"] = bool((image == np.arange(users)).all())
    if not sec["prp_bijection"]:
        report.fail(sec, "sampler-bijection",
                    f"prp_map is not a bijection on [0, {users}): sorted "
                    f"image {image.tolist()[:12]}...")
    return sec


def flop_budget_check(report: AuditReport, setup,
                      level_prog_names: Dict[float, str],
                      tol: Optional[float] = None) -> Dict[str, Any]:
    """Measured per-level-program FLOP shares vs the analytic shares from
    :func:`~..fed.core.level_flop_shares` (equal client counts per level in
    the audit matrix -> uniform weights), plus strict monotonicity of the
    measured shares in the rate."""
    from ..fed.core import level_flop_shares

    if tol is None:
        tol = FLAGSHIP_FLOP_TOL if setup["flagship"] else SMALL_FLOP_TOL
    rates = sorted(level_prog_names, reverse=True)
    measured = {r: report.programs[level_prog_names[r]].flops for r in rates}
    sec: Dict[str, Any] = {"ok": True, "tol": tol,
                           "measured_flops": {f"{r:g}": measured[r] for r in rates}}
    if any(measured[r] is None for r in rates):
        report.fail(sec, "flop-budget", "cost_analysis unavailable for a "
                    "level program; FLOP budget cannot be audited")
        return sec
    total = sum(measured.values())
    analytic = level_flop_shares(setup["cfg"])
    sec["measured_shares"] = {f"{r:g}": measured[r] / total for r in rates}
    sec["analytic_shares"] = {f"{r:g}": analytic[r] for r in rates}
    for r in rates:
        ms, as_ = measured[r] / total, analytic[r]
        rel = abs(ms - as_) / as_
        if rel > tol:
            report.fail(sec, "flop-budget",
                        f"level {r:g}: measured FLOP share {ms:.4f} vs "
                        f"analytic {as_:.4f} (rel err {rel:.3f} > tol {tol})")
    for hi, lo in zip(rates, rates[1:]):
        if measured[hi] <= measured[lo]:
            report.fail(sec, "flop-monotonicity",
                        f"level {hi:g} program FLOPs ({measured[hi]:.3e}) not "
                        f"above level {lo:g} ({measured[lo]:.3e}): the "
                        f"dense-per-level win has regressed")
    return sec


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def _build_targets(setup):
    """Assemble the full program matrix: ``(targets, level_prog_names)``
    where each target is ``(name, prog, args, expect)``.  Shared by the
    audit proper and the CLI's ``--list``."""
    targets = list(_masked_targets(setup))
    grouped, level_prog_names, _ = _grouped_targets(setup)
    targets.extend(grouped)
    targets.extend(_codec_targets(setup))
    targets.extend(_sched_targets(setup))
    targets.extend(_obs_targets(setup))
    targets.extend(_obs_hist_targets(setup))
    targets.extend(_quarantine_targets(setup))
    targets.extend(_arms_targets(setup))
    return targets, level_prog_names


#: names of the cross-program checks, for ``--list`` (the per-program
#: checks run inside every audited program and have no standalone names)
CROSS_CHECKS = ("flop_budget", "wire_frontier", "sampler", "arms",
                "recompile", "lattice", "key_streams")


def list_targets(flagship: bool = False, seed: int = 0) -> List[str]:
    """Program names of the audit matrix, without auditing anything
    (the target builders only close over setup; nothing is traced)."""
    setup = build_setup(flagship=flagship, seed=seed)
    targets, _ = _build_targets(setup)
    return [name for name, _prog, _args, _expect in targets]


def run_audit(flagship: bool = False, flop_tol: Optional[float] = None,
              seed: int = 0, with_recompile_check: bool = True,
              with_aot: bool = False,
              only: Optional[str] = None) -> AuditReport:
    """The full program audit.  Returns an :class:`AuditReport` (the CLI
    adds lint findings and serialises to STATICCHECK.json).

    ``with_aot`` additionally runs the subprocess v4-128 AOT multi-host
    check (ISSUE 17) and records it under ``config["aot_v4128"]`` -- a
    config record, never a program entry, so the ratchet baseline stays
    environment-stable; a child that RAN and violated the DCN budget
    still fails the audit.

    ``only`` (ISSUE 18): an fnmatch glob over program names; audits the
    matching subset and SKIPS every cross-program check (they reason
    over the full matrix -- a partial run would fabricate findings).
    The CLI refuses ``--only`` + ``--diff-baseline`` for the same
    reason."""
    report = AuditReport()
    setup = build_setup(flagship=flagship, seed=seed)
    report.config = {
        "flagship": flagship,
        "data_name": setup["cfg"]["data_name"],
        "model_name": setup["cfg"]["model_name"],
        "num_users": setup["users"],
        "levels": sorted({float(r) for r in setup["cfg"]["model_rate"]},
                         reverse=True),
        "mesh": dict(zip(setup["mesh"].axis_names,
                         (int(s) for s in setup["mesh"].devices.shape))),
    }
    mesh = setup["mesh"]
    targets, level_prog_names = _build_targets(setup)
    if only is not None:
        report.config["only"] = only
        targets = [t for t in targets if fnmatch.fnmatch(t[0], only)]
    bind_files: Set[str] = set()
    for name, prog, args, expect in targets:
        report.add_program(audit_program(name, prog, args, expect, mesh,
                                         bind_files=bind_files))

    if only is not None:
        skipped = {"ok": True, "skipped": f"--only {only}"}
        report.flop_budget = dict(skipped)
        report.recompile = dict(skipped)
        report.wire_frontier = dict(skipped)
        report.sampler = dict(skipped)
        report.arms = dict(skipped)
        report.lattice = dict(skipped)
        report.key_streams = dict(skipped)
        return report

    report.flop_budget = flop_budget_check(report, setup, level_prog_names,
                                           tol=flop_tol)
    report.wire_frontier = codec_frontier_check(report)
    report.sampler = sampler_stream_check(report, setup)
    report.arms = arms_flop_check(report)

    # ISSUE 18: config-lattice exhaustiveness + RNG-stream provenance.
    # The lattice's program: evidence refs must point at GREEN audited
    # programs; the key-stream pass gets the PRNG bind files collected
    # from every traced jaxpr above.
    from .keys import key_streams_check
    from .lattice import lattice_check

    report.lattice = lattice_check(
        audited={n for n, p in report.programs.items() if p.ok})
    report.ok = report.ok and report.lattice["ok"]
    report.key_streams = key_streams_check(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        bind_files=sorted(bind_files))
    report.ok = report.ok and report.key_streams["ok"]
    if with_recompile_check:
        rc = recompile_hazard_check(setup)
        for which, sizes in list(rc.items()):
            if isinstance(sizes, dict) and \
                    sizes["after_repeat"] > sizes["after_warm"]:
                report.fail(rc, "recompile-hazard",
                            f"{which}: program cache grew "
                            f"{sizes['after_warm']} -> {sizes['after_repeat']} "
                            f"on a fresh-but-identical dispatch (cache-key "
                            f"leak: weak types / python scalars / slot "
                            f"re-bucketing)")
        report.recompile = rc
    if with_aot:
        from .aot import aot_v4128_check

        res = aot_v4128_check(flagship=flagship)
        report.config["aot_v4128"] = res
        if res.get("available") and res.get("ok") is False:
            report.fail(res, "aot-dcn",
                        f"v4-128 AOT audit ({res.get('mode')}): DCN carries "
                        f"{res.get('dcn_bytes_per_round')} bytes/round "
                        f"against a budget of exactly "
                        f"{res.get('budget_bytes')} with "
                        f"{res.get('reshards_jaxpr')} reshard(s)")
    return report


def flop_account(cfg, data, mesh, user_idx, rates,
                 params=None) -> Dict[str, Any]:
    """Masked-vs-grouped compiled FLOP account at an explicit active mix:
    the one implementation behind ``scripts/grouped_flops.py`` and the
    engine-comparison numbers in MEASUREMENTS.md.  Nothing is executed --
    programs are lowered and compiled only.  Counts are per scan-body
    execution (XLA's cost model counts loop bodies once), which cancels in
    every ratio/share."""
    import jax

    from ..analysis import cost_analysis_dict
    from ..fed.core import level_flop_shares
    from ..models import make_model
    from ..parallel import GroupedRoundEngine, RoundEngine

    model = make_model(cfg)
    if params is None:
        params = model.init(jax.random.key(0))
    key, lr = jax.random.key(0), np.float32(0.1)
    data = tuple(data)

    eng = RoundEngine(model, cfg, mesh)
    fix = (eng.fix_rates,) if eng.fix_rates is not None else ()
    ug = np.asarray(user_idx, np.int32)
    masked = cost_analysis_dict(
        eng._build_train().lower(params, key, lr, ug, ug, *(data + fix))
        .compile())["flops"]

    grp = GroupedRoundEngine(cfg, mesh)
    by: Dict[float, List[int]] = {}
    for pos, r in enumerate(np.asarray(rates)):
        by.setdefault(float(r), []).append(pos)
    per_level: Dict[str, float] = {}
    sums, cnts = [], []
    for r in sorted(by, reverse=True):
        u = np.asarray(ug[by[r]], np.int32)
        prog = grp._level_prog(r, len(u))
        per_level[f"{r:g}"] = cost_analysis_dict(
            prog.lower(params, key, lr, u, *data).compile())["flops"]
        # avals only (nothing executes): the combine lowering needs the
        # level partials' shapes/dtypes, not values
        s, c, _ = jax.eval_shape(prog, params, key, lr, u, *data)
        sums.append(s)
        cnts.append(c)
    combine = cost_analysis_dict(
        grp._combine_prog(len(sums)).lower(params, sums, cnts).compile())["flops"]
    grouped_total = sum(per_level.values()) + combine
    weights = {r: float(len(p)) for r, p in by.items()}
    return {
        "masked_flops_per_round": masked,
        "grouped_flops_per_round": grouped_total,
        "grouped_per_level_flops": per_level,
        "combine_flops": combine,
        "flop_ratio_masked_over_grouped": round(masked / grouped_total, 3),
        "analytic_level_shares": {f"{r:g}": v for r, v in
                                  level_flop_shares(cfg, weights).items()},
    }
