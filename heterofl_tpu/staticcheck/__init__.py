"""Static analysis for the round engines: AST lint + compiled-program audit.

Two fronts, one gate (ISSUE 3):

* :mod:`.rules` -- path-scoped banned-call lint over the package source
  (``jnp.asarray`` wraps, ``float()`` coercions, undonated ``jax.jit``,
  wall-clock/fresh-RNG calls in steady-state code), suppressible per line
  with ``# staticcheck: allow(<rule-id>)`` pragmas.  Pure-AST, jax-free,
  runs in milliseconds.
* :mod:`.audit` -- lowers the flagship round programs (masked + grouped
  engines x span/slices placements x ``superstep_rounds`` in {1, 8}) on a
  CPU mesh and walks the jaxpr/StableHLO/optimized-HLO to enforce: no host
  callbacks or f64 in any round program, full donation coverage (every
  donated leaf consumed by input-output aliasing, donation warnings
  promoted to failures), the collectives budget (exactly ONE global psum
  per fused round, axes resolvable in the mesh), recompile-hazard freedom
  (fresh-but-identical host inputs leave the program cache untouched), the
  FLOP budget (``cost_analysis()`` per level vs the analytic shares from
  :func:`~..fed.core.level_flop_shares`), and the ISSUE 7 passes: the
  bytes-on-the-wire budget (:mod:`.wire`, enforced by equality against
  ``fed.core.level_byte_table``), the HBM footprint budget
  (:mod:`.memory`), and the reshard detector (zero data-movement
  collectives, jaxpr and optimized-HLO halves).
* :mod:`.ratchet` -- every audited metric diffed against the committed
  ``STATICCHECK_BASELINE.json`` with per-metric tolerances
  (``--diff-baseline`` exits 2 on regression; ``--update-baseline``
  re-pins after an intentional change).

CLI: ``python -m heterofl_tpu.staticcheck --json`` (exits non-zero on any
finding; writes the ``STATICCHECK.json`` artifact ``bench.py`` folds into
``extra.staticcheck``).

This module stays import-light (no jax): the CLI must scrub the TPU-tunnel
env hooks before any backend initialises, and the lint front must be
usable without booting a platform.
"""

from .report import AuditReport, Finding, ProgramReport  # noqa: F401
from .rules import DEFAULT_RULES, lint_paths, lint_tree  # noqa: F401

__all__ = [
    "AuditReport", "Finding", "ProgramReport",
    "DEFAULT_RULES", "lint_paths", "lint_tree",
    "run_audit",
]


def run_audit(*args, **kwargs):
    """Lazy forwarder to :func:`.audit.run_audit` (imports jax)."""
    from .audit import run_audit as _run

    return _run(*args, **kwargs)
