"""Wire model (ISSUE 7 tentpole): static bytes-on-the-wire accounting for
every collective in an audited program.

HeteroFL's headline claim is *communication* efficiency, but until this
module the auditor only counted psum binds -- it never measured the bytes
they move.  Here every collective bind in a traced program is priced from
its operand avals (shape x dtype x participating mesh-axis size) and
classified by link class:

* **payload_bytes** -- the per-participant logical reduction payload (the
  sum of operand aval bytes at the bind; under ``shard_map`` the operands
  are per-device values, so this is exactly what each participant
  contributes).
* **ring_bytes_per_device** -- the per-participant wire traffic of a
  bidirectional-ring all-reduce, ``2 (p-1)/p x payload`` (reduce-scatter +
  all-gather phases): the standard lower bound, and the number the
  compression PR will shrink.
* **scope** -- ``ici`` (intra-slice interconnect) vs ``dcn`` (data-center
  network): a collective is DCN-eligible when any of its mesh axes crosses
  a process boundary (:func:`dcn_axes_of`).  On the single-process audit
  mesh everything is ICI; the multi-host slices work must keep the DCN
  budget at exactly the one global reduction per round.

The enforced budget (``wire-budget``): the single-axis ``clients`` psums of
a fused training round must move EXACTLY ``sum(param_bytes) + count_bytes``
-- one dense global reduction of the program's level footprint, both trees
f32 (:func:`~..fed.core.level_byte_table` supplies the analytic number,
which matches the traced operand avals bit-for-bit).  The eval phase's
joint (clients, data) reductions are budgeted separately
(``wire-eval-budget``): every traced eval point must move the identical
payload set.  ``wire-dcn`` holds cross-slice bytes to the per-program DCN
budget (zero today).

Import-light on purpose (no jax at module level): ``bench.py``'s
``extra.wire`` record and the report plumbing use the analytic half
without booting a backend.
"""

from __future__ import annotations

from collections import Counter
from typing import Any, Dict, Optional, Sequence, Tuple

#: the training-round reduction axis and the eval phase's joint axes --
#: must match the audit's psum budget split (audit.py counts them the same
#: way)
TRAIN_AXIS = "clients"
EVAL_AXES = ("clients", "data")


def dcn_axes_of(mesh) -> Tuple[str, ...]:
    """Mesh axes whose traversal crosses a process boundary: collectives
    binding such an axis are DCN-eligible (their reduction cannot complete
    on intra-slice links alone).  Derived from the device array's
    ``process_index`` grid, so a multi-host mesh classifies itself --
    nothing to configure when the pod-scale slices placement lands."""
    import numpy as np

    devs = np.asarray(mesh.devices)
    names = tuple(mesh.axis_names)
    out = []
    for i in range(devs.ndim):
        moved = np.moveaxis(devs, i, 0).reshape(devs.shape[i], -1)
        for col in range(moved.shape[1]):
            procs = {getattr(d, "process_index", 0) for d in moved[:, col]}
            if len(procs) > 1:
                out.append(names[i])
                break
    return tuple(out)


def classify(axes: Sequence[str], dcn_axes: Sequence[str]) -> str:
    """Link class of a collective binding ``axes``: ``dcn`` when any bound
    axis crosses a slice boundary, else ``ici``."""
    return "dcn" if any(a in dcn_axes for a in axes) else "ici"


def participants_of(axes: Sequence[str], mesh) -> int:
    """Number of devices participating in a collective over ``axes``."""
    shape = dict(zip(mesh.axis_names, mesh.devices.shape))
    n = 1
    for a in axes:
        n *= int(shape.get(a, 1))
    return n


def ring_allreduce_bytes(payload_bytes: int, participants: int) -> int:
    """Per-participant wire traffic of a bidirectional-ring all-reduce:
    ``2 (p-1)/p x payload`` (reduce-scatter then all-gather).  Zero for a
    single participant (the reduction is local)."""
    if participants <= 1:
        return 0
    return int(round(2.0 * (participants - 1) / participants * payload_bytes))


def program_wire(jaxpr, mesh, dcn_axes: Optional[Sequence[str]] = None
                 ) -> Dict[str, Any]:
    """The per-program wire table: one priced row per collective bind plus
    the totals the budget checks and the ratchet consume.

    ``train_bytes_per_round`` sums the single-axis psums binding
    :data:`TRAIN_AXIS` (one bind per fused round -- scan bodies execute it
    once per round, so the bind payload IS the per-round wire cost);
    ``eval_bytes_total`` sums the joint ``(clients, data)`` psums (the eval
    phase's sBN-moment + Global-metric reductions, one pair per traced
    eval point); everything else lands in ``other_bytes`` (zero in every
    green program)."""
    from .jaxpr_walk import collective_payload_rows

    if dcn_axes is None:
        dcn_axes = dcn_axes_of(mesh)
    rows = []
    train = eval_total = other = dcn_total = 0
    eval_payloads = []
    for r in collective_payload_rows(jaxpr):
        axes = tuple(r["axes"])
        p = participants_of(axes, mesh)
        scope = classify(axes, dcn_axes)
        rows.append({**r, "participants": p, "scope": scope,
                     "ring_bytes_per_device":
                         ring_allreduce_bytes(r["payload_bytes"], p)})
        if r["primitive"] == "psum" and all(a in axes for a in EVAL_AXES):
            eval_total += r["payload_bytes"]
            eval_payloads.append(r["payload_bytes"])
        elif r["primitive"] == "psum" and TRAIN_AXIS in axes:
            train += r["payload_bytes"]
        else:
            other += r["payload_bytes"]
        if scope == "dcn":
            dcn_total += r["payload_bytes"]
    return {
        "collectives": rows,
        "train_bytes_per_round": train,
        "train_ring_bytes_per_device":
            ring_allreduce_bytes(train, participants_of((TRAIN_AXIS,), mesh)),
        "eval_bytes_total": eval_total,
        "eval_payloads": sorted(eval_payloads),
        "other_bytes": other,
        "dcn_bytes": dcn_total,
        "dcn_axes": list(dcn_axes),
    }


def check_wire(rep, wire: Dict[str, Any], expected_train_bytes: int,
               n_eval_points: int, dcn_budget_bytes: int = 0,
               dcn_exact: bool = False) -> None:
    """Enforce the wire budgets on one program report (``rep`` is a
    :class:`~.report.ProgramReport`).

    * ``wire-budget``: the training reduction moves exactly
      ``expected_train_bytes`` per round (today: one dense global psum of
      the level's ``sum(param_bytes) + count_bytes``).  An extra psum, a
      widened operand or a smuggled dtype all land here with the measured
      vs budgeted bytes.
    * ``wire-eval-budget``: each of the ``n_eval_points`` traced eval
      points moves the identical payload multiset (the sBN + Global pair);
      a lopsided point means an eval reduction forked.
    * ``wire-dcn``: cross-slice bytes within ``dcn_budget_bytes`` (zero on
      the single-slice audit mesh).  ``dcn_exact=True`` -- the multi-host
      variants (ISSUE 17) -- tightens the bound to EQUALITY: DCN must
      carry exactly one dense level-a reduction per training round,
      nothing more (a smuggled reshard) and nothing less (the reduction
      silently left the cross-host axis).
    * ``wire-unbudgeted``: collectives outside the train/eval buckets
      (``pmax``/``pmin``/``reduce_scatter``/``all_gather`` binds, psums
      over other axis sets) move ZERO bytes -- a reduction smuggled past
      the psum bind count still shows up here by its payload."""
    got = wire["train_bytes_per_round"]
    if got != expected_train_bytes:
        rep.fail("wire-budget",
                 f"training-round collective payload is {got} bytes/round, "
                 f"budget is exactly {expected_train_bytes} (one dense "
                 f"global reduction of sum(param_bytes) + count_bytes at "
                 f"this program's level)")
    if n_eval_points > 0:
        per_payload = Counter(wire["eval_payloads"])
        bad = {pay: n for pay, n in per_payload.items()
               if n % n_eval_points != 0}
        if bad or not per_payload:
            rep.fail("wire-eval-budget",
                     f"eval payloads {dict(per_payload)} do not divide into "
                     f"{n_eval_points} identical eval points (sBN + Global "
                     f"pair per point)")
        wire["eval_bytes_per_point"] = wire["eval_bytes_total"] // n_eval_points
    elif wire["eval_bytes_total"]:
        rep.fail("wire-eval-budget",
                 f"{wire['eval_bytes_total']} joint (clients, data) psum "
                 f"bytes in a program with no eval points")
    if wire["other_bytes"]:
        others = [r for r in wire["collectives"]
                  if not (r["primitive"] == "psum"
                          and (all(a in r["axes"] for a in EVAL_AXES)
                               or TRAIN_AXIS in r["axes"]))]
        rep.fail("wire-unbudgeted",
                 f"{wire['other_bytes']} collective bytes outside the "
                 f"train/eval budgets "
                 f"({[(r['primitive'], r['axes']) for r in others]}): every "
                 f"byte on the wire must ride the budgeted reductions")
    if dcn_exact and wire["dcn_bytes"] != dcn_budget_bytes:
        rep.fail("wire-dcn",
                 f"{wire['dcn_bytes']} cross-slice (DCN) collective bytes, "
                 f"budget is EXACTLY {dcn_budget_bytes} (one dense level-a "
                 f"reduction per training round on a multi-process mesh): "
                 f"either a second cross-host transfer crept in or the "
                 f"training reduction left the cross-host axis (axes "
                 f"{wire['dcn_axes']})")
    elif wire["dcn_bytes"] > dcn_budget_bytes:
        rep.fail("wire-dcn",
                 f"{wire['dcn_bytes']} cross-slice (DCN) collective bytes, "
                 f"budget is {dcn_budget_bytes}: a reshard or a second "
                 f"cross-slice reduction crept in (axes {wire['dcn_axes']})")


def link_split(payload_bytes: int, participants: int,
               processes: int = 1) -> Dict[str, int]:
    """Analytic per-link ICI-vs-DCN byte split of one bidirectional-ring
    all-reduce (ISSUE 17 satellite: ``bench.py``'s ``extra.wire`` record).

    A ring over ``p`` participants has ``p`` links, each carrying the same
    ``2 (p-1)/p x payload`` bytes (reduce-scatter + all-gather, the
    :func:`ring_allreduce_bytes` number).  With the participants laid out
    as ``h`` contiguous per-process blocks (the host-aligned slices
    placement), exactly ``h`` of those links cross a process boundary --
    the scarce DCN links (PAPERS.md 2405.20431); the remaining ``p - h``
    stay on intra-host ICI.  ``processes <= 1`` puts every byte on ICI.
    Import-light like the rest of the analytic half (no jax)."""
    p = max(1, int(participants))
    h = max(1, int(processes))
    per_link = ring_allreduce_bytes(payload_bytes, p)
    dcn_links = h if (h > 1 and p > 1) else 0
    ici_links = (p if p > 1 else 0) - dcn_links
    return {
        "participants": p,
        "processes": h,
        "bytes_per_link": per_link,
        "dcn_links": dcn_links,
        "ici_links": ici_links,
        "dcn_bytes_total": dcn_links * per_link,
        "ici_bytes_total": ici_links * per_link,
    }


def codec_round_wire(codec: str, payload_bytes: int, dense_bytes: int,
                     participants: int) -> Dict[str, Any]:
    """The analytic COMPRESSED-aggregation wire record for one training
    round under ``codec`` (ISSUE 8): what ``bench.py`` writes into
    ``extra.wire`` alongside the dense baseline.  ``payload_bytes`` must
    come from :func:`~..fed.core.level_codec_byte_table` -- the same table
    the staticcheck wire budget enforces by equality against the traced
    psum operand avals, so there is no second bytes formula."""
    return {
        "format": codec,
        "payload_bytes_per_round": int(payload_bytes),
        "dense_bytes_per_round": int(dense_bytes),
        "ratio_vs_dense": round(payload_bytes / dense_bytes, 6),
        "reduction_x": round(dense_bytes / payload_bytes, 3),
        "ring_allreduce_bytes_per_device":
            ring_allreduce_bytes(payload_bytes, participants),
        "participants": int(participants),
    }


def dense_round_wire(param_bytes: int, participants: int,
                     count_bytes: Optional[int] = None) -> Dict[str, Any]:
    """The analytic dense-aggregation wire record for one training round:
    what ``bench.py`` writes into ``extra.wire`` so the compressed-
    aggregation frontier lands against a recorded dense baseline.  One
    global reduction of the update sums plus the count masks (both
    param-shaped f32 -> ``count_bytes`` defaults to ``param_bytes``)."""
    if count_bytes is None:
        count_bytes = param_bytes
    payload = param_bytes + count_bytes
    return {
        "format": "dense-f32",
        "param_bytes": int(param_bytes),
        "count_bytes": int(count_bytes),
        "payload_bytes_per_round": int(payload),
        "ring_allreduce_bytes_per_device":
            ring_allreduce_bytes(payload, participants),
        "participants": int(participants),
    }
