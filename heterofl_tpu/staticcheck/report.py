"""Finding/report containers shared by the lint and audit fronts.

Kept jax-free: the lint front and the CLI's report plumbing must import
without booting a JAX backend (the CLI scrubs the TPU-tunnel env hooks
before jax loads).
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field
from typing import Any, Dict, List, Optional


@dataclass
class Finding:
    """One violation.  ``where`` is ``path:line`` for lint findings and the
    program name (plus op provenance when known) for audit findings."""

    rule: str
    where: str
    message: str

    def __str__(self) -> str:  # `path:line: [rule] message` -- grep-friendly
        return f"{self.where}: [{self.rule}] {self.message}"


@dataclass
class ProgramReport:
    """Audit result for one lowered/compiled program."""

    name: str
    ok: bool = True
    findings: List[Finding] = field(default_factory=list)
    #: psum binds over the ``clients`` axis alone (the per-training-round
    #: global-collective budget; eval-phase joint reductions are separate)
    psum_clients: int = 0
    #: psum binds over ``(clients, data)`` jointly -- the eval-fused
    #: superstep's sBN + Global reductions, audited as their own budget
    psum_eval: int = 0
    all_gather: int = 0
    #: collective axis names seen in the program
    collective_axes: List[str] = field(default_factory=list)
    #: donation: leaves marked for donation at lowering / consumed by
    #: input-output aliasing in the optimized HLO / expected count
    donated: int = 0
    aliased: int = 0
    donation_expected: int = 0
    flops: Optional[float] = None
    memory: Optional[Dict[str, int]] = None
    #: analytic HBM bound the memory fields were held to, plus the
    #: donation-savings accounting (ISSUE 7: staticcheck/memory.py)
    memory_budget: Optional[Dict[str, Any]] = None
    #: per-collective bytes-on-the-wire table + train/eval/DCN totals
    #: (ISSUE 7: staticcheck/wire.py)
    wire: Optional[Dict[str, Any]] = None
    #: explicit (jaxpr) + GSPMD-introduced (optimized HLO) reshard op
    #: counts; zero allowed (ISSUE 7 reshard detector)
    reshards: Optional[Dict[str, Any]] = None
    #: optimized-HLO kernel stats of the program's scan body (the local-step
    #: loop): fusion launches + instruction count per iteration, and the
    #: budget enforced against it (None = recorded, not budgeted)
    step_body: Optional[Dict[str, Any]] = None
    step_body_budget: Optional[int] = None

    def fail(self, rule: str, message: str) -> None:
        self.ok = False
        self.findings.append(Finding(rule, self.name, message))


@dataclass
class AuditReport:
    """The whole staticcheck run: lint findings + per-program audits +
    cross-program checks, serialisable to STATICCHECK.json."""

    ok: bool = True
    config: Dict[str, Any] = field(default_factory=dict)
    programs: Dict[str, ProgramReport] = field(default_factory=dict)
    flop_budget: Dict[str, Any] = field(default_factory=dict)
    recompile: Dict[str, Any] = field(default_factory=dict)
    #: analytic flagship compression frontier (ISSUE 8:
    #: audit.codec_frontier_check) -- per-codec payload bytes vs dense,
    #: with the int8 <= 25%-of-dense acceptance line enforced
    wire_frontier: Dict[str, Any] = field(default_factory=dict)
    #: sampling-stream consistency (ISSUE 11: audit.sampler_stream_check)
    #: -- in-jit == host draw bitwise for both sampler kinds, all-ones
    #: availability == uniform cohort, PRP exact bijection
    sampler: Dict[str, Any] = field(default_factory=dict)
    #: arms-axis FLOP linearity (ISSUE 14: audit.arms_flop_check) -- an
    #: E-arm program's compiled FLOPs == E x its unbatched twin's
    arms: Dict[str, Any] = field(default_factory=dict)
    #: config-lattice exhaustiveness (ISSUE 18: lattice.lattice_check) --
    #: every point of the declared feature lattice classified SUPPORTED
    #: (audited anchor / equivalence contract) or REFUSED (typed
    #: ValueError from exactly one resolve_* validator); UNREACHED
    #: points are findings
    lattice: Dict[str, Any] = field(default_factory=dict)
    #: RNG-stream provenance (ISSUE 18: keys.key_streams_check) -- the
    #: salt/fold_in graph: interval disjointness per root, pinned salt
    #: constants, declared fold sites, raw-key reuse, jaxpr bind roots
    key_streams: Dict[str, Any] = field(default_factory=dict)
    lint: List[Finding] = field(default_factory=list)
    #: baseline-ratchet diff (ISSUE 7: staticcheck/ratchet.py).  ``checked``
    #: is False unless the CLI ran ``--diff-baseline``; a regressed ratchet
    #: keeps ``ok`` True (the audit itself is green) but exits 2 and makes
    #: bench.py refuse to record.
    ratchet: Dict[str, Any] = field(default_factory=lambda: {"checked": False})
    generated_at: Optional[str] = None

    def add_program(self, prog: ProgramReport) -> None:
        self.programs[prog.name] = prog
        self.ok = self.ok and prog.ok

    def add_lint(self, findings: List[Finding]) -> None:
        self.lint.extend(findings)
        self.ok = self.ok and not findings

    def fail(self, section: Dict[str, Any], rule: str, message: str) -> None:
        """Record a cross-program failure in ``section`` (flop_budget /
        recompile) and flip the report."""
        self.ok = False
        section.setdefault("findings", []).append(
            asdict(Finding(rule, "audit", message)))
        section["ok"] = False

    def all_findings(self) -> List[Finding]:
        out = list(self.lint)
        for p in self.programs.values():
            out.extend(p.findings)
        for sec in (self.flop_budget, self.recompile, self.wire_frontier,
                    self.sampler, self.arms, self.lattice, self.key_streams):
            out.extend(Finding(**f) for f in sec.get("findings", []))
        return out

    def to_dict(self) -> Dict[str, Any]:
        return {
            "version": 2,  # 2: + per-program wire/memory/reshards, ratchet
            "ok": self.ok,
            "generated_at": self.generated_at,
            "config": self.config,
            "programs": {k: asdict(v) for k, v in self.programs.items()},
            "flop_budget": self.flop_budget,
            "recompile": self.recompile,
            "wire_frontier": self.wire_frontier,
            "sampler": self.sampler,
            "arms": self.arms,
            "lattice": self.lattice,
            "key_streams": self.key_streams,
            "ratchet": self.ratchet,
            "lint": [asdict(f) for f in self.lint],
        }

    def to_json(self, indent: Optional[int] = 2) -> str:
        return json.dumps(self.to_dict(), indent=indent, sort_keys=True)
