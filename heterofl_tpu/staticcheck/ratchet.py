"""Baseline ratchet (ISSUE 7 tentpole): every audited metric becomes
diffable -- and non-regressable -- against a committed baseline.

The analytic budgets in :mod:`.wire` and :mod:`.memory` are ceilings; the
ratchet is the tight line.  ``STATICCHECK_BASELINE.json`` (repo root,
committed) pins the per-program metric view of a known-good audit:
collective counts and wire bytes (exact -- they are pure functions of
shapes), donation coverage (exact), scan-body fusion/instruction counts
and memory bytes and FLOPs (small relative headroom for compiler/platform
variance).  ``python -m heterofl_tpu.staticcheck --diff-baseline``
structurally diffs a fresh audit against it and exits 2 on any regression
(1 stays the audit/lint failure code); ``--update-baseline`` re-pins after
an intentional change.  ``bench.py`` refuses to record a run whose
artifact carries a regressed ratchet section, the same way it refuses a
failing audit.

jax-free: the diff works on report dicts, so CI and tests can exercise it
without lowering anything.
"""

from __future__ import annotations

import json
from typing import Any, Dict, List, Optional, Sequence, Tuple

BASELINE_BASENAME = "STATICCHECK_BASELINE.json"

#: per-program metric table: (label, path into the serialised
#: ProgramReport, relative headroom, mode).  ``up_bad``: growth beyond the
#: headroom is a regression, shrinkage an improvement; ``change_bad``: any
#: drift regresses (donation coverage has one right answer).  Exact (0.0)
#: headroom for everything that is a pure function of program shapes;
#: small headroom where codegen/platform variance moves the number.
PROGRAM_METRICS: Tuple[Tuple[str, Tuple[str, ...], float, str], ...] = (
    ("psum_clients", ("psum_clients",), 0.0, "up_bad"),
    ("psum_eval", ("psum_eval",), 0.0, "up_bad"),
    ("all_gather", ("all_gather",), 0.0, "up_bad"),
    ("donated", ("donated",), 0.0, "change_bad"),
    ("aliased", ("aliased",), 0.0, "change_bad"),
    ("wire.train_bytes_per_round",
     ("wire", "train_bytes_per_round"), 0.0, "up_bad"),
    ("wire.eval_bytes_total", ("wire", "eval_bytes_total"), 0.0, "up_bad"),
    ("wire.other_bytes", ("wire", "other_bytes"), 0.0, "up_bad"),
    ("wire.dcn_bytes", ("wire", "dcn_bytes"), 0.0, "up_bad"),
    ("reshards.total", ("reshards", "total"), 0.0, "up_bad"),
    ("step_body.fusions", ("step_body", "fusions"), 0.15, "up_bad"),
    ("step_body.instructions", ("step_body", "instructions"), 0.15, "up_bad"),
    ("memory.temp_size_in_bytes",
     ("memory", "temp_size_in_bytes"), 0.25, "up_bad"),
    ("memory.argument_size_in_bytes",
     ("memory", "argument_size_in_bytes"), 0.10, "up_bad"),
    ("memory.output_size_in_bytes",
     ("memory", "output_size_in_bytes"), 0.25, "up_bad"),
    ("flops", ("flops",), 0.10, "up_bad"),
)

#: audit-config keys that must match for a diff to be meaningful at all
CONFIG_KEYS = ("flagship", "data_name", "model_name", "num_users", "levels",
               "mesh")

#: cross-program coverage counters pinned by the baseline (ISSUE 18):
#: the declared config lattice and key-stream provenance graph must
#: never silently SHRINK -- dropping an axis value, a registry row, or
#: a declared fold_in site without re-pinning is a ratchet regression
#: (growth is recorded as an improvement).  Finding-grade properties
#: (unreached points, salt collisions) fail the audit itself and need
#: no headroom here.
COVERAGE_METRICS: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("lattice.points", ("lattice", "points")),
    ("lattice.refusal_rules", ("lattice", "refusal_rules")),
    ("key_streams.fold_in_sites", ("key_streams", "fold_in_sites")),
    ("key_streams.registry_rows", ("key_streams", "registry_rows")),
)


def _get(d: Optional[Dict[str, Any]], path: Sequence[str]):
    for k in path:
        if not isinstance(d, dict) or k not in d:
            return None
        d = d[k]
    return d


def baseline_view(report_dict: Dict[str, Any]) -> Dict[str, Any]:
    """The committed shape: config subset + per-program metric values.
    Stored instead of the full report so baseline diffs in review stay
    readable (one line per metric, no HLO body names or provenance)."""
    programs = {}
    for name, prog in sorted((report_dict.get("programs") or {}).items()):
        programs[name] = {label: _get(prog, path)
                          for label, path, _tol, _mode in PROGRAM_METRICS}
    coverage = {}
    for label, path in COVERAGE_METRICS:
        v = _get(report_dict, path)
        coverage[label] = len(v) if isinstance(v, list) else v
    return {
        "version": 2,
        "generated_at": report_dict.get("generated_at"),
        "config": {k: (report_dict.get("config") or {}).get(k)
                   for k in CONFIG_KEYS},
        "programs": programs,
        "coverage": coverage,
    }


def diff_reports(current_dict: Dict[str, Any],
                 baseline: Dict[str, Any]) -> Dict[str, Any]:
    """Structural diff of a fresh report against a committed baseline view.

    Returns the ``ratchet`` section: ``ok`` is False on any regression --
    a metric past its headroom, a metric that went dark (None where the
    baseline had a number), a baseline program missing from the fresh
    audit, or an incomparable audit config.  Improvements (metrics that
    shrank) and brand-new programs are recorded, never failed: the ratchet
    only tightens."""
    out: Dict[str, Any] = {"checked": True, "ok": True,
                           "baseline_generated_at": baseline.get("generated_at"),
                           "regressions": [], "improvements": [],
                           "new_programs": [], "missing_programs": []}

    def regress(program, metric, base, cur, tol, msg):
        out["ok"] = False
        out["regressions"].append({
            "program": program, "metric": metric, "baseline": base,
            "current": cur, "tolerance": tol, "message": msg})

    cur_cfg = {k: (current_dict.get("config") or {}).get(k)
               for k in CONFIG_KEYS}
    base_cfg = baseline.get("config") or {}
    if cur_cfg != base_cfg:
        regress("<config>", "config", base_cfg, cur_cfg, 0.0,
                "audit config differs from the baseline's; the diff is "
                "apples-to-oranges -- re-pin with --update-baseline if the "
                "config change is intentional")
        return out

    cur_full = baseline_view(current_dict)
    base_cov = baseline.get("coverage") or {}
    for label, _path in COVERAGE_METRICS:
        base, cur = base_cov.get(label), cur_full["coverage"].get(label)
        if base is None:
            continue  # counter not pinned by this baseline
        if cur is None:
            regress("<coverage>", label, base, None, 0.0,
                    "coverage counter recorded in the baseline is absent "
                    "from the fresh audit (the measurement went dark)")
        elif cur < base:
            regress("<coverage>", label, base, cur, 0.0,
                    "declared coverage shrank below the pinned baseline -- "
                    "re-pin with --update-baseline if the removal is "
                    "intentional")
        elif cur > base:
            out["improvements"].append(
                {"program": "<coverage>", "metric": label,
                 "baseline": base, "current": cur})

    cur_view = cur_full["programs"]
    base_progs = baseline.get("programs") or {}
    for name in sorted(set(base_progs) - set(cur_view)):
        out["ok"] = False
        out["missing_programs"].append(name)
        regress(name, "<program>", "audited", "absent", 0.0,
                "program audited in the baseline is missing from the fresh "
                "audit: the matrix shrank")
    out["new_programs"] = sorted(set(cur_view) - set(base_progs))

    for name in sorted(set(base_progs) & set(cur_view)):
        base_m, cur_m = base_progs[name], cur_view[name]
        for label, _path, tol, mode in PROGRAM_METRICS:
            base, cur = base_m.get(label), cur_m.get(label)
            if base is None:
                continue  # metric not pinned by this baseline
            if cur is None:
                regress(name, label, base, None, tol,
                        "metric recorded in the baseline is absent from the "
                        "fresh audit (the measurement went dark)")
                continue
            if mode == "change_bad":
                if cur != base:
                    regress(name, label, base, cur, 0.0,
                            "exact metric drifted")
                continue
            limit = base * (1.0 + tol)
            if cur > limit:
                regress(name, label, base, cur, tol,
                        f"grew past the baseline by more than "
                        f"{tol:.0%} headroom" if tol else
                        "grew past the exact baseline")
            elif cur < base:
                out["improvements"].append(
                    {"program": name, "metric": label, "baseline": base,
                     "current": cur})
    return out


def load_baseline(path: str) -> Dict[str, Any]:
    with open(path) as f:
        return json.load(f)


def write_baseline(path: str, report_dict: Dict[str, Any]) -> None:
    with open(path, "w") as f:
        json.dump(baseline_view(report_dict), f, indent=2, sort_keys=True)
        f.write("\n")
