"""Key-stream audit (ISSUE 18): the PRNG salt/fold_in provenance graph.

The repo derives every random stream by ``jax.random.fold_in`` from a
small set of named roots (the driver host key, the per-epoch/round keys,
the per-client slot keys, ...).  Correctness of the engine-equivalence
contracts and of the fault-tolerance replay story rests on those streams
being DISJOINT: two different purposes must never fold the same salt
into the same root, or their "independent" draws are bit-identical
copies of one another.  That property is invisible at runtime -- a
collision produces valid-looking numbers -- so this module proves it
statically:

* ``SALT_REGISTRY`` declares every ``fold_in`` site in the package as a
  ``(root, stream)`` edge of the provenance graph.  The AST scanner
  walks the real tree; a fold site the registry does not recognise is a
  ``key-undeclared-stream`` finding, a registry row matching no site is
  ``key-registry-stale`` (the declaration rotted).
* ``ROOTS`` declares, per root, the integer interval each stream's salts
  occupy.  Overlapping intervals under one root are ``key-salt-collision``
  findings.  This is the check that catches the two real collisions the
  audit was built on: the flat ``fold_in(round_key, 13 + uid)`` client
  derivation whose uid family swallowed the failure salt 98 and the
  deadline salt 131, and ``ARM_STREAM_SALT = 17`` sitting inside the
  host key's per-round epoch family (round 17's key WAS the arms root).
* ``SALT_CONSTANTS`` pins every module-level ``*_SALT`` constant by
  value; drift (changed, added or deleted constants) is
  ``key-salt-drift`` -- a salt cannot move without this table moving
  with it, which forces the interval review above.
* A per-function scan flags a raw key consumed by two or more
  ``jax.random`` draws (``key-raw-reuse``): reusing an unsplit key makes
  the two draws correlated.
* ``check_binds`` receives, from the compiled-program audit, the source
  files of every in-jaxpr ``random_*`` bind; a bind originating from a
  package file the registry does not model is ``key-unrooted-bind`` --
  randomness with no declared (salt, purpose) ancestry.

Everything here is stdlib-only (ast + re): the pass must run where jax
is absent, and the registry doubles as the human-readable inventory of
every random stream in the system.
"""
from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

#: Findings lists inside the report section are capped (the section is
#: evidence, not an enumeration) -- same cap as the lattice pass.
MAX_FINDING_SAMPLES = 12

#: Modeled bounds of the symbolic fold families.  These are the audit's
#: declared envelopes, deliberately generous: epochs/rounds are 1-based
#: and bounded by NUM_ROUNDS_BOUND, per-client uids by NUM_USERS_BOUND,
#: watchdog retries by MAX_RETRIES_BOUND, arm sweep seeds by MAX_ARMS.
NUM_ROUNDS_BOUND = 4096
NUM_USERS_BOUND = 4096
MAX_RETRIES_BOUND = 32
MAX_ARMS_BOUND = 64

#: Module-level ``*_SALT`` constants, pinned by value.  The scanner
#: diffs the real tree against this table; any drift is a finding, so a
#: salt cannot change silently -- changing one forces a review of the
#: interval declarations below.
SALT_CONSTANTS: Dict[str, Dict[str, int]] = {
    "compress/codecs.py": {"QUANT_NOISE_SALT": 9173, "TOPK_BLOCK_SALT": 9177},
    "fed/core.py": {
        "ROUND_RATE_SALT": 7,
        "USER_SAMPLE_SALT": 11,
        "CLIENT_STREAM_SALT": 13,
        "FAILURE_STREAM_SALT": 98,
        "ARM_STREAM_SALT": 0x4152,
    },
    "fed/sampling.py": {"PRP_KEY_SALT": 23},
    "obs/watchdog.py": {"RETRY_SALT": 0x5EED},
    "sched/deadline.py": {"DEADLINE_SALT": 131},
}

#: Per-root stream intervals ``(stream, lo, hi)`` with ``hi`` exclusive;
#: ``(stream, None, None)`` declares a symbolic single-family stream
#: (e.g. a dropout site id) that is exempt from the interval check
#: because it is the only family folded into that root at that layer.
#: Within one root every bounded interval must be disjoint from every
#: other -- that IS the no-collision proof.
ROOTS: Dict[str, Tuple[Tuple[str, Optional[int], Optional[int]], ...]] = {
    # The driver's host key: params init (0), the per-round epoch keys
    # (1-based, one per round), the arms salt root, the watchdog's
    # replayed-retry window.  ARM_STREAM_SALT's old value 17 overlapped
    # the epoch family here.
    "host_key": (
        ("init", 0, 1),
        ("epoch", 1, 1 + NUM_ROUNDS_BOUND),
        ("arms", 0x4152, 0x4152 + 1),
        ("retry", 0x5EED, 0x5EED + MAX_RETRIES_BOUND),
    ),
    # The per-round key handed to the engines: every subsystem folds its
    # own named salt before drawing.  The client/failure streams moved
    # to sub-roots (13/98) exactly so the unbounded uid family below
    # cannot creep into this namespace.
    "round_key": (
        ("rate", 7, 8),
        ("user-sample", 11, 12),
        ("client-stream", 13, 14),
        ("failure", 98, 99),
        ("deadline", 131, 132),
        ("quant-noise", 9173, 9174),
        ("topk-block", 9177, 9178),
    ),
    # The per-client slot key inside local training: epoch-shuffle root,
    # per-step augmentation keys, per-step model-apply rng.
    "client_key": (
        ("epoch-perm", 1, 2),
        ("augment", 2, 2 + NUM_ROUNDS_BOUND),
        ("model-rng", 5000, 5000 + NUM_ROUNDS_BOUND),
    ),
    # fold_in(round_key, CLIENT_STREAM_SALT) -> per-uid slot keys.
    "client_stream_root": (("uid", 0, NUM_USERS_BOUND),),
    # fold_in(round_key, FAILURE_STREAM_SALT) -> per-uid crash draws.
    "failure_root": (("uid", 0, NUM_USERS_BOUND),),
    # fold_in(round_key, DEADLINE_SALT) -> per-uid step budgets.
    "deadline_root": (("uid", 0, NUM_USERS_BOUND),),
    # fold_in(host_key, ARM_STREAM_SALT) -> per-arm streams by seed.
    "arm_salt_key": (("seed", 0, MAX_ARMS_BOUND),),
    # A per-arm root's params-init fold (the arms driver's twin of the
    # host key's init stream).
    "arm_root": (("init", 0, 1),),
    # The per-epoch key: in-superstep round index t (0-based).
    "epoch_key": (("round", 0, NUM_ROUNDS_BOUND),),
    # Per-arm per-epoch key, same in-superstep round family.
    "arm_epoch_key": (("round", 0, NUM_ROUNDS_BOUND),),
    # The PRP sampler's commitment key.
    "sample_key": (("prp", 23, 24),),
    # Central (non-federated) baseline: per-global-step keys, then the
    # step key's augment/model split.
    "central_round_key": (("step", None, None),),
    "central_step_key": (("augment", 1, 2), ("model-rng", 2, 3)),
    # Evaluation: the users/global cohort roots, their per-epoch keys,
    # and the per-slot decorrelation inside the sharded eval program.
    "eval_base": (("users", 0, 1), ("global", 1, 2)),
    "eval_users_root": (("epoch", 1, 1 + NUM_ROUNDS_BOUND),),
    "eval_global_root": (("epoch", 1, 1 + NUM_ROUNDS_BOUND),),
    "eval_epoch_key": (("slot", None, None),),
    # Codecs: the salted codec key's per-device axis_index fold.
    "codec_salted_key": (("device", None, None),),
    # Model internals: rng -> corruption (0) / dropout base (1); the
    # dropout base then folds the shard offset and per-site ids -- the
    # site family is the only one at its layer (offset re-roots the
    # base, see models/transformer.py).
    "model_rng": (("corruption", 0, 1), ("dropout-base", 1, 2)),
    "dropout_base": (("shard-offset", None, None), ("site", None, None)),
    # Long-context LM data pipeline: per-document keys.
    "lm_doc_key": (("doc", None, None),),
    # Per-device augmentation decorrelation under data sharding.
    "aug_shard_key": (("device", None, None),),
    # The reference-twin comparison harness re-derives per-round keys
    # from the bare seed (host-side, analysis only).
    "reference_key": (("round", None, None),),
    # Staticcheck's own audit probes (synthetic keys inside traced
    # probe programs; not part of the training derivation tree).
    "audit_probe": (("wire", 77, 78), ("arm", 1, 1 + MAX_ARMS_BOUND)),
}

#: THE declaration of every ``fold_in`` site in the package:
#: ``(root, stream, module, key_regex, salt_regex, purpose)``.  The
#: scanner fullmatches the unparsed key/salt expressions of each real
#: call against these rows; ``(root, stream)`` must exist in ``ROOTS``.
SALT_REGISTRY: Tuple[Tuple[str, str, str, str, str, str], ...] = (
    ("reference_key", "round", "analysis/compare_reference.py",
     r"jax\.random\.key\(seed\)", r"r",
     "reference-twin per-round key from the bare seed"),
    ("host_key", "retry", "chaos/drill.py",
     r"key", r"RETRY_SALT \+ n",
     "chaos drill replays the watchdog's retry keys"),
    ("round_key", "quant-noise", "compress/codecs.py",
     r"key", r"salt",
     "codec noise root (QUANT_NOISE_SALT passed by value)"),
    ("codec_salted_key", "device", "compress/codecs.py",
     r"k", r"jax\.lax\.axis_index\(self\.axis\)",
     "per-device codec noise decorrelation"),
    ("round_key", "topk-block", "compress/codecs.py",
     r"key", r"TOPK_BLOCK_SALT",
     "top-k block permutation root"),
    ("host_key", "init", "entry/central.py",
     r"self\.host_key", r"0", "central params-init key"),
    ("host_key", "epoch", "entry/central.py",
     r"self\.host_key", r"epoch", "central per-epoch key"),
    ("central_round_key", "step", "entry/central.py",
     r"key", r"t", "central per-global-step key"),
    ("central_step_key", "augment", "entry/central.py",
     r"kk", r"1", "central augmentation key"),
    ("central_step_key", "model-rng", "entry/central.py",
     r"kk", r"2", "central model-apply rng"),
    ("host_key", "epoch", "entry/common.py",
     r"self\.host_key", r"epoch", "driver per-epoch key"),
    ("host_key", "retry", "entry/common.py",
     r"self\.host_key", r"RETRY_SALT \+ attempt",
     "watchdog rollback retry keys"),
    ("host_key", "init", "entry/common.py",
     r"self\.host_key", r"0", "driver params-init key"),
    ("arm_root", "init", "entry/common.py",
     r"roots\[e\]", r"0", "per-arm params-init key"),
    ("host_key", "arms", "fed/core.py",
     r"base_key", r"ARM_STREAM_SALT", "arms salt root"),
    ("arm_salt_key", "seed", "fed/core.py",
     r"salted", r"s", "per-arm stream by sweep seed"),
    ("round_key", "client-stream", "fed/core.py",
     r"round_key", r"CLIENT_STREAM_SALT", "client-stream sub-root"),
    ("client_stream_root", "uid", "fed/core.py",
     r"root", r"u", "per-client slot key"),
    ("round_key", "failure", "fed/core.py",
     r"round_key", r"FAILURE_STREAM_SALT", "failure-draw sub-root"),
    ("round_key", "rate", "fed/core.py",
     r"round_key", r"ROUND_RATE_SALT", "dynamic width-rate draw"),
    ("round_key", "user-sample", "fed/core.py",
     r"round_key", r"USER_SAMPLE_SALT", "cohort sampling draw"),
    ("host_key", "epoch", "fed/core.py",
     r"host_key", r"epoch0 \+ r", "superstep per-round host keys"),
    ("sample_key", "prp", "fed/sampling.py",
     r"key", r"PRP_KEY_SALT", "PRP sampler commitment key"),
    ("model_rng", "corruption", "models/transformer.py",
     r"rng", r"0", "LM corruption draw root"),
    ("model_rng", "dropout-base", "models/transformer.py",
     r"rng", r"1", "dropout base key"),
    ("dropout_base", "shard-offset", "models/transformer.py",
     r"drop_base", r"off", "sequence-shard dropout decorrelation"),
    ("dropout_base", "site", "models/transformer.py",
     r"drop_base", r"site", "per-site dropout keys (remat-stable)"),
    ("eval_base", "users", "parallel/evaluation.py",
     r"base", r"0", "users-eval cohort root"),
    ("eval_base", "global", "parallel/evaluation.py",
     r"base", r"1", "global-eval cohort root"),
    ("eval_users_root", "epoch", "parallel/evaluation.py",
     r"self\._users_key|ukey_root", r"epoch", "users-eval per-epoch key"),
    ("eval_global_root", "epoch", "parallel/evaluation.py",
     r"self\._global_key|gkey_root", r"epoch", "global-eval per-epoch key"),
    ("eval_epoch_key", "slot", "parallel/evaluation.py",
     r"key", r"dev \* a \+ i", "per-slot eval decorrelation"),
    ("epoch_key", "round", "parallel/grouped.py",
     r"base_key", r"t", "grouped superstep per-round key"),
    ("arm_epoch_key", "round", "parallel/grouped.py",
     r"akey", r"t", "grouped arms per-round key"),
    ("failure_root", "uid", "parallel/grouped.py",
     r"fkey", r"u", "grouped per-client crash draw"),
    ("lm_doc_key", "doc", "parallel/long_context.py",
     r"key", r"idx", "long-context per-document key"),
    ("host_key", "epoch", "parallel/pod.py",
     r"host_key", r"epoch0 \+ r", "pod superstep per-round host keys"),
    ("client_key", "epoch-perm", "parallel/round_engine.py",
     r"key", r"1", "local-training epoch shuffle root"),
    ("client_key", "augment", "parallel/round_engine.py",
     r"key", r"2 \+ t", "per-step augmentation key"),
    ("aug_shard_key", "device", "parallel/round_engine.py",
     r"aug_key", r"d", "per-device augmentation decorrelation"),
    ("client_key", "model-rng", "parallel/round_engine.py",
     r"key", r"5000 \+ t", "per-step model-apply rng"),
    ("failure_root", "uid", "parallel/round_engine.py",
     r"fkey", r"u", "masked per-client crash draw"),
    ("arm_epoch_key", "round", "parallel/round_engine.py",
     r"akey", r"t", "masked arms per-round key"),
    ("epoch_key", "round", "parallel/round_engine.py",
     r"base_key", r"t", "masked superstep per-round key"),
    ("round_key", "deadline", "sched/deadline.py",
     r"key", r"DEADLINE_SALT", "deadline budget sub-root"),
    ("deadline_root", "uid", "sched/deadline.py",
     r"dkey", r"u", "per-client step-budget draw"),
    ("audit_probe", "wire", "staticcheck/audit.py",
     r"setup\['key'\]", r"77", "wire-frontier probe key"),
    ("audit_probe", "arm", "staticcheck/audit.py",
     r"base", r"1 \+ j", "arms probe per-arm keys"),
)

#: Modules whose in-jaxpr draws consume keys DERIVED in a modeled
#: module (the fold_in provenance lives upstream; these only spend the
#: key they were handed).  ``check_binds`` accepts binds traced to
#: them; each entry documents which declared stream the key descends
#: from so the acceptance is provenance, not a waiver.
DERIVED_CONSUMER_MODULES: Dict[str, str] = {
    "ops/quant.py": "codec_salted_key: stochastic-rounding draws on the "
                    "key compress/codecs.py derives (QUANT_NOISE_SALT + "
                    "per-device axis_index fold) and passes in",
}

#: ``jax.random`` draws that CONSUME a key (fold_in derives, these
#: spend).  A bare key name fed to two of these in one function is a
#: correlated-stream bug.
CONSUMERS = frozenset({
    "normal", "uniform", "bernoulli", "bits", "permutation",
    "categorical", "gumbel", "laplace", "exponential", "randint",
    "truncated_normal", "choice", "split",
})


# ---------------------------------------------------------------------------
# scanners (pure ast, no jax)

def _call_name(node: ast.Call) -> Optional[str]:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return None


def scan_fold_sites(root_dir) -> List[Dict[str, Any]]:
    """Every ``fold_in(key, salt)`` call under ``root_dir``, with the
    key/salt argument expressions rendered back to source text."""
    sites = []
    for path in sorted(Path(root_dir).rglob("*.py")):
        module = path.relative_to(root_dir).as_posix()
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if (isinstance(node, ast.Call) and _call_name(node) == "fold_in"
                    and len(node.args) >= 2):
                sites.append({
                    "module": module, "line": node.lineno,
                    "key": ast.unparse(node.args[0]),
                    "salt": ast.unparse(node.args[1]),
                })
    return sites


def scan_salt_constants(root_dir) -> Dict[str, Dict[str, int]]:
    """Module-level ``*_SALT = <int>`` assignments under ``root_dir``."""
    found: Dict[str, Dict[str, int]] = {}
    for path in sorted(Path(root_dir).rglob("*.py")):
        module = path.relative_to(root_dir).as_posix()
        tree = ast.parse(path.read_text())
        for node in tree.body:
            if (isinstance(node, ast.Assign) and len(node.targets) == 1
                    and isinstance(node.targets[0], ast.Name)
                    and node.targets[0].id.endswith("_SALT")):
                try:
                    val = ast.literal_eval(node.value)
                except ValueError:
                    continue
                if isinstance(val, int):
                    found.setdefault(module, {})[node.targets[0].id] = val
    return found


def _exclusive(p1, p2) -> bool:
    """Two branch paths are exclusive iff they take different arms of
    the same ``if`` -- then at most one of the two sites executes."""
    for a, b in zip(p1, p2):
        if a[0] == b[0] and a[1] != b[1]:
            return True
        if a != b:
            return False
    return False


def scan_raw_reuse(root_dir,
                   consumers: frozenset = CONSUMERS) -> List[Dict[str, Any]]:
    """Functions where one bare key name is consumed by >= 2 draws that
    can execute together.

    Branch-aware: two draws on opposite arms of the same ``if`` spend
    the key once per execution path and are fine.  A name that is
    (re)assigned anywhere inside the function is skipped: loop bodies
    like ``key = fold_in(key, t); normal(key)`` rebind the name per
    iteration, so textual repetition is not reuse there.  The flagged
    shape -- a never-reassigned name spent twice on one path -- has no
    such excuse: both draws read the identical key.  Nested function
    defs are scanned as their own roots."""
    findings = []
    for path in sorted(Path(root_dir).rglob("*.py")):
        module = path.relative_to(root_dir).as_posix()
        tree = ast.parse(path.read_text())
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            assigned = set()
            uses: Dict[str, List[Tuple[int, tuple]]] = {}

            def walk(node, p):
                if (isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
                        and node is not fn):
                    return
                if isinstance(node, ast.If):
                    walk(node.test, p)
                    for n in node.body:
                        walk(n, p + ((id(node), 0),))
                    for n in node.orelse:
                        walk(n, p + ((id(node), 1),))
                    return
                if isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign,
                                     ast.For, ast.NamedExpr, ast.comprehension)):
                    targets = (node.targets if isinstance(node, ast.Assign)
                               else [node.target])
                    for t in targets:
                        for leaf in ast.walk(t):
                            if isinstance(leaf, ast.Name):
                                assigned.add(leaf.id)
                if (isinstance(node, ast.Call) and _call_name(node) in consumers
                        and node.args and isinstance(node.args[0], ast.Name)):
                    uses.setdefault(node.args[0].id, []).append((node.lineno, p))
                for child in ast.iter_child_nodes(node):
                    walk(child, p)

            walk(fn, ())
            for name, sites in sorted(uses.items()):
                if name in assigned or len(sites) < 2:
                    continue
                clash = [
                    (l1, l2)
                    for i, (l1, p1) in enumerate(sites)
                    for l2, p2 in sites[i + 1:] if not _exclusive(p1, p2)]
                if clash:
                    lines = sorted({ln for pair in clash for ln in pair})
                    findings.append({
                        "rule": "key-raw-reuse",
                        "where": f"{module}:{lines[0]} {fn.name}()",
                        "message": (
                            f"raw key '{name}' consumed by multiple "
                            f"jax.random draws on one path (lines "
                            f"{lines}) without an intervening fold_in/"
                            f"split -- the draws are correlated"),
                    })
    return findings


# ---------------------------------------------------------------------------
# checks

def _check_intervals(roots) -> List[Dict[str, Any]]:
    findings = []
    for root, streams in sorted(roots.items()):
        bounded = [(s, lo, hi) for s, lo, hi in streams if lo is not None]
        for i, (s1, lo1, hi1) in enumerate(bounded):
            for s2, lo2, hi2 in bounded[i + 1:]:
                if lo1 < hi2 and lo2 < hi1:
                    findings.append({
                        "rule": "key-salt-collision",
                        "where": f"root {root}",
                        "message": (
                            f"streams '{s1}' [{lo1}, {hi1}) and '{s2}' "
                            f"[{lo2}, {hi2}) overlap under root "
                            f"'{root}': the same fold_in salt would "
                            f"derive both purposes"),
                    })
    return findings


def _check_constants(found, expected) -> List[Dict[str, Any]]:
    findings = []
    for module, consts in sorted(expected.items()):
        have = found.get(module, {})
        for name, val in sorted(consts.items()):
            if name not in have:
                findings.append({
                    "rule": "key-salt-drift", "where": module,
                    "message": f"declared salt {name}={val} no longer "
                               f"defined in {module}",
                })
            elif have[name] != val:
                findings.append({
                    "rule": "key-salt-drift", "where": module,
                    "message": (
                        f"salt {name} drifted: declared {val}, found "
                        f"{have[name]} -- update SALT_CONSTANTS and "
                        f"re-review the ROOTS intervals"),
                })
    for module, consts in sorted(found.items()):
        for name, val in sorted(consts.items()):
            if name not in expected.get(module, {}):
                findings.append({
                    "rule": "key-salt-drift", "where": f"{module}",
                    "message": f"undeclared salt constant {name}={val} "
                               f"in {module}: add it to SALT_CONSTANTS "
                               f"and to a ROOTS interval",
                })
    return findings


def _match_sites(sites, registry, roots) -> List[Dict[str, Any]]:
    findings = []
    hit = [0] * len(registry)
    for site in sites:
        matched = False
        for i, (root, stream, module, key_re, salt_re, _purpose) in enumerate(registry):
            if (site["module"] == module
                    and re.fullmatch(key_re, site["key"])
                    and re.fullmatch(salt_re, site["salt"])):
                hit[i] += 1
                matched = True
        if not matched:
            findings.append({
                "rule": "key-undeclared-stream",
                "where": f"{site['module']}:{site['line']}",
                "message": (
                    f"fold_in({site['key']}, {site['salt']}) matches no "
                    f"SALT_REGISTRY row: declare its (root, stream) "
                    f"provenance before landing it"),
            })
    for i, (root, stream, module, key_re, salt_re, _purpose) in enumerate(registry):
        declared = {s for s, _lo, _hi in roots.get(root, ())}
        if root not in roots or stream not in declared:
            findings.append({
                "rule": "key-registry-stale",
                "where": f"registry[{i}] {module}",
                "message": f"row declares undeclared stream "
                           f"({root!r}, {stream!r}): add it to ROOTS",
            })
        if hit[i] == 0:
            findings.append({
                "rule": "key-registry-stale",
                "where": f"registry[{i}] {module}",
                "message": (
                    f"no fold_in site matches ({key_re!r}, {salt_re!r}) "
                    f"in {module}: the declared '{root}/{stream}' "
                    f"stream rotted out of the tree"),
            })
    return findings


def check_binds(bind_files: Sequence[str],
                registry=SALT_REGISTRY,
                derived_consumers=None) -> List[Dict[str, Any]]:
    """Compiled-program cross-check: every source file contributing an
    in-jaxpr ``random_*``/key-consuming bind must be one the registry
    models -- or a declared derived-key consumer -- so the bind provably
    descends from a declared root."""
    if derived_consumers is None:
        derived_consumers = DERIVED_CONSUMER_MODULES
    modeled = {module for _r, _s, module, _k, _sa, _p in registry}
    modeled |= set(derived_consumers)
    findings = []
    for f in sorted(set(bind_files)):
        if f not in modeled:
            findings.append({
                "rule": "key-unrooted-bind",
                "where": f,
                "message": (
                    f"compiled program draws randomness traced to {f}, "
                    f"which declares no SALT_REGISTRY stream: the bind "
                    f"has no (salt, purpose) provenance"),
            })
    return findings


def key_streams_check(package_dir,
                      registry=SALT_REGISTRY,
                      roots=ROOTS,
                      constants=SALT_CONSTANTS,
                      consumers: frozenset = CONSUMERS,
                      bind_files: Optional[Sequence[str]] = None) -> Dict[str, Any]:
    """Run the full key-stream audit over the package tree.

    Returns the ``key_streams`` report section: a summary of the
    provenance graph plus findings.  All tables are injectable so the
    regression tests can seed a duplicated salt, an undeclared fold
    site, or a reused raw key and watch the named finding trip.
    """
    package_dir = Path(package_dir)
    sites = scan_fold_sites(package_dir)
    found_consts = scan_salt_constants(package_dir)

    findings: List[Dict[str, Any]] = []
    findings += _check_intervals(roots)
    findings += _check_constants(found_consts, constants)
    findings += _match_sites(sites, registry, roots)
    findings += scan_raw_reuse(package_dir, consumers)
    if bind_files is not None:
        findings += check_binds(bind_files, registry)

    streams = {}
    for root, decl in sorted(roots.items()):
        streams[root] = [
            {"stream": s, "lo": lo, "hi": hi} for s, lo, hi in decl]
    return {
        "ok": not findings,
        "fold_in_sites": len(sites),
        "registry_rows": len(registry),
        "salt_constants": {m: dict(sorted(c.items()))
                           for m, c in sorted(found_consts.items())},
        "roots": streams,
        "binds_checked": len(set(bind_files)) if bind_files is not None else 0,
        "findings": findings[:MAX_FINDING_SAMPLES],
        "findings_total": len(findings),
    }
