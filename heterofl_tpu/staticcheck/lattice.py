"""Config-lattice exhaustiveness pass (ISSUE 18 tentpole).

The repo's feature axes (engine x placement x codec x scheduler x
telemetry x ledger x arms x quarantine x sampler x store x pod x
eval-cohort) multiply into a lattice of ~10^5 nominally-expressible
configs.  Before this pass, the only exhaustiveness statement was
social: each subsystem promised its validator refused "the bad combos"
and the audit compiled "the good ones".  This module makes the
statement mechanical -- it enumerates EVERY point of the declared
lattice (one machine-readable axis table, :data:`AXES`) and proves each
point is exactly one of:

* **SUPPORTED** -- its structural core maps to an audited-green anchor
  program (:data:`ANCHORS`, names cross-checked against the live audit
  report) and every riding axis value is covered by a *named
  equivalence contract* (:data:`CONTRACTS`, each carrying its audited
  program evidence);
* **REFUSED** -- replaying :func:`heterofl_tpu.config.validator_chain`
  on the point's cfg raises a typed ``ValueError`` from exactly one
  ``resolve_*`` validator, the refusal matches a *declared* refusal
  rule (:data:`REFUSAL_RULES`: same owner validator, message naming the
  offending cfg keys), and the rule actually fires somewhere (a
  declared rule that never fires is a silent-fallback finding);
* **UNREACHED** -- anything else, which is a finding: an unclassified
  combo, a refusal with undeclared provenance, or a declared refusal
  the validators no longer deliver (the silent fallback).

Deliberately jax-free (the report.py convention): classification only
replays the config validators, so ``--lattice-md`` and the regression
tests run without booting a backend.  The audit front passes its
compiled-program report in via ``audited=`` to also prove every piece
of program evidence is audited green (``lattice-evidence-missing``).

Every table is injectable (``lattice_check(axes=..., rules=...,
anchors=..., contracts=...)``) so the regression tests can seed an
unclassified combo, a silently-falling-back rule, or rotted evidence
and watch the named finding trip.
"""

from __future__ import annotations

import itertools
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from .. import config as C

#: How many example points a single finding rule reports before
#: summarising -- the full list of a rotted axis can be ~10^4 points.
MAX_FINDING_SAMPLES = 12

# ---------------------------------------------------------------------------
# the declared feature lattice
# ---------------------------------------------------------------------------

#: THE machine-readable axis table: every (axis, value-domain) the repo
#: declares.  The first value of each axis is its default; the product
#: of all domains is the lattice this pass enumerates exhaustively.
#: Domains mirror the config registries (config.STRATEGIES & friends)
#: -- test_lattice.py pins that correspondence so the table cannot rot.
AXES: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("engine", ("masked", "grouped", "sliced")),
    ("placement", ("replicated", "sharded")),
    ("levels", ("span", "slices")),
    ("store", ("eager", "stream")),
    ("codec", ("dense", "int8", "signsgd", "topk")),
    ("scheduler", ("k1", "k8", "k1-deadline", "k8-deadline",
                   "k1-buffered", "k8-buffered")),
    ("telemetry", ("off", "on", "hist")),
    ("ledger", ("off", "on")),
    ("arms", ("off", "e2")),
    ("quarantine", ("off", "on")),
    ("sampler", ("prp", "perm")),
    ("eval_cohort", ("off", "c8")),
    ("pod", ("local", "pod")),
)

#: cfg skeleton every lattice point is written over: the non-axis keys
#: the validators consult (num_users for eval cohorts, the vision model
#: for the eval-cohort x LM refusal, lockstep fetch cadence).
BASE_CFG: Dict[str, Any] = {
    "num_users": 100,
    "model_name": "conv",
    "metrics_fetch_every": 1,
    "eval_interval": 1,
    "scheduler_name": "MultiStepLR",
}


def point_cfg(point: Dict[str, str]) -> Dict[str, Any]:
    """Materialise one lattice point as the cfg dict the validator chain
    consumes -- THE single mapping from axis values to cfg keys."""
    cfg = dict(BASE_CFG)
    cfg["strategy"] = point["engine"]
    cfg["data_placement"] = point["placement"]
    cfg["level_placement"] = point["levels"]
    cfg["client_store"] = point["store"]
    cfg["wire_codec"] = point["codec"]
    sched = point["scheduler"]
    cfg["superstep_rounds"] = 1 if sched.startswith("k1") else 8
    if sched.endswith("-deadline"):
        cfg["schedule"] = {"deadline": {"min_frac": 0.5}}
    elif sched.endswith("-buffered"):
        cfg["schedule"] = {"aggregation": "buffered"}
    else:
        cfg["schedule"] = None
    cfg["telemetry"] = point["telemetry"]
    cfg["ledger"] = point["ledger"]
    cfg["arms"] = None if point["arms"] == "off" else 2
    cfg["quarantine"] = point["quarantine"]
    cfg["sampler"] = point["sampler"]
    cfg["eval_cohort"] = None if point["eval_cohort"] == "off" else 8
    cfg["strict_placement"] = point["pod"] == "pod"
    return cfg


#: cfg key(s) each axis writes -- the provenance test asserts a REFUSED
#: point's message names the keys its matching rule declares, and those
#: keys must come from this map.
AXIS_CFG_KEYS: Dict[str, Tuple[str, ...]] = {
    "engine": ("strategy",),
    "placement": ("data_placement",),
    "levels": ("level_placement",),
    "store": ("client_store",),
    "codec": ("wire_codec",),
    "scheduler": ("superstep_rounds", "schedule"),
    "telemetry": ("telemetry",),
    "ledger": ("ledger",),
    "arms": ("arms",),
    "quarantine": ("quarantine",),
    "sampler": ("sampler",),
    "eval_cohort": ("eval_cohort",),
    "pod": ("strict_placement",),
}

# ---------------------------------------------------------------------------
# declared refusals: the provenance table
# ---------------------------------------------------------------------------

#: Every cross-axis refusal the lattice can reach, declared: ``when``
#: matches axis values (a string or a tuple of alternatives), ``owner``
#: is the ONE validator that must raise first in the chain, ``keys``
#: the cfg keys its message must name.  A REFUSED point with no
#: validating rule is an undeclared refusal (lattice-unreached); a rule
#: that validates zero points is a silent fallback
#: (lattice-silent-fallback).  Ordering does not matter: any validating
#: rule clears a point.
REFUSAL_RULES: Tuple[Dict[str, Any], ...] = (
    {"id": "grouped-sharded",
     "when": {"engine": "grouped", "placement": "sharded"},
     "owner": "resolve_placement_cfg", "keys": ("data_placement", "strategy")},
    {"id": "slices-needs-grouped",
     "when": {"engine": ("masked", "sliced"), "levels": "slices"},
     "owner": "resolve_placement_cfg",
     "keys": ("level_placement", "strategy")},
    {"id": "sliced-sharded-noop",
     "when": {"engine": "sliced", "placement": "sharded"},
     "owner": "resolve_placement_cfg", "keys": ("data_placement", "strategy")},
    {"id": "stream-needs-mesh-native",
     "when": {"engine": "sliced", "store": "stream"},
     "owner": "resolve_store_cfg", "keys": ("client_store", "strategy")},
    {"id": "stream-sharded-noop",
     "when": {"engine": ("masked", "grouped"), "store": "stream",
              "placement": "sharded"},
     "owner": "resolve_store_cfg", "keys": ("data_placement", "client_store")},
    {"id": "sliced-superstep",
     "when": {"engine": "sliced",
              "scheduler": ("k8", "k8-deadline", "k8-buffered")},
     "owner": "resolve_superstep_cfg",
     "keys": ("superstep_rounds", "strategy")},
    {"id": "sliced-codec",
     "when": {"engine": "sliced", "codec": ("int8", "signsgd", "topk"),
              "scheduler": ("k1", "k1-deadline", "k1-buffered")},
     "owner": "resolve_codec_cfg", "keys": ("wire_codec", "strategy")},
    {"id": "grouped-k1-codec",
     "when": {"engine": "grouped", "codec": ("int8", "signsgd", "topk"),
              "scheduler": ("k1", "k1-deadline", "k1-buffered"),
              "store": "eager", "placement": "replicated"},
     "owner": "resolve_codec_cfg",
     "keys": ("wire_codec", "strategy", "superstep_rounds", "client_store")},
    {"id": "sliced-schedule",
     "when": {"engine": "sliced",
              "scheduler": ("k1-deadline", "k1-buffered"),
              "codec": "dense"},
     "owner": "resolve_schedule_cfg", "keys": ("schedule", "strategy")},
    {"id": "buffered-lossy-codec",
     "when": {"engine": ("masked", "grouped"),
              "scheduler": ("k1-buffered", "k8-buffered"),
              "codec": ("int8", "signsgd", "topk")},
     "owner": "resolve_schedule_cfg", "keys": ("schedule", "wire_codec")},
    {"id": "grouped-k1-buffered",
     "when": {"engine": "grouped", "scheduler": "k1-buffered",
              "codec": "dense", "store": "eager", "placement": "replicated"},
     "owner": "resolve_schedule_cfg",
     "keys": ("schedule", "strategy", "superstep_rounds", "client_store")},
    {"id": "eval-cohort-needs-stream",
     "when": {"eval_cohort": "c8", "store": "eager"},
     "owner": "resolve_eval_cohort", "keys": ("eval_cohort", "client_store")},
    {"id": "sliced-telemetry",
     "when": {"engine": "sliced", "telemetry": ("on", "hist"),
              "store": "eager", "eval_cohort": "off"},
     "owner": "resolve_telemetry_cfg", "keys": ("telemetry", "strategy")},
    {"id": "grouped-k1-telemetry",
     "when": {"engine": "grouped", "telemetry": ("on", "hist"),
              "scheduler": ("k1", "k1-deadline"), "store": "eager",
              "codec": "dense", "placement": "replicated",
              "eval_cohort": "off"},
     "owner": "resolve_telemetry_cfg",
     "keys": ("telemetry", "strategy", "superstep_rounds", "client_store")},
    {"id": "sliced-ledger",
     "when": {"engine": "sliced", "ledger": "on"},
     "owner": "resolve_ledger_cfg", "keys": ("ledger", "strategy")},
    {"id": "sharded-ledger",
     "when": {"engine": "masked", "placement": "sharded", "ledger": "on",
              "store": "eager"},
     "owner": "resolve_ledger_cfg", "keys": ("ledger", "data_placement")},
    {"id": "sliced-quarantine",
     "when": {"engine": "sliced", "quarantine": "on"},
     "owner": "resolve_quarantine_cfg", "keys": ("quarantine", "strategy")},
    {"id": "sliced-arms",
     "when": {"engine": "sliced", "arms": "e2"},
     "owner": "resolve_arms_cfg", "keys": ("arms", "strategy")},
    {"id": "arms-ledger",
     "when": {"engine": ("masked", "grouped"), "arms": "e2", "ledger": "on"},
     "owner": "resolve_arms_cfg", "keys": ("arms", "ledger")},
    {"id": "arms-buffered",
     "when": {"engine": ("masked", "grouped"), "arms": "e2",
              "scheduler": ("k1-buffered", "k8-buffered"), "codec": "dense",
              "ledger": "off"},
     "owner": "resolve_arms_cfg", "keys": ("arms", "schedule")},
    {"id": "arms-stream",
     "when": {"engine": ("masked", "grouped"), "arms": "e2",
              "store": "stream", "ledger": "off",
              "scheduler": ("k1", "k8", "k1-deadline", "k8-deadline")},
     "owner": "resolve_arms_cfg", "keys": ("arms", "client_store")},
    {"id": "grouped-arms-codec",
     "when": {"engine": "grouped", "arms": "e2",
              "codec": ("int8", "signsgd", "topk"),
              "scheduler": ("k8", "k8-deadline"), "store": "eager",
              "ledger": "off"},
     "owner": "resolve_arms_cfg", "keys": ("arms", "wire_codec", "strategy")},
    {"id": "grouped-arms-telemetry",
     "when": {"engine": "grouped", "arms": "e2", "telemetry": ("on", "hist"),
              "codec": "dense", "scheduler": ("k8", "k8-deadline"),
              "store": "eager", "ledger": "off"},
     "owner": "resolve_arms_cfg", "keys": ("arms", "telemetry", "strategy")},
    {"id": "grouped-arms-quarantine",
     "when": {"engine": "grouped", "arms": "e2", "quarantine": "on",
              "telemetry": "off", "codec": "dense",
              "scheduler": ("k1", "k8", "k1-deadline", "k8-deadline"),
              "store": "eager", "ledger": "off"},
     "owner": "resolve_arms_cfg", "keys": ("arms", "quarantine", "strategy")},
    {"id": "grouped-arms-slices",
     "when": {"engine": "grouped", "arms": "e2", "levels": "slices",
              "quarantine": "off", "telemetry": "off", "codec": "dense",
              "scheduler": ("k1", "k8", "k1-deadline", "k8-deadline"),
              "store": "eager", "ledger": "off"},
     "owner": "resolve_arms_cfg", "keys": ("arms", "level_placement")},
)

# ---------------------------------------------------------------------------
# declared support: anchors + contracts
# ---------------------------------------------------------------------------

#: Structural-core anchors: (engine, placement, levels, store) -> the
#: audited program (``program:<name>``) or named contract
#: (``contract:<name>``) that proves the core lowers, per K class.
#: A surviving point whose core has no anchor is UNREACHED -- this map
#: is where the exhaustiveness proof has teeth.
ANCHORS: Dict[Tuple[str, str, str, str], Dict[str, str]] = {
    ("masked", "replicated", "span", "eager"): {
        "k1": "program:masked/replicated/k1",
        "k8": "program:masked/replicated/k8"},
    ("masked", "replicated", "span", "stream"): {
        "k1": "contract:stream-k1-superstep",
        "k8": "program:masked/stream/k8"},
    ("masked", "sharded", "span", "eager"): {
        "k1": "program:masked/sharded/k1",
        "k8": "program:masked/sharded/k8"},
    ("grouped", "replicated", "span", "eager"): {
        "k1": "contract:grouped-k1-host-orchestrated",
        "k8": "program:grouped/span/k8-fused"},
    ("grouped", "replicated", "span", "stream"): {
        "k1": "contract:stream-k1-superstep",
        "k8": "program:grouped/stream/span/k8"},
    ("grouped", "replicated", "slices", "eager"): {
        "k1": "contract:grouped-k1-host-orchestrated",
        "k8": "program:grouped/slices/k8-fused"},
    ("grouped", "replicated", "slices", "stream"): {
        "k1": "contract:stream-k1-superstep",
        "k8": "program:grouped/stream/slices/k8"},
    ("sliced", "replicated", "span", "eager"): {
        "k1": "contract:sliced-reference-twin"},
}

#: Named equivalence contracts: each covers one riding axis value (or a
#: k1 anchor) on every surviving point, with the audited programs that
#: evidence it.  ``evidence`` entries are ``program:<audited name>``
#: (checked against the live audit report), ``check:<cross-check
#: section>`` or ``test:<pytest node>`` (documentary).
CONTRACTS: Dict[str, Dict[str, Any]] = {
    "stream-k1-superstep": {
        "note": "the driver routes client_store='stream' at "
                "superstep_rounds=1 through the k=1 superstep program "
                "(the fused path with a length-1 scan), never the legacy "
                "round path",
        "evidence": ("program:masked/stream/k8",
                     "program:grouped/stream/span/k8",
                     "test:tests/test_streaming.py")},
    "grouped-k1-host-orchestrated": {
        "note": "grouped at K=1 runs L per-level programs + one combine "
                "program; audited per level and as the combine",
        "evidence": ("program:grouped/span/level-1/k1",
                     "program:grouped/span/combine",
                     "test:tests/test_grouped.py")},
    "sliced-reference-twin": {
        "note": "the sliced engine is the host-orchestrated debug twin: "
                "bitwise-equivalent to the masked engine per round "
                "(shared client_stream_keys derivation), never compiled "
                "as one program",
        "evidence": ("program:masked/replicated/k1",
                     "test:tests/test_sliced.py")},
    "codec-wire-frontier": {
        "note": "a lossy codec wraps THE one global psum (the wire "
                "frontier); data placement and client store only change "
                "staging, audited by the codec variants per engine",
        "evidence": ("program:masked/replicated/k8-int8",
                     "program:masked/sharded/k8-int8",
                     "program:grouped/span/k8-fused-int8",
                     "program:grouped/slices/k8-fused-int8",
                     "check:wire_frontier")},
    "deadline-budget-draw": {
        "note": "deadline budgets are per-client draws folded into the "
                "round core; engine-invariant by the shared "
                "deadline_steps derivation",
        "evidence": ("program:masked/replicated/k8-deadline",
                     "program:grouped/span/k8-fused-deadline",
                     "test:tests/test_sched.py")},
    "buffered-staleness-carry": {
        "note": "buffered aggregation adds one replicated [2, total] "
                "carry to the superstep scan; K=1 is the length-1 scan "
                "of the same program",
        "evidence": ("program:masked/replicated/k8-buffered",
                     "program:grouped/span/k8-fused-buffered",
                     "test:tests/test_sched.py")},
    "telemetry-probe-rows": {
        "note": "probes ride the round core as extra metric rows "
                "(split_probes); store/placement only change staging",
        "evidence": ("program:masked/replicated/k1-telemetry",
                     "program:masked/replicated/k8-telemetry",
                     "program:grouped/span/k8-fused-telemetry",
                     "test:tests/test_obs.py")},
    "telemetry-hist-rows": {
        "note": "hist mode widens the probe rows with bucket counts; "
                "same carriage as telemetry='on'",
        "evidence": ("program:masked/replicated/k1-hist",
                     "program:masked/replicated/k8-hist",
                     "program:grouped/span/k8-fused-hist",
                     "test:tests/test_obs.py")},
    "ledger-host-fold": {
        "note": "the ledger is a host-side O(active) fold over fetched "
                "metric rows -- NEVER a program change; the compiled "
                "program set is identical with it on",
        "evidence": ("test:tests/test_obs.py",)},
    "arms-batched-superstep": {
        "note": "arms vmap the superstep scan over a leading [E] axis; "
                "E=1 is bit-identical to the unbatched program and the "
                "tail dispatch covers k=1",
        "evidence": ("program:masked/replicated/k8-arms2",
                     "program:grouped/span/k8-fused-arms2",
                     "check:arms",
                     "test:tests/test_arms.py")},
    "quarantine-gate": {
        "note": "the quarantine gate folds into each round/level core "
                "before aggregation; engine-invariant counter rows",
        "evidence": ("program:masked/replicated/k1-quarantine",
                     "program:masked/replicated/k8-quarantine",
                     "program:grouped/span/k8-fused-quarantine",
                     "test:tests/test_chaos.py")},
    "sampler-stream-commitment": {
        "note": "both sampler kinds draw the identical cohort in-jit and "
                "on the host (sampler_stream_check: bitwise), so the "
                "sampler axis never changes program structure",
        "evidence": ("program:masked/replicated/k8-perm",
                     "check:sampler",
                     "test:tests/test_sampling.py")},
    "eval-cohort-sampled-local": {
        "note": "eval_cohort subsamples the streaming store's Local eval "
                "operand staging; the eval-fused program family is the "
                "same (cohort size is a staging shape)",
        "evidence": ("program:masked/stream/k8-eval1",
                     "test:tests/test_sched.py")},
    "pod-placement-pinned": {
        "note": "strict_placement pins the pod layout: multi-process "
                "slices refuse instead of silently falling back to span; "
                "single-process meshes are unaffected",
        "evidence": ("program:grouped/slices/k8-fused/mh",
                     "program:grouped/stream/slices/k8/mh",
                     "test:tests/test_grouped.py")},
}

#: riding-axis value -> contract that covers it on surviving points.
#: Axes absent here (engine/placement/levels/store) are anchor
#: coordinates; default values ride the anchor itself.
RIDER_CONTRACTS: Dict[Tuple[str, str], str] = {
    ("codec", "int8"): "codec-wire-frontier",
    ("codec", "signsgd"): "codec-wire-frontier",
    ("codec", "topk"): "codec-wire-frontier",
    ("scheduler", "k1-deadline"): "deadline-budget-draw",
    ("scheduler", "k8-deadline"): "deadline-budget-draw",
    ("scheduler", "k1-buffered"): "buffered-staleness-carry",
    ("scheduler", "k8-buffered"): "buffered-staleness-carry",
    ("telemetry", "on"): "telemetry-probe-rows",
    ("telemetry", "hist"): "telemetry-hist-rows",
    ("ledger", "on"): "ledger-host-fold",
    ("arms", "e2"): "arms-batched-superstep",
    ("quarantine", "on"): "quarantine-gate",
    ("sampler", "perm"): "sampler-stream-commitment",
    ("eval_cohort", "c8"): "eval-cohort-sampled-local",
    ("pod", "pod"): "pod-placement-pinned",
}

# ---------------------------------------------------------------------------
# classification
# ---------------------------------------------------------------------------


def iter_points(axes: Sequence[Tuple[str, Tuple[str, ...]]] = AXES
                ) -> Iterable[Dict[str, str]]:
    """Every point of the declared lattice, as axis -> value dicts."""
    names = [a for a, _ in axes]
    for combo in itertools.product(*(vals for _, vals in axes)):
        yield dict(zip(names, combo))


def _rule_matches(rule: Dict[str, Any], point: Dict[str, str]) -> bool:
    for axis, want in rule["when"].items():
        have = point.get(axis)
        if isinstance(want, tuple):
            if have not in want:
                return False
        elif have != want:
            return False
    return True


def classify_point(point: Dict[str, str],
                   chain: Optional[Sequence[Tuple[str, Any]]] = None
                   ) -> Dict[str, Any]:
    """Replay the validator chain on one point: REFUSED with the owning
    validator + message, or SUPPORTED-candidate (evidence resolved by
    the caller)."""
    cfg = point_cfg(point)
    for name, fn in (chain if chain is not None else C.validator_chain()):
        try:
            fn(cfg)
        except ValueError as e:
            return {"class": "REFUSED", "owner": name, "message": str(e)}
    return {"class": "SUPPORTED"}


def support_evidence(point: Dict[str, str],
                     anchors: Dict[Tuple[str, str, str, str],
                                   Dict[str, str]] = ANCHORS,
                     riders: Dict[Tuple[str, str], str] = RIDER_CONTRACTS,
                     contracts: Dict[str, Dict[str, Any]] = CONTRACTS,
                     axes: Sequence[Tuple[str, Tuple[str, ...]]] = AXES,
                     ) -> Optional[List[str]]:
    """Evidence refs proving a surviving point is supported, or ``None``
    when the declared tables leave it uncovered (an UNREACHED hole)."""
    core = (point["engine"], point["placement"], point["levels"],
            point["store"])
    k_class = "k1" if point["scheduler"].startswith("k1") else "k8"
    anchor = anchors.get(core, {}).get(k_class)
    if anchor is None:
        return None
    evidence = [anchor]
    defaults = {axis: vals[0] for axis, vals in axes}
    for axis, value in point.items():
        if axis in ("engine", "placement", "levels", "store"):
            continue
        if axis == "scheduler" and value in ("k1", "k8"):
            continue
        if value == defaults.get(axis):
            continue
        name = riders.get((axis, value))
        if name is None or name not in contracts:
            return None
        evidence.append(f"contract:{name}")
    return evidence


def lattice_check(chain: Optional[Sequence[Tuple[str, Any]]] = None,
                  axes: Sequence[Tuple[str, Tuple[str, ...]]] = AXES,
                  rules: Sequence[Dict[str, Any]] = REFUSAL_RULES,
                  anchors: Dict[Tuple[str, str, str, str],
                                Dict[str, str]] = ANCHORS,
                  riders: Dict[Tuple[str, str], str] = RIDER_CONTRACTS,
                  contracts: Dict[str, Dict[str, Any]] = CONTRACTS,
                  audited: Optional[Iterable[str]] = None,
                  ) -> Dict[str, Any]:
    """Run the exhaustiveness pass; returns the ``lattice`` section dict
    for STATICCHECK.json (``ok``/counts/per-rule fire counts/findings).

    ``audited``: the live audit report's program names; when given,
    every ``program:`` evidence ref must be in it (and green is the
    caller's concern -- run_audit only passes names of green programs).
    """
    chain = list(chain) if chain is not None else C.validator_chain()
    owners = {name for name, _ in chain}
    fired: Dict[str, int] = {r["id"]: 0 for r in rules}
    counts = {"SUPPORTED": 0, "REFUSED": 0, "UNREACHED": 0}
    findings: List[Dict[str, str]] = []
    samples: Dict[str, int] = {}
    evidence_used: Dict[str, int] = {}
    owner_counts: Dict[str, int] = {}

    def fail(rule: str, point: Optional[Dict[str, str]], message: str):
        samples[rule] = samples.get(rule, 0) + 1
        if samples[rule] > MAX_FINDING_SAMPLES:
            return
        where = "lattice" if point is None else \
            "lattice:" + "/".join(point[a] for a, _ in axes)
        findings.append({"rule": rule, "where": where, "message": message})

    for r in rules:
        if r["owner"] not in owners:
            fail("lattice-silent-fallback", None,
                 f"refusal rule {r['id']!r} names owner {r['owner']!r}, "
                 f"which is not in the validator chain")

    n_points = 0
    for point in iter_points(axes):
        n_points += 1
        res = classify_point(point, chain)
        if res["class"] == "REFUSED":
            owner, message = res["owner"], res["message"]
            owner_counts[owner] = owner_counts.get(owner, 0) + 1
            validated = False
            for r in rules:
                if not _rule_matches(r, point):
                    continue
                if r["owner"] != owner:
                    continue
                if all(k in message for k in r["keys"]):
                    fired[r["id"]] += 1
                    validated = True
                    break
            if validated:
                counts["REFUSED"] += 1
            else:
                counts["UNREACHED"] += 1
                fail("lattice-unreached", point,
                     f"refusal with undeclared provenance: {owner} raised "
                     f"{message!r} but no declared rule matches "
                     f"(owner + offending-key naming)")
            continue
        # validators passed: a declared refusal that did NOT fire here is
        # a silent fallback -- the combo would run and quietly degrade.
        silent = [r["id"] for r in rules if _rule_matches(r, point)]
        if silent:
            counts["UNREACHED"] += 1
            fail("lattice-silent-fallback", point,
                 f"declared refusal rule(s) {silent} match this point but "
                 f"no validator refused it -- the combo silently falls "
                 f"back / degrades mid-run")
            continue
        evidence = support_evidence(point, anchors, riders, contracts, axes)
        if evidence is None:
            counts["UNREACHED"] += 1
            fail("lattice-unreached", point,
                 "unclassified combo: no validator refuses it and no "
                 "anchor/contract covers it")
            continue
        counts["SUPPORTED"] += 1
        for ref in evidence:
            evidence_used[ref] = evidence_used.get(ref, 0) + 1

    for r in rules:
        if r["owner"] in owners and fired[r["id"]] == 0:
            fail("lattice-silent-fallback", None,
                 f"declared refusal rule {r['id']!r} (owner {r['owner']}) "
                 f"validated zero lattice points -- either the combo "
                 f"silently falls back or the rule rotted")

    # evidence liveness: every program ref used by a supported point (or
    # named by a live contract) must be in the audited-green program set
    if audited is not None:
        audited = set(audited)
        program_refs = {ref for ref in evidence_used if
                        ref.startswith("program:")}
        for name, c in contracts.items():
            if f"contract:{name}" in evidence_used or name in {
                    v.split(":", 1)[1] for a in anchors.values()
                    for v in a.values() if v.startswith("contract:")}:
                program_refs.update(e for e in c.get("evidence", ())
                                    if e.startswith("program:"))
        for ref in sorted(program_refs):
            if ref.split(":", 1)[1] not in audited:
                fail("lattice-evidence-missing", None,
                     f"evidence {ref} backs supported lattice points but "
                     f"is not in the audited program set")

    ok = not findings
    return {
        "ok": ok,
        "points": n_points,
        "supported": counts["SUPPORTED"],
        "refused": counts["REFUSED"],
        "unreached": counts["UNREACHED"],
        "axes": {a: list(v) for a, v in axes},
        "refusal_rules": [{"id": r["id"], "owner": r["owner"],
                           "points": fired[r["id"]]} for r in rules],
        "refusal_owners": owner_counts,
        "contracts": [{"name": n,
                       "points": evidence_used.get(f"contract:{n}", 0),
                       "evidence": list(c.get("evidence", ()))}
                      for n, c in sorted(contracts.items())],
        "evidence_checked": audited is not None,
        "findings": findings,
    }


# ---------------------------------------------------------------------------
# human-readable rendering (README's Compatibility-lattice section)
# ---------------------------------------------------------------------------


def lattice_markdown(section: Optional[Dict[str, Any]] = None) -> str:
    """Render the lattice summary as the README's auto-generated
    "Compatibility lattice" block (jax-free; classification only)."""
    if section is None:
        section = lattice_check()
    lines = [
        "<!-- generated by: python -m heterofl_tpu.staticcheck "
        "--lattice-md (do not edit by hand) -->",
        "",
        f"The declared feature lattice has **{section['points']}** points "
        f"({' x '.join(str(len(v)) for v in section['axes'].values())} "
        f"over {len(section['axes'])} axes): "
        f"**{section['supported']} supported** (audited anchor + named "
        f"contracts), **{section['refused']} refused** (typed ValueError "
        f"at config resolution), **{section['unreached']} unreached**.",
        "",
        "| axis | values |",
        "|---|---|",
    ]
    for axis, vals in section["axes"].items():
        pretty = [f"`{v}`" + (" (default)" if i == 0 else "")
                  for i, v in enumerate(vals)]
        lines.append(f"| {axis} | {', '.join(pretty)} |")
    lines += [
        "",
        "Refusal provenance (one owning validator per axis; points each "
        "rule refuses):",
        "",
        "| rule | owner | points |",
        "|---|---|---|",
    ]
    for r in section["refusal_rules"]:
        lines.append(f"| `{r['id']}` | `{r['owner']}` | {r['points']} |")
    lines += [
        "",
        "Equivalence contracts carrying the riding axes (points each "
        "covers; program evidence is audited green):",
        "",
        "| contract | points | evidence |",
        "|---|---|---|",
    ]
    for c in section["contracts"]:
        ev = ", ".join(f"`{e}`" for e in c["evidence"])
        lines.append(f"| `{c['name']}` | {c['points']} | {ev} |")
    if section["findings"]:
        lines += ["", "**FINDINGS:**", ""]
        lines += [f"- `{f['rule']}` at `{f['where']}`: {f['message']}"
                  for f in section["findings"]]
    return "\n".join(lines) + "\n"
