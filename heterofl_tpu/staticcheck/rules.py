"""Front 2: path-scoped AST lint rules over the package source.

Each rule bans a set of calls in a set of path prefixes; a finding on a
given line is suppressed by a ``# staticcheck: allow(<rule-id>)`` pragma on
any line the offending call spans (put the reason after the pragma -- the
pragma is the machine-readable half, the comment the human half).

The rules guard the zero-resharding / zero-host-tax contract of the round
engines (PR 1/PR 2): in ``parallel/`` steady-state code, device arrays must
be produced by the explicit staging layer, not per-call ``asarray`` wraps;
nothing on the round path may synchronise (``block_until_ready``,
``device_get``, ``float()`` on device values); traced scopes must not reach
wall clocks or fresh-seeded RNG (cache-key and determinism hazards); and
every ``jax.jit`` must take an explicit donation stance.

Pure AST + stdlib: no jax import, so the lint front runs in milliseconds
and anywhere (pre-commit, the CLI's ``--skip-audit`` mode, the test gate).
"""

from __future__ import annotations

import ast
import io
import os
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from .report import Finding

PRAGMA_RE = re.compile(r"#\s*staticcheck:\s*allow\(([A-Za-z0-9_,\- ]+)\)")

#: modules whose plain ``import x`` already binds the canonical name
_CANONICAL_ROOTS = ("jax", "numpy", "time", "random")


@dataclass(frozen=True)
class Rule:
    """One banned-call rule.

    ``calls``: canonical dotted names (``numpy.asarray``, ``time.time``);
    ``methods``: attribute names banned as method calls on ANY receiver
    (``block_until_ready``); ``builtins``: bare builtin calls (``float``);
    ``require_kwargs``: when set, ``calls`` are not banned outright but must
    pass at least one of these keywords (the ``jax.jit`` donation rule).
    ``paths``: repo-relative path prefixes the rule applies to.
    """

    id: str
    description: str
    paths: Tuple[str, ...]
    calls: Tuple[str, ...] = ()
    methods: Tuple[str, ...] = ()
    builtins: Tuple[str, ...] = ()
    require_kwargs: Tuple[str, ...] = ()
    #: flag a function-body ``import x`` whose name is already bound by a
    #: module-level import (a shadowed inline re-import)
    shadowed_imports: bool = False

    def applies_to(self, relpath: str) -> bool:
        rp = relpath.replace(os.sep, "/")
        return any(rp.startswith(p) or f"/{p}" in rp for p in self.paths)


_PARALLEL = ("heterofl_tpu/parallel/",)
#: kernel/model hot-path code (ISSUE 5): ops/ and models/ run INSIDE the
#: round programs, so the same banned-call rules apply -- trace-time
#: constant coercions carry `allow` pragmas with their reasons.  The wire
#: codecs (ISSUE 8, compress/) encode/decode inside the scanned superstep,
#: so they are hot-path code under the same rules.
#: the scheduler's jax halves (ISSUE 9): deadline draws and the staleness
#: buffer run inside the scanned superstep -- hot-path code under the same
#: rules.  sched/__init__ is the import-light config/validation half (like
#: config.py) and stays out of scope: its float()/rng calls parse host
#: config, never device values.
_SCHED = ("heterofl_tpu/sched/deadline", "heterofl_tpu/sched/buffer")
#: the telemetry jax halves (ISSUE 10/12): obs/probes.py computes the
#: health probes and obs/hist.py the cohort histograms inside the fused
#: round -- hot-path code under the same rules.  obs/__init__ (config
#: validation + host probe assembly), obs/trace, obs/watchdog, obs/ledger
#: and obs/report are host-side (numpy) like sched/__init__ and stay out.
_OBS = ("heterofl_tpu/obs/probes", "heterofl_tpu/obs/hist")
_KERNEL = ("heterofl_tpu/ops/", "heterofl_tpu/models/",
           "heterofl_tpu/compress/") + _SCHED + _OBS
_TRACED = ("heterofl_tpu/parallel/", "heterofl_tpu/fed/") + _KERNEL
_DRIVER = ("heterofl_tpu/entry/",)

DEFAULT_RULES: Tuple[Rule, ...] = (
    Rule("no-asarray",
         "per-call asarray device/host wraps in steady-state code: commit "
         "operands once via the staging layer (PlacementCache) instead",
         _PARALLEL + _KERNEL,
         calls=("jax.numpy.asarray", "numpy.asarray")),
    Rule("no-block-until-ready",
         "host synchronisation on the round path: only the bench/driver "
         "boundary may block",
         _PARALLEL + _KERNEL,
         calls=("jax.block_until_ready",),
         methods=("block_until_ready",)),
    Rule("no-device-get",
         "implicit D2H on the round path: metric sums stay on device "
         "(PendingMetrics) until the caller fetches",
         _PARALLEL + _KERNEL,
         calls=("jax.device_get",),
         methods=("device_get",)),
    Rule("no-float-coercion",
         "float() on a device value blocks on the transfer; fetch through "
         "PendingMetrics / eval boundaries instead",
         _PARALLEL + _KERNEL,
         builtins=("float",)),
    Rule("no-wallclock",
         "wall-clock reads reachable from traced scopes poison program "
         "purity (and silently constant-fold at trace time)",
         _TRACED,
         calls=("time.time", "time.perf_counter", "time.monotonic",
                "time.time_ns", "time.perf_counter_ns")),
    Rule("no-fresh-rng",
         "fresh-seeded host RNG in engine code breaks the reproducible "
         "PRNG-stream contract (fed.core.round_rates/round_users own the "
         "streams)",
         _TRACED,
         calls=("numpy.random.default_rng", "numpy.random.seed",
                "numpy.random.RandomState", "random.seed", "random.random",
                "random.randint")),
    Rule("jit-needs-donation",
         "every jax.jit in the round path must take an explicit donation "
         "stance (donate_argnums/donate_argnames), or carry an allow pragma "
         "saying why buffers must survive",
         _PARALLEL + ("heterofl_tpu/sched/buffer",),
         calls=("jax.jit",),
         require_kwargs=("donate_argnums", "donate_argnames")),
    Rule("no-shadowed-inline-import",
         "inline import of a module the file already imports at module "
         "level: dead weight that shadows the top-level binding for its "
         "scope and hides which imports a function really adds (the "
         "entry/common.py `import math` regression)",
         _DRIVER,
         shadowed_imports=True),
    Rule("no-host-eval-in-driver",
         "host-side eval dispatch in the driver loop: with "
         "superstep_rounds>1 the sBN+eval phases run INSIDE the fused "
         "superstep program (Evaluator.fused); host "
         "sbn_stats/eval_users/eval_global calls belong only on the K=1 "
         "host-loop path or offline tools (pragma with the reason)",
         _DRIVER,
         methods=("sbn_stats", "eval_users", "eval_global")),
)


@dataclass(frozen=True)
class PragmaEntry:
    """One ``# staticcheck: allow(...)`` occurrence: where it sits, which
    rule ids it licenses, and which source lines it covers.  The stale
    check walks these -- a pragma none of whose covered lines suppressed a
    finding for a licensed rule is dead weight."""

    line: int
    ids: Tuple[str, ...]
    covered: Tuple[int, ...]


def _collect_pragmas(src: str) -> Tuple[Dict[int, Set[str]],
                                        List[PragmaEntry]]:
    """(line number -> allowed rule ids, pragma occurrences).

    A pragma covers its own line; a pragma inside a standalone comment
    block also covers the statement line the block precedes (so a
    multi-line reason can sit above the call it licenses).

    Only REAL comments count: the source is tokenized and the pragma
    regex runs on COMMENT tokens, so a pragma-shaped line inside a
    string literal (e.g. the lint tests' fixture snippets) is neither a
    licence nor a liveness obligation.  Unparseable source falls back to
    the line scan -- the lint reports the syntax error separately."""
    lines = src.splitlines()
    out: Dict[int, Set[str]] = {}
    entries: List[PragmaEntry] = []

    comment_lines: Optional[Set[int]] = None
    try:
        comment_lines = {
            tok.start[0]
            for tok in tokenize.generate_tokens(io.StringIO(src).readline)
            if tok.type == tokenize.COMMENT}
    except (tokenize.TokenError, SyntaxError, IndentationError):
        pass

    def add(i: int, ids: Set[str]) -> None:
        out.setdefault(i, set()).update(ids)

    for i, line in enumerate(lines, start=1):
        if comment_lines is not None and i not in comment_lines:
            continue
        m = PRAGMA_RE.search(line)
        if not m:
            continue
        ids = {tok.strip() for tok in m.group(1).split(",") if tok.strip()}
        covered = [i]
        add(i, ids)
        if line.lstrip().startswith("#"):
            j = i + 1
            while j <= len(lines) and lines[j - 1].lstrip().startswith("#"):
                add(j, ids)
                covered.append(j)
                j += 1
            if j <= len(lines):
                add(j, ids)
                covered.append(j)
        entries.append(PragmaEntry(i, tuple(sorted(ids)), tuple(covered)))
    return out, entries


def _alias_map(tree: ast.AST) -> Dict[str, str]:
    """local name -> canonical dotted prefix (``jnp`` -> ``jax.numpy``,
    ``time`` (from-import of ``time.time``) -> ``time.time``)."""
    aliases: Dict[str, str] = {}
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for a in node.names:
                if a.name.split(".")[0] in _CANONICAL_ROOTS:
                    aliases[a.asname or a.name.split(".")[0]] = \
                        a.name if a.asname else a.name.split(".")[0]
        elif isinstance(node, ast.ImportFrom) and node.module:
            if node.module.split(".")[0] in _CANONICAL_ROOTS:
                for a in node.names:
                    aliases[a.asname or a.name] = f"{node.module}.{a.name}"
    return aliases


def _qualname(node: ast.AST, aliases: Dict[str, str]) -> Optional[str]:
    """Canonical dotted name of a Name/Attribute chain, or None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if not isinstance(node, ast.Name):
        return None
    base = aliases.get(node.id, node.id)
    parts.append(base)
    return ".".join(reversed(parts))


def _node_lines(node: ast.AST) -> Iterable[int]:
    lo = getattr(node, "lineno", None)
    if lo is None:
        return ()
    hi = getattr(node, "end_lineno", None) or lo
    return range(lo, hi + 1)


def lint_source(src: str, relpath: str,
                rules: Sequence[Rule] = DEFAULT_RULES) -> List[Finding]:
    """Lint one file's source.  ``relpath`` decides which rules apply.

    Besides the banned-call findings, every ``allow(<rule>)`` pragma is
    audited for liveness: a pragma whose rule no longer fires on any line
    it covers (or that names an unknown rule, or a rule not scoped to this
    path) is a ``stale-pragma`` finding -- dead pragmas otherwise rot
    silently and mask the next real violation on their line."""
    active = [r for r in rules if r.applies_to(relpath)]
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("syntax-error", f"{relpath}:{e.lineno or 0}", str(e))]
    aliases = _alias_map(tree)
    pragmas, pragma_entries = _collect_pragmas(src)
    if not active and not pragma_entries:
        return []
    findings: List[Finding] = []
    #: (rule id, covered line) pairs that actually suppressed a finding
    used_pragmas: Set[Tuple[str, int]] = set()

    def report(rule: Rule, node: ast.AST, what: str) -> None:
        hit = [ln for ln in _node_lines(node)
               if rule.id in pragmas.get(ln, ())]
        if hit:
            used_pragmas.update((rule.id, ln) for ln in hit)
            return
        findings.append(Finding(
            rule.id, f"{relpath}:{node.lineno}",
            f"{what}: {rule.description}"))

    shadow_rules = [r for r in active if r.shadowed_imports]
    if shadow_rules:
        top_names = set()
        for node in tree.body:
            if isinstance(node, ast.Import):
                for a in node.names:
                    top_names.add(a.asname or a.name.split(".")[0])
            elif isinstance(node, ast.ImportFrom):
                for a in node.names:
                    top_names.add(a.asname or a.name)
        # only imports nested in a function body create a shadowing local
        # scope; module-level conditional imports (try/except fallbacks,
        # platform guards) legitimately rebind the module name
        fn_imports: List[ast.AST] = []
        seen_ids: Set[int] = set()
        for fn in ast.walk(tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            for sub in ast.walk(fn):
                if isinstance(sub, (ast.Import, ast.ImportFrom)) \
                        and id(sub) not in seen_ids:
                    seen_ids.add(id(sub))
                    fn_imports.append(sub)
        for node in fn_imports:
            for a in node.names:
                name = a.asname or (a.name if isinstance(node, ast.ImportFrom)
                                    else a.name.split(".")[0])
                if name in top_names:
                    for rule in shadow_rules:
                        report(rule, node, f"inline import of {name!r} "
                               f"already imported at module level")
                    break

    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            qn = _qualname(node.func, aliases)
            for rule in active:
                if rule.require_kwargs:
                    if qn in rule.calls and not any(
                            kw.arg in rule.require_kwargs for kw in node.keywords):
                        report(rule, node, f"{qn}(...) without "
                               f"{'/'.join(rule.require_kwargs)}")
                    continue
                if qn is not None and qn in rule.calls:
                    report(rule, node, f"call to {qn}")
                elif rule.methods and isinstance(node.func, ast.Attribute) \
                        and node.func.attr in rule.methods:
                    report(rule, node, f"method call .{node.func.attr}()")
                elif rule.builtins and isinstance(node.func, ast.Name) \
                        and node.func.id in rule.builtins:
                    report(rule, node, f"builtin {node.func.id}() coercion")
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # a bare @jax.jit decorator takes no donation stance either
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                qn = _qualname(target, aliases)
                for rule in active:
                    if rule.require_kwargs and qn in rule.calls \
                            and not isinstance(dec, ast.Call):
                        report(rule, dec, f"bare @{qn} decorator without "
                               f"{'/'.join(rule.require_kwargs)}")

    # stale-pragma audit (ISSUE 7 satellite): every allow(<rule>) occurrence
    # must have actually suppressed a finding this pass -- per licensed rule
    # id, so a multi-id pragma reports only its dead halves
    known_ids = {r.id for r in rules}
    active_ids = {r.id for r in active}
    for ent in pragma_entries:
        for rid in ent.ids:
            if rid not in known_ids:
                findings.append(Finding(
                    "stale-pragma", f"{relpath}:{ent.line}",
                    f"allow({rid}) names an unknown rule id; known ids: "
                    f"{sorted(known_ids)}"))
            elif rid not in active_ids:
                findings.append(Finding(
                    "stale-pragma", f"{relpath}:{ent.line}",
                    f"allow({rid}) licenses a rule that is not scoped to "
                    f"this path -- the pragma can never suppress anything "
                    f"here; remove it"))
            elif not any((rid, ln) in used_pragmas for ln in ent.covered):
                findings.append(Finding(
                    "stale-pragma", f"{relpath}:{ent.line}",
                    f"allow({rid}) no longer suppresses any `{rid}` finding "
                    f"on the lines it covers; the violation it licensed is "
                    f"gone -- remove the dead pragma before it masks the "
                    f"next real one"))
    return findings


def lint_paths(files: Iterable[Tuple[str, str]],
               rules: Sequence[Rule] = DEFAULT_RULES) -> List[Finding]:
    """Lint ``(relpath, source)`` pairs."""
    out: List[Finding] = []
    for relpath, src in files:
        out.extend(lint_source(src, relpath, rules))
    return out


def lint_tree(root: str, rules: Sequence[Rule] = DEFAULT_RULES,
              subdirs: Optional[Sequence[str]] = None) -> List[Finding]:
    """Walk ``root`` (a repo checkout or any directory laid out like one)
    and lint every ``.py`` file under it.  ``subdirs`` restricts the walk.

    Relpaths are prefixed with ``root``'s own directory name so the rule
    path scopes resolve even when ``root`` points INSIDE the layout (e.g.
    ``--lint-root heterofl_tpu`` yields ``heterofl_tpu/parallel/...``, not
    the scope-defeating ``parallel/...``)."""
    pairs: List[Tuple[str, str]] = []
    findings: List[Finding] = []
    prefix = os.path.basename(os.path.abspath(root))
    roots = [os.path.join(root, s) for s in subdirs] if subdirs else [root]
    for base in roots:
        for dirpath, dirnames, filenames in os.walk(base):
            dirnames[:] = [d for d in dirnames
                           if d not in (".git", "__pycache__", ".jax_cache")]
            for fn in sorted(filenames):
                if not fn.endswith(".py"):
                    continue
                full = os.path.join(dirpath, fn)
                rel = os.path.join(prefix, os.path.relpath(full, root))
                try:
                    with open(full, encoding="utf-8") as f:
                        pairs.append((rel, f.read()))
                except OSError as e:
                    # unreadable source IS a finding: the gate must not
                    # silently skip files (and must keep the rest's findings)
                    findings.append(Finding("unreadable", rel, str(e)))
    return findings + lint_paths(pairs, rules)


def pragma_sweep(root: str, rules: Sequence[Rule] = DEFAULT_RULES,
                 exclude: Sequence[str] = ()) -> List[Finding]:
    """Whole-repo stale-pragma liveness (ISSUE 18 satellite).

    The banned-call rules stay scoped to the package tree, but pragmas
    rot ANYWHERE -- a ``# staticcheck: allow(...)`` in tests/ or
    scripts/ that no longer suppresses anything (or licenses a rule that
    cannot fire on its path) masks the next real violation just the
    same.  This walks every ``.py`` under ``root``, runs the full lint
    per file, and keeps ONLY the pragma-liveness verdicts
    (``stale-pragma``/``syntax-error``/``unreadable``).  ``exclude``
    skips top-level subtrees the scoped lint already covered, so the
    two fronts never double-report."""
    keep = {"stale-pragma", "syntax-error", "unreadable"}
    prefix = os.path.basename(os.path.abspath(root))
    findings: List[Finding] = []
    skip = set(exclude) | {".git", "__pycache__", ".jax_cache",
                           ".claude", "node_modules"}
    for dirpath, dirnames, filenames in os.walk(root):
        if os.path.abspath(dirpath) == os.path.abspath(root):
            dirnames[:] = [d for d in dirnames if d not in skip]
        else:
            dirnames[:] = [d for d in dirnames
                           if d not in (".git", "__pycache__", ".jax_cache")]
        for fn in sorted(filenames):
            if not fn.endswith(".py"):
                continue
            full = os.path.join(dirpath, fn)
            rel = os.path.join(prefix, os.path.relpath(full, root))
            try:
                with open(full, encoding="utf-8") as f:
                    src = f.read()
            except OSError as e:
                findings.append(Finding("unreadable", rel, str(e)))
                continue
            if "staticcheck:" not in src:
                continue  # nothing to audit; skip the parse
            findings.extend(f for f in lint_source(src, rel, rules)
                            if f.rule in keep)
    return findings
