"""HBM footprint auditor (ISSUE 7 tentpole): the ``memory_analysis()``
fields stop being decoration and become budgeted findings.

Until this module the audit recorded per-program temp/argument/output bytes
into STATICCHECK.json and enforced nothing -- a silent memory doubling
(an un-donated carry, a duplicated staging commit, a forgotten eval
operand) would fail on the TPU at 1e6-user scale instead of failing the
audit.  Three layers now:

* **required fields** (``memory-analysis-missing``): a compiled flagship
  program whose ``memory_analysis()`` lacks temp/argument/output bytes is
  a loud finding, not an empty record (the old ``getattr``-skip silently
  produced exactly that).
* **analytic bounds** (``hbm-budget``): each field is held to a bound
  derived from the analytic byte tables
  (:func:`~..fed.core.level_byte_table` activations + params, the flat
  scan carry, the staged operand bytes).  The bounds are deliberately
  generous ceilings (the audit widths leave the compiler room); they catch
  order-of-magnitude blowups outright, while the **ratchet**
  (:mod:`.ratchet`) pins the exact measured bytes against the committed
  baseline at tight tolerances -- that is where a 2x doubling fails.
* **donation savings** (``hbm-donation-savings``): the bytes input-output
  aliasing ACTUALLY saved, accounted from the donated argument footprint x
  the consumed-alias fraction.  An un-donated leaf shows up here as lost
  bytes, not just as a count mismatch.

Import-light (no jax at module level), like the rest of the package.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from .report import Finding

#: ``memory_analysis()`` fields a compiled flagship program MUST expose --
#: their absence means the audit can no longer see the program's HBM
#: footprint and must say so loudly (ISSUE 7 satellite: audit.py used to
#: ``getattr``-skip these into an empty record)
REQUIRED_MEMORY_FIELDS = ("temp_size_in_bytes", "argument_size_in_bytes",
                          "output_size_in_bytes")

#: recorded when present, never required (backend-dependent)
OPTIONAL_MEMORY_FIELDS = ("generated_code_size_in_bytes",
                          "alias_size_in_bytes", "peak_memory_in_bytes",
                          "host_temp_size_in_bytes")

#: HBM temp budget = TEMP_FACTOR x (per-device analytic working set) +
#: SLACK.  The working set: ACT_WORKING_SET live activation copies per
#: concurrent client (forward outputs + backward-saved + grad workspace),
#: CARRY_COPIES param-shaped carry buffers (params, momentum, update sums,
#: count masks, double-buffered across the donation boundary), the psum
#: payload, and one materialised copy of the staged operands.  Sized so the
#: green matrix sits at <= ~0.5x of budget (measured on the audit widths)
#: and a 10x blowup trips unconditionally; the ratchet holds the tight
#: line.
TEMP_FACTOR = 2.5
ACT_WORKING_SET = 3
CARRY_COPIES = 8
TEMP_SLACK = 1 << 20

#: argument budget: the per-device argument bytes can never exceed the
#: whole staged operand footprint (sharded placements hold a 1/n_dev
#: shard); the margin absorbs XLA's tupling/padding
ARG_MARGIN = 1.02
ARG_SLACK = 64 << 10

#: output budget: fresh params (aliased over the donated ones) + stacked
#: per-round metrics
OUT_SLACK = 1 << 20


def collect_memory(ma, name: str) -> Tuple[Optional[Dict[str, int]],
                                           List[Finding]]:
    """Extract the memory fields of one ``memory_analysis()`` result.

    Returns ``(fields, findings)``: every :data:`REQUIRED_MEMORY_FIELDS`
    member that is absent (or the whole analysis being unavailable) is a
    ``memory-analysis-missing`` finding -- the audit's view of the
    program's HBM footprint just went dark, which is itself a regression.
    ``peak_bytes`` is derived (argument + temp + output; XLA:CPU exposes
    no direct peak) so the ratchet has one headline number per program."""
    findings: List[Finding] = []
    if ma is None:
        findings.append(Finding(
            "memory-analysis-missing", name,
            "memory_analysis() returned None for a compiled flagship "
            "program: the HBM footprint audit is blind here"))
        return None, findings
    out: Dict[str, int] = {}
    for k in REQUIRED_MEMORY_FIELDS:
        if not hasattr(ma, k):
            findings.append(Finding(
                "memory-analysis-missing", name,
                f"memory_analysis() lacks required field `{k}`: the HBM "
                f"budget for this program can no longer be audited"))
            continue
        out[k] = int(getattr(ma, k))
    for k in OPTIONAL_MEMORY_FIELDS:
        if hasattr(ma, k):
            out[k] = int(getattr(ma, k))
    if all(k in out for k in REQUIRED_MEMORY_FIELDS):
        out["peak_bytes"] = (out["temp_size_in_bytes"]
                             + out["argument_size_in_bytes"]
                             + out["output_size_in_bytes"])
    return out, findings


def analytic_budget(param_bytes: int, activation_bytes: int,
                    clients_per_device: int, staged_arg_bytes: int,
                    train_payload_bytes: int) -> Dict[str, int]:
    """The per-program analytic HBM bound (see module docstring for the
    model).  All inputs are analytic or example-arg derived -- nothing is
    fitted to measured values, so the bound holds at flagship widths by
    construction."""
    working = (clients_per_device * ACT_WORKING_SET * activation_bytes
               + CARRY_COPIES * param_bytes
               + train_payload_bytes
               + staged_arg_bytes)
    return {
        "temp_budget": int(TEMP_FACTOR * working) + TEMP_SLACK,
        "argument_budget": int(ARG_MARGIN * staged_arg_bytes) + ARG_SLACK,
        "output_budget": int(param_bytes) + OUT_SLACK,
        "inputs": {
            "param_bytes": int(param_bytes),
            "activation_bytes": int(activation_bytes),
            "clients_per_device": int(clients_per_device),
            "staged_arg_bytes": int(staged_arg_bytes),
            "train_payload_bytes": int(train_payload_bytes),
        },
    }


#: measured field -> budget key
_BUDGETED = (("temp_size_in_bytes", "temp_budget"),
             ("argument_size_in_bytes", "argument_budget"),
             ("output_size_in_bytes", "output_budget"))


def check_memory(rep, mem: Optional[Dict[str, int]],
                 budget: Dict[str, int]) -> None:
    """Hold one program's measured memory fields to the analytic bound
    (``rep`` is a :class:`~.report.ProgramReport`; ``hbm-budget``
    findings name the field and both numbers)."""
    if mem is None:
        return  # collect_memory already failed memory-analysis-missing
    for field, bkey in _BUDGETED:
        if field not in mem:
            continue  # absence already reported by collect_memory
        if mem[field] > budget[bkey]:
            rep.fail("hbm-budget",
                     f"{field} = {mem[field]} bytes exceeds the analytic "
                     f"bound {budget[bkey]} ({bkey}; inputs "
                     f"{budget['inputs']}): the program's HBM footprint "
                     f"blew past what its shapes justify")


def donation_accounting(rep, donated_arg_bytes: int) -> Dict[str, int]:
    """Bytes input-output aliasing actually saved vs what full donation
    coverage would save.  ``donated_arg_bytes`` is the footprint of the
    donation-expected argument leaves (the params carry); the consumed
    fraction comes from the compiled alias count already parsed by the
    audit.  Shortfall -> ``hbm-donation-savings`` with the lost bytes (the
    buffers XLA will double)."""
    expected = int(donated_arg_bytes) if rep.donation_expected else 0
    if rep.donation_expected:
        saved = expected * rep.aliased // rep.donation_expected
    else:
        saved = 0
    acct = {"expected_saved_bytes": expected, "saved_bytes": saved}
    if saved < expected:
        rep.fail("hbm-donation-savings",
                 f"input-output aliasing saved {saved} of the "
                 f"{expected} donated-carry bytes ({rep.aliased}/"
                 f"{rep.donation_expected} leaves consumed): the "
                 f"difference is silently double-buffered every dispatch")
    return acct
