"""AOT v4-128 multi-host audit (ISSUE 17): classify the flagship grouped
slices fused superstep against a REAL pod topology's process grid.

The fake-mesh entries in audit.py prove the wire model holds when the
clients axis is *declared* cross-host; this module proves the same against
an actual ``v4-128`` device grid -- 64 megacore chips over 16 hosts, the
ROADMAP's >=10 rounds/sec target topology -- where
:func:`~.wire.dcn_axes_of` derives the DCN axes from each device's
``process_index`` instead of an override.  The engine's host-aligned
slices partition (``_clients_row_chunks``) sees the same grid, so the
audit exercises the exact placement a pod run would take.

Environment reality: TPU topology descriptions need a PJRT TPU plugin, and
this container's plugin hangs on discovery (it tunnels to real hardware).
Everything therefore runs in a SUBPROCESS under a hard timeout:

* child ``tpu``: ``jax.experimental.topologies.get_topology_desc`` for
  v4-128, mesh over the topology devices, trace + AOT-lower the fused
  slices program, classify DCN from the real process grid.
* child ``cpu`` (fallback): 64 forced host devices in 1 process -- the
  same program and mesh SHAPE, with ``dcn_axes=("clients",)`` supplied
  explicitly (recorded as synthetic).

Results land in ``report.config["aot_v4128"]`` ONLY -- never as a program
entry -- so the ratchet baseline stays stable across environments where
the TPU path is (un)available.  The audit fails only on an actual budget
violation from a child that RAN; unavailability is recorded, not fatal.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Any, Dict, Optional

#: v4-128: 4x4x8 chip grid, megacore (one device per chip), 4 chips/host
V4128 = {"name": "v4-128", "topology_name": "v4:4x4x8",
         "chip_config_name": "megacore",
         "chips_per_host_bounds": (2, 2, 1), "devices": 64, "processes": 16}


def _child_payload(mode: str, flagship: bool) -> Dict[str, Any]:
    """Runs INSIDE the subprocess: build the mesh (topology or forced-host
    CPU), trace the fused grouped-slices superstep, price + classify its
    collectives, attempt the AOT lowering.  Returns a plain JSON-able
    dict; any exception is caught by the __main__ wrapper."""
    import numpy as np

    import jax

    from ..fed.core import level_byte_table
    from ..parallel import GroupedRoundEngine
    from ..parallel.grouped import _bucket_pow2
    from ..utils.optim import make_traced_lr_fn
    from .audit import _ceil_div, _sds, default_audit_cfg
    from .jaxpr_walk import find_reshards
    from .wire import dcn_axes_of, program_wire

    from jax.sharding import Mesh

    cfg = default_audit_cfg(flagship)
    out: Dict[str, Any] = {"mode": mode, "flagship": flagship}
    if mode == "tpu":
        from jax.experimental import topologies

        topo = topologies.get_topology_desc(
            V4128["name"], platform="tpu",
            topology_name=V4128["topology_name"],
            chip_config_name=V4128["chip_config_name"],
            chips_per_host_bounds=V4128["chips_per_host_bounds"],
            num_slices=1)
        devices = list(topo.devices)
        synthetic_dcn = None
    else:
        devices = list(jax.devices())
        synthetic_dcn = ("clients",)  # 1 process: declare the split
    n_dev = len(devices)
    mesh = Mesh(np.array(devices).reshape(n_dev, 1), ("clients", "data"))
    out["devices"] = n_dev
    out["processes"] = len({getattr(d, "process_index", 0) for d in devices})

    grp = GroupedRoundEngine(dict(cfg, level_placement="slices",
                                  strict_placement=True), mesh)
    grp._lr_fn = make_traced_lr_fn(cfg)
    mode_got, _ = grp._fused_layout()
    if mode_got != "slices":
        raise RuntimeError(f"fused layout refused slices on the {mode} "
                           f"mesh: {mode_got}")
    bt = level_byte_table(cfg)
    wire_top = bt[max(bt)]["wire_bytes"]
    k = 8
    per_level = 2
    need = max(_ceil_div(per_level, grp._slices[r][1] - grp._slices[r][0])
               for r in grp.levels)
    per_dev = _bucket_pow2(need)
    prog = grp._superstep_prog(k, per_dev, "slices")

    # params/key are real host values (init runs on the local CPU backend);
    # the data operands are avals only -- nothing is placed on the topology
    from ..models import make_model

    params = make_model(cfg).init(jax.random.key(0))
    key = jax.random.key(0)
    U = cfg["num_users"]
    from ..data import fetch_dataset, split_dataset, stack_client_shards, \
        label_split_masks

    ds = fetch_dataset(cfg["data_name"], synthetic=True, seed=0,
                       synthetic_sizes={"train": 2000 if flagship else 400,
                                        "test": 100})
    rng = np.random.default_rng(0)
    split, lsplit = split_dataset(ds, U, "iid", rng, classes_size=10)
    x, y, m = stack_client_shards(ds["train"].data, ds["train"].target,
                                  split["train"], list(range(U)))
    lm = label_split_masks(lsplit, U, 10)
    data = tuple(_sds(a.shape, a.dtype) for a in (x, y, m, lm))

    traced = prog.trace(params, key, np.int32(1),
                        _sds((k, per_dev * n_dev)), *data)
    jaxpr = traced.jaxpr
    dcn_axes = dcn_axes_of(mesh)
    out["real_dcn_axes"] = list(dcn_axes)
    out["synthetic_dcn_axes"] = synthetic_dcn is not None
    wire = program_wire(jaxpr, mesh,
                        dcn_axes=dcn_axes if dcn_axes else synthetic_dcn)
    reshards = find_reshards(jaxpr)
    out["dcn_axes"] = wire["dcn_axes"]
    out["dcn_bytes_per_round"] = wire["dcn_bytes"]
    out["train_bytes_per_round"] = wire["train_bytes_per_round"]
    out["budget_bytes"] = wire_top
    out["reshards_jaxpr"] = len(reshards)
    out["dcn_ok"] = (wire["dcn_bytes"] == wire_top
                     and wire["other_bytes"] == 0 and not reshards)
    try:
        prog.lower(params, key, np.int32(1),
                   _sds((k, per_dev * n_dev)), *data)
        out["lowered"] = True
    except Exception as e:  # AOT compile support varies by plugin
        out["lowered"] = False
        out["lower_error"] = f"{type(e).__name__}: {e}"[:300]
    out["ok"] = bool(out["dcn_ok"])
    return out


def _spawn(mode: str, flagship: bool, timeout_s: int) -> Dict[str, Any]:
    env = dict(os.environ)
    # same scrub as the CPU audit: no remote-compile pools, and the cpu
    # child needs 64 host devices to lay out the v4-128-shaped mesh
    for k in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
              "AXON_LOOPBACK_RELAY", "AXON_POOL_SVC_OVERRIDE"):
        env.pop(k, None)
    if mode == "cpu":
        env["JAX_PLATFORMS"] = "cpu"
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + " --xla_force_host_platform_device_count=64").strip()
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "heterofl_tpu.staticcheck.aot", mode]
            + (["--flagship"] if flagship else []),
            capture_output=True, text=True, timeout=timeout_s, env=env)
    except subprocess.TimeoutExpired:
        return {"mode": mode, "available": False,
                "reason": f"timed out after {timeout_s}s (TPU plugin "
                          f"discovery hangs without hardware)"}
    if proc.returncode != 0:
        return {"mode": mode, "available": False,
                "reason": (proc.stderr or proc.stdout or "")[-400:]}
    try:
        return {"available": True, **json.loads(proc.stdout.strip().splitlines()[-1])}
    except Exception as e:
        return {"mode": mode, "available": False,
                "reason": f"unparseable child output ({e}): "
                          f"{proc.stdout[-200:]}"}


def aot_v4128_check(flagship: bool = False, tpu_timeout_s: int = 120,
                    cpu_timeout_s: int = 420) -> Dict[str, Any]:
    """Best-effort v4-128 AOT audit: try the real TPU topology first, fall
    back to the 64-device CPU mesh with a declared DCN axis.  Always
    returns a record for ``report.config["aot_v4128"]``; ``ok`` is absent
    when no child could run (environment, not regression)."""
    res = _spawn("tpu", flagship, tpu_timeout_s)
    if not res.get("available"):
        fb = _spawn("cpu", flagship, cpu_timeout_s)
        fb["tpu_unavailable_reason"] = res.get("reason", "")[:400]
        return fb
    return res


if __name__ == "__main__":
    mode = sys.argv[1] if len(sys.argv) > 1 else "cpu"
    flagship = "--flagship" in sys.argv
    try:
        print(json.dumps(_child_payload(mode, flagship)))
    except Exception as e:  # noqa: BLE001 - parent records the reason
        print(f"{type(e).__name__}: {e}", file=sys.stderr)
        sys.exit(1)
