"""Evaluation entry points (the reference's ``test_*.py`` drivers).

Parity: ``src/test_classifier_fed.py`` (§3.6 of SURVEY.md): load the best
checkpoint, re-run sBN recalibration over the train set, evaluate Local +
Global metrics, and bundle them to ``output/result/{tag}.pkl`` -- the input
to the result-aggregation tooling (:mod:`heterofl_tpu.analysis.process`).
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from .. import config as C
from ..utils import Logger, load_checkpoint, checkpoint_path, summarize_sums
from .common import FedExperiment, build_cli, cfg_from_args


def evaluate_experiment(cfg: Dict[str, Any], seed: int, load_tag: str = "best") -> Dict[str, Any]:
    if cfg["control"].get("data_split_mode") == "none":
        return _evaluate_central(cfg, seed, load_tag)
    exp = FedExperiment(cfg, seed)
    path = checkpoint_path(cfg["output_dir"], exp.tag, load_tag)
    if not os.path.exists(path):
        raise SystemExit(f"Not exists model tag: {exp.tag} "
                         f"(expected checkpoint at {path}) -- train first")
    blob = load_checkpoint(path)
    params = {k: jnp.asarray(v) for k, v in blob["params"].items()}
    data_split, label_split = blob["data_split"], blob["label_split"]
    exp.stage(data_split, label_split)
    logger = Logger(os.path.join(cfg["output_dir"], "runs", f"test_{exp.tag}"),
                    use_tensorboard=bool(cfg.get("use_tensorboard")))
    logger.safe(True)
    # checkpoints store the *resume* epoch (epoch+1); the eval RNG must reuse
    # the epoch the checkpoint was evaluated at during training, or the
    # re-evaluated LM metrics won't reproduce the logged ones
    ckpt_epoch = max(int(blob.get("epoch") or 1) - 1, 0)
    named_global = exp.evaluate(params, ckpt_epoch, logger, label_split)
    logger.safe(False)
    result = {
        "cfg": {k: v for k, v in exp.cfg.items() if k != "vocab"},
        "epoch": blob.get("epoch"),
        "logger_history": dict(logger.history),
        "train_history": blob.get("logger_history", {}),
    }
    out_path = os.path.join(cfg["output_dir"], "result", f"{exp.tag}.pkl")
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "wb") as f:
        pickle.dump(result, f)
    print(f"saved result bundle: {out_path}")
    return result


def _evaluate_central(cfg: Dict[str, Any], seed: int, load_tag: str) -> Dict[str, Any]:
    from .central import CentralExperiment, _batch_pad, _stack_windows
    from ..data import bptt_windows

    exp = CentralExperiment(cfg, seed)
    cfg = exp.cfg
    blob = load_checkpoint(checkpoint_path(cfg["output_dir"], exp.tag, load_tag))
    params = {k: jnp.asarray(v) for k, v in blob["params"].items()}
    # stored epoch is the resume epoch (epoch+1); rewind to the evaluated one
    ep = max(int(blob.get("epoch") or 1) - 1, 0)
    if exp.kind == "vision":
        xs, ws = _batch_pad(exp.dataset["train"].data, cfg["batch_size"]["train"])
        # staticcheck: allow(no-host-eval-in-driver): offline one-shot eval
        # tool, not the federated round loop
        bn = exp.evaluator.sbn_stats(params, xs, ws)
        te = exp.dataset["test"]
        xg, wg = _batch_pad(te.data, cfg["batch_size"]["test"])
        yg, _ = _batch_pad(te.target, cfg["batch_size"]["test"])
        # staticcheck: allow(no-host-eval-in-driver): offline eval tool
        g = exp.evaluator.eval_global(params, bn, xg, yg, wg, epoch=ep)
    else:
        xs, ws = _stack_windows(bptt_windows(exp.dataset["test"].token, cfg["bptt"]), cfg["bptt"])
        # staticcheck: allow(no-host-eval-in-driver): offline eval tool
        g = exp.evaluator.eval_global(params, {}, xs, ws, epoch=ep)
    named = summarize_sums({k: np.asarray(v) for k, v in g.items()}, cfg["model_name"], prefix="")
    result = {"cfg": {k: v for k, v in cfg.items() if k != "vocab"},
              "epoch": blob.get("epoch"), "metrics": named,
              "train_history": blob.get("logger_history", {})}
    out_path = os.path.join(cfg["output_dir"], "result", f"{exp.tag}.pkl")
    os.makedirs(os.path.dirname(out_path), exist_ok=True)
    with open(out_path, "wb") as f:
        pickle.dump(result, f)
    print(f"saved result bundle: {out_path}  {named}")
    return result


def run_test_main(description: str, model_default: str, data_default: str,
                  argv: Optional[List[str]] = None):
    parser = build_cli(description)
    args = parser.parse_args(argv)
    cfg = cfg_from_args(args)
    if args.model_name is None:
        cfg["model_name"] = model_default
    if args.data_name is None:
        cfg["data_name"] = data_default
    cfg = C.process_control(cfg)
    results = []
    for i in range(cfg["num_experiments"]):
        seed = cfg["init_seed"] + i
        results.append(evaluate_experiment(cfg, seed))
    return results
