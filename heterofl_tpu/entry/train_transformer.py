"""Centralized masked-LM baseline (parity: ``src/train_transformer.py``)."""

from .central import run_central_main


def main(argv=None):
    return run_central_main("heterofl-tpu centralized transformer", "transformer", "WikiText2",
                            pivot_metric="Perplexity", pivot_mode="min", argv=argv)


if __name__ == "__main__":
    main()
