"""Federated vision training (flagship entry point).

Parity: ``src/train_classifier_fed.py`` -- per round: sample
``ceil(frac * num_users)`` users, heterogeneous local SGD, counted-average
aggregation, sBN recalibration, Local+Global eval, MultiStep LR, checkpoint +
best copy pivoted on Global-Accuracy.  The whole round is one XLA program
(see parallel/round_engine.py); steady-state rounds dispatch with zero
implicit host->device transfers (parallel/staging.py), each info line
carries the stage/dispatch/fetch phase breakdown, and
``--metrics_fetch_every K`` keeps metric sums on device for K rounds so
dispatch overlaps the fetch (eval boundaries flush).
"""

from .common import run_main


def main(argv=None):
    return run_main("heterofl-tpu federated classifier", "resnet18", "CIFAR10",
                    pivot_metric="Global-Accuracy", pivot_mode="max", argv=argv)


if __name__ == "__main__":
    main()
