"""Federated vision training (flagship entry point).

Parity: ``src/train_classifier_fed.py`` -- per round: sample
``ceil(frac * num_users)`` users, heterogeneous local SGD, counted-average
aggregation, sBN recalibration, Local+Global eval, MultiStep LR, checkpoint +
best copy pivoted on Global-Accuracy.  The whole round is one XLA program
(see parallel/round_engine.py).
"""

from .common import run_main


def main(argv=None):
    return run_main("heterofl-tpu federated classifier", "resnet18", "CIFAR10",
                    pivot_metric="Global-Accuracy", pivot_mode="max", argv=argv)


if __name__ == "__main__":
    main()
