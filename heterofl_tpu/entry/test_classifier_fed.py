"""Evaluation driver (parity: ``src/test_classifier_fed.py``)."""

from .evaluate import run_test_main


def main(argv=None):
    return run_test_main("heterofl-tpu test_classifier_fed", "resnet18", "CIFAR10", argv=argv)


if __name__ == "__main__":
    main()
