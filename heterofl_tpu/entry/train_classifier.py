"""Centralized vision baseline (parity: ``src/train_classifier.py``)."""

from .central import run_central_main


def main(argv=None):
    return run_central_main("heterofl-tpu centralized classifier", "resnet18", "CIFAR10",
                            pivot_metric="Accuracy", pivot_mode="max", argv=argv)


if __name__ == "__main__":
    main()
