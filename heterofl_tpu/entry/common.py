"""Shared experiment driver for all entry points.

Mirrors the reference's L5 structure (ref train_classifier_fed.py:37-96):
CLI flags auto-derived from cfg keys + ``--control_name``; per-seed
experiment loop; per-round train -> sBN recalibration -> Local/Global eval ->
scheduler step -> checkpoint + best-pivot copy.  The compute path is the
jitted :class:`~heterofl_tpu.parallel.RoundEngine`; only user sampling,
logging and checkpointing live on the host.
"""

from __future__ import annotations

import argparse
import datetime
import json
import math
import os
import time
import warnings
from contextlib import nullcontext
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from .. import config as C
from ..chaos import resolve_poison_cfg
from ..compress import resolve_codec_cfg
from ..obs import (resolve_ledger_cfg, resolve_quarantine_cfg,
                   resolve_telemetry_cfg, split_probes)
from ..obs.ledger import ClientLedger
from ..obs.watchdog import (RETRY_SALT, Watchdog, WatchdogError,
                            WatchdogRollback)
from ..data import (
    bptt_windows,
    stack_windows,
    fetch_dataset,
    label_split_masks,
    process_dataset,
    split_dataset,
    stack_client_shards,
    stack_client_token_rows,
)
from ..fed.core import (arm_stream_keys, round_rates, round_users,
                        superstep_rate_schedule, superstep_user_schedule,
                        validate_width_geometry)
from ..fed.sampling import ScheduleCommitment, resolve_sampler_cfg
from ..multi import resolve_arms_cfg
from ..sched import resolve_schedule_cfg
from ..models import make_model
from ..parallel import (ClientStore, MetricsPipeline, PendingMetrics,
                        PhaseTimer, RoundEngine, make_mesh)
from ..parallel.evaluation import Evaluator
from ..utils.compile_cache import enable_persistent_cache
from ..utils import (
    Logger,
    checkpoint_path,
    copy_best,
    dense_from_blocks,
    is_shard_marker,
    make_scheduler,
    resume,
    save_checkpoint,
    save_checkpoint_sharded,
    summarize_sums,
)
from ..utils.optim import PlateauScheduler


# ---------------------------------------------------------------------------
# CLI (ref train_classifier_fed.py:20-30: every cfg key is a flag)
# ---------------------------------------------------------------------------

def build_cli(description: str) -> argparse.ArgumentParser:
    parser = argparse.ArgumentParser(description=description)
    for k, v in C.DEFAULT_CFG.items():
        if v is None or isinstance(v, (dict, list)):
            parser.add_argument(f"--{k}", default=None, type=str,
                                help=f"JSON override (default {json.dumps(v)})")
        elif isinstance(v, bool):
            parser.add_argument(f"--{k}", default=None, type=int)
        else:
            parser.add_argument(f"--{k}", default=None, type=type(v))
    parser.add_argument("--control_name", default=None, type=str)
    return parser


def cfg_from_args(args: argparse.Namespace) -> Dict[str, Any]:
    cfg = C.default_cfg()
    for k, v in C.DEFAULT_CFG.items():
        val = getattr(args, k, None)
        if val is None:
            continue
        if v is None:
            # None-default flags: JSON containers/null parse, anything else
            # stays a raw string (paths like "123" must not become ints)
            try:
                parsed = json.loads(val)
            except json.JSONDecodeError:
                parsed = val
            cfg[k] = parsed if isinstance(parsed, (dict, list, type(None))) else val
        elif isinstance(v, (dict, list)):
            cfg[k] = json.loads(val)
        elif isinstance(v, bool):
            cfg[k] = bool(val)
        else:
            cfg[k] = val
    if getattr(args, "control_name", None) and args.control_name != "None":
        cfg["control"] = C.parse_control_name(args.control_name)
    return cfg


# ---------------------------------------------------------------------------
# multi-host resume consistency (ISSUE 17 satellite: tested directly)
# ---------------------------------------------------------------------------

def check_multihost_resume(blob: Optional[Dict[str, Any]]) -> int:
    """Verify every process resumed the SAME checkpoint state and return
    the agreed epoch.

    Sharded checkpoints load through the shared filesystem (the header
    names every process's shard file), so hosts given per-host LOCAL
    ``output_dir``\\ s diverge immediately: hosts 1..k see no blob (or a
    stale one) while process 0 resumes -- and the runs silently split into
    different round counts.  A cross-host broadcast of process 0's epoch
    catches that before any training dispatch.  No-op (returns this
    process's epoch) on a single-process runtime."""
    mine = int(blob.get("epoch", 0) if blob else 0)
    if jax.process_count() <= 1:
        return mine
    from jax.experimental import multihost_utils

    epoch0 = int(multihost_utils.broadcast_one_to_all(jnp.int32(mine)))
    if mine != epoch0:
        raise RuntimeError(
            f"resume state differs across hosts (process 0 at epoch "
            f"{epoch0}, this host at {mine}): output_dir must be a "
            f"shared filesystem for multi-host resume")
    return epoch0


def _restore_params(blob_params: Dict[str, Any]) -> Dict[str, Any]:
    """Checkpointed params -> device trees: shard-blocks markers (written
    by a multi-process run) densify from the merged block set first, so a
    blob restores onto ANY process count."""
    return {k: jnp.asarray(dense_from_blocks(v) if is_shard_marker(v) else v)
            for k, v in blob_params.items()}


# ---------------------------------------------------------------------------
# data staging for the engines
# ---------------------------------------------------------------------------

def _batch_array(x: np.ndarray, b: int, pad_value=0) -> Tuple[np.ndarray, np.ndarray]:
    """[N, ...] -> ([S, b, ...], weights [S, b]) padding the tail."""
    n = x.shape[0]
    s = math.ceil(n / b)
    pad = s * b - n
    w = np.concatenate([np.ones(n, np.float32), np.zeros(pad, np.float32)])
    if pad:
        x = np.concatenate([x, np.full((pad,) + x.shape[1:], pad_value, x.dtype)])
    return x.reshape((s, b) + x.shape[1:]), w.reshape(s, b)


def stage_local_eval(xu: np.ndarray, yu: np.ndarray, mu: np.ndarray,
                     batch_size: int) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Per-user test shards ``[U, N, ...]`` -> batched ``[U, S, B, ...]``
    (tail padded with zero-weight samples): THE Local-eval operand layout,
    shared by the driver, the staticcheck eval-fused audit and bench.py so
    their committed operands cannot drift apart."""
    u, n = xu.shape[0], xu.shape[1]
    b = min(batch_size, n)
    s = math.ceil(n / b)
    pad = s * b - n
    if pad:
        xu = np.concatenate([xu, np.zeros((u, pad) + xu.shape[2:], xu.dtype)], 1)
        yu = np.concatenate([yu, np.zeros((u, pad), yu.dtype)], 1)
        mu = np.concatenate([mu, np.zeros((u, pad), np.float32)], 1)
    return (xu.reshape(u, s, b, *xu.shape[2:]), yu.reshape(u, s, b),
            mu.reshape(u, s, b))


def stage_eval_operands(cfg, train_set, test_set, test_split, lm):
    """THE vision eval-operand assembly -- ``(sbn_batches, local_eval,
    global_eval)`` exactly as the driver commits them -- shared by
    :meth:`FedExperiment.stage`, the staticcheck eval-fused audit and
    bench.py, so the audited/benched operand layout cannot drift from the
    driver's."""
    users = cfg["num_users"]
    sbn = _batch_array(train_set.data, cfg["batch_size"]["train"])
    b = cfg["batch_size"]["test"]
    xg, wg = _batch_array(test_set.data, b)
    yg, _ = _batch_array(test_set.target, b)
    xu, yu, mu = stack_client_shards(test_set.data, test_set.target,
                                     test_split, list(range(users)))
    local = stage_local_eval(xu, yu, mu, b) + (lm,)
    return sbn, local, (xg, yg, wg)


def _maybe_compute_norm_stats(cfg: Dict[str, Any], dataset: Dict[str, Any]) -> None:
    """Datasets without a DATASET_STATS entry get per-channel stats computed
    from the train split (cached; ref utils.py:218-228 ``make_stats``)."""
    from ..data.datasets import DATASET_STATS

    if cfg.get("norm_stats") or cfg["data_name"] in DATASET_STATS:
        return
    if not hasattr(dataset["train"], "data"):
        return
    from ..data.stats import dataset_stats

    mean, std = dataset_stats(cfg["data_name"], dataset["train"].data, cfg["data_dir"])
    cfg["norm_stats"] = (tuple(float(x) for x in mean), tuple(float(x) for x in std))


class FedExperiment:
    """One federated experiment (one seed): owns the data staging, engine,
    evaluator, logger and checkpoint loop."""

    #: experiment arms (ISSUE 14) need the multiplexed driver loop --
    #: :class:`ArmsExperiment` flips this; the base loop refuses loudly
    _arms_capable = False

    def __init__(self, cfg: Dict[str, Any], seed: int):
        self.cfg = cfg
        self.seed = seed
        self.tag = C.make_model_tag(seed, cfg)
        self.kind = "transformer" if cfg["model_name"] == "transformer" else "vision"
        self.rng = np.random.default_rng(seed)
        self.host_key = jax.random.key(seed)

        dataset = fetch_dataset(cfg["data_name"], cfg["data_dir"], synthetic=cfg["synthetic"],
                                seed=seed, synthetic_sizes=cfg.get("synthetic_sizes"),
                                subset=cfg.get("subset", "label"))
        self.cfg, self.dataset = process_dataset(cfg, dataset)
        cfg = self.cfg
        _maybe_compute_norm_stats(cfg, self.dataset)
        self.model = make_model(cfg)
        validate_width_geometry(self.model, cfg)
        n_data = max(1, cfg["mesh"].get("data", 1))
        n_clients = cfg["mesh"].get("clients", 0) or None
        # arms mesh axis (ISSUE 14): cfg['mesh']['arms'] = E lays each
        # experiment arm on its own device rows (the 'experiments' mesh
        # dimension); 0/absent keeps the (clients, data) mesh and the
        # vmap arms placement
        n_arms_axis = max(1, int(cfg["mesh"].get("arms", 1) or 1))
        try:
            self.mesh = make_mesh(n_clients, n_data, n_arms=n_arms_axis)
        except (ValueError, AssertionError):
            if n_arms_axis > 1:
                # an explicit arms mesh axis must not silently degrade to
                # the vmap placement -- the user asked for one arm per
                # device-row group, and the device count cannot honor it
                raise
            self.mesh = make_mesh(len(jax.devices()), 1)
        self.engine = RoundEngine(self.model, cfg, self.mesh)
        self.evaluator = Evaluator(self.model, cfg, self.mesh, seed=seed)
        self.scheduler = make_scheduler(cfg)
        self.num_active = int(np.ceil(cfg["frac"] * cfg["num_users"]))
        if not 0 <= self.num_active <= cfg["num_users"]:
            # round_users would raise the same on the first draw; failing
            # at construction names the config knob instead of a mid-run
            # sampling error (ISSUE 11 satellite)
            raise ValueError(
                f"frac={cfg['frac']} draws num_active={self.num_active} "
                f"outside [0, num_users={cfg['num_users']}]")
        # population sampler (ISSUE 11, fed/sampling.py): 'prp' = O(active)
        # index-map draw (default), 'perm' = the legacy full-permutation
        # stream.  sample_horizon != None turns on schedule commitment:
        # superstep N+1's cohort draws from superstep N-horizon's FETCHED
        # state, which keeps the streaming prefetch overlap legal for
        # output-dependent samplers (stateless samplers are bit-identical
        # under commitment -- contract-tested).
        self.sampler_spec = resolve_sampler_cfg(cfg)
        self._commitment = (ScheduleCommitment(self.sampler_spec.horizon)
                            if self.sampler_spec.committed else None)
        self._ss_dispatched = 0  # streaming superstep dispatch counter
        self._ss_fetched = 0     # ... and its fetched-state twin
        self._round_times: List[float] = []  # steady-state round durations (ETA)
        self._first_round_done = False
        # staging/dispatch telemetry + async metric fetch (parallel/staging.py):
        # per-round metric sums stay on device and are drained every
        # cfg['metrics_fetch_every'] rounds (eval boundaries flush)
        self.phase_timer = PhaseTimer()
        fetch_every = int(cfg.get("metrics_fetch_every", 1) or 1)
        eval_iv = max(1, int(cfg.get("eval_interval", 1) or 1))
        self.eval_interval = eval_iv
        if cfg.get("strategy", "masked") not in ("masked", "sliced", "grouped"):
            raise ValueError(f"Not valid strategy: {cfg.get('strategy')!r}")
        # streaming client store (ISSUE 6): the population lives as an
        # O(1)-per-user index (parallel/staging.ClientStore) and only each
        # superstep's sampled cohort is materialised + prefetched
        store_mode = cfg.get("client_store", "eager") or "eager"
        if store_mode not in ("eager", "stream"):
            raise ValueError(f"Not valid client_store: {store_mode!r}")
        self.streaming = store_mode == "stream"
        self.stream_prefetch = bool(cfg.get("stream_prefetch", True))
        self.store: Optional[ClientStore] = None
        # prefetched (epoch0, k, StagedCohort) queue, up to
        # cfg['stream_prefetch_depth'] supersteps ahead (ISSUE 8 satellite)
        self._next_cohorts: List[Tuple[int, int, Any]] = []
        self._prefetch_depth = C.resolve_prefetch_depth(cfg)
        self._stream_sync_warned = False
        if self.streaming and cfg.get("strategy") == "sliced":
            raise ValueError(
                "client_store='stream' needs a mesh-native strategy "
                "('masked' or 'grouped'): the cohort pipeline stages "
                "through the engines' superstep programs")
        # wire codec (ISSUE 8): validated loudly here so a typo'd codec
        # never runs a silently-dense experiment; the lossy codecs need the
        # engines' single-global-psum programs
        self.wire_codec, self.error_feedback = resolve_codec_cfg(cfg)
        if isinstance(self.wire_codec, dict) \
                and cfg.get("strategy") != "grouped":
            raise ValueError(
                "a per-level wire_codec map needs strategy='grouped' (its "
                "fused superstep compresses each level's sliced payload "
                "under that level's codec); the other strategies have no "
                "levels to assign codecs to")
        if self.wire_codec != "dense":
            if cfg.get("strategy") == "sliced":
                raise ValueError(
                    f"wire_codec={self.wire_codec!r} needs a mesh-native "
                    f"strategy ('masked' or 'grouped'): the sliced debug "
                    f"twin aggregates on the host, there is no psum to "
                    f"compress")
            if cfg.get("strategy") == "grouped" \
                    and int(cfg.get("superstep_rounds", 1) or 1) <= 1 \
                    and store_mode != "stream":
                raise ValueError(
                    f"wire_codec={self.wire_codec!r} with the grouped "
                    f"strategy needs the fused superstep (superstep_rounds "
                    f"> 1 or client_store='stream'): the K=1 "
                    f"host-orchestrated path reduces per level and has no "
                    f"single global psum to compress")
        # fused multi-round superstep (ISSUE 2) with the sBN+eval phase
        # folded into the scan (ISSUE 4): K rounds per compiled program,
        # eval windows no longer clamp K.  Most knob combinations are now
        # expressible in-jit; the remaining conflicts fail LOUDLY here.
        self.superstep_rounds = max(1, int(cfg.get("superstep_rounds", 1) or 1))
        if self.superstep_rounds > 1:
            K = self.superstep_rounds
            if cfg.get("strategy") == "sliced":
                raise ValueError(
                    "superstep_rounds>1 needs a mesh-native engine "
                    "(strategy 'masked' or 'grouped'); 'sliced' is the "
                    "host-orchestrated debug twin")
            if fetch_every != 1 and fetch_every % K:
                raise ValueError(
                    f"metrics_fetch_every={fetch_every} conflicts with "
                    f"superstep_rounds={K}: a superstep fetches its metrics "
                    f"exactly once per K rounds (use 1 for synchronous fetch "
                    f"or exactly {K}; larger multiples would defer metrics "
                    f"past the superstep's checkpoint)")
            if isinstance(self.scheduler, PlateauScheduler):
                # ISSUE 4 relaxation: Plateau IS expressible now -- the LR is
                # constant within a superstep (staged scalar, not the traced
                # schedule) and steps on the fused eval metrics at superstep
                # boundaries.  That needs every eval to land on the FINAL
                # round of its superstep and the metrics fetched before the
                # next superstep dispatches.
                if eval_iv % K:
                    raise ValueError(
                        f"ReduceLROnPlateau with superstep_rounds={K} needs "
                        f"eval boundaries on superstep boundaries "
                        f"(eval_interval % superstep_rounds == 0, got "
                        f"eval_interval={eval_iv}): a mid-superstep eval "
                        f"would require an LR step inside the compiled scan")
                if fetch_every > K:
                    raise ValueError(
                        f"ReduceLROnPlateau feeds on each superstep's eval "
                        f"metrics before the next superstep dispatches; "
                        f"metrics_fetch_every={fetch_every} would defer them "
                        f"(use 1 or {K})")
            if fetch_every > K:
                # ISSUE 6 satellite: deferring fetch past the superstep
                # boundary makes pivot_fresh (run()) never true -- the
                # best-checkpoint copy silently stops updating.  Every
                # comparable knob conflict fails loudly; so does this one.
                raise ValueError(
                    f"metrics_fetch_every={fetch_every} exceeds "
                    f"superstep_rounds={K}: each superstep's eval metrics "
                    f"would be deferred past its checkpoint, silently "
                    f"disabling best-checkpoint tracking (pivot never "
                    f"fresh); use 1 or {K}")
            if eval_iv % K and K % eval_iv:
                # legal (the mask is data for the driver, structure for the
                # compiler) but worth a loud note: each distinct mask pattern
                # compiles its own K-round program (~40s at flagship scale)
                warnings.warn(
                    f"eval_interval={eval_iv} and superstep_rounds={K} are "
                    f"mutually non-divisible: the eval mask cycles through "
                    f"{math.lcm(eval_iv, K) // K} patterns, each compiling "
                    f"its own superstep program (cached and bounded, but "
                    f"expensive); align one to a multiple of the other to "
                    f"avoid the extra compiles")
            # the superstep pipeline counts PUSHES (one per superstep of K
            # rounds), so fetch_every=m*K defers m whole supersteps
            self.metrics_pipe = MetricsPipeline(max(1, fetch_every // K))
        else:
            self.metrics_pipe = MetricsPipeline(fetch_every)
            if self.streaming and fetch_every > 1:
                # streaming routes superstep_rounds=1 through the (k=1)
                # superstep path, whose pivot needs a synchronous fetch --
                # same silent best-checkpoint disable as fetch > K above
                raise ValueError(
                    f"metrics_fetch_every={fetch_every} with "
                    f"client_store='stream' at superstep_rounds=1 would "
                    f"defer each round's eval metrics past its checkpoint "
                    f"(best-checkpoint pivot never fresh); use 1")
            if self.metrics_pipe.fetch_every > eval_iv:
                # evaluate() drains the pipeline, so batches never grow past
                # the eval interval -- say so instead of silently
                # under-delivering
                warnings.warn(
                    f"metrics_fetch_every={self.metrics_pipe.fetch_every} exceeds "
                    f"eval_interval={eval_iv}: each eval boundary flushes the metric "
                    f"pipeline, so the effective fetch batch is eval_interval rounds")
        # client scheduler (ISSUE 9, heterofl_tpu/sched/): validated loudly
        # here so scenario configs fail at construction, not mid-run.  The
        # lockstep default changes nothing (bit-identical engines).
        self.sched_spec = resolve_schedule_cfg(cfg)
        if not self.sched_spec.lockstep and cfg.get("strategy") == "sliced":
            raise ValueError(
                "schedule scenarios (trace/markov availability, deadline, "
                "buffered aggregation) need a mesh-native strategy "
                "('masked' or 'grouped'): the sliced debug twin replays the "
                "reference host loop")
        if self.sched_spec.buffered:
            if self.wire_codec != "dense":
                raise ValueError(
                    "schedule aggregation='buffered' cannot combine with a "
                    "lossy wire_codec yet: both add a scan carry with its "
                    "own donation/checkpoint contract -- pick one per "
                    "experiment")
            if cfg.get("strategy") == "grouped" \
                    and self.superstep_rounds <= 1 and not self.streaming:
                raise ValueError(
                    "schedule aggregation='buffered' with the grouped "
                    "strategy needs the fused superstep (superstep_rounds "
                    "> 1 or client_store='stream'): the K=1 "
                    "host-orchestrated path combines in its own program "
                    "and has no scan carry to buffer")
        # sampled/rolling eval cohort (ISSUE 9 satellite): O(eval_cohort)
        # Local eval for streaming populations; loud cross-field checks
        self.eval_cohort = C.resolve_eval_cohort(cfg)
        if self.eval_cohort is not None:
            if not self.streaming:
                raise ValueError(
                    "eval_cohort needs client_store='stream': the eager "
                    "store already densifies the population, so its local "
                    "eval is O(num_users) either way")
            if self.kind != "vision":
                raise ValueError(
                    "eval_cohort samples the per-user Local eval, which "
                    "only vision experiments run (LM evaluates Global "
                    "only)")
        # runtime telemetry (ISSUE 10, heterofl_tpu/obs/): in-program health
        # probes + watchdog + run tracing -- validated loudly here so a
        # telemetry config that cannot run fails at construction
        self.obs_spec = resolve_telemetry_cfg(cfg)
        if self.obs_spec.probes:
            if cfg.get("strategy") == "sliced":
                raise ValueError(
                    "telemetry='on' needs a mesh-native strategy ('masked' "
                    "or 'grouped'): the sliced debug twin replays the "
                    "reference host loop and has no in-program round core "
                    "to probe")
            if cfg.get("strategy") == "grouped" \
                    and self.superstep_rounds <= 1 and not self.streaming:
                raise ValueError(
                    "telemetry='on' with the grouped strategy needs the "
                    "fused superstep (superstep_rounds > 1 or client_store="
                    "'stream'): the K=1 path splits the round across L+1 "
                    "host-orchestrated programs with no shared round core "
                    "to probe")
        self.watchdog = Watchdog(self.obs_spec.watchdog) \
            if (self.obs_spec.probes and self.obs_spec.watchdog is not None) \
            else None
        self.tracer = None  # obs.trace.TraceRecorder, built in run()
        # client-update quarantine (ISSUE 15): validated loudly here so a
        # quarantine config that cannot run fails at construction.  The
        # gate lives in the engines' round cores -- the sliced debug twin
        # replays the reference host loop and has no core to gate in.
        self.quarantine = resolve_quarantine_cfg(cfg)
        if self.quarantine.enabled and cfg.get("strategy") == "sliced":
            raise ValueError(
                "quarantine needs a mesh-native strategy ('masked' or "
                "'grouped'): the sliced debug twin replays the reference "
                "host loop and has no in-program round core to gate")
        if resolve_poison_cfg(cfg) is not None \
                and cfg.get("strategy") == "sliced":
            raise ValueError(
                "chaos_poison needs a mesh-native strategy ('masked' or "
                "'grouped'): the sliced debug twin has no in-program "
                "update to poison")
        # durable generational checkpoints (ISSUE 15): rotation depth
        self.checkpoint_keep = C.resolve_checkpoint_keep(cfg)
        # rollback budget bookkeeping (watchdog action='rollback'):
        # attempts since the last CLEAN checkpoint write -- a completed
        # superstep + checkpoint proves recovery, resetting the budget
        self._rollback_attempts = 0
        # chaos fault injector (heterofl_tpu/chaos/): attached by the
        # drill harness; None (always, outside drills) = zero-cost checks
        self.chaos = None
        # population-observatory ledger (ISSUE 12, obs/ledger.py): a
        # host-side per-client record updated O(active) at each metrics
        # fetch -- never a program change, so it composes with every
        # telemetry mode.  Cross-field conflicts fail loudly here.
        self.ledger_spec = resolve_ledger_cfg(cfg)
        self.ledger = None
        if self.ledger_spec.enabled:
            if cfg.get("strategy") == "sliced":
                raise ValueError(
                    "ledger='on' needs a mesh-native strategy ('masked' or "
                    "'grouped'): the sliced debug twin replays the "
                    "reference host loop, whose metrics never ride the "
                    "fetch path the ledger folds from")
            if cfg.get("data_placement") == "sharded":
                raise ValueError(
                    "ledger='on' needs replicated (or streaming) data "
                    "placement: the sharded slot packing re-orders metric "
                    "rows by owning device, dropping the schedule-order "
                    "uid alignment the O(active) fold consumes")
            self.ledger = ClientLedger(
                cfg["num_users"],
                sorted({float(r) for r in cfg["model_rate"]}, reverse=True))
        # experiment arms (ISSUE 14, heterofl_tpu/multi/): the base driver
        # runs ONE trajectory -- a multiplexed cfg must go through the
        # ArmsExperiment loop (per-arm checkpoints/logs/Plateau state),
        # which python -m heterofl_tpu.multi.sweep drives
        self.arms_spec = resolve_arms_cfg(cfg)
        if self.arms_spec is not None and not self._arms_capable:
            raise ValueError(
                "cfg['arms'] needs the multiplexed driver loop: run the "
                "sweep front-end (python -m heterofl_tpu.multi.sweep) or "
                "construct entry.common.ArmsExperiment directly -- the "
                "single-trajectory FedExperiment loop cannot thread "
                "per-arm checkpoints/logs")
        if self.arms_spec is not None:
            if cfg.get("strategy") == "sliced":
                raise ValueError(
                    "arms need a mesh-native strategy ('masked' or "
                    "'grouped'): the sliced debug twin replays the "
                    "reference host loop one trajectory at a time")
            if self.ledger_spec.enabled:
                raise ValueError(
                    "ledger='on' cannot combine with arms yet: the "
                    "O(active) fold consumes ONE sampling stream's cohort "
                    "rows, and each arm draws its own (a ROADMAP "
                    "follow-on)")
            if self.obs_spec.trace_dir:
                raise ValueError(
                    "trace_dir cannot combine with arms yet: the "
                    "multiplexed loop does not build the TraceRecorder, "
                    "so the trace would be silently empty (a ROADMAP "
                    "follow-on; per-arm probes/watchdog DO run)")
            # arms-mesh multi-process runs are supported since ISSUE 17:
            # staging commits through commit_global (GSPMD NamedSharding
            # assembly) and the checkpoint path writes per-process shard
            # files for non-addressable leaves (save_checkpoint_sharded)
        self._eval_widx = None  # rolling Local-eval window currently staged
        self._fused = None  # FusedEval, built on first eval-bearing superstep
        self.alt_engine = None
        if cfg.get("strategy") == "sliced":
            from ..fed.sliced import SlicedFederation

            self.alt_engine = SlicedFederation(cfg)
        elif cfg.get("strategy") == "grouped":
            from ..parallel.grouped import GroupedRoundEngine

            self.alt_engine = GroupedRoundEngine(cfg, self.mesh)

    # -- staging -------------------------------------------------------

    def make_splits(self):
        return split_dataset(self.dataset, self.cfg["num_users"], self.cfg["data_split_mode"],
                             self.rng, classes_size=self.cfg["classes_size"])

    def _place(self, data):
        """Train stacks onto devices per ``cfg['data_placement']``."""
        if self.cfg.get("data_placement") == "sharded" and self.alt_engine is None:
            from ..parallel import shard_client_data

            return shard_client_data(self.mesh, data)
        return tuple(jnp.asarray(a) for a in data)

    def stage(self, data_split, label_split):
        cfg = self.cfg
        U = cfg["num_users"]
        if self.streaming:
            # ISSUE 6: no [U, ...] densification -- the population is an
            # O(1)-per-user index over the raw arrays, and train cohorts
            # materialise per superstep (stage_cohort + prefetch).  Eval
            # operands stage LAZILY on the first eval: local (per-user)
            # eval is the one remaining O(U) surface, so runs that never
            # evaluate (population benches) never pay it.
            tr = self.dataset["train"]
            if self.kind == "vision":
                self.store = ClientStore.from_split(
                    tr.data, tr.target, data_split["train"], label_split,
                    cfg["classes_size"])
            else:
                self.store = ClientStore.from_split(
                    tr.token, None, data_split["train"], label_split,
                    cfg["num_tokens"], kind="lm")
            self.train_data = None
            self._eval_split = (data_split["test"], label_split)
            self._eval_staged = False
            return
        if self.kind == "vision":
            tr = self.dataset["train"]
            x, y, m = stack_client_shards(tr.data, tr.target, data_split["train"], list(range(U)))
            lm = label_split_masks(label_split, U, cfg["classes_size"])
            self.train_data = self._place((x, y, m, lm))
            # sBN recalibration batches over the whole train set, per-user
            # local eval shards, batched global test set -- the shared
            # assembly (audit/bench stage the same layout)
            self.sbn_batches, self.local_eval, self.global_eval = \
                stage_eval_operands(cfg, tr, self.dataset["test"],
                                    data_split["test"], lm)
        else:
            tr = self.dataset["train"]
            rows = stack_client_token_rows(tr.token, data_split["train"], list(range(U)))
            lm = label_split_masks(label_split, U, cfg["num_tokens"])
            self.train_data = self._place((rows, lm))
            te = self.dataset["test"]
            xs, ws = stack_windows(bptt_windows(te.token, cfg["bptt"]), cfg["bptt"])
            self.global_eval = (xs, ws)

    def _ensure_eval_staged(self):
        """Streaming mode's lazy eval staging (see :meth:`stage`)."""
        if not self.streaming or self._eval_staged:
            return
        cfg = self.cfg
        U = cfg["num_users"]
        test_split, label_split = self._eval_split
        if self.kind == "vision":
            if self.eval_cohort is not None:
                # sampled/rolling eval cohort (ISSUE 9 satellite): Local
                # eval stages O(eval_cohort) per window instead of O(U) --
                # the one population-scaling surface the streaming store
                # left (and the reason the O(U) warning below is retired
                # on this path).  sBN and Global keep their full sets.
                self.sbn_batches = _batch_array(self.dataset["train"].data,
                                                cfg["batch_size"]["train"])
                b = cfg["batch_size"]["test"]
                te = self.dataset["test"]
                xg, wg = _batch_array(te.data, b)
                yg, _ = _batch_array(te.target, b)
                self.global_eval = (xg, yg, wg)
                self.local_eval = None  # staged per rolling window
                self._eval_staged = True
                return
            if U > 100_000:
                warnings.warn(
                    f"local eval stages every user's test shard (O(U) at "
                    f"num_users={U}); set eval_cohort for a rolling "
                    f"O(cohort) Local eval, cap eval_interval past "
                    f"num_epochs, or stick to population benches if this "
                    f"OOMs")
            lm = label_split_masks(label_split, U, cfg["classes_size"])
            self.sbn_batches, self.local_eval, self.global_eval = \
                stage_eval_operands(cfg, self.dataset["train"],
                                    self.dataset["test"], test_split, lm)
        else:
            te = self.dataset["test"]
            xs, ws = stack_windows(bptt_windows(te.token, cfg["bptt"]), cfg["bptt"])
            self.global_eval = (xs, ws)
        self._eval_staged = True

    # -- one round -----------------------------------------------------

    def sample_users(self, epoch: int) -> np.ndarray:
        """The K=1 host draw.  Uniform under ``sampler='perm'`` keeps the
        drivers' legacy numpy permutation stream (reference parity,
        bit-identical trajectories); everything else -- the 'prp' sampler
        and every availability schedule -- draws through THE shared
        sampling stream (:func:`~..fed.core.round_users` at the round key)
        so the K=1 and superstep paths replay the same trace: unavailable
        slots come back -1 and flow through the engines as padding."""
        if self.sched_spec.kind == "uniform" \
                and self.sampler_spec.kind == "perm":
            return self.rng.permutation(self.cfg["num_users"])[: self.num_active].astype(np.int32)
        key = jax.random.fold_in(self.host_key, epoch)
        with self.phase_timer.phase("sample"):
            return np.asarray(round_users(key, self.cfg["num_users"],
                                          self.num_active,
                                          avail=self.sched_spec.avail_row(epoch),
                                          sampler=self.sampler_spec.kind))

    def _chaos(self, point: str) -> None:
        """Chaos kill check (ISSUE 15, heterofl_tpu/chaos/): raises
        ChaosKill when an attached drill plan schedules a death at this
        boundary; no-op (one attribute test) outside drills."""
        if self.chaos is not None:
            self.chaos.check(point)

    def train_round(self, params, epoch: int, lr: float, logger: Logger):
        self._chaos("superstep")  # the K=1 dispatch boundary
        user_idx = self.sample_users(epoch)
        key = jax.random.fold_in(self.host_key, epoch)
        t0 = time.time()
        phases0 = self.phase_timer.snapshot()
        # first steady-state round actually executed (works under resume too)
        profiling = (self.cfg.get("profile_dir") and self._first_round_done
                     and not getattr(self, "_profiled", False))
        if profiling:
            self._profiled = True
            jax.profiler.start_trace(self.cfg["profile_dir"])
        if self.alt_engine is not None:
            rates = np.asarray(round_rates(key, self.cfg, jnp.asarray(user_idx)))
            if self.cfg.get("strategy") == "grouped":
                # mesh-native: params stay on device end to end; the metric
                # sums stay there too until the pipeline drains them
                params, pending = self.alt_engine.train_round(
                    params, user_idx, rates, self.train_data, lr, key,
                    timer=self.phase_timer, async_metrics=True)
            else:
                new_np, ms = self.alt_engine.train_round(
                    {k: np.asarray(v) for k, v in params.items()}, user_idx, rates,
                    self.train_data, lr, key)
                params = {k: jnp.asarray(v) for k, v in new_np.items()}
                pending = PendingMetrics(ms)
        else:
            params, ms = self.engine.train_round(params, key, lr, user_idx,
                                                 self.train_data,
                                                 timer=self.phase_timer,
                                                 epoch=epoch)
            pending = PendingMetrics(ms)
        if profiling:
            jax.block_until_ready(params)
            jax.profiler.stop_trace()
        # uids ride the tag (ISSUE 12): the K=1 ledger fold needs the drawn
        # cohort, and the legacy perm+uniform numpy stream is stateful --
        # it cannot be re-drawn at fetch time like the superstep streams
        tag = {"epoch": epoch, "lr": lr, "dt": 0.0, "phases": {},
               "uids": user_idx}
        self._chaos("fetch")
        with self.phase_timer.phase("fetch"):
            due = self.metrics_pipe.push(tag, pending)
        # dt and the phase breakdown are filled in AFTER the push (the tag is
        # the same dict object the pipeline holds, so deferred entries carry
        # their own round's values): at the parity default
        # (metrics_fetch_every=1) the push fetches synchronously, so dt spans
        # dispatch + device compute exactly like the pre-staging driver and
        # the round's own fetch shows up in ITS phases line; with K>1 the
        # non-fetching rounds record their (tiny) dispatch wall and the
        # batch-fetching round absorbs the whole batch's compute + drain, so
        # the ETA mean over rounds stays the true cadence.  First processed
        # round (compile) is excluded, parity with the reference's telemetry
        # (train_classifier_fed.py:105-119).
        tag["dt"] = dt = time.time() - t0
        tag["phases"] = self.phase_timer.delta(phases0)
        if self._first_round_done:
            self._round_times.append(dt)
        else:
            self._first_round_done = True  # exclude the compile round
        for tag0, ms_host in due:
            self._log_train_round(logger, tag0["epoch"], tag0["lr"], tag0["dt"],
                                  tag0["phases"], ms_host,
                                  uids=tag0.get("uids"))
        return params

    def _superstep_schedule(self, epoch0: int, k: int) -> np.ndarray:
        """Host-side [k, A] active-user draw from the superstep sampling
        stream (fed.core.superstep_user_schedule): what the masked engine
        samples in-jit, evaluated on the host where slot packing needs the
        ids (sharded placement, grouped level grouping, cohort staging).
        The availability schedule (ISSUE 9) threads through the shared
        stream, so host- and in-jit-sampled paths replay the same trace;
        the sampler kind (ISSUE 11) threads the same way -- host schedules
        and the in-jit draw must name the same sampler.  The draw is its
        own ``sample`` phase (PhaseTimer) so the O(U) -> O(active) win is
        visible per round instead of hiding inside ``stage``."""
        with self.phase_timer.phase("sample"):
            return superstep_user_schedule(self.host_key, epoch0, k,
                                           self.cfg["num_users"],
                                           self.num_active,
                                           schedule=self.sched_spec,
                                           sampler=self.sampler_spec.kind)

    # -- streaming cohort pipeline (ISSUE 6) ---------------------------

    def _stage_cohort(self, epoch0: int, k: int):
        """Materialise + commit the cohort for rounds ``epoch0..epoch0+k-1``
        through the engine's store-backed staging."""
        users = self._superstep_schedule(epoch0, k)
        if self.cfg.get("strategy") == "grouped":
            rates = superstep_rate_schedule(self.host_key, epoch0, k,
                                            self.cfg, users)
            return self.alt_engine.stage_cohort(self.store, users, rates,
                                                timer=self.phase_timer)
        return self.engine.stage_cohort(self.store, users,
                                        timer=self.phase_timer)

    def _take_cohort(self, epoch0: int, k: int):
        """The prefetched cohort for this superstep, or a synchronous stage
        (first superstep of a run; ``stream_prefetch`` off -- warned once:
        a sampler that depends on round-N outputs cannot prefetch, and the
        staging then serialises with compute)."""
        if self._next_cohorts and self._next_cohorts[0][:2] == (epoch0, k):
            return self._next_cohorts.pop(0)[2]
        self._next_cohorts = []  # a schedule jump invalidates the queue
        if self._commitment is not None \
                and not self._commitment.may_draw(self._ss_dispatched + 1):
            # every legal knob combination fetches (and commits) at least
            # once per superstep push, so the state THIS dispatch's draw
            # consumes is always on the host by now; reaching here means a
            # metrics fetch was deferred past the commitment horizon, and
            # drawing anyway would consume uncommitted state silently --
            # the exact hole sample_horizon exists to close.  Fail loudly.
            raise RuntimeError(
                f"schedule commitment: the superstep at epoch {epoch0} "
                f"draws from superstep "
                f"{self._ss_dispatched - self.sampler_spec.horizon}'s "
                f"state but only {self._ss_fetched} superstep(s) have "
                f"fetched -- a deferred metrics fetch crossed "
                f"sample_horizon={self.sampler_spec.horizon}")
        if self._commitment is not None and self.sampler_spec.horizon == 0 \
                and self._ss_dispatched > 0 and self.stream_prefetch \
                and not self._stream_sync_warned:
            self._stream_sync_warned = True
            warnings.warn(
                "sample_horizon=0 (strictly output-dependent sampler) is "
                "staging SYNCHRONOUSLY: each cohort draws from the "
                "previous superstep's just-fetched state, so staging "
                "cannot overlap compute -- sample_horizon=1 commits one "
                "state further back and keeps the overlap")
        if not self.stream_prefetch and not self._stream_sync_warned:
            self._stream_sync_warned = True
            warnings.warn(
                "client_store='stream' is staging SYNCHRONOUSLY "
                "(stream_prefetch=False): cohort materialisation serialises "
                "with the round compute instead of overlapping it -- an "
                "output-dependent sampler can keep the overlap by "
                "committing its schedule instead (cfg['sample_horizon'], "
                "ISSUE 11)")
        return self._stage_cohort(epoch0, k)

    def _prefetch_cohort(self, epoch0: int):
        """Stage UPCOMING supersteps' cohorts right after this superstep
        dispatched: the device_put pipeline overlaps with the in-flight
        scanned program.  ``stream_prefetch_depth`` (ISSUE 8 satellite)
        bounds how many supersteps ahead the queue runs; the stager's ring
        holds depth+1 slots and fences each slot on its previous private
        copy, so staging ahead can never corrupt an in-flight superstep."""
        if not self.stream_prefetch:
            return
        self._chaos("prefetch")
        n_rounds = self.cfg["num_epochs"]["global"]
        e = (self._next_cohorts[-1][0] + self._next_cohorts[-1][1]
             if self._next_cohorts else epoch0)
        while len(self._next_cohorts) < self._prefetch_depth \
                and e <= n_rounds:
            if self._commitment is not None and not self._commitment.may_draw(
                    self._ss_dispatched + len(self._next_cohorts) + 1):
                # schedule commitment (ISSUE 11): this superstep's cohort
                # would consume state not yet fetched -- stop here; the
                # queue refills after the next fetch commits it.  At the
                # sync default (fetch_every=1) horizon 1 always admits the
                # next superstep, so the PR 6 overlap survives.
                break
            k = min(self.superstep_rounds, n_rounds - e + 1)
            self._next_cohorts.append((e, k, self._stage_cohort(e, k)))
            e += k

    def _codec_engine(self):
        """The engine holding the wire-codec error-feedback carry and the
        buffered-async staleness buffer (the one that dispatches the
        carry-bearing programs)."""
        return self.alt_engine if self.cfg.get("strategy") == "grouped" \
            else self.engine

    def _eval_cohort_users(self, widx: int) -> list:
        """The rolling Local-eval window: ``eval_cohort`` consecutive users
        starting at ``widx * eval_cohort`` (mod the population) -- each eval
        window advances the cohort, so repeated evals sweep the population.
        Deterministic in ``widx`` (itself derived from the eval epoch), so
        checkpoint resume stages the identical window."""
        n, u = self.eval_cohort, self.cfg["num_users"]
        return [int(x) for x in (widx * n + np.arange(n)) % u]

    def _local_cohort_operands(self, widx: int):
        """Stage the rolling window's Local-eval operands (O(cohort) host
        gather + device commit; same batched layout as the population
        path's ``stage_local_eval``).  Shards pad to the POPULATION-wide
        max test-shard size so every window shares one operand shape -- the
        cached superstep program then takes each window as plain arguments
        instead of recompiling per window."""
        users = self._eval_cohort_users(widx)
        test_split, label_split = self._eval_split
        if not hasattr(self, "_eval_shard_max"):
            self._eval_shard_max = max(
                len(test_split[u]) for u in range(self.cfg["num_users"]))
        te = self.dataset["test"]
        xu, yu, mu = stack_client_shards(te.data, te.target, test_split,
                                         users)
        n = self._eval_shard_max
        if xu.shape[1] < n:
            pad = n - xu.shape[1]
            xu = np.concatenate(
                [xu, np.zeros((len(users), pad) + xu.shape[2:], xu.dtype)], 1)
            yu = np.concatenate(
                [yu, np.zeros((len(users), pad), yu.dtype)], 1)
            mu = np.concatenate(
                [mu, np.zeros((len(users), pad), np.float32)], 1)
        lm = label_split_masks({i: label_split[u] for i, u in enumerate(users)},
                               len(users), self.cfg["classes_size"])
        b = min(self.cfg["batch_size"]["test"], n)
        return stage_local_eval(xu, yu, mu, b) + (lm,)

    def _fused_eval(self, widx: Optional[int] = None):
        """The experiment's :class:`~..parallel.evaluation.FusedEval`: eval
        operands committed once (shared with the host-path memos), built
        lazily on the first eval-bearing superstep.

        ``widx`` (rolling eval cohort, ISSUE 9 satellite): the Local-eval
        window to stage.  A window change re-stages ONLY the cohort's local
        operands and rebuilds the FusedEval wrapper around them -- the sBN/
        Global commits are identity memo hits and the engines' cached
        superstep programs take the new operands as plain arguments (same
        avals, no recompile)."""
        if self.eval_cohort is not None and widx != self._eval_widx:
            self._ensure_eval_staged()
            local = self._local_cohort_operands(widx)
            self._fused = self.evaluator.fused(
                sbn_batches=self.sbn_batches, local_eval=local,
                global_eval=self.global_eval)
            self._eval_widx = widx
        if self._fused is None:
            self._ensure_eval_staged()
            if self.kind == "vision":
                self._fused = self.evaluator.fused(
                    sbn_batches=self.sbn_batches, local_eval=self.local_eval,
                    global_eval=self.global_eval)
            else:
                self._fused = self.evaluator.fused(global_eval=self.global_eval)
        return self._fused

    def train_superstep(self, params, epoch0: int, k: int, logger: Logger):
        """Run rounds ``epoch0 .. epoch0+k-1`` as ONE compiled program
        (``superstep_rounds``): the round boundary leaves the host -- one
        stage+dispatch cycle and one metric fetch serve all k rounds, and the
        per-round phase breakdown is the amortized cost (PhaseTimer).

        Rounds where the eval cadence fires (``epoch % eval_interval == 0``
        or the final round) run the fused sBN+eval phase INSIDE the program
        (ISSUE 4): the static eval mask keys the compiled superstep, the
        eval results come back in the same per-superstep fetch, and the last
        per-eval-window host round-trip is gone -- ``eval_interval`` no
        longer clamps K."""
        self._chaos("superstep")
        cfg = self.cfg
        n_rounds = cfg["num_epochs"]["global"]
        mask = tuple((epoch0 + r) % self.eval_interval == 0
                     or (epoch0 + r) == n_rounds for r in range(k))
        widx = None
        if any(mask) and self.eval_cohort is not None:
            # rolling Local-eval window (ISSUE 9 satellite): derived from
            # this superstep's FIRST eval epoch, so the sweep is
            # deterministic in the cadence and stable across resume
            first_eval = min(epoch0 + r for r in range(k) if mask[r])
            widx = first_eval // self.eval_interval
        fused = self._fused_eval(widx) if any(mask) else None
        plateau = isinstance(self.scheduler, PlateauScheduler)
        # Plateau holds the LR constant between metric steps, and steps only
        # at superstep boundaries (validated in __init__): the superstep
        # takes it as a staged scalar instead of the traced schedule
        lr_const = self.scheduler(epoch0) if plateau else None
        t0 = time.time()
        phases0 = self.phase_timer.snapshot()
        if self.streaming:
            # the cohort was (normally) prefetched while the PREVIOUS
            # superstep computed; dispatch it, then immediately stage the
            # next one so its device_put pipeline overlaps with this
            # superstep's in-flight scan
            cohort = self._take_cohort(epoch0, k)
            eng = self.alt_engine if cfg.get("strategy") == "grouped" \
                else self.engine
            params, pending = eng.train_superstep(
                params, self.host_key, epoch0, k, timer=self.phase_timer,
                eval_mask=mask if fused else None, fused_eval=fused,
                lr=lr_const, cohort=cohort)
            self._ss_dispatched += 1
            with self._trace_span("prefetch", {"epoch0": int(epoch0 + k)}):
                self._prefetch_cohort(epoch0 + k)
        elif cfg.get("strategy") == "grouped":
            users = self._superstep_schedule(epoch0, k)
            rates = superstep_rate_schedule(self.host_key, epoch0, k, cfg,
                                            users)
            params, pending = self.alt_engine.train_superstep(
                params, self.host_key, epoch0, k, users, rates,
                self.train_data, timer=self.phase_timer,
                eval_mask=mask if fused else None, fused_eval=fused,
                lr=lr_const)
        else:
            sched = None
            if cfg.get("data_placement") == "sharded":
                sched = self._superstep_schedule(epoch0, k)
            params, pending = self.engine.train_superstep(
                params, self.host_key, epoch0, k, self.train_data,
                user_schedule=sched, num_active=self.num_active,
                timer=self.phase_timer, eval_mask=mask if fused else None,
                fused_eval=fused, lr=lr_const)
        tag = {"kind": "superstep", "epoch0": epoch0, "k": k, "dt": 0.0,
               "phases": {},
               "lrs": [self.scheduler(epoch0 + r) for r in range(k)]}
        self._chaos("fetch")
        with self.phase_timer.phase("fetch"):
            due = self.metrics_pipe.push(tag, pending)
        # dt/phases fill in AFTER the push (the tag object rides the
        # pipeline, so deferred entries carry their own superstep's values);
        # at the sync default every superstep drains immediately
        dt = time.time() - t0
        tag["dt"] = dt
        tag["phases"] = self.phase_timer.amortized(phases0, k)
        if self._first_round_done:
            self._round_times.extend([dt / k] * k)
        else:
            self._first_round_done = True  # exclude the compile superstep
        for tag0, out in due:
            self._log_superstep(logger, tag0, out)
        return params

    def _trace_span(self, name: str, args: Optional[Dict[str, Any]] = None):
        """A run-trace span (ISSUE 10) -- nullcontext when tracing is off,
        so the driver's event sites cost nothing un-traced."""
        if self.tracer is not None:
            return self.tracer.span(name, cat="driver", args=args)
        return nullcontext()

    def _observe(self, logger: Logger, epoch: int, probes: Dict[str, Any],
                 ms) -> None:
        """Surface one fetched round's health probes (ISSUE 10): a
        structured obs event on the run's JSONL, a trace instant, and the
        watchdog check (loud warning or configurable abort).  This runs at
        the FETCH boundary -- the first host code that sees the round."""
        loss = None
        n = float(np.sum(ms["n"]))
        if n > 0:
            loss = float(np.sum(ms["loss_sum"])) / n
        logger.emit({"event": "probes", "epoch": int(epoch), "loss": loss,
                     **probes})
        if self.tracer is not None:
            self.tracer.instant("probes", cat="obs",
                                args={"epoch": int(epoch), "loss": loss,
                                      **probes})
        if self.watchdog is not None:
            def emit_trip(ev):
                # a watchdog trip is abort evidence: it lands on BOTH the
                # run log and the trace timeline (ISSUE 12 satellite) --
                # the last event of an aborted run is the watchdog instant
                logger.emit(ev)
                if self.tracer is not None:
                    self.tracer.instant("watchdog", cat="obs", args=ev)

            try:
                self.watchdog.check(epoch, probes=probes, loss=loss,
                                    emit=emit_trip)
            except WatchdogRollback:
                # rollback durability (ISSUE 15 satellite): the SAME
                # artifacts as the abort path, per recovery attempt -- the
                # trip instant is the last event on disk before the
                # rollback unwinds -- but via sync(), not close(): the run
                # continues tracing through the recovery
                if self.tracer is not None:
                    self.tracer.sync()
                logger.flush()
                if self.ledger is not None and jax.process_index() == 0:
                    self.ledger.save(self._ledger_path())
                raise
            except WatchdogError:
                # durability (ISSUE 12 satellite): the evidence must be ON
                # DISK before the abort unwinds -- close() fsyncs
                # events.jsonl and writes + fsyncs the Chrome trace, so a
                # crash right after loses nothing (the outer finally's
                # close is then an idempotent no-op)
                if self.tracer is not None:
                    self.tracer.close()
                logger.flush()
                if self.ledger is not None and jax.process_index() == 0:
                    # process 0 only, like the normal exit path: concurrent
                    # saves through the shared tmp name would corrupt the
                    # very snapshot the abort is trying to preserve
                    self.ledger.save(self._ledger_path())
                raise

    def _fold_ledger(self, logger: Logger, epoch0: int, k: int, rounds,
                     uid_rows: Optional[np.ndarray] = None) -> None:
        """Fold one fetch's rounds into the :class:`ClientLedger` (ISSUE
        12) and emit the ``{"tag": "ledger"}`` summary -- O(active) per
        fetch.  ``uid_rows=None`` re-draws the cohort ids from THE one
        sampling stream (:func:`~..fed.core.superstep_user_schedule`, the
        host twin of the in-jit draw -- bit-identical by contract), which
        is exactly the ``ScheduleCommitment.state_for`` alignment: fetch
        order is dispatch order, so round ``epoch0 + r``'s metric row r
        IS that draw's cohort in schedule order."""
        if uid_rows is None:
            uid_rows = superstep_user_schedule(
                self.host_key, epoch0, k, self.cfg["num_users"],
                self.num_active, schedule=self.sched_spec,
                sampler=self.sampler_spec.kind)
        tot_active = tot_new = 0
        last = None
        for r in range(k):
            u = uid_rows[r]
            a = len(u)
            ms = rounds[r]
            last = self.ledger.update(epoch0 + r, u,
                                      np.asarray(ms["rate"])[:a],
                                      np.asarray(ms["loss_sum"])[:a],
                                      np.asarray(ms["n"])[:a])
            tot_active += last["active"]
            tot_new += last["new_users"]
        rec = {"event": "ledger", "epoch0": int(epoch0), "k": int(k),
               "active": tot_active, "new_users": tot_new,
               "coverage": last["coverage"],
               "loss_ema_mean": last["loss_ema_mean"],
               "bytes": self.ledger.nbytes}
        logger.emit(rec, tag="ledger")
        if self.tracer is not None:
            self.tracer.instant("ledger", cat="obs", args=rec)

    def _ledger_path(self) -> str:
        """Where this run's ``ledger.npz`` snapshot lands: next to the
        trace artifacts when tracing (the report surface reads them
        together), else under the run's output dir."""
        base = os.path.join(self.obs_spec.trace_dir, self.tag) \
            if self.obs_spec.trace_dir \
            else os.path.join(self.cfg["output_dir"], "obs", self.tag)
        return os.path.join(base, "ledger.npz")

    def _log_superstep(self, logger: Logger, tag: Dict[str, Any], out):
        """Log one (possibly deferred) superstep's rounds: train metrics per
        round, with each fused eval's Local/Global metrics logged right
        after the round it evaluated -- the K=1 host-loop ordering."""
        if self._commitment is not None:
            # schedule commitment (ISSUE 11): this superstep's state is on
            # the host NOW -- cohorts that draw from it become stageable.
            # Fetch order == dispatch order (the metrics pipeline is FIFO),
            # so the counter pair stays consistent.
            self._ss_fetched += 1
            self._commitment.commit(self._ss_fetched, state=out)
        rounds = out["train"] if isinstance(out, dict) else out
        evals = {e["epoch"]: e for e in (out.get("eval") or [])} \
            if isinstance(out, dict) else {}
        probes = out.get("obs") if isinstance(out, dict) else None
        if self.ledger is not None:
            self._fold_ledger(logger, tag["epoch0"], tag["k"], rounds)
        per_round = tag["dt"] / tag["k"]
        for r in range(tag["k"]):
            epoch = tag["epoch0"] + r
            self._log_train_round(logger, epoch, tag["lrs"][r], per_round,
                                  tag["phases"], rounds[r],
                                  probes=probes[r] if probes else None)
            ev = evals.get(epoch)
            if ev is not None:
                self._log_fused_eval(logger, epoch, ev)
                if isinstance(self.scheduler, PlateauScheduler):
                    # same feed as the K=1 path: min-mode plateau on the
                    # test Global loss of rounds that evaluated
                    self.scheduler.step_metric(
                        logger.mean.get("test/Global-Loss", 0.0))

    def _log_fused_eval(self, logger: Logger, epoch: int, ev: Dict[str, Any]):
        """Mirror :meth:`evaluate`'s logging for one fused eval result."""
        cfg = self.cfg
        # each fused eval's test means stand alone (ISSUE 6 satellite): the
        # K=1 host loop resets the logger every round, so without this a
        # superstep's later evals BLEND with its earlier ones and the
        # best-checkpoint pivot / Plateau feed compare a blended mean
        # instead of the boundary round's own eval
        logger.reset_tag("test")
        if self.kind == "vision" and ev["local"]:
            local = ev["local"]
            named_local = summarize_sums(local, cfg["model_name"])
            logger.append(named_local, "test", n=float(np.sum(local["n"])))
        named_global = summarize_sums({k: np.asarray(v) for k, v in ev["global"].items()},
                                      cfg["model_name"], prefix="Global-")
        logger.append(named_global, "test", n=ev["global"]["n"])
        info = {"info": [f"Model: {self.tag}", f"Test Epoch: {epoch}"]}
        logger.append(info, "test", mean=False)
        test_names = [n.split("/", 1)[1] for n in logger.mean if n.startswith("test/")]
        logger.write("test", test_names)
        self.bn_state = ev["bn"]
        return named_global

    def _log_train_round(self, logger: Logger, epoch: int, lr: float, dt: float,
                         phases: Dict[str, float], ms: Dict[str, np.ndarray],
                         probes: Optional[Dict[str, Any]] = None,
                         uids: Optional[np.ndarray] = None):
        """Log one (possibly deferred) round's train metrics + info lines.

        ``probes``: this round's assembled health-probe record (superstep
        fetches carry it pre-split); the K=1 ``train_round`` path still has
        the raw ``obs_*`` leaves riding the metrics dict and splits them
        here, at the fetch boundary.  ``uids``: the K=1 path's drawn cohort
        (rides the tag) -- its ledger fold happens here, at the same fetch
        boundary the superstep path folds at."""
        if probes is None and (self.obs_spec.probes
                               or self.quarantine.enabled):
            # the quarantine counter rides as an obs_ probe even with
            # telemetry off (ISSUE 15) -- split either way
            ms, plist = split_probes(ms, self.mesh.shape["clients"])
            if plist:
                probes = plist[0]
        if uids is not None and self.ledger is not None:
            self._fold_ledger(logger, epoch, 1, [ms],
                              uid_rows=np.asarray(uids)[None])
        named = summarize_sums(ms, self.cfg["model_name"])
        logger.append(named, "train", n=float(ms["n"].sum()))
        mean_dt = float(np.mean(self._round_times)) if self._round_times else dt
        remain = self.cfg["num_epochs"]["global"] - epoch
        eta = datetime.timedelta(seconds=round(mean_dt * remain))
        breakdown = " ".join(f"{k} {v:.3f}s" for k, v in sorted(phases.items()))
        info = {"info": [f"Model: {self.tag}",
                         f"Train Epoch: {epoch}",
                         f"Learning rate: {lr:g}",
                         f"Rates: {sorted(set(ms['rate'][ms['n'] > 0].tolist()))}",
                         f"Round time: {dt:.2f}s",
                         f"Round phases: {breakdown}" if breakdown else "Round phases: n/a",
                         f"Experiment Finished Time: {eta}"]}
        logger.append(info, "train", mean=False)
        logger.write("train", list(named))
        if probes is not None:
            self._observe(logger, epoch, probes, ms)

    def _drain_metrics(self, logger: Logger):
        """Flush the async metric pipeline (checkpoint/eval boundaries)."""
        with self.phase_timer.phase("fetch"):
            due = self.metrics_pipe.flush()
        for tag, ms_host in due:
            if tag.get("kind") == "superstep":
                self._log_superstep(logger, tag, ms_host)
            else:
                self._log_train_round(logger, tag["epoch"], tag["lr"], tag["dt"],
                                      tag["phases"], ms_host,
                                      uids=tag.get("uids"))

    def evaluate(self, params, epoch: int, logger: Logger, label_split) -> Dict[str, float]:
        """Host-loop sBN + Local/Global eval -- the ``superstep_rounds=1``
        reference path (supersteps run the same phases in-program via
        :meth:`_fused_eval`; the staticcheck lint keeps host eval dispatch
        out of the steady-state superstep stride)."""
        self._drain_metrics(logger)  # eval boundary: fetch any deferred rounds
        self._ensure_eval_staged()
        cfg = self.cfg
        bn = {}
        if self.kind == "vision":
            # staticcheck: allow(no-host-eval-in-driver): the K=1 host-loop
            # eval path; supersteps fuse these phases in-program
            bn = self.evaluator.sbn_stats(params, *self.sbn_batches)
            xu, yu, mu, lm = self.local_eval
            # staticcheck: allow(no-host-eval-in-driver): K=1 host-loop path
            local = self.evaluator.eval_users(params, bn, xu, yu, mu, lm, epoch=epoch)
            named_local = summarize_sums(local, cfg["model_name"])
            logger.append(named_local, "test", n=float(np.sum(local["n"])))
            # staticcheck: allow(no-host-eval-in-driver): K=1 host-loop path
            g = self.evaluator.eval_global(params, bn, *self.global_eval, epoch=epoch)
        else:
            # staticcheck: allow(no-host-eval-in-driver): K=1 host-loop path
            g = self.evaluator.eval_global(params, {}, *self.global_eval, epoch=epoch)
        named_global = summarize_sums({k: np.asarray(v) for k, v in g.items()},
                                      cfg["model_name"], prefix="Global-")
        logger.append(named_global, "test", n=g["n"])
        info = {"info": [f"Model: {self.tag}", f"Test Epoch: {epoch}"]}
        logger.append(info, "test", mean=False)
        test_names = [n.split("/", 1)[1] for n in logger.mean if n.startswith("test/")]
        logger.write("test", test_names)
        self.bn_state = bn
        return named_global

    # -- full loop -----------------------------------------------------

    def run(self, pivot_metric: str, pivot_mode: str = "max") -> Dict[str, Any]:
        cfg = self.cfg
        blob = resume(cfg["output_dir"], self.tag, cfg["resume_mode"])
        check_multihost_resume(blob)
        if blob and "data_split" in blob and blob["data_split"] is not None:
            data_split, label_split = blob["data_split"], blob["label_split"]
        else:
            data_split, label_split = self.make_splits()
        self.stage(data_split, label_split)
        params = self.model.init(jax.random.fold_in(self.host_key, 0))
        last_epoch = 1
        logger = Logger(os.path.join(cfg["output_dir"], "runs", f"train_{self.tag}"),
                        use_tensorboard=bool(cfg.get("use_tensorboard")))
        if self.obs_spec.trace_dir and self.tracer is None \
                and jax.process_index() == 0:
            # run tracing (ISSUE 10): one Chrome-trace + events-JSONL
            # recorder per run; PhaseTimer phases file onto the same
            # timeline, driver events land via _trace_span below
            from ..obs.trace import TraceRecorder

            self.tracer = TraceRecorder(
                os.path.join(self.obs_spec.trace_dir, self.tag))
            self.phase_timer.trace = self.tracer
        pivot = -float("inf") if pivot_mode == "max" else float("inf")
        if blob:
            params = _restore_params(blob["params"])
            if blob.get("wire_resid") is not None:
                # resume the wire codec's error-feedback carry (ISSUE 8):
                # without it the first resumed round re-loses the residual a
                # checkpointed run already accounted for (weights-only
                # resume_mode=2 intentionally resets it to zeros)
                self._codec_engine().set_wire_resid(blob["wire_resid"])
            if blob.get("sched_buf") is not None:
                # resume the buffered-async staleness carry (ISSUE 9):
                # cohort k's in-flight update survives the checkpoint
                # boundary, so a resumed run replays the exact trajectory
                self._codec_engine().set_sched_buf(blob["sched_buf"])
            if blob.get("ledger") is not None and self.ledger is not None:
                # resume the population ledger (ISSUE 12): counts, EMAs
                # and level history CONTINUE instead of resetting --
                # bit-identical to an uninterrupted run (tested)
                self.ledger.load_state_dict(blob["ledger"])
            if "epoch" in blob:
                last_epoch = blob["epoch"]
                pivot = blob.get("pivot", pivot)
                if blob.get("logger_state"):
                    # full fidelity: running means/counters + TB step counters
                    logger.load_state_dict(blob["logger_state"])
                else:  # older blobs carried history only
                    logger.history = blob.get("logger_history", logger.history)
                if blob.get("scheduler_state") and hasattr(self.scheduler, "load_state_dict"):
                    self.scheduler.load_state_dict(blob["scheduler_state"])
        n_rounds = cfg["num_epochs"]["global"]
        eval_interval = self.eval_interval
        epoch = last_epoch
        if self.tracer is not None:
            self.tracer.instant("run-start",
                                args={"tag": self.tag, "epoch0": int(epoch),
                                      "rounds": int(n_rounds)})
        try:
            return self._run_loop(logger, pivot_metric, pivot_mode, pivot,
                                  epoch, n_rounds, eval_interval, data_split,
                                  label_split, params)
        finally:
            if self.tracer is not None:
                # the trace must survive aborts (the watchdog's whole
                # point): close on every exit path
                self.tracer.close()
                self.phase_timer.trace = None
            if self.ledger is not None and jax.process_index() == 0:
                # the ledger.npz snapshot the report surface reads (ISSUE
                # 12): written on every exit path, aborts included
                self.ledger.save(self._ledger_path())

    @staticmethod
    def _tree_finite(tree) -> bool:
        """True iff every float array leaf of a nested dict/list tree is
        all-finite (non-array / non-float leaves pass)."""
        if isinstance(tree, dict):
            return all(FedExperiment._tree_finite(v) for v in tree.values())
        if isinstance(tree, (list, tuple)):
            return all(FedExperiment._tree_finite(v) for v in tree)
        try:
            arr = np.asarray(tree)
        except Exception:
            return True
        if not np.issubdtype(arr.dtype, np.floating):
            return True
        return bool(np.all(np.isfinite(arr)))

    def _load_rollback_blob(self) -> Optional[Dict[str, Any]]:
        """The newest checkpoint generation that BOTH verifies (checksum)
        and holds all-finite restorable state (ISSUE 15): under a deferred
        metrics fetch the newest generation can checksum clean yet carry
        the very NaN the watchdog tripped on -- in the params, OR in a
        restored carry (the EF residual, the buffered staleness buffer,
        the sBN state).  Restoring such a blob would trip again
        immediately and burn the whole retry budget on one poisoned blob.
        Returns None when no usable generation exists (fresh restart)."""
        from ..utils.checkpoint import iter_verified_generations

        path = checkpoint_path(self.cfg["output_dir"], self.tag)
        for p, blob in iter_verified_generations(path):
            finite = all(
                self._tree_finite(blob.get(k))
                for k in ("params", "bn_state", "wire_resid", "sched_buf"))
            if finite:
                return blob
            warnings.warn(f"rollback: checkpoint generation {p} verifies "
                          f"but holds non-finite params or carries; "
                          f"falling back a generation")
        return None

    def _recover_rollback(self, logger: Logger, trip: WatchdogRollback,
                          pivot_mode: str):
        """One watchdog-rollback recovery attempt (ISSUE 15): emit the
        recovery evidence, drop every piece of in-flight state, salt the
        round key stream (the replayed superstep draws a FRESH cohort),
        restore the newest usable checkpoint generation (or restart fresh
        when none exists), back off, and hand (params, epoch, pivot) back
        to the run loop.  Escalates to :class:`WatchdogError` -- with the
        abort path's durability -- once ``max_retries`` is spent."""
        spec = self.obs_spec.watchdog
        self._rollback_attempts += 1
        attempt = self._rollback_attempts
        if attempt > spec.max_retries:
            if self.tracer is not None:
                self.tracer.close()
            logger.flush()
            if self.ledger is not None and jax.process_index() == 0:
                self.ledger.save(self._ledger_path())
            raise WatchdogError(
                f"watchdog rollback budget spent ({spec.max_retries} "
                f"attempt(s)): escalating to abort; last trip "
                f"{trip.events[0] if trip.events else trip!r}") from trip
        # the retry salt: every replayed round re-derives its keys from the
        # salted stream, so the re-drawn cohort excludes the poisoned draw
        # deterministically (chaos.drill predicts these draws)
        self.host_key = jax.random.fold_in(self.host_key,
                                           RETRY_SALT + attempt)
        blob = self._load_rollback_blob()
        rec = {"event": "rollback", "attempt": attempt,
               "max_retries": spec.max_retries,
               "kind": trip.events[0].get("kind") if trip.events else None,
               "trip_epoch": trip.events[0].get("epoch")
               if trip.events else None,
               "restored_epoch": (blob or {}).get("epoch"),
               "fresh_restart": blob is None}
        logger.emit(rec, tag="recovery")
        if self.tracer is not None:
            self.tracer.instant("recovery", cat="obs", args=rec)
        warnings.warn(f"watchdog rollback attempt {attempt}/"
                      f"{spec.max_retries}: restoring "
                      f"{'a fresh init' if blob is None else 'epoch %s' % rec['restored_epoch']} "
                      f"with a salted cohort stream")
        logger.safe(False)  # close the aborted iteration's writer
        # drop EVERY piece of in-flight state the unwound iteration left:
        # pending metric fetches (discarded -- their rounds replay),
        # prefetched cohorts (drawn pre-salt), commitment counters, the
        # spike window, and the engines' device scan carries
        try:
            self.metrics_pipe.flush()
        except Exception:
            pass  # a poisoned pending fetch must not block recovery
        self._next_cohorts = []
        self._ss_dispatched = self._ss_fetched = 0
        if self._commitment is not None:
            self._commitment = ScheduleCommitment(self.sampler_spec.horizon)
        if self.watchdog is not None:
            self.watchdog.reset_window()
        self._codec_engine().reset_carries()
        pivot0 = -float("inf") if pivot_mode == "max" else float("inf")
        if blob is None:
            params = self.model.init(jax.random.fold_in(self.host_key, 0))
            logger.load_state_dict({})
            logger.reset()
            self.scheduler = make_scheduler(self.cfg)
            if self.ledger is not None:
                self.ledger = ClientLedger(
                    self.cfg["num_users"],
                    sorted({float(r) for r in self.cfg["model_rate"]},
                           reverse=True))
            self.bn_state = {}
            epoch, pivot = 1, pivot0
        else:
            params = {k: jnp.asarray(v) for k, v in blob["params"].items()}
            if blob.get("wire_resid") is not None:
                self._codec_engine().set_wire_resid(blob["wire_resid"])
            if blob.get("sched_buf") is not None:
                self._codec_engine().set_sched_buf(blob["sched_buf"])
            if blob.get("ledger") is not None and self.ledger is not None:
                self.ledger.load_state_dict(blob["ledger"])
            logger.load_state_dict(blob.get("logger_state") or {})
            if blob.get("scheduler_state") \
                    and hasattr(self.scheduler, "load_state_dict"):
                self.scheduler.load_state_dict(blob["scheduler_state"])
            self.bn_state = blob.get("bn_state", {})
            epoch = blob.get("epoch", 1)
            pivot = blob.get("pivot", pivot0)
        if spec.backoff > 0:
            time.sleep(min(spec.backoff * (2 ** (attempt - 1)), 30.0))
        return params, epoch, pivot

    def _run_loop(self, logger, pivot_metric, pivot_mode, pivot, epoch,
                  n_rounds, eval_interval, data_split, label_split, params):
        cfg = self.cfg
        while True:
            try:
                if epoch > n_rounds:
                    # the final drain sits INSIDE the recovery loop: under
                    # a deferred fetch the last superstep's trip surfaces
                    # here, and a rollback must restore + re-enter the
                    # round loop instead of degrading to an abort
                    self._drain_metrics(logger)  # nothing stays on device
                    break
                params, epoch, pivot = self._run_iteration(
                    logger, pivot_metric, pivot_mode, pivot, epoch, n_rounds,
                    eval_interval, data_split, label_split, params)
            except WatchdogRollback as trip:
                # watchdog auto-rollback (ISSUE 15): restore, salt, retry
                params, epoch, pivot = self._recover_rollback(
                    logger, trip, pivot_mode)
        return {"params": params, "bn_state": getattr(self, "bn_state", {}),
                "logger": logger, "data_split": data_split, "label_split": label_split}

    def _run_iteration(self, logger, pivot_metric, pivot_mode, pivot, epoch,
                       n_rounds, eval_interval, data_split, label_split,
                       params):
        """One run-loop iteration: a dispatch window (superstep or K=1
        round + eval), the best-pivot decision, and the durable checkpoint
        write.  Returns ``(params, next_epoch, pivot)``; raises
        :class:`WatchdogRollback` through to :meth:`_run_loop` when the
        watchdog trips under ``action='rollback'``."""
        cfg = self.cfg
        logger.safe(True)
        # superstep length: the end of the run is the ONLY clamp left --
        # eval windows run inside the scan (ISSUE 4), so K no longer
        # shortens to the next eval boundary.  Checkpoints land on
        # superstep boundaries; evals inside a superstep are logged (and
        # feed Plateau) when its metrics are fetched.
        k_eff = 1
        if self.superstep_rounds > 1 or self.streaming:
            # streaming always takes the superstep path (k_eff=1 at
            # superstep_rounds=1): cohorts ride the scanned program's
            # xs, so there is exactly one store-backed dispatch shape
            k_eff = min(self.superstep_rounds, n_rounds - epoch + 1)
            # a clamped end-of-run tail still goes through the superstep
            # path (smaller k) so ONE sampling stream covers the run
            with self._trace_span("superstep",
                                  {"epoch0": int(epoch), "k": int(k_eff)}):
                params = self.train_superstep(params, epoch, k_eff, logger)
            epoch = epoch + k_eff - 1  # last round this iteration covered
            # pivot integrity: the checkpoint below holds END-OF-SUPERSTEP
            # params, so only an eval on the boundary round -- fetched
            # synchronously, i.e. logged THIS iteration -- may update the
            # best-copy pivot; mid-superstep evals log and feed Plateau
            # but their params were consumed inside the scan
            pivot_fresh = (self.metrics_pipe.fetch_every == 1
                           and (epoch % eval_interval == 0
                                or epoch == n_rounds))
        else:
            pivot_fresh = True
            lr = self.scheduler(epoch)
            with self._trace_span("round", {"epoch": int(epoch)}):
                params = self.train_round(params, epoch, lr, logger)
            evaluated = epoch % eval_interval == 0 or epoch == n_rounds
            if evaluated:
                with self._trace_span("eval", {"epoch": int(epoch)}):
                    self.evaluate(params, epoch, logger, label_split)
                if isinstance(self.scheduler, PlateauScheduler):
                    # min-mode plateau fed the test Global loss, only on
                    # rounds that actually evaluated.  (The reference
                    # feeds logger.mean['train/Global-Accuracy'], a key
                    # its train loop never writes, i.e. a constant 0 --
                    # an upstream bug we do not reproduce.)
                    self.scheduler.step_metric(
                        logger.mean.get("test/Global-Loss", 0.0))
        logger.safe(False)
        cur = logger.history.get(f"test/{pivot_metric}", [None])[-1]
        is_best = pivot_fresh and cur is not None \
            and (cur > pivot if pivot_mode == "max" else cur < pivot)
        if is_best:
            pivot = cur  # update BEFORE saving so a resumed run keeps it
        blob_out = {
            "cfg": {k: v for k, v in cfg.items() if k != "vocab"},
            "epoch": epoch + 1,
            "data_split": data_split,
            "label_split": label_split,
            "params": params,
            "bn_state": getattr(self, "bn_state", {}),
            # the error-feedback residual carry at this superstep
            # boundary (ISSUE 8; None under the dense codec)
            "wire_resid": (self._codec_engine().wire_resid_host()
                           if self.wire_codec != "dense" else None),
            # the buffered-async staleness carry at this superstep
            # boundary (ISSUE 9; None under sync aggregation)
            "sched_buf": (self._codec_engine().sched_buf_host()
                          if self.sched_spec.buffered else None),
            # the population ledger at this superstep boundary (ISSUE
            # 12; None when ledger='off')
            "ledger": (self.ledger.state_dict()
                       if self.ledger is not None else None),
            "pivot": pivot,
            "logger_history": dict(logger.history),
            "logger_state": logger.state_dict(),
            "scheduler_state": self.scheduler.state_dict()
            if hasattr(self.scheduler, "state_dict") else None,
        }
        # multi-host: the sharded writer is COLLECTIVE -- every process
        # calls it; replicated-only blobs degenerate to the process-0
        # plain write, process-local leaves (the slices EF carry) land in
        # per-process shard files named by the header (ISSUE 17)
        if jax.process_index() == 0:
            self._chaos("checkpoint")
        with self._trace_span("checkpoint", {"epoch": int(epoch)}):
            save_checkpoint_sharded(
                checkpoint_path(cfg["output_dir"], self.tag),
                blob_out, keep=self.checkpoint_keep)
            if is_best and jax.process_index() == 0:
                copy_best(cfg["output_dir"], self.tag)
        logger.reset()
        # a clean iteration ending in a durable checkpoint proves recovery:
        # the rollback budget re-arms for the next (independent) incident
        self._rollback_attempts = 0
        return params, epoch + 1, pivot


class ArmsExperiment(FedExperiment):
    """The multiplexed driver loop (ISSUE 14, heterofl_tpu/multi/): E
    trace-compatible experiment arms in ONE fused superstep program per
    dispatch.

    Reuses the base experiment's staging, engines, evaluator and schedule
    helpers; the loop differs where the arms axis surfaces on the host --
    per-arm init trees (each arm's stream root seeds its own
    ``model.init``), per-arm ``{"tag": "arms"}`` JSONL log lines carrying
    an ``arm`` field, per-arm ReduceLROnPlateau state (one scheduler per
    arm, stepped on that arm's own fused-eval Global loss, staged into the
    program as the ``[E]`` LR vector), per-arm best-pivot tracking, and
    per-arm checkpoints (one exportable blob per arm next to the
    multiplexed resume blob).  Fetches are synchronous (one fetch per
    superstep serves all E arms -- the arms win is batching compute, not
    deferring metrics)."""

    _arms_capable = True

    def __init__(self, cfg: Dict[str, Any], seed: int):
        super().__init__(cfg, seed)
        if self.arms_spec is None:
            raise ValueError("ArmsExperiment needs cfg['arms'] (an int "
                             "count or a {count, seeds, lr_scales} dict)")
        self._plateau = isinstance(self.scheduler, PlateauScheduler)
        # per-arm Plateau state: each arm owns a scheduler instance stepped
        # on its OWN eval metrics (the solo loop's semantics, per arm); the
        # arm's lr_scale multiplies the scheduler's output either way, so a
        # Plateau LR sweep still trains each arm at ITS grid value
        self._arm_scheds = [make_scheduler(self.cfg)
                            for _ in range(self.arms_spec.count)] \
            if self._plateau else None
        # per-arm watchdogs: the spike detector's rolling loss window is
        # per trajectory -- one shared Watchdog would mix E loss streams
        self._arm_watchdogs = ([Watchdog(self.obs_spec.watchdog)
                                for _ in range(self.arms_spec.count)]
                               if self.watchdog is not None else None)
        if self.obs_spec.watchdog is not None \
                and self.obs_spec.watchdog.action == "rollback":
            raise ValueError(
                "watchdog action='rollback' cannot combine with arms yet: "
                "one arm's trip would roll every arm back, and the "
                "multiplexed loop has no per-arm recovery (a ROADMAP "
                "follow-on); use 'warn'/'abort' for arms runs")
        self._staged_lr_vec = None  # the [E] LR vector of the live dispatch

    def _arms_tag(self) -> str:
        return f"{self.tag}_arms{self.arms_spec.count}"

    def _arm_tag(self, e: int) -> str:
        return f"{self._arms_tag()}_a{e}"

    def _arm_lr(self, e: int, epoch: int) -> float:
        sched = self._arm_scheds[e] if self._plateau else self.scheduler
        return float(sched(epoch)) * self.arms_spec.lr_scales[e]

    def _observe_arm(self, logger: Logger, e: int, epoch: int,
                     probes: Dict[str, Any], ms) -> None:
        """The solo loop's :meth:`_observe` with the arms axis: the probes
        event carries the ``arm`` field and each arm feeds ITS OWN
        watchdog (the spike window is per trajectory)."""
        loss = None
        n = float(np.sum(ms["n"]))
        if n > 0:
            loss = float(np.sum(ms["loss_sum"])) / n
        logger.emit({"event": "probes", "arm": e, "epoch": int(epoch),
                     "loss": loss, **probes})
        if self._arm_watchdogs is not None:
            try:
                self._arm_watchdogs[e].check(
                    epoch, probes=probes, loss=loss,
                    emit=lambda ev: logger.emit({**ev, "arm": e}))
            except WatchdogError:
                # abort evidence must be ON DISK before the unwind (the
                # solo loop's durability contract; arms runs have no
                # tracer/ledger -- both are refused at construction)
                logger.flush()
                raise

    def _init_params(self):
        """Stacked per-arm init trees: arm e's params come from ITS stream
        root (``fold_in(arm_root, 0)``, the solo loop's derivation), so the
        identity arm inits exactly like a solo run."""
        roots = arm_stream_keys(self.host_key, self.arms_spec.seeds)
        trees = [self.model.init(jax.random.fold_in(roots[e], 0))
                 for e in range(self.arms_spec.count)]
        return jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *trees)

    def _dispatch(self, params, epoch0: int, k: int, mask):
        """One multiplexed superstep: the engines batch the arms axis; the
        driver supplies shared schedules (grouped/sharded) and the per-arm
        LR vector under Plateau."""
        fused = self._fused_eval(None) if any(mask) else None
        lr_vec = np.asarray(
            [s(epoch0) * sc for s, sc in zip(self._arm_scheds,
                                             self.arms_spec.lr_scales)],
            np.float32) if self._plateau else None
        # the fetch loop steps the Plateau schedulers mid-superstep; the
        # logged LR must be what THIS dispatch actually staged, not the
        # scheduler's post-step value (the solo loop pins lrs pre-fetch)
        self._staged_lr_vec = lr_vec
        if self.cfg.get("strategy") == "grouped":
            users = self._superstep_schedule(epoch0, k)
            rates = superstep_rate_schedule(self.host_key, epoch0, k,
                                            self.cfg, users)
            return self.alt_engine.train_superstep(
                params, self.host_key, epoch0, k, users, rates,
                self.train_data, timer=self.phase_timer,
                eval_mask=mask if fused else None, fused_eval=fused,
                lr=lr_vec)
        sched = None
        if self.cfg.get("data_placement") == "sharded":
            sched = self._superstep_schedule(epoch0, k)
        return self.engine.train_superstep(
            params, self.host_key, epoch0, k, self.train_data,
            user_schedule=sched, num_active=self.num_active,
            timer=self.phase_timer, eval_mask=mask if fused else None,
            fused_eval=fused, lr=lr_vec)

    def run(self, pivot_metric: str, pivot_mode: str = "max") -> Dict[str, Any]:
        cfg = self.cfg
        E = self.arms_spec.count
        tag = self._arms_tag()
        blob = resume(cfg["output_dir"], tag, cfg["resume_mode"])
        check_multihost_resume(blob)
        if blob and blob.get("data_split") is not None:
            data_split, label_split = blob["data_split"], blob["label_split"]
        else:
            data_split, label_split = self.make_splits()
        self.stage(data_split, label_split)
        logger = Logger(os.path.join(cfg["output_dir"], "runs",
                                     f"train_{tag}"),
                        use_tensorboard=bool(cfg.get("use_tensorboard")))
        params = self._init_params()
        epoch = 1
        pivots = [(-float("inf") if pivot_mode == "max" else float("inf"))
                  for _ in range(E)]
        if blob:
            params = _restore_params(blob["params"])
            epoch = blob.get("epoch", 1)
            pivots = blob.get("arm_pivots", pivots)
            if blob.get("wire_resid") is not None:
                # the stacked [E, ...] EF carry resumes like a solo run's
                self._codec_engine().set_wire_resid(blob["wire_resid"])
            if blob.get("arm_scheds") and self._arm_scheds:
                for s, st in zip(self._arm_scheds, blob["arm_scheds"]):
                    s.load_state_dict(st)
        n_rounds = cfg["num_epochs"]["global"]
        K = self.superstep_rounds
        while epoch <= n_rounds:
            k = min(K, n_rounds - epoch + 1)
            mask = tuple((epoch + r) % self.eval_interval == 0
                         or (epoch + r) == n_rounds for r in range(k))
            t0 = time.time()
            params, pending = self._dispatch(params, epoch, k, mask)
            with self.phase_timer.phase("fetch"):
                out = pending.fetch()
            dt = time.time() - t0
            logger.safe(True)
            evaluated: List[Optional[Dict[str, float]]] = [None] * E
            for e, arm_out in enumerate(out["arms"]):
                rounds = arm_out["train"] if isinstance(arm_out, dict) \
                    else arm_out
                evals = {ev["epoch"]: ev
                         for ev in (arm_out.get("eval") or [])} \
                    if isinstance(arm_out, dict) else {}
                probes = arm_out.get("obs") \
                    if isinstance(arm_out, dict) else None
                for r in range(k):
                    ms = rounds[r]
                    if probes:
                        self._observe_arm(logger, e, epoch + r,
                                          probes[r], ms)
                    n = float(np.sum(ms["n"]))
                    logger.emit(
                        {"event": "train", "arm": e, "epoch": epoch + r,
                         "lr": (float(self._staged_lr_vec[e])
                                if self._plateau
                                else self._arm_lr(e, epoch + r)),
                         "loss": (float(np.sum(ms["loss_sum"])) / n
                                  if n > 0 else None),
                         "n": n, "dt": dt / (k * E)}, tag="arms")
                    ev = evals.get(epoch + r)
                    if ev is not None:
                        g = summarize_sums(
                            {kk: np.asarray(v)
                             for kk, v in ev["global"].items()},
                            cfg["model_name"], prefix="Global-")
                        logger.emit({"event": "eval", "arm": e,
                                     "epoch": epoch + r,
                                     **{kk: float(vv)
                                        for kk, vv in g.items()}},
                                    tag="arms")
                        evaluated[e] = g
                        if self._plateau:
                            # per-arm Plateau: min-mode on this ARM's own
                            # test Global loss (the solo loop's feed)
                            self._arm_scheds[e].step_metric(
                                g.get("Global-Loss", 0.0))
            epoch_end = epoch + k - 1
            # per-arm exportable blobs need HOST arm slices; on an arms-
            # sharded multi-process mesh that is a collective gather (every
            # process executes it in lockstep -- checkpoint boundary only,
            # never round-path wire), on a single process a plain D2H
            from ..parallel.staging import host_fetch
            host_params = {kk: host_fetch(v) for kk, v in params.items()}
            for e in range(E):
                g = evaluated[e]
                cur = g.get(pivot_metric) if g else None
                is_best = cur is not None and \
                    (cur > pivots[e] if pivot_mode == "max"
                     else cur < pivots[e])
                if is_best:
                    pivots[e] = cur
                # per-arm exportable checkpoint: arm e's params slice +
                # stream identity, loadable by any solo consumer
                arm_blob = {
                    "cfg": {kk: v for kk, v in cfg.items() if kk != "vocab"},
                    "arm": e, "arm_seed": self.arms_spec.seeds[e],
                    "lr_scale": self.arms_spec.lr_scales[e],
                    "epoch": epoch_end + 1,
                    "params": {kk: np.asarray(v[e])
                               for kk, v in host_params.items()},
                    "pivot": pivots[e],
                }
                if jax.process_index() == 0:
                    save_checkpoint(
                        checkpoint_path(cfg["output_dir"], self._arm_tag(e)),
                        arm_blob, keep=self.checkpoint_keep)
                    if is_best:
                        copy_best(cfg["output_dir"], self._arm_tag(e))
            # the multiplexed resume blob: stacked params + per-arm state
            blob_out = {
                "cfg": {kk: v for kk, v in cfg.items() if kk != "vocab"},
                "epoch": epoch_end + 1,
                "data_split": data_split, "label_split": label_split,
                "params": params, "arm_pivots": pivots,
                "wire_resid": (self._codec_engine().wire_resid_host()
                               if self.wire_codec != "dense" else None),
                "arm_scheds": ([s.state_dict() for s in self._arm_scheds]
                               if self._arm_scheds else None),
            }
            # collective: arms-sharded params land in per-process shard
            # files; replicated blobs degenerate to the process-0 write
            save_checkpoint_sharded(checkpoint_path(cfg["output_dir"], tag),
                                    blob_out, keep=self.checkpoint_keep)
            logger.safe(False)
            epoch = epoch_end + 1
        return {"params": params, "arms": self.arms_spec, "pivots": pivots,
                "data_split": data_split, "label_split": label_split}


def run_main(description: str, model_default: str, data_default: str,
             pivot_metric: str, pivot_mode: str, argv: Optional[List[str]] = None):
    """Shared ``main()``: parse flags, loop seeds (ref
    train_classifier_fed.py:37-45), run experiments."""
    from ..parallel.mesh import initialize_distributed

    initialize_distributed()  # no-op single-host; joins the pod otherwise
    # persistent XLA compilation cache: repeated experiments skip the ~40s
    # flagship-round compile (BENCH_r05 compile_sec); operator env wins
    enable_persistent_cache()
    parser = build_cli(description)
    args = parser.parse_args(argv)
    cfg = cfg_from_args(args)
    if args.model_name is None:
        cfg["model_name"] = model_default
    if args.data_name is None:
        cfg["data_name"] = data_default
    cfg = C.process_control(cfg)
    results = []
    for i in range(cfg["num_experiments"]):
        seed = cfg["init_seed"] + i
        exp = FedExperiment(cfg, seed)
        print(f"Experiment: {exp.tag}")
        results.append(exp.run(pivot_metric, pivot_mode))
    return results
