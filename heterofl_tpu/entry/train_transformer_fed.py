"""Federated masked-LM training.

Parity: ``src/train_transformer_fed.py`` -- no sBN recalibration, global
metrics only, pivot = minimised Global-Perplexity
(ref train_transformer_fed.py:31-32, 90).  Shares the staged zero-
resharding dispatch path, per-round phase telemetry and
``--metrics_fetch_every`` async metric fetch with the classifier driver
(entry/common.py + parallel/staging.py).
"""

from .common import run_main


def main(argv=None):
    return run_main("heterofl-tpu federated transformer", "transformer", "WikiText2",
                    pivot_metric="Global-Perplexity", pivot_mode="min", argv=argv)


if __name__ == "__main__":
    main()
