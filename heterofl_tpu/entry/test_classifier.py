"""Evaluation driver (parity: ``src/test_classifier.py``)."""

from .evaluate import run_test_main


def main(argv=None):
    return run_test_main("heterofl-tpu test_classifier", "resnet18", "CIFAR10", argv=argv)


if __name__ == "__main__":
    main()
