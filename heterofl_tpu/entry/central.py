"""Centralized (non-federated) baselines.

Parity: ``src/train_classifier.py`` / ``src/train_transformer.py`` (§3.5 of
SURVEY.md): plain epoch training of the global-rate model with a persistent
optimizer, sBN recalibration + test each epoch.  The reference's
``nn.DataParallel`` multi-GPU path (train_classifier.py:65-66) becomes batch
data-parallelism over the whole mesh: each device takes a slice of every
batch and gradients are ``psum``-ed -- the same program at any device count.
"""

from __future__ import annotations

import math
import time
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..data.datasets import DATASET_STATS
from ..models.base import ModelDef
from ..ops.augment import augment_cifar, normalize_image
from ..data.pipeline import stack_windows as _stack_windows
from ..parallel.round_engine import _ceil_div, _shard_map
from ..utils.optim import clip_by_global_norm, make_optimizer
from .common import _batch_array as _batch_pad


class CentralEngine:
    """Jitted data-parallel epoch for the non-fed baseline."""

    def __init__(self, model: ModelDef, cfg: Dict[str, Any], mesh):
        self.model = model
        self.cfg = cfg
        self.mesh = mesh
        self.is_lm = model.meta.get("kind") == "transformer"
        self.norm_stats = cfg.get("norm_stats") or DATASET_STATS.get(cfg["data_name"])
        self.augment = cfg["data_name"].startswith("CIFAR")
        self._opt_init, self._opt_update = make_optimizer(cfg)
        self._epoch = None

    def init_opt(self, params):
        return self._opt_init(params)

    def _build(self):
        model = self.model
        axes = ("clients", "data")

        def body(params, opt, key, lr, *data):
            def stepf(carry, inp):
                p, opt = carry
                *arrs, t = inp
                kk = jax.random.fold_in(key, t)
                if self.is_lm:
                    lab, w = arrs
                    batch = {"label": lab}
                else:
                    xb, yb, w = arrs
                    if self.augment:
                        xb = augment_cifar(jax.random.fold_in(kk, 1), xb)
                    img = normalize_image(xb, *self.norm_stats) if self.norm_stats \
                        else xb.astype(jnp.float32)
                    batch = {"img": img, "label": yb}

                def loss_fn(p):
                    out, _ = model.apply(p, batch, train=True, sample_weight=w,
                                         rng=jax.random.fold_in(kk, 2))
                    n_loc = jnp.sum(w)
                    return out["loss"] * n_loc, (out["score"], n_loc)

                (lsum, (score, n_loc)), grads = jax.value_and_grad(loss_fn, has_aux=True)(p)
                n_tot = jax.lax.psum(n_loc, axes)
                lsum = jax.lax.psum(lsum, axes)
                grads = jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, axes) / jnp.maximum(n_tot, 1e-6), grads)
                grads, _ = clip_by_global_norm(grads, 1.0)
                p, opt = self._opt_update(p, grads, opt, lr)
                if self.is_lm:
                    rows = jnp.asarray(batch["label"].shape[0], jnp.float32)
                    rows = jax.lax.psum(rows * (jnp.sum(w) > 0).astype(jnp.float32), axes)
                    metric = jnp.exp(lsum / jnp.maximum(n_tot, 1e-6)) * rows
                    stats = (lsum / jnp.maximum(n_tot, 1e-6) * rows, metric, rows)
                else:
                    correct = jax.lax.psum(jnp.sum((jnp.argmax(score, -1) == batch["label"]) * w), axes)
                    stats = (lsum, correct, n_tot)
                return (p, opt), stats

            S = data[0].shape[0]
            (params, opt), stats = jax.lax.scan(stepf, (params, opt),
                                                tuple(data) + (jnp.arange(S),))
            return params, opt, tuple(jnp.sum(s, 0) for s in stats)

        n_arrs = 2 if self.is_lm else 3
        # batch axis (axis 1 of each [S, B, ...] array) sharded over all devices
        data_specs = tuple(P(None, axes) for _ in range(n_arrs))
        fn = _shard_map(body, self.mesh,
                        in_specs=(P(), P(), P(), P()) + data_specs,
                        out_specs=(P(), P(), P()))
        return jax.jit(fn, donate_argnums=(0, 1))

    def train_epoch(self, params, opt, key, lr, *data):
        """data: vision ``(x [S,B,...]u8, y [S,B], w [S,B])``;
        LM ``(labels [S,B,bptt], w [S,B,bptt])``.  Returns
        ``(params, opt, (loss_sum, metric_sum, n))``."""
        if self._epoch is None:
            self._epoch = self._build()
        return self._epoch(params, opt, key, jnp.asarray(lr, jnp.float32), *data)


class CentralExperiment:
    """Non-federated baseline experiment (data_split_mode 'none')."""

    def __init__(self, cfg: Dict[str, Any], seed: int):
        from .. import config as C
        from ..data import fetch_dataset, process_dataset
        from ..models import make_model
        from ..parallel import make_mesh
        from ..parallel.evaluation import Evaluator
        from ..utils import make_scheduler

        self.seed = seed
        self.rng = np.random.default_rng(seed)
        self.host_key = jax.random.key(seed)
        dataset = fetch_dataset(cfg["data_name"], cfg["data_dir"], synthetic=cfg["synthetic"],
                                seed=seed, synthetic_sizes=cfg.get("synthetic_sizes"),
                                subset=cfg.get("subset", "label"))
        self.cfg, self.dataset = process_dataset(cfg, dataset)
        cfg = self.cfg
        from .common import _maybe_compute_norm_stats

        _maybe_compute_norm_stats(cfg, self.dataset)
        self.tag = C.make_model_tag(seed, cfg)
        self.kind = "transformer" if cfg["model_name"] == "transformer" else "vision"
        self.model = make_model(cfg)
        self.mesh = make_mesh(len(jax.devices()), 1)
        self.engine = CentralEngine(self.model, cfg, self.mesh)
        self.evaluator = Evaluator(self.model, cfg, self.mesh, seed=seed)
        self.scheduler = make_scheduler(cfg)

    def _epoch_batches(self):
        """Shuffled, device-count-padded batches for one epoch."""
        cfg = self.cfg
        n_dev = self.mesh.devices.size
        if self.kind == "vision":
            tr = self.dataset["train"]
            b = cfg["batch_size"]["train"]
            b = _ceil_div(b, n_dev) * n_dev
            perm = self.rng.permutation(len(tr.data))
            x, w = _batch_pad(tr.data[perm], b)
            y, _ = _batch_pad(tr.target[perm], b)
            return x, y, w
        tr = self.dataset["train"]
        from ..data import bptt_windows
        wins = bptt_windows(tr.token, cfg["bptt"])
        xs, ws = _stack_windows(wins, cfg["bptt"])
        r = xs.shape[1]
        rpad = _ceil_div(r, n_dev) * n_dev - r
        if rpad:
            xs = np.concatenate([xs, np.zeros((xs.shape[0], rpad, xs.shape[2]), xs.dtype)], 1)
            ws = np.concatenate([ws, np.zeros((ws.shape[0], rpad, ws.shape[2]), np.float32)], 1)
        return xs, ws

    def run(self, pivot_metric: str, pivot_mode: str = "max"):
        import os

        from ..utils import (Logger, checkpoint_path, copy_best, resume,
                             save_checkpoint)

        cfg = self.cfg
        params = self.model.init(jax.random.fold_in(self.host_key, 0))
        opt = self.engine.init_opt(params)
        last_epoch = 1
        pivot = -float("inf") if pivot_mode == "max" else float("inf")
        logger = Logger(os.path.join(cfg["output_dir"], "runs", f"train_{self.tag}"),
                        use_tensorboard=bool(cfg.get("use_tensorboard")))
        blob = resume(cfg["output_dir"], self.tag, cfg["resume_mode"])
        if blob and "params" in blob:
            params = {k: jnp.asarray(v) for k, v in blob["params"].items()}
            if "epoch" in blob:
                last_epoch = blob["epoch"]
                pivot = blob.get("pivot", pivot)
            if blob.get("opt_state") is not None:  # momentum/moments survive resume
                st = blob["opt_state"]
                opt = type(opt)(jnp.asarray(st.step),
                                jax.tree_util.tree_map(jnp.asarray, st.slots))
        n_epochs = cfg["num_epochs"] if not isinstance(cfg["num_epochs"], dict) \
            else cfg["num_epochs"]["global"]
        # evaluation staging (same arrays as the federated driver's global eval)
        if self.kind == "vision":
            te = self.dataset["test"]
            xg, wg = _batch_pad(te.data, cfg["batch_size"]["test"])
            yg, _ = _batch_pad(te.target, cfg["batch_size"]["test"])
            geval = (xg, yg, wg)
            xs, ws = _batch_pad(self.dataset["train"].data, cfg["batch_size"]["train"])
            sbn_batches = (xs, ws)
        else:
            from ..data import bptt_windows
            xs, ws = _stack_windows(bptt_windows(self.dataset["test"].token, cfg["bptt"]),
                                    cfg["bptt"])
            geval = (xs, ws)
        from ..utils import summarize_sums
        for epoch in range(last_epoch, n_epochs + 1):
            logger.safe(True)
            lr = self.scheduler(epoch)
            t0 = time.time()
            data = self._epoch_batches()
            params, opt, (lsum, msum, n) = self.engine.train_epoch(
                params, opt, jax.random.fold_in(self.host_key, epoch), lr,
                *[jnp.asarray(a) for a in data])
            sums = {"loss_sum": np.asarray(lsum), "score_sum": np.asarray(msum), "n": np.asarray(n)}
            named = summarize_sums(sums, cfg["model_name"], prefix="")
            logger.append(named, "train", n=float(sums["n"]))
            logger.append({"info": [f"Model: {self.tag}", f"Train Epoch: {epoch}",
                                    f"Learning rate: {lr:g}",
                                    f"Epoch time: {time.time()-t0:.2f}s"]}, "train", mean=False)
            logger.write("train", list(named))
            bn = {}
            if self.kind == "vision":
                # staticcheck: allow(no-host-eval-in-driver): centralized
                # (non-federated) epoch loop -- no superstep to fuse into
                bn = self.evaluator.sbn_stats(params, *sbn_batches)
            # staticcheck: allow(no-host-eval-in-driver): centralized loop
            g = self.evaluator.eval_global(params, bn, *geval, epoch=epoch)
            named_g = summarize_sums({k: np.asarray(v) for k, v in g.items()},
                                     cfg["model_name"], prefix="")
            logger.append(named_g, "test", n=g["n"])
            logger.append({"info": [f"Model: {self.tag}", f"Test Epoch: {epoch}"]},
                          "test", mean=False)
            logger.write("test", list(named_g))
            logger.safe(False)
            cur = logger.history.get(f"test/{pivot_metric}", [None])[-1]
            is_best = cur is not None and (cur > pivot if pivot_mode == "max" else cur < pivot)
            if is_best:
                pivot = cur  # update BEFORE saving so a resumed run keeps it
            save_checkpoint(checkpoint_path(cfg["output_dir"], self.tag), {
                "cfg": {k: v for k, v in cfg.items() if k != "vocab"},
                "epoch": epoch + 1, "params": params, "bn_state": bn,
                "pivot": pivot, "logger_history": dict(logger.history),
                "opt_state": opt})
            if is_best:
                copy_best(cfg["output_dir"], self.tag)
            logger.reset()
        return {"params": params, "bn_state": bn, "logger": logger}


def run_central_main(description: str, model_default: str, data_default: str,
                     pivot_metric: str, pivot_mode: str, argv=None):
    from .. import config as C
    from .common import build_cli, cfg_from_args

    parser = build_cli(description)
    args = parser.parse_args(argv)
    cfg = cfg_from_args(args)
    if args.model_name is None:
        cfg["model_name"] = model_default
    if args.data_name is None:
        cfg["data_name"] = data_default
    cfg["control"]["data_split_mode"] = "none"
    cfg = C.process_control(cfg)
    results = []
    for i in range(cfg["num_experiments"]):
        seed = cfg["init_seed"] + i
        exp = CentralExperiment(cfg, seed)
        print(f"Experiment: {exp.tag}")
        results.append(exp.run(pivot_metric, pivot_mode))
    return results
