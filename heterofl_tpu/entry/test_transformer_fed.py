"""Evaluation driver (parity: ``src/test_transformer_fed.py``)."""

from .evaluate import run_test_main


def main(argv=None):
    return run_test_main("heterofl-tpu test_transformer_fed", "transformer", "WikiText2", argv=argv)


if __name__ == "__main__":
    main()
