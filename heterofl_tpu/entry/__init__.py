"""Entry points mirroring the reference's L5 drivers
(``src/train_{classifier,transformer}{,_fed}.py``, ``src/test_*``)."""
