"""Configuration core: defaults, control-string codec, derived hyperparameters.

Parity target: the reference's global ``cfg`` dict (``src/config.py:3-6``,
``src/config.yml``) and ``process_control()`` (``src/utils.py:113-215``).
Unlike the reference this module is purely functional -- no import-time global
mutable state; entry points build a cfg dict and pass it explicitly.

The 9-field control string
``fed_numusers_frac_datasplit_modelsplit_modelmode_norm_scale_mask``
(e.g. ``1_100_0.1_iid_fix_a2-b8_bn_1_1``) doubles as the experiment tag
(``src/train_classifier_fed.py:30,41-42``).
"""

from __future__ import annotations

import copy
import math
from typing import Any, Dict, List

import numpy as np

# Width multiplier per complexity level (ref src/utils.py:114).
MODEL_SPLIT_RATE: Dict[str, float] = {"a": 1.0, "b": 0.5, "c": 0.25, "d": 0.125, "e": 0.0625}

CONTROL_KEYS = (
    "fed",
    "num_users",
    "frac",
    "data_split_mode",
    "model_split_mode",
    "model_mode",
    "norm",
    "scale",
    "mask",
)

# Canonical name registries, kept here (jax-free) so offline analysis tooling
# can validate tags without importing the model/data stacks.  models/ and
# data/ import these rather than re-declaring them.
NORM_TYPES = ("bn", "in", "ln", "gn", "none")
MODEL_NAMES = ("conv", "resnet18", "resnet34", "resnet50", "resnet101",
               "resnet152", "transformer")
# Feature-axis value registries (ISSUE 18): THE declared domains of the
# engine/placement/store/pod axes, consumed by the axis validators below and
# by staticcheck's config-lattice pass (staticcheck/lattice.py enumerates
# every combination and proves it is either audited-green or refused here).
STRATEGIES = ("masked", "grouped", "sliced")
DATA_PLACEMENTS = ("replicated", "sharded")
LEVEL_PLACEMENTS = ("span", "slices")
CLIENT_STORES = ("eager", "stream")
VISION_DATASETS = ("MNIST", "FashionMNIST", "EMNIST", "CIFAR10", "CIFAR100")
FOLDER_DATASETS = ("Omniglot", "ImageNet", "ImageFolder")
LM_DATASETS = ("PennTreebank", "WikiText2", "WikiText103")

# Defaults mirroring the reference's config.yml (src/config.yml:1-55), minus
# torch-isms. ``device`` keeps its role as an execution hint ("tpu"/"cpu").
DEFAULT_CFG: Dict[str, Any] = {
    "control": {
        "fed": "1",
        "num_users": "100",
        "frac": "0.1",
        "data_split_mode": "iid",
        "model_split_mode": "fix",
        "model_mode": "a1",
        "norm": "bn",
        "scale": "1",
        "mask": "1",
    },
    "data_name": "CIFAR10",
    "subset": "label",
    "batch_size": {"train": 128, "test": 128},
    "shuffle": {"train": True, "test": False},
    "num_workers": 0,
    "model_name": "resnet18",
    "metric_name": {"train": ["Loss", "Accuracy"], "test": ["Loss", "Accuracy"]},
    "optimizer_name": "Adam",
    "lr": 3.0e-4,
    "momentum": 0.9,
    "weight_decay": 5.0e-4,
    "scheduler_name": "None",
    "step_size": 1,
    "milestones": [100, 150],
    "patience": 10,
    "threshold": 1.0e-3,
    "factor": 0.5,
    "min_lr": 1.0e-4,
    "init_seed": 0,
    "num_experiments": 1,
    "num_epochs": 200,
    "log_interval": 0.25,
    "device": "tpu",
    "world_size": 1,
    "resume_mode": 0,
    "save_format": "pdf",
    # ref writes TB scalars + info text every round unconditionally
    # (src/logger.py:57-84); here the writer is gated so headless runs stay
    # dependency-light, ON matching the reference when tensorboard is present
    "use_tensorboard": False,
    # TPU-native extras (no reference counterpart):
    # "masked" (one program, channel masks), "grouped" (rate-grouped dense
    # per-level programs on the mesh), "sliced" (host-orchestrated debug twin)
    "strategy": "masked",
    # "sharded": per-user train stacks live sharded over the clients axis and
    # every client trains on the device owning its shard (device memory scales
    # as U/n_devices); "replicated": all shards on every device.
    "data_placement": "replicated",
    # fuse the train-time masked BN into a Pallas TPU kernel (ops/pallas_norm.py)
    "pallas_norm": False,
    # conv lowering: None/"direct" = lax.conv (vmapped per-client kernels
    # become grouped convs); "im2col" = patch-extraction + batched matmul,
    # which keeps the client-vmapped hot path on dense MXU ops (ops/layers.py)
    "conv_impl": None,
    # lax.scan unroll factor for the local-step loop (1 = no unrolling);
    # latency-bound rounds can gain from fewer loop trips, A/B in tpu_ab.py
    "scan_unroll": 1,
    # fused masked-SGD optimizer epilogue + flat scan carry
    # (ops/fused_update.py): collapse the per-step grad normalise/mask/clip/
    # momentum/update/has-gate tail into one fused primitive and carry
    # params/momentum through the local-step scan as single lane-packed
    # buffers.  True = Pallas TPU kernel on TPU, flat-carry XLA fallback
    # elsewhere; False = the seed program (tree carry + reference op chain);
    # "xla"/"pallas" force an implementation.  The primitive and the
    # engines' STEP results are bit-identical to the reference chain
    # (tests/test_fused_update.py); long multi-step trajectories agree at
    # float-association level, like the masked-vs-sliced engine contract.
    # Non-SGD optimizers always use the reference chain.
    "fused_update": True,
    # explicit layout policy (models/layout.py): "auto" pins the params
    # carry's device layouts (row-major; width axes lane-packed minor-most)
    # at the program boundary on TPU backends and passes through on CPU;
    # "pinned" forces the pin, "none" disables it.
    "layout_policy": "auto",
    "param_dtype": "float32",
    "compute_dtype": "float32",  # set "bfloat16" to run matmuls/convs in bf16
    "mesh": {"clients": 0, "data": 1},  # 0 => use all available devices
    "data_dir": "./data",
    "output_dir": "./output",
    "synthetic": False,  # force synthetic data (offline/testing)
    "client_failure_rate": 0.0,  # per-round client crash probability (fault injection)
    "eval_interval": 1,  # rounds between sBN+eval passes (1 = reference parity)
    # async round pipelining: per-round train-metric sums stay on device and
    # are fetched every K rounds (parallel/staging.py MetricsPipeline), so
    # round t+1 dispatches while round t's sums transfer; eval boundaries
    # flush.  1 = synchronous fetch (reference parity).  K>1 logs train
    # metrics in K-round batches and a mid-batch checkpoint omits the not-
    # yet-fetched rounds from logger history (a perf knob, not a semantics
    # one).  With superstep_rounds>1 the legal values are 1 and
    # superstep_rounds: a larger batch would defer each superstep's eval
    # metrics past its checkpoint and silently disable best-checkpoint
    # tracking -- the driver fails loudly instead (ISSUE 6 satellite).
    "metrics_fetch_every": 1,
    # fused multi-round superstep: compile lax.scan over K federated rounds
    # into ONE jitted/donated program (parallel round_engine/grouped
    # train_superstep) -- per-round sampling, dynamic rate re-roll, failure
    # injection, the LR schedule AND the sBN+eval cadence all run in-jit
    # (eval rounds fire inside the scan on a static mask; eval_interval no
    # longer clamps K), metrics -- train and eval -- accumulate on device
    # and cross to the host once per superstep.  1 = one program per round
    # (host-loop eval, reference parity).  K>1 requires a mesh-native
    # strategy and metrics_fetch_every in {1} or multiples of K (whole
    # supersteps defer); ReduceLROnPlateau works when eval_interval % K == 0
    # (LR rides as a per-superstep scalar, stepped on the fused eval metrics
    # at superstep boundaries) and metrics_fetch_every <= K.  Checkpoints/
    # resume land on superstep boundaries; best-copy pivots on the LAST eval
    # of each superstep (intermediate evals log + feed Plateau but their
    # params are consumed inside the scan).  Under the masked engine with
    # replicated placement the per-round active set is sampled in-jit from
    # the jax key stream (fed.core.round_users) -- NOT the drivers' numpy
    # permutation stream used at superstep_rounds=1.
    "superstep_rounds": 1,
    # streaming million-user client store (ISSUE 6, parallel/staging.py
    # ClientStore + CohortStager): "eager" densifies the whole population
    # into [num_users, ...] stacks staged up front (the reference layout --
    # host/device memory scales with the population); "stream" keeps the
    # population as an O(1)-per-user metadata index and materialises only
    # each superstep's sampled cohort, committed via a double-buffered
    # device_put pipeline -- memory scales with active_clients and
    # superstep N+1's cohort stages while superstep N computes.  Streamed
    # supersteps are bit-identical to eager ones at matched seeds.  Needs a
    # mesh-native strategy; with superstep_rounds=1 the driver still runs
    # the (k=1) superstep path so rounds stay one-dispatch.
    "client_store": "eager",
    # streaming prefetch: True overlaps superstep N+1's cohort staging with
    # superstep N's compute (depth-1 double buffering); False forces
    # SYNCHRONOUS staging -- the loud fallback for samplers whose next
    # cohort depends on round-N outputs (the driver warns once).
    "stream_prefetch": True,
    # streaming prefetch depth (ISSUE 8 satellite): how many upcoming
    # supersteps' cohorts may be staged ahead of the in-flight one.  The
    # CohortStager ring holds depth+1 slots and fences each slot on its
    # previous private copy, so deeper pipelines stay corruption-safe; 1 =
    # the PR 6 double buffer.  Depth > 1 pays off once per-superstep
    # compute shrinks below the host gather time (real-TPU regime).
    "stream_prefetch_depth": 1,
    # wire codec (ISSUE 8, heterofl_tpu/compress/): compress the client
    # update INSIDE the fused round -- quantise -> ONE global psum ->
    # dequantise, preserving the one-global-psum invariant.  "dense"
    # (default) keeps today's f32 aggregation bit for bit; "int8" =
    # per-leaf stochastic-rounding quantisation with int32 lane-packed
    # accumulation (25% of dense bytes); "signsgd" = 1-bit signs with a
    # per-leaf scale (~19%); "topk" = rotating-block sparsification riding
    # the flat width-mask layout (25%).  Lossy codecs carry an
    # error-feedback residual in the scan state (donated, checkpointed),
    # have explicit tolerance contracts instead of the dense bitwise ones
    # (tests/test_compress.py), and need the fused superstep on the
    # grouped/sliced strategies.
    "wire_codec": "dense",
    # error feedback (ISSUE 8): re-inject each round's compression error
    # into the next round's payload (the residual carry).  True (default)
    # is the convergence-preserving setting; False drops the error -- the
    # A/B the convergence contract test pins.  Ignored by "dense".
    "error_feedback": True,
    # client scheduler (ISSUE 9, heterofl_tpu/sched/): who trains, for how
    # long, and when their update lands.  None (default) = lockstep -- the
    # paper's semantics, bit-identical to the pre-scheduler engines (zero
    # new program arguments).  A dict selects scenario mechanisms, all
    # running inside the fused K-round scan:
    #   {"kind": "uniform"|"trace"|"markov",  # availability schedule
    #    "trace": [[0/1,...],...],    # kind='trace': [rounds, num_users]
    #    "markov": {"p_on": .5, "p_off": .2, "length": 64, "seed": 0},
    #    "deadline": {"min_frac": 0.25},  # straggler local-step truncation
    #    "aggregation": "sync"|"buffered",  # buffered-async (staleness) or
    #    "staleness": 0.5}                  # its mixing coefficient alpha
    # Availability slots that cannot fill surface as -1 (padding) ids --
    # partial participation, not resampling.  Trace/markov schedules are
    # replayable from the config/seed, so checkpoint resume reproduces
    # identical cohorts and streaming prefetch keeps overlapping.  The
    # deadline and buffered modes have explicit contracts (superstep ==
    # sequential with the staleness buffer bit-for-bit; accuracy vs
    # lockstep recorded in MEASUREMENTS.md) instead of the dense bitwise
    # ones; buffered cannot combine with a lossy wire_codec (both add a
    # scan carry) and scenario schedules need a mesh-native strategy.
    "schedule": None,
    # population sampler (ISSUE 11, heterofl_tpu/fed/sampling.py): how the
    # per-round active cohort is drawn from THE one sampling stream
    # (fed.core.round_users).  "prp" (default) draws round r's cohort as
    # the image of [0, num_active) under a keyed pseudorandom-permutation
    # index map (variable-round Feistel + cycle-walking, exact bijection
    # for arbitrary num_users) -- O(active) work, no [num_users] buffer,
    # traceable in-jit; availability rows filter via an O(active x
    # overdraw) draw-then-filter walk with bounded spill to -1 padding.
    # "perm" is the legacy full jax.random.permutation(num_users) draw,
    # bit-for-bit identical to the pre-ISSUE-11 stream (parity tests, old
    # trajectory reproduction).  The two are different streams: switching
    # re-baselines every seeded trajectory, and bench.py refuses to
    # compare records across them without BENCH_ALLOW_STREAM_CHANGE=1.
    "sampler": "prp",
    # schedule commitment (ISSUE 11): None (default) = stateless sampler,
    # the schedule is a pure function of the key stream and streaming
    # prefetch is unconstrained.  An int >= 0 turns on commitment:
    # superstep N+1's cohort is drawn from superstep N-sample_horizon's
    # FETCHED state (fed.sampling.ScheduleCommitment gates the prefetch
    # queue), so an output-dependent sampler keeps the PR 6 staging
    # overlap (horizon 1) instead of forcing stream_prefetch=False.  For
    # the stateless perm/prp samplers the committed schedule is
    # bit-identical to the immediate one (contract-tested).
    "sample_horizon": None,
    # sampled/rolling eval cohort (ISSUE 9 satellite): with
    # client_store='stream', evaluate the per-user Local metrics on a
    # rolling N-user window instead of the whole population -- local eval
    # cost becomes O(eval_cohort), which is what makes eval_interval
    # affordable on a million-user run.  The window advances per eval
    # cadence (deterministic in the epoch, so resume is stable); sBN and
    # Global eval still cover their full sets.  None = whole-population
    # local eval (the pre-scheduler behaviour, warned past 1e5 users).
    "eval_cohort": None,
    # runtime telemetry (ISSUE 10, heterofl_tpu/obs/): "on" folds per-round
    # health probes -- global grad/update norm, per-level participation,
    # wire-codec residual norm, buffered staleness mass, a non-finite leaf
    # counter -- into the fused round programs' metrics pytree, computed
    # in-program from already-reduced values (ZERO new collectives; the
    # staticcheck telemetry variants pin the same one-psum wire budget).
    # "hist" (ISSUE 12) additionally folds the fixed-bucket COHORT
    # histograms in (obs/hist.py: per-client loss, deadline step fraction,
    # level membership, buffered staleness magnitude) -- still zero new
    # collectives, audited at the same budgets.
    # "off" (default) builds bit-identical programs to the pre-obs engines.
    # Needs a mesh-native strategy; the grouped engine needs the fused
    # superstep (superstep_rounds > 1 or client_store='stream').
    "telemetry": "off",
    # population observatory ledger (ISSUE 12, obs/ledger.py): "on"
    # maintains a host-side per-client record -- participation count,
    # last-seen round, cumulative staleness, loss EMA, level history --
    # updated O(active) at each metrics fetch from the cohort uid rows of
    # THE one sampling stream, checkpointed/restored with the run, and
    # snapshotted to ledger.npz for `python -m heterofl_tpu.obs.report`.
    # Resident cost ~27 bytes/user (uint8..uint32 arrays); never touches
    # the compiled programs (telemetry-independent).  Needs a mesh-native
    # strategy and replicated/streaming placement (the sharded slot
    # packing drops the uid ordering the fold consumes).
    "ledger": "off",
    # experiment arms multiplexer (ISSUE 14, heterofl_tpu/multi/): batch E
    # sweep arms into ONE fused superstep program.  None (default) = single
    # trajectory, every program byte-identical to pre-arms.  An int E (or a
    # dict {"count": E, "seeds": [...], "lr_scales": [...]}) vmaps the
    # K-round scan over a leading arms axis: per-arm PRNG streams
    # (fed.core.arm_stream_keys; seed None = the base stream), per-arm LR
    # scales over the shared schedule shape, metrics/eval stacked [E, K,
    # ...], still EXACTLY one global psum per fused round (wire bytes and
    # FLOPs scale linearly in E -- staticcheck arms variants audit this by
    # equality).  Arm i of a batched run is bitwise-identical to a solo
    # arms=1 run with the same seed.  Structural knobs (strategy, codec,
    # placement, schedule kind) stay per-program; unsupported combos --
    # sliced strategy, per-level codec maps, buffered aggregation, the
    # streaming store, grouped 'slices' placement, telemetry with grouped
    # -- refuse loudly.  python -m heterofl_tpu.multi.sweep partitions a
    # grid spec into arm batches x structural launches.
    "arms": None,
    # watchdog knobs (telemetry='on' enables it at warn defaults): a dict
    # {"action": "warn"|"abort"|"rollback"|"off", "spike_factor": 3.0,
    # "window": 8, "max_retries": 3, "backoff": 0.5} -- non-finite params
    # and loss-spikes-vs-rolling-median trip at fetch boundaries with a
    # loud warning ("warn"), a WatchdogError ("abort"), or an automatic
    # rollback (ISSUE 15): restore the newest finite-verifying checkpoint
    # generation, fold a retry salt into the round key stream (the
    # replayed superstep draws a FRESH cohort), retry up to max_retries
    # times with exponential backoff seconds, then escalate to abort.
    "watchdog": None,
    # in-program client-update quarantine (ISSUE 15 tentpole): a per-client
    # finiteness (+ optional update-norm) gate computed inside the fused
    # round from values each device already holds, folded into BOTH the
    # sums and the counts BEFORE the single global psum -- a NaN-poisoned
    # (or norm-exploded) client becomes a zero-count participant and the
    # globals never see its update.  "off" (default) keeps every program
    # bit-identical to the pre-quarantine engines; "on" gates on
    # finiteness only (bit-identical outputs when every update is clean);
    # a dict {"max_norm": R} additionally quarantines updates whose
    # masked L2 norm exceeds R.  The quarantined-client count rides the
    # metrics pytree as the obs_quarantine probe (zero new collectives,
    # same one-psum/wire budgets -- staticcheck quarantine variants).
    "quarantine": "off",
    # checkpoint generations (ISSUE 15): how many rotated checkpoint
    # generations to retain ({tag}_checkpoint.pkl, .g1, .g2, ...).  Every
    # write is fsync-before-rename with a SHA-256 content checksum;
    # resume/rollback fall back generation-by-generation to the newest
    # verifying blob.
    "checkpoint_keep": 3,
    # chaos fault injection (ISSUE 15, heterofl_tpu/chaos/): a list of
    # [round, uid] pairs whose client updates are NaN-poisoned IN-PROGRAM
    # after local training, before aggregation -- the deterministic
    # poisoned-client model the chaos drill and the quarantine/rollback
    # tests exercise.  None (default) leaves every program untouched.
    "chaos_poison": None,
    # run tracing (obs/trace.py): a directory to write a Chrome-trace-event
    # trace.json (PhaseTimer phases + driver events + jax.profiler
    # annotations; open in Perfetto) and a schema'd events.jsonl per run.
    # None = no tracing.  Independent of the probes (host-side only).
    "trace_dir": None,
    "profile_dir": None,  # write a jax.profiler trace of round 2 here
    "synthetic_sizes": None,  # {"train": n, "test": n} for synthetic data
    # Applied LAST by process_control: per-key overrides of any derived field
    # (dict values merge shallowly). E.g. {"num_epochs": {"global": 2},
    # "conv": {"hidden_size": [8, 16]}} -- used by tests and bench harnesses.
    "override": {},
}


def default_cfg() -> Dict[str, Any]:
    return copy.deepcopy(DEFAULT_CFG)


def parse_control_name(control_name: str) -> Dict[str, str]:
    """Split an underscore-separated control string into the 9 control fields.

    Mirrors ``src/train_classifier_fed.py:27-29``.
    """
    if control_name in (None, "None", ""):
        return {}
    parts = control_name.split("_")
    if len(parts) != len(CONTROL_KEYS):
        raise ValueError(
            f"control string must have {len(CONTROL_KEYS)} fields "
            f"{CONTROL_KEYS}, got {len(parts)}: {control_name!r}"
        )
    return dict(zip(CONTROL_KEYS, parts))


def control_name_of(control: Dict[str, str]) -> str:
    """Inverse of :func:`parse_control_name` (ref train_classifier_fed.py:30).

    Joins in canonical ``CONTROL_KEYS`` order (not dict insertion order) so a
    reordered dict still produces the canonical tag."""
    return "_".join(control[k] for k in CONTROL_KEYS)


def make_model_tag(seed: int, cfg: Dict[str, Any]) -> str:
    """Experiment tag keying checkpoints/results (ref train_classifier_fed.py:41-42)."""
    parts = [str(seed), cfg["data_name"], cfg.get("subset", ""), cfg["model_name"], cfg["control_name"]]
    return "_".join(x for x in parts if x)


def _fix_rate_vector(mode_rate: List[float], proportion: List[int], num_users: int) -> List[float]:
    """Static per-user rate assignment for ``fix`` mode.

    Exact parity with src/utils.py:134-144: each level gets
    ``num_users // sum(proportion) * proportion_i`` users in level order, and
    any remainder is filled with the *last* (smallest) level's rate.
    """
    if num_users < sum(proportion):
        raise ValueError(
            f"fix mode needs num_users >= sum of proportions: {num_users} users "
            f"< {sum(proportion)} (the reference crashes with an opaque "
            f"IndexError here); reduce the number of levels or add users")
    num_users_proportion = num_users // sum(proportion)
    model_rate: List[float] = []
    for i in range(len(mode_rate)):
        model_rate += list(np.repeat(mode_rate[i], num_users_proportion * proportion[i]))
    model_rate = model_rate + [model_rate[-1] for _ in range(num_users - len(model_rate))]
    return [float(r) for r in model_rate]


def process_control(cfg: Dict[str, Any]) -> Dict[str, Any]:
    """Expand ``cfg['control']`` into every derived hyperparameter.

    Parity with ``src/utils.py:113-215``. Returns a new cfg dict (input is not
    mutated). Raises ``ValueError`` on invalid modes, like the reference.
    """
    cfg = copy.deepcopy(cfg)
    ctl = cfg["control"]
    cfg["control_name"] = control_name_of(ctl)
    cfg["model_split_rate"] = dict(MODEL_SPLIT_RATE)
    cfg["fed"] = int(ctl["fed"])
    cfg["num_users"] = int(ctl["num_users"])
    cfg["frac"] = float(ctl["frac"])
    cfg["data_split_mode"] = ctl["data_split_mode"]
    cfg["model_split_mode"] = ctl["model_split_mode"]
    cfg["model_mode"] = ctl["model_mode"]
    cfg["norm"] = ctl["norm"]
    cfg["scale"] = bool(int(ctl["scale"]))
    cfg["mask"] = bool(int(ctl["mask"]))
    cfg["global_model_mode"] = cfg["model_mode"][0]
    cfg["global_model_rate"] = cfg["model_split_rate"][cfg["global_model_mode"]]
    model_mode = cfg["model_mode"].split("-")
    mode_rate, proportion = [], []
    for m in model_mode:
        mode_rate.append(cfg["model_split_rate"][m[0]])
        proportion.append(int(m[1:]))
    if cfg["model_split_mode"] == "dynamic":
        cfg["model_rate"] = mode_rate
        cfg["proportion"] = (np.array(proportion) / sum(proportion)).tolist()
    elif cfg["model_split_mode"] == "fix":
        cfg["model_rate"] = _fix_rate_vector(mode_rate, proportion, cfg["num_users"])
    else:
        raise ValueError("Not valid model split mode")
    # Architecture tables (ref src/utils.py:147-149).
    cfg["conv"] = {"hidden_size": [64, 128, 256, 512]}
    cfg["resnet"] = {"hidden_size": [64, 128, 256, 512]}
    cfg["transformer"] = {
        "embedding_size": 256,
        "num_heads": 8,
        "hidden_size": 512,
        "num_layers": 4,
        "dropout": 0.2,
    }
    # Per-dataset hyperparameters (ref src/utils.py:150-212).
    data_name = cfg["data_name"]
    split = cfg["data_split_mode"]
    if data_name in ("MNIST", "FashionMNIST", "EMNIST", "Omniglot"):
        cfg["data_shape"] = [105, 105, 1] if data_name == "Omniglot" else [28, 28, 1]  # NHWC
        cfg["optimizer_name"] = "SGD"
        cfg["lr"] = 1e-2
        cfg["momentum"] = 0.9
        cfg["weight_decay"] = 5e-4
        cfg["scheduler_name"] = "MultiStepLR"
        cfg["factor"] = 0.1
        if split == "iid":
            cfg["num_epochs"] = {"global": 200, "local": 5}
            cfg["batch_size"] = {"train": 10, "test": 50}
            cfg["milestones"] = [100]
        elif "non-iid" in split:
            cfg["num_epochs"] = {"global": 400, "local": 5}
            cfg["batch_size"] = {"train": 10, "test": 50}
            cfg["milestones"] = [200]
        elif split == "none":
            cfg["num_epochs"] = 200
            cfg["batch_size"] = {"train": 100, "test": 500}
            cfg["milestones"] = [100]
        else:
            raise ValueError("Not valid data_split_mode")
    elif data_name in ("ImageNet", "ImageFolder"):
        # shape is provisional; process_dataset overwrites it from the loaded
        # tree (folder datasets have data-defined geometry)
        cfg["data_shape"] = [224, 224, 3]
        cfg["optimizer_name"] = "SGD"
        cfg["lr"] = 1e-1
        cfg["momentum"] = 0.9
        cfg["weight_decay"] = 5e-4
        cfg["scheduler_name"] = "MultiStepLR"
        cfg["factor"] = 0.1
        if split == "iid" or "non-iid" in split:
            cfg["num_epochs"] = {"global": 400, "local": 5}
            cfg["batch_size"] = {"train": 10, "test": 50}
            cfg["milestones"] = [150, 250]
        elif split == "none":
            cfg["num_epochs"] = 400
            cfg["batch_size"] = {"train": 100, "test": 500}
            cfg["milestones"] = [150, 250]
        else:
            raise ValueError("Not valid data_split_mode")
    elif data_name in ("CIFAR10", "CIFAR100"):
        cfg["data_shape"] = [32, 32, 3]
        cfg["optimizer_name"] = "SGD"
        cfg["lr"] = 1e-1
        cfg["momentum"] = 0.9
        cfg["weight_decay"] = 5e-4
        cfg["scheduler_name"] = "MultiStepLR"
        cfg["factor"] = 0.1
        if split == "iid":
            cfg["num_epochs"] = {"global": 400, "local": 5}
            cfg["batch_size"] = {"train": 10, "test": 50}
            cfg["milestones"] = [150, 250]
        elif "non-iid" in split:
            cfg["num_epochs"] = {"global": 800, "local": 5}
            cfg["batch_size"] = {"train": 10, "test": 50}
            cfg["milestones"] = [300, 500]
        elif split == "none":
            cfg["num_epochs"] = 400
            cfg["batch_size"] = {"train": 100, "test": 500}
            cfg["milestones"] = [150, 250]
        else:
            raise ValueError("Not valid data_split_mode")
    elif data_name in ("PennTreebank", "WikiText2", "WikiText103"):
        cfg["optimizer_name"] = "SGD"
        cfg["lr"] = 1e-1
        cfg["momentum"] = 0.9
        cfg["weight_decay"] = 5e-4
        cfg["scheduler_name"] = "MultiStepLR"
        cfg["factor"] = 0.1
        cfg["bptt"] = 64
        cfg["mask_rate"] = 0.15
        if split == "iid":
            cfg["num_epochs"] = {"global": 200, "local": 1}
            cfg["batch_size"] = {"train": 100, "test": 10}
            cfg["milestones"] = [50, 100]
        elif split == "none":
            cfg["num_epochs"] = 100
            cfg["batch_size"] = {"train": 100, "test": 100}
            cfg["milestones"] = [25, 50]
        else:
            raise ValueError("Not valid data_split_mode")
    else:
        raise ValueError("Not valid dataset")
    for k, v in (cfg.get("override") or {}).items():
        if isinstance(v, dict) and isinstance(cfg.get(k), dict):
            cfg[k] = {**cfg[k], **v}
        else:
            cfg[k] = v
    # stale-config lint (ISSUE 8/18): unknown knob values AND cross-axis
    # conflicts fail HERE, at config validation, with the PR 6
    # loud-ValueError convention -- never as a silent fallback or a
    # mid-run refusal.  The chain below is THE canonical validator order;
    # staticcheck's config-lattice pass replays it point by point, so a
    # combination no validator refuses must be audited-green.
    for _name, fn in validator_chain():
        fn(cfg)
    return cfg


def validator_chain():
    """The canonical ``(name, resolve_*)`` validator sequence, in the order
    ``process_control`` applies it (ISSUE 18).  Axis validators run first
    (each owning its knob's domain), then the subsystem validators that
    additionally own that subsystem's cross-axis conflicts.  staticcheck's
    lattice pass (``staticcheck/lattice.py``) invokes exactly this chain to
    prove every refused config point raises from exactly one validator at
    config-resolution time -- keep additions HERE, never as driver-only
    checks (the lattice classifies a mid-run-only refusal as a finding).

    Every validator is jax-free and takes the full cfg dict; subsystem
    packages stay import-light so this chain never boots a backend."""
    from .chaos import resolve_poison_cfg
    from .compress import resolve_codec_cfg
    from .fed.sampling import resolve_sampler_cfg
    from .multi import resolve_arms_cfg
    from .obs import (resolve_ledger_cfg, resolve_quarantine_cfg,
                      resolve_telemetry_cfg)
    from .sched import resolve_schedule_cfg

    return [
        ("resolve_strategy_cfg", resolve_strategy_cfg),
        ("resolve_placement_cfg", resolve_placement_cfg),
        ("resolve_store_cfg", resolve_store_cfg),
        ("resolve_superstep_cfg", resolve_superstep_cfg),
        ("resolve_codec_cfg", resolve_codec_cfg),
        ("resolve_prefetch_depth", resolve_prefetch_depth),
        ("resolve_sampler_cfg", resolve_sampler_cfg),
        ("resolve_schedule_cfg", resolve_schedule_cfg),
        ("resolve_eval_cohort", resolve_eval_cohort),
        ("resolve_telemetry_cfg", resolve_telemetry_cfg),
        ("resolve_ledger_cfg", resolve_ledger_cfg),
        ("resolve_quarantine_cfg", resolve_quarantine_cfg),
        ("resolve_checkpoint_keep", resolve_checkpoint_keep),
        ("resolve_poison_cfg", resolve_poison_cfg),
        ("resolve_arms_cfg", resolve_arms_cfg),
    ]


def resolve_strategy_cfg(cfg: Dict[str, Any]) -> str:
    """Validate ``cfg['strategy']`` and return it (ISSUE 18).  THE one
    validator of the engine axis: an unknown strategy fails at config
    resolution, never as a driver-construction error."""
    strategy = cfg.get("strategy", "masked") or "masked"
    if strategy not in STRATEGIES:
        raise ValueError(f"Not valid strategy: {strategy!r} "
                         f"(one of {STRATEGIES})")
    return strategy


def resolve_placement_cfg(cfg: Dict[str, Any]):
    """Validate ``cfg['data_placement']`` / ``cfg['level_placement']`` and
    return ``(data_placement, level_placement)`` (ISSUE 18).  THE one
    validator of the placement axis, including its engine cross-checks:

    - ``grouped`` needs replicated data placement (a level's clients span
      the whole clients axis) -- promoted from the grouped constructor;
    - ``level_placement='slices'`` is the grouped engine's per-level
      device partition; the other engines have no level sub-meshes;
    - the ``sliced`` host twin takes neither placement knob -- previously
      both were silently ignored (exactly the silent fallback the lattice
      pass exists to refuse)."""
    strategy = resolve_strategy_cfg(cfg)
    dp = cfg.get("data_placement", "replicated") or "replicated"
    lp = cfg.get("level_placement", "span") or "span"
    if dp not in DATA_PLACEMENTS:
        raise ValueError(f"Not valid data_placement: {dp!r} "
                         f"(one of {DATA_PLACEMENTS})")
    if lp not in LEVEL_PLACEMENTS:
        raise ValueError(f"Not valid level_placement: {lp!r} "
                         f"(one of {LEVEL_PLACEMENTS})")
    if strategy == "grouped" and dp == "sharded":
        raise ValueError(
            "Not valid data_placement='sharded' with strategy='grouped': "
            "a level's clients span the whole clients axis, so the grouped "
            "engine packs slot schedules from the replicated store; use "
            "strategy='masked' for sharded placement")
    if lp == "slices" and strategy != "grouped":
        raise ValueError(
            f"Not valid level_placement='slices' with strategy="
            f"{strategy!r}: the slices partition assigns each rate level "
            f"its own clients-axis device rows, which only the grouped "
            f"engine's per-level dense programs consume")
    if strategy == "sliced" and dp != "replicated":
        raise ValueError(
            f"Not valid data_placement={dp!r} with strategy='sliced': the "
            f"host-orchestrated debug twin replays the reference loop and "
            f"ignores device placement -- the knob would silently no-op")
    return dp, lp


def resolve_store_cfg(cfg: Dict[str, Any]) -> str:
    """Validate ``cfg['client_store']`` and return it (ISSUE 18).  THE one
    validator of the store axis: unknown modes and the stream x sliced
    conflict (promoted from the driver) fail at config resolution."""
    strategy = resolve_strategy_cfg(cfg)
    store = cfg.get("client_store", "eager") or "eager"
    if store not in CLIENT_STORES:
        raise ValueError(f"Not valid client_store: {store!r} "
                         f"(one of {CLIENT_STORES})")
    if store == "stream" and strategy == "sliced":
        raise ValueError(
            "Not valid client_store='stream' with strategy='sliced': the "
            "cohort pipeline stages through the mesh-native engines' "
            "superstep programs ('masked' or 'grouped')")
    if store == "stream" and cfg.get("data_placement") == "sharded":
        raise ValueError(
            "Not valid data_placement='sharded' with client_store="
            "'stream': the streaming population stages per-superstep "
            "cohorts through its own placement path, so the sharded "
            "slot packing would silently no-op -- use replicated")
    return store


def resolve_superstep_cfg(cfg: Dict[str, Any]) -> int:
    """Validate ``cfg['superstep_rounds']`` and its cross-axis contracts,
    returning the round count K (ISSUE 18).  THE one validator of the pod
    axis; the ``metrics_fetch_every`` / Plateau / streaming interplays are
    promoted from the driver (``entry/common.py``), where they refused at
    construction -- same typed messages, now at config-resolution time."""
    raw = cfg.get("superstep_rounds", 1)
    if raw is None:
        raw = 1
    if not isinstance(raw, int) or isinstance(raw, bool) or raw < 1:
        raise ValueError(f"Not valid superstep_rounds: {raw!r} "
                         f"(an int >= 1)")
    K = raw
    strategy = resolve_strategy_cfg(cfg)
    store = resolve_store_cfg(cfg)
    fetch_every = int(cfg.get("metrics_fetch_every", 1) or 1)
    eval_iv = max(1, int(cfg.get("eval_interval", 1) or 1))
    if K > 1:
        if strategy == "sliced":
            raise ValueError(
                "Not valid superstep_rounds>1 with strategy='sliced': the "
                "fused superstep needs a mesh-native engine ('masked' or "
                "'grouped'); 'sliced' is the host-orchestrated debug twin")
        if fetch_every != 1 and fetch_every % K:
            raise ValueError(
                f"Not valid metrics_fetch_every={fetch_every} with "
                f"superstep_rounds={K}: a superstep fetches its metrics "
                f"exactly once per K rounds (use 1 for synchronous fetch "
                f"or exactly {K}; larger multiples would defer metrics "
                f"past the superstep's checkpoint)")
        if fetch_every > K:
            raise ValueError(
                f"Not valid metrics_fetch_every={fetch_every} with "
                f"superstep_rounds={K}: each superstep's eval metrics "
                f"would be deferred past its checkpoint, silently "
                f"disabling best-checkpoint tracking (pivot never fresh); "
                f"use 1 or {K}")
        if cfg.get("scheduler_name") == "ReduceLROnPlateau" and eval_iv % K:
            raise ValueError(
                f"Not valid scheduler_name='ReduceLROnPlateau' with "
                f"superstep_rounds={K} and eval_interval={eval_iv}: "
                f"Plateau needs eval boundaries on superstep boundaries "
                f"(eval_interval % superstep_rounds == 0) -- a "
                f"mid-superstep eval would require an LR step inside the "
                f"compiled scan")
    elif store == "stream" and fetch_every > 1:
        raise ValueError(
            f"Not valid metrics_fetch_every={fetch_every} with "
            f"client_store='stream' at superstep_rounds=1: streaming "
            f"routes through the (k=1) superstep path, whose "
            f"best-checkpoint pivot needs a synchronous fetch; use 1")
    return K


def resolve_prefetch_depth(cfg: Dict[str, Any]) -> int:
    """Validate ``cfg['stream_prefetch_depth']`` and return it (ISSUE 8
    satellite).  THE one validator: process_control applies it, and the
    engines/driver (often built directly from a cfg dict, bypassing
    process_control) re-apply it rather than coercing bad values to the
    default."""
    depth = cfg.get("stream_prefetch_depth", 1)
    if depth is None:
        return 1
    if not isinstance(depth, int) or isinstance(depth, bool) or depth < 1:
        raise ValueError(f"Not valid stream_prefetch_depth: {depth!r} "
                         f"(an int >= 1)")
    return depth


def resolve_checkpoint_keep(cfg: Dict[str, Any]) -> int:
    """Validate ``cfg['checkpoint_keep']`` and return it (ISSUE 15).  THE
    one validator: process_control applies it and the driver re-applies it
    -- a malformed value fails loudly at config time, never as a silent
    single-generation fallback mid-run.  Lives here (not in
    utils.checkpoint) to keep this module's jax-free import contract."""
    keep = cfg.get("checkpoint_keep", 3)
    if keep is None:
        return 3
    if not isinstance(keep, int) or isinstance(keep, bool) or keep < 1:
        raise ValueError(f"Not valid checkpoint_keep: {keep!r} (an int >= 1 "
                         f"checkpoint generations to retain)")
    return keep


def resolve_eval_cohort(cfg: Dict[str, Any]):
    """Validate ``cfg['eval_cohort']`` and return it (ISSUE 9 satellite).
    THE one validator: process_control applies it and the driver re-applies
    it (cross-field constraints -- streaming store, vision models -- live
    in the driver, which owns those facts)."""
    ec = cfg.get("eval_cohort")
    if ec is None:
        return None
    if not isinstance(ec, int) or isinstance(ec, bool) or ec < 1:
        raise ValueError(f"Not valid eval_cohort: {ec!r} (an int >= 1, the "
                         f"rolling Local-eval window size, or None for "
                         f"whole-population local eval)")
    users = cfg.get("num_users")
    if users is not None and ec > int(users):
        raise ValueError(f"Not valid eval_cohort: {ec} exceeds "
                         f"num_users={users} (drop eval_cohort for "
                         f"whole-population local eval)")
    # eval-cohort cross-checks (ISSUE 18): promoted from the driver.  This
    # validator OWNS the eval-cohort axis in the staticcheck lattice.
    if (cfg.get("client_store", "eager") or "eager") != "stream":
        raise ValueError(
            f"Not valid eval_cohort={ec} with client_store='eager': the "
            f"eager store already densifies the population, so its local "
            f"eval is O(num_users) either way -- eval_cohort needs "
            f"client_store='stream'")
    if cfg.get("model_name") == "transformer":
        raise ValueError(
            f"Not valid eval_cohort={ec} with model_name='transformer': "
            f"eval_cohort samples the per-user Local eval, which only "
            f"vision experiments run (LM evaluates Global only)")
    return ec


def ceil_width(size: int, rate: float) -> int:
    """Active width of a sliced dimension: ``ceil(size * rate)`` (ref fed.py:47)."""
    return int(math.ceil(size * rate))


def scaled_hidden(hidden_size: List[int], model_rate: float) -> List[int]:
    """Per-layer widths of a sub-model (ref models/conv.py:77)."""
    return [ceil_width(x, model_rate) for x in hidden_size]
