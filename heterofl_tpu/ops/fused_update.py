"""Fused masked-SGD optimizer epilogue + flat scan carry for the hot step.

The local-step tail of both round engines (``parallel/round_engine.py``,
``_local_train_vision``/``_local_train_lm``) was a long chain of tiny
elementwise ops executed 250 times per round: grad mean-normalise, width
``param_mask`` multiply, ``clip_by_global_norm``, the SGD momentum /
weight-decay update, and (vision) the two ``has``-gated ``jnp.where``
tree_maps that skip all-padding batches -- all PER LEAF, and the
``lax.scan`` carried every param/momentum leaf separately (one loop-carry
copy + several kernels per leaf per step).  At HeteroFL's shapes the round
is per-step-LATENCY-bound, not FLOP-bound (MEASUREMENTS.md: ~20 ms/step,
BN stack ~35-40%, bf16 buys nothing), so every extra kernel in the scan
body is a direct tax on the critical path -- the kernel-layer twin of the
comms overheads targeted by arXiv:1610.05492.

``cfg['fused_update']`` replaces that tail with a fused masked-update
primitive over ONE flattened-tree buffer:

* :class:`FlatSpec` packs a param tree into a single contiguous f32 vector
  (row-major leaf order; each leaf a contiguous segment, so per-leaf views
  are zero-copy slices).  The engines carry ``(params_flat, momentum_flat)``
  through the scan -- the carry tuple shrinks from O(leaves) to O(1)
  buffers with a pinned packed layout, and the model fwd/bwd sees ordinary
  leaf views unflattened inside the step.
* ``'xla'`` (what ``True`` resolves to off-TPU): every numeric op of the
  epilogue stays PER-LEAF -- literally the reference chain's ops on the
  reference chain's arrays (a reduce over a flat-buffer view and a
  flat-concat elementwise tail were both measured to lower with a
  different association/contraction on XLA:CPU) -- and the fusion win
  comes from the flat carry alone.  Bit-identity vs the reference chain
  is proven by tests for the full engine matrix at the repo's standard
  test config (conv + transformer; masked x replicated/sharded, grouped
  x span/slices, K in {1, 8}, with/without the eval mask).  On much
  deeper bodies (ResNet-18: 56 leaves, ~400 fusions/step) XLA's global
  fusion choices shift reduce emission by 1 ulp somewhere in the loop
  body, which SGD then amplifies chaotically -- a single local step is
  still bitwise exact (pinned by test), multi-round trajectories agree
  the way the masked-vs-sliced engines do (float association level).
* ``'pallas'`` (what ``True`` resolves to on TPU): a flattened-tree Pallas
  TPU kernel over the lane-packed ``[rows, 128]`` reshape -- phase 0
  accumulates the global-norm sum of squares in VMEM scratch (the
  two-phase reduction), phase 1 is the single elementwise update pass.
  Elementwise bits match the reference chain exactly; the norm reduction
  is associated per block instead of per leaf, so when clipping actually
  engages the scale may differ in the last ulp (tests pin bit-identity in
  the no-clip regime and value agreement under clipping).

Only SGD (momentum + weight decay, the optimizer every federated reference
config uses) is fused; other optimizers keep the reference chain.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp


#: lane width of the flattened-tree packing (TPU vector lane count)
LANE = 128


def resolve_fused_mode(cfg: Dict[str, Any]) -> Optional[str]:
    """Map ``cfg['fused_update']`` to an implementation name or None.

    ``True`` (the default) resolves by backend: the Pallas kernel on TPU,
    the XLA fallback elsewhere.  ``False`` keeps the reference op chain.
    Non-SGD optimizers always keep the reference chain (the fused primitive
    implements exactly torch-parity SGD momentum + weight decay).
    """
    fu = cfg.get("fused_update", True)
    if not fu or cfg.get("optimizer_name") != "SGD":
        return None
    if fu is True:
        return "pallas" if jax.default_backend() == "tpu" else "xla"
    if fu in ("xla", "pallas"):
        return fu
    raise ValueError(f"Not valid fused_update: {fu!r} "
                     f"(use True/False/'xla'/'pallas')")


class FlatSpec:
    """Static packing of a ``{name: array}`` tree into one flat f32 vector.

    Leaf order is sorted-key order -- the same order jax flattens a dict,
    hence the same leaf order ``clip_by_global_norm`` reduces in, which is
    what keeps the fused norm bit-compatible with the reference chain.
    Instances are trace-time constants (shapes only)."""

    def __init__(self, shapes: Dict[str, Tuple[int, ...]]):
        self.names = sorted(shapes)
        self.shapes = {k: tuple(shapes[k]) for k in self.names}
        self.sizes = {}
        self.offsets = {}
        off = 0
        for k in self.names:
            sz = 1
            for d in self.shapes[k]:
                sz *= d
            self.sizes[k] = sz
            self.offsets[k] = off
            off += sz
        self.total = off

    @classmethod
    def of(cls, tree: Dict[str, jnp.ndarray]) -> "FlatSpec":
        return cls({k: v.shape for k, v in tree.items()})

    def flatten(self, tree: Dict[str, jnp.ndarray]) -> jnp.ndarray:
        return jnp.concatenate(
            [jnp.ravel(tree[k]).astype(jnp.float32) for k in self.names])

    def unflatten(self, flat: jnp.ndarray) -> Dict[str, jnp.ndarray]:
        return {k: self.leaf(flat, k) for k in self.names}

    def leaf(self, flat: jnp.ndarray, k: str) -> jnp.ndarray:
        off = self.offsets[k]
        return flat[off:off + self.sizes[k]].reshape(self.shapes[k])


# ---------------------------------------------------------------------------
# the XLA fallback: per-leaf norm terms + one flat elementwise chain
# ---------------------------------------------------------------------------

def _xla_flat(spec, pf, grads, bf, masks, denom, lr, momentum, wd, max_norm,
              has):
    from ..utils.optim import clip_by_global_norm

    # every numeric op stays PER-LEAF -- literally the reference chain's
    # ops on the reference chain's arrays, so the whole update is the same
    # f32 bit pattern by construction (both a reduce over a flat-buffer
    # view and a flat-concat elementwise tail were measured to lower with
    # different association/contraction on XLA:CPU); the fusion win comes
    # from the FLAT CARRY (O(1) loop-carried buffers instead of O(leaves),
    # zero-copy leaf views in, one flatten out)
    pt, bt = spec.unflatten(pf), spec.unflatten(bf)
    gm = {k: (grads[k] / denom) * masks[k] for k in spec.names}
    gm, _ = clip_by_global_norm(gm, max_norm)
    nb = {k: momentum * bt[k] + gm[k] + wd * pt[k] for k in spec.names}
    np_ = {k: pt[k] - lr * nb[k] for k in spec.names}
    if has is not None:
        np_ = {k: jnp.where(has, np_[k], pt[k]) for k in spec.names}
        nb = {k: jnp.where(has, nb[k], bt[k]) for k in spec.names}
    return spec.flatten(np_), spec.flatten(nb)


# ---------------------------------------------------------------------------
# the Pallas TPU kernel: two-phase norm reduction + one elementwise pass
# ---------------------------------------------------------------------------

def _fused_sgd_kernel(g_ref, p_ref, b_ref, m_ref, s_ref, p_out, b_out, acc,
                      *, momentum: float, wd: float, max_norm: float,
                      rows_total: int, block_rows: int):
    from jax.experimental import pallas as pl

    phase, i = pl.program_id(0), pl.program_id(1)

    @pl.when(jnp.logical_and(phase == 0, i == 0))
    def _():
        acc[:] = jnp.zeros_like(acc)

    # block-padding rows may hold undefined VMEM: `where` them out, never
    # multiply (the pallas_norm.py lesson)
    row = jax.lax.broadcasted_iota(jnp.int32, (block_rows, 1), 0) \
        + i * block_rows
    rowmask = row < rows_total
    denom, lr, has = s_ref[0, 0], s_ref[0, 1], s_ref[0, 2]
    gm = jnp.where(rowmask, (g_ref[:] / denom) * m_ref[:], 0.0)

    @pl.when(phase == 0)
    def _():
        acc[0, 0] += jnp.sum(gm * gm)

    @pl.when(phase == 1)
    def _():
        total = jnp.sqrt(acc[0, 0])
        scale = jnp.minimum(1.0, max_norm / (total + 1e-6))
        pv = jnp.where(rowmask, p_ref[:], 0.0)
        bv = jnp.where(rowmask, b_ref[:], 0.0)
        nb = momentum * bv + gm * scale + wd * pv
        pn = pv - lr * nb
        keep = has > 0.0
        p_out[:] = jnp.where(keep, pn, pv)
        b_out[:] = jnp.where(keep, nb, bv)


def _pallas_flat(spec, pf, grads, bf, masks, denom, lr, momentum, wd,
                 max_norm, has, block_rows, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    gf, mf = spec.flatten(grads), spec.flatten(masks)
    rows = -(-spec.total // LANE)
    pad = rows * LANE - spec.total

    def pack(flat):
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros(pad, jnp.float32)])
        return flat.reshape(rows, LANE)

    has_val = jnp.float32(1.0) if has is None else has.astype(jnp.float32)
    scal = jnp.stack([denom, lr.astype(jnp.float32), has_val]).reshape(1, 3)
    bm = min(block_rows, max(1, rows))
    nm = pl.cdiv(rows, bm)
    p2, b2 = pl.pallas_call(
        partial(_fused_sgd_kernel, momentum=momentum, wd=wd,
                max_norm=max_norm, rows_total=rows, block_rows=bm),
        grid=(2, nm),
        in_specs=[
            pl.BlockSpec((bm, LANE), lambda p, i: (i, 0)),
            pl.BlockSpec((bm, LANE), lambda p, i: (i, 0)),
            pl.BlockSpec((bm, LANE), lambda p, i: (i, 0)),
            pl.BlockSpec((bm, LANE), lambda p, i: (i, 0)),
            pl.BlockSpec((1, 3), lambda p, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, LANE), lambda p, i: (i, 0)),
            pl.BlockSpec((bm, LANE), lambda p, i: (i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, LANE), jnp.float32),
            jax.ShapeDtypeStruct((rows, LANE), jnp.float32),
        ],
        scratch_shapes=[pltpu.VMEM((1, 1), jnp.float32)],
        interpret=interpret,
    )(pack(gf), pack(pf), pack(bf), pack(mf), scal)
    return p2.reshape(-1)[:spec.total], b2.reshape(-1)[:spec.total]


# ---------------------------------------------------------------------------
# entry points
# ---------------------------------------------------------------------------

def fused_sgd_flat(spec: FlatSpec, p_flat, grads: Dict[str, jnp.ndarray],
                   b_flat, masks: Dict[str, jnp.ndarray],
                   n_glob, lr, *, momentum: float, weight_decay: float,
                   max_norm: float = 1.0, has=None, mode: str = "xla",
                   block_rows: int = 256, interpret: Optional[bool] = None
                   ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """One fused masked-SGD step over the flat carry:
    ``(new_params_flat, new_momentum_flat)``.

    ``p_flat``/``b_flat`` are the packed carry buffers; ``grads``/``masks``
    stay trees (grads are differentiated per-leaf so the norm terms reduce
    over the same arrays, in the same order, as the reference chain).
    Semantics are exactly the reference op chain over the packed tree::

        g   = (g / max(n_glob, 1e-6)) * mask          # mean-normalise+mask
        g   = g * min(1, 1 / (||g||_2 + 1e-6))        # clip_by_global_norm
        buf = momentum * buf + g + weight_decay * p   # torch SGD
        p   = p - lr * buf
        p, buf = where(has, new, old)                 # all-padding skip

    ``has=None`` skips the gating (the LM path).  ``mode``: 'xla' or
    'pallas'; ``interpret=None`` runs the real kernel on TPU and the
    interpreter elsewhere (the CPU test mesh).
    """
    # staticcheck: allow(no-asarray): traced-value dtype coercion inside the
    # jitted step (n_glob/lr are already on device; no host wrap happens)
    denom = jnp.maximum(jnp.asarray(n_glob, jnp.float32), 1e-6)
    lr = jnp.asarray(lr, jnp.float32)  # staticcheck: allow(no-asarray): traced dtype coercion
    if mode == "xla":
        return _xla_flat(spec, p_flat, grads, b_flat, masks, denom, lr,
                         momentum, weight_decay, max_norm, has)
    if mode == "pallas":
        if interpret is None:
            interpret = jax.default_backend() != "tpu"
        return _pallas_flat(spec, p_flat, grads, b_flat, masks, denom, lr,
                            momentum, weight_decay, max_norm, has,
                            block_rows, interpret)
    raise ValueError(f"Not valid fused-update mode: {mode!r}")


def masked_sgd_step(params: Dict[str, jnp.ndarray],
                    grads: Dict[str, jnp.ndarray],
                    bufs: Dict[str, jnp.ndarray],
                    masks: Dict[str, jnp.ndarray],
                    n_glob, lr, *, momentum: float, weight_decay: float,
                    max_norm: float = 1.0, has=None, mode: str = "xla",
                    block_rows: int = 256,
                    interpret: Optional[bool] = None
                    ) -> Tuple[Dict[str, jnp.ndarray], Dict[str, jnp.ndarray]]:
    """Tree-level wrapper of :func:`fused_sgd_flat` (kernel unit tests and
    one-off callers; the engines keep the flat buffers in the scan carry
    and call the flat form directly)."""
    spec = FlatSpec.of(params)
    np_, nb = fused_sgd_flat(
        spec, spec.flatten(params), grads, spec.flatten(bufs), masks,
        n_glob, lr, momentum=momentum, weight_decay=weight_decay,
        max_norm=max_norm, has=has, mode=mode, block_rows=block_rows,
        interpret=interpret)
    return spec.unflatten(np_), spec.unflatten(nb)
