"""Quantisation kernels for the wire codecs (ISSUE 8).

Integer *lane packing* is what turns "int8 quantisation" into actual wire
bytes under the one-psum contract: each device packs its quantised values
into the sub-fields of int32 words, the ONE global psum adds the words,
and because every lane is sized so the cross-device lane sums cannot
produce a carry, word addition IS independent per-lane integer
accumulation -- "int8 on the wire, int32 in the accumulator".  The psum
operand aval (``int32[ceil(N/lanes_per_word)]``) is then literally the
compressed payload, which is what lets ``staticcheck/wire.py`` price the
compressed round by equality exactly like the dense one.

The quantise+pack hot pass also has a Pallas TPU fast path mirroring
``ops/fused_update.py``'s flat-tree layout: one kernel over the
lane-packed ``[rows, 128]`` reshape fuses scale/noise/clip/round and the
4-lane pack into a single VMEM pass (off-TPU it runs in interpreter mode
for tests; the XLA path is the default elsewhere and is bit-identical by
construction -- both are pure integer/float elementwise chains).
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from .fused_update import LANE


def pack_lanes(q: jnp.ndarray, lane_bits: int) -> jnp.ndarray:
    """Pack flat int32 values ``q`` (each in ``[0, 2**lane_bits)``) into
    int32 words, ``32 // lane_bits`` consecutive values per word (flat
    order preserved; tail padded with zero lanes)."""
    per = 32 // lane_bits
    n = q.shape[0]
    pad = (-n) % per
    if pad:
        q = jnp.concatenate([q, jnp.zeros(pad, jnp.int32)])
    q = q.reshape(-1, per)
    w = q[:, 0]
    for i in range(1, per):
        w = jnp.bitwise_or(w, jnp.left_shift(q[:, i], i * lane_bits))
    return w


def unpack_lanes(w: jnp.ndarray, lane_bits: int, n: int) -> jnp.ndarray:
    """Inverse of :func:`pack_lanes` on (possibly psum-accumulated) words:
    returns the first ``n`` int32 lane values.  The arithmetic right shift
    sign-fills on a negative top lane; the mask strips the fill, so lane
    extraction is exact as long as no cross-device lane sum overflowed its
    ``lane_bits`` (the codecs size their lanes to guarantee that)."""
    per = 32 // lane_bits
    mask = (1 << lane_bits) - 1
    cols = [jnp.bitwise_and(jnp.right_shift(w, i * lane_bits), mask)
            for i in range(per)]
    return jnp.stack(cols, axis=1).reshape(-1)[:n]


def stochastic_round(x: jnp.ndarray, key: jax.Array) -> jnp.ndarray:
    """Unbiased stochastic rounding: ``floor(x + U[0,1))`` -- E[result] = x.
    The quantisation primitive of the int8 codec (deterministic rounding
    would bias every round the same way; with error feedback the stochastic
    form keeps the per-round bias zero-mean)."""
    return jnp.floor(x + jax.random.uniform(key, x.shape, jnp.float32))


# ---------------------------------------------------------------------------
# fused quantise + 4-lane pack (the int8 codec's hot pass)
# ---------------------------------------------------------------------------

def _quant_pack_xla(x, scale, key, qmax: int, bias: int):
    q = stochastic_round(x / scale, key)
    q = jnp.clip(q, -qmax, qmax).astype(jnp.int32)
    return pack_lanes(q + bias, 8), q


def _quant_pack_kernel(x_ref, s_ref, u_ref, w_out, q_out, *, qmax: int,
                       bias: int):
    # one elementwise pass: scale -> stochastic round -> clip -> bias ->
    # 4-lane pack (the [bm, 128] block reshapes to [bm, 32, 4] word groups;
    # flat order is preserved, so the packed words match pack_lanes exactly)
    q = jnp.clip(jnp.floor(x_ref[:] / s_ref[:] + u_ref[:]),
                 -qmax, qmax).astype(jnp.int32)
    q_out[:] = q
    qb = (q + bias).reshape(q.shape[0], LANE // 4, 4)
    w = qb[:, :, 0]
    for i in range(1, 4):
        w = jnp.bitwise_or(w, jnp.left_shift(qb[:, :, i], i * 8))
    w_out[:] = w


def _quant_pack_pallas(x, scale, key, qmax: int, bias: int, block_rows: int,
                       interpret: Optional[bool]):
    from jax.experimental import pallas as pl

    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    n = x.shape[0]
    rows = -(-n // LANE)
    pad = rows * LANE - n

    def pack2d(flat, fill=0.0):
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.full(pad, fill, flat.dtype)])
        return flat.reshape(rows, LANE)

    u = jax.random.uniform(key, (n,), jnp.float32)
    bm = min(block_rows, max(1, rows))
    nm = pl.cdiv(rows, bm)
    # padding lanes divide by scale fill 1.0 and quantise x=0 -> q=0, so the
    # packed tail words beyond ceil(n/4) are sliced off below and the lane
    # values within them never reach the decoder
    w2, q2 = pl.pallas_call(
        partial(_quant_pack_kernel, qmax=qmax, bias=bias),
        grid=(nm,),
        in_specs=[pl.BlockSpec((bm, LANE), lambda i: (i, 0))] * 3,
        out_specs=[pl.BlockSpec((bm, LANE // 4), lambda i: (i, 0)),
                   pl.BlockSpec((bm, LANE), lambda i: (i, 0))],
        out_shape=[jax.ShapeDtypeStruct((rows, LANE // 4), jnp.int32),
                   jax.ShapeDtypeStruct((rows, LANE), jnp.int32)],
        interpret=interpret,
    )(pack2d(x), pack2d(scale, fill=1.0), pack2d(u))
    words = -(-n // 4)
    return w2.reshape(-1)[:words], q2.reshape(-1)[:n]


def quantize_pack(x: jnp.ndarray, scale: jnp.ndarray, key: jax.Array,
                  qmax: int, bias: int, mode: str = "xla",
                  block_rows: int = 256,
                  interpret: Optional[bool] = None):
    """Stochastic-round ``x / scale`` onto ``[-qmax, qmax]``, bias to
    unsigned, and pack 4 values per int32 word (8-bit lanes).  Returns
    ``(packed_words, q)`` -- ``q`` is the signed quantised grid value the
    encoder needs locally for the error-feedback residual.  ``mode``:
    'xla' (default off-TPU) or 'pallas' (the fused single-pass kernel)."""
    if mode == "xla":
        return _quant_pack_xla(x, scale, key, qmax, bias)
    if mode == "pallas":
        return _quant_pack_pallas(x, scale, key, qmax, bias, block_rows,
                                  interpret)
    raise ValueError(f"Not valid quantize_pack mode: {mode!r}")
