"""Pallas TPU kernels for the train-time (sBN-free) batch norm.

The masked-width BN in the round step (ops/layers.py:batch_norm, mode
"batch") is bandwidth-bound: XLA materialises the weighted moments and the
normalisation as separate HBM passes over the activation.  These kernels fuse
each direction -- forward: one accumulation pass (weighted sum / sumsq /
count) and one normalise pass with the statistics living in VMEM scratch
between phases; backward (custom VJP): one pass accumulating ``db``/``dg``
and one pass emitting ``dx`` from the standard BN backward formula.  Width
masking needs no extra input: masked channels carry ``g == b == 0``, which
zeroes their output exactly like the XLA path.

Opt-in via ``cfg['pallas_norm'] = True`` (see models/norms.py); the XLA path
still serves running/collect modes and cross-device (sync-BN) reductions.
Measured A/B vs the XLA op: scripts/tpu_ab.py.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp

from jax.experimental import pallas as pl


def _vmem(shape):
    from jax.experimental.pallas import tpu as pltpu

    return pltpu.VMEM(shape, jnp.float32)


def _masks(i, w_ref, m_total, block_m):
    """(real-row mask, weight-valid mask) for the current block; block
    padding rows may hold non-finite garbage and must be `where`-ed out, not
    multiplied out."""
    row = jax.lax.broadcasted_iota(jnp.int32, (block_m, 1), 0) + i * block_m
    rowmask = (row < m_total).astype(jnp.float32)
    # where, not multiply: the padding rows of w are undefined VMEM too
    return rowmask, jnp.where(rowmask > 0, w_ref[:], 0.0)


def _bn_fwd_kernel(x_ref, w_ref, g_ref, b_ref, y_ref, st_ref, s1, s2, cnt, *,
                   eps: float, m_total: int, block_m: int):
    phase, i = pl.program_id(0), pl.program_id(1)

    @pl.when(jnp.logical_and(phase == 0, i == 0))
    def _():
        s1[:] = jnp.zeros_like(s1)
        s2[:] = jnp.zeros_like(s2)
        cnt[:] = jnp.zeros_like(cnt)

    rowmask, valid = _masks(i, w_ref, m_total, block_m)

    @pl.when(phase == 0)
    def _():
        x = jnp.where(valid > 0, x_ref[:].astype(jnp.float32), 0.0)
        s1[:] += jnp.sum(x * valid, axis=0, keepdims=True)
        s2[:] += jnp.sum(x * x * valid, axis=0, keepdims=True)
        cnt[:] += jnp.sum(valid, axis=0, keepdims=True)

    @pl.when(phase == 1)
    def _():
        n = jnp.maximum(cnt[0, 0], 1e-6)
        mean = s1[:] / n
        var = jnp.maximum(s2[:] / n - mean * mean, 0.0)
        inv = jax.lax.rsqrt(var + eps)
        x = jnp.where(rowmask > 0, x_ref[:].astype(jnp.float32), 0.0)
        y = (x - mean) * inv * g_ref[:] + b_ref[:]
        y_ref[:] = y.astype(y_ref.dtype)
        st_ref[0:1, :] = mean
        st_ref[1:2, :] = inv
        st_ref[2:3, :] = jnp.full_like(mean, n)


def _bn_bwd_kernel(x_ref, w_ref, g_ref, dy_ref, st_ref, dx_ref, dg_ref, db_ref,
                   a1, a2, *, m_total: int, block_m: int):
    phase, i = pl.program_id(0), pl.program_id(1)

    @pl.when(jnp.logical_and(phase == 0, i == 0))
    def _():
        a1[:] = jnp.zeros_like(a1)
        a2[:] = jnp.zeros_like(a2)

    rowmask, valid = _masks(i, w_ref, m_total, block_m)
    mean = st_ref[0:1, :]
    inv = st_ref[1:2, :]
    n = jnp.maximum(st_ref[2, 0], 1e-6)
    x = jnp.where(rowmask > 0, x_ref[:].astype(jnp.float32), 0.0)
    xhat = (x - mean) * inv
    dy = jnp.where(rowmask > 0, dy_ref[:].astype(jnp.float32), 0.0)

    @pl.when(phase == 0)
    def _():
        a1[:] += jnp.sum(dy, axis=0, keepdims=True)          # db
        a2[:] += jnp.sum(dy * xhat, axis=0, keepdims=True)   # dg

    @pl.when(phase == 1)
    def _():
        g = g_ref[:]
        # dx_k = inv*g*dy_k - w_k*inv/n*(g*db) - w_k*xhat_k*inv/n*(g*dg)
        dx = inv * g * dy \
            - valid * (inv / n) * (g * a1[:]) \
            - valid * xhat * (inv / n) * (g * a2[:])
        dx_ref[:] = dx.astype(dx_ref.dtype)
        dg_ref[:] = a2[:]
        db_ref[:] = a1[:]


def _call_fwd(x2, w, g, b, eps, bm, interpret):
    M, C = x2.shape
    nm = pl.cdiv(M, bm)
    return pl.pallas_call(
        partial(_bn_fwd_kernel, eps=eps, m_total=M, block_m=bm),
        grid=(2, nm),
        in_specs=[
            pl.BlockSpec((bm, C), lambda p, i: (i, 0)),
            pl.BlockSpec((bm, 1), lambda p, i: (i, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, C), lambda p, i: (i, 0)),
            pl.BlockSpec((8, C), lambda p, i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M, C), x2.dtype),
            jax.ShapeDtypeStruct((8, C), jnp.float32),  # mean/inv/n (+pad)
        ],
        scratch_shapes=[_vmem((1, C)), _vmem((1, C)), _vmem((1, 1))],
        interpret=interpret,
    )(x2, w, g.reshape(1, C), b.reshape(1, C))


def _call_bwd(x2, w, g, dy, stats, bm, interpret):
    M, C = x2.shape
    nm = pl.cdiv(M, bm)
    return pl.pallas_call(
        partial(_bn_bwd_kernel, m_total=M, block_m=bm),
        grid=(2, nm),
        in_specs=[
            pl.BlockSpec((bm, C), lambda p, i: (i, 0)),
            pl.BlockSpec((bm, 1), lambda p, i: (i, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
            pl.BlockSpec((bm, C), lambda p, i: (i, 0)),
            pl.BlockSpec((8, C), lambda p, i: (0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((bm, C), lambda p, i: (i, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
            pl.BlockSpec((1, C), lambda p, i: (0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((M, C), x2.dtype),
            jax.ShapeDtypeStruct((1, C), jnp.float32),
            jax.ShapeDtypeStruct((1, C), jnp.float32),
        ],
        scratch_shapes=[_vmem((1, C)), _vmem((1, C))],
        interpret=interpret,
    )(x2, w, g.reshape(1, C), dy, stats)


@partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _bn2d(x2, w, g, b, eps, bm, interpret):
    y, _ = _call_fwd(x2, w, g, b, eps, bm, interpret)
    return y


def _bn2d_fwd(x2, w, g, b, eps, bm, interpret):
    y, stats = _call_fwd(x2, w, g, b, eps, bm, interpret)
    return y, (x2, w, g, stats)


def _bn2d_bwd(eps, bm, interpret, res, dy):
    x2, w, g, stats = res
    dx, dg, db = _call_bwd(x2, w, g, dy, stats, bm, interpret)
    return dx, jnp.zeros_like(w), dg.reshape(g.shape), db.reshape(g.shape)


_bn2d.defvjp(_bn2d_fwd, _bn2d_bwd)


def batch_norm_pallas(x: jnp.ndarray, g: jnp.ndarray, b: jnp.ndarray,
                      sample_weight: Optional[jnp.ndarray] = None,
                      eps: float = 1e-5, block_m: int = 2048,
                      interpret: Optional[bool] = None) -> jnp.ndarray:
    """Fused, differentiable batch-stat normalisation of an NHWC (or NC)
    tensor.

    Semantics match ``ops.layers.batch_norm(mode='batch')``: per-channel
    weighted moments over all leading axes, biased variance, then
    ``(x - mean) * rsqrt(var + eps) * g + b``.

    ``interpret=None``: real kernel on TPU, interpreter elsewhere (so the
    same model code runs on the CPU test mesh).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    orig_shape = x.shape
    C = x.shape[-1]
    n = x.shape[0]
    x2 = x.reshape(-1, C)
    M = x2.shape[0]
    if sample_weight is None:
        w = jnp.ones((M, 1), jnp.float32)
    else:
        w = jnp.repeat(sample_weight.astype(jnp.float32), M // n).reshape(M, 1)
    bm = min(block_m, max(8, M))
    y = _bn2d(x2, w, g, b, eps, bm, interpret)
    return y.reshape(orig_shape)
