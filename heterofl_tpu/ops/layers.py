"""Masked primitive layers.

These primitives make the **masked full-width** execution strategy exact: a
HeteroFL sub-model is always a *prefix* slice of the global tensors
(ref ``src/fed.py:46-48``), so running the full-width model with the suffix
channels held at zero produces bit-identical math to the sliced sub-model --
provided every op that mixes channels uses masked statistics.  Per-channel ops
(conv, BN, instance norm, ReLU, pooling) commute with zero-masking for free;
LayerNorm / GroupNorm need the active count ``k`` instead of the full width,
implemented here.

Conventions: NHWC activations, HWIO conv kernels, ``[in, out]`` linear
kernels -- the native layouts for XLA:TPU.
"""

from __future__ import annotations

import math
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

#: THE conv dimension-number convention (models/layout.py re-exports it as
#: part of the explicit layout policy; one owner, two consumers)
CONV_DIMENSION_NUMBERS: Tuple[str, str, str] = ("NHWC", "HWIO", "NHWC")


def conv2d(x: jnp.ndarray, w: jnp.ndarray, b: Optional[jnp.ndarray] = None,
           stride: int = 1, padding: int = 1,
           compute_dtype: Optional[jnp.dtype] = None,
           impl: Optional[str] = None) -> jnp.ndarray:
    """3x3/1x1 convolution, NHWC x HWIO -> NHWC.

    ``compute_dtype`` (e.g. bfloat16) casts the MXU operands while
    accumulating in float32 -- the TPU mixed-precision recipe; params stay
    float32 outside the op.

    ``impl='im2col'`` expresses the op as patch extraction + matmul.  Under
    ``vmap`` with per-client kernels (the federated round engine's hot path)
    the direct form lowers to a ``feature_group_count=clients`` grouped
    convolution whose small per-group channel counts under-tile the 128x128
    MXU; the im2col form instead keeps patch extraction a *shared-kernel*
    dense conv (vmap folds clients into the batch dim) and turns only the
    kernel application into a batched matmul, which the MXU executes
    natively.  Numerically identical (same f32 accumulation); see
    tests/test_models.py::test_conv2d_im2col_matches_direct.
    """
    if compute_dtype is not None:
        x, w = x.astype(compute_dtype), w.astype(compute_dtype)
    if impl == "im2col":
        kh, kw, cin, cout = w.shape
        if (kh, kw) == (1, 1) and padding == 0:
            # 1x1 conv IS a matmul on strided pixels; skip patch extraction
            patches = x[:, ::stride, ::stride, :]
            y = patches @ w.reshape(cin, cout)
        else:
            patches = lax.conv_general_dilated_patches(
                x, filter_shape=(kh, kw), window_strides=(stride, stride),
                padding=((padding, padding), (padding, padding)),
                dimension_numbers=CONV_DIMENSION_NUMBERS)
            # patch features are ordered (C, kh, kw); transpose w to match
            w_flat = jnp.transpose(w, (2, 0, 1, 3)).reshape(kh * kw * cin, cout)
            y = patches @ w_flat
    else:
        y = lax.conv_general_dilated(
            x, w,
            window_strides=(stride, stride),
            padding=((padding, padding), (padding, padding)),
            dimension_numbers=CONV_DIMENSION_NUMBERS,
        )
    if compute_dtype is not None:
        y = y.astype(jnp.float32)  # XLA:TPU accumulates bf16 convs in f32
    if b is not None:
        y = y + b
    return y


def linear(x: jnp.ndarray, w: jnp.ndarray, b: Optional[jnp.ndarray] = None,
           compute_dtype: Optional[jnp.dtype] = None) -> jnp.ndarray:
    if compute_dtype is not None:
        x, w = x.astype(compute_dtype), w.astype(compute_dtype)
        y = (x @ w).astype(jnp.float32)
    else:
        y = x @ w
    if b is not None:
        y = y + b
    return y


def embed(table: jnp.ndarray, ids: jnp.ndarray) -> jnp.ndarray:
    return jnp.take(table, ids, axis=0)


def scaler(x: jnp.ndarray, rate, train: bool) -> jnp.ndarray:
    """HeteroFL Scaler: ``x / rate`` in training, identity in eval
    (ref src/modules/modules.py:9-11)."""
    return x / rate if train else x


def max_pool2(x: jnp.ndarray) -> jnp.ndarray:
    """MaxPool2d(2) with floor semantics (torch default)."""
    return lax.reduce_window(x, -jnp.inf, lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "VALID")


def global_avg_pool(x: jnp.ndarray) -> jnp.ndarray:
    """AdaptiveAvgPool2d(1) + flatten: NHWC -> NC."""
    return jnp.mean(x, axis=(1, 2))


def batch_norm(x: jnp.ndarray, g: jnp.ndarray, b: jnp.ndarray, *,
               mode: str = "batch",
               running: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
               sample_weight: Optional[jnp.ndarray] = None,
               eps: float = 1e-5,
               axis_name=None):
    """Static batch norm (momentum=None, per-channel) for NHWC or NC inputs.

    Parity: ``nn.BatchNorm2d(C, momentum=None, track_running_stats=track)``
    (ref models/conv.py:14).  ``mode``:

    * ``"batch"``   -- normalise with batch statistics (training, and eval of a
      ``track=False`` model, which torch also normalises with batch stats).
    * ``"running"`` -- normalise with provided ``running = (mean, var)``
      (eval after sBN recalibration).
    * ``"collect"`` -- like ``"batch"`` but also return
      ``(batch_mean, batch_var_unbiased)`` for cumulative-average
      recalibration (momentum=None => CMA, ref SURVEY §5.4).

    ``sample_weight``: optional ``[N]`` 0/1 weights so padded examples do not
    pollute the statistics (the reference's final partial batch has exact
    semantics; we pad + mask instead).

    ``axis_name``: synchronised BN -- batch statistics are reduced with
    ``psum`` across that mesh axis, so a batch sharded over devices sees
    exactly the full-batch statistics (needed for intra-client batch DP to be
    numerically identical to single-device execution).

    Per-channel statistics mean masked-out channels are exactly equivalent to
    the sliced sub-model's BN for the active channels.
    """
    axes = tuple(range(x.ndim - 1))  # all but channel
    if mode == "running":
        mean, var = running
        y = (x - mean) / jnp.sqrt(var + eps) * g + b
        return y, None
    w = None
    if sample_weight is not None:
        w = sample_weight.reshape((-1,) + (1,) * (x.ndim - 1))
        w = jnp.broadcast_to(w, x.shape)
    # staticcheck: allow(no-float-coercion): static shape product, not a
    # device value
    n_local = float(math.prod(x.shape[a] for a in axes))
    if axis_name is not None:
        # Cross-device sync: one-pass (sum, sumsq, count) psums -- the only
        # form expressible as single-shot collectives.
        if w is None:
            s1 = jnp.sum(x, axis=axes, keepdims=True, dtype=jnp.float32)
            s2 = jnp.sum(x * x, axis=axes, keepdims=True, dtype=jnp.float32)
            # staticcheck: allow(no-asarray): trace-time static count scalar
            n = jnp.asarray(n_local, jnp.float32) * jax.lax.psum(1.0, axis_name)
        else:
            s1 = jnp.sum(x * w, axis=axes, keepdims=True, dtype=jnp.float32)
            s2 = jnp.sum(w * x * x, axis=axes, keepdims=True, dtype=jnp.float32)
            n = jax.lax.psum(jnp.sum(w, axis=axes, keepdims=True, dtype=jnp.float32),
                             axis_name)
        s1 = jax.lax.psum(s1, axis_name)
        s2 = jax.lax.psum(s2, axis_name)
        d = jnp.maximum(n, 1e-6)
        mean = s1 / d
        var = jnp.maximum(s2 / d - mean * mean, 0.0)
    else:
        # Single-device: two-pass mean-then-centered-var (torch parity form).
        # The one-pass E[x^2]-mean^2 alternative was A/B'd on TPU and is
        # perf-neutral (19.71 vs 19.85 ms/step, MEASUREMENTS.md) -- XLA's
        # fusion makes the second read ~free at these shapes -- while its
        # uncentered sums are measurably more reduction-order-sensitive
        # (masked-vs-sliced divergence grows ~5x), so the tighter two-pass
        # form wins.
        if w is None:
            # staticcheck: allow(no-asarray): trace-time static count scalar
            n = jnp.asarray(n_local, jnp.float32)
            mean = jnp.sum(x, axis=axes, keepdims=True, dtype=jnp.float32) / n
            var = jnp.sum((x - mean) ** 2, axis=axes, keepdims=True,
                          dtype=jnp.float32) / n
        else:
            n = jnp.sum(w, axis=axes, keepdims=True, dtype=jnp.float32)
            d = jnp.maximum(n, 1e-6)  # all-padding batches: 0-stats, not NaN
            mean = jnp.sum(x * w, axis=axes, keepdims=True, dtype=jnp.float32) / d
            var = jnp.sum(w * (x - mean) ** 2, axis=axes, keepdims=True,
                          dtype=jnp.float32) / d
    y = (x - mean) / jnp.sqrt(var + eps) * g + b
    if mode == "collect":
        unbiased = var * n / jnp.maximum(n - 1, 1)
        return y, (mean.reshape(-1), unbiased.reshape(-1))
    return y, None


def masked_layer_norm(x: jnp.ndarray, g: jnp.ndarray, b: jnp.ndarray,
                      mask: jnp.ndarray, k, eps: float = 1e-5) -> jnp.ndarray:
    """LayerNorm over the last axis counting only the ``k`` active dims.

    ``mask`` is the 0/1 activity mask over the last axis; ``k = sum(mask)``
    (passed separately so it can be a traced scalar).  For a full-width model
    (mask all ones) this is standard LayerNorm (eps=1e-5, biased var, parity
    with ``nn.LayerNorm``).  ``g``/``b`` are zero at masked dims, which zeroes
    the output there.
    """
    xm = x * mask
    mean = jnp.sum(xm, axis=-1, keepdims=True) / k
    var = jnp.sum(mask * (xm - mean) ** 2, axis=-1, keepdims=True) / k
    return (xm - mean) / jnp.sqrt(var + eps) * g + b


def dynamic_group_norm(x: jnp.ndarray, g: jnp.ndarray, b: jnp.ndarray,
                       num_groups: int, mask: jnp.ndarray, k,
                       eps: float = 1e-5) -> jnp.ndarray:
    """GroupNorm(G) whose group boundaries follow the *active* channel count.

    A sliced sub-model with ``k`` channels splits **its** channels into G
    contiguous groups of ``k/G`` (ref models/conv.py:20); since active
    channels are a prefix, the equivalent full-width op assigns channel ``c``
    to group ``floor(c*G/k)`` and computes masked statistics per group over
    (H, W, group-channels).  Requires ``G | k`` (torch enforces divisibility).

    ``num_groups=C`` (instance norm) and ``num_groups=1`` (layer norm over
    CHW) are handled by the same formula.  NHWC input.
    """
    C = x.shape[-1]
    c_idx = jnp.arange(C)
    gid = jnp.clip((c_idx * num_groups) // jnp.maximum(k, 1), 0, num_groups - 1)
    onehot = (jax.nn.one_hot(gid, num_groups) * mask[:, None])  # [C, G]
    spatial = 1
    for a in range(1, x.ndim - 1):
        spatial *= x.shape[a]
    occ = jnp.sum(onehot, axis=0)  # active channels per group
    n_per_group = jnp.maximum(occ * spatial, 1.0)
    xm = x * mask
    # Per-sample, per-group sums via matmul over the channel axis.
    sum_g = jnp.einsum("...c,cg->...g", xm, onehot)
    red_axes = tuple(range(1, x.ndim - 1))
    mean_g = jnp.sum(sum_g, axis=red_axes, keepdims=True) / n_per_group  # [N,1..,G]
    mean_c = jnp.einsum("...g,cg->...c", mean_g, onehot)
    d = (xm - mean_c) * mask
    var_g = jnp.sum(jnp.einsum("...c,cg->...g", d * d, onehot), axis=red_axes, keepdims=True) / n_per_group
    var_c = jnp.einsum("...g,cg->...c", var_g, onehot)
    y = d / jnp.sqrt(var_c + eps) * g + b
    return y * mask


def masked_logits(out: jnp.ndarray, label_mask: Optional[jnp.ndarray], enabled: bool) -> jnp.ndarray:
    """Zero-fill logits of classes outside the client's label set
    (ref models/conv.py:66-69 -- zero fill, *not* -inf)."""
    if label_mask is None or not enabled:
        return out
    return jnp.where(label_mask == 0, 0.0, out)


def cross_entropy(logits: jnp.ndarray, labels: jnp.ndarray,
                  sample_weight: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Mean cross entropy; class axis is the LAST axis of ``logits``.

    ``sample_weight`` broadcasts over the label shape (used to neutralise
    padded examples).  Matches ``F.cross_entropy(reduction='mean')``.
    """
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[..., None], axis=-1)[..., 0]
    if sample_weight is None:
        return jnp.mean(nll)
    w = jnp.broadcast_to(sample_weight.reshape(sample_weight.shape + (1,) * (nll.ndim - sample_weight.ndim)),
                         nll.shape)
    return jnp.sum(nll * w) / jnp.maximum(jnp.sum(w), 1e-12)
