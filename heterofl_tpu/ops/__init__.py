"""Device-side primitive ops: masked layers, pooling, losses, augmentation."""

from .layers import (  # noqa: F401
    conv2d,
    linear,
    embed,
    scaler,
    batch_norm,
    masked_layer_norm,
    dynamic_group_norm,
    max_pool2,
    global_avg_pool,
    cross_entropy,
    masked_logits,
)
from .augment import normalize_image, augment_cifar  # noqa: F401
