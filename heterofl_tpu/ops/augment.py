"""On-device data augmentation + normalisation.

The reference normalises/augments on the host via torchvision transforms
(``src/data.py:15-27``: CIFAR train = RandomCrop(32, padding=4) +
RandomHorizontalFlip).  Here raw uint8 batches are shipped to the device once
and augmentation runs inside the jitted client step, fusing into the forward
pass -- no host round-trips in the training loop.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp


def normalize_image(x: jnp.ndarray, mean: Sequence[float], std: Sequence[float]) -> jnp.ndarray:
    """uint8 NHWC -> float32 normalised (ToTensor + Normalize parity)."""
    x = x.astype(jnp.float32) / 255.0
    # staticcheck: allow(no-asarray): trace-time dataset-stat constants
    return (x - jnp.asarray(mean, jnp.float32)) / jnp.asarray(std, jnp.float32)


def augment_cifar(rng: jax.Array, x: jnp.ndarray) -> jnp.ndarray:
    """RandomCrop(pad=4) + RandomHorizontalFlip on a uint8/float NHWC batch."""
    n, h, w, c = x.shape
    k_shift, k_flip = jax.random.split(rng)
    pad = 4
    xp = jnp.pad(x, ((0, 0), (pad, pad), (pad, pad), (0, 0)))
    shifts = jax.random.randint(k_shift, (n, 2), 0, 2 * pad + 1)

    def crop_one(img, sh):
        return jax.lax.dynamic_slice(img, (sh[0], sh[1], 0), (h, w, c))

    out = jax.vmap(crop_one)(xp, shifts)
    flip = jax.random.bernoulli(k_flip, 0.5, (n,))
    return jnp.where(flip[:, None, None, None], out[:, :, ::-1, :], out)
