"""Model definition container and init helpers.

Models are pure functions over flat ``{name: array}`` param dicts (explicit
pytrees, haiku-style without the framework): ``init(key) -> params`` and
``apply(params, batch, ...) -> (output, bn_stats)``.  Widths are static
(global model sizes); per-client width heterogeneity enters only through the
traced ``width_rate``/``scaler_rate`` scalars and the masks they induce, so
one compiled program serves every rate level.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List

import jax
import jax.numpy as jnp

from .spec import Group, ParamSpec


@dataclass
class ModelDef:
    name: str
    init: Callable[[jax.Array], Dict[str, jnp.ndarray]]
    apply: Callable[..., Any]
    specs: Dict[str, ParamSpec]
    groups: Dict[str, Group]
    bn_sites: List[str] = field(default_factory=list)  # prefixes carrying sBN state
    meta: Dict[str, Any] = field(default_factory=dict)

    def init_bn_state(self) -> Dict[str, Any]:
        """Zeroed running (mean, var) per BN site, matching fresh
        ``track=True`` modules (ref train_classifier_fed.py:127-138)."""
        out = {}
        for site in self.bn_sites:
            size = self.meta["bn_sizes"][site]
            out[site] = (jnp.zeros(size, jnp.float32), jnp.ones(size, jnp.float32))
        return out


def uniform_fan_in(key: jax.Array, shape, fan_in: int) -> jnp.ndarray:
    """torch's default kaiming_uniform(a=sqrt(5)): U(-1/sqrt(fan_in), +)."""
    # staticcheck: allow(no-asarray, no-float-coercion): init-time static
    # fan-in scalar, never on the round path
    bound = 1.0 / jnp.sqrt(jnp.asarray(float(fan_in)))
    return jax.random.uniform(key, shape, jnp.float32, -bound, bound)


def normal_init(key: jax.Array, shape, std: float) -> jnp.ndarray:
    return std * jax.random.normal(key, shape, jnp.float32)
