"""Pre-activation ResNet family (18/34 basic Block; 50/101/152 Bottleneck).

Parity: ``src/models/resnet.py`` -- scaler->norm->relu *before* each conv
(resnet.py:44-50), bare 1x1 conv shortcut (resnet.py:41-42), final
norm->relu->avgpool->linear with zero-fill label masking (resnet.py:148-157).

Slicing rules mirror ``src/fed.py:63-103``: stage channels prefix-sliced and
chained; the shortcut's input follows conv1's input (fed.py:82-84); the
classifier keeps full output width (fed.py:85-87).  NOTE: the reference's
``split_model`` raises on Bottleneck parameters (no ``conv3`` rule,
fed.py:89), i.e. federated ResNet-50+ *crashes* upstream; here Bottleneck
gets a proper rule (mid widths are their own groups) as a strict superset.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import jax
import jax.numpy as jnp

from functools import partial

from ..ops.layers import conv2d as _conv2d, cross_entropy, global_avg_pool, linear as _linear, masked_logits, scaler
from .base import ModelDef, uniform_fan_in
from .norms import apply_norm, norm_has_params, norm_init
from .spec import Group, ParamSpec


def make_resnet(data_shape, hidden_size, num_blocks: List[int], classes_size: int, *,
                bottleneck: bool = False, norm: str = "bn", scale: bool = True,
                mask: bool = True, compute_dtype=None,
                pallas_norm: bool = False, conv_impl=None) -> ModelDef:
    in_ch = data_shape[-1]
    expansion = 4 if bottleneck else 1
    n_stages = len(hidden_size)

    groups: Dict[str, Group] = {f"s{s}": Group(f"s{s}", hidden_size[s] * expansion) for s in range(n_stages)}
    if bottleneck:
        groups.update({f"m{s}": Group(f"m{s}", hidden_size[s]) for s in range(n_stages)})
    groups["classes"] = Group("classes", classes_size, kind="full")

    # Walk the architecture once, recording blocks:
    # (prefix, in_planes, in_group, planes, stage, stride, has_shortcut)
    blocks = []
    in_planes, in_group = hidden_size[0], "s0_stem"
    groups["s0_stem"] = Group("s0_stem", hidden_size[0])
    for s in range(n_stages):
        strides = [1 if s == 0 else 2] + [1] * (num_blocks[s] - 1)
        for b, stride in enumerate(strides):
            planes = hidden_size[s]
            has_short = stride != 1 or in_planes != planes * expansion
            blocks.append((f"layer{s}.{b}", in_planes, in_group, planes, s, stride, has_short))
            in_planes, in_group = planes * expansion, f"s{s}"

    specs: Dict[str, ParamSpec] = {}
    bn_sizes: Dict[str, int] = {}

    def add_norm(prefix: str, group: str, size: int):
        if norm_has_params(norm):
            specs[f"{prefix}.g"] = ParamSpec({0: group})
            specs[f"{prefix}.b"] = ParamSpec({0: group})
        bn_sizes[prefix] = size

    specs["conv1.w"] = ParamSpec({3: "s0_stem"})
    for (pfx, inp, ig, planes, s, stride, has_short) in blocks:
        out_g = f"s{s}"
        if bottleneck:
            mid_g = f"m{s}"
            add_norm(f"{pfx}.n1", ig, inp)
            specs[f"{pfx}.conv1.w"] = ParamSpec({2: ig, 3: mid_g})
            add_norm(f"{pfx}.n2", mid_g, planes)
            specs[f"{pfx}.conv2.w"] = ParamSpec({2: mid_g, 3: mid_g})
            add_norm(f"{pfx}.n3", mid_g, planes)
            specs[f"{pfx}.conv3.w"] = ParamSpec({2: mid_g, 3: out_g})
        else:
            add_norm(f"{pfx}.n1", ig, inp)
            specs[f"{pfx}.conv1.w"] = ParamSpec({2: ig, 3: out_g})
            add_norm(f"{pfx}.n2", out_g, planes)
            specs[f"{pfx}.conv2.w"] = ParamSpec({2: out_g, 3: out_g})
        if has_short:
            specs[f"{pfx}.shortcut.w"] = ParamSpec({2: ig, 3: out_g})
    final_size = hidden_size[-1] * expansion
    add_norm("n4", f"s{n_stages-1}", final_size)
    specs["linear.w"] = ParamSpec({0: f"s{n_stages-1}"}, label_axis=1)
    specs["linear.b"] = ParamSpec({}, label_axis=0)

    def init(key: jax.Array) -> Dict[str, jnp.ndarray]:
        params: Dict[str, jnp.ndarray] = {}
        n_keys = 2 + 4 * len(blocks)
        keys = iter(jax.random.split(key, n_keys))

        def conv_init(shape):
            fan_in = shape[0] * shape[1] * shape[2]
            return uniform_fan_in(next(keys), shape, fan_in)

        params["conv1.w"] = conv_init((3, 3, in_ch, hidden_size[0]))
        for (pfx, inp, ig, planes, s, stride, has_short) in blocks:
            if bottleneck:
                params[f"{pfx}.conv1.w"] = conv_init((1, 1, inp, planes))
                params[f"{pfx}.conv2.w"] = conv_init((3, 3, planes, planes))
                params[f"{pfx}.conv3.w"] = conv_init((1, 1, planes, planes * expansion))
                for n, size in (("n1", inp), ("n2", planes), ("n3", planes)):
                    params.update({f"{pfx}.{n}.{k}": v for k, v in norm_init(norm, size).items()})
            else:
                params[f"{pfx}.conv1.w"] = conv_init((3, 3, inp, planes))
                params[f"{pfx}.conv2.w"] = conv_init((3, 3, planes, planes))
                for n, size in (("n1", inp), ("n2", planes)):
                    params.update({f"{pfx}.{n}.{k}": v for k, v in norm_init(norm, size).items()})
            if has_short:
                params[f"{pfx}.shortcut.w"] = conv_init((1, 1, inp, planes * expansion))
        params.update({f"n4.{k}": v for k, v in norm_init(norm, final_size).items()})
        params["linear.w"] = uniform_fan_in(next(keys), (final_size, classes_size), final_size)
        params["linear.b"] = jnp.zeros(classes_size, jnp.float32)
        return params

    conv2d = partial(_conv2d, compute_dtype=compute_dtype, impl=conv_impl)
    linear = partial(_linear, compute_dtype=compute_dtype)

    def apply(params, batch, *, train: bool, width_rate=1.0, scaler_rate=1.0,
              label_mask: Optional[jnp.ndarray] = None, bn_mode: str = "batch",
              bn_state=None, sample_weight=None, rng=None, bn_axis=None):
        collected = {}

        def norm_site(site, x, group_name):
            g = groups[group_name]
            y, st = apply_norm(
                norm, x, params.get(f"{site}.g"), params.get(f"{site}.b"),
                mask=g.mask(width_rate), k=g.active_count(width_rate),
                bn_mode=bn_mode, bn_running=None if bn_state is None else bn_state.get(site),
                sample_weight=sample_weight, bn_axis=bn_axis, use_pallas=pallas_norm)
            if st is not None:
                collected[site] = st
            return y

        def sc(x):
            return scaler(x, scaler_rate, train) if scale else x

        x = conv2d(x=batch["img"], w=params["conv1.w"], stride=1, padding=1)
        for (pfx, inp, ig, planes, s, stride, has_short) in blocks:
            out = jax.nn.relu(norm_site(f"{pfx}.n1", sc(x), ig))
            short = conv2d(out, params[f"{pfx}.shortcut.w"], stride=stride, padding=0) if has_short else x
            if bottleneck:
                out = conv2d(out, params[f"{pfx}.conv1.w"], stride=1, padding=0)
                out = jax.nn.relu(norm_site(f"{pfx}.n2", sc(out), f"m{s}"))
                out = conv2d(out, params[f"{pfx}.conv2.w"], stride=stride, padding=1)
                out = jax.nn.relu(norm_site(f"{pfx}.n3", sc(out), f"m{s}"))
                out = conv2d(out, params[f"{pfx}.conv3.w"], stride=1, padding=0)
            else:
                out = conv2d(out, params[f"{pfx}.conv1.w"], stride=stride, padding=1)
                out = conv2d(jax.nn.relu(norm_site(f"{pfx}.n2", sc(out), f"s{s}")),
                             params[f"{pfx}.conv2.w"], stride=1, padding=1)
            x = out + short
        x = jax.nn.relu(norm_site("n4", sc(x), f"s{n_stages-1}"))
        x = global_avg_pool(x)
        out = linear(x, params["linear.w"], params["linear.b"])
        out = masked_logits(out, label_mask, mask)
        loss = cross_entropy(out, batch["label"], sample_weight)
        return {"score": out, "loss": loss}, collected

    bn_sites = list(bn_sizes.keys()) if norm == "bn" else []
    meta = {"bn_sizes": bn_sizes, "hidden_size": list(hidden_size),
            "classes_size": classes_size, "kind": "resnet", "expansion": expansion}
    return ModelDef("resnet", init, apply, specs, groups, bn_sites, meta)
