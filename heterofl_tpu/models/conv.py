"""The HeteroFL CNN: Conv3x3 -> Scaler -> Norm -> ReLU -> MaxPool x4, then
GlobalAvgPool -> Linear, loss inside apply.

Parity: ``src/models/conv.py`` (incl. the quirk that the *last* MaxPool is
dropped, conv.py:56, and the zero-fill label mask, conv.py:66-69).  Width
slicing rules mirror ``src/fed.py:27-62``: hidden channels are prefix-sliced
and chained; the classifier keeps its full output dim (label-restricted at
aggregation time only).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..ops.layers import conv2d, cross_entropy, global_avg_pool, linear, masked_logits, max_pool2, scaler
from .base import ModelDef, uniform_fan_in
from .norms import apply_norm, norm_has_params, norm_init
from .spec import Group, ParamSpec


def make_conv(data_shape, hidden_size, classes_size, *, norm: str = "bn",
              scale: bool = True, mask: bool = True, compute_dtype=None,
              pallas_norm: bool = False, conv_impl=None) -> ModelDef:
    """Build the CNN at the given (global) widths.

    ``hidden_size`` are the *constructed* widths: the global model passes
    ``ceil(global_rate * [64,128,256,512])`` (ref models/conv.py:77); a sliced
    sub-model passes its own smaller widths and runs with ``width_rate=1``.
    """
    in_ch = data_shape[-1]
    n_blocks = len(hidden_size)

    groups = {f"h{i}": Group(f"h{i}", hidden_size[i]) for i in range(n_blocks)}
    groups["classes"] = Group("classes", classes_size, kind="full")

    specs: Dict[str, ParamSpec] = {}
    for i in range(n_blocks):
        in_group = {} if i == 0 else {2: f"h{i-1}"}
        specs[f"block{i}.conv.w"] = ParamSpec({**in_group, 3: f"h{i}"})
        specs[f"block{i}.conv.b"] = ParamSpec({0: f"h{i}"})
        if norm_has_params(norm):
            specs[f"block{i}.norm.g"] = ParamSpec({0: f"h{i}"})
            specs[f"block{i}.norm.b"] = ParamSpec({0: f"h{i}"})
    specs["linear.w"] = ParamSpec({0: f"h{n_blocks-1}"}, label_axis=1)
    specs["linear.b"] = ParamSpec({}, label_axis=0)

    def init(key: jax.Array) -> Dict[str, jnp.ndarray]:
        params: Dict[str, jnp.ndarray] = {}
        keys = jax.random.split(key, 2 * n_blocks + 1)
        ci = in_ch
        for i in range(n_blocks):
            co = hidden_size[i]
            fan_in = 3 * 3 * ci
            params[f"block{i}.conv.w"] = uniform_fan_in(keys[2 * i], (3, 3, ci, co), fan_in)
            params[f"block{i}.conv.b"] = uniform_fan_in(keys[2 * i + 1], (co,), fan_in)
            params.update({f"block{i}.norm.{n}": v for n, v in norm_init(norm, co).items()})
            ci = co
        params["linear.w"] = uniform_fan_in(keys[-1], (hidden_size[-1], classes_size), hidden_size[-1])
        params["linear.b"] = jnp.zeros(classes_size, jnp.float32)  # ref models/utils.py:8
        return params

    def apply(params, batch, *, train: bool, width_rate=1.0, scaler_rate=1.0,
              label_mask: Optional[jnp.ndarray] = None, bn_mode: str = "batch",
              bn_state=None, sample_weight=None, rng=None, bn_axis=None):
        x = batch["img"]
        collected = {}
        for i in range(n_blocks):
            x = conv2d(x, params[f"block{i}.conv.w"], params[f"block{i}.conv.b"],
                       compute_dtype=compute_dtype, impl=conv_impl)
            if scale:
                x = scaler(x, scaler_rate, train)
            g = groups[f"h{i}"]
            site = f"block{i}.norm"
            x, st = apply_norm(
                norm, x, params.get(f"{site}.g"), params.get(f"{site}.b"),
                mask=g.mask(width_rate), k=g.active_count(width_rate),
                bn_mode=bn_mode, bn_running=None if bn_state is None else bn_state.get(site),
                sample_weight=sample_weight, bn_axis=bn_axis, use_pallas=pallas_norm)
            if st is not None:
                collected[site] = st
            x = jax.nn.relu(x)
            if i < n_blocks - 1:  # last pool dropped (ref conv.py:56)
                x = max_pool2(x)
        x = global_avg_pool(x)
        out = linear(x, params["linear.w"], params["linear.b"], compute_dtype=compute_dtype)
        out = masked_logits(out, label_mask, mask)
        loss = cross_entropy(out, batch["label"], sample_weight)
        return {"score": out, "loss": loss}, collected

    bn_sites = [f"block{i}.norm" for i in range(n_blocks)] if norm == "bn" else []
    meta = {
        "bn_sizes": {f"block{i}.norm": hidden_size[i] for i in range(n_blocks)},
        "hidden_size": list(hidden_size),
        "classes_size": classes_size,
        "kind": "conv",
    }
    return ModelDef("conv", init, apply, specs, groups, bn_sites, meta)
