"""Width groups and parameter slicing specs.

The reference materialises per-client ``param_idx`` index tensors by walking
the state_dict with model-family-specific rules (``src/fed.py:26-159``).  Here
the same information is *declared once* per model as:

* ``Group`` -- a named width axis of the global model (e.g. ResNet stage 2's
  channels).  Given a client's ``width_rate`` it yields a 0/1 activity mask:
  - ``prefix``: first ``ceil(size * rate)`` entries active (fed.py:46-48);
  - ``per_head``: first ``ceil(head_dim * rate)`` entries of each attention
    head active (fed.py:124-131);
  - ``full``: always fully active (output layers, fed.py:43-44,85-87).
* ``ParamSpec`` -- which group governs each axis of each parameter, plus the
  axis (if any) restricted to the client's label split during aggregation
  (fed.py:193-198,228-233,263-274).

Everything is a pure function of a (possibly traced) ``width_rate`` scalar, so
dynamic-mode rate re-sampling stays inside the jitted round.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional, Tuple

import jax.numpy as jnp


@dataclass(frozen=True)
class Group:
    name: str
    size: int
    kind: str = "prefix"  # "prefix" | "per_head" | "full"
    num_heads: int = 1

    def active_count(self, width_rate) -> jnp.ndarray:
        """Number of active entries for a client at ``width_rate``."""
        if self.kind == "full":
            # staticcheck: allow(no-asarray): trace-time static group size
            return jnp.asarray(self.size, jnp.int32)
        if self.kind == "prefix":
            return jnp.ceil(self.size * width_rate).astype(jnp.int32)
        if self.kind == "per_head":
            hd = self.size // self.num_heads
            return (jnp.ceil(hd * width_rate).astype(jnp.int32) * self.num_heads).astype(jnp.int32)
        raise ValueError(self.kind)

    def mask(self, width_rate) -> jnp.ndarray:
        """0/1 activity mask of shape ``[size]``."""
        idx = jnp.arange(self.size)
        if self.kind == "full":
            return jnp.ones(self.size, jnp.float32)
        if self.kind == "prefix":
            k = jnp.ceil(self.size * width_rate)
            return (idx < k).astype(jnp.float32)
        if self.kind == "per_head":
            hd = self.size // self.num_heads
            kh = jnp.ceil(hd * width_rate)
            return ((idx % hd) < kh).astype(jnp.float32)
        raise ValueError(self.kind)


@dataclass(frozen=True)
class ParamSpec:
    """Slicing rule for one parameter.

    ``axis_groups`` maps tensor axis -> group name.  Unlisted axes are never
    sliced.  ``label_axis`` marks the axis whose rows are restricted to the
    client's label split when aggregating (None for most parameters).
    """

    axis_groups: Dict[int, str] = field(default_factory=dict)
    label_axis: Optional[int] = None


def axis_mask(shape: Tuple[int, ...], axis: int, vec: jnp.ndarray) -> jnp.ndarray:
    """Broadcast a per-axis mask vector across a tensor shape."""
    view = [1] * len(shape)
    view[axis] = shape[axis]
    return vec.reshape(view)


def param_mask(shape: Tuple[int, ...], spec: ParamSpec, groups: Dict[str, Group],
               width_rate, label_mask: Optional[jnp.ndarray] = None,
               with_label: bool = False) -> jnp.ndarray:
    """Activity mask for one parameter (product over its sliced axes).

    With ``with_label=True`` the ``label_axis`` is additionally restricted by
    ``label_mask`` -- this is the aggregation-time *count* mask; without it,
    the distribute-time parameter mask.
    """
    m = jnp.ones((), jnp.float32)
    for axis, gname in spec.axis_groups.items():
        m = m * axis_mask(shape, axis, groups[gname].mask(width_rate))
    if with_label and spec.label_axis is not None and label_mask is not None:
        vec = label_mask.astype(jnp.float32)
        short = shape[spec.label_axis] - vec.shape[0]
        if short > 0:
            # e.g. the transformer's <mask>-token embedding row (vocab+1):
            # outside every label split, never aggregated (ref fed.py:263-268).
            vec = jnp.concatenate([vec, jnp.zeros(short, jnp.float32)])
        m = m * axis_mask(shape, spec.label_axis, vec)
    return jnp.broadcast_to(m, shape) if m.ndim else jnp.full(shape, m)


def mask_params(params: Dict[str, jnp.ndarray], specs: Dict[str, ParamSpec],
                groups: Dict[str, Group], width_rate) -> Dict[str, jnp.ndarray]:
    """Zero the inactive entries of every parameter (distribute-time mask).

    Equivalent to the reference's sub-model extraction (fed.py:165-178): the
    active prefix holds the global values, everything else is zero.
    """
    return {k: v * param_mask(v.shape, specs[k], groups, width_rate) for k, v in params.items()}


def count_masks(params_shapes: Dict[str, Tuple[int, ...]], specs: Dict[str, ParamSpec],
                groups: Dict[str, Group], width_rate,
                label_mask: Optional[jnp.ndarray]) -> Dict[str, jnp.ndarray]:
    """Aggregation-time contribution masks (label-restricted)."""
    return {
        k: param_mask(shape, specs[k], groups, width_rate, label_mask, with_label=True)
        for k, shape in params_shapes.items()
    }
