"""Masked-LM Transformer encoder with HeteroFL width scaling.

Parity: ``src/models/transformer.py`` -- learned positional embedding over
``bptt`` positions (transformer.py:11-20), custom multi-head attention with
separate q/k/v/o projections each followed by a Scaler (transformer.py:54-85,
Scaler is unconditional here, unlike the vision models), post-norm encoder
layers with exact GELU (transformer.py:88-119), 2-layer decoder head
(transformer.py:122-133), Bernoulli(mask_rate) token corruption to an extra
``<mask>`` id = num_tokens applied in *every* forward incl. eval
(transformer.py:148-151), CE over all positions vs. uncorrupted labels.

Slicing rules mirror ``src/fed.py:104-156``: embeddings sliced on the
embedding (column) axis, q/k/v sliced *per head* (fed.py:124-131), decoder
output kept full-width and label-restricted at aggregation (fed.py:263-274 --
token-embedding rows likewise).  Scores are returned class-LAST ``[N, S, V]``
(the reference permutes to ``[N, V, S]`` for torch's CE layout).

Divergence: each encoder layer is initialised independently; torch's
``nn.TransformerEncoder`` deep-copies one layer so all reference layers start
identical (transformer.py:141-142) -- an artifact, not a feature.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp

from ..ops.layers import cross_entropy, embed, linear as _linear, masked_layer_norm, masked_logits, scaler
from .base import ModelDef, normal_init, uniform_fan_in
from .spec import Group, ParamSpec


def make_transformer(num_tokens: int, embedding_size: int, num_heads: int,
                     hidden_size: int, num_layers: int, dropout: float, bptt: int,
                     mask_rate: float, *, mask: bool = True, compute_dtype=None,
                     attn_impl=None, remat: bool = False) -> ModelDef:
    E, H, F = embedding_size, num_heads, hidden_size

    groups = {
        "emb": Group("emb", E),
        "qkv": Group("qkv", E, kind="per_head", num_heads=H),
        "ffn": Group("ffn", F),
        "vocab": Group("vocab", num_tokens, kind="full"),
    }

    specs: Dict[str, ParamSpec] = {
        "embedding.tok.w": ParamSpec({1: "emb"}, label_axis=0),
        "embedding.pos.w": ParamSpec({1: "emb"}),
        "embedding.norm.g": ParamSpec({0: "emb"}),
        "embedding.norm.b": ParamSpec({0: "emb"}),
        "dec.l1.w": ParamSpec({0: "emb", 1: "emb"}),
        "dec.l1.b": ParamSpec({0: "emb"}),
        "dec.norm.g": ParamSpec({0: "emb"}),
        "dec.norm.b": ParamSpec({0: "emb"}),
        "dec.l2.w": ParamSpec({0: "emb"}, label_axis=1),
        "dec.l2.b": ParamSpec({}, label_axis=0),
    }
    for i in range(num_layers):
        p = f"enc{i}"
        for h in ("q", "k", "v"):
            specs[f"{p}.mha.{h}.w"] = ParamSpec({0: "emb", 1: "qkv"})
            specs[f"{p}.mha.{h}.b"] = ParamSpec({0: "qkv"})
        specs[f"{p}.mha.o.w"] = ParamSpec({0: "qkv", 1: "emb"})
        specs[f"{p}.mha.o.b"] = ParamSpec({0: "emb"})
        for n in ("norm1", "norm2"):
            specs[f"{p}.{n}.g"] = ParamSpec({0: "emb"})
            specs[f"{p}.{n}.b"] = ParamSpec({0: "emb"})
        specs[f"{p}.ff.l1.w"] = ParamSpec({0: "emb", 1: "ffn"})
        specs[f"{p}.ff.l1.b"] = ParamSpec({0: "ffn"})
        specs[f"{p}.ff.l2.w"] = ParamSpec({0: "ffn", 1: "emb"})
        specs[f"{p}.ff.l2.b"] = ParamSpec({0: "emb"})

    def init(key: jax.Array) -> Dict[str, jnp.ndarray]:
        params: Dict[str, jnp.ndarray] = {}
        keys = iter(jax.random.split(key, 4 + 6 * num_layers + 2))
        params["embedding.tok.w"] = normal_init(next(keys), (num_tokens + 1, E), 1.0)
        params["embedding.pos.w"] = normal_init(next(keys), (bptt, E), 1.0)
        params["embedding.norm.g"] = jnp.ones(E); params["embedding.norm.b"] = jnp.zeros(E)
        for i in range(num_layers):
            p = f"enc{i}"
            for h in ("q", "k", "v", "o"):
                params[f"{p}.mha.{h}.w"] = uniform_fan_in(next(keys), (E, E), E)
                params[f"{p}.mha.{h}.b"] = jnp.zeros(E)  # ref models/utils.py:8
            params[f"{p}.ff.l1.w"] = normal_init(next(keys), (E, F), 0.02)  # ref transformer.py:104
            params[f"{p}.ff.l1.b"] = jnp.zeros(F)
            params[f"{p}.ff.l2.w"] = normal_init(next(keys), (F, E), 0.02)
            params[f"{p}.ff.l2.b"] = jnp.zeros(E)
            for n in ("norm1", "norm2"):
                params[f"{p}.{n}.g"] = jnp.ones(E); params[f"{p}.{n}.b"] = jnp.zeros(E)
        params["dec.l1.w"] = uniform_fan_in(next(keys), (E, E), E)
        params["dec.l1.b"] = jnp.zeros(E)
        params["dec.norm.g"] = jnp.ones(E); params["dec.norm.b"] = jnp.zeros(E)
        params["dec.l2.w"] = uniform_fan_in(next(keys), (E, num_tokens), E)
        params["dec.l2.b"] = jnp.zeros(num_tokens)
        return params

    apply = _make_apply(num_tokens, E, H, F, num_layers, dropout, bptt, mask_rate, mask, groups, specs,
                        compute_dtype=compute_dtype, attn_impl=attn_impl, remat=remat)

    meta = {"bn_sizes": {}, "kind": "transformer", "num_tokens": num_tokens,
            "embedding_size": E, "num_heads": H, "hidden_size": F,
            "num_layers": num_layers, "bptt": bptt}
    return ModelDef("transformer", init, apply, specs, groups, [], meta)


def _make_apply(num_tokens, E, H, F, num_layers, dropout_rate, bptt, mask_rate, mask_flag,
                groups, specs, compute_dtype=None, attn_impl=None, remat=False):
    linear = partial(_linear, compute_dtype=compute_dtype)
    head_dim = E // H

    def apply(params, batch, *, train: bool, width_rate=1.0, scaler_rate=1.0,
              label_mask=None, bn_mode: str = "batch", bn_state=None,
              sample_weight=None, rng=None, bn_axis=None, attn_override=None):
        assert rng is not None, "transformer apply needs an rng (token corruption)"
        labels = batch["label"]
        N, S = labels.shape
        # Sequence-sharded execution: ``pos_offset`` is this shard's global
        # position and ``seq_full`` the full window length; corruption is
        # drawn over the FULL window on every shard and sliced locally, so a
        # sharded run corrupts exactly like an unsharded one.
        off = batch.get("pos_offset", 0)
        S_full = batch.get("seq_full", S)
        emb_mask = groups["emb"].mask(width_rate)
        k_emb = groups["emb"].active_count(width_rate).astype(jnp.float32)
        temp = jnp.sqrt(jnp.floor(k_emb / H))

        corrupt_key = jax.random.fold_in(rng, 0)
        # dropout keys are derived per site id (NOT an iterator) so remat's
        # replay of a layer block regenerates identical masks; shards of a
        # sequence-sharded window are decorrelated via their position offset
        drop_base = jax.random.fold_in(rng, 1)
        if S_full != S:
            drop_base = jax.random.fold_in(drop_base, off)

        def dropout(x, site: int):
            if not train or dropout_rate == 0.0:
                return x
            key = jax.random.fold_in(drop_base, site)
            keep = jax.random.bernoulli(key, 1.0 - dropout_rate, x.shape)
            return jnp.where(keep, x / (1.0 - dropout_rate), 0.0)

        def sc(x):
            return scaler(x, scaler_rate, train)

        def ln(site, x):
            return masked_layer_norm(x, params[f"{site}.g"], params[f"{site}.b"], emb_mask, k_emb)

        corrupt = jax.random.bernoulli(corrupt_key, mask_rate, (N, S_full))
        if S_full != S:
            corrupt = jax.lax.dynamic_slice(corrupt, (0, off), (N, S))
        src_ids = jnp.where(corrupt, num_tokens, labels)

        # Embedding: scaler(tok) + scaler(pos), LayerNorm, dropout
        # (ref transformer.py:34-37).  ``pos_offset`` supports sequence-
        # sharded execution (each shard embeds its global positions).
        pos = jax.lax.dynamic_slice_in_dim(params["embedding.pos.w"], off, S, axis=0)
        x = sc(embed(params["embedding.tok.w"], src_ids)) + sc(pos)[None, :, :]
        x = dropout(ln("embedding.norm", x), 0)

        def heads_split(t):  # [N,S,E] -> [N,H,S,hd]
            return t.reshape(N, S, H, head_dim).transpose(0, 2, 1, 3)

        def layer_block(x, i):
            p = f"enc{i}"
            q = sc(linear(x, params[f"{p}.mha.q.w"], params[f"{p}.mha.q.b"]))
            k = sc(linear(x, params[f"{p}.mha.k.w"], params[f"{p}.mha.k.b"]))
            v = sc(linear(x, params[f"{p}.mha.v.w"], params[f"{p}.mha.v.b"]))
            q, k, v = heads_split(q), heads_split(k), heads_split(v)
            if compute_dtype is not None:
                q, k, v = (t.astype(compute_dtype) for t in (q, k, v))
            attn_fn = attn_override if attn_override is not None else attn_impl
            if attn_fn is not None:
                o = attn_fn(q, k, v, temp).astype(jnp.float32)
            else:
                scores = jnp.einsum("nhqd,nhkd->nhqk", q, k).astype(jnp.float32) / temp
                attn = jax.nn.softmax(scores, axis=-1)
                if compute_dtype is not None:
                    attn = attn.astype(compute_dtype)
                o = jnp.einsum("nhqk,nhkd->nhqd", attn, v).astype(jnp.float32)
            o = o.transpose(0, 2, 1, 3).reshape(N, S, E)
            o = sc(linear(o, params[f"{p}.mha.o.w"], params[f"{p}.mha.o.b"]))
            x = ln(f"{p}.norm1", x + dropout(o, 1 + 3 * i))
            h = dropout(jax.nn.gelu(sc(linear(x, params[f"{p}.ff.l1.w"], params[f"{p}.ff.l1.b"])),
                                    approximate=False), 2 + 3 * i)
            h = sc(linear(h, params[f"{p}.ff.l2.w"], params[f"{p}.ff.l2.b"]))
            x = ln(f"{p}.norm2", x + dropout(h, 3 + 3 * i))
            return x

        block = jax.checkpoint(layer_block, static_argnums=(1,)) if remat else layer_block
        for i in range(num_layers):
            x = block(x, i)

        # Decoder head (ref transformer.py:131-133).
        d = jax.nn.gelu(sc(linear(x, params["dec.l1.w"], params["dec.l1.b"])), approximate=False)
        d = ln("dec.norm", d)
        out = linear(d, params["dec.l2.w"], params["dec.l2.b"])  # [N,S,V]
        out = masked_logits(out, label_mask, mask_flag)
        loss = cross_entropy(out, labels, sample_weight)
        return {"score": out, "loss": loss}, {}

    return apply
