"""Model factory registry.

Parity with the reference factories (``src/models/conv.py:75-82``,
``src/models/resnet.py:161-208``, ``src/models/transformer.py:165-175``):
constructed widths are ``ceil(model_rate * base)``, the Scaler rate is
``model_rate / global_model_rate``.

``make_model(cfg)`` builds the **global** model; ``make_model(cfg, rate)``
builds a true sliced sub-model (used by the "sliced" strategy and the
equivalence tests).  In the default masked strategy only the global model is
ever constructed.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

from ..config import MODEL_NAMES, ceil_width, scaled_hidden  # noqa: F401
from .base import ModelDef  # noqa: F401
from .conv import make_conv
from .resnet import make_resnet
from .spec import Group, ParamSpec, count_masks, mask_params, param_mask  # noqa: F401
from .transformer import make_transformer

RESNET_BLOCKS = {
    "resnet18": ([2, 2, 2, 2], False),
    "resnet34": ([3, 4, 6, 3], False),
    "resnet50": ([3, 4, 6, 3], True),
    "resnet101": ([3, 4, 23, 3], True),
    "resnet152": ([3, 8, 36, 3], True),
}

# the canonical registry lives in config (jax-free for analysis tooling); keep
# it in lockstep with the families actually buildable here.  A hard raise, not
# an assert: the guard must survive `python -O` (advisor r3).
if MODEL_NAMES != ("conv",) + tuple(RESNET_BLOCKS) + ("transformer",):
    raise ImportError(
        f"config.MODEL_NAMES {MODEL_NAMES!r} out of lockstep with buildable "
        f"families {('conv',) + tuple(RESNET_BLOCKS) + ('transformer',)!r}")


def parse_compute_dtype(cd):
    """cfg['compute_dtype'] -> jnp dtype or None, with validation."""
    import jax.numpy as jnp

    if cd in ("bfloat16", "bf16"):
        return jnp.bfloat16
    if cd in (None, "float32", "f32", "fp32"):
        return None
    raise ValueError(f"Not valid compute_dtype: {cd!r} (float32 | bfloat16)")


def make_model(cfg: Dict[str, Any], model_rate: Optional[float] = None) -> ModelDef:
    name = cfg["model_name"]
    if model_rate is None:
        model_rate = cfg["global_model_rate"]
    scaler_rate = model_rate / cfg["global_model_rate"]
    compute_dtype = parse_compute_dtype(cfg.get("compute_dtype"))
    pallas_norm = bool(cfg.get("pallas_norm", False))
    conv_impl = cfg.get("conv_impl")  # None (direct) | "im2col" (bmm path)
    if conv_impl not in (None, "direct", "im2col"):
        raise ValueError(f"Not valid conv_impl: {conv_impl!r}")
    if conv_impl == "direct":
        conv_impl = None
    if name == "conv":
        model = make_conv(cfg["data_shape"], scaled_hidden(cfg["conv"]["hidden_size"], model_rate),
                          cfg["classes_size"], norm=cfg["norm"], scale=cfg["scale"], mask=cfg["mask"],
                          compute_dtype=compute_dtype, pallas_norm=pallas_norm,
                          conv_impl=conv_impl)
    elif name in RESNET_BLOCKS:
        num_blocks, bottleneck = RESNET_BLOCKS[name]
        model = make_resnet(cfg["data_shape"], scaled_hidden(cfg["resnet"]["hidden_size"], model_rate),
                            num_blocks, cfg["classes_size"], bottleneck=bottleneck,
                            norm=cfg["norm"], scale=cfg["scale"], mask=cfg["mask"],
                            compute_dtype=compute_dtype, pallas_norm=pallas_norm,
                            conv_impl=conv_impl)
    elif name == "transformer":
        t = cfg["transformer"]
        model = make_transformer(
            cfg["num_tokens"], ceil_width(t["embedding_size"], model_rate), t["num_heads"],
            ceil_width(t["hidden_size"], model_rate), t["num_layers"], t["dropout"],
            cfg["bptt"], cfg["mask_rate"], mask=cfg["mask"], compute_dtype=compute_dtype)
    else:
        raise ValueError("Not valid model name")
    model.meta["model_rate"] = model_rate
    model.meta["scaler_rate"] = scaler_rate
    return model
