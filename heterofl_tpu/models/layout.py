"""Explicit layout/dtype policy for the round programs (ISSUE 5 pass 2).

The hot path is per-step-latency-bound, so a hidden relayout (a transpose
or copy XLA inserts to reconcile a parameter's device layout with the
layout the compute wants) is pure tax -- and the K-round superstep scan
pays it per scan trip if the params carry enters the program in a layout
the scan body does not keep.  This module makes the repo's implicit
conventions an explicit, enforceable policy:

* **Activations NHWC, conv kernels HWIO, linear kernels [in, out]** -- the
  native XLA:TPU layouts (``ops/layers.py`` has always computed in these;
  the dimension-numbers constant now lives HERE and layers.py consumes it,
  so the convention has one owner).
* **Width-group axes minor-most**: every parameter's HeteroFL width axis
  (the axis its ``ParamSpec`` slices -- conv output channels, linear
  output features, BN/embedding vectors) must be the trailing axis, which
  row-major packs into the 128-wide TPU lane dimension.  Lane-packed BN
  moment vectors ((C,) trailing) ride the same rule.  ``check_policy``
  audits a model's spec table against it.
* **Pinned program-entry layouts**: ``param_formats`` emits per-leaf
  ``jax.experimental.layout.Layout`` objects (row-major major-to-minor --
  the policy above makes row-major the compute layout) and ``pin_params``
  commits a params tree with them, so the jitted round/superstep programs
  specialise on exactly that layout and the scan carry is never re-laid
  out at the program boundary.  Applied on TPU backends only: XLA:CPU
  (the test mesh) ignores custom device layouts, so there ``pin_params``
  is the identity and the policy is exercised structurally by tests.

Param dtype policy is unchanged and re-stated here: params and optimizer
state are float32; ``compute_dtype`` (bf16) casts MXU operands per-op and
never leaks into stored state.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax

from ..ops.layers import CONV_DIMENSION_NUMBERS  # noqa: F401  (the policy's
# conv convention -- owned by ops/layers.py, re-exported as policy surface)


def check_policy(specs: Dict[str, Any],
                 shapes: Dict[str, Tuple[int, ...]]) -> Dict[str, int]:
    """Audit a model's param table against the lane policy.

    Row-major packs the TRAILING axis into TPU lanes, and the policy is
    that this axis is a FEATURE axis -- either sliced by a width group
    (conv O, hidden-to-hidden linear out) or the label/classes axis of an
    output head; a weight stored transposed (torch-style [out, in]) would
    put a reduction axis in the lanes.  Returns ``{name: trailing_axis}``
    for every >=2D parameter that violates this (empty = compliant).  The
    models test gate keeps it empty for every model family."""
    bad = {}
    for name, shape in shapes.items():
        if len(shape) < 2:
            continue
        spec = specs.get(name)
        last = len(shape) - 1
        groups = getattr(spec, "axis_groups", None) or {}
        if not groups and getattr(spec, "label_axis", None) is None:
            continue  # unsliced parameter: no lane constraint
        if last not in groups and getattr(spec, "label_axis", None) != last:
            bad[name] = last
    return bad


def param_formats(params, mesh=None, spec=None):
    """Per-leaf pinned-layout ``Layout`` objects for a params tree: the
    policy's row-major major-to-minor order (identity permutation), with
    the mesh's replicated sharding attached when given.

    Row-major IS the policy: :func:`check_policy` guarantees the lane axis
    is already trailing, so pinning row-major pins lanes."""
    from jax.experimental.layout import DeviceLocalLayout, Layout
    from jax.sharding import (NamedSharding, PartitionSpec as P,
                              SingleDeviceSharding)

    if mesh is not None:
        sh = NamedSharding(mesh, P() if spec is None else spec)
    else:  # Layout requires a concrete sharding alongside a concrete DLL
        sh = SingleDeviceSharding(jax.devices()[0])

    def one(a):
        return Layout(DeviceLocalLayout(tuple(range(a.ndim))), sh)

    return jax.tree_util.tree_map(one, params)


def pin_params(params, mesh=None, policy: str = "auto", formats=None):
    """Commit a params tree with the policy's pinned device layouts.

    ``policy``: 'auto' pins on TPU backends and passes through elsewhere
    (XLA:CPU ignores custom layouts -- pinning there would only add an
    unconditional copy to the test mesh); 'pinned' forces the pin;
    'none' is the identity.  ``formats``: a precomputed
    :func:`param_formats` tree (the steady-state path caches it -- see
    :class:`ParamPinner`).  Returns the (possibly re-put) tree."""
    if policy == "none":
        return params
    if policy == "auto" and jax.default_backend() != "tpu":
        return params
    if policy not in ("auto", "pinned"):
        raise ValueError(f"Not valid layout_policy: {policy!r}")
    return jax.device_put(params,
                          param_formats(params, mesh) if formats is None
                          else formats)


class ParamPinner:
    """Per-engine layout pin with the Format tree cached.

    The formats are static per (param shapes, mesh), so rebuilding the
    per-leaf Layout objects every dispatch would be per-round host work on
    exactly the steady-state path the staging layer keeps free of per-call
    wraps; the engines construct ONE pinner and call it at their params
    commit.  Validates the policy at construction (loud config errors at
    engine build, not first dispatch); a no-op callable off-TPU under
    'auto' and always under 'none'."""

    def __init__(self, mesh, policy: str = "auto"):
        if policy not in ("auto", "pinned", "none"):
            raise ValueError(f"Not valid layout_policy: {policy!r}")
        self.mesh = mesh
        self.policy = policy
        self.active = policy == "pinned" or (
            policy == "auto" and jax.default_backend() == "tpu")
        self._formats = None

    def __call__(self, params):
        if not self.active:
            return params
        if self._formats is None:
            self._formats = param_formats(params, self.mesh)
        return jax.device_put(params, self._formats)
