"""Norm zoo shared by the vision models.

Parity with the reference's inline norm construction (models/conv.py:13-24,
models/resnet.py:15-31): ``bn`` -> BatchNorm(momentum=None,
track_running_stats=track), ``in`` -> GroupNorm(C, C), ``ln`` -> GroupNorm(1,
C), ``gn`` -> GroupNorm(4, C), ``none`` -> identity.  All masked-width-aware.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax.numpy as jnp

from ..config import NORM_TYPES  # noqa: F401  (canonical registry, re-exported)
from ..ops.layers import batch_norm, dynamic_group_norm


def norm_has_params(norm_type: str) -> bool:
    return norm_type != "none"


def norm_init(norm_type: str, size: int) -> Dict[str, jnp.ndarray]:
    """weight=1, bias=0 (ref models/utils.py:4-10)."""
    if norm_type == "none":
        return {}
    return {"g": jnp.ones(size, jnp.float32), "b": jnp.zeros(size, jnp.float32)}


def apply_norm(norm_type: str, x: jnp.ndarray, g: Optional[jnp.ndarray],
               b: Optional[jnp.ndarray], *, mask: jnp.ndarray, k,
               bn_mode: str = "batch",
               bn_running: Optional[Tuple[jnp.ndarray, jnp.ndarray]] = None,
               sample_weight: Optional[jnp.ndarray] = None,
               bn_axis=None, use_pallas: bool = False):
    """Apply one norm site. Returns ``(y, bn_stats_or_None)``.

    ``mask``/``k``: channel activity mask and active count for the client's
    width (full-width callers pass all-ones / the static size).
    """
    if norm_type == "none":
        return x, None
    if norm_type == "bn":
        if (use_pallas and bn_mode == "batch" and bn_running is None
                and bn_axis is None):
            from ..ops.pallas_norm import batch_norm_pallas

            return batch_norm_pallas(x, g, b, sample_weight=sample_weight), None
        return batch_norm(x, g, b, mode=bn_mode, running=bn_running,
                          sample_weight=sample_weight, axis_name=bn_axis)
    if norm_type == "in":
        # GroupNorm(C, C): per-sample per-channel stats over spatial dims.
        axes = tuple(range(1, x.ndim - 1))
        mean = jnp.mean(x, axis=axes, keepdims=True)
        var = jnp.mean((x - mean) ** 2, axis=axes, keepdims=True)
        return (x - mean) / jnp.sqrt(var + 1e-5) * g + b, None
    if norm_type == "ln":
        return dynamic_group_norm(x, g, b, 1, mask, k), None
    if norm_type == "gn":
        return dynamic_group_norm(x, g, b, 4, mask, k), None
    raise ValueError("Not valid norm")
