"""Client scheduler (ISSUE 9 tentpole): who trains, for how long, and when
their update lands.

HeteroFL's simulation samples a fixed fraction of clients each round and
marches every survivor in lockstep -- the one scenario axis the paper never
varies.  Real federated deployments are dominated by partial availability,
stragglers and asynchrony (PAPERS.md 2405.20431, the practicality survey;
2308.11953 frames staleness-weighted updates).  This package owns the three
scheduling mechanisms, all of which run INSIDE the engines' fused K-round
scan:

* **who** -- replayable availability-trace sampling: a pluggable schedule
  behind :func:`~..fed.core.round_users`.  ``uniform`` (default) is
  today's permutation draw, bit for bit.  ``trace`` replays a recorded
  ``[T, U]`` 0/1 availability matrix (rounds cycle through the rows);
  ``markov`` generates such a trace from a seeded per-client on/off chain
  (:func:`markov_trace`) and then IS a trace -- deterministic, so a run
  (and a checkpoint resume) reproduces identical cohorts, and the
  streaming prefetch pipeline keeps overlapping (the schedule never
  depends on round outputs).  Unavailable slots surface as ``-1`` ids,
  which the engines already treat as padding -- a short round degrades to
  partial participation instead of resampling.
* **for how long** -- deadline-based partial participation: each active
  client draws a per-round local-step budget
  (:func:`~.deadline.deadline_steps`, seeded by ``(round key, user id)``
  so both engines draw identically) and steps past the budget are masked
  out IN the local-step scan -- pure in-scan arithmetic on the masked
  engine, per-level masks on the grouped one.  A slow client contributes
  truncated training instead of dropping (generalising the all-or-nothing
  ``client_failure_rate`` injection).
* **when it lands** -- buffered asynchronous aggregation: with
  ``aggregation='buffered'`` the server applies cohort k's update while
  cohort k+1 trains -- a second scan-carry buffer holds the previous
  round's ``(sums, counts)`` and is applied one round late with a
  staleness-discounted mixing weight (:func:`staleness_weight`).  The
  buffer is checkpointed at superstep boundaries exactly like the
  wire-codec error-feedback residual (:mod:`.buffer`).

Contracts: the lockstep default (``cfg['schedule']=None``) adds ZERO new
program arguments and stays bit-identical to the pre-scheduler engines;
deadline and buffered modes pin superstep == sequential with the buffer
carried bit for bit (tests/test_sched.py) and record accuracy-vs-lockstep
in MEASUREMENTS.md instead of silently weakening the dense contracts.

This module is import-light (numpy only): config validation and the
analytic staleness weight live here; the jax halves are in
:mod:`.deadline` and :mod:`.buffer`.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import numpy as np

#: the schedule registry (``cfg['schedule']['kind']``)
SCHEDULE_KINDS = ("uniform", "trace", "markov")

#: when a cohort's update lands (``cfg['schedule']['aggregation']``)
AGGREGATION_KINDS = ("sync", "buffered")

#: default staleness mixing coefficient of the buffered-async combine
DEFAULT_STALENESS = 0.5

#: default Markov on/off chain parameters (P(off->on), P(on->off), trace
#: length in rounds, trace seed)
DEFAULT_MARKOV = {"p_on": 0.5, "p_off": 0.2, "length": 64, "seed": 0}


def staleness_weight(alpha: float, staleness: int) -> float:
    """Mixing weight of a buffered update that is ``staleness`` rounds old:
    ``alpha / sqrt(1 + s)`` -- the standard polynomial staleness discount
    (FedBuff-style; PAPERS.md 2308.11953 is the convergence frame).  The
    in-scan buffer holds exactly one round, so the engines evaluate this at
    ``s = 1``; the formula is THE one definition both engines and the docs
    share."""
    return float(alpha) / math.sqrt(1.0 + float(staleness))


def markov_trace(num_users: int, length: int, p_on: float, p_off: float,
                 seed: int) -> np.ndarray:
    """A replayable ``[length, num_users]`` uint8 availability trace from a
    seeded two-state Markov chain: each client flips off with ``p_off`` and
    back on with ``p_on`` per round, initialised at the stationary
    distribution.  Deterministic in ``seed`` -- re-running (or resuming)
    regenerates the identical trace, which is what makes Markov scheduling
    a special case of trace replay."""
    if num_users < 1 or length < 1:
        raise ValueError(f"markov trace needs num_users>=1, length>=1 "
                         f"(got {num_users}, {length})")
    rng = np.random.default_rng(int(seed))
    pi_on = p_on / max(p_on + p_off, 1e-12)
    state = rng.random(num_users) < pi_on
    rows = np.empty((length, num_users), np.uint8)
    for t in range(length):
        rows[t] = state
        u = rng.random(num_users)
        state = np.where(state, u >= p_off, u < p_on)
    return rows


class ScheduleSpec:
    """The resolved scheduler configuration: one immutable object the
    engines, the driver, staticcheck and bench all consume (built by
    :func:`resolve_schedule_cfg` -- there is no second parser).

    ``lockstep`` is the contract bit: uniform sampling + no deadline +
    synchronous aggregation, i.e. every new mechanism off -- the engines
    must then build byte-identical programs to the pre-scheduler tree."""

    def __init__(self, kind: str = "uniform",
                 trace: Optional[np.ndarray] = None,
                 markov: Optional[Dict[str, Any]] = None,
                 deadline_min_frac: Optional[float] = None,
                 aggregation: str = "sync",
                 staleness: float = DEFAULT_STALENESS):
        self.kind = kind
        self._trace = trace
        self.markov = markov
        self.deadline_min_frac = deadline_min_frac
        self.aggregation = aggregation
        self.staleness = staleness

    @property
    def lockstep(self) -> bool:
        return (self.kind == "uniform" and self.deadline_min_frac is None
                and self.aggregation == "sync")

    @property
    def buffered(self) -> bool:
        return self.aggregation == "buffered"

    @property
    def has_deadline(self) -> bool:
        return self.deadline_min_frac is not None

    @property
    def trace(self) -> Optional[np.ndarray]:
        """The ``[T, U]`` uint8 availability matrix (``None`` for uniform).
        Markov kinds materialise their trace lazily and cache it -- engines
        that never sample in-jit (host-schedule paths) still share the one
        replayable matrix through this property."""
        if self.kind == "uniform":
            return None
        if self._trace is None and self.kind == "markov":
            m = self.markov
            self._trace = markov_trace(m["num_users"], m["length"],
                                       m["p_on"], m["p_off"], m["seed"])
        return self._trace

    def avail_row(self, epoch: int) -> Optional[np.ndarray]:
        """Round ``epoch``'s availability row (1-based epochs cycle through
        the trace), or ``None`` for uniform -- the host twin of the in-jit
        ``trace[(t - 1) % T]`` index, shared so the two streams cannot
        fork."""
        t = self.trace
        if t is None:
            return None
        return t[(int(epoch) - 1) % t.shape[0]]


def resolve_schedule_cfg(cfg: Dict[str, Any]) -> ScheduleSpec:
    """Validate ``cfg['schedule']`` and return the :class:`ScheduleSpec`.

    THE one validator (the PR 6/8 convention: unknown keys or malformed
    values fail loudly at config time, never as a silent lockstep fallback
    mid-run).  ``None``/absent -> the lockstep spec (zero new behaviour)."""
    raw = cfg.get("schedule")
    if raw is None:
        return ScheduleSpec()
    if not isinstance(raw, dict):
        raise ValueError(f"Not valid schedule: {raw!r} (a dict with keys "
                         f"kind/trace/markov/deadline/aggregation/staleness, "
                         f"or None for lockstep)")
    unknown = set(raw) - {"kind", "trace", "markov", "deadline",
                          "aggregation", "staleness"}
    if unknown:
        raise ValueError(f"Not valid schedule keys: {sorted(unknown)}")
    kind = raw.get("kind", "uniform") or "uniform"
    if kind not in SCHEDULE_KINDS:
        raise ValueError(f"Not valid schedule kind: {kind!r} "
                         f"(one of {SCHEDULE_KINDS})")
    num_users = cfg.get("num_users")
    trace = None
    markov = None
    if kind == "trace":
        t = raw.get("trace")
        if t is None:
            raise ValueError("schedule kind 'trace' needs a 'trace' entry: "
                             "a [rounds, num_users] 0/1 availability matrix "
                             "(nested lists or an array)")
        trace = np.asarray(t)
        if trace.ndim != 2 or trace.size == 0:
            raise ValueError(f"Not valid availability trace shape "
                             f"{trace.shape}: needs [rounds, num_users] "
                             f"with both axes non-empty")
        vals = np.unique(trace)
        if not np.isin(vals, (0, 1)).all():
            raise ValueError(f"Not valid availability trace values "
                             f"{vals.tolist()[:8]}: 0/1 only")
        if num_users is not None and trace.shape[1] != int(num_users):
            raise ValueError(
                f"availability trace covers {trace.shape[1]} users but "
                f"cfg['num_users']={num_users}: the trace's user axis must "
                f"match the federation")
        trace = trace.astype(np.uint8)
    elif kind == "markov":
        m = dict(DEFAULT_MARKOV, **(raw.get("markov") or {}))
        unknown_m = set(m) - {"p_on", "p_off", "length", "seed"}
        if unknown_m:
            raise ValueError(f"Not valid schedule markov keys: "
                            f"{sorted(unknown_m)}")
        for p in ("p_on", "p_off"):
            v = m[p]
            if not isinstance(v, (int, float)) or not 0.0 < float(v) <= 1.0:
                raise ValueError(f"Not valid markov {p}: {v!r} "
                                 f"(a probability in (0, 1])")
        if not isinstance(m["length"], int) or m["length"] < 1:
            raise ValueError(f"Not valid markov length: {m['length']!r} "
                             f"(an int >= 1)")
        if num_users is None:
            raise ValueError("markov schedule needs cfg['num_users'] "
                             "(resolve after process_control)")
        markov = {"p_on": float(m["p_on"]), "p_off": float(m["p_off"]),
                  "length": int(m["length"]), "seed": int(m.get("seed", 0)),
                  "num_users": int(num_users)}
    elif raw.get("trace") is not None or raw.get("markov") is not None:
        raise ValueError(f"schedule kind {kind!r} takes no trace/markov "
                         f"entries (set kind='trace'/'markov')")
    deadline = raw.get("deadline")
    deadline_min_frac = None
    if deadline is not None:
        if not isinstance(deadline, dict) or set(deadline) - {"min_frac"}:
            raise ValueError(f"Not valid schedule deadline: {deadline!r} "
                             f"(a dict {{'min_frac': f}} with f in (0, 1), "
                             f"or None)")
        f = deadline.get("min_frac")
        if not isinstance(f, (int, float)) or not 0.0 < float(f) < 1.0:
            raise ValueError(f"Not valid deadline min_frac: {f!r} (the "
                             f"slowest client's fraction of the full local "
                             f"step budget, in (0, 1); 1.0 would be "
                             f"lockstep -- drop the deadline instead)")
        deadline_min_frac = float(f)
    agg = raw.get("aggregation", "sync") or "sync"
    if agg not in AGGREGATION_KINDS:
        raise ValueError(f"Not valid schedule aggregation: {agg!r} "
                         f"(one of {AGGREGATION_KINDS})")
    staleness = raw.get("staleness", DEFAULT_STALENESS)
    if not isinstance(staleness, (int, float)) \
            or not 0.0 < float(staleness) <= 1.0:
        raise ValueError(f"Not valid schedule staleness: {staleness!r} "
                         f"(the buffered combine's mixing coefficient, in "
                         f"(0, 1])")
    spec = ScheduleSpec(kind=kind, trace=trace, markov=markov,
                        deadline_min_frac=deadline_min_frac,
                        aggregation=agg, staleness=float(staleness))
    # scheduler x engine/codec cross-checks (ISSUE 18): promoted from the
    # driver so a scenario the engines cannot lower refuses at config
    # resolution.  This validator OWNS the scheduler axis in the
    # staticcheck config lattice.
    strategy = cfg.get("strategy", "masked") or "masked"
    if not spec.lockstep and strategy == "sliced":
        raise ValueError(
            "Not valid schedule with strategy='sliced': scenarios "
            "(trace/markov availability, deadline, buffered aggregation) "
            "need a mesh-native strategy ('masked' or 'grouped'); the "
            "sliced debug twin replays the reference host loop")
    if spec.buffered:
        codec = cfg.get("wire_codec", "dense") or "dense"
        if isinstance(codec, dict) and all(v == "dense"
                                           for v in codec.values()):
            codec = "dense"  # an all-dense map collapses to the plain path
        if codec != "dense":
            raise ValueError(
                f"Not valid schedule aggregation='buffered' with "
                f"wire_codec={codec!r}: both add a scan carry with its "
                f"own donation/checkpoint contract -- pick one per "
                f"experiment")
        if strategy == "grouped" \
                and int(cfg.get("superstep_rounds", 1) or 1) <= 1 \
                and (cfg.get("client_store", "eager") or "eager") != "stream":
            raise ValueError(
                "Not valid schedule aggregation='buffered' with strategy="
                "'grouped' at superstep_rounds<=1 and client_store="
                "'eager': the K=1 host-orchestrated path combines in its "
                "own program and has no scan carry to buffer")
    return spec
