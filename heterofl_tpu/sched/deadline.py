"""Deadline stragglers (ISSUE 9): per-client local-step budgets, in-jit.

Real deployments impose a wall-clock deadline per round; slow clients
either drop (the reference's implicit behaviour, generalised by
``client_failure_rate``) or upload whatever they finished.  This module
implements the second, better-behaved semantics: each active client draws
a per-round step budget from a seeded ``(round key, user id)`` stream and
its local-step scan masks out every step past the budget -- the optimizer
update AND the metric contributions gate off together, so a truncated
client contributes exactly its completed steps' training and nothing else.

The draw is pure in-scan arithmetic and engine-invariant: both engines
fold the SAME round key and global user id, and the step budget scales the
SAME static ``E x S`` total, so the masked and grouped engines truncate
identically (the cross-engine equivalence contract survives at its usual
association tolerance).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: PRNG salt of the deadline stream -- disjoint from the engines'
#: per-client (13) and failure (98) salts, fed.core's rate/user salts and
#: the codec salts (compress.codecs)
DEADLINE_SALT = 131


def deadline_steps(key: jax.Array, uids: jnp.ndarray, total_steps: int,
                   min_frac: float) -> jnp.ndarray:
    """Per-client local-step budgets for one round: ``[slots] int32`` in
    ``[ceil(min_frac * total), total]``.

    Each client's speed is an i.i.d. uniform draw from
    ``fold_in(fold_in(round_key, DEADLINE_SALT), uid)`` -- deterministic,
    replayable, identical across engines/placements (global uid keyed, like
    every per-client stream).  ``min_frac`` is the slowest client's
    fraction of the full budget; the ``ceil`` keeps every participant at
    >= 1 completed step, so a deadline round never degenerates to a pure
    dropout round (use ``client_failure_rate`` for crashes)."""
    dkey = jax.random.fold_in(key, DEADLINE_SALT)

    def one(u):
        speed = jax.random.uniform(jax.random.fold_in(dkey, u))
        frac = min_frac + (1.0 - min_frac) * speed
        return jnp.ceil(frac * total_steps).astype(jnp.int32)

    return jax.vmap(one)(jnp.maximum(uids, 0))
