"""Buffered asynchronous aggregation (ISSUE 9): the staleness carry.

With ``cfg['schedule']['aggregation']='buffered'`` the server applies
cohort k's update while cohort k+1 trains: inside the fused K-round scan
the carry grows a second buffer holding the PREVIOUS round's reduced
``(update sums, count masks)`` pair -- flat, in the
:class:`~..ops.fused_update.FlatSpec` layout, stacked ``[2, total]`` --
and each round (a) trains its cohort on params that do NOT yet include the
in-flight update (the simulated overlap) and (b) applies the buffered
one-round-stale update with the staleness-discounted mixing weight
:func:`~.staleness_weight` ``(alpha, s=1)``.  Elements no buffered client
held keep the previous global value (the counted-average stale rule,
unchanged).

The buffer rides the scan carry, leaves the program as an output, and is
checkpointed/restored at superstep boundaries exactly like the wire-codec
error-feedback residual -- :class:`_SchedBufCarry` mirrors
:class:`~..parallel.round_engine._WireCodecCarry`, including the donation
policy: buffered programs donate ONLY the buffer carry, because donating
the replicated params carry alongside a params-sized extra output is the
trigger pattern of the XLA:CPU executable-serialization bug that forced
resid-only donation on the codec programs (see _WireCodecCarry).
"""

from __future__ import annotations

from typing import Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from . import staleness_weight
from ..ops.fused_update import FlatSpec

#: rounds the in-scan buffer holds an update before it lands: the carry is
#: depth-1 by construction (cohort k's update applies while k+1 trains)
BUFFER_STALENESS = 1


def buffered_combine(params: Dict[str, jnp.ndarray], buf: jnp.ndarray,
                     summed: Dict[str, jnp.ndarray],
                     counts: Dict[str, jnp.ndarray], spec: FlatSpec,
                     alpha: float
                     ) -> Tuple[Dict[str, jnp.ndarray], jnp.ndarray]:
    """One buffered-async server step: apply the BUFFERED (one-round-stale)
    update to the globals with weight ``staleness_weight(alpha, 1)`` and
    buffer this round's freshly-reduced ``(summed, counts)`` for the next
    round.  ``buf`` is the ``[2, total]`` flat carry; a zero buffer (first
    round, or no buffered contributor for an element) leaves the globals
    untouched -- the stale rule."""
    w = staleness_weight(alpha, BUFFER_STALENESS)
    bsum, bcnt = spec.unflatten(buf[0]), spec.unflatten(buf[1])
    new_p = {k: jnp.where(bcnt[k] > 0,
                          (1.0 - w) * v + w * (bsum[k] / jnp.maximum(bcnt[k], 1.0)),
                          v)
             for k, v in params.items()}
    new_buf = jnp.stack([spec.flatten(summed), spec.flatten(counts)])
    return new_p, new_buf


class _SchedBufCarry:
    """Shared buffered-aggregation scaffolding of both round engines: the
    device-resident staleness buffer with its checkpoint read/restore pair
    (the :class:`~..parallel.round_engine._WireCodecCarry` pattern -- one
    copy on purpose).

    Expects on ``self``: ``mesh``, ``_sched_spec``, ``_sched_buf``
    (initialised to None)."""

    def _sched_buf_shape(self, params) -> Tuple[int, int]:
        return (2, FlatSpec.of(params).total)

    def _ensure_sched_buf(self, params):
        """The committed staleness carry (zeros on first use): built by a
        jitted program so the buffer is PRIVATE and donation-safe,
        replicated (every device applies the identical buffered update
        post-psum)."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        shape = self._sched_buf_shape(params)
        if self._sched_buf is None or tuple(self._sched_buf.shape) != shape:
            sh = NamedSharding(self.mesh, P())
            # staticcheck: allow(jit-needs-donation): one-time zeros init
            # (nothing to donate); steady-state rounds donate the carry
            self._sched_buf = jax.jit(
                lambda: jnp.zeros(shape, jnp.float32), out_shardings=sh)()
        return self._sched_buf

    def sched_buf_host(self):
        """Host copy of the staleness buffer (checkpointing); None for sync
        aggregation or before the first buffered round."""
        if self._sched_buf is None:
            return None
        # replicated carry: every process holds the full value, so the
        # multi-process path reads its local replica (host_fetch)
        from ..parallel.staging import host_fetch
        return host_fetch(self._sched_buf)

    def set_sched_buf(self, arr) -> None:
        """Restore the staleness buffer from a checkpoint (resume):
        committed through a jitted copy so the restored buffer is
        donation-safe."""
        from jax.sharding import NamedSharding, PartitionSpec as P

        sh = NamedSharding(self.mesh, P())
        # staticcheck: allow(no-asarray): checkpoint-restore host
        # normalization; the carry reaches the mesh via the explicit
        # device_put + jitted private copy below
        host = np.asarray(arr, np.float32)
        from ..parallel.staging import commit_global
        # staticcheck: allow(jit-needs-donation): one-time restore copy
        # severing host-buffer aliasing; donating its input would free the
        # caller's checkpoint array
        self._sched_buf = jax.jit(lambda t: t + 0, out_shardings=sh)(
            commit_global(host, sh))
