"""Device-feed preparation: token batchify, bptt windowing, and stacking of
per-client shards into dense ``[num_clients, ...]`` arrays for the jitted
federated round.

The reference streams per-client Python ``DataLoader``\\ s sequentially
(``src/train_classifier_fed.py:177-180``); here all active clients' shards are
materialised as one stacked array so local training vectorises with ``vmap``
and shards over the ``clients`` mesh axis.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np


def process_dataset(cfg: Dict, dataset: Dict) -> Tuple[Dict, Dict]:
    """Derive data-dependent cfg fields and batchify LM streams
    (ref src/utils.py:100-110). Returns (new_cfg, new_dataset)."""
    import copy

    cfg = copy.deepcopy(cfg)
    dataset = dict(dataset)
    if hasattr(dataset["train"], "classes_size"):
        cfg["classes_size"] = dataset["train"].classes_size
        cfg["data_shape"] = list(dataset["train"].data.shape[1:])
    else:
        cfg["vocab"] = dataset["train"].vocab
        cfg["num_tokens"] = len(dataset["train"].vocab)
        cfg["classes_size"] = cfg["num_tokens"]
        for split in dataset:
            ds = dataset[split]
            bs = cfg["batch_size"][split]
            dataset[split] = type(ds)(batchify(ds.token, bs), ds.vocab, ds.data_name)
    return cfg, dataset


def batchify(token: np.ndarray, batch_size: int) -> np.ndarray:
    """Reshape a 1-D token stream to ``[batch_size, -1]`` (ref utils.py:353-357)."""
    num_batch = len(token) // batch_size
    return token[: num_batch * batch_size].reshape(batch_size, -1)


def bptt_windows(rows: np.ndarray, bptt: int) -> List[np.ndarray]:
    """Split ``[R, T]`` rows into windows of ``bptt`` along T (ref data.py:136-150).

    The final window may be shorter, matching ``BatchDataset``.
    """
    return [rows[:, s: s + bptt] for s in range(0, rows.shape[1], bptt)]


def stack_windows(wins: List[np.ndarray], bptt: int) -> Tuple[np.ndarray, np.ndarray]:
    """Stack bptt windows into ``([S, R, bptt], weights)``; a short tail
    window is zero-padded with zero position weights."""
    full = [w for w in wins if w.shape[1] == bptt]
    if full:
        xs = np.stack(full)
    else:
        r = wins[0].shape[0] if wins else 0
        xs = np.zeros((0, r, bptt), np.int64)
    ws = np.ones(xs.shape, np.float32)
    tail = wins[-1] if wins and wins[-1].shape[1] < bptt else None
    if tail is not None:
        pad = bptt - tail.shape[1]
        xs = np.concatenate([xs, np.pad(tail, ((0, 0), (0, pad)))[None]], 0)
        ws = np.concatenate([ws, np.pad(np.ones(tail.shape, np.float32),
                                        ((0, 0), (0, pad)))[None]], 0)
    return xs, ws


def stack_client_shards(data: np.ndarray, target: np.ndarray,
                        data_split: Dict[int, List[int]], user_idx: List[int]
                        ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Gather the selected users' shards into dense arrays.

    Returns ``(x, y, sample_mask)`` with shapes ``[C, N, ...]``, ``[C, N]``,
    ``[C, N]`` where ``N`` is the max shard size among the selected users;
    shorter shards are padded by repeating their first items with
    ``sample_mask == 0`` so padded examples carry zero loss weight.
    """
    from .. import native

    sizes = [len(data_split[u]) for u in user_idx]
    n = max(sizes)
    all_idx, ms = [], []
    for u, sz in zip(user_idx, sizes):
        idx = np.asarray(data_split[u], dtype=np.int64)
        if sz < n:
            pad = idx[np.arange(n - sz) % sz]
            idx = np.concatenate([idx, pad])
        all_idx.append(idx)
        m = np.zeros(n, dtype=np.float32)
        m[:sz] = 1.0
        ms.append(m)
    flat = np.concatenate(all_idx)
    x = native.permute_gather(data, flat).reshape((len(user_idx), n) + data.shape[1:])
    y = target[flat].reshape(len(user_idx), n)
    return x, y, np.stack(ms)


def stack_client_token_rows(token_rows: np.ndarray, data_split: Dict[int, List[int]],
                            user_idx: List[int]) -> np.ndarray:
    """LM analogue: gather each user's batchified rows -> ``[C, R, T]``.

    After ``batchify`` each "example" is a row of the token matrix; iid
    splitting assigns whole rows to users (ref data.py:64-65 with
    ``train_transformer_fed.py:161``).
    """
    rows = [token_rows[np.asarray(data_split[u], dtype=np.int64)] for u in user_idx]
    r = max(x.shape[0] for x in rows)
    assert all(x.shape[0] == r for x in rows), "per-user row counts must match"
    return np.stack(rows)


def label_split_masks(label_split, num_users: int, classes_size: int) -> np.ndarray:
    """Dense ``[num_users, classes_size]`` 0/1 masks from per-user label lists.

    Replaces the reference's variable-length ``label_split`` index lists
    (``src/fed.py:193-198``) with a static-shape mask, as required for XLA.
    """
    m = np.zeros((num_users, classes_size), dtype=np.float32)
    for i in range(num_users):
        m[i, np.asarray(label_split[i], dtype=np.int64)] = 1.0
    return m
