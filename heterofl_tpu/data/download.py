"""Dataset acquisition: md5-checked downloads and archive extraction.

Parity with the reference's download path (ref src/datasets/utils.py:16-110):
``download_url`` fetches with an https->http retry and validates the md5;
``extract_file`` dispatches on the archive suffix.  The loaders in
:mod:`.datasets` are offline-first (they pick up standard on-disk formats);
these helpers complete the story for boxes WITH egress.  stdlib-only.
"""

from __future__ import annotations

import gzip
import hashlib
import os
import tarfile
import zipfile
from typing import Optional


def calculate_md5(path: str, chunk_size: int = 1024 * 1024) -> str:
    md5 = hashlib.md5()
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(chunk_size), b""):
            md5.update(chunk)
    return md5.hexdigest()


def check_integrity(path: str, md5: Optional[str] = None) -> bool:
    """True iff ``path`` exists and (when given) matches ``md5``
    (ref src/datasets/utils.py:82-87)."""
    if not os.path.isfile(path):
        return False
    return md5 is None or calculate_md5(path) == md5


def download_url(url: str, root: str, filename: Optional[str] = None,
                 md5: Optional[str] = None) -> str:
    """Fetch ``url`` into ``root/filename`` unless an md5-verified copy is
    already there; https->http retry on failure; raise on a bad checksum
    (ref src/datasets/utils.py:90-108).  Returns the local path."""
    import urllib.request

    filename = filename or os.path.basename(url)
    path = os.path.join(root, filename)
    os.makedirs(root, exist_ok=True)
    if check_integrity(path, md5):
        return path
    try:
        urllib.request.urlretrieve(url, path)
    except OSError:
        if not url.startswith("https:"):
            raise
        urllib.request.urlretrieve(url.replace("https:", "http:", 1), path)
    if not check_integrity(path, md5):
        raise RuntimeError(f"Not valid downloaded file: {path}")
    return path


def extract_file(src: str, dest: Optional[str] = None, delete: bool = False) -> None:
    """Extract zip / tar / tar.gz / tgz / gz next to ``src`` (or into
    ``dest``), optionally deleting the archive (ref
    src/datasets/utils.py:111-129)."""
    dest = os.path.dirname(src) if dest is None else dest
    name = os.path.basename(src)
    if name.endswith(".zip"):
        with zipfile.ZipFile(src) as zf:
            zf.extractall(dest)
    elif name.endswith((".tar.gz", ".tgz")):
        with tarfile.open(src, "r:gz") as tf:
            tf.extractall(dest, filter="data")
    elif name.endswith(".tar"):
        with tarfile.open(src) as tf:
            tf.extractall(dest, filter="data")
    elif name.endswith(".gz"):
        out = os.path.join(dest, os.path.basename(src)[: -len(".gz")])
        with gzip.open(src, "rb") as zf, open(out, "wb") as f:
            f.write(zf.read())
    else:
        raise ValueError(f"Not valid archive: {src}")
    if delete:
        os.remove(src)
