"""Client data partitioning: iid equal shards and non-iid label-shard
assignment.

Parity: ``src/data.py:48-110``. Randomness comes from an explicit
``numpy.random.Generator`` instead of torch's global state; statistical
behaviour matches (uniform permutations / shard draws).
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

import numpy as np


def _labels_of(dataset) -> np.ndarray:
    if hasattr(dataset, "target"):
        return np.asarray(dataset.target)
    # LM datasets: the "label" is the token array itself (ref data.py:64-65).
    return np.asarray(dataset.token)


def iid(dataset, num_users: int, rng: np.random.Generator) -> Tuple[Dict[int, List[int]], Dict[int, List[int]]]:
    """Random equal shards + per-user observed label sets (ref data.py:61-76)."""
    label = _labels_of(dataset)
    n = len(dataset)
    num_items = n // num_users
    perm = rng.permutation(n)
    data_split: Dict[int, List[int]] = {}
    label_split: Dict[int, List[int]] = {}
    for i in range(num_users):
        shard = perm[i * num_items: (i + 1) * num_items]
        data_split[i] = shard.tolist()
        label_split[i] = np.unique(label[shard].reshape(-1)).tolist()
    return data_split, label_split


def non_iid(dataset, num_users: int, rng: np.random.Generator,
            shard_per_user: int, classes_size: int,
            label_split: Optional[List[List[int]]] = None
            ) -> Tuple[Dict[int, List[int]], List[List[int]]]:
    """"non-iid-N": each user holds shards of N distinct labels (ref data.py:79-110).

    Per-label index pools are cut into ``shard_per_class`` equal shards
    (leftovers appended one-per-shard); users draw ``shard_per_user`` shards
    according to ``label_split`` (generated here on the train split and reused
    verbatim for the test split).
    """
    label = _labels_of(dataset)
    label_idx_split: Dict[int, List[int]] = {}
    for i in range(len(label)):
        label_idx_split.setdefault(int(label[i]), []).append(i)
    shard_per_class = int(shard_per_user * num_users / classes_size)
    # Same implicit constraints as the reference (data.py:90,101-103), which
    # either crashes there with an opaque reshape error or silently floors the
    # per-user shard count: shards must tile users and classes exactly.
    if (shard_per_class < 1
            or (shard_per_user * num_users) % classes_size != 0
            or (classes_size * shard_per_class) % num_users != 0):
        raise ValueError(
            f"non-iid-{shard_per_user} needs shard_per_user*num_users to tile "
            f"classes_size exactly (and classes*shards to tile users): got "
            f"num_users={num_users}, classes_size={classes_size}; try "
            f"num_users a multiple of {classes_size}")
    pools: Dict[int, List[np.ndarray]] = {}
    for label_i, label_idx in label_idx_split.items():
        num_leftover = len(label_idx) % shard_per_class
        leftover = label_idx[-num_leftover:] if num_leftover > 0 else []
        body = np.array(label_idx[:-num_leftover]) if num_leftover > 0 else np.array(label_idx)
        shards = [s for s in body.reshape(shard_per_class, -1)]
        for i, extra in enumerate(leftover):
            shards[i] = np.concatenate([shards[i], [extra]])
        pools[label_i] = shards
    if label_split is None:
        flat = np.array(list(range(classes_size)) * shard_per_class)
        flat = flat[rng.permutation(len(flat))]
        label_split = [np.unique(row).tolist() for row in flat.reshape(num_users, -1)]
    data_split: Dict[int, List[int]] = {i: [] for i in range(num_users)}
    for i in range(num_users):
        for label_i in label_split[i]:
            pick = int(rng.integers(len(pools[label_i])))
            data_split[i].extend(pools[label_i].pop(pick).tolist())
    return data_split, label_split


def span_population(num_items: int, num_users: int, shard_size: int,
                    stride: int = 9973) -> Tuple[np.ndarray, np.ndarray]:
    """Synthetic million-user population layout: per-user contiguous
    ``(start, size)`` windows onto a shared sample pool of ``num_items``
    items -- O(num_users) metadata, no index lists, no densified stacks
    (the ``ClientStore.from_spans`` input).

    Users window onto the pool at a fixed odd ``stride`` so neighbouring
    users see decorrelated (but overlapping) shards; every user gets the
    same ``shard_size`` (static shapes: one program for the whole
    population).  This is how a population larger than the physical dataset
    is simulated -- the reference's disjoint iid split caps users at
    ``num_items / shard_size``, which a million-user run cannot satisfy."""
    if shard_size <= 0 or shard_size > num_items:
        raise ValueError(f"shard_size {shard_size} must be in [1, {num_items}]")
    hi = num_items - shard_size + 1
    # a stride sharing a factor with hi collapses the walk onto
    # hi/gcd distinct starts (gcd == hi: every user gets THE SAME shard)
    # -- bump to the next stride coprime to hi so the window set always
    # cycles through all hi offsets
    stride = max(1, stride)
    while math.gcd(stride, hi) != 1:
        stride += 1
    starts = (np.arange(num_users, dtype=np.int64) * stride) % hi
    sizes = np.full(num_users, shard_size, np.int64)
    return starts, sizes


def split_dataset(dataset, num_users: int, data_split_mode: str, rng: np.random.Generator,
                  classes_size: Optional[int] = None):
    """Split train and test for all users (ref data.py:48-58)."""
    data_split = {}
    if data_split_mode == "iid":
        data_split["train"], label_split = iid(dataset["train"], num_users, rng)
        data_split["test"], _ = iid(dataset["test"], num_users, rng)
    elif "non-iid" in data_split_mode:
        shard_per_user = int(data_split_mode.split("-")[-1])
        cs = classes_size if classes_size is not None else dataset["train"].classes_size
        data_split["train"], label_split = non_iid(dataset["train"], num_users, rng, shard_per_user, cs)
        data_split["test"], _ = non_iid(dataset["test"], num_users, rng, shard_per_user, cs, label_split)
        label_split = {i: label_split[i] for i in range(num_users)}
    else:
        raise ValueError("Not valid data split mode")
    return data_split, label_split
