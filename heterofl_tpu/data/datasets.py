"""Dataset ingestion: offline binary parsers with a deterministic synthetic
fallback (this environment has no network egress).

Parity targets: ``src/data.py:10-34`` (registry + transforms),
``src/datasets/mnist.py`` (idx-ubyte parsing), ``src/datasets/cifar.py``
(pickle batches), ``src/datasets/lm.py`` (token files + Vocab).

Images are kept as raw ``uint8`` NHWC; normalisation and train-time
augmentation happen **on device** inside the jitted client step
(:mod:`heterofl_tpu.ops.augment`), which is the TPU-native replacement for the
reference's torchvision transform pipeline.
"""

from __future__ import annotations

import gzip
import os
import pickle
import struct
import tarfile
import zipfile
from dataclasses import dataclass, field
from typing import Any, Dict, Optional

import numpy as np

from .vocab import Vocab

# Per-channel normalisation stats, parity with src/data.py:15-27 plus the
# standard CIFAR100 values (the reference declares CIFAR100 in its config
# tables but never wires transforms for it).
DATASET_STATS = {
    "MNIST": ((0.1307,), (0.3081,)),
    "FashionMNIST": ((0.2860,), (0.3530,)),
    "CIFAR10": ((0.4914, 0.4822, 0.4465), (0.2023, 0.1994, 0.2010)),
    "CIFAR100": ((0.5071, 0.4865, 0.4409), (0.2673, 0.2564, 0.2762)),
}


@dataclass
class ArrayDataset:
    """In-memory labelled image dataset (NHWC uint8)."""

    data: np.ndarray
    target: np.ndarray
    classes_size: int
    data_name: str
    augment: bool = False  # train split of CIFAR: random crop + flip on device

    def __len__(self) -> int:
        return len(self.data)

    def __getitem__(self, i):
        return {"img": self.data[i], "label": self.target[i]}


@dataclass
class TokenDataset:
    """Token-stream LM dataset; ``token`` is 1-D before ``batchify`` and
    2-D ``[batch_size, T]`` after (ref src/utils.py:353-357)."""

    token: np.ndarray
    vocab: Vocab
    data_name: str
    extras: Dict[str, Any] = field(default_factory=dict)

    def __len__(self) -> int:
        return len(self.token)

    def __getitem__(self, i):
        return {"label": self.token[i]}


# ---------------------------------------------------------------------------
# Binary parsers (offline-first)
# ---------------------------------------------------------------------------

def _read_idx(path: str) -> np.ndarray:
    """Parse an IDX (ubyte) file, gzip-transparent (ref src/datasets/mnist.py:159-180).

    Uncompressed files go through the native C++ parser when available."""
    if not path.endswith(".gz"):
        from .. import native

        arr = native.read_idx(path)
        if arr is not None:
            return arr
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        magic = struct.unpack(">I", f.read(4))[0]
        ndim = magic & 0xFF
        dims = struct.unpack(">" + "I" * ndim, f.read(4 * ndim))
        data = np.frombuffer(f.read(), dtype=np.uint8)
    return data.reshape(dims)


_MNIST_FILES = {
    "train": ("train-images-idx3-ubyte", "train-labels-idx1-ubyte"),
    "test": ("t10k-images-idx3-ubyte", "t10k-labels-idx1-ubyte"),
}


def _find(root: str, name: str) -> Optional[str]:
    for cand in (name, name + ".gz"):
        for sub in ("", "raw"):
            p = os.path.join(root, sub, cand)
            if os.path.exists(p):
                return p
    return None


def _load_mnist_like(root: str, split: str, data_name: str) -> Optional[ArrayDataset]:
    img_name, lbl_name = _MNIST_FILES[split]
    img_p, lbl_p = _find(root, img_name), _find(root, lbl_name)
    if img_p is None or lbl_p is None:
        return None
    imgs = _read_idx(img_p)[..., None]  # [N,28,28,1]
    labels = _read_idx(lbl_p).astype(np.int64)
    return ArrayDataset(imgs, labels, 10, data_name)


def _load_cifar_bin(root: str, split: str, data_name: str) -> Optional[ArrayDataset]:
    """Parse the CIFAR *binary* distribution natively (C++ parser)."""
    from .. import native

    if data_name == "CIFAR10":
        subdir, label_bytes, classes = "cifar-10-batches-bin", 1, 10
        files = [f"data_batch_{i}.bin" for i in range(1, 6)] if split == "train" \
            else ["test_batch.bin"]
    else:
        subdir, label_bytes, classes = "cifar-100-binary", 2, 100
        files = ["train.bin"] if split == "train" else ["test.bin"]
    base = None
    for sub in ("", "raw"):
        p = os.path.join(root, sub, subdir)
        if os.path.isdir(p):
            base = p
            break
    if base is None:
        return None
    imgs_parts, lab_parts = [], []
    for fn in files:
        path = os.path.join(base, fn)
        if not os.path.exists(path):
            return None
        n = os.path.getsize(path) // (label_bytes + 3072)
        out = native.read_cifar_bin(path, n, label_bytes)
        if out is None:
            # pure-NumPy fallback: same record layout, no native lib needed
            raw = np.fromfile(path, np.uint8, n * (label_bytes + 3072))
            rec = raw.reshape(n, label_bytes + 3072)
            labels = rec[:, label_bytes - 1].astype(np.int64)
            imgs = rec[:, label_bytes:].reshape(n, 3, 32, 32).transpose(0, 2, 3, 1)
            out = (np.ascontiguousarray(imgs), labels)
        imgs_parts.append(out[0])
        lab_parts.append(out[1])
    return ArrayDataset(np.concatenate(imgs_parts), np.concatenate(lab_parts), classes,
                        data_name, augment=(split == "train"))


def _load_cifar(root: str, split: str, data_name: str) -> Optional[ArrayDataset]:
    """Parse CIFAR10/100 python-pickle batches (ref src/datasets/cifar.py:109-119);
    the binary distribution is handled by the native parser first."""
    ds = _load_cifar_bin(root, split, data_name)
    if ds is not None:
        return ds
    if data_name == "CIFAR10":
        archive, subdir = "cifar-10-python.tar.gz", "cifar-10-batches-py"
        files = [f"data_batch_{i}" for i in range(1, 6)] if split == "train" else ["test_batch"]
        label_key, classes = b"labels", 10
    else:
        archive, subdir = "cifar-100-python.tar.gz", "cifar-100-python"
        files = ["train"] if split == "train" else ["test"]
        label_key, classes = b"fine_labels", 100

    def read_entry(raw: bytes):
        entry = pickle.loads(raw, encoding="bytes")
        data = entry[b"data"].reshape(-1, 3, 32, 32).transpose(0, 2, 3, 1)  # -> NHWC
        return data, np.array(entry[label_key], dtype=np.int64)

    base = os.path.join(root, subdir)
    if os.path.isdir(base):
        parts = []
        for fn in files:
            with open(os.path.join(base, fn), "rb") as f:
                parts.append(read_entry(f.read()))
    else:
        tar_p = None
        for sub in ("", "raw"):
            p = os.path.join(root, sub, archive)
            if os.path.exists(p):
                tar_p = p
                break
        if tar_p is None:
            return None
        parts = []
        with tarfile.open(tar_p, "r:gz") as tf:
            for fn in files:
                member = tf.getmember(f"{subdir}/{fn}")
                parts.append(read_entry(tf.extractfile(member).read()))
    data = np.concatenate([p[0] for p in parts])
    target = np.concatenate([p[1] for p in parts])
    return ArrayDataset(data, target, classes, data_name, augment=(split == "train"))


_EMNIST_CLASSES = {"byclass": 62, "bymerge": 47, "balanced": 47, "letters": 26,
                   "digits": 10, "mnist": 10}


def _emnist_subset(subset) -> str:
    """Normalise + validate an EMNIST subset name (cfg default 'label' -- the
    reference's target-key field -- maps to 'balanced')."""
    if subset in ("label", None, ""):
        return "balanced"
    if subset not in _EMNIST_CLASSES:
        raise ValueError(f"Not valid EMNIST subset: {subset!r} "
                         f"(one of {sorted(_EMNIST_CLASSES)})")
    return subset


def _load_emnist(root: str, split: str, subset: str) -> Optional[ArrayDataset]:
    """EMNIST idx files (ref src/datasets/mnist.py EMNIST subsets)."""
    subset = _emnist_subset(subset)
    img_p = _find(root, f"emnist-{subset}-{split}-images-idx3-ubyte")
    lbl_p = _find(root, f"emnist-{subset}-{split}-labels-idx1-ubyte")
    if img_p is None or lbl_p is None:
        return None
    # EMNIST ships images column-major; transpose to match the reference
    # pipeline (ref src/datasets/mnist.py EMNIST np.transpose(img, [0,2,1])).
    imgs = _read_idx(img_p).transpose(0, 2, 1)[..., None]
    labels = _read_idx(lbl_p).astype(np.int64)
    if subset == "letters":
        labels = labels - 1  # letters are 1-indexed upstream
    return ArrayDataset(imgs, labels, _EMNIST_CLASSES[subset], "EMNIST")


def _class_dirs(base: str):
    """Deepest directories containing image files, sorted."""
    out = []
    for dirpath, _, filenames in sorted(os.walk(base)):
        if any(f.lower().endswith((".png", ".jpg", ".jpeg")) for f in filenames):
            out.append(dirpath)
    return out


def _load_image_folder(root: str, split: str, data_name: str,
                       size: Optional[tuple] = None) -> Optional[ArrayDataset]:
    """Generic class-per-subdirectory image tree (ref src/datasets/folder.py):
    ``{root}/{split}/{class_name}/*.png|jpg``.

    Omniglot follows the reference's split (ref src/datasets/omniglot.py):
    ONE shared class enumeration over ``images_background`` +
    ``images_evaluation`` (1623 characters), split per-example by drawing
    index (``_NN`` suffix <= 10 -> train, > 10 -> test).

    Mixed image sizes are resized to the first image's size (``size``
    overrides).
    """
    try:
        from PIL import Image
    except ImportError:
        return None

    def find_dir(sub):
        for s in (sub, os.path.join("raw", sub)):
            p = os.path.join(root, s)
            if os.path.isdir(p):
                return p
        return None

    if data_name == "Omniglot":
        bases = [b for b in (find_dir("images_background"), find_dir("images_evaluation"))
                 if b is not None]
        if not bases:
            return None
        classes = [d for b in bases for d in _class_dirs(b)]
    else:
        base = find_dir(split)
        if base is None:
            return None
        classes = _class_dirs(base)
        if data_name == "ImageNet":
            # ILSVRC synset hierarchy: when meta.mat is present, the label
            # order follows the meta's leaf-synset order, not the sorted
            # directory walk (ref src/datasets/imagenet.py:102-120 via
            # make_tree/make_flat_index) -- sorted enumeration would label
            # nested synsets differently than the reference.
            meta = next((p for sub in ("", "raw", "data", os.path.join("raw", "data"))
                         if os.path.isfile(p := os.path.join(root, sub, "meta.mat"))), None)
            if meta is not None:
                try:
                    from .hierarchy import imagenet_meta_tree

                    _, wnids, _ = imagenet_meta_tree(meta)
                    by_name = {os.path.basename(d): d for d in classes}
                    ordered = [by_name[w] for w in wnids if w in by_name]
                    if ordered:
                        classes = ordered
                except ImportError:  # scipy absent: keep sorted order
                    pass
                except Exception as e:  # corrupt/v7.3 meta.mat: warn, keep sorted
                    import warnings

                    warnings.warn(f"ignoring unreadable {meta}: {e}")
    if not classes:
        return None

    def want(fn: str, pos: int) -> bool:
        if data_name != "Omniglot":
            return True
        stem = os.path.splitext(fn)[0]
        try:
            draw = int(stem.rsplit("_", 1)[-1])
        except ValueError:
            draw = pos + 1
        return (draw <= 10) == (split == "train")

    imgs, labels = [], []
    target_size = size
    for ci, cdir in enumerate(classes):
        files = [f for f in sorted(os.listdir(cdir))
                 if f.lower().endswith((".png", ".jpg", ".jpeg"))]
        for pos, fn in enumerate(files):
            if not want(fn, pos):
                continue
            with Image.open(os.path.join(cdir, fn)) as im:
                im = im.convert("L" if data_name == "Omniglot" else "RGB")
                if target_size is None:
                    target_size = im.size
                elif im.size != target_size:
                    im = im.resize(target_size)
                arr = np.asarray(im, np.uint8)
            if arr.ndim == 2:
                arr = arr[..., None]
            imgs.append(arr)
            labels.append(ci)
    if not imgs:
        return None
    return ArrayDataset(np.stack(imgs), np.asarray(labels, np.int64), len(classes), data_name)


_LM_FILES = {
    "PennTreebank": {"train": "ptb.train.txt", "valid": "ptb.valid.txt", "test": "ptb.test.txt", "dir": ""},
    "WikiText2": {"train": "wiki.train.tokens", "valid": "wiki.valid.tokens", "test": "wiki.test.tokens",
                  "dir": "wikitext-2"},
    "WikiText103": {"train": "wiki.train.tokens", "valid": "wiki.valid.tokens", "test": "wiki.test.tokens",
                    "dir": "wikitext-103"},
}


def _lm_path(root: str, data_name: str, split: str) -> Optional[str]:
    spec = _LM_FILES[data_name]
    for sub in ("", "raw"):
        for mid in (spec["dir"], ""):
            p = os.path.join(root, sub, mid, spec[split])
            if os.path.exists(p):
                return p
    return None


def _read_tokens(vocab: Vocab, path: str, build: bool) -> np.ndarray:
    """Whitespace tokenisation + ``<eos>`` per line (ref src/datasets/lm.py:202-219)."""
    out = []
    with open(path, "r", encoding="utf-8") as f:
        for line in f:
            for symbol in line.split() + ["<eos>"]:
                if build:
                    vocab.add(symbol)
                else:
                    out.append(vocab[symbol])
    return np.array(out, dtype=np.int64) if not build else None


_VOCAB_CACHE: Dict[str, Vocab] = {}


def _load_lm(root: str, split: str, data_name: str) -> Optional[TokenDataset]:
    # Auto-extract a downloaded zip if present but unextracted.
    for sub in ("", "raw"):
        for z in (f"wikitext-2-v1.zip", f"wikitext-103-v1.zip"):
            zp = os.path.join(root, sub, z)
            if os.path.exists(zp) and _lm_path(root, data_name, "train") is None:
                with zipfile.ZipFile(zp) as zf:
                    zf.extractall(os.path.join(root, sub))
    train_p = _lm_path(root, data_name, "train")
    split_p = _lm_path(root, data_name, split)
    if train_p is None or split_p is None:
        return None
    # Vocab is built from the train stream only (ref lm.py:158-160; valid/test
    # OOV symbols map to <ukn>), cached per train file so multi-split loads
    # parse the (potentially huge) train corpus for the vocab only once.
    vocab = _VOCAB_CACHE.get(train_p)
    if vocab is None:
        vocab = Vocab()
        _read_tokens(vocab, train_p, build=True)
        _VOCAB_CACHE[train_p] = vocab
    token = _read_tokens(vocab, split_p, build=False)
    return TokenDataset(token, vocab, data_name)


# ---------------------------------------------------------------------------
# Deterministic synthetic fallback
# ---------------------------------------------------------------------------

def synthetic_vision(data_name: str, split: str, n: Optional[int] = None, seed: int = 0,
                     subset: str = "balanced") -> ArrayDataset:
    """Class-conditional random images: mean brightness and a per-class spatial
    stripe depend on the label so that models can actually learn from it."""
    shape = (28, 28, 1) if data_name in ("MNIST", "FashionMNIST", "EMNIST") else (32, 32, 3)
    if data_name == "EMNIST":
        classes = _EMNIST_CLASSES[_emnist_subset(subset)]
    else:
        classes = {"CIFAR100": 100}.get(data_name, 10)
    if n is None:
        n = 2000 if split == "train" else 500
    rng = np.random.default_rng(seed + (0 if split == "train" else 1))
    labels = rng.integers(0, classes, size=n).astype(np.int64)
    imgs = rng.integers(0, 96, size=(n,) + shape).astype(np.int64)
    h, w = shape[0], shape[1]
    lab = labels[:, None, None, None]
    # Two class-dependent stripes (row = label mod H, col = a label hash mod W)
    # plus a bounded brightness shift: every class <= H*W stays separable.
    row = np.arange(h)[None, :, None, None]
    col = np.arange(w)[None, None, :, None]
    imgs = (imgs
            + 40 * (row == lab % h)
            + 40 * (col == (lab * 7 + 3) % w)
            + 8 * (lab % 8))
    return ArrayDataset(np.clip(imgs, 0, 255).astype(np.uint8), labels, classes, data_name,
                        augment=(split == "train" and data_name.startswith("CIFAR")))


def synthetic_lm(data_name: str, split: str, n_tokens: int = 200_000, vocab_size: int = 512,
                 seed: int = 0) -> TokenDataset:
    """Markov-ish token stream over a synthetic vocabulary."""
    vocab = Vocab()
    for i in range(vocab_size - 2):
        vocab.add(f"w{i}")
    rng = np.random.default_rng(seed + (0 if split == "train" else 1))
    # order-1 structure: next token correlated with current one.
    token = np.empty(n_tokens, dtype=np.int64)
    token[0] = 2
    jumps = rng.integers(0, vocab_size, size=n_tokens)
    noise = rng.random(n_tokens)
    for i in range(1, n_tokens):
        token[i] = (token[i - 1] * 7 + 3) % vocab_size if noise[i] < 0.7 else jumps[i]
    return TokenDataset(token, vocab, data_name)


# ---------------------------------------------------------------------------
# Registry
# ---------------------------------------------------------------------------

# canonical registries live in config (jax-free); re-exported here for the
# loaders' callers
from ..config import FOLDER_DATASETS, LM_DATASETS, VISION_DATASETS  # noqa: E402,F401


def fetch_dataset(data_name: str, data_dir: str = "./data", synthetic: bool = False,
                  seed: int = 0, synthetic_sizes: Optional[Dict[str, int]] = None,
                  subset: str = "label") -> Dict[str, Any]:
    """Return ``{'train': dataset, 'test': dataset}`` (ref src/data.py:10-34).

    Resolution order: on-disk files under ``{data_dir}/{data_name}``, else a
    deterministic synthetic dataset (``synthetic=True`` forces the latter).
    Folder datasets (Omniglot/ImageNet/ImageFolder) have no synthetic twin
    and raise if absent.
    """
    root = os.path.join(data_dir, data_name)
    out: Dict[str, Any] = {}
    for split in ("train", "test"):
        ds = None
        if not synthetic:
            if data_name in ("MNIST", "FashionMNIST"):
                ds = _load_mnist_like(root, split, data_name)
            elif data_name == "EMNIST":
                ds = _load_emnist(root, split, subset)
            elif data_name in ("CIFAR10", "CIFAR100"):
                ds = _load_cifar(root, split, data_name)
            elif data_name in FOLDER_DATASETS:
                ds = _load_image_folder(root, split, data_name)
                if ds is None:
                    raise FileNotFoundError(
                        f"{data_name} expects an image tree under {root}/<split>/<class>/ "
                        f"(Omniglot: images_background/images_evaluation)")
            elif data_name in LM_DATASETS:
                ds = _load_lm(root, split, data_name)
            else:
                raise ValueError("Not valid dataset name")
        if ds is None:
            n = (synthetic_sizes or {}).get(split)
            if data_name in FOLDER_DATASETS:
                raise ValueError(f"{data_name} has no synthetic twin; provide the "
                                 f"image tree under {root}")
            if data_name in VISION_DATASETS:
                ds = synthetic_vision(data_name, split, n=n, seed=seed, subset=subset)
            elif data_name in LM_DATASETS:
                ds = synthetic_lm(data_name, split, n_tokens=n or 200_000, seed=seed)
            else:
                raise ValueError("Not valid dataset name")
        out[split] = ds
    return out
