"""Dict-aware host-side transforms.

Parity with the reference's transform plumbing (ref
src/datasets/utils.py:191-211, src/datasets/transforms.py:1-17): samples are
``{'img': ..., 'label': ...}`` dicts, a plain transform sees only the image,
a :class:`CustomTransform` sees the whole dict (e.g. to read a bounding box).
The TPU pipeline does its augmentation on device (ops/augment.py); these
exist for host-side preprocessing parity and ad-hoc dataset preparation.
numpy in, numpy out.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Sequence

import numpy as np


class CustomTransform:
    """Marker base: ``__call__(sample_dict) -> img`` instead of
    ``__call__(img) -> img``."""

    def __call__(self, sample: Dict[str, Any]):  # pragma: no cover - abstract
        raise NotImplementedError


class Compose:
    """Apply transforms in order; CustomTransforms get the whole sample
    (ref src/datasets/utils.py:191-202)."""

    def __init__(self, transforms: Sequence[Callable]):
        self.transforms = list(transforms)

    def __call__(self, sample: Dict[str, Any]) -> Dict[str, Any]:
        for t in self.transforms:
            if isinstance(t, CustomTransform):
                sample["img"] = t(sample)
            else:
                sample["img"] = t(sample["img"])
        return sample

    def __repr__(self):
        inner = "\n".join(f"    {t}" for t in self.transforms)
        return f"{type(self).__name__}(\n{inner}\n)"


class BoundingBoxCrop(CustomTransform):
    """Crop ``img`` to the sample's ``bbox`` = (top, left, height, width)
    (ref src/datasets/transforms.py:4-17)."""

    def __call__(self, sample: Dict[str, Any]) -> np.ndarray:
        img = np.asarray(sample["img"])
        top, left, h, w = [int(v) for v in np.asarray(sample["bbox"]).tolist()]
        return img[top: top + h, left: left + w]
