"""Online per-channel mean/std over a dataset.

Parity: ``src/utils.py:218-257`` (``make_stats`` + the ``Stats`` merging
accumulator): batch-wise moment merging with the standard pooled-variance
update, cached to ``{data_dir}/stats/{name}.npz``.  Used to normalise
datasets that have no entry in ``DATASET_STATS``.
"""

from __future__ import annotations

import os
from typing import Optional, Tuple

import numpy as np


class Stats:
    """Mergeable per-channel mean/std (channel = last axis)."""

    def __init__(self):
        self.n = 0
        self.mean: Optional[np.ndarray] = None
        self.std: Optional[np.ndarray] = None

    def update(self, batch: np.ndarray) -> None:
        x = batch.reshape(-1, batch.shape[-1]).astype(np.float64)
        n, mean = x.shape[0], x.mean(0)
        std = x.std(0, ddof=1) if n > 1 else np.zeros_like(mean)
        if self.n == 0:
            self.n, self.mean, self.std = n, mean, std
            return
        m = float(self.n)
        tot = m + n
        new_mean = m / tot * self.mean + n / tot * mean
        self.std = np.sqrt(m / tot * self.std ** 2 + n / tot * std ** 2
                           + m * n / tot ** 2 * (self.mean - mean) ** 2)
        self.mean = new_mean
        self.n += n


def compute_stats(data: np.ndarray, batch: int = 100) -> Tuple[np.ndarray, np.ndarray]:
    """Channel stats of a uint8 image array (scaled to [0,1] like ToTensor)."""
    st = Stats()
    for i in range(0, len(data), batch):
        st.update(data[i: i + batch].astype(np.float32) / 255.0)
    return st.mean.astype(np.float32), st.std.astype(np.float32)


def dataset_stats(name: str, data: np.ndarray, data_dir: str = "./data"
                  ) -> Tuple[np.ndarray, np.ndarray]:
    """Cached stats (ref make_stats caches to ./data/stats/{name}.pt)."""
    path = os.path.join(data_dir, "stats", f"{name}.npz")
    if os.path.exists(path):
        z = np.load(path)
        return z["mean"], z["std"]
    mean, std = compute_stats(data)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    np.savez(path, mean=mean, std=std)
    return mean, std
