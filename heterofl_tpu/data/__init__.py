"""Host-side data layer: ingestion, partitioning, device-feed preparation.

Parity targets: ``src/data.py``, ``src/datasets/*`` in the reference. All
arrays are NumPy (NHWC for images); device-side augmentation/normalisation
lives in :mod:`heterofl_tpu.ops.augment` so it fuses into the jitted step.
"""

from .datasets import ArrayDataset, TokenDataset, fetch_dataset, DATASET_STATS  # noqa: F401
from .partition import iid, non_iid, span_population, split_dataset  # noqa: F401
from .pipeline import (  # noqa: F401
    process_dataset,
    batchify,
    bptt_windows,
    stack_windows,
    stack_client_shards,
    stack_client_token_rows,
    label_split_masks,
)
from .vocab import Vocab  # noqa: F401
from .download import check_integrity, download_url, extract_file  # noqa: F401
from .hierarchy import ClassNode, make_flat_index, make_tree, tree_from_paths  # noqa: F401
from .transforms import BoundingBoxCrop, Compose, CustomTransform  # noqa: F401
