"""Class-hierarchy trees for nested-label datasets (ImageNet synsets).

The reference builds anytree tries of class paths and labels images by each
leaf's ``flat_index`` (ref src/datasets/utils.py:152-188, imagenet.py:102-120)
-- so for nested synsets the label order follows the hierarchy's leaf order,
NOT a flat sorted-directory enumeration.  This is the dependency-free
equivalent: a trie of :class:`ClassNode` with the same index / flat-index
assignment rules, used by the ImageNet loader in :mod:`.datasets`.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence


class ClassNode:
    """One node of a class trie: ``index`` is the path of child positions
    from the root (anytree ``Node(..., index=...)`` parity)."""

    __slots__ = ("name", "parent", "children", "index", "flat_index", "attrs")

    def __init__(self, name: str, parent: Optional["ClassNode"] = None,
                 index: Optional[List[int]] = None, **attrs: Any):
        self.name = name
        self.parent = parent
        self.children: List[ClassNode] = []
        self.index = list(index or [])
        self.flat_index: Optional[int] = None
        self.attrs = attrs
        if parent is not None:
            parent.children.append(self)

    def child(self, name: str) -> Optional["ClassNode"]:
        for c in self.children:
            if c.name == name:
                return c
        return None

    def find(self, name: str) -> Optional["ClassNode"]:
        """First node named ``name`` in pre-order (anytree find_by_attr)."""
        for node in preorder(self):
            if node.name == name:
                return node
        return None

    @property
    def leaves(self) -> List["ClassNode"]:
        return [n for n in preorder(self) if not n.children]

    def __repr__(self):  # pragma: no cover
        return f"ClassNode({self.name!r}, index={self.index}, flat={self.flat_index})"


def preorder(root: ClassNode):
    yield root
    for c in root.children:
        yield from preorder(c)


def make_tree(root: ClassNode, names: Sequence[str],
              attribute: Optional[Dict[str, Sequence[Any]]] = None) -> None:
    """Insert the class path ``names`` (e.g. a synset chain) into the trie,
    one node per level, threading per-level ``attribute`` values
    (ref src/datasets/utils.py:152-168)."""
    if not names:
        return
    attribute = attribute or {}
    this_attr = {k: v[0] for k, v in attribute.items()}
    next_attr = {k: v[1:] for k, v in attribute.items()}
    node = root.child(names[0])
    if node is None:
        node = ClassNode(names[0], parent=root,
                         index=root.index + [len(root.children)], **this_attr)
    make_tree(node, names[1:], next_attr)


def make_flat_index(root: ClassNode, given: Optional[Sequence[str]] = None) -> int:
    """Assign ``flat_index`` to every leaf -- pre-order when ``given`` is
    None, else each leaf's position in ``given`` -- and return the class
    count (ref src/datasets/utils.py:175-188)."""
    classes_size = 0
    for i, leaf in enumerate(root.leaves):
        if given is not None:
            leaf.flat_index = given.index(leaf.name)
            classes_size = max(classes_size, leaf.flat_index + 1)
        else:
            leaf.flat_index = i
            classes_size = i + 1
    return classes_size


def tree_from_paths(paths: Sequence[Sequence[str]],
                    given: Optional[Sequence[str]] = None) -> ClassNode:
    """Build a rooted trie from class paths and flat-index it: the one-call
    form used by loaders."""
    root = ClassNode("U", index=[])
    for p in paths:
        make_tree(root, list(p))
    make_flat_index(root, given)
    return root


def imagenet_meta_tree(meta_mat_path: str):
    """Synset hierarchy from ILSVRC ``meta.mat`` (ref imagenet.py:102-120):
    leaves are the 1000 wnids, each inserted with its root->leaf chain;
    ``flat_index`` follows the meta's leaf order (``given=classes``).

    Returns ``(root, classes, classes_size)`` where ``classes`` is the wnid
    list defining the label order.  Requires scipy (gated by the caller).
    """
    import numpy as np
    import scipy.io as sio

    meta = sio.loadmat(meta_mat_path, squeeze_me=True)["synsets"]
    rows = [tuple(r.item()) if hasattr(r, "item") else tuple(r) for r in meta]
    # row: (id, wnid, classes, ..., num_children@4, children@5, ...)
    by_id = {int(r[0]): r for r in rows}
    parent_of: Dict[int, int] = {}
    for r in rows:
        # squeeze_me squeezes a single-child 'children' field to a numpy
        # scalar -- atleast_1d handles scalar, 0-d and array uniformly
        if int(r[4]) > 0:
            for k in np.atleast_1d(r[5]):
                parent_of[int(k)] = int(r[0])
    leaves = [r for r in rows if int(r[4]) == 0]
    root = ClassNode("U", index=[])
    classes = []
    for leaf in leaves:
        chain = []
        nid = int(leaf[0])
        while nid in by_id:
            chain.append(str(by_id[nid][1]))
            nid = parent_of.get(nid, -1)
        chain = list(reversed(chain))
        make_tree(root, chain)
        classes.append(str(leaf[1]))
    classes_size = make_flat_index(root, classes)
    return root, classes, classes_size
