"""Symbol/index vocabulary for language-modeling datasets.

Parity: ``src/datasets/lm.py:9-51`` (``<ukn>``=0, ``<eos>``=1, insertion
order, unknown-symbol fallback).
"""

from __future__ import annotations


class Vocab:
    def __init__(self):
        self.symbol_to_index = {"<ukn>": 0, "<eos>": 1}
        self.index_to_symbol = ["<ukn>", "<eos>"]

    def add(self, symbol: str) -> None:
        if symbol not in self.symbol_to_index:
            self.index_to_symbol.append(symbol)
            self.symbol_to_index[symbol] = len(self.index_to_symbol) - 1

    def __len__(self) -> int:
        return len(self.index_to_symbol)

    def __getitem__(self, query):
        if isinstance(query, int):
            if 0 <= query < len(self.index_to_symbol):
                return self.index_to_symbol[query]
            return "<ukn>"
        if isinstance(query, str):
            return self.symbol_to_index.get(query, self.symbol_to_index["<ukn>"])
        raise ValueError("Not valid data type")

    def __contains__(self, query) -> bool:
        if isinstance(query, int):
            return 0 <= query < len(self.index_to_symbol)
        if isinstance(query, str):
            return query in self.symbol_to_index
        raise ValueError("Not valid data type")
