"""Population sampler (ISSUE 11 tentpole): O(active) cohort draws.

HeteroFL's client draw is a full-population shuffle
(``rng.permutation(num_users)[:num_active]``, ref fed.py) and our jax twin
kept that shape: :func:`~.core.round_users` materialised
``jax.random.permutation(num_users)`` every round -- O(U log U) work and a
``[U]`` buffer per round, ~0.8 s/round at 1e6 users, which dominates once
the TPU round itself shrinks (ROADMAP "population-scale sampling").  This
module makes the draw a *subsystem* behind the existing one-stream
contract:

* **``sampler='prp'`` (default)** -- a keyed pseudorandom-permutation
  index map: a variable-round balanced Feistel network over the smallest
  even-bit binary domain covering ``[0, num_users)``, made an EXACT
  bijection on ``[0, num_users)`` for arbitrary (non-power-of-two) U by
  cycle-walking.  Round r's cohort is ``prp(fold_in(key, r))([0..A))`` --
  O(A) work, O(A) memory, traceable in-jit (the engines draw it inside the
  fused K-round scan), and never builds a ``[U]`` buffer.
* **``sampler='perm'``** -- the legacy full-permutation draw, preserved
  bit for bit for parity tests and old-trajectory reproduction.

Availability (ISSUE 9) composes without the full-row sort: instead of
gathering ``avail[perm]`` and stable-argsorting a ``[U]`` row, the PRP
path walks ``overdraw x A`` candidates along the permutation, keeps the
available ones in PRP order, and spills unfillable slots to ``-1``
(partial participation) -- O(A x overdraw) gathers.  An all-ones row
selects exactly the uniform-PRP cohort, so trace replay stays a strict
generalisation of the uniform stream.

Schedule commitment (``sample_horizon``): an OUTPUT-dependent sampler
(loss/staleness-prioritized cohorts, the ROADMAP follow-ons) cannot draw
superstep N+1 while N is still in flight -- which is why PR 6's streaming
driver had to offer the synchronous ``stream_prefetch=False`` fallback.
``sample_horizon=1`` commits the draw one state behind instead: superstep
N+1's cohort is drawn from superstep N-1's fetched state
(:class:`ScheduleCommitment` gates the prefetch queue), so the staging
overlap survives.  For the stateless perm/prp samplers the committed
schedule is identical to the immediate one -- bit-for-bit, which is the
contract tests pin.

This module is import-light at the top (numpy only), like ``sched/`` and
``obs/``: config validation stays jax-free for ``config.process_control``;
the jax halves import jax lazily inside the traced functions.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import numpy as np

#: the sampler registry (``cfg['sampler']``)
SAMPLER_KINDS = ("perm", "prp")

#: availability overdraw: how many PRP candidates the draw-then-filter walk
#: visits per cohort slot before spilling the remainder to -1 (padding).
#: At overdraw b, a round whose availability rate is p fills every slot
#: with probability ~1 - exp(-A(bp - 1)^2 / 2b) -- 4 covers p >= 0.5
#: essentially always, and thinner rounds *should* degrade to partial
#: participation (the ISSUE 9 semantics) rather than scan the whole row.
AVAIL_OVERDRAW = 4

#: PRP round-key derivation salt (folded into the per-round sample key so
#: the Feistel key schedule is independent of any other use of the key)
PRP_KEY_SALT = 23


# ---------------------------------------------------------------------------
# config half (jax-free)
# ---------------------------------------------------------------------------

class SamplerSpec:
    """The resolved sampler configuration: one immutable object the driver,
    engines, staticcheck and bench all consume (built by
    :func:`resolve_sampler_cfg` -- there is no second parser).

    ``kind``: ``'prp'`` (O(active) index-map draw, the default) or
    ``'perm'`` (the legacy full-permutation stream, bit-for-bit).
    ``horizon``: ``None`` for stateless samplers (the schedule is a pure
    function of the key stream; prefetch is unconstrained) or an int >= 0
    -- the schedule-commitment mode, where superstep N+1's cohort may only
    consume state fetched through superstep ``N - horizon``."""

    def __init__(self, kind: str = "prp", horizon: Optional[int] = None):
        self.kind = kind
        self.horizon = horizon

    @property
    def committed(self) -> bool:
        return self.horizon is not None


def resolve_sampler_cfg(cfg: Dict[str, Any]) -> SamplerSpec:
    """Validate ``cfg['sampler']`` / ``cfg['sample_horizon']`` and return
    the :class:`SamplerSpec`.  THE one validator (the PR 6/8 convention:
    unknown values fail loudly at config time, never as a silent
    default-sampler fallback mid-run)."""
    kind = cfg.get("sampler", "prp") or "prp"
    if kind not in SAMPLER_KINDS:
        raise ValueError(f"Not valid sampler: {kind!r} (one of "
                         f"{SAMPLER_KINDS}; 'prp' is the O(active) "
                         f"index-map draw, 'perm' the legacy full "
                         f"permutation)")
    horizon = cfg.get("sample_horizon")
    if horizon is not None:
        if not isinstance(horizon, int) or isinstance(horizon, bool) \
                or horizon < 0:
            raise ValueError(f"Not valid sample_horizon: {horizon!r} (an "
                             f"int >= 0 -- superstep N+1's cohort draws "
                             f"from superstep N-horizon's committed state "
                             f"-- or None for a stateless sampler)")
    return SamplerSpec(kind=kind, horizon=horizon)


class ScheduleCommitment:
    """The schedule-commitment ledger (``sample_horizon``): which superstep
    states have been fetched, and therefore which future cohorts may be
    drawn.  Superstep indices count dispatches (1-based); superstep ``n``'s
    cohort may consume state no fresher than superstep ``n - horizon - 1``,
    so :meth:`may_draw` answers "is everything that draw would read already
    on the host?".

    With the driver's dispatch -> prefetch -> fetch ordering and
    ``horizon=1``, prefetching superstep N+1 while N is in flight is
    allowed exactly because its draw reads superstep N-1's state -- the
    PR 6 staging overlap survives output-dependent samplers.  ``state`` is
    the opaque committed payload a state-consuming sampler would read
    (:meth:`state_for`); the stateless perm/prp samplers ignore it, which
    is why their committed schedule is bit-identical to the immediate
    one."""

    def __init__(self, horizon: int):
        self.horizon = int(horizon)
        self._committed = 0  # highest superstep index whose state is fetched
        self._states: Dict[int, Any] = {}

    @property
    def committed_through(self) -> int:
        return self._committed

    def commit(self, index: int, state: Any = None) -> None:
        """Record superstep ``index``'s fetched state (monotonic)."""
        index = int(index)
        if index > self._committed:
            self._committed = index
        self._states[index] = state
        # the ledger only ever needs states a draw can still reference
        floor = self._committed - (self.horizon + 1)
        for k in [k for k in self._states if k < floor]:
            del self._states[k]

    def may_draw(self, index: int) -> bool:
        """May superstep ``index``'s cohort be drawn now?  True iff the
        state it consumes (superstep ``index - horizon - 1``; <= 0 means
        the initial state) is committed."""
        return int(index) - (self.horizon + 1) <= self._committed

    def state_for(self, index: int) -> Any:
        """The committed state superstep ``index``'s draw consumes (None
        before any commit / for pre-run indices)."""
        return self._states.get(int(index) - (self.horizon + 1))


# ---------------------------------------------------------------------------
# jax half: the PRP index map (traced; jax imported lazily so the module
# top stays import-light for config.process_control)
# ---------------------------------------------------------------------------

def _feistel_geometry(num_users: int):
    """Static Feistel geometry for a domain covering ``[0, num_users)``:
    half-width ``b`` (the balanced domain is ``4**b >= num_users``, always
    < 4x num_users) and the variable round count -- small domains mix
    poorly per round, so they get more rounds (the cost is O(A) either
    way)."""
    b = 1
    while (1 << (2 * b)) < num_users:
        b += 1
    rounds = 24 if b <= 4 else (16 if b <= 8 else 10)
    return b, rounds


def _mix32(v, k):
    """murmur3-style 32-bit finalizer of ``v`` keyed by ``k`` -- the
    Feistel round function (uint32 lattice, wraps naturally)."""
    import jax.numpy as jnp

    h = v ^ k
    h = (h ^ (h >> jnp.uint32(16))) * jnp.uint32(0x85EBCA6B)
    h = (h ^ (h >> jnp.uint32(13))) * jnp.uint32(0xC2B2AE35)
    return h ^ (h >> jnp.uint32(16))


def prp_map(key, x, num_users: int):
    """Apply the keyed PRP over ``[0, num_users)`` to ``x`` (int array of
    in-range indices): an exact bijection for ARBITRARY num_users, built
    from a balanced Feistel network on the covering binary domain plus
    cycle-walking (re-encrypt until the image lands back in range;
    starting in range guarantees termination because the walk follows the
    permutation's own cycle).  O(len(x)) work and memory -- independent of
    ``num_users`` -- and traceable (``key`` and ``x`` may be traced; the
    walk is a ``lax.while_loop``)."""
    import jax
    import jax.numpy as jnp

    if num_users < 1:
        raise ValueError(f"prp_map needs num_users >= 1, got {num_users}")
    x = jnp.asarray(x)
    if num_users == 1:
        return jnp.zeros(x.shape, jnp.int32)
    b, rounds = _feistel_geometry(num_users)
    mask = jnp.uint32((1 << b) - 1)
    rk = jax.random.bits(jax.random.fold_in(key, PRP_KEY_SALT),
                         (rounds,), jnp.uint32)
    u = jnp.uint32(num_users)

    def enc(v):
        lo = v & mask
        hi = v >> jnp.uint32(b)
        for r in range(rounds):
            hi, lo = lo, hi ^ (_mix32(lo, rk[r]) & mask)
        return (hi << jnp.uint32(b)) | lo

    y = enc(x.astype(jnp.uint32))
    y = jax.lax.while_loop(
        lambda v: jnp.any(v >= u),
        lambda v: jnp.where(v >= u, enc(v), v),
        y)
    return y.astype(jnp.int32)


#: host-path compiled draws, keyed by the static draw geometry.  The PRP
#: is ~30 tiny integer ops plus the cycle walk; dispatched eagerly they
#: cost ~1e5x the compute (per-op host dispatch), so the HOST draw runs
#: through one cached jit per (U, A, overdraw, has-avail) shape while
#: traced callers (the engines' in-jit draw) inline the plain ops --
#: integer lattice both ways, so jit == eager bitwise by construction.
_HOST_DRAWS: Dict[tuple, Any] = {}


def prp_round_users(sample_key, num_users: int, num_active: int,
                    avail=None, overdraw: int = AVAIL_OVERDRAW):
    """One round's cohort under the PRP sampler: the image of ``[0,
    num_active)`` under the keyed bijection -- O(num_active), no ``[U]``
    buffer (``sample_key`` is the already-salted per-round sample key;
    :func:`~.core.round_users` owns the salt).

    ``avail``: this round's ``[num_users]`` 0/1 availability row.  The
    draw-then-filter walk visits ``min(num_users, overdraw * num_active)``
    PRP candidates in permutation order, keeps the available ones, and
    spills slots the walk could not fill to ``-1`` -- the engines' padding
    convention, so a thin round degrades to partial participation exactly
    like the legacy sort path (bounded spill: availability below
    ~1/overdraw trades full cohorts for O(A) cost, by design).  An
    all-ones row selects exactly the uniform-PRP cohort (the first
    ``num_active`` candidates ARE that cohort)."""
    import jax

    if not isinstance(sample_key, jax.core.Tracer) \
            and not isinstance(avail, jax.core.Tracer):
        ck = (num_users, num_active, overdraw, avail is None)
        fn = _HOST_DRAWS.get(ck)
        if fn is None:
            def fn(k, av=None, _ck=ck):
                return _prp_round_users(k, _ck[0], _ck[1], av, _ck[2])

            fn = jax.jit(fn)
            _HOST_DRAWS[ck] = fn
        return fn(sample_key) if avail is None else fn(sample_key, avail)
    return _prp_round_users(sample_key, num_users, num_active, avail,
                            overdraw)


def _prp_round_users(sample_key, num_users: int, num_active: int,
                     avail, overdraw: int):
    import jax.numpy as jnp

    if avail is None:
        return prp_map(sample_key, jnp.arange(num_active, dtype=jnp.int32),
                       num_users)
    budget = min(num_users, max(1, overdraw) * num_active)
    cand = prp_map(sample_key, jnp.arange(budget, dtype=jnp.int32),
                   num_users)
    ok = jnp.asarray(avail, jnp.float32)[cand] > 0
    rank = jnp.cumsum(ok.astype(jnp.int32)) - 1
    keep = ok & (rank < num_active)
    # scatter kept candidates to their fill rank; unfilled slots stay -1
    # (mode='drop' discards the not-kept lanes routed to index num_active)
    return jnp.full((num_active,), -1, jnp.int32).at[
        jnp.where(keep, rank, num_active)].set(cand, mode="drop")
