"""Federation core: sub-model extraction and counted-average aggregation.

This replaces the reference ``Federation`` class (``src/fed.py``) with pure
functions over param pytrees.  Two execution strategies share one algebra:

* **masked** (default, TPU-native): ``distribute`` multiplies the global
  params by the client's width mask (suffix -> 0); ``combine`` is
  ``sum(P_c * M_c) / sum(M_c)`` with the stale-value fallback where no client
  contributed (ref fed.py:217-218).  Everything is static-shape and jittable;
  under ``shard_map`` the two sums become ``psum`` over the clients axis.
* **sliced**: true small tensors via host-side gather (``extract_sliced``) and
  scatter-back (``embed_sliced``), matching the reference's deepcopy
  simulation; used for debugging and the equivalence tests.

Label-split restriction of output layers (ref fed.py:193-198,228-233,263-274)
enters through the ``label_mask`` axis of the count masks -- clients train
their full output rows but only their label rows are aggregated.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..models.base import ModelDef
from ..models.spec import Group, ParamSpec, count_masks as _count_masks, mask_params


def sample_model_rates(key: jax.Array, cfg: Dict[str, Any],
                       user_idx: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """Absolute model rates of the given users for one round.

    ``fix``: the static per-user vector computed by ``process_control`` (ref
    utils.py:134-144), indexed by the *selected* user ids (ref fed.py
    ``self.model_rate[user_idx[m]]``).  ``dynamic``: i.i.d. multinomial
    re-roll over ``cfg['proportion']`` every round (ref fed.py:15-19) -- a
    traced sample, so dynamic mode stays inside the jitted round.

    NOTE: these are *absolute* rates; convert with :func:`to_width_rates`
    before driving masks/Scaler (the reference likewise slices by
    ``model_rate / global_model_rate``, fed.py:46).
    """
    if user_idx is None:
        user_idx = jnp.arange(cfg["num_users"])
    user_idx = jnp.asarray(user_idx)
    if cfg["model_split_mode"] == "fix":
        return jnp.take(jnp.asarray(cfg["model_rate"], jnp.float32), user_idx)
    if cfg["model_split_mode"] == "dynamic":
        # re-roll ALL users then index the selected ones (ref fed.py:15-24 +
        # distribute) -- also keeps the PRNG stream identical to the masked
        # round engine's in-jit draw for any selection.
        rates = jnp.asarray(cfg["model_rate"], jnp.float32)
        idx = jax.random.choice(key, len(rates), shape=(cfg["num_users"],),
                                p=jnp.asarray(cfg["proportion"]))
        return rates[idx][user_idx]
    raise ValueError("Not valid model split mode")


def validate_width_geometry(model: ModelDef, cfg: Dict[str, Any]) -> None:
    """Reject width configs where the per-head q/k/v slice outruns the
    prefix width slice (ref fed.py:115-131 couples the two; when
    ``heads * ceil(head_dim * r) != ceil(size * r)`` at some level the
    sub-model rows reference zeroed embedding dims -- the reference
    silently degrades, here it would NaN).  Raises with the minimal fix."""
    rates = {float(r) / cfg["global_model_rate"] for r in cfg["model_rate"]}
    for name, g in model.groups.items():
        if g.kind != "per_head":
            continue
        hd = g.size // g.num_heads
        for wr in sorted(rates):
            if g.num_heads * math.ceil(hd * wr) != math.ceil(g.size * wr):
                raise ValueError(
                    f"width geometry: group {name!r} (size {g.size}, "
                    f"{g.num_heads} heads) is inconsistent at rate {wr:g}: "
                    f"per-head slice keeps {g.num_heads * math.ceil(hd * wr)} "
                    f"dims but the width slice keeps {math.ceil(g.size * wr)}; "
                    f"pick embedding_size so embedding*rate is a multiple-safe "
                    f"size (e.g. embedding_size*min_rate >= num_heads and "
                    f"head_dim divisible by 1/min_rate)")


ROUND_RATE_SALT = 7
USER_SAMPLE_SALT = 11
#: PRNG salt of the per-arm stream derivation (ISSUE 14), folded into
#: the HOST key.  Must stay outside the host key's other fold families
#: (the per-round epoch keys [1, NUM_ROUNDS_BOUND] and the watchdog's
#: RETRY_SALT window): the old value 17 sat inside the epoch family, so
#: round 17's key WAS the arms salt root and arm seed 7's stream
#: collided with round 17's rate stream (staticcheck's key-stream audit
#: now proves the intervals disjoint).
ARM_STREAM_SALT = 0x4152  # 16722, past any epoch index
#: PRNG sub-root salts of the engines' in-round streams (ISSUE 18).  The
#: per-client slot keys descend from ``fold_in(round_key,
#: CLIENT_STREAM_SALT)`` and the failure draws from ``fold_in(round_key,
#: FAILURE_STREAM_SALT)``, so the unbounded uid family lives in its own
#: subtree: the old flat ``fold_in(round_key, 13 + uid)`` derivation
#: collided with the failure root at uid 85 (13 + 85 == 98) and with the
#: deadline salt at uid 118 (13 + 118 == 131) -- at flagship scale
#: (num_users=100) client 85's stream WAS the failure stream.
CLIENT_STREAM_SALT = 13
FAILURE_STREAM_SALT = 98


def arm_stream_keys(base_key: jax.Array, seeds) -> jax.Array:
    """Stacked ``[E]`` per-arm base keys: THE one definition of the arms
    stream derivation (ISSUE 14, :mod:`~..multi`).

    Arm ``e`` with seed ``s`` owns the stream ``fold_in(fold_in(base_key,
    ARM_STREAM_SALT), s)``; a ``None`` seed is the IDENTITY arm -- it
    consumes ``base_key`` itself, which is what makes an ``arms=1`` run
    bit-identical to the unbatched program (the equivalence contract in
    tests/test_arms.py).  Engines consume these as the per-round key roots
    of each arm's round cores (cohort draw, dynamic rates, client/slot
    keys, deadline budgets, failure draws); the batched program and a solo
    run with the same seed therefore replay the identical streams."""
    salted = jax.random.fold_in(base_key, ARM_STREAM_SALT)
    return jnp.stack([base_key if s is None
                      else jax.random.fold_in(salted, s) for s in seeds])


def client_stream_keys(round_key: jax.Array, uids: jnp.ndarray) -> jax.Array:
    """Stacked per-client slot keys ``fold_in(fold_in(round_key,
    CLIENT_STREAM_SALT), uid)``: THE one definition of the client stream.

    The masked, grouped and sliced engines all consume this derivation
    for their local-training keys, which is what keeps the engine
    equivalence contracts bitwise.  The two-level fold keeps the
    unbounded uid family in its own subtree (see CLIENT_STREAM_SALT
    above); staticcheck's key-stream audit pins this shape."""
    root = jax.random.fold_in(round_key, CLIENT_STREAM_SALT)
    return jax.vmap(lambda u: jax.random.fold_in(root, u))(jnp.asarray(uids))


def failure_stream_key(round_key: jax.Array) -> jax.Array:
    """The failure-draw root ``fold_in(round_key, FAILURE_STREAM_SALT)``:
    per-client crash draws fold the uid into THIS key, never into the
    round key directly (uid subtrees stay disjoint from sibling salts)."""
    return jax.random.fold_in(round_key, FAILURE_STREAM_SALT)


def round_rates(round_key: jax.Array, cfg: Dict[str, Any],
                user_idx: Optional[jnp.ndarray] = None) -> jnp.ndarray:
    """The per-round rate draw, salt included: THE one definition of the
    rate stream.  Used in-jit by the masked engine's dynamic branch and on
    the host by ``entry/common.py`` and the parity harness for the grouped/
    sliced engines -- all three must consume the identical stream or
    round-level engine equivalence silently becomes a PRNG artifact."""
    return sample_model_rates(jax.random.fold_in(round_key, ROUND_RATE_SALT), cfg, user_idx)


def round_users(round_key: jax.Array, num_users: int, num_active: int,
                avail=None, sampler: str = "prp") -> jnp.ndarray:
    """The per-round active-client draw, salt included: THE one definition
    of the superstep sampling stream (the jax twin of the drivers'
    ``rng.permutation(num_users)[:num_active]``).  Consumed in-jit by the
    masked superstep (replicated placement) and on the host when packing
    slot schedules (sharded placement, grouped engine) -- every consumer
    must use this function or superstep-vs-sequential equivalence silently
    becomes a PRNG artifact.  Traceable (``round_key`` may be a traced
    key).

    ``sampler`` (ISSUE 11, :mod:`.sampling`): ``'prp'`` (default) draws
    the cohort as the image of ``[0, num_active)`` under a keyed
    pseudorandom-permutation index map -- O(num_active) work, no ``[U]``
    buffer; ``'perm'`` is the legacy full ``permutation(num_users)`` draw,
    preserved bit for bit for parity tests and old trajectories.  The two
    are DIFFERENT streams: switching re-baselines every seeded trajectory
    (deliberately; the bench refuses cross-stream comparisons).

    ``avail`` (ISSUE 9, :mod:`~..sched`): this round's ``[num_users]`` 0/1
    availability row.  ``None`` (uniform) keeps the sampler's plain draw
    bit for bit.  With a row, available users are drawn FIRST in
    permutation order and slots the availability cannot fill come back as
    ``-1`` -- the engines' padding-slot convention, so a thin round
    degrades to partial participation instead of resampling unavailable
    users.  Under ``perm`` the filter is the legacy ``[U]`` gather +
    stable argsort; under ``prp`` it is an O(num_active x overdraw)
    draw-then-filter walk along the PRP with bounded spill
    (:func:`~.sampling.prp_round_users`).  Either way an all-ones row
    selects exactly that sampler's uniform cohort, which is what makes
    trace replay a strict generalisation of the uniform stream."""
    if not 0 <= num_active <= num_users:
        raise ValueError(
            f"round_users: num_active={num_active} must be in [0, "
            f"num_users={num_users}] -- the legacy permutation draw would "
            f"silently short the cohort (and a negative count silently "
            f"wrap); fix cfg['frac']/num_active")
    if sampler not in ("perm", "prp"):
        raise ValueError(f"Not valid sampler: {sampler!r} (one of "
                         f"('perm', 'prp'))")
    skey = jax.random.fold_in(round_key, USER_SAMPLE_SALT)
    if sampler == "prp":
        from .sampling import prp_round_users

        return prp_round_users(skey, num_users, num_active, avail=avail)
    perm = jax.random.permutation(skey, num_users)
    if avail is None:
        return perm[:num_active].astype(jnp.int32)
    a = jnp.asarray(avail, jnp.float32)[perm]
    order = jnp.argsort(-a, stable=True)[:num_active]
    sel = perm[order]
    ok = a[order] > 0
    return jnp.where(ok, sel, -1).astype(jnp.int32)


def superstep_user_schedule(host_key: jax.Array, epoch0: int, k: int,
                            num_users: int, num_active: int,
                            schedule=None, sampler: str = "prp") -> np.ndarray:
    """Host-side ``[k, A]`` active-user draw from THE superstep sampling
    stream (:func:`round_users` at per-round keys ``fold_in(host_key,
    epoch0 + r)``): the one host twin of the masked engine's in-jit draw.
    Shared by the fed drivers, ``bench.py``, the streaming cohort staging
    and the equivalence tests -- a private copy of this loop is how the
    superstep stream silently forks.

    ``schedule`` (ISSUE 9): a :class:`~..sched.ScheduleSpec`; its per-round
    availability rows thread into :func:`round_users` (``None`` or the
    uniform kind leaves the stream untouched).  ``-1`` entries mark slots
    the availability could not fill -- padding slots to every consumer.
    ``sampler`` (ISSUE 11) threads straight through -- the host schedule
    and the in-jit draw must name the same sampler or the stream forks."""
    if epoch0 < 0:
        raise ValueError(f"superstep_user_schedule: epoch0={epoch0} must "
                         f"be non-negative (per-round keys are fold_in("
                         f"host_key, epoch0 + r); a negative epoch silently "
                         f"replays another round's stream)")
    if k < 0:
        raise ValueError(f"superstep_user_schedule: k={k} must be "
                         f"non-negative")
    return np.stack([
        np.asarray(round_users(
            jax.random.fold_in(host_key, epoch0 + r), num_users, num_active,
            avail=None if schedule is None else schedule.avail_row(epoch0 + r),
            sampler=sampler))
        for r in range(k)]) if k else np.zeros((0, num_active), np.int32)


def superstep_rate_schedule(host_key: jax.Array, epoch0: int, k: int,
                            cfg: Dict[str, Any], user_schedule) -> np.ndarray:
    """Host-side ``[k, A]`` absolute-rate draw matching
    :func:`superstep_user_schedule`'s rounds (:func:`round_rates` at the
    same per-round keys) -- what the grouped engine's slot grouping and the
    masked engine's in-jit draw both consume."""
    return np.stack([
        np.asarray(round_rates(jax.random.fold_in(host_key, epoch0 + r), cfg,
                               jnp.asarray(user_schedule[r])))
        for r in range(k)])


def snap_to_levels(rates, levels, rtol: float = 1e-5, atol: float = 1e-8) -> np.ndarray:
    """Snap sampled absolute model rates onto an engine's level table.

    Incoming rates round-trip through float32 (:func:`round_rates`) while
    level tables are host floats; exact-equality lookups only work because
    the stock ``MODEL_SPLIT_RATE`` table is dyadic.  Nearest-level matching
    with an ``isclose`` guard makes any rate table either snap cleanly or
    fail loudly AT STAGING -- a ``ValueError`` naming the offending rates --
    instead of a ``KeyError`` mid-round (ADVICE r5 item 2)."""
    table = np.asarray(sorted({float(r) for r in levels}, reverse=True), np.float64)
    r = np.asarray(rates, np.float64).reshape(-1)
    if r.size == 0:
        return r
    snapped = table[np.argmin(np.abs(r[:, None] - table[None, :]), axis=1)]
    ok = np.isclose(r, snapped, rtol=rtol, atol=atol)
    if not ok.all():
        bad = sorted(set(np.round(r[~ok], 6).tolist()))
        raise ValueError(
            f"model rates {bad} are not in the engine's level table "
            f"{table.tolist()}: every sampled rate must match a level built "
            f"at engine construction (fix cfg['model_rate'] or the incoming "
            f"rate stream)")
    return snapped


#: module_table rows whose backward pass re-runs the contraction twice
#: (grad wrt inputs + grad wrt weights); everything else (norms, relu,
#: pools) back-propagates at ~1x its forward cost.
_MATMUL_LIKE = ("conv", "linear", "shortcut", "mha", "ff.l", "dec.l",
                "embedding", "qk", "av")

#: optimizer + width/label masking + clipping cost per parameter per step
#: (SGD momentum update, weight decay, mask multiply, global-norm terms)
_OPT_FLOPS_PER_PARAM = 10.0


def level_flop_table(cfg: Dict[str, Any], rates: Optional[list] = None
                     ) -> Dict[float, float]:
    """Analytic per-client per-local-step training FLOPs at each level of the
    rate table: THE one source of truth for level FLOP budgets.

    Derived from the profiler's per-module MAC table
    (:func:`~..analysis.summary.module_table`) rather than the bare
    ``rate^2`` heuristic: forward = 2x MACs, backward = 2x forward for
    matmul-like modules (input grad + weight grad) and ~1x for elementwise
    ones, plus an optimizer/masking term per parameter and the
    width-INDEPENDENT per-batch data-prep cost (normalize/augment) that
    dominates tiny levels.  Consumers: the grouped engine's ``slices`` row
    allocation (:meth:`~..parallel.grouped.GroupedRoundEngine._static_mesh_slices`),
    the staticcheck FLOP-budget audit, and ``scripts/grouped_flops.py``.
    Absolute values are a model, not a measurement -- compare *shares*
    (:func:`level_flop_shares`) against ``cost_analysis()`` numbers."""
    from ..analysis.summary import module_table

    grate = cfg["global_model_rate"]
    if rates is None:
        rates = sorted({float(r) for r in cfg["model_rate"]}, reverse=True)
    bs = cfg["batch_size"]["train"] if isinstance(cfg["batch_size"], dict) \
        else cfg["batch_size"]
    prep = 0.0
    if cfg.get("data_shape"):
        h, w, c = cfg["data_shape"]
        # normalize: sub+div per pixel; CIFAR adds crop/flip augmentation
        prep = 2.0 * bs * h * w * c
        if str(cfg.get("data_name", "")).startswith("CIFAR"):
            prep *= 3.0
    out: Dict[float, float] = {}
    for r in rates:
        wr = float(r) / grate
        fwd = bwd = 0.0
        nparam = 0
        for name, _insz, _outsz, p, macs in module_table(cfg, wr, bs):
            fl = 2.0 * macs
            fwd += fl
            bwd += fl * (2.0 if any(t in name for t in _MATMUL_LIKE) else 1.0)
            nparam += p
        out[float(r)] = fwd + bwd + _OPT_FLOPS_PER_PARAM * nparam + prep
    return out


#: bytes per parameter element on the wire and in HBM: params, update sums
#: and count masks are all float32 (compute_dtype only narrows activations)
PARAM_ITEMSIZE = 4


def level_param_table(cfg: Dict[str, Any], rates: Optional[list] = None
                      ) -> Dict[float, int]:
    """Analytic per-level parameter COUNTS of the sliced sub-model at each
    rate of the level table (a count view over :func:`level_byte_table`,
    which owns the per-module accounting; the counts match ``model.init``
    trees exactly, which the staticcheck wire audit relies on)."""
    return {r: v["param_bytes"] // PARAM_ITEMSIZE
            for r, v in level_byte_table(cfg, rates).items()}


def level_byte_table(cfg: Dict[str, Any], rates: Optional[list] = None,
                     itemsize: int = PARAM_ITEMSIZE) -> Dict[float, Dict[str, int]]:
    """Analytic per-level byte/shape table (ISSUE 7): for each rate level,

    * ``param_bytes`` -- the sliced sub-model's parameter footprint;
    * ``wire_bytes`` -- the dense per-round reduction payload of that
      level's round program: ``sum(param_bytes) + count_bytes`` (the
      counted-average aggregation psums the update sums AND the
      element-count masks, both param-shaped f32, in ONE bind);
    * ``activation_bytes`` -- per-local-step forward activation output
      bytes at the training batch size (``module_table`` output sizes x
      f32), the per-client working-set term of the HBM budget.

    The wire numbers are exact for the audited programs (verified against
    traced psum operand avals), which is what lets staticcheck enforce the
    wire budget by equality rather than tolerance."""
    from ..analysis.summary import module_table

    grate = cfg["global_model_rate"]
    if rates is None:
        rates = sorted({float(r) for r in cfg["model_rate"]}, reverse=True)
    out: Dict[float, Dict[str, int]] = {}
    for r in rates:
        rows = module_table(cfg, float(r) / grate)
        nparam = int(sum(row[3] for row in rows))
        act = int(sum(int(np.prod(row[2])) for row in rows))
        out[float(r)] = {
            "param_bytes": nparam * itemsize,
            "wire_bytes": 2 * nparam * itemsize,
            "activation_bytes": act * itemsize,
        }
    return out


def level_codec_byte_table(cfg: Dict[str, Any], codec: str,
                           rates: Optional[list] = None,
                           n_leaves: int = 0) -> Dict[float, int]:
    """Analytic per-level COMPRESSED wire bytes of one fused training round
    under ``codec`` (ISSUE 8): the per-participant psum payload of that
    level's flat element count, priced by the one formula in
    :func:`~..compress.codec_payload_bytes`.  THE single source the
    staticcheck wire budget enforces by equality against the traced psum
    operand avals AND ``bench.py``'s ``extra.wire`` records -- there is no
    second bytes formula.  ``n_leaves`` (the param-tree leaf count) only
    affects the ``signsgd`` scale vector; the fused rounds of both engines
    reduce at the level-a (global) footprint, so their budget is this
    table's top-rate entry."""
    from ..compress import codec_payload_bytes

    return {r: codec_payload_bytes(codec, n, n_leaves)
            for r, n in level_param_table(cfg, rates).items()}


def level_codec_map_byte_table(cfg: Dict[str, Any],
                               codec_map: Dict[float, str],
                               rates: Optional[list] = None,
                               n_leaves: int = 0) -> Dict[float, int]:
    """Analytic per-level wire bytes of one fused GROUPED round under a
    per-level codec map (ISSUE 9 satellite): level ``r``'s payload is its
    SLICED flat element count priced by its own codec -- dense levels move
    ``2 x 4 x n_r`` (f32 sums + counts at sliced shape), lossy levels their
    packed-lane footprint -- and the round's single psum carries the sum
    over levels.  Same single bytes formula
    (:func:`~..compress.codec_payload_bytes`) as every other wire budget,
    so staticcheck still enforces the per-level-codec programs by equality
    against the traced psum operand avals."""
    from ..compress import codec_payload_bytes

    table = level_param_table(cfg, rates)
    missing = set(table) - {float(r) for r in codec_map}
    if missing:
        raise ValueError(f"codec map misses levels {sorted(missing)}: every "
                         f"level in the rate table needs a codec")
    return {r: codec_payload_bytes(codec_map[float(r)], n, n_leaves)
            for r, n in table.items()}


def level_flop_shares(cfg: Dict[str, Any],
                      weights: Optional[Dict[float, float]] = None,
                      rates: Optional[list] = None) -> Dict[float, float]:
    """Normalized expected FLOP share of each rate level: ``weight x
    per-step analytic cost`` (:func:`level_flop_table`), summing to 1.
    ``weights`` defaults to uniform (equal client counts per level)."""
    table = level_flop_table(cfg, rates)
    w = {r: 1.0 for r in table} if weights is None \
        else {float(r): float(v) for r, v in weights.items()}
    raw = {r: w.get(r, 0.0) * f for r, f in table.items()}
    tot = sum(raw.values())
    if tot <= 0.0:
        raise ValueError(f"level FLOP shares degenerate: weights {w}")
    return {r: v / tot for r, v in raw.items()}


def to_width_rates(model_rates: jnp.ndarray, cfg: Dict[str, Any]) -> jnp.ndarray:
    """Absolute model rate -> width/scaler rate relative to the global model
    (``scaler_rate = model_rate / global_model_rate``, ref fed.py:46,
    models/conv.py:79).  Group sizes are already scaled by the global rate, so
    masks must use this relative rate or non-'a' global modes double-shrink."""
    return jnp.asarray(model_rates, jnp.float32) / cfg["global_model_rate"]


def distribute_masked(global_params: Dict[str, jnp.ndarray], model: ModelDef,
                      width_rate) -> Dict[str, jnp.ndarray]:
    """Masked-strategy ``Federation.distribute`` for one client
    (ref fed.py:161-178): active prefix keeps global values, suffix is zero."""
    return mask_params(global_params, model.specs, model.groups, width_rate)


def client_count_masks(global_params: Dict[str, jnp.ndarray], model: ModelDef,
                       width_rate, label_mask) -> Dict[str, jnp.ndarray]:
    """Aggregation contribution masks for one client (width x label split)."""
    shapes = {k: v.shape for k, v in global_params.items()}
    return _count_masks(shapes, model.specs, model.groups, width_rate, label_mask)


def combine_counted(global_params: Dict[str, jnp.ndarray],
                    summed: Dict[str, jnp.ndarray],
                    counts: Dict[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
    """Counted average with stale fallback: ``v[count>0] = (sum/count)``,
    elements no client held keep the previous global value (ref fed.py:217-218)."""
    out = {}
    for k, v in global_params.items():
        c = counts[k]
        out[k] = jnp.where(c > 0, summed[k] / jnp.maximum(c, 1.0), v)
    return out


# ---------------------------------------------------------------------------
# Sliced strategy, in-jit half (static prefix slices / zero-pad embeds)
#
# HeteroFL's index sets are always nested prefixes (ref fed.py:46-48) or
# per-head prefixes (ref fed.py:124-131), so at a *static* width rate the
# reference's gather ``v[meshgrid(idx)]`` is a static XLA slice and the
# scatter-back is a zero pad -- no gather/scatter ops, fully fusible.  These
# power the mesh-native rate-grouped engine (parallel/grouped.py).
# ---------------------------------------------------------------------------

def _per_head_counts(group: Group, width_rate: float) -> tuple:
    hd = group.size // group.num_heads
    return hd, int(math.ceil(hd * width_rate))


def slice_axis(v: jnp.ndarray, group: Group, width_rate: float, axis: int) -> jnp.ndarray:
    """Slice one tensor axis to its active prefix at a static ``width_rate``."""
    if group.kind == "full":
        return v
    if group.kind == "prefix":
        k = int(math.ceil(group.size * width_rate))
        return jax.lax.slice_in_dim(v, 0, k, axis=axis)
    if group.kind == "per_head":
        hd, kh = _per_head_counts(group, width_rate)
        shp = v.shape
        v = v.reshape(shp[:axis] + (group.num_heads, hd) + shp[axis + 1:])
        v = jax.lax.slice_in_dim(v, 0, kh, axis=axis + 1)
        return v.reshape(shp[:axis] + (group.num_heads * kh,) + shp[axis + 1:])
    raise ValueError(group.kind)


def pad_axis(v: jnp.ndarray, group: Group, width_rate: float, axis: int) -> jnp.ndarray:
    """Zero-pad one sliced axis back to full size (inverse of :func:`slice_axis`)."""
    if group.kind == "full":
        return v
    pads = [(0, 0)] * v.ndim
    if group.kind == "prefix":
        k = int(math.ceil(group.size * width_rate))
        pads[axis] = (0, group.size - k)
        return jnp.pad(v, pads)
    if group.kind == "per_head":
        hd, kh = _per_head_counts(group, width_rate)
        shp = v.shape
        v = v.reshape(shp[:axis] + (group.num_heads, kh) + shp[axis + 1:])
        pads = [(0, 0)] * v.ndim
        pads[axis + 1] = (0, hd - kh)
        v = jnp.pad(v, pads)
        return v.reshape(shp[:axis] + (group.size,) + shp[axis + 1:])
    raise ValueError(group.kind)


def extract_sliced_jnp(params: Dict[str, jnp.ndarray], specs: Dict[str, ParamSpec],
                       groups: Dict[str, Group], width_rate: float) -> Dict[str, jnp.ndarray]:
    """In-jit sub-model extraction at a static rate (the traced twin of
    :func:`extract_sliced`; ref fed.py:165-178)."""
    out = {}
    for k, v in params.items():
        for axis, gname in sorted(specs[k].axis_groups.items()):
            v = slice_axis(v, groups[gname], width_rate, axis)
        out[k] = v
    return out


def embed_sliced_jnp(sliced: Dict[str, jnp.ndarray], specs: Dict[str, ParamSpec],
                     groups: Dict[str, Group], width_rate: float) -> Dict[str, jnp.ndarray]:
    """In-jit zero-pad of sliced tensors back to global shapes (the traced
    twin of :func:`embed_sliced`)."""
    out = {}
    for k, v in sliced.items():
        for axis, gname in sorted(specs[k].axis_groups.items()):
            v = pad_axis(v, groups[gname], width_rate, axis)
        out[k] = v
    return out


# ---------------------------------------------------------------------------
# Sliced strategy (host-side gather/scatter, reference-shaped sub-models)
# ---------------------------------------------------------------------------

def active_indices(group: Group, width_rate: float) -> np.ndarray:
    """Concrete active index set of a group at a given rate (host-side)."""
    if group.kind == "full":
        return np.arange(group.size)
    if group.kind == "prefix":
        k = int(math.ceil(group.size * width_rate))
        return np.arange(group.size)[:k]
    if group.kind == "per_head":
        hd = group.size // group.num_heads
        kh = int(math.ceil(hd * width_rate))
        return (np.arange(group.size).reshape(group.num_heads, hd)[:, :kh]).reshape(-1)
    raise ValueError(group.kind)


def extract_sliced(params: Dict[str, np.ndarray], specs: Dict[str, ParamSpec],
                   groups: Dict[str, Group], width_rate: float) -> Dict[str, np.ndarray]:
    """Gather a true sub-model's params from the global params
    (the reference's ``v[torch.meshgrid(param_idx)]`` deepcopy, fed.py:165-178)."""
    out = {}
    for k, v in params.items():
        v = np.asarray(v)
        for axis, gname in sorted(specs[k].axis_groups.items()):
            v = np.take(v, active_indices(groups[gname], width_rate), axis=axis)
        out[k] = v.copy()
    return out


def embed_sliced(sliced: Dict[str, np.ndarray], specs: Dict[str, ParamSpec],
                 groups: Dict[str, Group], width_rate: float,
                 full_shapes: Dict[str, tuple]) -> Dict[str, np.ndarray]:
    """Scatter a sub-model's params back into zero full-width tensors
    (inverse of :func:`extract_sliced`; the sliced-strategy half of combine)."""
    out = {}
    for k, small in sliced.items():
        idx_arrays = {axis: active_indices(groups[gname], width_rate)
                      for axis, gname in specs[k].axis_groups.items()}
        if not idx_arrays:
            out[k] = np.asarray(small).copy()
            continue
        full = np.zeros(full_shapes[k], dtype=np.asarray(small).dtype)
        out[k] = _scatter_axes(full, np.asarray(small), idx_arrays)
    return out


def _scatter_axes(full: np.ndarray, small: np.ndarray, idx_arrays: Dict[int, np.ndarray]) -> np.ndarray:
    """full[axes-product of idx] = small, returning full."""
    axes = sorted(idx_arrays)
    perm = axes + [a for a in range(full.ndim) if a not in axes]
    inv = np.argsort(perm)
    fullp = np.transpose(full, perm)
    smallp = np.transpose(small, perm)
    fullp[np.ix_(*[idx_arrays[a] for a in axes])] = smallp
    return np.transpose(fullp, inv)
