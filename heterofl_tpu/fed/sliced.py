"""Sliced execution strategy: reference-shaped sub-models, one compiled
program per rate level.

The default "masked" strategy (parallel/round_engine.py) runs every client at
full width with channel masks -- the right trade on TPU (uniform shapes, MXU
tiles).  This runner instead materialises *true* sub-models per rate level
(exactly the tensors the reference's ``Federation.distribute`` ships,
fed.py:165-178): clients are grouped by level, each level's clients are
vmapped through a jitted local-train at its own small static shapes, and
aggregation happens host-side via gather/scatter + counted averaging.

Uses: host/CPU debugging, memory-constrained execution, and the round-level
equivalence check against the masked engine (tests/test_sliced.py) -- with
the same PRNG keys both strategies produce the same new global parameters.

NOTE: this is the host-orchestrated DEBUG twin (measured ~30x slower than
the masked engine).  The production dense-per-level path is the mesh-native
``parallel/grouped.py`` (``strategy: grouped``), which keeps the whole
round on device.
"""

from __future__ import annotations

from typing import Any, Dict, List, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..models import make_model
from ..models.spec import count_masks as make_count_masks
from ..parallel.round_engine import RoundEngine
from .core import (client_stream_keys, combine_counted, embed_sliced,
                   extract_sliced)


class SlicedFederation:
    """Host-orchestrated federated round over true sliced sub-models."""

    def __init__(self, cfg: Dict[str, Any]):
        self.cfg = cfg
        self.global_rate = cfg["global_model_rate"]
        self.global_model = make_model(cfg)
        self.is_lm = self.global_model.meta.get("kind") == "transformer"
        self.levels: Dict[float, Tuple[Any, Any]] = {}
        self._fns: Dict[float, Any] = {}
        for rate in sorted(set(float(r) for r in cfg["model_rate"]), reverse=True):
            model = make_model(cfg, model_rate=rate)
            self.levels[rate] = (model, RoundEngine(model, cfg, mesh=None))

    def _level_fn(self, rate: float):
        """Jitted vmapped local-train for one level (cached)."""
        if rate in self._fns:
            return self._fns[rate]
        model, engine = self.levels[rate]
        sr = rate / self.global_rate
        if self.is_lm:
            def one(p, rows, lm, key, lr):
                return engine._local_train_lm(p, 1.0, rows, lm, key, lr, scaler_rate=sr)
        else:
            def one(p, x, y, m, lm, key, lr):
                return engine._local_train_vision(p, 1.0, x, y, m, lm, key, lr, scaler_rate=sr)
        n_data = 2 if self.is_lm else 4
        fn = jax.jit(jax.vmap(one, in_axes=(0,) * (1 + n_data) + (0, None)))
        self._fns[rate] = fn
        return fn

    def train_round(self, global_params: Dict[str, Any], user_idx: np.ndarray,
                    rates: np.ndarray, data: Tuple, lr: float, key
                    ):
        """One round. ``data`` is the same stacked tuple the masked engine
        takes (vision: ``x[U,N,...], y, m, lm``; LM: ``rows[U,R,T], lm``).
        Client ``u`` uses the PRNG key ``client_stream_keys`` derives from
        its global user id, matching the masked engine on any
        mesh/placement."""
        gp_np = {k: np.asarray(v) for k, v in global_params.items()}
        shapes = {k: v.shape for k, v in gp_np.items()}
        summed = {k: np.zeros(s, np.float32) for k, s in shapes.items()}
        counts = {k: np.zeros(s, np.float32) for k, s in shapes.items()}
        gm = self.global_model
        user_idx = np.asarray(user_idx)
        lm_all = np.asarray(data[-1])

        n_slots = len(user_idx)
        metrics = {"loss_sum": np.zeros(n_slots, np.float32),
                   "score_sum": np.zeros(n_slots, np.float32),
                   "n": np.zeros(n_slots, np.float32),
                   "rate": np.asarray(rates, np.float32)}
        by_level: Dict[float, List[int]] = {}
        for slot, r in enumerate(np.asarray(rates, np.float64)):
            by_level.setdefault(float(r), []).append(slot)

        for rate, slots in sorted(by_level.items(), reverse=True):
            wr = rate / self.global_rate
            sliced = extract_sliced(gp_np, gm.specs, gm.groups, wr)
            params_stack = {k: jnp.asarray(np.broadcast_to(
                v, (len(slots),) + v.shape)) for k, v in sliced.items()}
            u = user_idx[slots]
            keys = client_stream_keys(key, np.asarray(u))
            client_data = tuple(jnp.asarray(np.asarray(a)[u]) for a in data)
            trained, ms = self._level_fn(rate)(params_stack, *client_data, keys,
                                               jnp.asarray(lr, jnp.float32))
            for mk in ("loss_sum", "score_sum", "n"):
                metrics[mk][slots] = np.asarray(ms[mk])
            trained = {k: np.asarray(v) for k, v in trained.items()}
            for ci, slot in enumerate(slots):
                small = {k: trained[k][ci] for k in trained}
                back = embed_sliced(small, gm.specs, gm.groups, wr, shapes)
                cm = {k: np.asarray(v) for k, v in
                      make_count_masks(shapes, gm.specs, gm.groups, wr,
                                       jnp.asarray(lm_all[user_idx[slot]])).items()}
                for k in shapes:
                    summed[k] += back[k] * cm[k]
                    counts[k] += cm[k]
        new = combine_counted(gp_np, summed, counts)
        return {k: np.asarray(v) for k, v in new.items()}, metrics
