"""Federation package: sub-model extraction, counted-average aggregation
(:mod:`.core`), the population sampler subsystem (:mod:`.sampling`, ISSUE
11) and the host-orchestrated sliced debug twin (:mod:`.sliced`).

The package ``__init__`` is LAZY (PEP 562): :mod:`.core` imports jax, but
:mod:`.sampling`'s config half must stay importable jax-free --
``config.process_control`` validates ``cfg['sampler']`` /
``cfg['sample_horizon']`` through it, and the config module's jax-free
import contract (offline analysis tooling) would otherwise silently
break.  ``from heterofl_tpu.fed import extract_sliced`` still works; it
just resolves :mod:`.core` on first touch.
"""

_CORE_EXPORTS = (
    "active_indices",
    "combine_counted",
    "embed_sliced",
    "extract_sliced",
    "sample_model_rates",
    "to_width_rates",
    "client_count_masks",
    "distribute_masked",
)

__all__ = list(_CORE_EXPORTS)


def __getattr__(name):
    if name in _CORE_EXPORTS:
        from . import core

        return getattr(core, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
