from .core import (  # noqa: F401
    active_indices,
    combine_counted,
    embed_sliced,
    extract_sliced,
    sample_model_rates,
    to_width_rates,
    client_count_masks,
    distribute_masked,
)
