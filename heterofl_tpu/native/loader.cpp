// Native data-loading kernels for heterofl_tpu.
//
// The reference is pure Python (SURVEY.md §2.4: no native components); this
// library accelerates the host-side ingestion path that feeds the TPU:
//   * IDX (MNIST-family) ubyte parsing (big-endian header + raw payload)
//   * CIFAR-10/100 *binary* batch parsing (1-2 label bytes + 3072 px/record)
//   * multi-threaded permutation-gather used to stack per-client shards
// Exposed with a C ABI for ctypes (no pybind11 in this image).
//
// Build: see heterofl_tpu/native/__init__.py (g++ -O3 -shared -fPIC).

#include <cstdint>
#include <cstdio>
#include <cstring>
#include <thread>
#include <vector>

extern "C" {

// Parse an IDX header; returns ndim (<=4) and fills dims. Returns -1 on error.
int idx_header(const char* path, int64_t* dims, int* ndim_out) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    unsigned char magic[4];
    if (fread(magic, 1, 4, f) != 4) { fclose(f); return -1; }
    if (magic[0] != 0 || magic[1] != 0 || magic[2] != 0x08) { fclose(f); return -1; }
    int ndim = magic[3];
    if (ndim < 1 || ndim > 4) { fclose(f); return -1; }
    for (int i = 0; i < ndim; ++i) {
        unsigned char b[4];
        if (fread(b, 1, 4, f) != 4) { fclose(f); return -1; }
        dims[i] = ((int64_t)b[0] << 24) | ((int64_t)b[1] << 16) | ((int64_t)b[2] << 8) | b[3];
    }
    *ndim_out = ndim;
    fclose(f);
    return 0;
}

// Read the IDX payload (uint8) into out (caller allocates total bytes).
int idx_read(const char* path, uint8_t* out, int64_t total) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    unsigned char magic[4];
    if (fread(magic, 1, 4, f) != 4) { fclose(f); return -1; }
    int ndim = magic[3];
    if (fseek(f, 4 + 4 * ndim, SEEK_SET) != 0) { fclose(f); return -1; }
    int64_t got = (int64_t)fread(out, 1, (size_t)total, f);
    fclose(f);
    return got == total ? 0 : -1;
}

// Parse a CIFAR binary batch file: n records of (label_bytes, 3072 pixels).
// label_bytes = 1 (CIFAR-10) or 2 (CIFAR-100: coarse, fine). Pixels are
// CHW planes; we emit HWC uint8. labels gets the last label byte (fine).
int cifar_bin_read(const char* path, int64_t n, int label_bytes,
                   uint8_t* images_hwc, int64_t* labels) {
    FILE* f = fopen(path, "rb");
    if (!f) return -1;
    const int HW = 32 * 32;
    std::vector<uint8_t> rec(label_bytes + 3 * HW);
    for (int64_t i = 0; i < n; ++i) {
        if (fread(rec.data(), 1, rec.size(), f) != rec.size()) { fclose(f); return -1; }
        labels[i] = rec[label_bytes - 1];
        const uint8_t* px = rec.data() + label_bytes;
        uint8_t* out = images_hwc + i * 3 * HW;
        for (int p = 0; p < HW; ++p) {
            out[3 * p + 0] = px[p];
            out[3 * p + 1] = px[HW + p];
            out[3 * p + 2] = px[2 * HW + p];
        }
    }
    fclose(f);
    return 0;
}

// out[i, :] = src[idx[i], :] for row_bytes-wide rows, threaded.
void permute_gather_u8(const uint8_t* src, const int64_t* idx, uint8_t* out,
                       int64_t rows, int64_t row_bytes, int n_threads) {
    if (n_threads < 1) n_threads = 1;
    auto work = [&](int64_t lo, int64_t hi) {
        for (int64_t i = lo; i < hi; ++i)
            std::memcpy(out + i * row_bytes, src + idx[i] * row_bytes, (size_t)row_bytes);
    };
    if (n_threads == 1 || rows < 1024) { work(0, rows); return; }
    std::vector<std::thread> ts;
    int64_t chunk = (rows + n_threads - 1) / n_threads;
    for (int t = 0; t < n_threads; ++t) {
        int64_t lo = t * chunk, hi = lo + chunk > rows ? rows : lo + chunk;
        if (lo >= hi) break;
        ts.emplace_back(work, lo, hi);
    }
    for (auto& t : ts) t.join();
}

}  // extern "C"
