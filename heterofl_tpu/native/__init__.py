"""ctypes bindings for the native data-loading kernels (loader.cpp).

Compiled lazily with g++ on first use and cached next to the source; every
entry point has a pure-Python/NumPy fallback, so the framework still works
where no compiler exists.  (pybind11 is unavailable in this image; the C ABI
+ ctypes keeps the binding dependency-free.)
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional, Tuple

import numpy as np

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_HERE, "loader.cpp")
_SO = os.path.join(_HERE, "_loader.so")
_lock = threading.Lock()
_lib = None
_tried = False


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            if (not os.path.exists(_SO)
                    or os.path.getmtime(_SO) < os.path.getmtime(_SRC)):
                tmp = f"{_SO}.{os.getpid()}.tmp"  # unique per process: parallel
                # first-use jobs must not clobber each other's build output
                subprocess.run(
                    ["g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-pthread",
                     _SRC, "-o", tmp],
                    check=True, capture_output=True)
                os.replace(tmp, _SO)
            lib = ctypes.CDLL(_SO)
            lib.idx_header.restype = ctypes.c_int
            lib.idx_read.restype = ctypes.c_int
            lib.cifar_bin_read.restype = ctypes.c_int
            lib.permute_gather_u8.restype = None
            _lib = lib
        except Exception:
            _lib = None
        return _lib


def available() -> bool:
    return _load() is not None


def read_idx(path: str) -> Optional[np.ndarray]:
    """Native IDX parse (uncompressed files); None -> caller falls back."""
    lib = _load()
    if lib is None or path.endswith(".gz"):
        return None
    dims = (ctypes.c_int64 * 4)()
    ndim = ctypes.c_int()
    if lib.idx_header(path.encode(), dims, ctypes.byref(ndim)) != 0:
        return None
    shape = tuple(dims[i] for i in range(ndim.value))
    total = int(np.prod(shape))
    out = np.empty(total, np.uint8)
    if lib.idx_read(path.encode(), out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                    ctypes.c_int64(total)) != 0:
        return None
    return out.reshape(shape)


def read_cifar_bin(path: str, n: int, label_bytes: int) -> Optional[Tuple[np.ndarray, np.ndarray]]:
    """Native CIFAR-binary parse -> (images NHWC uint8, fine labels)."""
    lib = _load()
    if lib is None:
        return None
    imgs = np.empty((n, 32, 32, 3), np.uint8)
    labels = np.empty(n, np.int64)
    rc = lib.cifar_bin_read(path.encode(), ctypes.c_int64(n), ctypes.c_int(label_bytes),
                            imgs.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                            labels.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)))
    if rc != 0:
        return None
    return imgs, labels


def permute_gather(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """out[i] = src[idx[i]] -- threaded native gather for big uint8 arrays,
    NumPy fancy-indexing fallback otherwise."""
    lib = _load()
    src = np.ascontiguousarray(src)
    idx = np.ascontiguousarray(idx, np.int64)
    if (lib is None or src.dtype != np.uint8 or src.nbytes < (1 << 20)
            or len(idx) == 0 or idx.min() < 0 or idx.max() >= len(src)):
        return src[idx]  # numpy path also raises on truly invalid indices
    row_bytes = int(np.prod(src.shape[1:])) * src.itemsize
    out = np.empty((len(idx),) + src.shape[1:], src.dtype)
    lib.permute_gather_u8(src.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                          idx.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                          out.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
                          ctypes.c_int64(len(idx)), ctypes.c_int64(row_bytes),
                          ctypes.c_int(os.cpu_count() or 1))
    return out
