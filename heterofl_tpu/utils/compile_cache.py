"""Persistent XLA compilation-cache wiring.

BENCH_r05 measured ``compile_sec`` 40.3s for the flagship round program --
about one full CPU round.  A warm persistent cache amortises that across
bench runs, tier-1 test sessions and repeated experiments, so the fed entry
drivers, ``tests/conftest.py`` and ``bench.py`` all route through here.

The default cache dir is fingerprinted by the host CPU's feature flags:
XLA:CPU AOT entries embed machine features, and loading a cache written on
a different host risks SIGILL mid-run (observed: ``cpu_aot_loader.cc``
feature-mismatch errors when this box was reprovisioned between rounds).
An operator-set ``JAX_COMPILATION_CACHE_DIR`` always wins.
"""

from __future__ import annotations

import hashlib
import os
import sys
from contextlib import contextmanager
from typing import Optional


def cache_fingerprint() -> str:
    """8-hex digest of the host CPU's feature flags (empty flags on
    non-procfs platforms hash to a stable constant)."""
    try:
        with open("/proc/cpuinfo") as f:
            flags = next((l for l in f if l.startswith("flags")), "")
    except OSError:
        flags = ""
    return hashlib.sha1(flags.encode()).hexdigest()[:8]


def default_cache_dir(root: Optional[str] = None) -> str:
    """``<repo>/.jax_cache/<cpu-fingerprint>`` (root defaults to the
    directory containing the ``heterofl_tpu`` package)."""
    if root is None:
        root = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    return os.path.join(root, ".jax_cache", cache_fingerprint())


def install_cache_counters() -> dict:
    """Live hit/miss counters for the persistent compile cache.

    Subscribes to jax's monitoring events and returns the counter dict they
    increment: ``requests`` counts backend compilations that consulted the
    persistent cache (``/jax/compilation_cache/compile_requests_use_cache``),
    ``hits`` the retrievals (``.../cache_hits``); misses are the difference
    (jax 0.4 emits no explicit miss event).  A bench round whose ``requests``
    grows compiled a new program shape -- the visibility that keeps
    superstep recompiles (a new program per K) from silently eating the
    ~40s flagship compile repeatedly (ISSUE 2 satellite).  Counters stay
    zero (and the bench says so) if the monitoring hook is unavailable or
    the cache is disabled."""
    counters = {"requests": 0, "hits": 0}
    try:
        from jax._src import monitoring

        def _on_event(event, **kwargs):
            if event == "/jax/compilation_cache/compile_requests_use_cache":
                counters["requests"] += 1
            elif event == "/jax/compilation_cache/cache_hits":
                counters["hits"] += 1

        monitoring.register_event_listener(_on_event)
    except Exception:  # jax-internal API; absent => counters stay zero
        pass
    return counters


def enable_persistent_cache(path: Optional[str] = None) -> str:
    """Point jax at a persistent compilation cache and return the dir.

    Safe to call before or after ``import jax``: the env var covers a
    not-yet-imported jax (and any child processes), and a live config
    update covers an already-imported one.
    """
    path = os.environ.get("JAX_COMPILATION_CACHE_DIR") or path or default_cache_dir()
    os.environ.setdefault("JAX_COMPILATION_CACHE_DIR", path)
    os.makedirs(path, exist_ok=True)
    if "jax" in sys.modules:
        import jax

        jax.config.update("jax_compilation_cache_dir", path)
    return path


@contextmanager
def no_persistent_cache():
    """Compile fresh (no persistent-cache reads OR writes) for the scope.

    The chaos drill (ISSUE 15) runs kill -> resume cycles inside ONE
    process; resuming with programs *deserialized* from a warm persistent
    cache while the killed run's donated buffers are still being reclaimed
    trips the XLA:CPU serialized-executable donation bug this repo already
    priced in for codec programs (MEASUREMENTS.md Round 10): bitwise-
    nondeterministic params on a stable subset of leaves, fresh compiles
    always correct (reproduced 3/4 warm vs 5/5 clean cold on the drill's
    corruption-fallback plan).  The drill therefore compiles its small
    synthetic programs fresh; everything outside the scope keeps the warm
    cache, so the tier-1 gate's cache contract is untouched.

    The config flag alone is NOT enough: ``compilation_cache.
    is_cache_used`` latches its decision in module globals at the first
    compile, so in a process that already compiled with the cache on
    (pytest under conftest's warm cache) a later flag flip is silently
    ignored -- ``reset_cache()`` drops the latch (and the initialized
    cache object) so the flag is re-read inside and after the scope."""
    import jax

    try:
        from jax._src import compilation_cache as _cc
    except ImportError:  # pragma: no cover - jax internals moved
        _cc = None
    prev = jax.config.jax_enable_compilation_cache
    jax.config.update("jax_enable_compilation_cache", False)
    if _cc is not None:
        _cc.reset_cache()
    try:
        yield
    finally:
        jax.config.update("jax_enable_compilation_cache", prev)
        if _cc is not None:
            _cc.reset_cache()
