from .compile_cache import default_cache_dir, enable_persistent_cache  # noqa: F401
from .checkpoint import (  # noqa: F401
    CheckpointCorruptError,
    checkpoint_path,
    commit_from_blocks,
    copy_best,
    dense_from_blocks,
    host_shard_blocks,
    is_shard_marker,
    load_checkpoint,
    load_checkpoint_sharded,
    load_newest_verifying,
    load_newest_verifying_sharded,
    resume,
    save_checkpoint,
    save_checkpoint_sharded,
    shard_path,
)
from .logger import Logger  # noqa: F401
from .metrics import Metric, accuracy, perplexity, summarize_sums  # noqa: F401
from .optim import (clip_by_global_norm, make_optimizer, make_scheduler,  # noqa: F401
                    make_traced_lr_fn)
