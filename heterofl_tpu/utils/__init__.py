from .compile_cache import default_cache_dir, enable_persistent_cache  # noqa: F401
from .checkpoint import (  # noqa: F401
    CheckpointCorruptError,
    checkpoint_path,
    copy_best,
    load_checkpoint,
    load_newest_verifying,
    resume,
    save_checkpoint,
)
from .logger import Logger  # noqa: F401
from .metrics import Metric, accuracy, perplexity, summarize_sums  # noqa: F401
from .optim import (clip_by_global_norm, make_optimizer, make_scheduler,  # noqa: F401
                    make_traced_lr_fn)
