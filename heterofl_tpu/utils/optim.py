"""Optimizers and LR schedules.

Parity: ``src/utils.py:260-297``.  Optimizers are pure ``(init, update)``
pairs over param pytrees so they vmap across clients and live inside
``lax.scan``; torch semantics are matched exactly for SGD (the one the
federated configs use: momentum + weight decay applied to the gradient,
``p -= lr * buf``) and closely for RMSprop/Adam/Adamax.

Schedules are pure ``step -> lr`` functions evaluated on the host once per
round (the reference steps a torch scheduler on the *global* optimizer purely
to derive the lr handed to each client's fresh local optimizer,
ref train_classifier_fed.py:104).
"""

from __future__ import annotations

import math
from typing import Any, Callable, Dict, NamedTuple, Tuple

import jax
import jax.numpy as jnp


def clip_by_global_norm(grads, max_norm: float = 1.0):
    """torch.nn.utils.clip_grad_norm_ parity (ref train_classifier_fed.py:205)."""
    leaves = jax.tree_util.tree_leaves(grads)
    total = jnp.sqrt(sum(jnp.sum(g.astype(jnp.float32) ** 2) for g in leaves))
    scale = jnp.minimum(1.0, max_norm / (total + 1e-6))
    return jax.tree_util.tree_map(lambda g: g * scale, grads), total


class OptState(NamedTuple):
    step: jnp.ndarray
    slots: Any  # optimizer-specific pytree(s)


def make_optimizer(cfg: Dict[str, Any]):
    """Return ``(init(params) -> state, update(params, grads, state, lr) ->
    (new_params, new_state))`` for ``cfg['optimizer_name']``."""
    name = cfg["optimizer_name"]
    momentum = cfg.get("momentum", 0.0)
    wd = cfg.get("weight_decay", 0.0)

    if name == "SGD":
        def init(params):
            return OptState(jnp.zeros((), jnp.int32),
                            jax.tree_util.tree_map(jnp.zeros_like, params))

        def update(params, grads, state, lr):
            new_b = jax.tree_util.tree_map(lambda p, g, b: momentum * b + g + wd * p,
                                           params, grads, state.slots)
            new_p = jax.tree_util.tree_map(lambda p, b: p - lr * b, params, new_b)
            return new_p, OptState(state.step + 1, new_b)

        return init, update

    if name == "RMSprop":
        alpha, eps = 0.99, 1e-8

        def init(params):
            z = jax.tree_util.tree_map(jnp.zeros_like, params)
            return OptState(jnp.zeros((), jnp.int32), {"sq": z, "buf": z})

        def update(params, grads, state, lr):
            # torch: grad = grad + wd*p, applied before square accumulation
            g2 = jax.tree_util.tree_map(lambda g, p: g + wd * p, grads, params)
            sq = jax.tree_util.tree_map(lambda s, g: alpha * s + (1 - alpha) * g * g,
                                        state.slots["sq"], g2)
            buf = jax.tree_util.tree_map(lambda b, g, s: momentum * b + g / (jnp.sqrt(s) + eps),
                                         state.slots["buf"], g2, sq)
            new_p = jax.tree_util.tree_map(lambda p, b: p - lr * b, params, buf)
            return new_p, OptState(state.step + 1, {"sq": sq, "buf": buf})

        return init, update

    if name in ("Adam", "Adamax"):
        b1, b2, eps = 0.9, 0.999, 1e-8

        def init(params):
            z = jax.tree_util.tree_map(jnp.zeros_like, params)
            return OptState(jnp.zeros((), jnp.int32), {"m": z, "v": z})

        def update(params, grads, state, lr):
            t = state.step + 1
            g2 = jax.tree_util.tree_map(lambda g, p: g + wd * p, grads, params)
            m = jax.tree_util.tree_map(lambda m_, g: b1 * m_ + (1 - b1) * g, state.slots["m"], g2)
            if name == "Adam":
                v = jax.tree_util.tree_map(lambda v_, g: b2 * v_ + (1 - b2) * g * g, state.slots["v"], g2)
                denom = jax.tree_util.tree_map(
                    lambda v_: jnp.sqrt(v_ / (1 - b2 ** t.astype(jnp.float32))) + eps, v)
            else:  # Adamax: infinity norm
                v = jax.tree_util.tree_map(lambda v_, g: jnp.maximum(b2 * v_, jnp.abs(g) + eps),
                                           state.slots["v"], g2)
                denom = v
            mhat = jax.tree_util.tree_map(lambda m_: m_ / (1 - b1 ** t.astype(jnp.float32)), m)
            new_p = jax.tree_util.tree_map(lambda p, mh, d: p - lr * mh / d, params, mhat, denom)
            return new_p, OptState(t, {"m": m, "v": v})

        return init, update

    raise ValueError("Not valid optimizer name")


def make_scheduler(cfg: Dict[str, Any]) -> Callable[[int], float]:
    """LR as a pure function of the (1-indexed) global round.

    Kinds mirror src/utils.py:276-297: None, StepLR, MultiStepLR,
    ExponentialLR, CosineAnnealingLR, CyclicLR, ReduceLROnPlateau (the last
    needs a metric feed; see :class:`PlateauScheduler`).
    """
    name = cfg["scheduler_name"]
    base = cfg["lr"]
    factor = cfg.get("factor", 0.1)
    if name == "None":
        return lambda step: base
    if name == "StepLR":
        size = cfg["step_size"]
        return lambda step: base * factor ** ((step - 1) // size)
    if name == "MultiStepLR":
        miles = sorted(cfg["milestones"])
        return lambda step: base * factor ** sum(1 for m in miles if step - 1 >= m)
    if name == "ExponentialLR":
        return lambda step: base * 0.99 ** (step - 1)
    if name == "CosineAnnealingLR":
        tmax = cfg["num_epochs"]["global"] if isinstance(cfg["num_epochs"], dict) else cfg["num_epochs"]
        eta_min = cfg.get("min_lr", 0.0)
        return lambda step: eta_min + (base - eta_min) * (1 + math.cos(math.pi * (step - 1) / tmax)) / 2
    if name == "CyclicLR":
        # torch defaults: step_size_up=2000 iters, triangular
        up = 2000
        return lambda step: base + (10 * base - base) * _triangle((step - 1) / up)
    if name == "ReduceLROnPlateau":
        return PlateauScheduler(base, factor, cfg.get("patience", 10),
                                cfg.get("threshold", 1e-3), cfg.get("min_lr", 0.0))
    raise ValueError("Not valid scheduler name")


def make_traced_lr_fn(cfg: Dict[str, Any]) -> Callable[[jnp.ndarray], jnp.ndarray]:
    """The in-jit twin of :func:`make_scheduler`: LR as a traced function of
    the (1-indexed, possibly traced) global round index.

    This is what lets the superstep driver (``train_superstep``) evaluate
    the schedule from the round index carried inside ``lax.scan`` instead of
    staging a host scalar per round.  Supported kinds are exactly the
    stateless ``step -> lr`` schedules; ``ReduceLROnPlateau`` needs the eval
    metric feed and raises here -- the config layer surfaces that as a loud
    ``superstep_rounds`` conflict.  Values match :func:`make_scheduler` to
    float32 resolution (the host path stages its f64 result to an f32 device
    scalar; tests/test_superstep.py pins the agreement over 400 rounds)."""
    name = cfg["scheduler_name"]
    base = jnp.float32(cfg["lr"])
    factor = jnp.float32(cfg.get("factor", 0.1))
    if name == "None":
        return lambda step: base
    if name == "StepLR":
        size = cfg["step_size"]
        return lambda step: base * factor ** ((step - 1) // size)
    if name == "MultiStepLR":
        miles = jnp.asarray(sorted(cfg["milestones"]), jnp.int32)
        return lambda step: base * factor ** jnp.sum(step - 1 >= miles)
    if name == "ExponentialLR":
        return lambda step: base * jnp.float32(0.99) ** (step - 1)
    if name == "CosineAnnealingLR":
        tmax = cfg["num_epochs"]["global"] if isinstance(cfg["num_epochs"], dict) else cfg["num_epochs"]
        eta_min = jnp.float32(cfg.get("min_lr", 0.0))
        return lambda step: eta_min + (base - eta_min) * (
            1 + jnp.cos(jnp.pi * (step - 1).astype(jnp.float32) / tmax)) / 2
    if name == "CyclicLR":
        up = 2000

        def _tri(x):
            cycle = jnp.floor(1 + x / 2)
            return jnp.maximum(0.0, 1 - jnp.abs(x - 2 * cycle + 1))

        return lambda step: base + (10 * base - base) * _tri((step - 1).astype(jnp.float32) / up)
    raise ValueError(
        f"scheduler {name!r} is not a pure function of the round index and "
        f"cannot run inside a superstep (set superstep_rounds=1 or pick a "
        f"stateless schedule)")


def _triangle(x: float) -> float:
    cycle = math.floor(1 + x / 2)
    xx = abs(x / 1 - 2 * cycle + 1)
    return max(0.0, 1 - xx)


class PlateauScheduler:
    """min-mode ReduceLROnPlateau with relative threshold (torch parity)."""

    def __init__(self, base: float, factor: float, patience: int, threshold: float, min_lr: float):
        self.lr = base
        self.factor, self.patience, self.threshold, self.min_lr = factor, patience, threshold, min_lr
        self.best = float("inf")
        self.bad = 0

    def __call__(self, step: int) -> float:
        return self.lr

    def step_metric(self, metric: float) -> None:
        if metric < self.best * (1 - self.threshold):
            self.best = metric
            self.bad = 0
        else:
            self.bad += 1
            if self.bad > self.patience:
                self.lr = max(self.lr * self.factor, self.min_lr)
                self.bad = 0

    def state_dict(self) -> Dict[str, float]:
        """Mutable state for checkpoints (the reference pickles the whole
        torch scheduler, src/utils.py:302-312); a resumed run keeps its
        plateau counters instead of restarting them."""
        return {"lr": self.lr, "best": self.best, "bad": self.bad}

    def load_state_dict(self, state: Dict[str, float]) -> None:
        self.lr = float(state["lr"])
        self.best = float(state["best"])
        self.bad = int(state["bad"])
