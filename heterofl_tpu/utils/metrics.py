"""Metric registry: Loss / Accuracy / Perplexity with Local-/Global- prefixed
variants (parity: ``src/metrics/metrics.py``).

Two consumption paths:

* :class:`Metric` -- name -> closure registry evaluated on a single batch's
  ``(input, output)`` dicts, like the reference.
* :func:`summarize_sums` -- converts the round engine's device-side weighted
  sums (``loss_sum`` / ``score_sum`` / ``n``) into the same named metrics
  without a host round-trip per batch.
"""

from __future__ import annotations

from typing import Dict, Iterable

import numpy as np


def accuracy(score, label, topk: int = 1) -> float:
    """Top-k accuracy in percent (ref metrics.py:7-13). Class axis is last."""
    score = np.asarray(score)
    label = np.asarray(label)
    flat = score.reshape(-1, score.shape[-1])
    lab = label.reshape(-1)
    if topk == 1:
        correct = (np.argmax(flat, -1) == lab).sum()
    else:
        top = np.argsort(-flat, axis=-1)[:, :topk]
        correct = (top == lab[:, None]).any(-1).sum()
    return float(correct * 100.0 / lab.shape[0])


def perplexity(score, label) -> float:
    """exp(cross entropy) (ref metrics.py:16-25). Class axis is last."""
    score = np.asarray(score, np.float64)
    label = np.asarray(label)
    flat = score.reshape(-1, score.shape[-1])
    lab = label.reshape(-1)
    mx = flat.max(-1, keepdims=True)
    logz = mx[:, 0] + np.log(np.exp(flat - mx).sum(-1))
    ce = (logz - flat[np.arange(lab.shape[0]), lab]).mean()
    return float(np.exp(ce))


class Metric:
    def __init__(self):
        loss = lambda inp, out: float(out["loss"])
        acc = lambda inp, out: accuracy(out["score"], inp["label"])
        ppl = lambda inp, out: perplexity(out["score"], inp["label"])
        self.metric = {}
        for prefix in ("", "Local-", "Global-"):
            self.metric[prefix + "Loss"] = loss
            self.metric[prefix + "Accuracy"] = acc
            self.metric[prefix + "Perplexity"] = ppl

    def evaluate(self, metric_names: Iterable[str], inp, out) -> Dict[str, float]:
        return {name: self.metric[name](inp, out) for name in metric_names}


def summarize_sums(sums: Dict[str, np.ndarray], kind: str, prefix: str = "Local-"
                   ) -> Dict[str, float]:
    """Round-engine sums -> named means.

    vision: ``score_sum`` is the weighted correct count -> Accuracy %%;
    LM: ``score_sum`` is the row-weighted sum of per-window exp(CE) ->
    Perplexity (the reference's size-weighted mean of batch perplexities).
    """
    n = float(np.sum(sums["n"]))
    if n <= 0:
        return {}
    loss = float(np.sum(sums["loss_sum"])) / n
    out = {prefix + "Loss": loss}
    if kind == "transformer":
        out[prefix + "Perplexity"] = float(np.sum(sums["score_sum"])) / n
    else:
        out[prefix + "Accuracy"] = float(np.sum(sums["score_sum"])) / n * 100.0
    return out
