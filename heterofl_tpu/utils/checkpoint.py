"""Checkpoint / resume -- durable, generational, verified (ISSUE 15).

Parity: ``src/utils.py:300-344`` + the per-round save in
``train_classifier_fed.py:84-93``: each round stores
``{cfg, epoch, data_split, label_split, params, bn_state, scheduler_state,
logger history}`` to ``output/model/{tag}_checkpoint.pkl`` with a best-pivot
copy to ``_best.pkl``; resume restores everything *including the data
partition* so a resumed run keeps identical client shards.

``resume_mode``: 0 fresh / 1 full resume / 2 weights+splits only
(ref train_classifier_fed.py:57-69).

Fault tolerance (ISSUE 15 tentpole piece 2) -- the seed implementation had
three durability holes the chaos harness now exercises on purpose:

* **torn writes**: ``os.replace`` alone is atomic against *renames*, not
  against the page cache -- a power loss between the pickle write and the
  rename could land a zero-length (or partially-flushed) blob under the
  final name on some filesystems.  Every write now goes tmp -> flush ->
  ``os.fsync(file)`` -> ``os.replace`` -> ``os.fsync(dir)``.
* **silent corruption**: a bit-flip on disk unpickled into garbage (or a
  raw ``UnpicklingError`` traceback).  Blobs now carry a header --
  ``HFTCKPT1`` magic + SHA-256 of the payload -- verified on load; any
  mismatch/truncation/unpickling failure raises the typed
  :class:`CheckpointCorruptError` so callers can distinguish "corrupt"
  from "absent".  Headerless legacy blobs still load (verified only by
  unpickling cleanly).
* **single generation**: the newest blob was the only blob, so corrupting
  it bricked the run.  ``save_checkpoint(..., keep=N)`` rotates the
  previous checkpoint to ``.g1`` (and ``.g1`` to ``.g2``, ...) keeping
  ``N`` generations; :func:`resume` falls back generation by generation
  to the newest VERIFYING blob with a loud structured warning, and raises
  :class:`CheckpointCorruptError` only when every present generation
  fails -- never a silent fresh start over a recoverable run.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import warnings
from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax.numpy as jnp
import numpy as np

#: blob header: magic + 32-byte SHA-256 of the pickle payload.  Versioned
#: in the magic itself so a future format bump is detectable, not a
#: checksum mismatch.
CHECKPOINT_MAGIC = b"HFTCKPT1"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint blob exists but fails verification: bad checksum,
    truncated header/payload, or an unpickling failure.  Distinguishes
    "corrupt" from "absent" (``FileNotFoundError``) so resume/rollback can
    fall back a generation instead of dying on a raw traceback."""


def _to_host(tree):
    if isinstance(tree, dict):
        return {k: _to_host(v) for k, v in tree.items()}
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):  # NamedTuple
        return type(tree)(*(_to_host(v) for v in tree))
    if isinstance(tree, (list, tuple)):
        return type(tree)(_to_host(v) for v in tree)
    if isinstance(tree, (jnp.ndarray, np.ndarray)):
        return np.asarray(tree)
    return tree


def _fsync_dir(path: str) -> None:
    """fsync the directory entry so a rename survives power loss (no-op on
    filesystems that do not support opening directories)."""
    d = os.path.dirname(os.path.abspath(path))
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:  # pragma: no cover - exotic fs
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - exotic fs
        pass
    finally:
        os.close(fd)


def _write_durable(path: str, payload: bytes) -> None:
    """tmp -> flush -> fsync -> rename -> fsync(dir): the one torn-write-
    safe byte sink every checkpoint write (save, rotation seed, best copy)
    goes through."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # atomic: a crash never corrupts the previous ckpt
    _fsync_dir(path)


def _blob_bytes(blob: Dict[str, Any]) -> bytes:
    payload = pickle.dumps(_to_host(blob), protocol=4)
    digest = hashlib.sha256(payload).digest()
    return CHECKPOINT_MAGIC + digest + payload


def generation_path(path: str, gen: int) -> str:
    """Generation ``gen`` of ``path``: 0 is the live checkpoint, 1.. are
    the rotated older generations (``{path}.g1``, ``{path}.g2``, ...)."""
    return path if gen == 0 else f"{path}.g{gen}"


def generation_paths(path: str) -> List[str]:
    """Every existing generation of ``path``, newest first.

    Rotated generations are discovered by LISTING the directory, not by
    walking until the first hole: a crash between :func:`_rotate`'s
    renames can leave a gap (e.g. ``{live, .g2}`` with no ``.g1``), and a
    walk that stopped there would strand the older verifying blob the
    fallback exists to reach."""
    out = [path] if os.path.exists(path) else []
    d, base = os.path.split(path)
    prefix = base + ".g"
    try:
        names = os.listdir(d or ".")
    except OSError:
        names = []
    gens = sorted(int(n[len(prefix):]) for n in names
                  if n.startswith(prefix) and n[len(prefix):].isdigit())
    out.extend(os.path.join(d, f"{base}.g{g}") for g in gens)
    return out


def _rotate(path: str, keep: int) -> None:
    """Shift existing generations one slot older, dropping those past
    ``keep - 1`` (the live blob the caller is about to write is generation
    0).  Pure renames -- cheap, and a crash mid-rotation leaves every blob
    intact under SOME generation name, which resume's fallback walk
    tolerates."""
    if keep <= 1 or not os.path.exists(path):
        return
    # drop the oldest slot(s) that rotation would push past the cap
    gens = []
    g = 1
    while os.path.exists(generation_path(path, g)):
        gens.append(g)
        g += 1
    for g in reversed(gens):
        src = generation_path(path, g)
        if g + 1 >= keep:
            os.remove(src)
        else:
            os.replace(src, generation_path(path, g + 1))
    os.replace(path, generation_path(path, 1))
    _fsync_dir(path)


def save_checkpoint(path: str, blob: Dict[str, Any], keep: int = 1) -> None:
    """Durably write ``blob`` to ``path``, rotating up to ``keep``
    generations (``keep=1`` keeps only the live blob -- the seed
    behaviour, still torn-write-safe and checksummed)."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = _blob_bytes(blob)
    _rotate(path, keep)
    _write_durable(path, payload)


def load_checkpoint(path: str) -> Dict[str, Any]:
    """Load + verify one checkpoint blob.

    Raises ``FileNotFoundError`` when absent and
    :class:`CheckpointCorruptError` on any verification failure: checksum
    mismatch, truncated header/payload, or (for headerless legacy blobs)
    an unpickling error."""
    with open(path, "rb") as f:
        raw = f.read()
    if raw.startswith(CHECKPOINT_MAGIC):
        head = len(CHECKPOINT_MAGIC)
        if len(raw) < head + 32:
            raise CheckpointCorruptError(
                f"checkpoint {path}: truncated header "
                f"({len(raw)} bytes)")
        digest, payload = raw[head:head + 32], raw[head + 32:]
        if hashlib.sha256(payload).digest() != digest:
            raise CheckpointCorruptError(
                f"checkpoint {path}: SHA-256 mismatch (bit rot or a torn "
                f"write); {len(payload)} payload bytes")
    else:
        payload = raw  # legacy headerless blob: verified by unpickling only
    try:
        return pickle.loads(payload)
    except Exception as e:
        raise CheckpointCorruptError(
            f"checkpoint {path}: unpickling failed ({e!r})") from e


def checkpoint_path(output_dir: str, tag: str, which: str = "checkpoint") -> str:
    return os.path.join(output_dir, "model", f"{tag}_{which}.pkl")


def copy_best(output_dir: str, tag: str) -> None:
    """Copy the live checkpoint to the best-pivot blob through the SAME
    tmp+fsync+rename path as :func:`save_checkpoint` (ISSUE 15 satellite:
    the seed's plain ``shutil.copy`` could leave a torn ``_best.pkl`` on a
    crash mid-copy).  Bytes are copied verbatim, so the checksum header
    rides along unchanged."""
    with open(checkpoint_path(output_dir, tag, "checkpoint"), "rb") as f:
        payload = f.read()
    _write_durable(checkpoint_path(output_dir, tag, "best"), payload)


def iter_verified_generations(path: str
                              ) -> Iterator[Tuple[str, Dict[str, Any]]]:
    """Yield ``(generation path, verified blob)`` newest-first, emitting a
    loud structured warning for every generation that fails verification
    (the rollback/resume fallback walk)."""
    for p in generation_paths(path):
        try:
            yield p, load_checkpoint(p)
        except CheckpointCorruptError as e:
            warnings.warn(
                "checkpoint generation failed verification, falling back: "
                + json.dumps({"event": "checkpoint-corrupt", "path": p,
                              "error": str(e)}))


def load_newest_verifying(path: str) -> Optional[Dict[str, Any]]:
    """The newest generation of ``path`` that verifies, or None when no
    generation exists at all.  Raises :class:`CheckpointCorruptError` when
    generations exist but EVERY one fails -- a silent fresh start over a
    recoverable run is the one outcome this module exists to prevent."""
    gens = generation_paths(path)
    if not gens:
        return None
    for _p, blob in iter_verified_generations(path):
        return blob
    raise CheckpointCorruptError(
        f"all {len(gens)} checkpoint generation(s) of {path} failed "
        f"verification; refusing to silently restart from scratch (delete "
        f"the blobs to run fresh)")


def resume(output_dir: str, tag: str, mode: int, load_tag: str = "checkpoint"
           ) -> Optional[Dict[str, Any]]:
    """Return the checkpoint blob according to ``resume_mode`` or None.

    mode 0 -> always fresh; mode 1 -> full blob; mode 2 -> weights + splits
    only (epoch restarts at 1, fresh logger/scheduler).

    A corrupt newest generation falls back, generation by generation, to
    the newest verifying blob (loud structured warning per skipped
    generation); when every present generation fails,
    :class:`CheckpointCorruptError` propagates."""
    if mode == 0:
        return None
    path = checkpoint_path(output_dir, tag, load_tag)
    blob = load_newest_verifying(path)
    if blob is None:
        print(f"Not exists model tag: {tag}, start from scratch")
        return None
    print(f"Resume from {blob.get('epoch')}")
    if mode == 2:
        return {k: blob[k] for k in ("params", "bn_state", "data_split", "label_split")
                if k in blob}
    return blob
