"""Checkpoint / resume -- durable, generational, verified (ISSUE 15).

Parity: ``src/utils.py:300-344`` + the per-round save in
``train_classifier_fed.py:84-93``: each round stores
``{cfg, epoch, data_split, label_split, params, bn_state, scheduler_state,
logger history}`` to ``output/model/{tag}_checkpoint.pkl`` with a best-pivot
copy to ``_best.pkl``; resume restores everything *including the data
partition* so a resumed run keeps identical client shards.

``resume_mode``: 0 fresh / 1 full resume / 2 weights+splits only
(ref train_classifier_fed.py:57-69).

Fault tolerance (ISSUE 15 tentpole piece 2) -- the seed implementation had
three durability holes the chaos harness now exercises on purpose:

* **torn writes**: ``os.replace`` alone is atomic against *renames*, not
  against the page cache -- a power loss between the pickle write and the
  rename could land a zero-length (or partially-flushed) blob under the
  final name on some filesystems.  Every write now goes tmp -> flush ->
  ``os.fsync(file)`` -> ``os.replace`` -> ``os.fsync(dir)``.
* **silent corruption**: a bit-flip on disk unpickled into garbage (or a
  raw ``UnpicklingError`` traceback).  Blobs now carry a header --
  ``HFTCKPT1`` magic + SHA-256 of the payload -- verified on load; any
  mismatch/truncation/unpickling failure raises the typed
  :class:`CheckpointCorruptError` so callers can distinguish "corrupt"
  from "absent".  Headerless legacy blobs still load (verified only by
  unpickling cleanly).
* **single generation**: the newest blob was the only blob, so corrupting
  it bricked the run.  ``save_checkpoint(..., keep=N)`` rotates the
  previous checkpoint to ``.g1`` (and ``.g1`` to ``.g2``, ...) keeping
  ``N`` generations; :func:`resume` falls back generation by generation
  to the newest VERIFYING blob with a loud structured warning, and raises
  :class:`CheckpointCorruptError` only when every present generation
  fails -- never a silent fresh start over a recoverable run.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import warnings
from typing import Any, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

#: blob header: magic + 32-byte SHA-256 of the pickle payload.  Versioned
#: in the magic itself so a future format bump is detectable, not a
#: checksum mismatch.
CHECKPOINT_MAGIC = b"HFTCKPT1"

#: reserved header-blob key describing a sharded checkpoint's shard set
#: (ISSUE 17): ``{"count", "files", "stamp"}``.  Present only in blobs
#: written by :func:`save_checkpoint_sharded` on a multi-process runtime.
SHARD_SET_KEY = "__heterofl_shard_set__"

#: marker key identifying a leaf that was persisted as per-process device
#: shard blocks instead of one dense host array.
BLOCKS_KEY = "__shard_blocks__"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint blob exists but fails verification: bad checksum,
    truncated header/payload, or an unpickling failure.  Distinguishes
    "corrupt" from "absent" (``FileNotFoundError``) so resume/rollback can
    fall back a generation instead of dying on a raw traceback."""


def _to_host(tree):
    if isinstance(tree, dict):
        return {k: _to_host(v) for k, v in tree.items()}
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):  # NamedTuple
        return type(tree)(*(_to_host(v) for v in tree))
    if isinstance(tree, (list, tuple)):
        return type(tree)(_to_host(v) for v in tree)
    if isinstance(tree, (jnp.ndarray, np.ndarray)):
        if isinstance(tree, jax.Array) and not tree.is_fully_addressable:
            if tree.is_fully_replicated:
                # multi-process replicated leaf: the local replica IS the
                # full value (staticcheck: allow(no-asarray): ckpt D2H)
                return np.asarray(tree.addressable_data(0))
            raise ValueError(
                "checkpoint blob contains a sharded multi-process array "
                f"(shape {tuple(tree.shape)}, sharding {tree.sharding}); "
                "use save_checkpoint_sharded / host_shard_blocks so each "
                "process persists only its own rows (ISSUE 17)")
        return np.asarray(tree)
    return tree


def _fsync_dir(path: str) -> None:
    """fsync the directory entry so a rename survives power loss (no-op on
    filesystems that do not support opening directories)."""
    d = os.path.dirname(os.path.abspath(path))
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:  # pragma: no cover - exotic fs
        return
    try:
        os.fsync(fd)
    except OSError:  # pragma: no cover - exotic fs
        pass
    finally:
        os.close(fd)


def _write_durable(path: str, payload: bytes) -> None:
    """tmp -> flush -> fsync -> rename -> fsync(dir): the one torn-write-
    safe byte sink every checkpoint write (save, rotation seed, best copy)
    goes through."""
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        f.write(payload)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)  # atomic: a crash never corrupts the previous ckpt
    _fsync_dir(path)


def _blob_bytes(blob: Dict[str, Any]) -> bytes:
    payload = pickle.dumps(_to_host(blob), protocol=4)
    digest = hashlib.sha256(payload).digest()
    return CHECKPOINT_MAGIC + digest + payload


def generation_path(path: str, gen: int) -> str:
    """Generation ``gen`` of ``path``: 0 is the live checkpoint, 1.. are
    the rotated older generations (``{path}.g1``, ``{path}.g2``, ...)."""
    return path if gen == 0 else f"{path}.g{gen}"


def generation_paths(path: str) -> List[str]:
    """Every existing generation of ``path``, newest first.

    Rotated generations are discovered by LISTING the directory, not by
    walking until the first hole: a crash between :func:`_rotate`'s
    renames can leave a gap (e.g. ``{live, .g2}`` with no ``.g1``), and a
    walk that stopped there would strand the older verifying blob the
    fallback exists to reach."""
    out = [path] if os.path.exists(path) else []
    d, base = os.path.split(path)
    prefix = base + ".g"
    try:
        names = os.listdir(d or ".")
    except OSError:
        names = []
    gens = sorted(int(n[len(prefix):]) for n in names
                  if n.startswith(prefix) and n[len(prefix):].isdigit())
    out.extend(os.path.join(d, f"{base}.g{g}") for g in gens)
    return out


def _rotate(path: str, keep: int) -> None:
    """Shift existing generations one slot older, dropping those past
    ``keep - 1`` (the live blob the caller is about to write is generation
    0).  Pure renames -- cheap, and a crash mid-rotation leaves every blob
    intact under SOME generation name, which resume's fallback walk
    tolerates."""
    if keep <= 1 or not os.path.exists(path):
        return
    # drop the oldest slot(s) that rotation would push past the cap
    gens = []
    g = 1
    while os.path.exists(generation_path(path, g)):
        gens.append(g)
        g += 1
    for g in reversed(gens):
        src = generation_path(path, g)
        if g + 1 >= keep:
            os.remove(src)
        else:
            os.replace(src, generation_path(path, g + 1))
    os.replace(path, generation_path(path, 1))
    _fsync_dir(path)


def save_checkpoint(path: str, blob: Dict[str, Any], keep: int = 1) -> None:
    """Durably write ``blob`` to ``path``, rotating up to ``keep``
    generations (``keep=1`` keeps only the live blob -- the seed
    behaviour, still torn-write-safe and checksummed)."""
    os.makedirs(os.path.dirname(path), exist_ok=True)
    payload = _blob_bytes(blob)
    _rotate(path, keep)
    _write_durable(path, payload)


def load_checkpoint(path: str) -> Dict[str, Any]:
    """Load + verify one checkpoint blob.

    Raises ``FileNotFoundError`` when absent and
    :class:`CheckpointCorruptError` on any verification failure: checksum
    mismatch, truncated header/payload, or (for headerless legacy blobs)
    an unpickling error."""
    with open(path, "rb") as f:
        raw = f.read()
    if raw.startswith(CHECKPOINT_MAGIC):
        head = len(CHECKPOINT_MAGIC)
        if len(raw) < head + 32:
            raise CheckpointCorruptError(
                f"checkpoint {path}: truncated header "
                f"({len(raw)} bytes)")
        digest, payload = raw[head:head + 32], raw[head + 32:]
        if hashlib.sha256(payload).digest() != digest:
            raise CheckpointCorruptError(
                f"checkpoint {path}: SHA-256 mismatch (bit rot or a torn "
                f"write); {len(payload)} payload bytes")
    else:
        payload = raw  # legacy headerless blob: verified by unpickling only
    try:
        return pickle.loads(payload)
    except Exception as e:
        raise CheckpointCorruptError(
            f"checkpoint {path}: unpickling failed ({e!r})") from e


def checkpoint_path(output_dir: str, tag: str, which: str = "checkpoint") -> str:
    return os.path.join(output_dir, "model", f"{tag}_{which}.pkl")


def copy_best(output_dir: str, tag: str) -> None:
    """Copy the live checkpoint to the best-pivot blob through the SAME
    tmp+fsync+rename path as :func:`save_checkpoint` (ISSUE 15 satellite:
    the seed's plain ``shutil.copy`` could leave a torn ``_best.pkl`` on a
    crash mid-copy).  Bytes are copied verbatim, so the checksum header
    rides along unchanged.

    A SHARDED live checkpoint (ISSUE 17) is mirrored file-by-file: every
    shard copies verbatim under the best tag's shard names and the header
    is re-serialised with the renamed shard set (same stamp, so a later
    rotation of the live shards cannot tear the best blob)."""
    src = checkpoint_path(output_dir, tag, "checkpoint")
    dst = checkpoint_path(output_dir, tag, "best")
    with open(src, "rb") as f:
        payload = f.read()
    header = load_checkpoint(src)
    ss = header.get(SHARD_SET_KEY) if isinstance(header, dict) else None
    if ss:
        d = os.path.dirname(src)
        files = []
        for j, base in enumerate(ss["files"]):
            with open(os.path.join(d, base), "rb") as f:
                sbytes = f.read()
            nbase = os.path.basename(shard_path(dst, j, ss["count"]))
            _write_durable(os.path.join(d, nbase), sbytes)
            files.append(nbase)
        header[SHARD_SET_KEY] = {**ss, "files": files}
        _write_durable(dst, _blob_bytes(header))
        return
    _write_durable(dst, payload)


def iter_verified_generations(path: str
                              ) -> Iterator[Tuple[str, Dict[str, Any]]]:
    """Yield ``(generation path, verified blob)`` newest-first, emitting a
    loud structured warning for every generation that fails verification
    (the rollback/resume fallback walk)."""
    for p in generation_paths(path):
        try:
            yield p, load_checkpoint(p)
        except CheckpointCorruptError as e:
            warnings.warn(
                "checkpoint generation failed verification, falling back: "
                + json.dumps({"event": "checkpoint-corrupt", "path": p,
                              "error": str(e)}))


def load_newest_verifying(path: str) -> Optional[Dict[str, Any]]:
    """The newest generation of ``path`` that verifies, or None when no
    generation exists at all.  Raises :class:`CheckpointCorruptError` when
    generations exist but EVERY one fails -- a silent fresh start over a
    recoverable run is the one outcome this module exists to prevent."""
    gens = generation_paths(path)
    if not gens:
        return None
    for _p, blob in iter_verified_generations(path):
        return blob
    raise CheckpointCorruptError(
        f"all {len(gens)} checkpoint generation(s) of {path} failed "
        f"verification; refusing to silently restart from scratch (delete "
        f"the blobs to run fresh)")


# ---------------------------------------------------------------------------
# Per-process shard checkpoints (ISSUE 17)
# ---------------------------------------------------------------------------

def shard_path(path: str, i: int, n: int) -> str:
    """Process ``i``'s shard file of an ``n``-process sharded checkpoint:
    ``{path}.shard{i:03d}-of-{n:03d}``.  Each shard is a self-verifying
    blob (same magic + SHA-256 header as the main checkpoint)."""
    return f"{path}.shard{i:03d}-of-{n:03d}"


def is_shard_marker(x) -> bool:
    """True for a leaf persisted as per-process shard blocks."""
    return isinstance(x, dict) and x.get(BLOCKS_KEY) is True


def host_shard_blocks(a) -> Dict[str, Any]:
    """THIS process's host copy of its addressable shards of a committed
    (possibly multi-process) array, as a picklable marker dict:
    ``{BLOCKS_KEY: True, shape, dtype, blocks: {((start, stop), ...):
    ndarray}}``.  Replicated shards deduplicate by index, so the union of
    every process's blocks tiles the global array exactly once."""
    blocks: Dict[Tuple, np.ndarray] = {}
    shape = tuple(a.shape)
    for sh in a.addressable_shards:
        key = tuple(s.indices(d)[:2] for s, d in zip(sh.index, shape))
        if key not in blocks:
            # checkpoint-boundary D2H of a local device shard (superstep
            # boundaries only; utils/ is outside the hot-path lint scope)
            blocks[key] = np.asarray(sh.data)
    return {BLOCKS_KEY: True, "shape": shape, "dtype": str(a.dtype),
            "blocks": blocks}


def commit_from_blocks(marker: Dict[str, Any], sharding):
    """Re-commit a shard-blocks marker onto ``sharding``: the restore twin
    of :func:`host_shard_blocks`.  Each process hands the runtime the
    blocks its devices need via ``jax.make_array_from_callback``; a block
    missing from the (merged) set raises ``CheckpointCorruptError`` --
    resuming onto a mesh whose shard grid does not match the saved one is
    a detectable error, not silent garbage."""
    shape = tuple(marker["shape"])
    blocks = marker["blocks"]

    def cb(index):
        key = tuple(s.indices(d)[:2] for s, d in zip(index, shape))
        try:
            return blocks[key]
        except KeyError:
            raise CheckpointCorruptError(
                f"sharded checkpoint leaf (shape {shape}) has no block for "
                f"device index {key}: the restore mesh's shard grid does "
                f"not match the saved one (have {sorted(blocks)})")

    return jax.make_array_from_callback(shape, sharding, cb)


def dense_from_blocks(marker: Dict[str, Any]) -> np.ndarray:
    """Assemble a full host array from a MERGED shard-blocks marker (every
    process's blocks, i.e. a marker out of :func:`load_checkpoint_sharded`).
    The topology-independent restore path: the dense array re-commits onto
    ANY mesh via ``commit_global``, so a 2-process checkpoint resumes on 1
    process (and vice versa).  Raises :class:`CheckpointCorruptError` on
    coverage holes."""
    shape = tuple(marker["shape"])
    out = np.empty(shape, np.dtype(marker["dtype"]))
    filled = np.zeros(shape, bool) if shape else None
    for key, blk in marker["blocks"].items():
        idx = tuple(slice(a, b) for a, b in key)
        out[idx] = blk
        if filled is not None:
            filled[idx] = True
    if filled is not None and not filled.all():
        raise CheckpointCorruptError(
            f"sharded checkpoint leaf (shape {shape}) has coverage holes: "
            f"{int((~filled).sum())} elements missing from the merged "
            f"shard blocks (an incomplete shard set verified?)")
    return out


def _split_shards(tree, blocks_out: Dict[str, Any], path: str = ""):
    """Walk a blob replacing non-addressable SHARDED leaves with
    metadata-only markers (header side) while collecting this process's
    blocks into ``blocks_out`` keyed by the leaf's tree path."""
    if isinstance(tree, dict):
        return {k: _split_shards(v, blocks_out, f"{path}/{k}")
                for k, v in tree.items()}
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):  # NamedTuple
        return type(tree)(*(_split_shards(v, blocks_out, f"{path}/{i}")
                            for i, v in enumerate(tree)))
    if isinstance(tree, (list, tuple)):
        return type(tree)(_split_shards(v, blocks_out, f"{path}/{i}")
                          for i, v in enumerate(tree))
    if is_shard_marker(tree):
        # an engine hook (wire_resid_host) already produced local blocks
        blocks_out[path] = tree["blocks"]
        return {BLOCKS_KEY: True, "shape": tuple(tree["shape"]),
                "dtype": str(tree["dtype"]), "key": path}
    if isinstance(tree, jax.Array) and not tree.is_fully_addressable \
            and not tree.is_fully_replicated:
        marker = host_shard_blocks(tree)
        blocks_out[path] = marker["blocks"]
        return {BLOCKS_KEY: True, "shape": marker["shape"],
                "dtype": marker["dtype"], "key": path}
    return tree  # _to_host finishes the remaining leaves at pickle time


def _join_shards(tree, blocks_by_key: Dict[str, Dict]):
    """Replace header-side metadata markers with full shard-blocks markers
    carrying the merged block set (load side of :func:`_split_shards`)."""
    if is_shard_marker(tree):
        key = tree.get("key")
        if key not in blocks_by_key:
            raise CheckpointCorruptError(
                f"sharded checkpoint header references leaf {key!r} but no "
                f"shard file carried blocks for it")
        return {BLOCKS_KEY: True, "shape": tuple(tree["shape"]),
                "dtype": str(tree["dtype"]), "blocks": blocks_by_key[key]}
    if isinstance(tree, dict):
        return {k: _join_shards(v, blocks_by_key) for k, v in tree.items()}
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):
        return type(tree)(*(_join_shards(v, blocks_by_key) for v in tree))
    if isinstance(tree, (list, tuple)):
        return type(tree)(_join_shards(v, blocks_by_key) for v in tree)
    return tree


def _shard_barrier(name: str) -> None:
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        multihost_utils.sync_global_devices(name)


def save_checkpoint_sharded(path: str, blob: Dict[str, Any], keep: int = 1,
                            stamp: Optional[str] = None) -> None:
    """Collective durable checkpoint write for multi-process meshes: EVERY
    process calls this with the same ``blob`` structure (ISSUE 17).

    Each process persists only the device-shard blocks it owns (its level
    rows under the grouped slices placement) into a self-verifying shard
    file; process 0 additionally writes the header blob -- the ordinary
    checkpoint structure with sharded leaves replaced by metadata markers
    plus a :data:`SHARD_SET_KEY` record naming every shard file and a
    generation ``stamp`` each shard must echo, so a torn multi-file write
    (some files rotated, some not) fails verification instead of silently
    mixing generations.  Barriers bracket the header write: shards are on
    disk before the header names them, and no process returns (and maybe
    immediately reads) before the header landed.

    On a single-process runtime with a fully-addressable blob this
    degenerates to :func:`save_checkpoint` -- no shard files, no barrier.
    """
    n = jax.process_count()
    i = jax.process_index()
    blocks: Dict[str, Any] = {}
    header = _split_shards(blob, blocks)
    if not blocks:
        # no process-local leaves: the ordinary process-0 plain write (the
        # single-host format, still readable by load_checkpoint_sharded)
        if i == 0:
            save_checkpoint(path, blob, keep)
        _shard_barrier(f"ckpt-plain:{path}")
        return
    if stamp is None:
        stamp = f"e{blob.get('epoch', 0)}"
    os.makedirs(os.path.dirname(path), exist_ok=True)
    sp = shard_path(path, i, n)
    _rotate(sp, keep)
    _write_durable(sp, _blob_bytes({"stamp": stamp, "process": i,
                                    "blocks": blocks}))
    _shard_barrier(f"ckpt-shards:{path}:{stamp}")
    if i == 0:
        header[SHARD_SET_KEY] = {
            "count": n, "stamp": stamp,
            "files": [os.path.basename(shard_path(path, j, n))
                      for j in range(n)]}
        _rotate(path, keep)
        _write_durable(path, _blob_bytes(header))
    _shard_barrier(f"ckpt-header:{path}:{stamp}")


def load_checkpoint_sharded(path: str, gen: int = 0) -> Dict[str, Any]:
    """Load + verify generation ``gen`` of a (possibly sharded) checkpoint
    through the shared filesystem: the header names its shard set, every
    shard must verify AND echo the header's generation stamp, and the
    merged blocks must cover every marker leaf.  A plain (unsharded) blob
    loads unchanged, so callers need not know which format they wrote."""
    header = load_checkpoint(generation_path(path, gen))
    ss = header.pop(SHARD_SET_KEY, None) if isinstance(header, dict) else None
    if ss is None:
        return header
    d = os.path.dirname(os.path.abspath(path))
    blocks_by_key: Dict[str, Dict] = {}
    for base in ss["files"]:
        spath = generation_path(os.path.join(d, base), gen)
        try:
            shard = load_checkpoint(spath)
        except FileNotFoundError as e:
            raise CheckpointCorruptError(
                f"sharded checkpoint {path} (gen {gen}): shard file {base} "
                f"named by the header is missing") from e
        if shard.get("stamp") != ss["stamp"]:
            raise CheckpointCorruptError(
                f"sharded checkpoint {path} (gen {gen}): shard {base} stamp "
                f"{shard.get('stamp')!r} != header stamp {ss['stamp']!r} "
                f"(torn multi-file rotation)")
        for key, blk in shard["blocks"].items():
            blocks_by_key.setdefault(key, {}).update(blk)
    return _join_shards(header, blocks_by_key)


def load_newest_verifying_sharded(path: str) -> Optional[Dict[str, Any]]:
    """Generation-fallback walk over sharded checkpoints: the sharded twin
    of :func:`load_newest_verifying` (same contract), where a generation
    verifies only if the header AND its entire shard set verify."""
    gens = generation_paths(path)
    if not gens:
        return None
    for p in gens:
        gen = 0 if p == path else int(p.rsplit(".g", 1)[1])
        try:
            return load_checkpoint_sharded(path, gen)
        except CheckpointCorruptError as e:
            warnings.warn(
                "checkpoint generation failed verification, falling back: "
                + json.dumps({"event": "checkpoint-corrupt", "path": p,
                              "error": str(e)}))
    raise CheckpointCorruptError(
        f"all {len(gens)} checkpoint generation(s) of {path} failed "
        f"verification; refusing to silently restart from scratch (delete "
        f"the blobs to run fresh)")


def resume(output_dir: str, tag: str, mode: int, load_tag: str = "checkpoint"
           ) -> Optional[Dict[str, Any]]:
    """Return the checkpoint blob according to ``resume_mode`` or None.

    mode 0 -> always fresh; mode 1 -> full blob; mode 2 -> weights + splits
    only (epoch restarts at 1, fresh logger/scheduler).

    A corrupt newest generation falls back, generation by generation, to
    the newest verifying blob (loud structured warning per skipped
    generation); when every present generation fails,
    :class:`CheckpointCorruptError` propagates."""
    if mode == 0:
        return None
    path = checkpoint_path(output_dir, tag, load_tag)
    blob = load_newest_verifying_sharded(path)
    if blob is None:
        print(f"Not exists model tag: {tag}, start from scratch")
        return None
    print(f"Resume from {blob.get('epoch')}")
    if mode == 2:
        return {k: blob[k] for k in ("params", "bn_state", "data_split", "label_split")
                if k in blob}
    return blob
