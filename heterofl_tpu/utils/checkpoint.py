"""Checkpoint / resume.

Parity: ``src/utils.py:300-344`` + the per-round save in
``train_classifier_fed.py:84-93``: each round stores
``{cfg, epoch, data_split, label_split, params, bn_state, scheduler_state,
logger history}`` to ``output/model/{tag}_checkpoint.pkl`` with a best-pivot
copy to ``_best.pkl``; resume restores everything *including the data
partition* so a resumed run keeps identical client shards.

``resume_mode``: 0 fresh / 1 full resume / 2 weights+splits only
(ref train_classifier_fed.py:57-69).
"""

from __future__ import annotations

import os
import pickle
import shutil
from typing import Any, Dict, Optional, Tuple

import jax.numpy as jnp
import numpy as np


def _to_host(tree):
    if isinstance(tree, dict):
        return {k: _to_host(v) for k, v in tree.items()}
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):  # NamedTuple
        return type(tree)(*(_to_host(v) for v in tree))
    if isinstance(tree, (list, tuple)):
        return type(tree)(_to_host(v) for v in tree)
    if isinstance(tree, (jnp.ndarray, np.ndarray)):
        return np.asarray(tree)
    return tree


def save_checkpoint(path: str, blob: Dict[str, Any]) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = path + ".tmp"
    with open(tmp, "wb") as f:
        pickle.dump(_to_host(blob), f, protocol=4)
    os.replace(tmp, path)  # atomic: a crash never corrupts the previous ckpt


def load_checkpoint(path: str) -> Dict[str, Any]:
    with open(path, "rb") as f:
        return pickle.load(f)


def checkpoint_path(output_dir: str, tag: str, which: str = "checkpoint") -> str:
    return os.path.join(output_dir, "model", f"{tag}_{which}.pkl")


def copy_best(output_dir: str, tag: str) -> None:
    shutil.copy(checkpoint_path(output_dir, tag, "checkpoint"),
                checkpoint_path(output_dir, tag, "best"))


def resume(output_dir: str, tag: str, mode: int, load_tag: str = "checkpoint"
           ) -> Optional[Dict[str, Any]]:
    """Return the checkpoint blob according to ``resume_mode`` or None.

    mode 0 -> always fresh; mode 1 -> full blob; mode 2 -> weights + splits
    only (epoch restarts at 1, fresh logger/scheduler).
    """
    if mode == 0:
        return None
    path = checkpoint_path(output_dir, tag, load_tag)
    if not os.path.exists(path):
        print(f"Not exists model tag: {tag}, start from scratch")
        return None
    blob = load_checkpoint(path)
    print(f"Resume from {blob.get('epoch')}")
    if mode == 2:
        return {k: blob[k] for k in ("params", "bn_state", "data_split", "label_split")
                if k in blob}
    return blob
