"""Experiment logger: size-weighted running means, per-round history, and
pluggable writers (JSONL always; TensorBoard if available).

Parity: ``src/logger.py`` -- ``append(result, tag, n)`` updates running means
keyed ``{tag}/{metric}``; ``safe(True/False)`` opens/closes a writer and
snapshots means into ``history``; ``write`` emits one info line.
"""

from __future__ import annotations

import json
import os
import time
import warnings
from collections import defaultdict
from numbers import Number
from typing import Dict, Iterable, List, Optional


class Logger:
    def __init__(self, log_path: str, use_tensorboard: bool = False):
        self.log_path = log_path
        self.use_tensorboard = use_tensorboard
        self.writer = None
        self._jsonl = None
        self._tb_warned = False
        self.tracker: Dict[str, object] = {}
        self.counter: Dict[str, float] = defaultdict(float)
        self.mean: Dict[str, float] = defaultdict(float)
        self.history: Dict[str, List[float]] = defaultdict(list)
        self.iterator: Dict[str, int] = defaultdict(int)

    # -- lifecycle ----------------------------------------------------
    def safe(self, write: bool) -> None:
        if write:
            os.makedirs(self.log_path, exist_ok=True)
            self._jsonl = open(os.path.join(self.log_path, "log.jsonl"), "a")
            if self.use_tensorboard and self.writer is None:
                try:
                    from torch.utils.tensorboard import SummaryWriter

                    self.writer = SummaryWriter(self.log_path)
                except Exception as e:
                    # ISSUE 10 satellite: the bare except used to swallow
                    # this silently -- an operator asking for tensorboard
                    # got JSONL-only logging with no hint why.  One warning
                    # per Logger, then the degraded mode proceeds as before.
                    if not self._tb_warned:
                        self._tb_warned = True
                        warnings.warn(
                            f"use_tensorboard=True but the tensorboard "
                            f"writer is unavailable ({e!r}); continuing "
                            f"with JSONL-only logging")
                    self.writer = None
        else:
            if self.writer is not None:
                self.writer.close()
                self.writer = None
            if self._jsonl is not None:
                self._jsonl.close()
                self._jsonl = None
            for name in self.mean:
                self.history[name].append(self.mean[name])

    def reset(self) -> None:
        self.tracker = {}
        self.counter = defaultdict(float)
        self.mean = defaultdict(float)

    def reset_tag(self, tag: str) -> None:
        """Clear ONE tag's running means/counters (history untouched).

        The eval-fused superstep logs several evals between two ``reset()``
        boundaries; resetting the ``test`` tag before each fused eval keeps
        every eval's means standalone -- the K=1 host loop's semantics,
        where ``reset()`` runs every round (the best-checkpoint pivot and
        ReduceLROnPlateau both read these means)."""
        prefix = f"{tag}/"
        for d in (self.counter, self.mean):
            for k in [k for k in d if k.startswith(prefix)]:
                del d[k]

    # -- persistence (ref utils.py:302-312 pickles the whole Logger; here the
    # state rides inside the checkpoint blob so resume-mode 1 restores running
    # means/counters and TB step counters, not just history) ---------------
    def state_dict(self) -> Dict[str, object]:
        return {"counter": dict(self.counter), "mean": dict(self.mean),
                "history": {k: list(v) for k, v in self.history.items()},
                "iterator": dict(self.iterator)}

    def load_state_dict(self, state: Dict[str, object]) -> None:
        self.counter = defaultdict(float, state.get("counter", {}))
        self.mean = defaultdict(float, state.get("mean", {}))
        self.history = defaultdict(list, {k: list(v)
                                          for k, v in state.get("history", {}).items()})
        self.iterator = defaultdict(int, state.get("iterator", {}))

    # -- accumulation -------------------------------------------------
    def append(self, result: Dict[str, object], tag: str, n: float = 1, mean: bool = True) -> None:
        for k, v in result.items():
            name = f"{tag}/{k}"
            self.tracker[name] = v
            if mean and isinstance(v, Number):
                self.counter[name] += n
                c = self.counter[name]
                self.mean[name] = ((c - n) * self.mean[name] + n * float(v)) / c

    # -- output -------------------------------------------------------
    def write(self, tag: str, metric_names: Iterable[str]) -> str:
        parts = []
        record = {"tag": tag, "t": time.time()}
        for k in metric_names:
            name = f"{tag}/{k}"
            if name in self.mean:
                parts.append(f"{k}: {self.mean[name]:.4f}")
                record[k] = self.mean[name]
                if self.writer is not None:
                    self.iterator[name] += 1
                    self.writer.add_scalar(name, self.mean[name], self.iterator[name])
        info = self.tracker.get(f"{tag}/info")
        line_items = list(info) if isinstance(info, list) else ([str(info)] if info else [])
        line_items[2:2] = parts
        line = "  ".join(line_items) if line_items else "  ".join(parts)
        print(line)
        if self.writer is not None:
            # info line to the TB text channel (ref logger.py:81-83)
            name = f"{tag}/info"
            self.iterator[name] += 1
            self.writer.add_text(name, line, self.iterator[name])
        if self._jsonl is not None:
            self._jsonl.write(json.dumps(record) + "\n")
            self._jsonl.flush()
        return line

    def emit(self, event: Dict[str, object], tag: str = "obs") -> None:
        """Structured obs event on the existing JSONL writer (ISSUE 10):
        one ``{"tag": tag, "t": ..., **event}`` line next to the metric
        records, so probe snapshots, watchdog trips and ledger summaries
        (``tag="ledger"``, ISSUE 12) land in the same ``log.jsonl`` a run
        already produces.  No-op while the writer is closed (outside a
        ``safe(True)`` window) -- obs events are advisory, never worth
        crashing a checkpoint boundary over."""
        if self._jsonl is not None:
            self._jsonl.write(json.dumps({"tag": tag, "t": time.time(),
                                          **event}) + "\n")
            self._jsonl.flush()

    def flush(self) -> None:
        if self.writer is not None:
            self.writer.flush()
        if self._jsonl is not None:
            self._jsonl.flush()
