from .grouped import GroupedRoundEngine  # noqa: F401
from .mesh import make_mesh  # noqa: F401
from .round_engine import RoundEngine, shard_client_data  # noqa: F401
from .staging import (ClientStore, CohortStager, MetricsPipeline,  # noqa: F401
                      PendingMetrics, PhaseTimer, PlacementCache, SlotPacker,
                      StagedCohort)
