"""Jitted evaluation programs: sBN recalibration and test metrics.

sBN ("static batch norm"): federated training runs BN without running stats;
before each evaluation the aggregated global model does one no-grad pass over
the train set with fresh cumulative running statistics (momentum=None CMA),
ref train_classifier_fed.py:127-138.  Here that pass is a ``lax.scan`` over
batches with the batch axis sharded across all mesh devices (``psum`` of
partial sums) -- the whole recalibration is one XLA program.

Evaluation mirrors ref train_classifier_fed.py:141-168: "Local" = per-user
test shards with that user's label mask; "Global" = full test set, no mask.
Users are vmapped and sharded over the ``clients`` axis like the train round.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..data.datasets import DATASET_STATS
from ..models.base import ModelDef
from .round_engine import _ceil_div, _shard_map
from .staging import PlacementCache


class Evaluator:
    def __init__(self, model: ModelDef, cfg: Dict[str, Any], mesh, seed: int = 0):
        self.model = model
        self.cfg = cfg
        self.mesh = mesh
        # Eval RNG descends from the EXPERIMENT seed (ref draws fresh noise
        # per pass from the global torch RNG, src/models/transformer.py:148-151,
        # which the experiment seed controls); stream tags 0/1 keep the
        # per-user and global eval streams distinct.
        base = jax.random.key(seed)
        self._users_key = jax.random.fold_in(base, 0)
        self._global_key = jax.random.fold_in(base, 1)
        self.is_lm = model.meta.get("kind") == "transformer"
        self.norm_stats = cfg.get("norm_stats") or DATASET_STATS.get(cfg["data_name"])
        self.bptt = cfg.get("bptt", 64)
        self._sbn = None
        self._users = None
        self._global = None
        # eval operands are padded + committed to the mesh once per staged
        # dataset (PlacementCache.memo); repeated eval passes re-use the
        # device-resident buffers instead of re-uploading every round
        self._staging = PlacementCache(mesh)

    def _norm(self, x):
        from ..ops.augment import normalize_image

        if self.norm_stats is None:
            return x.astype(jnp.float32)
        return normalize_image(x, *self.norm_stats)

    # -------------------- sBN recalibration --------------------

    def _build_sbn(self):
        model = self.model

        def body(params, xb, wb):
            # xb: [s_local, B, H, W, C] uint8; wb: [s_local, B]
            def one(carry, inp):
                x, w = inp
                has = (jnp.sum(w) > 0).astype(jnp.float32)
                _, col = model.apply(params, {"img": self._norm(x),
                                              "label": jnp.zeros(x.shape[0], jnp.int32)},
                                     train=True, bn_mode="collect", sample_weight=w)
                sums = {site: (m * has, v * has) for site, (m, v) in col.items()}
                carry_sums, carry_n = carry
                carry_sums = {s: (carry_sums[s][0] + sums[s][0], carry_sums[s][1] + sums[s][1])
                              for s in carry_sums}
                return (carry_sums, carry_n + has), None

            zero = {site: (jnp.zeros(model.meta["bn_sizes"][site]),
                           jnp.zeros(model.meta["bn_sizes"][site]))
                    for site in model.bn_sites}
            (sums, n), _ = jax.lax.scan(one, (zero, jnp.zeros(())), (xb, wb))
            sums = jax.lax.psum(sums, ("clients", "data"))
            n = jax.lax.psum(n, ("clients", "data"))
            return {s: (sums[s][0] / jnp.maximum(n, 1.0), sums[s][1] / jnp.maximum(n, 1.0))
                    for s in sums}

        fn = _shard_map(body, self.mesh,
                        in_specs=(P(), P(("clients", "data")), P(("clients", "data"))),
                        out_specs=P())
        # staticcheck: allow(jit-needs-donation): sBN reads the live globals
        # and the committed train batches -- donation would delete both
        return jax.jit(fn)

    def sbn_stats(self, params, x_batches: np.ndarray, w_batches: np.ndarray):
        """Cumulative-average BN stats over ``[S, B, ...]`` uint8 batches.

        S must be padded (zero-weight batches) to a multiple of the total
        device count; returns ``{site: (running_mean, running_var)}``.
        """
        if not self.model.bn_sites:
            return {}
        if self._sbn is None:
            self._sbn = self._build_sbn()

        def build():
            n_dev = self.mesh.devices.size
            s = x_batches.shape[0]
            pad = (-s) % n_dev
            xb, wb = x_batches, w_batches
            if pad:
                xb = np.concatenate([xb, np.zeros((pad,) + xb.shape[1:], xb.dtype)])
                wb = np.concatenate([wb, np.zeros((pad,) + wb.shape[1:], np.float32)])
            sh = NamedSharding(self.mesh, P(("clients", "data")))
            return jax.device_put(xb, sh), jax.device_put(wb, sh)

        xb, wb = self._staging.memo("sbn", (x_batches, w_batches), build)
        return self._sbn(params, xb, wb)

    # -------------------- evaluation --------------------

    def _eval_batch_metrics(self, params, bn_state, batch, lm, w, key):
        out, _ = self.model.apply(params, batch, train=False,
                                  bn_mode="running" if bn_state else "batch",
                                  bn_state=bn_state or None, label_mask=lm,
                                  sample_weight=w, rng=key)
        n = jnp.sum(w)
        loss = out["loss"]
        if self.is_lm:
            # reference Perplexity is exp(batch CE), size-weighted by rows
            rows = np.float32(batch["label"].shape[0])  # static trace-time constant
            return {"loss_sum": loss * rows, "score_sum": jnp.exp(loss) * rows, "n": rows}
        y = batch["label"]
        correct = jnp.sum((jnp.argmax(out["score"], -1) == y) * w)
        return {"loss_sum": loss * n, "score_sum": correct, "n": n}

    def _build_users(self):
        model = self.model

        def body(params, bn_state, key, valid, *data):
            def one_user(x, y, m, lm, k, v):
                # scan over the user's batches
                def stepf(acc, inp):
                    xb, yb, wb, kk = inp
                    ms = self._eval_batch_metrics(params, bn_state,
                                                  {"img": self._norm(xb), "label": yb},
                                                  lm, wb, kk)
                    return {kk2: acc[kk2] + ms[kk2] for kk2 in acc}, None

                S = x.shape[0]
                keys = jax.random.split(k, S)
                acc0 = {"loss_sum": jnp.zeros(()), "score_sum": jnp.zeros(()), "n": jnp.zeros(())}
                acc, _ = jax.lax.scan(stepf, acc0, (x, y, m, keys))
                return {kk: v * acc[kk] for kk in acc}

            x, y, m, lm = data
            a = x.shape[0]
            dev = jax.lax.axis_index("clients")
            keys = jax.vmap(lambda i: jax.random.fold_in(key, dev * a + i))(jnp.arange(a))
            return jax.vmap(one_user)(x, y, m, lm, keys, valid)

        fn = _shard_map(body, self.mesh,
                        in_specs=(P(), P(), P(), P("clients"), P("clients"), P("clients"),
                                  P("clients"), P("clients")),
                        out_specs=P("clients"))
        # staticcheck: allow(jit-needs-donation): eval reads the live globals
        # and the once-committed eval operands -- nothing here is consumable
        return jax.jit(fn)

    def eval_users(self, params, bn_state, x, y, m, lm, epoch: int = 0):
        """Per-user "Local" metrics: ``x [U, S, B, ...]`` batched test shards,
        label masks ``lm [U, classes]``.  Returns per-user metric sums.

        ``epoch`` seeds the eval RNG (LM token corruption) so noise is fresh
        each round, matching the reference's per-pass Bernoulli draws
        (ref ``src/models/transformer.py:148-151``) while staying reproducible.
        """
        if self._users is None:
            self._users = self._build_users()
        u = x.shape[0]

        def build():
            n_dev = self.mesh.shape["clients"]
            pad = (-u) % n_dev
            valid = np.concatenate([np.ones(u, np.float32), np.zeros(pad, np.float32)])
            arrs = [x, y, m, lm]
            if pad:
                arrs = [np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])
                        for a in arrs]
            sh = NamedSharding(self.mesh, P("clients"))
            return tuple(jax.device_put(a, sh) for a in [valid] + arrs)

        vd, xd, yd, md, lmd = self._staging.memo("local_eval", (x, y, m, lm), build)
        key = jax.random.fold_in(self._users_key, epoch)
        out = self._users(params, bn_state, key, vd, xd, yd, md, lmd)
        # staticcheck: allow(no-asarray): the eval-boundary D2H fetch point
        return {k: np.asarray(v)[:u] for k, v in out.items()}

    def _build_global(self):
        def body(params, bn_state, key, *data):
            if self.is_lm:
                rows, w = data  # [s_local, R, bptt], [s_local, R, bptt]
                def stepf(acc, inp):
                    lab, wb, kk = inp
                    ms = self._eval_batch_metrics(params, bn_state, {"label": lab},
                                                  None, wb, kk)
                    has = (jnp.sum(wb) > 0).astype(jnp.float32)
                    return {k2: acc[k2] + ms[k2] * has for k2 in acc}, None
                S = rows.shape[0]
                keys = jax.random.split(key, S)
                acc0 = {"loss_sum": jnp.zeros(()), "score_sum": jnp.zeros(()), "n": jnp.zeros(())}
                acc, _ = jax.lax.scan(stepf, acc0, (rows, w, keys))
            else:
                x, y, w = data
                def stepf(acc, inp):
                    xb, yb, wb, kk = inp
                    ms = self._eval_batch_metrics(params, bn_state,
                                                  {"img": self._norm(xb), "label": yb},
                                                  None, wb, kk)
                    return {k2: acc[k2] + ms[k2] for k2 in acc}, None
                S = x.shape[0]
                keys = jax.random.split(key, S)
                acc0 = {"loss_sum": jnp.zeros(()), "score_sum": jnp.zeros(()), "n": jnp.zeros(())}
                acc, _ = jax.lax.scan(stepf, acc0, (x, y, w, keys))
            return jax.lax.psum(acc, ("clients", "data"))

        n_data = 3 if not self.is_lm else 2
        fn = _shard_map(body, self.mesh,
                        in_specs=(P(), P(), P()) + (P(("clients", "data")),) * n_data,
                        out_specs=P())
        # staticcheck: allow(jit-needs-donation): eval reads the live globals
        # and the once-committed eval operands -- nothing here is consumable
        return jax.jit(fn)

    def eval_global(self, params, bn_state, *batched, epoch: int = 0):
        """"Global" metrics over the full test set: vision
        ``(x [S,B,...], y [S,B], w [S,B])``; LM ``(rows [S,R,bptt], w)``.

        ``epoch`` seeds the eval RNG so LM corruption noise differs round to
        round (ref ``src/models/transformer.py:148-151``)."""
        if self._global is None:
            self._global = self._build_global()

        def build():
            n_dev = self.mesh.devices.size
            pad = (-batched[0].shape[0]) % n_dev
            sh = NamedSharding(self.mesh, P(("clients", "data")))
            out = []
            for arr in batched:
                if pad:
                    arr = np.concatenate([arr, np.zeros((pad,) + arr.shape[1:], arr.dtype)])
                out.append(jax.device_put(arr, sh))
            return tuple(out)

        padded = self._staging.memo("global_eval", batched, build)
        key = jax.random.fold_in(self._global_key, epoch)
        out = self._global(params, bn_state, key, *padded)
        # staticcheck: allow(no-float-coercion): the eval-boundary D2H fetch
        return {k: float(v) for k, v in out.items()}
