"""Jitted evaluation programs: sBN recalibration and test metrics.

sBN ("static batch norm"): federated training runs BN without running stats;
before each evaluation the aggregated global model does one no-grad pass over
the train set with fresh cumulative running statistics (momentum=None CMA),
ref train_classifier_fed.py:127-138.  Here that pass is a ``lax.scan`` over
batches with the batch axis sharded across all mesh devices (``psum`` of
partial sums) -- the whole recalibration is one XLA program.

Evaluation mirrors ref train_classifier_fed.py:141-168: "Local" = per-user
test shards with that user's label mask; "Global" = full test set, no mask.
Users are vmapped and sharded over the ``clients`` axis like the train round.

The per-device batch cores (``_sbn_body``/``_users_body``/``_global_body``)
are pure functions of committed operands, shared by TWO callers: the
standalone host-dispatched programs below (the ``superstep_rounds=1``
reference path) and :class:`FusedEval`, which threads the same bodies into
the round engines' K-round superstep programs so eval windows no longer
break the scan (ISSUE 4 tentpole).  One body, two harnesses -- the
eval-fused superstep is bit-identical to the host loop by construction.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..data.datasets import DATASET_STATS
from ..models.base import ModelDef
from .round_engine import _ceil_div, _shard_map
from .staging import PlacementCache


class Evaluator:
    def __init__(self, model: ModelDef, cfg: Dict[str, Any], mesh, seed: int = 0):
        self.model = model
        self.cfg = cfg
        self.mesh = mesh
        # Eval RNG descends from the EXPERIMENT seed (ref draws fresh noise
        # per pass from the global torch RNG, src/models/transformer.py:148-151,
        # which the experiment seed controls); stream tags 0/1 keep the
        # per-user and global eval streams distinct.
        base = jax.random.key(seed)
        self._users_key = jax.random.fold_in(base, 0)
        self._global_key = jax.random.fold_in(base, 1)
        self.is_lm = model.meta.get("kind") == "transformer"
        self.norm_stats = cfg.get("norm_stats") or DATASET_STATS.get(cfg["data_name"])
        self.bptt = cfg.get("bptt", 64)
        self._sbn = None
        self._users = None
        self._global = None
        # eval operands are padded + committed to the mesh once per staged
        # dataset (PlacementCache.memo); repeated eval passes -- host-loop OR
        # eval-fused superstep dispatches -- re-use the same device-resident
        # buffers instead of re-uploading every round
        self._staging = PlacementCache(mesh)

    def _norm(self, x):
        from ..ops.augment import normalize_image

        if self.norm_stats is None:
            return x.astype(jnp.float32)
        return normalize_image(x, *self.norm_stats)

    # -------------------- sBN recalibration --------------------

    def _sbn_body(self, params, xb, wb):
        """Per-device sBN moment accumulation (pure; runs under any
        ``shard_map`` whose mesh carries the ``clients``/``data`` axes):
        scan this device's ``[s_local, B, ...]`` train batches, psum the
        moment sums across the whole mesh, return the CMA stats."""
        model = self.model

        def one(carry, inp):
            x, w = inp
            has = (jnp.sum(w) > 0).astype(jnp.float32)
            _, col = model.apply(params, {"img": self._norm(x),
                                          "label": jnp.zeros(x.shape[0], jnp.int32)},
                                 train=True, bn_mode="collect", sample_weight=w)
            sums = {site: (m * has, v * has) for site, (m, v) in col.items()}
            carry_sums, carry_n = carry
            carry_sums = {s: (carry_sums[s][0] + sums[s][0], carry_sums[s][1] + sums[s][1])
                          for s in carry_sums}
            return (carry_sums, carry_n + has), None

        zero = {site: (jnp.zeros(model.meta["bn_sizes"][site]),
                       jnp.zeros(model.meta["bn_sizes"][site]))
                for site in model.bn_sites}
        (sums, n), _ = jax.lax.scan(one, (zero, jnp.zeros(())), (xb, wb))
        # ONE psum bind for moments+count (bit-compatible with two binds;
        # staticcheck audits the eval phase's collective budget separately
        # from the per-training-round psum)
        sums, n = jax.lax.psum((sums, n), ("clients", "data"))
        return {s: (sums[s][0] / jnp.maximum(n, 1.0), sums[s][1] / jnp.maximum(n, 1.0))
                for s in sums}

    def _build_sbn(self):
        fn = _shard_map(self._sbn_body, self.mesh,
                        in_specs=(P(), P(("clients", "data")), P(("clients", "data"))),
                        out_specs=P())
        # staticcheck: allow(jit-needs-donation): sBN reads the live globals
        # and the committed train batches -- donation would delete both
        return jax.jit(fn)

    def _staged_sbn(self, x_batches: np.ndarray, w_batches: np.ndarray):
        """Pad-and-commit the ``[S, B, ...]`` sBN batches once (shared by the
        host-loop program and the eval-fused superstep operands)."""

        def build():
            n_dev = self.mesh.devices.size
            s = x_batches.shape[0]
            pad = (-s) % n_dev
            xb, wb = x_batches, w_batches
            if pad:
                xb = np.concatenate([xb, np.zeros((pad,) + xb.shape[1:], xb.dtype)])
                wb = np.concatenate([wb, np.zeros((pad,) + wb.shape[1:], np.float32)])
            sh = NamedSharding(self.mesh, P(("clients", "data")))
            return jax.device_put(xb, sh), jax.device_put(wb, sh)

        return self._staging.memo("sbn", (x_batches, w_batches), build)

    def sbn_stats(self, params, x_batches: np.ndarray, w_batches: np.ndarray):
        """Cumulative-average BN stats over ``[S, B, ...]`` uint8 batches.

        S must be padded (zero-weight batches) to a multiple of the total
        device count; returns ``{site: (running_mean, running_var)}``.
        """
        if not self.model.bn_sites:
            return {}
        if self._sbn is None:
            self._sbn = self._build_sbn()
        xb, wb = self._staged_sbn(x_batches, w_batches)
        return self._sbn(params, xb, wb)

    # -------------------- evaluation --------------------

    def _eval_batch_metrics(self, params, bn_state, batch, lm, w, key):
        out, _ = self.model.apply(params, batch, train=False,
                                  bn_mode="running" if bn_state else "batch",
                                  bn_state=bn_state or None, label_mask=lm,
                                  sample_weight=w, rng=key)
        n = jnp.sum(w)
        loss = out["loss"]
        if self.is_lm:
            # reference Perplexity is exp(batch CE), size-weighted by rows
            rows = np.float32(batch["label"].shape[0])  # static trace-time constant
            return {"loss_sum": loss * rows, "score_sum": jnp.exp(loss) * rows, "n": rows}
        y = batch["label"]
        correct = jnp.sum((jnp.argmax(out["score"], -1) == y) * w)
        return {"loss_sum": loss * n, "score_sum": correct, "n": n}

    def _users_body(self, params, bn_state, key, valid, x, y, m, lm):
        """Per-device "Local" eval core (pure, shard_map-reusable): vmap this
        device's user shards through their batched test sets; per-user keys
        descend from ``key`` by GLOBAL user position so results are
        mesh-placement-invariant.  No collective -- the per-user sums stay
        sharded over ``clients``."""

        def one_user(xu, yu, mu, lmu, k, v):
            def stepf(acc, inp):
                xb, yb, wb, kk = inp
                ms = self._eval_batch_metrics(params, bn_state,
                                              {"img": self._norm(xb), "label": yb},
                                              lmu, wb, kk)
                return {kk2: acc[kk2] + ms[kk2] for kk2 in acc}, None

            S = xu.shape[0]
            keys = jax.random.split(k, S)
            acc0 = {"loss_sum": jnp.zeros(()), "score_sum": jnp.zeros(()), "n": jnp.zeros(())}
            acc, _ = jax.lax.scan(stepf, acc0, (xu, yu, mu, keys))
            return {kk: v * acc[kk] for kk in acc}

        a = x.shape[0]
        dev = jax.lax.axis_index("clients")
        keys = jax.vmap(lambda i: jax.random.fold_in(key, dev * a + i))(jnp.arange(a))
        return jax.vmap(one_user)(x, y, m, lm, keys, valid)

    def _build_users(self):
        def body(params, bn_state, key, valid, *data):
            return self._users_body(params, bn_state, key, valid, *data)

        fn = _shard_map(body, self.mesh,
                        in_specs=(P(), P(), P(), P("clients"), P("clients"), P("clients"),
                                  P("clients"), P("clients")),
                        out_specs=P("clients"))
        # staticcheck: allow(jit-needs-donation): eval reads the live globals
        # and the once-committed eval operands -- nothing here is consumable
        return jax.jit(fn)

    def _staged_users(self, x, y, m, lm):
        """Pad-and-commit the per-user local-eval operands once: returns the
        committed ``(valid, x, y, m, lm)`` tuple (users padded to the
        clients-axis size, ``valid`` masking the pads)."""
        u = x.shape[0]

        def build():
            n_dev = self.mesh.shape["clients"]
            pad = (-u) % n_dev
            valid = np.concatenate([np.ones(u, np.float32), np.zeros(pad, np.float32)])
            arrs = [x, y, m, lm]
            if pad:
                arrs = [np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])
                        for a in arrs]
            sh = NamedSharding(self.mesh, P("clients"))
            return tuple(jax.device_put(a, sh) for a in [valid] + arrs)

        return self._staging.memo("local_eval", (x, y, m, lm), build)

    def eval_users(self, params, bn_state, x, y, m, lm, epoch: int = 0):
        """Per-user "Local" metrics: ``x [U, S, B, ...]`` batched test shards,
        label masks ``lm [U, classes]``.  Returns per-user metric sums.

        ``epoch`` seeds the eval RNG (LM token corruption) so noise is fresh
        each round, matching the reference's per-pass Bernoulli draws
        (ref ``src/models/transformer.py:148-151``) while staying reproducible.
        """
        if self._users is None:
            self._users = self._build_users()
        u = x.shape[0]
        vd, xd, yd, md, lmd = self._staged_users(x, y, m, lm)
        key = jax.random.fold_in(self._users_key, epoch)
        out = self._users(params, bn_state, key, vd, xd, yd, md, lmd)
        # staticcheck: allow(no-asarray): the eval-boundary D2H fetch point
        return {k: np.asarray(v)[:u] for k, v in out.items()}

    def _global_body(self, params, bn_state, key, *data):
        """Per-device "Global" eval core (pure, shard_map-reusable): scan
        this device's slice of the batched test set and psum the metric sums
        across the whole mesh."""
        if self.is_lm:
            rows, w = data  # [s_local, R, bptt], [s_local, R, bptt]

            def stepf(acc, inp):
                lab, wb, kk = inp
                ms = self._eval_batch_metrics(params, bn_state, {"label": lab},
                                              None, wb, kk)
                has = (jnp.sum(wb) > 0).astype(jnp.float32)
                return {k2: acc[k2] + ms[k2] * has for k2 in acc}, None

            S = rows.shape[0]
            keys = jax.random.split(key, S)
            acc0 = {"loss_sum": jnp.zeros(()), "score_sum": jnp.zeros(()), "n": jnp.zeros(())}
            acc, _ = jax.lax.scan(stepf, acc0, (rows, w, keys))
        else:
            x, y, w = data

            def stepf(acc, inp):
                xb, yb, wb, kk = inp
                ms = self._eval_batch_metrics(params, bn_state,
                                              {"img": self._norm(xb), "label": yb},
                                              None, wb, kk)
                return {k2: acc[k2] + ms[k2] for k2 in acc}, None

            S = x.shape[0]
            keys = jax.random.split(key, S)
            acc0 = {"loss_sum": jnp.zeros(()), "score_sum": jnp.zeros(()), "n": jnp.zeros(())}
            acc, _ = jax.lax.scan(stepf, acc0, (x, y, w, keys))
        return jax.lax.psum(acc, ("clients", "data"))

    def _build_global(self):
        def body(params, bn_state, key, *data):
            return self._global_body(params, bn_state, key, *data)

        n_data = 3 if not self.is_lm else 2
        fn = _shard_map(body, self.mesh,
                        in_specs=(P(), P(), P()) + (P(("clients", "data")),) * n_data,
                        out_specs=P())
        # staticcheck: allow(jit-needs-donation): eval reads the live globals
        # and the once-committed eval operands -- nothing here is consumable
        return jax.jit(fn)

    def _staged_global(self, *batched):
        """Pad-and-commit the global-eval operands once (batch axis padded
        to the total device count, sharded over ``(clients, data)``)."""

        def build():
            n_dev = self.mesh.devices.size
            pad = (-batched[0].shape[0]) % n_dev
            sh = NamedSharding(self.mesh, P(("clients", "data")))
            out = []
            for arr in batched:
                if pad:
                    arr = np.concatenate([arr, np.zeros((pad,) + arr.shape[1:], arr.dtype)])
                out.append(jax.device_put(arr, sh))
            return tuple(out)

        return self._staging.memo("global_eval", batched, build)

    def eval_global(self, params, bn_state, *batched, epoch: int = 0):
        """"Global" metrics over the full test set: vision
        ``(x [S,B,...], y [S,B], w [S,B])``; LM ``(rows [S,R,bptt], w)``.

        ``epoch`` seeds the eval RNG so LM corruption noise differs round to
        round (ref ``src/models/transformer.py:148-151``)."""
        if self._global is None:
            self._global = self._build_global()
        padded = self._staged_global(*batched)
        key = jax.random.fold_in(self._global_key, epoch)
        out = self._global(params, bn_state, key, *padded)
        # staticcheck: allow(no-float-coercion): the eval-boundary D2H fetch
        return {k: float(v) for k, v in out.items()}

    # -------------------- eval-fused superstep support --------------------

    def fused(self, sbn_batches: Optional[Tuple[np.ndarray, np.ndarray]] = None,
              local_eval: Optional[Tuple] = None,
              global_eval: Optional[Tuple] = None) -> "FusedEval":
        """Build the :class:`FusedEval` for this experiment: eval operands
        committed ONCE (sharing the host-path memo entries, so the two paths
        read the same device buffers) plus the pure per-device eval core the
        round engines splice into their superstep scan.

        ``sbn_batches``: the ``[S, B, ...]`` train batches for sBN
        recalibration (vision models with BN); ``local_eval``: the per-user
        ``(x, y, m, lm)`` batched test shards (vision); ``global_eval``: the
        batched full test set (always required)."""
        if global_eval is None:
            raise ValueError("fused eval needs the global-eval operands "
                             "(the reference evaluates Global every pass)")
        ops, specs = [], []
        has_sbn = (not self.is_lm and sbn_batches is not None
                   and bool(self.model.bn_sites))
        if has_sbn:
            xb, wb = self._staged_sbn(*sbn_batches)
            ops += [xb, wb]
            specs += [P(("clients", "data"))] * 2
        has_local = not self.is_lm and local_eval is not None
        n_users = 0
        if has_local:
            n_users = int(local_eval[0].shape[0])
            staged = self._staged_users(*local_eval)
            ops += list(staged)
            specs += [P("clients")] * len(staged)
        gops = self._staged_global(*global_eval)
        ops += list(gops)
        specs += [P(("clients", "data"))] * len(gops)
        # the eval PRNG roots ride as committed operands; fold_in(key, epoch)
        # happens in-jit from the scanned round index -- the same derivation
        # the host path performs outside its programs
        keys = self._staging.replicated("fused_eval_keys",
                                        (self._users_key, self._global_key))
        ops += list(keys)
        specs += [P(), P()]
        return FusedEval(self, tuple(ops), tuple(specs), has_sbn, has_local,
                         n_users)


class FusedEval:
    """The evaluator's batch cores packaged for in-superstep use (ISSUE 4).

    ``ops``/``specs``: once-committed device operands and their shard_map
    ``in_specs``, appended verbatim to the engines' superstep program
    arguments (NEVER closure-captured: a captured array would be baked into
    the program as a constant).  ``core(params, epoch, ops)`` is the
    per-device eval phase -- sBN moment accumulation, per-user Local sums
    and the Global psum -- called inside the engines' ``shard_map`` bodies
    on scan steps where the static eval mask fires.  ``out_specs`` is the
    matching output-spec prefix for the eval results stacked over the
    superstep's eval points.
    """

    def __init__(self, evaluator: Evaluator, ops: Tuple, specs: Tuple,
                 has_sbn: bool, has_local: bool, n_users: int):
        self._ev = evaluator
        self.ops = ops
        self.specs = specs
        self.has_sbn = has_sbn
        self.has_local = has_local
        self.n_users = n_users

    @property
    def out_specs(self):
        """Output-spec prefix for one stacked eval result: bn stats and the
        Global sums are replicated, the per-user Local sums stay sharded
        over ``clients`` behind the leading eval-stack axis."""
        return {"bn": P(), "local": P(None, "clients"), "global": P()}

    def core(self, params, epoch, ops) -> Dict[str, Any]:
        """One eval phase, per device: ``ops`` are this device's shards of
        :attr:`ops` in order.  Returns ``{"bn", "local", "global"}`` --
        identical math to the host-dispatched programs (same bodies).

        The phase is fenced with ``optimization_barrier`` on both sides:
        without the fence XLA context-fuses the eval ops with the
        surrounding superstep graph (measured ~1e-7 relative association
        drift on the CE reductions vs the standalone eval programs), which
        would break the bit-identical-to-host-loop contract."""
        params, epoch, ops = jax.lax.optimization_barrier((params, epoch, ops))
        return jax.lax.optimization_barrier(
            self.core_unfenced(params, epoch, ops))

    def core_unfenced(self, params, epoch, ops) -> Dict[str, Any]:
        """The eval phase WITHOUT the optimization_barrier fence: the
        arms-batched supersteps (ISSUE 14) vmap this over the arms axis and
        fence OUTSIDE the vmap (``optimization_barrier`` has no batching
        rule) -- same fusion isolation, one fence per eval point."""
        ev = self._ev
        ukey_root, gkey_root = ops[-2], ops[-1]
        i = 0
        bn: Dict[str, Any] = {}
        if self.has_sbn:
            bn = ev._sbn_body(params, ops[i], ops[i + 1])
            i += 2
        local: Dict[str, Any] = {}
        if self.has_local:
            valid, x, y, m, lm = ops[i:i + 5]
            i += 5
            local = ev._users_body(params, bn, jax.random.fold_in(ukey_root, epoch),
                                   valid, x, y, m, lm)
        g = ev._global_body(params, bn, jax.random.fold_in(gkey_root, epoch),
                            *ops[i:-2])
        return {"bn": bn, "local": local, "global": g}

    def assemble(self, host_tree, eval_epochs) -> list:
        """Host-side reassembly of the fetched eval stack: one dict per eval
        point ``{"epoch", "bn", "local", "global"}``, with the per-user Local
        sums sliced back to the true user count and the Global sums as
        python floats (the host-path ``eval_global`` contract)."""
        out = []
        for j, ep in enumerate(eval_epochs):
            out.append({
                "epoch": int(ep),
                "bn": {site: (mv[0][j], mv[1][j])
                       for site, mv in host_tree["bn"].items()},
                "local": {n: v[j][:self.n_users]
                          for n, v in host_tree["local"].items()},
                # staticcheck: allow(no-float-coercion): host-side assembly of
                # already-fetched numpy sums (the PendingMetrics boundary)
                "global": {n: float(v[j]) for n, v in host_tree["global"].items()},
            })
        return out
