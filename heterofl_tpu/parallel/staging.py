"""Staged placement + zero-resharding steady-state dispatch.

The round engines (round_engine.py masked, grouped.py rate-grouped) are
"one XLA program per round" designs, but until this layer existed the HOST
still paid a per-round tax that eroded exactly the concurrency they exist
for: the per-user data stacks were re-wrapped with ``jnp.asarray`` every
round (an implicit reshard/upload whenever the committed sharding did not
match the program's specs), ``level_placement='slices'`` re-broadcast the
global params and re-resharded the replicated data into every level's
sub-mesh on every call, slot-id packing reallocated identical layouts, and
metric sums were fetched synchronously before the next round could
dispatch (ADVICE r5 item 3).

Four pieces remove that tax:

* :class:`PlacementCache` -- commits operands to their final mesh placement
  ONCE, keyed by the static ``(lo, hi)`` clients-axis device-row range of
  the target sub-mesh (``None`` = the full mesh).  Steady-state rounds then
  pass device-resident, correctly-sharded buffers straight into the jitted
  programs: no implicit per-call resharding, no host bytes moved.  Every
  placement is an EXPLICIT ``jax.device_put``, so the round path stays
  clean under ``jax.transfer_guard_host_to_device("disallow")`` -- the
  regression oracle in tests/test_staging.py.
* :class:`SlotPacker` -- cached host-side slot-layout buffers: packing the
  active-client ids into padded slot arrays reuses one preallocated buffer
  per static layout key instead of reallocating every round.
* :class:`PendingMetrics` / :class:`MetricsPipeline` -- per-round metric
  sums stay ON DEVICE; the pipeline fetches them in batches of
  ``fetch_every`` rounds (default 1 = reference parity), so round ``t+1``
  dispatches while round ``t``'s sums transfer, and ``flush()`` drains at
  eval boundaries (and before the driver exits).
* :class:`PhaseTimer` -- wall-clock stage/dispatch/compute/fetch breakdown,
  threaded into ``bench.py``'s ``extra`` dict and the fed drivers' per-round
  info line, so placement regressions show up as a phase shift instead of
  an undifferentiated slowdown.

Streaming population staging (ISSUE 6) adds the input-side twins:

* :class:`ClientStore` -- the federation's user population as an
  O(1)-per-user METADATA index over the raw dataset arrays (per-user sample
  index rows or contiguous spans, per-user label sets), never densified
  into ``[num_users, ...]`` stacks.  Only the sampled cohort's shards are
  materialised, so host and device memory scale with ``active_clients``
  instead of the population -- "millions of users" becomes a config value.
* :class:`CohortStager` -- the double-buffered ``device_put`` pipeline:
  superstep N+1's cohort packs into a ring of :class:`SlotPacker` host
  buffers and commits to the mesh (explicit ``device_put`` + jitted private
  copy) while superstep N's scanned program computes.  A ring slot is
  refilled only after its previous private COPY is ready -- the copy severs
  any ``device_put`` host-buffer aliasing, so buffer reuse can never
  corrupt an in-flight superstep (same hazard :meth:`PlacementCache.put`
  documents, solved by pipelining instead of a per-call defensive copy).
* :class:`StagedCohort` -- one superstep's committed cohort (slot schedule
  + data stacks as scan xs) plus the static layout facts the dispatching
  engine needs; built by the engines' ``stage_cohort`` and consumed by
  ``train_superstep(..., cohort=...)``.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


def commit_global(x, sharding: NamedSharding):
    """Commit a host (or single-device) value onto ``sharding`` on a mesh
    that may span multiple processes (ISSUE 17).

    ``jax.device_put`` can only target addressable devices; on a
    multi-controller mesh the committed array must be assembled from every
    process's local shards instead.  Each process calls this with the SAME
    host value (staging inputs are computed identically everywhere -- the
    single-controller-per-process GSPMD contract) and contributes the
    shards its devices own via ``jax.make_array_from_callback``.  On a
    fully-addressable (single-process) mesh this is exactly the explicit
    ``device_put`` the transfer guard blesses, so the steady-state path is
    unchanged."""
    if sharding.is_fully_addressable:
        return jax.device_put(x, sharding)
    if isinstance(x, jax.Array) and not x.is_fully_addressable:
        # already a global array on this multi-process runtime: an explicit
        # jitted reshard (a collective program; all processes call this in
        # lockstep at staging boundaries)
        fn = _RESHARDERS.get(sharding)
        if fn is None:
            # staticcheck: allow(jit-needs-donation): staging-boundary
            # reshard copy; the source stays live with the caller
            fn = jax.jit(lambda t: t + 0, out_shardings=sharding)
            _RESHARDERS[sharding] = fn
        return fn(x)
    # staticcheck: allow(no-asarray): multi-process staging commit -- the
    # callback below hands device_put-equivalent host slices to the runtime
    x = np.asarray(x)
    return jax.make_array_from_callback(x.shape, sharding, lambda idx: x[idx])


_GATHERERS: Dict[Any, Any] = {}
_RESHARDERS: Dict[Any, Any] = {}


def host_fetch(a):
    """Host copy of a committed array that may not be fully addressable
    (multi-process meshes, ISSUE 17).

    Fully-addressable arrays (every single-process mesh) take the plain
    ``np.asarray`` D2H path.  A fully-replicated multi-process array reads
    its local replica.  A SHARDED multi-process array is first reshard-
    gathered to replicated by a jitted identity with explicit
    ``out_shardings`` -- a collective program, so every process must call
    this in lockstep (the metric-fetch and checkpoint boundaries both do)."""
    if not isinstance(a, jax.Array) or a.is_fully_addressable:
        # staticcheck: allow(no-asarray): checkpoint/metric-boundary D2H
        return np.asarray(a)
    if a.is_fully_replicated:
        # staticcheck: allow(no-asarray): local-replica read, no collective
        return np.asarray(a.addressable_data(0))
    mesh = a.sharding.mesh
    fn = _GATHERERS.get(mesh)
    if fn is None:
        # staticcheck: allow(jit-needs-donation): checkpoint-boundary gather
        # copy; donating would free the caller's live carry/metric buffer
        fn = jax.jit(lambda t: t + 0, out_shardings=NamedSharding(mesh, P()))
        _GATHERERS[mesh] = fn
    # staticcheck: allow(no-asarray): replicated local-replica read
    return np.asarray(fn(a).addressable_data(0))


class PlacementCache:
    """Once-per-experiment placement of operands onto a mesh or its slices.

    Entries are keyed by ``(name, srange)`` -- ``srange`` is the static
    ``(lo, hi)`` clients-axis row range of a sub-mesh (``None`` = the full
    mesh) -- and invalidated only when the *identity* of the source arrays
    changes (a restage).  The cache holds references to both sources and
    committed outputs, so the ``id()`` keys stay valid for its lifetime.
    """

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._submeshes: Dict[Tuple[int, int], Mesh] = {}
        self._placed: Dict[Any, Tuple[Tuple[int, ...], Any, Any]] = {}
        self._scalars: Dict[Any, Any] = {}
        self._broadcasters: Dict[Any, Any] = {}

    def submesh(self, lo: int, hi: int) -> Mesh:
        """The cached sub-mesh over clients-axis device rows ``[lo, hi)``."""
        key = (lo, hi)
        if key not in self._submeshes:
            self._submeshes[key] = Mesh(self.mesh.devices[lo:hi], self.mesh.axis_names)
        return self._submeshes[key]

    def mesh_for(self, srange: Optional[Tuple[int, int]]) -> Mesh:
        return self.mesh if srange is None else self.submesh(*srange)

    def replicated(self, name: str, arrays: Sequence[Any],
                   srange: Optional[Tuple[int, int]] = None,
                   spec: P = P()) -> Tuple[Any, ...]:
        """Commit ``arrays`` onto the (sub-)mesh with ``spec`` exactly once.

        Steady-state calls with the same source arrays return the committed
        buffers without touching the host or the interconnect.
        """
        key = (name, srange, spec)
        src = tuple(id(a) for a in arrays)
        hit = self._placed.get(key)
        if hit is not None and hit[0] == src:
            return hit[2]
        sh = NamedSharding(self.mesh_for(srange), spec)
        out = tuple(commit_global(a, sh) for a in arrays)
        self._placed[key] = (src, tuple(arrays), out)
        return out

    def scalar(self, value, srange: Optional[Tuple[int, int]] = None,
               dtype=np.float32):
        """A device scalar cached by value (LR repeats for whole schedule
        plateaus; re-putting it every round is an avoidable transfer).

        One slot per (srange, dtype), replaced on a new value: per-round
        schedules (cosine/exponential) would otherwise grow the cache -- and
        leak device buffers -- for the experiment's lifetime."""
        slot = (srange, np.dtype(dtype).name)
        hit = self._scalars.get(slot)
        # staticcheck: allow(no-float-coercion): THE blessed scalar staging
        # path -- host value compare + one explicit put
        if hit is None or hit[0] != float(value):
            arr = commit_global(np.asarray(value, dtype),  # staticcheck: allow(no-asarray): explicit staging put
                                NamedSharding(self.mesh_for(srange), P()))
            self._scalars[slot] = (float(value), arr)  # staticcheck: allow(no-float-coercion): host cache key
            return arr
        return hit[1]

    def commit(self, tree, srange: Optional[Tuple[int, int]] = None,
               spec: P = P()):
        """Ensure every leaf is COMMITTED to the (sub-)mesh with ``spec``;
        already-committed leaves pass through untouched.

        The round programs' params argument needs this: ``model.init``
        returns uncommitted single-device arrays, so without it the first
        dispatch specialises the program on the uncommitted layout and the
        steady state pays a SECOND full compile when the round outputs come
        back mesh-committed -- one silent extra flagship compile (~40s) per
        experiment, caught by the staticcheck recompile-hazard audit.  Like
        :meth:`put`, the output may alias a device source's shards: only
        donate it where the source is consumed by contract (the params
        donation)."""
        sh = NamedSharding(self.mesh_for(srange), spec)

        def one(a):
            if getattr(a, "sharding", None) == sh and getattr(a, "committed", False):
                return a
            return commit_global(a, sh)

        return jax.tree_util.tree_map(one, tree)

    def put(self, tree, srange: Optional[Tuple[int, int]] = None,
            spec: P = P()):
        """Uncached EXPLICIT placement for per-round values (slot ids, level
        partials moving back to the full mesh).  Device-resident sources
        move over the interconnect only; host sources are explicit H2D,
        which the transfer guard permits (it exists to catch *implicit*
        moves).

        Numpy leaves are privately copied first: ``device_put`` may
        ZERO-COPY-ALIAS an aligned host buffer for the device array's whole
        lifetime (measured on CPU for replicated puts), so handing it a
        caller-owned buffer that gets refilled next round -- the SlotPacker
        contract -- would corrupt in-flight rounds once dispatch is
        pipelined.  The copy is tiny (slot-id vectors) and makes buffer
        reuse unconditionally safe.  NOTE: the result may likewise alias a
        DEVICE source's shards (observed even with ``may_alias=False``) --
        never donate it; use :meth:`broadcast` for donation-safe copies."""
        tree = jax.tree_util.tree_map(
            lambda a: a.copy() if isinstance(a, np.ndarray) else a, tree)
        sh = NamedSharding(self.mesh_for(srange), spec)
        return jax.tree_util.tree_map(lambda a: commit_global(a, sh), tree)

    def broadcast(self, tree, srange: Optional[Tuple[int, int]] = None):
        """Jitted replicate-copy onto the (sub-)mesh: private buffers that a
        downstream program can DONATE.

        ``device_put`` reuses the source buffer as a shard whenever the
        target mesh contains the source's device, so donating its output
        deletes the source array out from under the caller (measured on
        jax 0.4.37 CPU; ``may_alias=False`` does not prevent it).  A jitted
        ``x + 0`` with explicit ``out_shardings`` always materialises fresh
        buffers, and as a compiled program it dispatches asynchronously --
        the broadcast overlaps with other levels' work."""
        fn = self._broadcasters.get(srange)
        sh = NamedSharding(self.mesh_for(srange), P())
        if fn is None:
            # staticcheck: allow(jit-needs-donation): the whole point of this
            # jit is to MATERIALISE fresh buffers the downstream program can
            # donate -- donating its input would re-alias the source
            fn = jax.jit(lambda t: jax.tree_util.tree_map(lambda a: a + 0, t),
                         out_shardings=sh)
            self._broadcasters[srange] = fn
        # two steps: the explicit put moves the data onto the (sub-)mesh (a
        # source committed to a SUPERSET of devices cannot enter the smaller
        # jit), then the jitted copy severs any buffer aliasing
        return fn(jax.tree_util.tree_map(lambda a: commit_global(a, sh), tree))

    def memo(self, name: str, sources: Sequence[Any], build: Callable[[], Any]):
        """Generic staged-computation cache (pad-and-commit paths in the
        evaluator): ``build()`` runs once per distinct source identity."""
        key = ("memo", name)
        src = tuple(id(s) for s in sources)
        hit = self._placed.get(key)
        if hit is not None and hit[0] == src:
            return hit[2]
        val = build()
        self._placed[key] = (src, tuple(sources), val)
        return val


class SlotPacker:
    """Cached host-side slot packing.

    ``buffer(key, shape)`` returns a preallocated buffer (int32 filled with
    -1, the padding-slot id, by default); callers write the active ids in
    place.  The per-round numpy packing previously reallocated identical
    layouts whenever the active-client count repeated -- with a fixed
    ``frac`` that is every round.  ``fill=None`` skips the fill for buffers
    whose every row is overwritten (the streaming cohort data stacks).
    """

    def __init__(self):
        self._bufs: Dict[Any, np.ndarray] = {}

    def buffer(self, key, shape: Tuple[int, ...], dtype=np.int32,
               fill=-1) -> np.ndarray:
        shape = tuple(shape)
        buf = self._bufs.get(key)
        if buf is None or buf.shape != shape or buf.dtype != np.dtype(dtype):
            buf = np.empty(shape, dtype)
            self._bufs[key] = buf
        if fill is not None:
            buf.fill(fill)
        return buf


class PhaseTimer:
    """Wall-clock phase accounting for the round path.

    Phases are free-form names; the engines use ``stage`` (host packing +
    placement-cache lookups), ``dispatch`` (program calls returning) and
    ``fetch`` (D2H metric assembly); the driver and bench.py add
    ``sample`` (the host cohort draw, ISSUE 11 -- its own phase so the
    O(population) -> O(active) sampler win is visible per round instead of
    hiding inside ``stage``) and bench.py ``compute``
    (block_until_ready).  Cheap enough to leave always on.

    ``trace`` (ISSUE 10): attach an :class:`~..obs.trace.TraceRecorder`
    and every finished phase is ALSO filed as a complete event on the
    run's Chrome-trace timeline -- the phase table and the trace share one
    measurement (and one clock: ``perf_counter``).
    """

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.calls: Dict[str, int] = {}
        self.trace = None  # optional obs.trace.TraceRecorder

    @contextmanager
    def phase(self, name: str):
        # staticcheck: allow(no-wallclock): host-side phase accounting -- the
        # timer never runs under trace (it wraps dispatch, not computation)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0  # staticcheck: allow(no-wallclock): host-side phase accounting
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.calls[name] = self.calls.get(name, 0) + 1
            if self.trace is not None:
                self.trace.complete(name, t0, dt, cat="phase")

    def snapshot(self) -> Dict[str, float]:
        return dict(self.totals)

    def delta(self, since: Dict[str, float]) -> Dict[str, float]:
        """Per-round breakdown: totals accumulated since ``since``."""
        return {k: v - since.get(k, 0.0) for k, v in self.totals.items()
                if v - since.get(k, 0.0) > 0.0}

    def amortized(self, since: Dict[str, float], rounds: int) -> Dict[str, float]:
        """Per-ROUND breakdown of a K-round superstep: the phase time
        accumulated since ``since`` divided by the rounds it paid for.  One
        stage+dispatch+fetch cycle serves all K rounds of a superstep, so
        this is the honest per-round host cost to compare against
        ``superstep_rounds=1`` (the ISSUE 2 acceptance metric)."""
        rounds = max(1, int(rounds))
        return {k: v / rounds for k, v in self.delta(since).items()}

    def summary(self, ndigits: int = 4) -> Dict[str, float]:
        return {k: round(v, ndigits) for k, v in sorted(self.totals.items())}


class PendingMetrics:
    """Per-round metric sums left on device; ``fetch()`` materialises them
    on the host (D2H) once and caches the result.  ``assemble`` maps the
    fetched tree to the caller-facing dict (the grouped engine packs
    per-level slot vectors back into active-client order)."""

    def __init__(self, device_tree, assemble: Optional[Callable[[Any], Any]] = None):
        self._tree = device_tree
        self._assemble = assemble
        self._host = None

    def fetch(self):
        if self._host is None:
            host = jax.tree_util.tree_map(host_fetch, self._tree)
            self._host = self._assemble(host) if self._assemble is not None else host
            self._tree = None  # release the device refs
        return self._host


class MetricsPipeline:
    """Deferred metric fetch: round ``t+1`` dispatches while round ``t``'s
    sums transfer.

    ``push`` returns the (tag, host_metrics) pairs that became due --
    everything pending once ``fetch_every`` rounds have accumulated
    (``fetch_every=1``, the default, degenerates to synchronous fetch =
    reference parity).  ``flush()`` drains unconditionally; call it at any
    boundary that must observe every round's metrics (the fed drivers flush
    at eval boundaries and before exit)."""

    def __init__(self, fetch_every: int = 1):
        self.fetch_every = max(1, int(fetch_every or 1))
        self._pending: List[Tuple[Any, PendingMetrics]] = []

    def push(self, tag, pending: PendingMetrics) -> List[Tuple[Any, Any]]:
        self._pending.append((tag, pending))
        if len(self._pending) >= self.fetch_every:
            return self.flush()
        return []

    def flush(self) -> List[Tuple[Any, Any]]:
        out = [(tag, p.fetch()) for tag, p in self._pending]
        self._pending = []
        return out

    def __len__(self) -> int:
        return len(self._pending)


# ---------------------------------------------------------------------------
# Streaming population staging (ISSUE 6)
# ---------------------------------------------------------------------------

def _idx64(a) -> np.ndarray:
    """Host index/label-metadata normalization for the ClientStore: a host
    int64 coercion that never wraps a device array -- cohort bytes reach the
    mesh only through the CohortStager's explicit device_put (hence the
    inline allow below)."""
    return np.asarray(a, np.int64)  # staticcheck: allow(no-asarray): host metadata only


class ClientStore:
    """The population as an O(1)-per-user metadata index; cohort shards
    materialise on demand.

    Holds references to the RAW dataset arrays (images/targets or batchified
    token rows) plus per-user index metadata in one of two layouts:

    * **CSR** (:meth:`from_split`): the driver's ``data_split`` index lists
      flattened into one int64 array with per-user offsets -- O(total
      samples) metadata, exactly what the split dict already holds, minus
      the per-user Python-list overhead.
    * **spans** (:meth:`from_spans`): per-user ``(start, size)`` contiguous
      ranges into the raw arrays -- O(num_users) metadata, the layout the
      million-user synthetic populations use (users window onto a shared
      sample pool; ``data/partition.span_population`` builds one).

    ``fill_*`` gather the SAMPLED users' shards into caller buffers with
    byte-identical layout to the eager ``data.pipeline.stack_client_shards``
    rows (same repeat-first-items padding, same sample masks, same label
    masks), so a streamed cohort reproduces the eager round bit for bit.
    Padding slots (user id -1) materialise user 0's shard -- the engines'
    ``maximum(uid, 0)`` convention -- so padded-slot local training stays
    finite exactly like the eager path; its results never reach aggregation
    or metrics (masked by ``valid``).
    """

    def __init__(self, data, target, sizes, classes_size, *, starts=None,
                 offsets=None, idx=None, label_offsets=None, label_idx=None,
                 kind="vision"):
        self.kind = kind
        self.data = np.ascontiguousarray(data)
        self.target = None if target is None else np.ascontiguousarray(target)
        self.sizes = _idx64(sizes)
        self.classes_size = int(classes_size)
        self._starts = None if starts is None else _idx64(starts)
        self._off = None if offsets is None else _idx64(offsets)
        self._idx = None if idx is None else _idx64(idx)
        self._loff = None if label_offsets is None else _idx64(label_offsets)
        self._lidx = None if label_idx is None else _idx64(label_idx)
        if (self._starts is None) == (self._off is None):
            raise ValueError("ClientStore needs exactly one of spans or CSR index")
        if self.sizes.size == 0 or (self.sizes <= 0).any():
            raise ValueError("every user needs a non-empty shard")
        self.num_users = int(self.sizes.size)
        self.shard_max = int(self.sizes.max())
        if kind == "lm" and (self.sizes != self.shard_max).any():
            raise ValueError("per-user row counts must match")  # stack parity

    # -- constructors --------------------------------------------------

    @classmethod
    def from_split(cls, data, target, data_split: Dict[int, Sequence[int]],
                   label_split, classes_size: int, kind: str = "vision"
                   ) -> "ClientStore":
        """Build from the driver's per-user index-list dicts (the eager
        stack's inputs)."""
        users = len(data_split)
        rows = [_idx64(data_split[u]) for u in range(users)]
        sizes = _idx64([r.size for r in rows])
        offsets = np.concatenate([[0], np.cumsum(sizes)])
        idx = np.concatenate(rows) if rows else np.zeros(0, np.int64)
        loff = lidx = None
        if label_split is not None:
            lrows = [_idx64(label_split[u]) for u in range(users)]
            loff = np.concatenate([[0], np.cumsum([r.size for r in lrows])])
            lidx = np.concatenate(lrows) if lrows else np.zeros(0, np.int64)
        return cls(data, target, sizes, classes_size, offsets=offsets, idx=idx,
                   label_offsets=loff, label_idx=lidx, kind=kind)

    @classmethod
    def from_spans(cls, data, target, starts, sizes, classes_size,
                   label_split=None, kind: str = "vision") -> "ClientStore":
        """Build from per-user contiguous ``(start, size)`` windows into the
        raw arrays: O(num_users) metadata, the million-user layout.
        ``label_split=None`` means every user sees every class (iid)."""
        starts = _idx64(starts)
        sizes = _idx64(sizes)
        if starts.shape != sizes.shape:
            raise ValueError(f"starts/sizes shape mismatch: {starts.shape} vs "
                             f"{sizes.shape}")
        if ((starts < 0) | (starts + sizes > len(data))).any():
            raise ValueError("a user span runs outside the raw data array")
        loff = lidx = None
        if label_split is not None:
            lrows = [_idx64(label_split[u]) for u in range(len(starts))]
            loff = np.concatenate([[0], np.cumsum([r.size for r in lrows])])
            lidx = np.concatenate(lrows) if lrows else np.zeros(0, np.int64)
        return cls(data, target, sizes, classes_size, starts=starts,
                   label_offsets=loff, label_idx=lidx, kind=kind)

    # -- metadata ------------------------------------------------------

    @property
    def metadata_nbytes(self) -> int:
        """Host bytes of the index metadata (the raw data pool is shared
        with the dataset and excluded): the O(active)-memory tests compare
        this against the eager ``[U, N, ...]`` stack it replaces."""
        return sum(a.nbytes for a in (self.sizes, self._starts, self._off,
                                      self._idx, self._loff, self._lidx)
                   if a is not None)

    @property
    def row_shape(self) -> Tuple[int, ...]:
        """Per-user shard shape at the store-wide static max: vision
        ``(shard_max,) + sample_shape``, LM ``(rows, row_len)``."""
        return (self.shard_max,) + self.data.shape[1:]

    def _row_idx(self, u: int, n: int) -> np.ndarray:
        """User ``u``'s padded sample-index row of length ``n`` -- the exact
        ``stack_client_shards`` rule: real indices first, then the first
        ``n - size`` indices repeated cyclically."""
        sz = int(self.sizes[u])
        j = np.arange(n)
        jj = np.where(j < sz, j, (j - sz) % sz)
        if self._starts is not None:
            return int(self._starts[u]) + jj
        lo = int(self._off[u])
        return self._idx[lo:lo + sz][jj]

    @staticmethod
    def _slot_user(u) -> int:
        # padding slots (-1) materialise user 0: the engines gather data at
        # maximum(uid, 0), so this is the eager stack's exact behaviour
        u = int(u)
        return u if u >= 0 else 0

    # -- cohort materialisation ----------------------------------------

    def fill_vision(self, user_ids, x_out: np.ndarray, y_out: np.ndarray,
                    m_out: np.ndarray) -> None:
        """Gather the given users' shards into ``[slots, shard_max, ...]``
        buffers (images, targets, sample masks)."""
        n = x_out.shape[1]
        ids = _idx64(user_ids).reshape(-1)
        for s, u in enumerate(ids):
            u = self._slot_user(u)
            idx = self._row_idx(u, n)
            x_out[s] = self.data[idx]
            y_out[s] = self.target[idx]
            sz = int(self.sizes[u])
            m_out[s, :sz] = 1.0
            m_out[s, sz:] = 0.0

    def fill_lm(self, user_ids, rows_out: np.ndarray) -> None:
        """Gather the given users' batchified token rows into
        ``[slots, rows, row_len]``."""
        ids = _idx64(user_ids).reshape(-1)
        for s, u in enumerate(ids):
            u = self._slot_user(u)
            rows_out[s] = self.data[self._row_idx(u, rows_out.shape[1])]

    def fill_labels(self, user_ids, lm_out: np.ndarray) -> None:
        """Per-user label-split masks ``[slots, classes]`` -- the streaming
        twin of ``data.pipeline.label_split_masks`` rows.  A store built
        without a label split (iid span populations) emits all-ones."""
        ids = _idx64(user_ids).reshape(-1)
        if self._lidx is None:
            lm_out[:] = 1.0
            return
        lm_out[:] = 0.0
        for s, u in enumerate(ids):
            u = self._slot_user(u)
            lm_out[s, self._lidx[self._loff[u]:self._loff[u + 1]]] = 1.0


class StagedCohort:
    """One superstep's committed cohort: the slot schedule + data stacks
    (device-resident, sharded over the cohort's slot axis, consumed as scan
    xs) plus the static layout facts that key the streaming program."""

    def __init__(self, engine: str, k: int, a: int, per_dev: int, sched,
                 data: Tuple, mode: Optional[str] = None,
                 positions: Optional[list] = None):
        self.engine = engine        # "masked" | "grouped"
        self.k = k                  # rounds in the superstep
        self.a = a                  # active clients per round
        self.per_dev = per_dev      # slots per device (per level, grouped)
        self.sched = sched          # device [k, ...] slot-id schedule
        self.data = data            # device cohort stacks, k-leading
        self.mode = mode            # grouped: "span" | "slices"
        self.positions = positions  # grouped: per-round per-level A-positions


class CohortStager:
    """Double-buffered cohort commit: host ring buffers -> explicit
    ``device_put`` -> jitted private copy.

    The pipeline contract: ``buffers()`` hands out one ring slot's host
    buffers to fill, ``commit()`` moves them to the mesh and returns PRIVATE
    device arrays.  ``device_put`` may zero-copy-alias an aligned host
    buffer for the device array's whole lifetime (the
    :meth:`PlacementCache.put` finding), so the committed arrays are a
    jitted replicate-copy of the put -- the copy dispatches asynchronously
    (it IS the overlap-able transfer) and its outputs share no buffers with
    the ring.  Before a ring slot is handed out again, ``buffers()`` blocks
    on that slot's previous COPY outputs: once the copy is ready its inputs
    are dead, so the refill can never corrupt an in-flight superstep -- and
    with prefetch depth 1 the wait lands two supersteps after the copy
    dispatched, i.e. it is effectively free.
    """

    def __init__(self, mesh: Mesh, depth: int = 1):
        self.mesh = mesh
        self.depth = max(1, int(depth))
        self._packer = SlotPacker()
        self._cursor: Dict[Any, int] = {}
        self._fences: Dict[Any, Any] = {}
        self._copiers: Dict[Any, Any] = {}

    def buffers(self, key, layouts: Sequence[Tuple]) -> Tuple[int, Tuple[np.ndarray, ...]]:
        """One ring slot's host buffers for ``layouts`` = [(shape, dtype,
        fill), ...]; returns ``(slot, buffers)``.  Blocks on the slot's
        previous private copy (see class docstring) before reuse."""
        slot = self._cursor.get(key, 0)
        fence = self._fences.pop((key, slot), None)
        if fence is not None:
            # staticcheck: allow(no-block-until-ready): the ring-slot fence
            # waits on the prior private COPY of these buffers (a memcpy that
            # finished supersteps ago), never on a round program
            jax.block_until_ready(fence)
        bufs = tuple(self._packer.buffer((key, slot, i), shape, dtype, fill)
                     for i, (shape, dtype, fill) in enumerate(layouts))
        return slot, bufs

    def _copier(self, sig, shardings):
        fn = self._copiers.get(sig)
        if fn is None:
            # staticcheck: allow(jit-needs-donation): the whole point of this
            # jit is to MATERIALISE private buffers severing any device_put
            # host aliasing -- donating its input would re-alias the ring
            fn = jax.jit(lambda t: tuple(a + 0 for a in t),
                         out_shardings=tuple(shardings))
            self._copiers[sig] = fn
        return fn

    def commit(self, key, slot: int, bufs: Sequence[np.ndarray],
               specs: Sequence[P]) -> Tuple:
        """Commit one ring slot's buffers to the mesh with ``specs`` and
        return the private device arrays; advances the ring cursor."""
        shardings = tuple(NamedSharding(self.mesh, s) for s in specs)
        put = tuple(commit_global(b, sh) for b, sh in zip(bufs, shardings))
        sig = tuple((b.shape, b.dtype.str, s) for b, s in zip(bufs, specs))
        out = self._copier(sig, shardings)(put)
        self._fences[(key, slot)] = out
        self._cursor[key] = (slot + 1) % (self.depth + 1)
        return out
