"""Staged placement + zero-resharding steady-state dispatch.

The round engines (round_engine.py masked, grouped.py rate-grouped) are
"one XLA program per round" designs, but until this layer existed the HOST
still paid a per-round tax that eroded exactly the concurrency they exist
for: the per-user data stacks were re-wrapped with ``jnp.asarray`` every
round (an implicit reshard/upload whenever the committed sharding did not
match the program's specs), ``level_placement='slices'`` re-broadcast the
global params and re-resharded the replicated data into every level's
sub-mesh on every call, slot-id packing reallocated identical layouts, and
metric sums were fetched synchronously before the next round could
dispatch (ADVICE r5 item 3).

Four pieces remove that tax:

* :class:`PlacementCache` -- commits operands to their final mesh placement
  ONCE, keyed by the static ``(lo, hi)`` clients-axis device-row range of
  the target sub-mesh (``None`` = the full mesh).  Steady-state rounds then
  pass device-resident, correctly-sharded buffers straight into the jitted
  programs: no implicit per-call resharding, no host bytes moved.  Every
  placement is an EXPLICIT ``jax.device_put``, so the round path stays
  clean under ``jax.transfer_guard_host_to_device("disallow")`` -- the
  regression oracle in tests/test_staging.py.
* :class:`SlotPacker` -- cached host-side slot-layout buffers: packing the
  active-client ids into padded slot arrays reuses one preallocated buffer
  per static layout key instead of reallocating every round.
* :class:`PendingMetrics` / :class:`MetricsPipeline` -- per-round metric
  sums stay ON DEVICE; the pipeline fetches them in batches of
  ``fetch_every`` rounds (default 1 = reference parity), so round ``t+1``
  dispatches while round ``t``'s sums transfer, and ``flush()`` drains at
  eval boundaries (and before the driver exits).
* :class:`PhaseTimer` -- wall-clock stage/dispatch/compute/fetch breakdown,
  threaded into ``bench.py``'s ``extra`` dict and the fed drivers' per-round
  info line, so placement regressions show up as a phase shift instead of
  an undifferentiated slowdown.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P


class PlacementCache:
    """Once-per-experiment placement of operands onto a mesh or its slices.

    Entries are keyed by ``(name, srange)`` -- ``srange`` is the static
    ``(lo, hi)`` clients-axis row range of a sub-mesh (``None`` = the full
    mesh) -- and invalidated only when the *identity* of the source arrays
    changes (a restage).  The cache holds references to both sources and
    committed outputs, so the ``id()`` keys stay valid for its lifetime.
    """

    def __init__(self, mesh: Mesh):
        self.mesh = mesh
        self._submeshes: Dict[Tuple[int, int], Mesh] = {}
        self._placed: Dict[Any, Tuple[Tuple[int, ...], Any, Any]] = {}
        self._scalars: Dict[Any, Any] = {}
        self._broadcasters: Dict[Any, Any] = {}

    def submesh(self, lo: int, hi: int) -> Mesh:
        """The cached sub-mesh over clients-axis device rows ``[lo, hi)``."""
        key = (lo, hi)
        if key not in self._submeshes:
            self._submeshes[key] = Mesh(self.mesh.devices[lo:hi], self.mesh.axis_names)
        return self._submeshes[key]

    def mesh_for(self, srange: Optional[Tuple[int, int]]) -> Mesh:
        return self.mesh if srange is None else self.submesh(*srange)

    def replicated(self, name: str, arrays: Sequence[Any],
                   srange: Optional[Tuple[int, int]] = None,
                   spec: P = P()) -> Tuple[Any, ...]:
        """Commit ``arrays`` onto the (sub-)mesh with ``spec`` exactly once.

        Steady-state calls with the same source arrays return the committed
        buffers without touching the host or the interconnect.
        """
        key = (name, srange, spec)
        src = tuple(id(a) for a in arrays)
        hit = self._placed.get(key)
        if hit is not None and hit[0] == src:
            return hit[2]
        sh = NamedSharding(self.mesh_for(srange), spec)
        out = tuple(jax.device_put(a, sh) for a in arrays)
        self._placed[key] = (src, tuple(arrays), out)
        return out

    def scalar(self, value, srange: Optional[Tuple[int, int]] = None,
               dtype=np.float32):
        """A device scalar cached by value (LR repeats for whole schedule
        plateaus; re-putting it every round is an avoidable transfer).

        One slot per (srange, dtype), replaced on a new value: per-round
        schedules (cosine/exponential) would otherwise grow the cache -- and
        leak device buffers -- for the experiment's lifetime."""
        slot = (srange, np.dtype(dtype).name)
        hit = self._scalars.get(slot)
        # staticcheck: allow(no-float-coercion, no-asarray): THE blessed
        # scalar staging path -- host value compare + one explicit put
        if hit is None or hit[0] != float(value):
            arr = jax.device_put(np.asarray(value, dtype),  # staticcheck: allow(no-asarray): explicit staging put
                                 NamedSharding(self.mesh_for(srange), P()))
            self._scalars[slot] = (float(value), arr)  # staticcheck: allow(no-float-coercion): host cache key
            return arr
        return hit[1]

    def commit(self, tree, srange: Optional[Tuple[int, int]] = None,
               spec: P = P()):
        """Ensure every leaf is COMMITTED to the (sub-)mesh with ``spec``;
        already-committed leaves pass through untouched.

        The round programs' params argument needs this: ``model.init``
        returns uncommitted single-device arrays, so without it the first
        dispatch specialises the program on the uncommitted layout and the
        steady state pays a SECOND full compile when the round outputs come
        back mesh-committed -- one silent extra flagship compile (~40s) per
        experiment, caught by the staticcheck recompile-hazard audit.  Like
        :meth:`put`, the output may alias a device source's shards: only
        donate it where the source is consumed by contract (the params
        donation)."""
        sh = NamedSharding(self.mesh_for(srange), spec)

        def one(a):
            if getattr(a, "sharding", None) == sh and getattr(a, "committed", False):
                return a
            return jax.device_put(a, sh)

        return jax.tree_util.tree_map(one, tree)

    def put(self, tree, srange: Optional[Tuple[int, int]] = None,
            spec: P = P()):
        """Uncached EXPLICIT placement for per-round values (slot ids, level
        partials moving back to the full mesh).  Device-resident sources
        move over the interconnect only; host sources are explicit H2D,
        which the transfer guard permits (it exists to catch *implicit*
        moves).

        Numpy leaves are privately copied first: ``device_put`` may
        ZERO-COPY-ALIAS an aligned host buffer for the device array's whole
        lifetime (measured on CPU for replicated puts), so handing it a
        caller-owned buffer that gets refilled next round -- the SlotPacker
        contract -- would corrupt in-flight rounds once dispatch is
        pipelined.  The copy is tiny (slot-id vectors) and makes buffer
        reuse unconditionally safe.  NOTE: the result may likewise alias a
        DEVICE source's shards (observed even with ``may_alias=False``) --
        never donate it; use :meth:`broadcast` for donation-safe copies."""
        tree = jax.tree_util.tree_map(
            lambda a: a.copy() if isinstance(a, np.ndarray) else a, tree)
        sh = NamedSharding(self.mesh_for(srange), spec)
        return jax.device_put(tree, sh)

    def broadcast(self, tree, srange: Optional[Tuple[int, int]] = None):
        """Jitted replicate-copy onto the (sub-)mesh: private buffers that a
        downstream program can DONATE.

        ``device_put`` reuses the source buffer as a shard whenever the
        target mesh contains the source's device, so donating its output
        deletes the source array out from under the caller (measured on
        jax 0.4.37 CPU; ``may_alias=False`` does not prevent it).  A jitted
        ``x + 0`` with explicit ``out_shardings`` always materialises fresh
        buffers, and as a compiled program it dispatches asynchronously --
        the broadcast overlaps with other levels' work."""
        fn = self._broadcasters.get(srange)
        sh = NamedSharding(self.mesh_for(srange), P())
        if fn is None:
            # staticcheck: allow(jit-needs-donation): the whole point of this
            # jit is to MATERIALISE fresh buffers the downstream program can
            # donate -- donating its input would re-alias the source
            fn = jax.jit(lambda t: jax.tree_util.tree_map(lambda a: a + 0, t),
                         out_shardings=sh)
            self._broadcasters[srange] = fn
        # two steps: the explicit put moves the data onto the (sub-)mesh (a
        # source committed to a SUPERSET of devices cannot enter the smaller
        # jit), then the jitted copy severs any buffer aliasing
        return fn(jax.device_put(tree, sh))

    def memo(self, name: str, sources: Sequence[Any], build: Callable[[], Any]):
        """Generic staged-computation cache (pad-and-commit paths in the
        evaluator): ``build()`` runs once per distinct source identity."""
        key = ("memo", name)
        src = tuple(id(s) for s in sources)
        hit = self._placed.get(key)
        if hit is not None and hit[0] == src:
            return hit[2]
        val = build()
        self._placed[key] = (src, tuple(sources), val)
        return val


class SlotPacker:
    """Cached host-side slot packing.

    ``buffer(key, shape)`` returns a preallocated int32 buffer filled with
    -1 (the padding-slot id); callers write the active ids in place.  The
    per-round numpy packing previously reallocated identical layouts
    whenever the active-client count repeated -- with a fixed ``frac`` that
    is every round.
    """

    def __init__(self):
        self._bufs: Dict[Any, np.ndarray] = {}

    def buffer(self, key, shape: Tuple[int, ...]) -> np.ndarray:
        shape = tuple(shape)
        buf = self._bufs.get(key)
        if buf is None or buf.shape != shape:
            buf = np.empty(shape, np.int32)
            self._bufs[key] = buf
        buf.fill(-1)
        return buf


class PhaseTimer:
    """Wall-clock phase accounting for the round path.

    Phases are free-form names; the engines use ``stage`` (host packing +
    placement-cache lookups), ``dispatch`` (program calls returning) and
    ``fetch`` (D2H metric assembly); bench.py adds ``compute``
    (block_until_ready).  Cheap enough to leave always on.
    """

    def __init__(self):
        self.totals: Dict[str, float] = {}
        self.calls: Dict[str, int] = {}

    @contextmanager
    def phase(self, name: str):
        # staticcheck: allow(no-wallclock): host-side phase accounting -- the
        # timer never runs under trace (it wraps dispatch, not computation)
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0  # staticcheck: allow(no-wallclock): host-side phase accounting
            self.totals[name] = self.totals.get(name, 0.0) + dt
            self.calls[name] = self.calls.get(name, 0) + 1

    def snapshot(self) -> Dict[str, float]:
        return dict(self.totals)

    def delta(self, since: Dict[str, float]) -> Dict[str, float]:
        """Per-round breakdown: totals accumulated since ``since``."""
        return {k: v - since.get(k, 0.0) for k, v in self.totals.items()
                if v - since.get(k, 0.0) > 0.0}

    def amortized(self, since: Dict[str, float], rounds: int) -> Dict[str, float]:
        """Per-ROUND breakdown of a K-round superstep: the phase time
        accumulated since ``since`` divided by the rounds it paid for.  One
        stage+dispatch+fetch cycle serves all K rounds of a superstep, so
        this is the honest per-round host cost to compare against
        ``superstep_rounds=1`` (the ISSUE 2 acceptance metric)."""
        rounds = max(1, int(rounds))
        return {k: v / rounds for k, v in self.delta(since).items()}

    def summary(self, ndigits: int = 4) -> Dict[str, float]:
        return {k: round(v, ndigits) for k, v in sorted(self.totals.items())}


class PendingMetrics:
    """Per-round metric sums left on device; ``fetch()`` materialises them
    on the host (D2H) once and caches the result.  ``assemble`` maps the
    fetched tree to the caller-facing dict (the grouped engine packs
    per-level slot vectors back into active-client order)."""

    def __init__(self, device_tree, assemble: Optional[Callable[[Any], Any]] = None):
        self._tree = device_tree
        self._assemble = assemble
        self._host = None

    def fetch(self):
        if self._host is None:
            host = jax.tree_util.tree_map(np.asarray, self._tree)
            self._host = self._assemble(host) if self._assemble is not None else host
            self._tree = None  # release the device refs
        return self._host


class MetricsPipeline:
    """Deferred metric fetch: round ``t+1`` dispatches while round ``t``'s
    sums transfer.

    ``push`` returns the (tag, host_metrics) pairs that became due --
    everything pending once ``fetch_every`` rounds have accumulated
    (``fetch_every=1``, the default, degenerates to synchronous fetch =
    reference parity).  ``flush()`` drains unconditionally; call it at any
    boundary that must observe every round's metrics (the fed drivers flush
    at eval boundaries and before exit)."""

    def __init__(self, fetch_every: int = 1):
        self.fetch_every = max(1, int(fetch_every or 1))
        self._pending: List[Tuple[Any, PendingMetrics]] = []

    def push(self, tag, pending: PendingMetrics) -> List[Tuple[Any, Any]]:
        self._pending.append((tag, pending))
        if len(self._pending) >= self.fetch_every:
            return self.flush()
        return []

    def flush(self) -> List[Tuple[Any, Any]]:
        out = [(tag, p.fetch()) for tag, p in self._pending]
        self._pending = []
        return out

    def __len__(self) -> int:
        return len(self._pending)
