"""Rate-grouped sliced execution ON the mesh: dense per-level programs,
device-resident aggregation, no host round-trips.

The masked engine (round_engine.py) runs every client at full width with
channel masks -- uniform shapes, but a ~3.9x FLOP overhead at the canonical
a1-e1 mix (MEASUREMENTS.md roofline): a rate-1/16 client's conv FLOPs are
(1/16)^2 of full width, yet the masked program spends full-width FLOPs on it.
This engine realises the roofline's "group clients by rate level" design:

  * active clients are grouped by rate level on the host (level membership is
    data, not shape -- grouping is O(A) bookkeeping);
  * each level runs ONE jitted ``shard_map`` program: extract the level's
    dense sub-model from the global params (static prefix slices,
    ``fed.core.extract_sliced_jnp``), vmap the level's clients through dense
    local SGD at the level's own small shapes -- client slots sharded over
    the ``clients`` mesh axis -- then ``psum`` the level's counted sums and
    zero-pad them back to global shape (``embed_sliced_jnp``);
  * a final jitted combine merges the level partials into the new globals
    (counted average + stale rule, semantics = ref fed.py:180-298).

All intermediates are device arrays: the host only *dispatches* the L+1
programs per round; no parameter or data bytes move through it.  The
staging layer (staging.py) makes that literal in steady state -- data
stacks are committed to each level's (sub-)mesh once, slot packing reuses
cached host buffers, and metric sums can stay on device until the caller
fetches them (``async_metrics``).  Programs
are cached per (rate, slot-count) with slot counts bucketed to powers of
two, so the compile space is O(levels x log A) -- NOT the cross-product of
per-level counts (a per-round-pattern mega-program would recompile
combinatorially as the sampled mix varies round to round).

Two level placements (``cfg['level_placement']``): ``span`` (default) runs
every level across the whole clients axis back-to-back; ``slices``
partitions the clients-axis device rows among the levels in proportion to
their EXPECTED FLOP share (static per experiment: fix-mode per-level user
counts, dynamic-mode proportions) and dispatches each level's program to
its own disjoint sub-mesh -- the programs then overlap in time (async
dispatch), which is the pod-regime layout the MEASUREMENTS.md roofline
prescribes (params are ICI-broadcast to each slice and the level partials
brought back to the full mesh for the combine).  Static allocation keeps
the compile space at O(levels x log A) and the cache keys bound to fixed
device ranges; per-round count fluctuation is absorbed by slot bucketing
inside each slice.  Multi-process meshes fall back to ``span`` (slice
boundaries are not yet host-aligned).

Client PRNG keys are ``fold_in(fold_in(key, CLIENT_STREAM_SALT),
global_uid)`` (:func:`~..fed.core.client_stream_keys`, the masked
engine's convention) -- so with the same inputs both engines produce the same
new global parameters (tests/test_grouped.py) up to float association.

Trade-off vs masked: dense per-level compute wins when active-clients /
devices >> number of levels (the pod regime); at tiny occupancy the
per-level padding to the axis size erodes the win.  Both engines share the
aggregation algebra, so the choice is per-experiment (``cfg['strategy']``).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..compress import make_codec, resid_slots, resolve_codec_cfg
from ..config import resolve_prefetch_depth
from ..fed.core import (arm_stream_keys, client_stream_keys, combine_counted,
                        embed_sliced_jnp, extract_sliced_jnp,
                        failure_stream_key, level_flop_table, snap_to_levels)
from ..fed.sampling import resolve_sampler_cfg
from ..models import make_model
from ..multi import resolve_arms_cfg
from ..models.layout import ParamPinner
from ..models.spec import count_masks as make_count_masks
from ..chaos import resolve_poison_cfg
from ..obs import resolve_quarantine_cfg, resolve_telemetry_cfg, split_probes
from ..obs.hist import round_hists
from ..obs.probes import round_probes
from ..ops.fused_update import FlatSpec
from ..sched import resolve_schedule_cfg
from ..sched.buffer import _SchedBufCarry, buffered_combine
from ..sched.deadline import deadline_steps
from ..utils.optim import make_traced_lr_fn
from .round_engine import (RoundEngine, _bucket_pow2, _ceil_div,
                           _shard_map, _WireCodecCarry)
from .staging import (ClientStore, CohortStager, PendingMetrics, PhaseTimer,
                      PlacementCache, SlotPacker, StagedCohort)


class GroupedRoundEngine(_WireCodecCarry, _SchedBufCarry):
    """Mesh-native sliced strategy: same public round signature as
    ``fed.sliced.SlicedFederation`` (host-side rates in, per-slot metrics
    out), but every program runs on the mesh and aggregation state never
    leaves the devices."""

    def __init__(self, cfg: Dict[str, Any], mesh):
        if cfg.get("data_placement", "replicated") == "sharded":
            raise ValueError("grouped strategy needs replicated data placement "
                             "(a level's clients span the whole clients axis); "
                             "use the masked engine for sharded placement")
        self.cfg = cfg
        self.mesh = mesh
        # 'span' (default): every level's slots spread over the whole
        # clients axis, levels run back-to-back.  'slices': the clients-axis
        # device rows are partitioned among the levels in proportion to
        # FLOP share, each level's dense program runs on its own sub-mesh
        # and the programs execute CONCURRENTLY (async dispatch to disjoint
        # devices) -- the pod-regime layout of the MEASUREMENTS.md roofline.
        # Falls back to 'span' when there are fewer device rows than levels.
        self.level_placement = cfg.get("level_placement", "span")
        if self.level_placement not in ("span", "slices"):
            raise ValueError(f"Not valid level_placement: {self.level_placement!r}")
        self.global_rate = cfg["global_model_rate"]
        self.global_model = make_model(cfg)
        # layout pinning (ISSUE 5 pass 2), same cached pinner as the
        # masked engine
        self._pin = ParamPinner(mesh, cfg.get("layout_policy", "auto"))
        self.is_lm = self.global_model.meta.get("kind") == "transformer"
        self.failure_rate = float(cfg.get("client_failure_rate", 0.0) or 0.0)  # staticcheck: allow(no-float-coercion): constructor-time config scalar
        self.levels: Dict[float, Tuple[Any, RoundEngine]] = {}
        for rate in sorted({float(r) for r in cfg["model_rate"]}, reverse=True):  # staticcheck: allow(no-float-coercion): constructor-time config parse
            model = make_model(cfg, model_rate=rate)
            self.levels[rate] = (model, RoundEngine(model, cfg, mesh=None))
        self._level_progs: Dict[Tuple, Any] = {}
        self._combine_progs: Dict[int, Any] = {}
        self._superstep_progs: Dict[Tuple, Any] = {}
        self._lr_fn = None  # built on first superstep (plateau raises there)
        self._slices: Dict[float, Tuple[int, int]] = {}
        # staged placement (ISSUE 1 tentpole): data stacks (and in slices
        # mode the per-level operands) are committed to their sub-meshes
        # ONCE, keyed by the static (lo, hi) ranges -- steady-state rounds
        # dispatch device-resident buffers with zero implicit resharding
        self._staging = PlacementCache(mesh)
        self._packer = SlotPacker()
        # streaming cohort pipeline (ISSUE 6): built on first stage_cohort;
        # ring depth = cfg['stream_prefetch_depth'] (ISSUE 8 satellite)
        self._cohort_stager = None
        self._prefetch_depth = resolve_prefetch_depth(cfg)
        # wire codec (ISSUE 8): compression lives in the fused superstep
        # (where the ONE global psum is); the K=1 host-orchestrated
        # per-level path stays dense and train_round refuses lossy codecs
        self._codec_name, self._error_feedback = resolve_codec_cfg(
            cfg, engine_strategy="grouped")
        self._codec_obj = None
        self._resid = None
        # per-level codec selection (ISSUE 9 satellite): a {rate: codec}
        # map compresses each level's SLICED partial under its own codec in
        # the one fused-superstep psum bind -- level-a int8 / level-e dense
        # and friends.  Works on BOTH level placements (ISSUE 14 satellite
        # retired the PR 9 slices refusal): under 'slices' every switch
        # branch emits every level's payload structure -- its own encoded
        # partial plus the other levels' identity payloads
        # (codec.zero_payload), with each level's codec counting its own
        # slice rows as participants.
        self._codec_map = None
        if isinstance(self._codec_name, dict):
            level_set = {float(r) for r in self.levels}  # staticcheck: allow(no-float-coercion): constructor-time config parse
            map_set = set(self._codec_name)
            if map_set != level_set:
                raise ValueError(
                    f"per-level wire_codec map keys {sorted(map_set)} do "
                    f"not match the engine's level table "
                    f"{sorted(level_set)}: every level needs exactly one "
                    f"codec")
            self._codec_map = self._codec_name
            self._codec_name = "per-level"  # truthy sentinel; never a codec
        self._map_lay = None  # cached per-level FlatSpec layout
        self._map_codec_objs: Dict[Tuple, Any] = {}
        # scheduler (ISSUE 9): deadline + buffered-async ride the fused
        # superstep; availability schedules reach this engine through the
        # host-packed user/rate schedules (superstep_user_schedule)
        self._sched_spec = resolve_schedule_cfg(cfg)
        # population sampler (ISSUE 11): this engine never draws in-jit
        # (level grouping needs the ids host-side, so cohorts arrive as
        # host-packed schedules drawn from THE one stream), but the kind is
        # resolved here so a typo'd sampler fails at construction and the
        # engine's stream identity is inspectable like the masked one's
        self._sampler = resolve_sampler_cfg(cfg).kind
        self._sched_buf = None
        if self._sched_spec.buffered and self._codec_name != "dense":
            raise ValueError(
                "schedule aggregation='buffered' cannot combine with a "
                "lossy wire_codec yet: both add a scan carry with its own "
                "donation/checkpoint contract -- pick one per experiment")
        # runtime telemetry (ISSUE 10): probes live in the fused superstep
        # (where the round's single psum and the combined globals are);
        # the K=1 host-orchestrated path refuses loudly in train_round
        self._obs_spec = resolve_telemetry_cfg(cfg)
        self._obs_on = self._obs_spec.probes
        # cohort histograms (ISSUE 12): telemetry='hist' folds the fixed-
        # bucket hist rows (obs/hist.py) in next to the scalar probes
        self._obs_hist = self._obs_spec.hist
        # staticcheck: allow(no-float-coercion): constructor-time config
        # parse (the probe level table, a trace-time constant)
        self._obs_levels = sorted({float(r) for r in cfg["model_rate"]},
                                  reverse=True)
        # client-update quarantine (ISSUE 15): the gate folds into each
        # level core's counted sums BEFORE the level embed and the single
        # global psum -- same zero-count-participant semantics as the
        # masked engine, identical programs when 'off'
        self._quarantine = resolve_quarantine_cfg(cfg)
        # chaos NaN poison (ISSUE 15): trace-time (round, uid) table; the
        # fused superstep threads the scan epoch into every level core
        self._poison = resolve_poison_cfg(cfg)
        # experiment arms multiplexer (ISSUE 14, heterofl_tpu/multi/): the
        # grouped engine batches arms over its SPAN fused superstep --
        # shared host user/rate schedules (level membership is slot
        # bookkeeping, one layout for all arms), per-arm streams for the
        # client/slot keys, deadline budgets and failure draws.  Carries
        # and layouts that do not batch yet refuse loudly here.
        self._arms_spec = resolve_arms_cfg(cfg)
        if "arms" in getattr(mesh, "axis_names", ()):
            raise ValueError(
                "the grouped engine does not take an 'arms' mesh axis "
                "yet: its level slot layouts assume the whole clients "
                "axis (a ROADMAP follow-on) -- use the masked engine for "
                "mesh-placed arms, or grouped arms under the vmap "
                "placement")
        if self._arms_spec is not None:
            if self._codec_name != "dense":
                raise ValueError(
                    "arms with the grouped strategy need the dense wire "
                    "codec: the grouped EF-residual carry (single-codec "
                    "and per-level maps alike) does not batch over the "
                    "arms axis yet (a ROADMAP follow-on); batch dense "
                    "grouped arms or use the masked engine for codec arms")
            if self._sched_spec.buffered:
                raise ValueError(
                    "arms cannot combine with schedule aggregation="
                    "'buffered' yet: the staleness buffer is a replicated "
                    "carry with its own donation/checkpoint contract -- "
                    "batch dense-sync arms or run buffered solo")
            if self._obs_on:
                raise ValueError(
                    "arms with the grouped strategy need telemetry='off': "
                    "the span probe rows do not carry the arms axis yet "
                    "(a ROADMAP follow-on); the masked engine supports "
                    "telemetry x arms")
            if self._quarantine.enabled:
                raise ValueError(
                    "arms with the grouped strategy need quarantine='off': "
                    "the quarantine counter rides the probe rows, which do "
                    "not carry the arms axis yet (a ROADMAP follow-on); "
                    "the masked engine supports quarantine x arms")
            if self.level_placement == "slices":
                raise ValueError(
                    "arms need level_placement='span': the slices layout "
                    "dispatches each level to its own device rows, and "
                    "the arms axis would have to batch across disjoint "
                    "sub-meshes (a ROADMAP follow-on)")
            if cfg.get("client_store", "eager") == "stream":
                raise ValueError(
                    "arms need client_store='eager': the streaming cohort "
                    "pipeline stages ONE schedule's shards per superstep "
                    "(a ROADMAP follow-on)")
        if self.level_placement == "slices":
            # multi-process meshes take the host-aligned partition (ISSUE
            # 17): level boundaries snap to process boundaries, so every
            # level's rows land on disjoint hosts and the fused switch
            # branches stay uniform per device row
            self._slices, refusal = self._static_mesh_slices()
            if not self._slices:
                self._refuse_slices(refusal)

    def _refuse_slices(self, reason: str) -> None:
        """Loud span fallback (ISSUE 17 satellite): a configured slices
        placement that cannot be honoured names WHY -- a structured
        warning by default, a :class:`ValueError` under
        ``cfg['strict_placement']`` (operators pinning the pod layout want
        the dispatch refused, not silently reshaped)."""
        import json as _json
        import warnings

        detail = _json.dumps({"event": "slices-fallback", "reason": reason,
                              "clients_rows": int(self.mesh.shape["clients"]),
                              "processes": int(jax.process_count())},
                             sort_keys=True)
        if self.cfg.get("strict_placement"):
            raise ValueError(
                f"level_placement='slices' cannot be honoured and "
                f"strict_placement is set: {reason} ({detail})")
        warnings.warn(f"level_placement='slices' falling back to 'span': "
                      f"{reason} ({detail})")
        self.level_placement = "span"

    def _clients_row_chunks(self) -> Optional[List[Tuple[int, int]]]:
        """The contiguous clients-row chunks level boundaries may land on:
        single rows on a single-process mesh, whole per-process row blocks
        on a multi-process mesh (derived from the MESH devices'
        ``process_index`` -- the same signal
        ``staticcheck.wire.dcn_axes_of`` classifies DCN-eligible axes from,
        so AOT topology meshes get host-aligned chunks too).  ``None`` when
        no host-aligned partition exists: a clients row straddling
        processes, or a process owning non-contiguous row ranges.

        ``cfg['slice_align']`` (int n > 0) forces allocation units of
        ``C/n`` contiguous rows instead -- the single-process reference run
        emulating a pod's per-process blocks (the bitwise probe,
        :mod:`~.pod`).  The forced boundaries must contain every process
        boundary, so a forced unit never straddles hosts."""
        # staticcheck: allow(no-asarray): constructor-time mesh introspection
        devs = np.asarray(self.mesh.devices)
        C = devs.shape[0]
        row_proc = []
        for i in range(C):
            procs = {getattr(d, "process_index", 0)
                     for d in np.ravel(devs[i])}
            if len(procs) > 1:
                return None
            row_proc.append(next(iter(procs)))
        if len(set(row_proc)) <= 1:
            chunks = [(i, i + 1) for i in range(C)]
            proc_bounds = {C}  # one process: no internal boundaries
        else:
            chunks, lo = [], 0
            for i in range(1, C):
                if row_proc[i] != row_proc[i - 1]:
                    chunks.append((lo, i))
                    lo = i
            chunks.append((lo, C))
            if len({row_proc[c_lo] for c_lo, _ in chunks}) != len(chunks):
                return None  # a process owns non-contiguous row ranges
            proc_bounds = {hi for _, hi in chunks}
        align = int(self.cfg.get("slice_align") or 0)
        if align > 0:
            if C % align:
                return None
            unit = C // align
            forced = [(i * unit, (i + 1) * unit) for i in range(align)]
            if not proc_bounds <= {hi for _, hi in forced}:
                return None  # a forced unit would straddle a process block
            chunks = forced
        return chunks

    def _static_mesh_slices(self
                            ) -> Tuple[Dict[float, Tuple[int, int]], str]:
        """Allocate clients-axis device rows to levels once per experiment,
        in proportion to EXPECTED FLOP share: fix mode weights each level by
        its user count, dynamic mode by its sampling proportion, both times
        the level's analytic per-step training cost from
        :func:`~..fed.core.level_flop_table` (the one source of truth the
        staticcheck FLOP audit also checks ``cost_analysis()`` against --
        unlike the bare ``rate^2`` heuristic it keeps the non-quadratic
        terms: input-channel convs, norms, the width-independent data prep).
        Static allocation keeps program cache keys bound to fixed (lo, hi)
        device ranges -- per-round count fluctuation is absorbed by slot
        bucketing inside each slice.

        Allocation happens in units of :meth:`_clients_row_chunks` -- rows
        on one process, whole per-process row blocks on a pod (ISSUE 17)
        -- so every level boundary is host-aligned by construction.
        Returns ``(slices, refusal_reason)``: an empty dict plus the reason
        when no partition exists (the caller falls back to span LOUDLY)."""
        cfg = self.cfg
        level_rates = sorted(self.levels, reverse=True)
        if len(level_rates) <= 1:
            return {}, "a single level leaves nothing to slice"
        chunks = self._clients_row_chunks()
        if chunks is None:
            return {}, ("no host-aligned partition exists: a clients row "
                        "straddles process boundaries (or a process owns "
                        "non-contiguous rows) on this mesh")
        if len(chunks) < len(level_rates):
            unit = ("process-aligned row chunks" if jax.process_count() > 1
                    else "clients rows")
            return {}, (f"{len(chunks)} {unit} cannot host "
                        f"{len(level_rates)} levels (each level needs at "
                        f"least one)")
        if cfg["model_split_mode"] == "fix":
            vec = np.asarray(cfg["model_rate"], np.float64)  # staticcheck: allow(no-asarray): constructor-time config parse
            weights = [float((vec == r).sum()) for r in level_rates]  # staticcheck: allow(no-float-coercion): host config parse
        else:
            weights = [float(p) for p in cfg["proportion"]]  # staticcheck: allow(no-float-coercion): host config parse
            # cfg['model_rate'] lists the level table in dynamic mode, in
            # the same order as cfg['proportion']
            order = {float(r): i for i, r in enumerate(cfg["model_rate"])}  # staticcheck: allow(no-float-coercion): host config parse
            weights = [weights[order[r]] for r in level_rates]
        table = level_flop_table(cfg, level_rates)
        shares = np.array([w * table[r] for w, r in zip(weights, level_rates)],
                          np.float64)
        shares = np.maximum(shares, 1e-9)
        n_units = len(chunks)
        rows = np.maximum(1, np.floor(shares / shares.sum()
                                      * n_units)).astype(int)
        while rows.sum() > n_units:  # the >=1 floor can overshoot
            cand = int(np.argmax(np.where(rows > 1, rows, -1)))
            rows[cand] -= 1
        while rows.sum() < n_units:  # leftovers go to the most loaded level
            rows[int(np.argmax(shares / rows))] += 1
        out, ulo = {}, 0
        for r, n in zip(level_rates, rows):
            out[r] = (chunks[ulo][0], chunks[ulo + int(n) - 1][1])
            ulo += int(n)
        return out, ""

    # -- per-level codec layout (ISSUE 9 satellite) --------------------

    def _map_layout(self, params) -> Dict[str, Any]:
        """Per-level flat layout of the per-level codec map: each level's
        sliced :class:`~..ops.fused_update.FlatSpec` plus the LOSSY levels'
        offsets into one concatenated ``[2, total_lossy]`` error-feedback
        carry (row 1 is only written by ``topk``; the quantising codecs use
        row 0).  Cached by the global param shapes -- a trace-time
        constant, like the codec objects themselves."""
        shapes_key = tuple((k, tuple(v.shape))
                           for k, v in sorted(params.items()))
        if self._map_lay is not None and self._map_lay[0] == shapes_key:
            return self._map_lay[1]
        gm = self.global_model
        sds = {k: jax.ShapeDtypeStruct(tuple(v.shape), jnp.float32)
               for k, v in params.items()}
        specs, offsets, off = {}, {}, 0
        for rate in sorted(self.levels, reverse=True):
            wr = rate / self.global_rate
            sub = jax.eval_shape(
                lambda p, w=wr: extract_sliced_jnp(p, gm.specs, gm.groups, w),
                sds)
            spec_l = FlatSpec({k: tuple(v.shape) for k, v in sub.items()})
            specs[rate] = spec_l
            if self._codec_map[rate] != "dense":
                offsets[rate] = off
                off += spec_l.total
        lay = {"specs": specs, "offsets": offsets, "total_lossy": off}
        self._map_lay = (shapes_key, lay)
        return lay

    def _map_codec(self, rate: float, spec_l: FlatSpec,
                   participants: Optional[int] = None):
        """The (cached) codec object of one lossy level in the per-level
        map, over that level's sliced flat layout.  ``participants``: how
        many devices ENCODE this level's payload -- the whole clients axis
        under 'span' (default), the level's own slice rows under 'slices'
        (every other row ships the codec's identity payload, and the
        decode must attribute lane offsets/scales to the encoders only)."""
        if participants is None:
            participants = self.mesh.shape["clients"]
        key = (float(rate), spec_l.total, int(participants))  # staticcheck: allow(no-float-coercion): host cache key (rate is a python level)
        obj = self._map_codec_objs.get(key)
        if obj is None:
            obj = make_codec(self._codec_map[rate], spec_l,
                             participants, self._error_feedback)
            self._map_codec_objs[key] = obj
        return obj

    def _resid_shape(self, params):
        """Per-level codec maps carry ONE concatenated EF residual
        ``[n_dev, 2, total_lossy]`` (sharded over clients rows like the
        single-codec carry); everything else defers to
        :class:`~.round_engine._WireCodecCarry`."""
        if self._codec_map is None:
            return super()._resid_shape(params)
        return (self.mesh.shape["clients"], 2,
                self._map_layout(params)["total_lossy"])

    # -- per-level program ---------------------------------------------

    def _level_core(self, rate: float, params, key, lr, uarr, data,
                    n_data: int = 1, data_axis=None, local_data: bool = False,
                    epoch=None):
        """One level's per-device in-jit core (inside ``shard_map``): dense
        local training of this device's ``uarr`` slots at ``rate`` and the
        level's counted sums in SLICED shape.  NO collectives -- the callers
        reduce: the per-level program psums sliced then embeds once, the
        fused superstep embeds per device and joins a single global psum
        (zero-pad embedding commutes with the sum exactly, so both
        associations add the same addends elementwise).

        ``local_data=True`` (ISSUE 6 streaming): ``data`` is already in
        slot order -- row j IS slot j's shard -- so no gather; ``uarr``
        still carries the GLOBAL user ids for the PRNG streams and slot
        validity."""
        gm = self.global_model
        model_l, eng_l = self.levels[rate]
        wr = rate / self.global_rate  # static for this core
        lm_all = data[-1]
        valid = (uarr >= 0).astype(jnp.float32)
        ugid = jnp.maximum(uarr, 0)
        if self.failure_rate > 0.0:
            # same crash model + PRNG stream as the masked engine
            fkey = failure_stream_key(key)
            alive = 1.0 - jax.vmap(
                lambda u: jax.random.bernoulli(
                    jax.random.fold_in(fkey, u), self.failure_rate)
            )(ugid).astype(jnp.float32)
            valid = valid * alive
        sub = extract_sliced_jnp(params, gm.specs, gm.groups, wr)
        slot_keys = client_stream_keys(key, ugid)
        lm = lm_all if local_data else lm_all[ugid]
        if self.is_lm:
            rows = data[0] if local_data else data[0][ugid]
            if self._sched_spec.has_deadline:
                # deadline stragglers (ISSUE 9): the masked engine's exact
                # per-client budget draw (same round key + global uid, same
                # static E x S total) -- per-level masks, engine-invariant
                total_steps = eng_l.local_epochs * _ceil_div(
                    int(rows.shape[-1]), eng_l.bptt)
                limits = deadline_steps(key, ugid, total_steps,
                                        self._sched_spec.deadline_min_frac)
                trained, ms = jax.vmap(
                    lambda r_, l_, k_, lim_: eng_l._local_train_lm(
                        sub, 1.0, r_, l_, k_, lr, scaler_rate=wr,
                        data_axis=data_axis, n_data=n_data, step_limit=lim_)
                )(rows, lm, slot_keys, limits)
            else:
                trained, ms = jax.vmap(
                    lambda r_, l_, k_: eng_l._local_train_lm(
                        sub, 1.0, r_, l_, k_, lr, scaler_rate=wr,
                        data_axis=data_axis, n_data=n_data)
                )(rows, lm, slot_keys)
        else:
            xs, ys, sms = (data[0], data[1], data[2]) if local_data \
                else (data[0][ugid], data[1][ugid], data[2][ugid])
            if self._sched_spec.has_deadline:
                total_steps = eng_l.local_epochs * _ceil_div(
                    int(xs.shape[1]), eng_l.batch_size)
                limits = deadline_steps(key, ugid, total_steps,
                                        self._sched_spec.deadline_min_frac)
                trained, ms = jax.vmap(
                    lambda x_, y_, m_, l_, k_, lim_: eng_l._local_train_vision(
                        sub, 1.0, x_, y_, m_, l_, k_, lr, scaler_rate=wr,
                        data_axis=data_axis, n_data=n_data, step_limit=lim_)
                )(xs, ys, sms, lm, slot_keys, limits)
            else:
                trained, ms = jax.vmap(
                    lambda x_, y_, m_, l_, k_: eng_l._local_train_vision(
                        sub, 1.0, x_, y_, m_, l_, k_, lr, scaler_rate=wr,
                        data_axis=data_axis, n_data=n_data)
                )(xs, ys, sms, lm, slot_keys)
        if self._poison is not None:
            # chaos NaN poison (ISSUE 15): same (round, uid) table and
            # injection point as the masked engine -- the update goes
            # non-finite after local training, before aggregation
            if epoch is None:
                raise ValueError(
                    "chaos_poison with the grouped strategy needs the "
                    "fused superstep (superstep_rounds > 1 or client_store"
                    "='stream'): the K=1 host-orchestrated path does not "
                    "thread the round epoch into its level programs")
            from ..chaos.inject import poison_updates

            trained = poison_updates(trained, self._poison, epoch, uarr)
        # counted sums in SLICED shape (within the slice the width mask is
        # all-ones by construction; only the label-split restriction remains)
        sub_shapes = {k: v.shape for k, v in sub.items()}
        cms = jax.vmap(lambda l_, v_: jax.tree_util.tree_map(
            lambda m: m * v_,
            make_count_masks(sub_shapes, model_l.specs, model_l.groups, 1.0, l_)))(
            lm, valid)
        ok = None
        if self._quarantine.enabled:
            # client-update quarantine (ISSUE 15): gate this level's slots
            # on finiteness (+ optional masked update norm vs the sliced
            # sub-model) and fold into sums AND counts before the embed /
            # single global psum -- zero-count participants, exactly the
            # masked engine's semantics at sliced shape
            from ..obs.probes import quarantine_gate

            ok = quarantine_gate(trained, sub, cms,
                                 self._quarantine.max_norm)
            okf = ok.astype(jnp.float32)
            cms = {k: cms[k] * okf.reshape((-1,) + (1,) * (cms[k].ndim - 1))
                   for k in cms}
            trained = {k: jnp.where(ok.reshape((-1,) + (1,) * (v.ndim - 1)),
                                    v, jnp.zeros((), v.dtype))
                       for k, v in trained.items()}
        sum_l = {k: jnp.sum(trained[k] * cms[k], axis=0) for k in sub}
        cnt_l = {k: jnp.sum(cms[k], axis=0) for k in sub}
        if ok is not None:
            okf = ok.astype(jnp.float32)
            ms = {k: jnp.where(ok, v, jnp.zeros((), v.dtype)) * valid
                  for k, v in ms.items()}
            ms["rate"] = jnp.full(uarr.shape, rate, jnp.float32) * valid * okf
            ms["obs_quarantine"] = jnp.reshape(
                jnp.sum(valid * (1.0 - okf)), (1,))
        else:
            ms = {k: v * valid for k, v in ms.items()}
            ms["rate"] = jnp.full(uarr.shape, rate, jnp.float32) * valid
        return sum_l, cnt_l, ms

    def _level_prog(self, rate: float, slots: int, sub_mesh=None,
                    slice_range=None):
        """Jitted shard_map for one (rate level, slot count): dense local
        training of ``slots`` clients (sharded over the clients axis) and the
        level's counted-sum partial, embedded to global shape.  With
        ``sub_mesh`` the program spans only that fixed device slice
        (level_placement='slices'; ``slice_range`` is its (lo, hi) row range
        and keys the cache so a program can never run on a stale slice)."""
        mesh = sub_mesh if sub_mesh is not None else self.mesh
        key_ = (rate, slots, slice_range)
        if key_ in self._level_progs:
            return self._level_progs[key_]
        gm = self.global_model
        wr = rate / self.global_rate  # static for this program
        n_data = mesh.shape["data"]
        data_axis = "data" if n_data > 1 else None

        def body(params, key, lr, uarr, *data):
            sum_l, cnt_l, ms = self._level_core(rate, params, key, lr, uarr,
                                                data, n_data, data_axis)
            # ONE psum bind for the level's sums+counts (bit-compatible with
            # two binds; staticcheck audits the one-collective budget)
            sum_l, cnt_l = jax.lax.psum((sum_l, cnt_l), "clients")
            sum_l = embed_sliced_jnp(sum_l, gm.specs, gm.groups, wr)
            cnt_l = embed_sliced_jnp(cnt_l, gm.specs, gm.groups, wr)
            return sum_l, cnt_l, ms

        data_specs = (P(), P()) if self.is_lm else (P(), P(), P(), P())
        fn = _shard_map(
            body, mesh,
            in_specs=(P(), P(), P(), P("clients")) + data_specs,
            out_specs=(P(), P(), P("clients")),
        )
        # Donation: in slices mode the params arg is this level's PRIVATE
        # broadcast copy (device_put per round in train_round), so donating
        # it releases the buffers the moment the level program consumes them.
        # In span mode the SAME global params feed every level program and
        # the combine -- donation there would invalidate shared buffers.
        prog = jax.jit(fn, donate_argnums=(0,) if sub_mesh is not None else ())
        self._level_progs[key_] = prog
        return prog

    def _combine_prog(self, n_levels: int):
        """Jitted merge of ``n_levels`` level partials into the new globals.

        Donates ONLY the old globals (arg 0): the outputs are exactly one
        params-tree, so every donated leaf is consumed by aliasing.  Donating
        the sums/cnts lists too left 2x``n_levels`` param-trees of donors
        with nothing to alias -- the "donated buffers were not usable"
        warning the test gate now promotes to an error; those intermediates
        are released by normal refcounting the moment the merge consumes
        them."""
        if n_levels in self._combine_progs:
            return self._combine_progs[n_levels]

        def merge(params, sums, cnts):
            summed = jax.tree_util.tree_map(lambda *xs: sum(xs), *sums)
            counts = jax.tree_util.tree_map(lambda *xs: sum(xs), *cnts)
            return combine_counted(params, summed, counts)

        prog = jax.jit(merge, donate_argnums=(0,))
        self._combine_progs[n_levels] = prog
        return prog

    def program_cache_size(self) -> int:
        """Total compiled specializations across this engine's programs
        (per-level + combine + fused superstep); see
        :meth:`~.round_engine.RoundEngine.program_cache_size`."""
        progs = list(self._level_progs.values()) \
            + list(self._combine_progs.values()) \
            + list(self._superstep_progs.values())
        return sum(p._cache_size() for p in progs)

    # -- host wrapper ---------------------------------------------------

    def train_round(self, global_params: Dict[str, Any], user_idx: np.ndarray,
                    rates: np.ndarray, data: Tuple, lr: float, key,
                    timer: PhaseTimer = None, async_metrics: bool = False):
        """One round.  ``data`` is the replicated stacked tuple the masked
        engine takes; ``rates`` are the active users' absolute rates (host
        side, same PRNG stream as the masked engine's in-jit draw).

        Steady state moves zero host data: the data stacks (and in slices
        mode every per-level operand) are committed to their (sub-)meshes
        once by the :class:`~.staging.PlacementCache`; per-round values --
        slot ids, the params broadcast -- use explicit ``device_put`` only.
        ``timer`` accounts the stage/dispatch/fetch phases.  With
        ``async_metrics=True`` the per-slot metric sums stay on device and a
        :class:`~.staging.PendingMetrics` is returned in their place, so the
        caller can overlap the D2H fetch with the next round's dispatch."""
        if self._arms_spec is not None:
            raise ValueError(
                "arms need the fused grouped superstep (train_superstep): "
                "the K=1 host-orchestrated path dispatches L+1 programs "
                "per round, which the arms axis would fork per arm")
        if self._codec_name != "dense":
            raise ValueError(
                f"wire_codec={self._codec_name!r} needs the fused grouped "
                f"superstep (set superstep_rounds > 1 or client_store="
                f"'stream'): the K=1 host-orchestrated path reduces per "
                f"level and has no single global psum to compress")
        if self._sched_spec.buffered:
            raise ValueError(
                "schedule aggregation='buffered' needs the fused grouped "
                "superstep (set superstep_rounds > 1 or client_store="
                "'stream'): the K=1 host-orchestrated path combines in its "
                "own program and has no scan carry to buffer")
        if self._obs_on:
            raise ValueError(
                "telemetry='on' with the grouped strategy needs the fused "
                "superstep (set superstep_rounds > 1 or client_store="
                "'stream'): the K=1 path splits the round across L+1 "
                "host-orchestrated programs with no shared round core to "
                "probe")
        if self.level_placement == "slices" and jax.process_count() > 1:
            raise ValueError(
                "level_placement='slices' on a multi-process mesh needs "
                "the fused superstep (set superstep_rounds > 1 or "
                "client_store='stream'): the K=1 host-orchestrated path "
                "dispatches each level onto its own sub-mesh, and a "
                "process with no devices in a level's slice cannot join "
                "that dispatch -- the fused program runs every level on "
                "the FULL mesh behind one lax.switch")
        timer = timer if timer is not None else PhaseTimer()
        n_dev = self.mesh.shape["clients"]
        with timer.phase("stage"):
            # staticcheck: allow(no-asarray): host slot-id normalization; the
            # ids reach the mesh via explicit staging.put only
            user_idx = np.asarray(user_idx, np.int32)
            # snap to the level table: float32-round-tripped or non-dyadic
            # rates either match a level or raise here, at staging -- never
            # a KeyError mid-round (ADVICE r5 item 2)
            rates = snap_to_levels(rates, self.levels)
            by_level: Dict[float, List[int]] = {}
            for pos, r in enumerate(rates):
                by_level.setdefault(float(r), []).append(pos)  # staticcheck: allow(no-float-coercion): host np scalar -> dict key
            level_order = sorted(by_level, reverse=True)
            sliced_mode = self.level_placement == "slices"
            lr_full = self._staging.scalar(lr)
            # commit the globals once: an uncommitted init tree would give
            # every level program AND the combine a second specialization on
            # round 2, when the combined outputs come back mesh-committed
            # (staticcheck recompile audit); layout pinned by the same policy
            global_params = self._staging.commit(self._pin(global_params))

        sums, cnts, ms_levels, positions = [], [], [], []
        for rate in level_order:
            pos = by_level[rate]
            with timer.phase("stage"):
                if sliced_mode:
                    srange = self._slices[rate]
                    sub = self._staging.submesh(*srange)
                    n_dev_l = srange[1] - srange[0]
                    lr_l = self._staging.scalar(lr, srange)
                    key_l = self._staging.put(key, srange)
                else:
                    sub, n_dev_l, srange = None, n_dev, None
                    lr_l, key_l = lr_full, key
                # the level's data stacks: committed to its (sub-)mesh once,
                # keyed by the static (lo, hi) range; per-round lookups are
                # identity hits returning device-resident buffers
                args = self._staging.replicated("train_data", data, srange=srange)
                slots = _bucket_pow2(_ceil_div(len(pos), n_dev_l)) * n_dev_l
                u = self._packer.buffer((rate, slots), (slots,))
                u[: len(pos)] = user_idx[pos]
                uarr = self._staging.put(u, srange, P("clients"))
            with timer.phase("dispatch"):
                if sliced_mode:
                    # params broadcast onto this level's fixed slice (jitted
                    # ICI replicate-copy with PRIVATE buffers -- see
                    # PlacementCache.broadcast); the level program donates the
                    # copy, releasing it the moment it is consumed.
                    # Dispatches to disjoint devices overlap in time.
                    p_in = self._staging.broadcast(global_params, srange)
                else:
                    p_in = global_params
                sum_l, cnt_l, ms = self._level_prog(rate, slots, sub, srange)(
                    p_in, key_l, lr_l, uarr, *args)
                if sliced_mode:
                    # bring the level partials back onto the full mesh so the
                    # combine program sees co-located inputs
                    sum_l = self._staging.put(sum_l)
                    cnt_l = self._staging.put(cnt_l)
            sums.append(sum_l)
            cnts.append(cnt_l)
            ms_levels.append(ms)
            positions.append(pos)
        with timer.phase("dispatch"):
            if sliced_mode:
                global_params = self._staging.put(global_params)
            new_params = self._combine_prog(len(sums))(global_params, sums, cnts)

        n_slots = len(user_idx)

        def _assemble(host_levels):
            metrics = {k: np.zeros(n_slots, np.float32)
                       for k in ("loss_sum", "score_sum", "n", "rate")}
            for pos, ms in zip(positions, host_levels):
                for k in metrics:
                    metrics[k][pos] = ms[k][: len(pos)]
            if host_levels and "obs_quarantine" in host_levels[0]:
                # quarantine counter (ISSUE 15): per-device partials of
                # every level concatenate; the driver's split_probes sums
                # them into the round's quarantined-client count
                metrics["obs_quarantine"] = np.concatenate(
                    [ms["obs_quarantine"] for ms in host_levels])
            return metrics

        pending = PendingMetrics(ms_levels, assemble=_assemble)
        if async_metrics:
            return new_params, pending
        with timer.phase("fetch"):
            return new_params, pending.fetch()

    # -- fused superstep ------------------------------------------------

    def _hist_total_steps(self, x) -> int:
        """Static per-client local-step total from a data-stack aval (the
        deadline-budget denominator of the step-fraction histogram, ISSUE
        12).  Shard shapes are level-invariant, so one number serves every
        level: vision stacks end ``[..., n, H, W, C]``, LM rows ``[...,
        T]`` -- eager population stacks and streaming cohort xs alike."""
        eng0 = next(iter(self.levels.values()))[1]
        if self.is_lm:
            return eng0.local_epochs * _ceil_div(int(x.shape[-1]), eng0.bptt)
        return eng0.local_epochs * _ceil_div(int(x.shape[-4]),
                                             eng0.batch_size)

    def _fused_layout(self):
        """(mode, level boundary table) of the fused round: 'slices'
        whenever the static row partition exists, else 'span'.

        A data axis no longer refuses slices mode (ISSUE 17): the branch
        index is a function of ``axis_index("clients")`` alone, so every
        device sharing a clients row -- the participant set of every
        data-axis collective inside a branch -- takes the SAME branch.
        Each collective's replica groups are therefore uniform (a group
        either enters its level's branch together or skips it together),
        which is the only uniformity XLA's grouped collectives need."""
        if self.level_placement == "slices" and self._slices:
            return "slices", [self._slices[r][0] for r in sorted(self._slices, reverse=True)]
        return "span", None

    def _superstep_prog(self, k: int, per_dev: int, mode: str, eval_mask=None,
                        fused_eval=None, lr_arg: bool = False,
                        streaming: bool = False, arms: int = 0):
        """ONE jitted+donated ``shard_map`` program for ``k`` grouped rounds:
        the five per-level programs AND the combine fused into a single XLA
        program, wrapped in a ``lax.scan`` over the rounds (ISSUE 2).

        ``mode='span'``: every device runs every level back-to-back (a
        static python loop over the level table inside the scan body).
        ``mode='slices'``: each device row runs ONLY its level's branch
        (``lax.switch`` on the row's static slice assignment) -- the levels
        execute concurrently because XLA schedules disjoint device groups,
        not because the host dispatched them asynchronously.  Either way the
        level partials are embedded to global shape per device, ONE global
        psum joins them, and the counted-average combine runs in-program --
        aggregation state never exists outside the program.

        ``per_dev`` is the UNIFORM per-device-per-level slot count (one
        count for all levels, bucketed by the caller), so the compile space
        stays O(k-shapes x log A) -- a per-level-count key would recompile
        combinatorially as the sampled mix varies.

        ``eval_mask`` + ``fused_eval`` (ISSUE 4): on scan steps where the
        static mask fires, the :class:`~.evaluation.FusedEval` core runs the
        sBN+Local/Global eval phase on the freshly-combined globals INSIDE
        this program (outside the slices-mode ``lax.switch``, so the eval
        collectives stay uniform across devices); the per-training-round
        single-psum invariant is untouched and the eval phase's reductions
        are audited separately.  ``lr_arg``: LR as a staged scalar instead
        of the traced schedule (ReduceLROnPlateau superstep mode).

        ``streaming=True`` (ISSUE 6): the replicated population stacks are
        replaced by the sampled cohort's shards riding the scan xs in the
        SAME slot layout as the schedule (span: ``[k, L, slots, ...]``,
        slices: ``[k, slots, ...]``, slot axis sharded over ``clients``);
        each level's core then indexes identity -- program memory is
        O(k x levels x slots), independent of the population."""
        from .round_engine import (_ArmsFusedEval, eval_fused_scan,
                                   superstep_eval_groups)

        key_ = (k, per_dev, mode, eval_mask, lr_arg, streaming, arms)
        if key_ in self._superstep_progs:
            return self._superstep_progs[key_]
        gm = self.global_model
        mesh = self.mesh
        n_data = mesh.shape["data"]
        data_axis = "data" if n_data > 1 else None
        level_rates = sorted(self.levels, reverse=True)
        lr_fn = self._lr_fn
        groups = superstep_eval_groups(eval_mask) if eval_mask else None
        if groups is not None and not any(ev for _, ev, _ in groups):
            groups = None
        if groups is not None and arms:
            # arms multiplexer (ISSUE 14): the fused eval phase runs vmapped
            # over the arms axis against the shared committed operands
            fused_eval = _ArmsFusedEval(fused_eval, arms)

        def embed(tree, rate):
            return embed_sliced_jnp(tree, gm.specs, gm.groups, rate / self.global_rate)

        if mode == "slices":
            # np (not jnp): an eager jnp array here would be an implicit H2D
            # whenever a fresh slot bucket triggers a rebuild inside a
            # transfer-guarded steady state; as an np closure constant it
            # enters the program at trace time instead
            # staticcheck: allow(no-asarray): trace-time closure constant
            level_los = np.asarray([self._slices[r][0] for r in level_rates],
                                   np.int32)

        n_data_args = 2 if self.is_lm else 4
        codec = self._codec_name != "dense"
        per_level = self._codec_map is not None
        buffered = self._sched_spec.buffered
        # per-device max contributing clients: the span layout runs every
        # level's slots on every device, the slices layout one level's --
        # this bounds the partial-sum magnitude the codec's grid must cover
        cmax = (len(level_rates) if mode == "span" else 1) * per_dev

        def sbody(params, *all_rest):
            if codec:
                resid0, base_key, epoch0, *rest = all_rest
            elif buffered:
                buf0, base_key, epoch0, *rest = all_rest
            else:
                base_key, epoch0, *rest = all_rest
            idx = 0
            ascales = None
            if lr_arg:
                # under arms this is the staged PER-ARM LR vector [E]
                lr_const = rest[0]
                idx = 1
            elif arms:
                # per-arm multiplicative scales over the shared schedule
                ascales = rest[0]
                idx = 1
            sched = rest[idx]
            if streaming:
                sdata = rest[idx + 1:idx + 1 + n_data_args]
                eval_ops = rest[idx + 1 + n_data_args:]
                data = None
            else:
                data = rest[idx + 1:idx + 1 + n_data_args]
                eval_ops = rest[idx + 1 + n_data_args:]

            def attach_probes(ms_, p_old, new_p_, tot_s_, tot_c_, nr_=None,
                              nb_=None, uids_=None, key_=None, ts_=None):
                """Fold the in-program health probes into the metrics tree
                (ISSUE 10): post-psum aggregates + the combined globals,
                zero new collectives.  Identity under telemetry='off'.
                ``uids_``/``key_``/``ts_`` (ISSUE 12): the slot-uid rows,
                round key and static step total the cohort histograms
                re-derive the deadline budgets from (telemetry='hist')."""
                if not self._obs_on:
                    return ms_
                pr = round_probes(self._obs_levels, p_old, new_p_, tot_s_,
                                  tot_c_, ms_["rate"], resid=nr_,
                                  sched_buf=nb_)
                if self._obs_hist:
                    # cohort histograms (ISSUE 12): fixed-bucket rows over
                    # this device's slots of every level it runs -- same
                    # zero-collective contract as the scalar probes
                    pr = {**pr, **round_hists(
                        self._obs_levels, ms_["rate"], ms_["loss_sum"],
                        ms_["n"], key=key_, uids=uids_, total_steps=ts_,
                        min_frac=(self._sched_spec.deadline_min_frac
                                  if self._sched_spec.has_deadline
                                  else None), sched_buf=nb_)}
                if mode == "span":
                    # span metric leaves are [L, slots]: rank-pad the probe
                    # rows so the one broadcast out-spec covers the tree
                    pr = {n: v[:, None] for n, v in pr.items()}
                return {**ms_, **pr}

            def step(carry, xs):
                if codec:
                    p, rs, sb = carry[0], carry[1], None
                elif buffered:
                    p, rs, sb = carry[0], None, carry[1]
                else:
                    p, rs, sb = carry, None, None
                if streaming:
                    t, srow, *d = xs
                else:
                    t, srow = xs
                if arms:
                    # arms multiplexer (ISSUE 14): the whole span round --
                    # every level core, the embeds, the SINGLE global psum
                    # and the counted-average combine -- vmapped over the
                    # leading arms axis of the params carry.  The host
                    # schedule (level-grouped slots) is SHARED across arms
                    # (level membership is slot bookkeeping, one layout for
                    # all); per-arm streams drive the client/slot keys,
                    # deadline budgets and failure draws, so arm e is a
                    # solo grouped run with seed e on the same schedule,
                    # bitwise.  The batched psum stays ONE bind; wire
                    # bytes and FLOPs scale linearly in E (staticcheck
                    # arms variants audit both by equality).
                    scales = lr_const if lr_arg else ascales

                    def arm_core(p_e, akey, sc_e):
                        key_e = jax.random.fold_in(akey, t)
                        lr_e = sc_e if lr_arg else lr_fn(t) * sc_e
                        tot_se = tot_ce = None
                        ms_lv = []
                        for li, rate in enumerate(level_rates):
                            s_l, c_l, ms_l = self._level_core(
                                rate, p_e, key_e, lr_e, srow[li], data,
                                n_data, data_axis, epoch=t)
                            s_l, c_l = embed(s_l, rate), embed(c_l, rate)
                            tot_se = s_l if tot_se is None else \
                                {n: tot_se[n] + s_l[n] for n in tot_se}
                            tot_ce = c_l if tot_ce is None else \
                                {n: tot_ce[n] + c_l[n] for n in tot_ce}
                            ms_lv.append(ms_l)
                        ms_e = {n: jnp.stack([m[n] for m in ms_lv])
                                for n in ms_lv[0]}
                        tot_se, tot_ce = jax.lax.psum((tot_se, tot_ce),
                                                      "clients")
                        return combine_counted(p_e, tot_se, tot_ce), ms_e

                    return jax.vmap(arm_core)(p, base_key, scales)
                key = jax.random.fold_in(base_key, t)
                lr = lr_const if lr_arg else lr_fn(t)
                hist_ts = None
                if self._obs_hist and self._sched_spec.has_deadline:
                    # the step-fraction histogram's static denominator
                    # (ISSUE 12) -- from the data aval, level-invariant
                    hist_ts = self._hist_total_steps(d[0] if streaming
                                                     else data[0])
                if per_level and mode == "slices":
                    # per-level codec map x slices layout (ISSUE 14
                    # satellite, retiring the PR 9 refusal): each device
                    # row runs ONLY its level's switch branch, yet every
                    # branch emits EVERY level's payload structure -- its
                    # own level's encoded partial plus the other levels'
                    # IDENTITY payloads (codec.zero_payload, all-zero
                    # lanes).  Each level's codec counts its slice's rows
                    # as participants, so the shared decode attributes
                    # lane bias/scale sums to exactly the rows that
                    # encoded.  Still ONE global psum bind carrying the
                    # per-level payload tree -- the same wire budget as
                    # the span map (fed.core.level_codec_map_byte_table,
                    # priced by equality in staticcheck).
                    lay = self._map_layout(p)
                    row = jax.lax.axis_index("clients")
                    branch = jnp.sum(row >= level_los) - 1
                    rows_of = {r_: self._slices[r_][1] - self._slices[r_][0]
                               for r_ in level_rates}

                    def zero_tree(rate_z):
                        spec_z = lay["specs"][rate_z]
                        if self._codec_map[rate_z] == "dense":
                            return (jnp.zeros(spec_z.total, jnp.float32),
                                    jnp.zeros(spec_z.total, jnp.float32))
                        return self._map_codec(
                            rate_z, spec_z, rows_of[rate_z]).zero_payload()

                    def mk_pl(rate_own):
                        def f(p_, key_l, lr_l, u_, rs_):
                            s_l, c_l, ms_l = self._level_core(
                                rate_own, p_, key_l, lr_l, u_,
                                tuple(d) if streaming else data, n_data,
                                data_axis, local_data=streaming, epoch=t)
                            spec_o = lay["specs"][rate_own]
                            sf, cf = spec_o.flatten(s_l), spec_o.flatten(c_l)
                            payload = {f"L{lz}": zero_tree(rz)
                                       for lz, rz in enumerate(level_rates)
                                       if rz != rate_own}
                            li_own = level_rates.index(rate_own)
                            if self._codec_map[rate_own] == "dense":
                                payload[f"L{li_own}"] = (sf, cf)
                                nr_own = rs_
                            else:
                                cobj = self._map_codec(rate_own, spec_o,
                                                       rows_of[rate_own])
                                off = lay["offsets"][rate_own]
                                rs_l = jax.lax.dynamic_slice(
                                    rs_, (0, off),
                                    (2, spec_o.total))[:cobj.resid_slots]
                                sub_o = extract_sliced_jnp(
                                    p_, gm.specs, gm.groups,
                                    rate_own / self.global_rate)
                                pl, nr_l = cobj.encode(sf, cf, rs_l, sub_o,
                                                       key_l, per_dev)
                                payload[f"L{li_own}"] = pl
                                nr_own = jax.lax.dynamic_update_slice(
                                    rs_, nr_l, (0, off))
                            return payload, nr_own, ms_l
                        return f

                    payload, nr, ms = jax.lax.switch(
                        branch, [mk_pl(r_) for r_ in level_rates], p, key,
                        lr, srow, rs)
                    # THE single global psum: one bind joins every level's
                    # payload across the whole clients axis
                    agg = jax.lax.psum(payload, "clients")
                    tot_s = tot_c = None
                    for li, rate in enumerate(level_rates):
                        spec_l = lay["specs"][rate]
                        if self._codec_map[rate] == "dense":
                            sf, cf = agg[f"L{li}"]
                        else:
                            cobj = self._map_codec(rate, spec_l,
                                                   rows_of[rate])
                            sub_l = extract_sliced_jnp(
                                p, gm.specs, gm.groups,
                                rate / self.global_rate)
                            sf, cf = cobj.decode(agg[f"L{li}"], sub_l, key,
                                                 per_dev)
                        s_e = embed(spec_l.unflatten(sf), rate)
                        c_e = embed(spec_l.unflatten(cf), rate)
                        tot_s = s_e if tot_s is None else \
                            {n: tot_s[n] + s_e[n] for n in tot_s}
                        tot_c = c_e if tot_c is None else \
                            {n: tot_c[n] + c_e[n] for n in tot_c}
                    new_p = combine_counted(p, tot_s, tot_c)
                    ms = attach_probes(ms, p, new_p, tot_s, tot_c, nr_=nr,
                                       uids_=srow, key_=key, ts_=hist_ts)
                    return (new_p, nr), ms
                if per_level:
                    # per-level codec selection (ISSUE 9 satellite): each
                    # level's SLICED counted sums join the round's ONE psum
                    # bind under that level's own codec -- dense levels ship
                    # raw f32 at sliced shape, lossy levels their packed
                    # lanes, and the EF residuals of the lossy levels
                    # concatenate into one [2, total_lossy] carry (span
                    # layout; the slices layout branches above).
                    lay = self._map_layout(p)
                    payload, ms_levels, dec = {}, [], {}
                    for li, rate in enumerate(level_rates):
                        d_li = tuple(x[li] for x in d) if streaming else data
                        s_l, c_l, ms_l = self._level_core(
                            rate, p, key, lr, srow[li], d_li, n_data,
                            data_axis, local_data=streaming, epoch=t)
                        ms_levels.append(ms_l)
                        spec_l = lay["specs"][rate]
                        sf, cf = spec_l.flatten(s_l), spec_l.flatten(c_l)
                        if self._codec_map[rate] == "dense":
                            payload[f"L{li}"] = (sf, cf)
                            continue
                        cobj = self._map_codec(rate, spec_l)
                        off = lay["offsets"][rate]
                        rs_l = jax.lax.dynamic_slice(
                            rs, (0, off),
                            (2, spec_l.total))[:cobj.resid_slots]
                        sub_l = extract_sliced_jnp(
                            p, gm.specs, gm.groups, rate / self.global_rate)
                        pl, nr_l = cobj.encode(sf, cf, rs_l, sub_l, key,
                                               per_dev)
                        payload[f"L{li}"] = pl
                        dec[li] = (cobj, sub_l, nr_l, off)
                    ms = {n: jnp.stack([m[n] for m in ms_levels])
                          for n in ms_levels[0]}
                    # THE single global psum: one bind joins every level's
                    # payload (a pytree psum is one bind; staticcheck holds
                    # the summed operand bytes to the per-level-map budget)
                    agg = jax.lax.psum(payload, "clients")
                    tot_s = tot_c = None
                    nr = jnp.zeros_like(rs)
                    for li, rate in enumerate(level_rates):
                        spec_l = lay["specs"][rate]
                        if li in dec:
                            cobj, sub_l, nr_l, off = dec[li]
                            sf, cf = cobj.decode(agg[f"L{li}"], sub_l, key,
                                                 per_dev)
                            nr = jax.lax.dynamic_update_slice(nr, nr_l,
                                                              (0, off))
                        else:
                            sf, cf = agg[f"L{li}"]
                        s_e = embed(spec_l.unflatten(sf), rate)
                        c_e = embed(spec_l.unflatten(cf), rate)
                        tot_s = s_e if tot_s is None else \
                            {n: tot_s[n] + s_e[n] for n in tot_s}
                        tot_c = c_e if tot_c is None else \
                            {n: tot_c[n] + c_e[n] for n in tot_c}
                    new_p = combine_counted(p, tot_s, tot_c)
                    ms = attach_probes(ms, p, new_p, tot_s, tot_c, nr_=nr,
                                       uids_=srow, key_=key, ts_=hist_ts)
                    return (new_p, nr), ms
                if mode == "span":
                    # srow: [L, per_dev] -- this device's slots of EVERY level
                    tot_s = tot_c = None
                    ms_levels = []
                    for li, rate in enumerate(level_rates):
                        d_li = tuple(x[li] for x in d) if streaming else data
                        s_l, c_l, ms_l = self._level_core(
                            rate, p, key, lr, srow[li], d_li, n_data,
                            data_axis, local_data=streaming, epoch=t)
                        s_l, c_l = embed(s_l, rate), embed(c_l, rate)
                        tot_s = s_l if tot_s is None else \
                            {n: tot_s[n] + s_l[n] for n in tot_s}
                        tot_c = c_l if tot_c is None else \
                            {n: tot_c[n] + c_l[n] for n in tot_c}
                        ms_levels.append(ms_l)
                    ms = {n: jnp.stack([m[n] for m in ms_levels])
                          for n in ms_levels[0]}
                else:
                    # srow: [per_dev] -- this device's slots of ITS OWN level
                    row = jax.lax.axis_index("clients")
                    branch = jnp.sum(row >= level_los) - 1

                    def mk(rate):
                        def f(p_, key_l, lr_l, u_):
                            # n_data/data_axis pass through (ISSUE 17): the
                            # data-axis collectives inside this branch are
                            # uniform per clients row -- every participant
                            # of a row's "data" group takes the same branch
                            s, c, m = self._level_core(
                                rate, p_, key_l, lr_l, u_,
                                tuple(d) if streaming else data, n_data,
                                data_axis, local_data=streaming, epoch=t)
                            return embed(s, rate), embed(c, rate), m
                        return f

                    tot_s, tot_c, ms = jax.lax.switch(
                        branch, [mk(r) for r in level_rates], p, key, lr, srow)
                if codec:
                    # wire codec (ISSUE 8): the SAME single bind carries the
                    # packed compressed payload of the embedded level
                    # partials; EF residual re-injected next round
                    from ..compress.codecs import compressed_psum

                    tot_s, tot_c, nr = compressed_psum(
                        self._codec(p), "clients", p, tot_s, tot_c, rs, key,
                        cmax)
                else:
                    # THE single global psum of the fused round (the PR 2
                    # invariant, audited by staticcheck): one bind joins the
                    # level sums AND counts across the whole clients axis
                    tot_s, tot_c = jax.lax.psum((tot_s, tot_c), "clients")
                if buffered:
                    # buffered-async aggregation (ISSUE 9): this round's
                    # reduction lands NEXT round, staleness-weighted; the
                    # previous round's buffered update applies now
                    new_p, nb = buffered_combine(p, sb, tot_s, tot_c,
                                                 FlatSpec.of(p),
                                                 self._sched_spec.staleness)
                    ms = attach_probes(ms, p, new_p, tot_s, tot_c, nb_=nb,
                                       uids_=srow, key_=key, ts_=hist_ts)
                    return (new_p, nb), ms
                new_p = combine_counted(p, tot_s, tot_c)
                ms = attach_probes(ms, p, new_p, tot_s, tot_c,
                                   nr_=nr if codec else None,
                                   uids_=srow, key_=key, ts_=hist_ts)
                return ((new_p, nr) if codec else new_p), ms

            epochs = epoch0 + jnp.arange(k, dtype=jnp.int32)
            xs = (epochs, sched) + (tuple(sdata) if streaming else ())
            if codec:
                carry0 = (params, resid0[0])
            elif buffered:
                carry0 = (params, buf0)
            else:
                carry0 = params

            def unpack(carry):
                if codec:
                    return carry[0], (carry[1][None],)
                if buffered:
                    return carry[0], (carry[1],)
                return carry, ()

            if groups is None:
                carry, ms = jax.lax.scan(step, carry0, xs)
                p_out, extra = unpack(carry)
                return (p_out,) + extra + (ms,)
            # eval runs on the combined globals AFTER the round(s) it
            # follows, outside the slices-mode switch; the shared walk keeps
            # it at the program's top level (bit-identical-to-host contract)
            carry, ms, ev = eval_fused_scan(
                step, carry0, xs, epochs, groups, fused_eval, eval_ops,
                params_of=(lambda c: c[0]) if (codec or buffered) else None)
            p_out, extra = unpack(carry)
            return (p_out,) + extra + (ms, ev)

        lr_specs = (P(),) if (lr_arg or arms) else ()
        eval_specs = tuple(fused_eval.specs) if groups else ()
        resid_specs = (P("clients"),) if codec else ()
        buf_specs = (P(),) if buffered else ()
        carry_specs = resid_specs + buf_specs  # mutually exclusive
        sched_spec = P(None, None, "clients") if mode == "span" else P(None, "clients")
        if streaming:
            # cohort stacks ride the xs in the schedule's own slot layout
            data_specs = (sched_spec,) * n_data_args
        else:
            data_specs = (P(), P()) if self.is_lm else (P(), P(), P(), P())
        if arms:
            # [k, E, L, slots]: the arms axis rides behind the round axis
            ms_spec = P(None, None, None, "clients")
        else:
            ms_spec = P(None, None, "clients") if mode == "span" \
                else P(None, "clients")
        out_specs = (P(),) + carry_specs + (ms_spec,)
        if groups is not None:
            out_specs = out_specs + (fused_eval.out_specs,)
        fn = _shard_map(
            sbody, mesh,
            in_specs=(P(),) + carry_specs + (P(), P()) + lr_specs
            + (sched_spec,) + data_specs + eval_specs,
            out_specs=out_specs,
        )
        # Codec/buffered programs donate ONLY their extra carry, not the
        # params carry: donating the replicated params here trips an
        # XLA:CPU executable-serialization bug (jaxlib 0.4.36) where the
        # program reloaded from the persistent compile cache mis-assigns
        # the params-sized extra OUTPUT buffer and returns nondeterministic
        # garbage on a stable subset of its elements (fresh compiles are
        # correct; caught by test_resid_checkpoint_roundtrip_grouped on a
        # warm cache).  Cost: one extra params-size buffer per dispatch,
        # priced into the staticcheck HBM budgets.  Arms programs (ISSUE
        # 14) donate NOTHING: the same bug class intermittently corrupts
        # the E-stacked params carry on deserialized executables (see
        # round_engine._build_superstep).
        if arms:
            donate = ()
        else:
            donate = (1,) if (codec or buffered) else (0,)
        prog = jax.jit(fn, donate_argnums=donate)
        self._superstep_progs[key_] = prog
        return prog

    def _cohort_layout(self, user_schedule: np.ndarray,
                       rate_schedule: np.ndarray):
        """Shared slot-layout math of the eager schedule packing and the
        streaming cohort staging: snap rates, group positions per level,
        and bucket the per-device slot count.  Returns ``(sched_shape,
        per_dev, mode, positions, level_rates)`` -- the schedule buffer of
        ``sched_shape`` (span: ``[k, L, n_dev*per_dev]``, slices:
        ``[k, n_dev*per_dev]`` with each level at its slice rows) is
        allocated by the caller and written by ``_fill_schedule``."""
        k, a = user_schedule.shape
        n_dev = self.mesh.shape["clients"]
        snapped = snap_to_levels(rate_schedule.reshape(-1), self.levels)
        rate_schedule = snapped.reshape(k, a)
        level_rates = sorted(self.levels, reverse=True)
        mode, _ = self._fused_layout()
        positions = [[np.flatnonzero(rate_schedule[r] == lr_)
                      for lr_ in level_rates] for r in range(k)]
        if mode == "slices":
            rows = {r: self._slices[r][1] - self._slices[r][0]
                    for r in level_rates}
            need = max(_ceil_div(len(pos), rows[lr_]) if len(pos) else 1
                       for per_round in positions
                       for lr_, pos in zip(level_rates, per_round))
            per_dev = _bucket_pow2(need)
            shape = (k, n_dev * per_dev)
        else:
            need = max(_ceil_div(len(pos), n_dev) if len(pos) else 1
                       for per_round in positions for pos in per_round)
            per_dev = _bucket_pow2(need)
            shape = (k, len(level_rates), n_dev * per_dev)
        return shape, per_dev, mode, positions, level_rates

    @staticmethod
    def _fill_schedule(sched: np.ndarray, user_schedule: np.ndarray,
                       positions, level_rates, mode, per_dev, slices):
        """Write the packed slot ids into a (pre-filled -1) schedule buffer
        -- one code path for the eager and streaming stagings."""
        k = user_schedule.shape[0]
        if mode == "slices":
            for r in range(k):
                for lr_, pos in zip(level_rates, positions[r]):
                    lo = slices[lr_][0]
                    sched[r, lo * per_dev: lo * per_dev + len(pos)] = \
                        user_schedule[r][pos]
        else:
            for r in range(k):
                for li, pos in enumerate(positions[r]):
                    sched[r, li, : len(pos)] = user_schedule[r][pos]

    def stage_cohort(self, store: ClientStore, user_schedule,
                     rate_schedule, timer: PhaseTimer = None) -> StagedCohort:
        """Materialise + commit ONE superstep's cohort from a
        :class:`~.staging.ClientStore` (ISSUE 6): the cohort's shards pack
        into the stager's ring buffers in the SAME per-level slot layout as
        the schedule (level grouping is slot bookkeeping, done here once
        per superstep) and commit via explicit ``device_put`` + private
        copy.  O(k x levels x slots x shard) memory, population-free.
        Call for superstep N+1 right after dispatching superstep N."""
        timer = timer if timer is not None else PhaseTimer()
        with timer.phase("stage"):
            # staticcheck: allow(no-asarray): host schedule normalization;
            # the cohort reaches the mesh via the stager's explicit puts only
            user_schedule = np.asarray(user_schedule, np.int32)
            rate_schedule = np.asarray(rate_schedule)  # staticcheck: allow(no-asarray): host schedule normalization
            if user_schedule.shape != rate_schedule.shape \
                    or user_schedule.ndim != 2:
                raise ValueError(
                    f"user/rate schedules must both be [k, A], got "
                    f"{user_schedule.shape} / {rate_schedule.shape}")
            k, a = user_schedule.shape
            shape, per_dev, mode, positions, level_rates = \
                self._cohort_layout(user_schedule, rate_schedule)
            if self._cohort_stager is None:
                self._cohort_stager = CohortStager(self.mesh,
                                                   depth=self._prefetch_depth)
            st = self._cohort_stager
            n = store.shard_max
            if self.is_lm:
                dshapes = [shape + store.row_shape,
                           shape + (store.classes_size,)]
                dtypes = [store.data.dtype, np.float32]
            else:
                dshapes = [shape + (n,) + store.data.shape[1:],
                           shape + (n,), shape + (n,),
                           shape + (store.classes_size,)]
                dtypes = [store.data.dtype, store.target.dtype, np.float32,
                          np.float32]
            layouts = [(shape, np.int32, -1)] + \
                [(s, d, None) for s, d in zip(dshapes, dtypes)]
            key = ("grouped", mode, shape)
            slot_i, bufs = st.buffers(key, layouts)
            sched = bufs[0]
            self._fill_schedule(sched, user_schedule, positions, level_rates,
                                mode, per_dev, self._slices)
            flat = sched.reshape(-1)
            if self.is_lm:
                store.fill_lm(flat, bufs[1].reshape((-1,) + store.row_shape))
                store.fill_labels(flat, bufs[2].reshape(-1, store.classes_size))
            else:
                store.fill_vision(flat,
                                  bufs[1].reshape((-1, n) + store.data.shape[1:]),
                                  bufs[2].reshape(-1, n),
                                  bufs[3].reshape(-1, n))
                store.fill_labels(flat, bufs[4].reshape(-1, store.classes_size))
            spec = P(None, None, "clients") if mode == "span" \
                else P(None, "clients")
            dev = st.commit(key, slot_i, bufs, (spec,) * len(bufs))
        return StagedCohort(engine="grouped", k=k, a=a, per_dev=per_dev,
                            sched=dev[0], data=tuple(dev[1:]), mode=mode,
                            positions=positions)

    def train_superstep(self, global_params: Dict[str, Any], base_key,
                        epoch0: int, k: int,
                        user_schedule: Optional[np.ndarray] = None,
                        rate_schedule: Optional[np.ndarray] = None,
                        data: Optional[Tuple] = None,
                        timer: PhaseTimer = None, eval_mask=None,
                        fused_eval=None, lr=None,
                        cohort: Optional[StagedCohort] = None):
        """Run ``k`` grouped rounds as ONE compiled program.

        ``user_schedule``: int32 ``[k, A]`` active user ids per round (the
        superstep sampling stream, :func:`~..fed.core.round_users`);
        ``rate_schedule``: ``[k, A]`` absolute model rates drawn host-side
        from the same per-round keys as the sequential wrapper
        (:func:`~..fed.core.round_rates`) -- level membership is slot
        bookkeeping, so the grouping happens here, once per superstep, and
        the rounds themselves never touch the host.  Per-round keys are
        ``fold_in(base_key, epoch0 + r)``; the LR schedule is evaluated
        in-jit from the round index.  Returns ``(new_params,
        PendingMetrics)`` whose ``fetch()`` yields a list of k per-round
        metric dicts in active-client order.

        ``eval_mask`` + ``fused_eval`` (ISSUE 4): fuse the sBN+eval phase
        into the scan on the masked rounds; the fetch then yields
        ``{"train": [...], "eval": [...]}`` (see
        :meth:`~.round_engine.RoundEngine.train_superstep`).  ``lr``: stage
        a constant LR scalar (ReduceLROnPlateau superstep mode).

        ``cohort`` (ISSUE 6): a :class:`~.staging.StagedCohort` from
        :meth:`stage_cohort` replaces ``user_schedule``/``rate_schedule``/
        ``data`` -- the level-grouped cohort rides the scan xs and the
        program never sees the population stacks; results are bit-identical
        to the eager path at matched schedules."""
        from .round_engine import normalize_eval_mask

        eval_mask = normalize_eval_mask(eval_mask, k, fused_eval)
        lr_arg = lr is not None
        if not lr_arg and self._lr_fn is None:
            self._lr_fn = make_traced_lr_fn(self.cfg)
        timer = timer if timer is not None else PhaseTimer()
        aspec = self._arms_spec
        arms = aspec.count if aspec is not None else 0
        if cohort is not None:
            if aspec is not None:
                raise ValueError(
                    "arms need the eager data path: a staged cohort holds "
                    "ONE schedule's shards, and per-arm cohorts would "
                    "multiply the staged bytes by E (a ROADMAP follow-on)")
            if cohort.engine != "grouped" or cohort.k != k:
                raise ValueError(
                    f"cohort mismatch: staged for engine={cohort.engine!r} "
                    f"k={cohort.k}, dispatching grouped k={k}")
            with timer.phase("stage"):
                a, per_dev, mode = cohort.a, cohort.per_dev, cohort.mode
                positions = cohort.positions
                level_rates = sorted(self.levels, reverse=True)
                sched_dev, args = cohort.sched, tuple(cohort.data)
                lr_args = (self._staging.scalar(lr),) if lr_arg else ()
                eval_args = tuple(fused_eval.ops) if eval_mask is not None else ()
                epoch0_dev = self._staging.scalar(epoch0, dtype=np.int32)
                global_params = self._staging.commit(self._pin(global_params))
                carry_args = self._carry_args(global_params)
                prog = self._superstep_prog(k, per_dev, mode,
                                            eval_mask=eval_mask,
                                            fused_eval=fused_eval,
                                            lr_arg=lr_arg, streaming=True)
        else:
            if user_schedule is None or rate_schedule is None or data is None:
                raise ValueError("train_superstep needs user/rate schedules "
                                 "+ data stacks, or a staged cohort")
            with timer.phase("stage"):
                n_dev = self.mesh.shape["clients"]
                # staticcheck: allow(no-asarray): host schedule normalization;
                # the packed slots reach the mesh via explicit staging.put only
                user_schedule = np.asarray(user_schedule, np.int32)
                rate_schedule = np.asarray(rate_schedule)  # staticcheck: allow(no-asarray): host schedule normalization
                if user_schedule.shape != rate_schedule.shape \
                        or user_schedule.ndim != 2 or user_schedule.shape[0] != k:
                    raise ValueError(
                        f"user/rate schedules must both be [k={k}, A], got "
                        f"{user_schedule.shape} / {rate_schedule.shape}")
                a = user_schedule.shape[1]
                # slot layout shared with the streaming staging (positions
                # drive metric reassembly + slot packing in both paths)
                shape, per_dev, mode, positions, level_rates = \
                    self._cohort_layout(user_schedule, rate_schedule)
                sched = self._packer.buffer(("gss", mode, shape), shape)
                self._fill_schedule(sched, user_schedule, positions,
                                    level_rates, mode, per_dev, self._slices)
                args = self._staging.replicated("train_data", data)
                spec = P(None, None, "clients") if mode == "span" \
                    else P(None, "clients")
                sched_dev = self._staging.put(sched, spec=spec)
                if lr_arg:
                    # arms: the per-arm LR vector [E] (Plateau steps each
                    # arm's own state); solo: a scalar
                    lr_args = ((self._staging.put(
                        np.asarray(lr, np.float32).reshape(arms)),) if arms  # staticcheck: allow(no-asarray): host LR-vector normalization; reaches the mesh via the explicit staging.put
                        else (self._staging.scalar(lr),))
                elif arms:
                    # per-arm multiplicative scales over the shared schedule
                    lr_args = (self._staging.put(
                        np.asarray(aspec.lr_scales, np.float32)),)  # staticcheck: allow(no-asarray): host scale-vector normalization; reaches the mesh via the explicit staging.put
                else:
                    lr_args = ()
                eval_args = tuple(fused_eval.ops) if eval_mask is not None else ()
                epoch0_dev = self._staging.scalar(epoch0, dtype=np.int32)
                # commit the params carry (see train_round), layout pinned
                global_params = self._staging.commit(self._pin(global_params))
                carry_args = self._carry_args(global_params)
                if arms and mode != "span":  # pragma: no cover - slices
                    raise ValueError(  # refused at construction already
                        "arms need level_placement='span'")
                prog = self._superstep_prog(k, per_dev, mode,
                                            eval_mask=eval_mask,
                                            fused_eval=fused_eval,
                                            lr_arg=lr_arg, arms=arms)
        # arms (ISSUE 14): the program takes the stacked [E] per-arm key
        # roots in the base-key slot (fed.core.arm_stream_keys)
        dispatch_key = arm_stream_keys(base_key, aspec.seeds) \
            if aspec is not None else base_key
        with timer.phase("dispatch"):
            out = prog(global_params, *carry_args, dispatch_key, epoch0_dev,
                       *lr_args, sched_dev, *args, *eval_args)
        if self._codec_name != "dense":
            # stash the new error-feedback carry (checkpointed via
            # wire_resid_host / set_wire_resid at superstep boundaries)
            self._resid = out[1]
            out = (out[0],) + out[2:]
        elif self._sched_spec.buffered:
            # stash the new staleness buffer (checkpointed via
            # sched_buf_host / set_sched_buf at superstep boundaries)
            self._sched_buf = out[1]
            out = (out[0],) + out[2:]

        def _split(host):
            """Probe leaves out of a fetched metrics tree (ISSUE 10):
            telemetry-off trees pass through untouched (None probes).  The
            quarantine counter (ISSUE 15) rides as an obs_ probe even
            under telemetry='off'."""
            if self._obs_on or self._quarantine.enabled:
                return split_probes(host, self.mesh.shape["clients"],
                                    layout="span" if mode == "span"
                                    else "flat")
            return host, None

        def _assemble_train(host):
            rounds = []
            for r in range(k):
                mr = {n: np.zeros(a, np.float32) for n in host}
                for li, (lr_, pos) in enumerate(zip(level_rates, positions[r])):
                    if not len(pos):
                        continue
                    for n in mr:
                        if mode == "span":
                            mr[n][pos] = host[n][r, li, : len(pos)]
                        else:
                            lo = self._slices[lr_][0]
                            mr[n][pos] = host[n][r, lo * per_dev:
                                                 lo * per_dev + len(pos)]
                rounds.append(mr)
            return rounds

        if eval_mask is None:
            new_params, ms = out

            def _assemble(host):
                if arms:
                    # [k, E, L, slots] -> per-arm [k, L, slots], then the
                    # solo reassembly (ISSUE 14; probes refused with arms)
                    return {"arms": [
                        _assemble_train({n: v[:, e] for n, v in host.items()})
                        for e in range(arms)]}
                host, probes = _split(host)
                rounds = _assemble_train(host)
                if probes is not None:
                    return {"train": rounds, "obs": probes}
                return rounds

            return new_params, PendingMetrics(ms, assemble=_assemble)

        new_params, ms, ev = out
        eval_epochs = [epoch0 + r for r, m in enumerate(eval_mask) if m]

        def _assemble_eval(host):
            ms_h, ev_h = host
            if arms:
                return {"arms": [
                    {"train": _assemble_train({n: v[:, e]
                                               for n, v in ms_h.items()}),
                     "eval": fused_eval.assemble(
                         jax.tree_util.tree_map(lambda v: v[:, e], ev_h),
                         eval_epochs)}
                    for e in range(arms)]}
            ms_h, probes = _split(ms_h)
            out_d = {"train": _assemble_train(ms_h),
                     "eval": fused_eval.assemble(ev_h, eval_epochs)}
            if probes is not None:
                out_d["obs"] = probes
            return out_d

        return new_params, PendingMetrics((ms, ev), assemble=_assemble_eval)
