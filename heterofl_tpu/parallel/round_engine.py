"""The federated round engine: one XLA program per communication round.

Replaces the reference's host-side round (sequential per-client training with
deepcopy'd state_dicts, ref train_classifier_fed.py:99-124) with a single
jitted ``shard_map`` over a ``clients`` mesh axis:

  gather client shards -> vmap(local SGD over epochs x batches via lax.scan)
  -> per-client count masks -> ``psum`` counted-average over ICI -> new global

Width heterogeneity (5 rate levels) is runtime data (masks), so one compiled
program serves every rate mix, including dynamic re-rolls (ref fed.py:15-19).
All client datasets stay resident on device; a round moves no host data.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

try:
    from jax import shard_map  # jax >= 0.7 new API

    def _shard_map(f, mesh, in_specs, out_specs):
        return shard_map(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                         check_vma=False)
except ImportError:  # pragma: no cover
    from jax.experimental.shard_map import shard_map as _sm

    def _shard_map(f, mesh, in_specs, out_specs):
        return _sm(f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False)

from ..chaos import resolve_poison_cfg
from ..compress import make_codec, resid_slots, resolve_codec_cfg
from ..config import resolve_prefetch_depth
from ..multi import resolve_arms_cfg
from ..obs import resolve_quarantine_cfg, resolve_telemetry_cfg, split_probes
from ..obs.hist import round_hists
from ..obs.probes import round_probes
from ..data.datasets import DATASET_STATS
from ..fed.core import (arm_stream_keys, client_stream_keys, combine_counted,
                        failure_stream_key, round_rates, round_users)
from ..fed.sampling import resolve_sampler_cfg
from ..sched import resolve_schedule_cfg
from ..sched.buffer import _SchedBufCarry, buffered_combine
from ..sched.deadline import deadline_steps
from .ring_attention import ring_attention
from .staging import (ClientStore, CohortStager, PendingMetrics, PhaseTimer,
                      PlacementCache, SlotPacker, StagedCohort)
from ..models.base import ModelDef
from ..models.layout import ParamPinner
from ..models.spec import count_masks as make_count_masks, mask_params, param_mask
from ..ops.augment import augment_cifar, normalize_image
from ..ops.fused_update import FlatSpec, fused_sgd_flat, resolve_fused_mode
from ..utils.optim import clip_by_global_norm, make_optimizer, make_traced_lr_fn


def _ceil_div(a: int, b: int) -> int:
    return -(-a // b)


def _bucket_pow2(n: int) -> int:
    """Smallest power of two >= n (n >= 1): slot-count bucketing keeps the
    program cache O(log A) instead of one entry per observed count."""
    p = 1
    while p < n:
        p *= 2
    return p


def eval_fused_scan(step, params, xs, epochs, groups, fused_eval, eval_ops,
                    params_of=None):
    """THE eval-fused scan-group walk, shared by both engines' superstep
    programs (parity-critical: the bit-identical-to-host-loop contract
    lives here, so there is exactly one copy).

    Walks the static ``groups`` from :func:`superstep_eval_groups`,
    threading the params carry through per-segment ``lax.scan``s of
    ``step`` and running ``fused_eval.core`` on the rounds where the mask
    fired.  The eval core always runs at the PROGRAM'S top level, never
    inside an outer scan body: XLA compiles a while-loop body differently
    from straight-line code (measured ~1e-7 relative drift on the local
    eval loss reduction), so a repeated group's scan emits a params
    SNAPSHOT per segment end (ys) and the eval phases run unrolled on the
    stacked snapshots -- one train-body trace, one eval trace per eval
    point, n_evals x params of transient snapshot memory.  Returns
    ``(new_carry, train_ms [k, ...], eval_ms [n_evals, ...])``.

    ``params_of`` extracts the params tree from a compound scan carry (the
    wire-codec supersteps carry ``(params, error-feedback residual)``,
    ISSUE 8); None = the carry IS the params tree."""
    if params_of is None:
        params_of = lambda c: c  # noqa: E731
    tree_map = jax.tree_util.tree_map
    p, train_ms, eval_ms, off = params, [], [], 0
    for n, do_eval, c in groups:
        xs_g = tree_map(
            lambda x, o=off, cc=c, nn=n:
                x[o:o + cc * nn].reshape((cc, nn) + x.shape[1:]), xs)
        if c == 1:
            p, ms = jax.lax.scan(step, p, tree_map(lambda x: x[0], xs_g))
            if do_eval:
                ev = fused_eval.core(params_of(p), epochs[off + n - 1],
                                     eval_ops)
                eval_ms.append(tree_map(lambda x: x[None], ev))
        else:
            # c repeats of (n train rounds + eval): only eval-bearing
            # segments group (the trailing train-only run is always a single
            # segment), so every outer step ends on an eval point and
            # snapshots its params
            def seg_body(p, xs_one):
                p, ms = jax.lax.scan(step, p, xs_one)
                return p, (ms, params_of(p))

            p, (ms, snaps) = jax.lax.scan(seg_body, p, xs_g)
            ms = tree_map(lambda x: x.reshape((c * n,) + x.shape[2:]), ms)
            for j in range(c):
                ev = fused_eval.core(
                    tree_map(lambda x, jj=j: x[jj], snaps),
                    epochs[off + (j + 1) * n - 1], eval_ops)
                eval_ms.append(tree_map(lambda x: x[None], ev))
        train_ms.append(ms)
        off += c * n
    ms = train_ms[0] if len(train_ms) == 1 else tree_map(
        lambda *xs_: jnp.concatenate(xs_, 0), *train_ms)
    ev = eval_ms[0] if len(eval_ms) == 1 else tree_map(
        lambda *xs_: jnp.concatenate(xs_, 0), *eval_ms)
    return p, ms, ev


def normalize_eval_mask(eval_mask, k: int, fused_eval):
    """Shared eval-mask validation for both engines' ``train_superstep``:
    returns the static bool tuple, or None when no round evaluates."""
    if eval_mask is None:
        return None
    eval_mask = tuple(bool(m) for m in eval_mask)
    if len(eval_mask) != k:
        raise ValueError(f"eval_mask must have k={k} entries, got "
                         f"{len(eval_mask)}")
    if not any(eval_mask):
        return None
    if fused_eval is None:
        raise ValueError("eval_mask needs a FusedEval (Evaluator.fused) "
                         "carrying the staged eval operands")
    return eval_mask


class _ArmsFusedEval:
    """:class:`~.evaluation.FusedEval` adapter for arms-batched supersteps
    (ISSUE 14): ``core`` runs the inner eval phase vmapped over the leading
    arms axis of the params stack against the SHARED once-committed eval
    operands, so each arm's sBN recalibration + Local/Global eval is the
    solo core's computation on that arm's params; ``out_specs`` grow the
    arms axis behind the eval-stack axis.  Host-side assembly stays the
    inner object's (the engines slice each arm out before assembling)."""

    def __init__(self, inner, count: int, axis=None):
        self._inner = inner
        self.count = count
        self.axis = axis  # 'arms' under the mesh placement, else None
        self.ops = inner.ops
        self.specs = inner.specs

    @property
    def out_specs(self):
        # [n_evals, E, ...]: bn moments and Global sums replicated within
        # an arm (sharded over the arms axis under the mesh placement),
        # the per-user Local sums sharded over clients behind (evals, arms)
        return {"bn": P(None, self.axis),
                "local": P(None, self.axis, "clients"),
                "global": P(None, self.axis)}

    def core(self, params, epoch, ops):
        # the fence sits OUTSIDE the vmap (optimization_barrier has no
        # batching rule): same fusion isolation as the solo core, one
        # fence per eval point
        params, epoch, ops = jax.lax.optimization_barrier(
            (params, epoch, ops))
        out = jax.vmap(
            lambda p: self._inner.core_unfenced(p, epoch, ops))(params)
        return jax.lax.optimization_barrier(out)


def superstep_eval_groups(mask):
    """Compress a static per-round eval mask into ``[(n, do_eval, repeat)]``
    scan groups: ``n`` training rounds followed (``do_eval``) by one fused
    eval phase, the segment repeated ``repeat`` times as an outer scan.

    The mask is STATIC (it keys the compiled superstep program), so the
    program unrolls O(groups) scan segments instead of K round bodies; any
    uniform cadence -- ``eval_interval`` dividing K, equal to K, or a
    multiple of K -- compresses to at most one eval group plus one trailing
    train-only group, and the steady-state mask repeats superstep to
    superstep (no recompiles).  ``sum(n * repeat) == len(mask)``."""
    segs, run = [], 0
    for m in mask:
        run += 1
        if m:
            segs.append((run, True))
            run = 0
    if run:
        segs.append((run, False))
    groups = []
    for seg in segs:
        if groups and groups[-1][0] == seg:
            groups[-1][1] += 1
        else:
            groups.append([seg, 1])
    return [(n, ev, c) for (n, ev), c in groups]


def shard_client_data(mesh: Mesh, data: Tuple[Any, ...]) -> Tuple[jnp.ndarray, ...]:
    """Place per-user data stacks with the user axis sharded over ``clients``.

    Pads the user dimension to a multiple of the ``clients`` axis size (the
    padded users own empty shards and are never sampled), then ``device_put``s
    each array with ``P('clients')`` so every device holds only ``U/n_dev``
    client shards -- device memory scales down with the mesh instead of
    replicating the whole federation's data everywhere (VERDICT r1 item 6).
    Use together with ``cfg['data_placement'] = 'sharded'``.
    """
    from jax.sharding import NamedSharding

    n_dev = mesh.shape["clients"]
    u = int(data[0].shape[0])
    pad = (-u) % n_dev
    out = []
    for arr in data:
        # staticcheck: allow(no-asarray): once-per-experiment staging helper;
        # the commit below is an explicit device_put, not an implicit wrap
        a = np.asarray(arr)
        if pad:
            a = np.concatenate([a, np.zeros((pad,) + a.shape[1:], a.dtype)])
        out.append(jax.device_put(a, NamedSharding(mesh, P("clients"))))
    return tuple(out)


class _WireCodecCarry:
    """Shared wire-codec scaffolding of both round engines (ISSUE 8): the
    lazily-built codec object over the engine's param shapes and the
    device-resident error-feedback residual carry, with its checkpoint
    read/restore pair.  ONE copy on purpose -- the donation policy below is
    a correctness pin, and a fix that lands in only one engine rots.

    Donation policy: codec programs donate ONLY the resid carry.  Donating
    the replicated params carry alongside a params-sized resid output trips
    an XLA:CPU executable-serialization bug (jaxlib 0.4.36): the program
    RELOADED from the persistent compile cache mis-assigns the resid output
    buffer and returns nondeterministic garbage on a stable subset of its
    elements, while fresh compiles are correct (caught by the checkpoint
    round-trip tests on a warm cache -- grouped int8 and masked signsgd).
    Cost: one extra params-size buffer per lossy-codec dispatch, priced
    into the staticcheck HBM budgets and donation-savings accounting.

    Expects on ``self``: ``mesh``, ``_codec_name``, ``_error_feedback``,
    ``_codec_obj``, ``_resid`` (the latter two initialised to None)."""

    def _codec(self, params):
        """The engine's wire codec over these param shapes (None = dense);
        built once.  The FlatSpec mirrors ops/fused_update's flat layout --
        for the grouped engine these are the GLOBAL shapes (its fused
        superstep's single psum joins the embedded level partials at global
        shape, the same layout the masked engine compresses)."""
        if self._codec_name == "dense":
            return None
        shapes = {k: tuple(v.shape) for k, v in params.items()}
        if self._codec_obj is None or self._codec_obj.spec.shapes != shapes:
            self._codec_obj = make_codec(self._codec_name, FlatSpec(shapes),
                                         self.mesh.shape["clients"],
                                         self._error_feedback)
        return self._codec_obj

    def _arms_count(self) -> int:
        """E when this engine multiplexes experiment arms (ISSUE 14), else
        0: the EF residual grows a leading arms axis (even at E=1 -- the
        arms programs always carry it) -- each arm owns its own
        compression-error stream, exactly like a solo run's."""
        spec = getattr(self, "_arms_spec", None)
        return spec.count if spec is not None else 0

    def _resid_pspec(self):
        """The residual carry's PartitionSpec: per-device rows over the
        clients axis, behind the arms axis when arms are on (the arms
        axis itself is sharded under the mesh placement)."""
        if not self._arms_count():
            return P("clients")
        return P("arms", "clients") if getattr(self, "_arms_mesh", False) \
            else P(None, "clients")

    def _resid_shape(self, params) -> Tuple[int, ...]:
        e = self._arms_count()
        # under arms the params tree arrives STACKED [E, ...]: the flat
        # layout (and so the residual's trailing dim) is per arm
        shapes = {k: (tuple(v.shape[1:]) if e else tuple(v.shape))
                  for k, v in params.items()}
        base = (self.mesh.shape["clients"], resid_slots(self._codec_name),
                FlatSpec(shapes).total)
        return ((e,) + base) if e else base

    def _ensure_resid(self, params):
        """The committed error-feedback carry (zeros on first use): built by
        a jitted program so the buffer is PRIVATE and donation-safe, sharded
        one row per device over the clients axis."""
        from jax.sharding import NamedSharding

        shape = self._resid_shape(params)
        if self._resid is None or tuple(self._resid.shape) != shape:
            sh = NamedSharding(self.mesh, self._resid_pspec())
            # staticcheck: allow(jit-needs-donation): one-time zeros init
            # (nothing to donate); steady-state rounds donate the carry
            self._resid = jax.jit(
                lambda: jnp.zeros(shape, jnp.float32), out_shardings=sh)()
        return self._resid

    def reset_carries(self) -> None:
        """Drop the device scan carries (EF residual / staleness buffer):
        the rollback path (ISSUE 15) re-seeds them from the restored
        checkpoint blob -- a NaN that reached a carry must not survive the
        recovery.  The next dispatch rebuilds zeros via the lazy
        ``_ensure_*`` paths unless a checkpointed carry is restored
        first."""
        self._resid = None
        self._sched_buf = None

    def wire_resid_host(self):
        """Host copy of the error-feedback residual carry (checkpointing);
        None for the dense codec or before the first compressed round.  On
        a multi-process mesh the carry is sharded over rows other hosts
        own, so this returns THIS process's local blocks as a
        :func:`~..utils.checkpoint.host_shard_blocks` marker -- the sharded
        checkpoint writer persists exactly those rows (ISSUE 17)."""
        if self._resid is None:
            return None
        if not self._resid.is_fully_addressable:
            from ..utils.checkpoint import host_shard_blocks
            return host_shard_blocks(self._resid)
        # staticcheck: allow(no-asarray): checkpoint-boundary D2H fetch
        # (superstep boundaries only), not steady-state round code
        return np.asarray(self._resid)

    def set_wire_resid(self, arr) -> None:
        """Restore the residual carry from a checkpoint (resume): committed
        through a jitted copy so the restored buffer is donation-safe.  A
        shard-blocks marker (multi-process checkpoint) recommits straight
        onto the carry sharding from the merged block set."""
        from jax.sharding import NamedSharding

        from ..utils.checkpoint import dense_from_blocks, is_shard_marker

        sh = NamedSharding(self.mesh, self._resid_pspec())
        if is_shard_marker(arr):
            # merged multi-process blocks -> dense host array: topology-
            # independent (a 2-process checkpoint resumes on 1, and back)
            arr = dense_from_blocks(arr)
        # staticcheck: allow(no-asarray): checkpoint-restore host
        # normalization; the carry reaches the mesh via the explicit
        # commit + jitted private copy below
        host = np.asarray(arr, np.float32)
        from .staging import commit_global
        # staticcheck: allow(jit-needs-donation): one-time restore copy
        # severing host-buffer aliasing; donating its input would free the
        # caller's checkpoint array
        self._resid = jax.jit(lambda t: t + 0, out_shardings=sh)(
            commit_global(host, sh))

    def _carry_args(self, params) -> Tuple:
        """The round/superstep programs' extra donated carry argument: the
        wire-codec EF residual (ISSUE 8) or the buffered-async staleness
        buffer (ISSUE 9, :class:`~..sched.buffer._SchedBufCarry` -- both
        engines mix the two carries in together); empty under dense sync
        lockstep, the zero-new-args contract.  The two carries are mutually
        exclusive (validated at engine construction)."""
        if self._codec_name != "dense":
            return (self._ensure_resid(params),)
        if self._sched_spec.buffered:
            return (self._ensure_sched_buf(params),)
        return ()


class RoundEngine(_WireCodecCarry, _SchedBufCarry):
    """Jitted train/eval/sBN programs for one (model, cfg, mesh) triple.

    Shapes are taken from the arrays passed in; jit re-specialises on new
    shapes automatically (in practice: one compile per experiment).
    """

    def __init__(self, model: ModelDef, cfg: Dict[str, Any], mesh: Optional[Mesh] = None):
        self.model = model
        self.cfg = cfg
        self.mesh = mesh
        self.global_rate = cfg["global_model_rate"]
        ne = cfg["num_epochs"]
        self.local_epochs = ne["local"] if isinstance(ne, dict) else 1
        self.batch_size = cfg["batch_size"]["train"]
        self.is_lm = model.meta.get("kind") == "transformer"
        self.bptt = cfg.get("bptt", 64)
        self.norm_stats = cfg.get("norm_stats") or DATASET_STATS.get(cfg["data_name"])
        self.augment = cfg["data_name"].startswith("CIFAR")
        # staticcheck: allow(no-asarray): constructor-time config parse
        self.fix_rates = np.asarray(cfg["model_rate"], np.float32) \
            if cfg["model_split_mode"] == "fix" else None
        self.placement = cfg.get("data_placement", "replicated")
        if self.placement not in ("replicated", "sharded"):
            raise ValueError(f"Not valid data_placement: {self.placement!r}")
        # lax.scan unroll factor for the local-step loop: the round is
        # latency-bound at HeteroFL's shapes (MEASUREMENTS.md), so fewer
        # while-loop trips with more fusion scope per trip can shave per-step
        # overhead; 1 = no unrolling (identical program)
        self.scan_unroll = int(cfg.get("scan_unroll", 1) or 1)
        self._opt_init, self._opt_update = make_optimizer(cfg)
        # fused masked-SGD epilogue (ISSUE 5 tentpole): None = the reference
        # op chain; 'xla'/'pallas' = ops/fused_update.py.  Resolved once at
        # construction so the scan body is shape-stable per engine.
        self._fused_mode = resolve_fused_mode(cfg)
        self._momentum = cfg.get("momentum", 0.0)
        self._weight_decay = cfg.get("weight_decay", 0.0)
        # debug/regression knob: re-materialise the per-param grad masks
        # inside the scan body (the pre-hoist program) -- exists so the
        # staticcheck step-body budget can prove it catches the regression
        self._masks_in_body = bool(cfg.get("_masks_in_body", False))
        # layout pinning (ISSUE 5 pass 2): commit the params carry with the
        # models/layout.py policy so the superstep scan carry enters every
        # dispatch in the compute layout (TPU; identity on the CPU mesh);
        # the pinner caches the static Format tree across dispatches
        self._pin = ParamPinner(mesh, cfg.get("layout_policy", "auto"))
        # wire codec (ISSUE 8): compress the aggregation payload inside the
        # round program -- quantise -> ONE global psum -> dequantise, with
        # the error-feedback residual as an extra donated carry.  'dense'
        # keeps today's program bit for bit (no new args, no residual).
        self._codec_name, self._error_feedback = resolve_codec_cfg(
            cfg, engine_strategy="masked")
        if isinstance(self._codec_name, dict):
            # per-level maps belong to the grouped engine's fused superstep;
            # this engine may still be CONSTRUCTED (the driver always builds
            # its default-engine slot), so the refusal fires at dispatch
            self._codec_name = "__per-level-map__"
        self._codec_obj = None  # built lazily (needs the param shapes)
        self._resid = None      # device [n_dev, slots, total] EF carry
        # scheduler (ISSUE 9, heterofl_tpu/sched/): availability schedule +
        # deadline stragglers + buffered-async aggregation.  The lockstep
        # default builds byte-identical programs (zero new carry args).
        self._sched_spec = resolve_schedule_cfg(cfg)
        # population sampler (ISSUE 11, heterofl_tpu/fed/sampling.py): the
        # in-jit cohort draw's kind -- 'prp' (O(active) index map, default)
        # or 'perm' (legacy full permutation).  Resolved at construction so
        # a typo'd sampler fails here, and captured by _build_superstep so
        # the compiled draw matches the host schedule stream.
        self._sampler = resolve_sampler_cfg(cfg).kind
        self._sched_buf = None  # device [2, total] staleness carry
        # runtime telemetry (ISSUE 10, heterofl_tpu/obs/): telemetry='on'
        # folds the in-program health probes into the metrics pytree of
        # every round core -- zero new collectives, zero new arguments;
        # 'off' (default) leaves every program bit-identical to pre-obs.
        self._obs_spec = resolve_telemetry_cfg(cfg)
        self._obs_on = self._obs_spec.probes
        # cohort histograms (ISSUE 12): telemetry='hist' folds the fixed-
        # bucket hist rows (obs/hist.py) in next to the scalar probes
        self._obs_hist = self._obs_spec.hist
        # staticcheck: allow(no-float-coercion): constructor-time config
        # parse (the probe level table, a trace-time constant)
        self._obs_levels = sorted({float(r) for r in cfg["model_rate"]},
                                  reverse=True)
        # client-update quarantine (ISSUE 15): a per-slot finiteness (+
        # optional update-norm) gate folded into the sums AND counts
        # before the single psum -- a poisoned client becomes a zero-count
        # participant.  'off' (default) builds bit-identical programs;
        # 'on' is bit-identical whenever every update is clean (the gate
        # multiplies by 1.0 / selects the unchanged value).
        self._quarantine = resolve_quarantine_cfg(cfg)
        # chaos NaN poison (ISSUE 15, heterofl_tpu/chaos/): a trace-time
        # (round, uid) table; None (default) leaves programs untouched
        self._poison = resolve_poison_cfg(cfg)
        if self._sched_spec.buffered and self._codec_name != "dense":
            raise ValueError(
                "schedule aggregation='buffered' cannot combine with a "
                "lossy wire_codec yet: both add a scan carry with its own "
                "donation/checkpoint contract -- pick one per experiment")
        # experiment arms multiplexer (ISSUE 14, heterofl_tpu/multi/): E
        # trace-compatible sweep arms vmapped over a leading axis of the
        # fused superstep -- structural for THIS engine instance (the arms
        # count keys every program), resolved once here.  None = single
        # trajectory, every program byte-identical to pre-arms.
        self._arms_spec = resolve_arms_cfg(cfg)
        if self._arms_spec is not None:
            if self._sched_spec.buffered:
                raise ValueError(
                    "arms cannot combine with schedule aggregation="
                    "'buffered' yet: the staleness buffer is a replicated "
                    "carry with its own donation/checkpoint contract -- "
                    "batch dense-sync arms or run buffered solo")
            if cfg.get("client_store", "eager") == "stream":
                raise ValueError(
                    "arms need client_store='eager': the streaming cohort "
                    "pipeline stages ONE schedule's shards per superstep, "
                    "and per-arm cohorts would multiply the staged bytes "
                    "by E (a ROADMAP follow-on)")
        # arms placement (ISSUE 14): the stacked arms axis is either
        # vmap-batched on every device (the default -- E x per-device
        # work, one dispatch) or laid over a dedicated 'arms' MESH axis
        # (make_mesh(n_arms=E)): each arm's whole federation lives on its
        # own device rows, the per-arm psum reduces within them, and E
        # arms execute CONCURRENTLY -- the mesh-filling layout for a pod
        # (or CPU core pool) a single arm cannot fill.
        self._arms_mesh = mesh is not None and "arms" in mesh.axis_names
        if self._arms_mesh:
            if self._arms_spec is None:
                raise ValueError(
                    "mesh has an 'arms' axis but cfg['arms'] is off: a "
                    "solo program on an arms mesh would silently train an "
                    "independent replica per arm row -- drop the axis or "
                    "set cfg['arms']")
            if mesh.shape["arms"] != self._arms_spec.count:
                raise ValueError(
                    f"mesh arms axis size ({mesh.shape['arms']}) must "
                    f"equal the arms count ({self._arms_spec.count}): one "
                    f"device row group per arm")
        self._train = None
        self._superstep_progs: Dict[Tuple, Any] = {}
        self._lr_fn = None  # built on first superstep (plateau raises there)
        self._sbn = None
        self._eval_users = None
        self._eval_global = None
        # staged placement + cached slot packing (ISSUE 1): the data stacks
        # are committed to the mesh once, the per-round slot arrays reuse
        # preallocated host buffers, and every transfer on the round path is
        # an explicit device_put.  mesh=None engines (the grouped engine's
        # per-level sub-engines) never run train_round and skip staging.
        self._staging = PlacementCache(mesh) if mesh is not None else None
        self._packer = SlotPacker()
        # streaming cohort pipeline (ISSUE 6): built on first stage_cohort;
        # ring depth = cfg['stream_prefetch_depth'] (ISSUE 8 satellite:
        # deeper pipelines once per-superstep compute shrinks on real TPUs)
        self._cohort_stager = None
        self._prefetch_depth = resolve_prefetch_depth(cfg)

    def _reject_per_level_map(self):
        """A per-level wire_codec map (ISSUE 9 satellite) only exists on
        the grouped engine's fused superstep; dispatching the masked engine
        under one is a config error, refused loudly here."""
        if self._codec_name == "__per-level-map__":
            raise ValueError(
                "a per-level wire_codec map needs the grouped strategy "
                "(its fused superstep owns per-level payloads); the masked "
                "engine has no levels to assign codecs to")

    # ------------------------------------------------------------------
    # per-client local training (pure; vmapped across clients)
    # ------------------------------------------------------------------

    def _prep_vision_batch(self, x_u8, w, key, train=True):
        if self.augment and train:
            x_u8 = augment_cifar(key, x_u8)
        if self.norm_stats is not None:
            img = normalize_image(x_u8, *self.norm_stats)
        else:
            img = x_u8.astype(jnp.float32)
        return img

    def _grad_masks(self, shapes, wr):
        """Per-param width-activity masks for the gradient epilogue.

        Loop-INVARIANT: they depend only on (shape, spec, wr), all fixed for
        one client's whole local run, so the callers hoist them OUT of the
        ``lax.scan`` step body (ISSUE 5 satellite) -- the seed program
        re-materialised every mask (iota + compare + broadcast per sliced
        axis per leaf) 250 times per round.  The staticcheck step-body
        kernel budget regression-tests the hoist."""
        model = self.model
        return {k: param_mask(shape, model.specs[k], model.groups, wr)
                for k, shape in shapes.items()}

    def _local_setup(self, p, wr):
        """(scan-carry params, opt state, FlatSpec-or-None, epilogue masks)
        for one client's local run.

        With the fused epilogue on, the params and momentum buffers ride
        the ``lax.scan`` carry as ONE lane-packed flat f32 buffer each
        (ops/fused_update.py FlatSpec) -- the carry shrinks from O(leaves)
        loop-carried buffers to O(1) with a pinned packed layout, the model
        fwd/bwd consumes zero-copy leaf views unflattened inside the step
        (and is differentiated w.r.t. those views, so the per-leaf grads
        and norm terms are the reference chain's), and the optimizer tail
        runs in the flat domain.  ``masks`` are the hoisted loop-invariant
        grad masks, or None under the ``_masks_in_body`` regression
        knob."""
        gmasks = None if self._masks_in_body else \
            self._grad_masks({k: v.shape for k, v in p.items()}, wr)
        if self._fused_mode is None:
            return p, self._opt_init(p), None, gmasks
        spec = FlatSpec.of(p)
        pf = spec.flatten(p)
        # the fused opt state is JUST the flat momentum buffer: SGD never
        # reads the OptState step counter, so carrying it through the scan
        # would be a dead loop-carried value
        return pf, jnp.zeros_like(pf), spec, gmasks

    def _apply_update(self, p, grads, opt, masks, spec, wr, n_glob, lr,
                      has=None):
        """The per-step optimizer epilogue: mean-normalise + width-mask +
        global-norm clip + optimizer update (+ ``has`` gating for
        all-padding batches).

        ``spec`` non-None selects the fused masked-SGD primitive over the
        flat carry (ops/fused_update.py -- Pallas on TPU, flat XLA fallback
        elsewhere, both bit-identical to this reference chain on the clip
        decision and elementwise tail); None keeps the reference op chain
        (non-SGD optimizers always do).  ``masks=None`` re-materialises
        the masks here, inside the scan body (the ``_masks_in_body``
        regression knob)."""
        if spec is not None:
            if masks is None:
                masks = self._grad_masks(spec.shapes, wr)
            return fused_sgd_flat(
                spec, p, grads, opt, masks, n_glob, lr,
                momentum=self._momentum, weight_decay=self._weight_decay,
                has=has, mode=self._fused_mode)
        if masks is None:
            masks = self._grad_masks({k: g.shape for k, g in grads.items()}, wr)
        grads = {k: g / jnp.maximum(n_glob, 1e-6) for k, g in grads.items()}
        grads = {k: g * masks[k] for k, g in grads.items()}
        grads, _ = clip_by_global_norm(grads, 1.0)
        p_new, opt_new = self._opt_update(p, grads, opt, lr)
        if has is not None:
            # all-padding batch: skip the step entirely (no wd/momentum drift)
            p_new = jax.tree_util.tree_map(
                lambda a, b: jnp.where(has, a, b), p_new, p)
            opt_new = jax.tree_util.tree_map(
                lambda a, b: jnp.where(has, a, b), opt_new, opt)
        return p_new, opt_new

    def _local_train_vision(self, params, wr, x, y, sm, lm, key, lr, scaler_rate=None,
                            data_axis=None, n_data: int = 1, step_limit=None):
        """Local SGD for one client.

        ``data_axis``/``n_data``: intra-client batch data-parallelism -- each
        device on that mesh axis processes ``B/n_data`` of every batch,
        gradients/metrics are ``psum``-ed and BN runs synchronised, so the
        result is numerically identical to single-device execution (modulo
        augmentation RNG).  Callers outside ``shard_map`` pass ``None``.

        ``step_limit`` (ISSUE 9 deadline): this client's local-step budget
        (traced int32); steps at index >= the budget gate off the optimizer
        update AND their metric contributions -- truncated training, pure
        in-scan arithmetic.  ``None`` (the lockstep default) leaves the
        step body byte-identical to the pre-scheduler program.
        """
        model, B, E = self.model, self.batch_size, self.local_epochs
        N = x.shape[0]
        S = _ceil_div(N, B)
        SB = S * B
        sr = wr if scaler_rate is None else scaler_rate
        p = mask_params(params, model.specs, model.groups, wr)
        p, opt, spec, emasks = self._local_setup(p, wr)
        ekeys = jax.random.split(jax.random.fold_in(key, 1), E)
        # Shuffle, then stable-sort the *real* samples (sm==1) to the front:
        # batches are dense like the reference's DataLoader over the true
        # shard, trailing all-padding batches carry zero weight and their
        # optimizer step is skipped below -- exact ceil(sz/B) step parity
        # for shards smaller than the stacked maximum.
        def epoch_perm(k):
            perm = jax.random.permutation(k, N)
            order = jnp.argsort(-sm[perm], stable=True)
            return perm[order]

        perms = jax.vmap(epoch_perm)(ekeys)  # [E, N]
        if SB > N:
            reps = _ceil_div(SB, N)
            perms = jnp.tile(perms, (1, reps))[:, :SB]
            wpad = jnp.concatenate([jnp.ones(N, jnp.float32), jnp.zeros(SB - N, jnp.float32)])
        else:
            wpad = jnp.ones(SB, jnp.float32)

        b_loc = _ceil_div(B, n_data)
        bp = b_loc * n_data

        def step(carry, t):
            p, opt, acc = carry
            e, s = t // S, t % S
            ids = jax.lax.dynamic_slice(perms, (e, s * B), (1, B))[0]
            w = jax.lax.dynamic_slice(wpad, (s * B,), (B,)) * sm[ids]
            has = (jnp.sum(w) > 0)  # global batch weight BEFORE any sharding
            n_glob = jnp.sum(w)
            live = None
            if step_limit is not None:
                # deadline straggler (ISSUE 9): steps past this client's
                # budget are no-ops -- update skipped, metrics zeroed
                live = t < step_limit
                has = jnp.logical_and(has, live)
            aug_key = jax.random.fold_in(key, 2 + t)
            if data_axis is not None and n_data > 1:
                # this device's slice of the client's batch, with the
                # augmentation key decorrelated across slices
                d = jax.lax.axis_index(data_axis)
                ids = jnp.concatenate([ids, ids[: bp - B]]) if bp > B else ids
                w = jnp.concatenate([w, jnp.zeros(bp - B, jnp.float32)]) if bp > B else w
                ids = jax.lax.dynamic_slice(ids, (d * b_loc,), (b_loc,))
                w = jax.lax.dynamic_slice(w, (d * b_loc,), (b_loc,))
                aug_key = jax.random.fold_in(aug_key, d)
            img = self._prep_vision_batch(x[ids], w, aug_key)
            batch = {"img": img, "label": y[ids]}

            def loss_fn(pt):
                out, _ = model.apply(pt, batch, train=True, width_rate=wr, scaler_rate=sr,
                                     label_mask=lm, sample_weight=w,
                                     rng=jax.random.fold_in(key, 5000 + t),
                                     bn_axis=data_axis if n_data > 1 else None)
                n_loc = jnp.sum(w)
                # weighted-SUM form so cross-device reduction recovers the
                # exact full-batch mean gradient
                return out["loss"] * n_loc, out["score"]

            # under the fused flat carry the model is differentiated w.r.t.
            # the per-leaf VIEWS, so grads come back per-leaf -- the norm
            # terms then reduce over the reference chain's exact arrays
            (lsum, score), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                spec.unflatten(p) if spec is not None else p)
            correct = jnp.sum((jnp.argmax(score, -1) == y[ids]) * w)
            if data_axis is not None and n_data > 1:
                grads, lsum, correct = jax.lax.psum((grads, lsum, correct), data_axis)
            p, opt = self._apply_update(p, grads, opt, emasks, spec, wr,
                                        n_glob, lr, has=has)
            if live is not None:
                g = live.astype(jnp.float32)
                lsum, correct, n_glob = lsum * g, correct * g, n_glob * g
            acc = (acc[0] + lsum, acc[1] + correct, acc[2] + n_glob)
            return (p, opt, acc), None

        acc0 = (jnp.zeros(()), jnp.zeros(()), jnp.zeros(()))
        (p, _, acc), _ = jax.lax.scan(step, (p, opt, acc0), jnp.arange(E * S),
                                      unroll=self.scan_unroll)
        if spec is not None:
            p = spec.unflatten(p)
        return p, {"loss_sum": acc[0], "score_sum": acc[1], "n": acc[2]}

    def _local_train_lm(self, params, wr, rows, lm, key, lr, scaler_rate=None,
                        data_axis=None, n_data: int = 1, step_limit=None):
        """Local SGD on one client's token rows.

        ``step_limit`` (ISSUE 9 deadline): per-client local-step budget --
        same truncation semantics as :meth:`_local_train_vision` (None =
        byte-identical lockstep body).

        ``data_axis``/``n_data``: sequence parallelism -- each device on that
        mesh axis holds ``bptt/n_data`` positions of every window, attention
        runs as exact ring attention over the axis (ppermute neighbour
        exchanges), and gradients are ``psum``-ed, so the result matches
        single-device execution up to float association (token corruption is
        drawn shard-invariantly; dropout shards are decorrelated by design).
        """
        model, E, bptt = self.model, self.local_epochs, self.bptt
        R, T = rows.shape
        S = _ceil_div(T, bptt)
        pad = S * bptt - T
        sr = wr if scaler_rate is None else scaler_rate
        rows_p = jnp.pad(rows, ((0, 0), (0, pad)))
        wpos = jnp.pad(jnp.ones((R, T), jnp.float32), ((0, 0), (0, pad)))
        p = mask_params(params, model.specs, model.groups, wr)
        p, opt, spec, emasks = self._local_setup(p, wr)

        seq_sharded = data_axis is not None and n_data > 1
        if seq_sharded:
            if bptt % n_data:
                raise ValueError(f"data axis size ({n_data}) must divide bptt={bptt} "
                                 f"for sequence-parallel LM rounds")
            s_loc = bptt // n_data
            attn = partial(ring_attention, axis_name=data_axis, axis_size=n_data)

        def step(carry, t):
            p, opt, acc = carry
            s = t % S
            lab = jax.lax.dynamic_slice(rows_p, (0, s * bptt), (R, bptt))
            w = jax.lax.dynamic_slice(wpos, (0, s * bptt), (R, bptt))
            batch = {"label": lab}
            extra = {}
            if seq_sharded:
                d = jax.lax.axis_index(data_axis)
                off = d * s_loc
                lab = jax.lax.dynamic_slice(lab, (0, off), (R, s_loc))
                w = jax.lax.dynamic_slice(w, (0, off), (R, s_loc))
                batch = {"label": lab, "pos_offset": off, "seq_full": bptt}
                extra = {"attn_override": lambda q, k, v, temp: attn(q, k, v, temperature=temp)}

            def loss_fn(pt):
                out, _ = model.apply(pt, batch, train=True, width_rate=wr,
                                     scaler_rate=sr, label_mask=lm, sample_weight=w,
                                     rng=jax.random.fold_in(key, 5000 + t), **extra)
                # weighted-SUM form so the cross-shard reduction recovers the
                # exact full-window mean gradient
                n_loc = jnp.sum(w)
                return out["loss"] * n_loc, n_loc

            # per-leaf grads even under the flat carry (see _local_train_vision)
            (lsum, n_loc), grads = jax.value_and_grad(loss_fn, has_aux=True)(
                spec.unflatten(p) if spec is not None else p)
            if seq_sharded:
                grads, lsum, n_glob = jax.lax.psum((grads, lsum, n_loc), data_axis)
            else:
                n_glob = n_loc
            loss = lsum / jnp.maximum(n_glob, 1e-6)
            live = None if step_limit is None else (t < step_limit)
            p, opt = self._apply_update(p, grads, opt, emasks, spec, wr,
                                        n_glob, lr, has=live)
            # Logger weight: rows per window (ref train_transformer_fed.py
            # appends with input['label'].size(0)); Perplexity = exp(window CE).
            n = np.float32(R)  # static trace-time constant, not a device wrap
            if live is not None:
                n = n * live.astype(jnp.float32)  # deadline: truncated steps
            acc = (acc[0] + loss * n, acc[1] + jnp.exp(loss) * n, acc[2] + n)
            return (p, opt, acc), None

        acc0 = (jnp.zeros(()), jnp.zeros(()), jnp.zeros(()))
        (p, _, acc), _ = jax.lax.scan(step, (p, opt, acc0), jnp.arange(E * S),
                                      unroll=self.scan_unroll)
        if spec is not None:
            p = spec.unflatten(p)
        return p, {"loss_sum": acc[0], "score_sum": acc[1], "n": acc[2]}

    # ------------------------------------------------------------------
    # the round program
    # ------------------------------------------------------------------

    def _round_core(self, params, key, lr, user_loc, user_glob, data,
                    resid=None, sched_buf=None, epoch=None):
        """One round's in-jit core, per device (runs inside ``shard_map``):
        slot training + counted-average ``psum``.  Shared by the one-round
        program (:meth:`_build_train`) and the K-round superstep scan
        (:meth:`_build_superstep`), so the two paths are the same
        computation by construction.

        ``user_loc``: this device's slot of active users as indices into its
        local view of the per-user data stacks (== ``user_glob`` under
        replicated placement), or ``None`` when the data stacks are already
        in slot order (the streaming cohort path: slot j's data IS row j, no
        gather); ``user_glob``: the users' global ids, used for all
        per-client randomness so results are placement- and
        mesh-shape-invariant.  -1 = padding slot.  ``data`` carries the
        fix-rates table as its last element in fix mode.  ``resid``: this
        device's ``[slots, total]`` error-feedback carry (lossy wire codecs
        only; None under dense).  ``sched_buf``: the replicated ``[2,
        total]`` staleness carry (buffered-async aggregation only, ISSUE 9;
        the previous round's reduced sums/counts apply here one round late
        while this cohort's reduction is buffered for the next).  Returns
        ``(new_params, metric sums, new_resid-or-None,
        new_sched_buf-or-None)``."""
        model, cfg, mesh = self.model, self.cfg, self.mesh
        dynamic = cfg["model_split_mode"] == "dynamic"
        # staticcheck: allow(no-float-coercion): trace-time config scalar
        failure_rate = float(cfg.get("client_failure_rate", 0.0) or 0.0)
        valid = (user_glob >= 0).astype(jnp.float32)
        ugid = jnp.maximum(user_glob, 0)
        if failure_rate > 0.0:
            # net-new fault injection (the reference only models dropout
            # implicitly via frac-sampling): a failed client trains but
            # its update never reaches aggregation -- like a crash after
            # local work. All-failed rounds degrade to the stale rule.
            fkey = failure_stream_key(key)
            alive = 1.0 - jax.vmap(
                lambda u: jax.random.bernoulli(jax.random.fold_in(fkey, u), failure_rate)
            )(ugid).astype(jnp.float32)
            valid = valid * alive
        uidx = None if user_loc is None else jnp.maximum(user_loc, 0)
        if dynamic:
            # the shared per-round rate stream (fed.core.round_rates):
            # re-roll ALL users, index the active ones (ref fed.py:15-24)
            rates_abs = round_rates(key, cfg, ugid)
        else:
            rates_abs = data[-1][ugid]  # fix_rates passed as last data arg
        wr = rates_abs / self.global_rate
        slot_keys = client_stream_keys(key, ugid)

        if self.is_lm:
            all_rows, all_lm = data[0], data[1]
            rows = all_rows if uidx is None else all_rows[uidx]
            lm = all_lm if uidx is None else all_lm[uidx]
            n_data = mesh.shape["data"]
            if self._sched_spec.has_deadline:
                # deadline stragglers (ISSUE 9): per-client step budgets
                # from the shared (round key, uid) stream -- the grouped
                # engine draws the identical budgets in _level_core
                total_steps = self.local_epochs * _ceil_div(
                    int(rows.shape[-1]), self.bptt)
                limits = deadline_steps(key, ugid, total_steps,
                                        self._sched_spec.deadline_min_frac)
                trained, ms = jax.vmap(
                    lambda w_, r_, l_, k_, lim_: self._local_train_lm(
                        params, w_, r_, l_, k_, lr,
                        data_axis="data" if n_data > 1 else None,
                        n_data=n_data, step_limit=lim_)
                )(wr, rows, lm, slot_keys, limits)
            else:
                trained, ms = jax.vmap(
                    lambda w_, r_, l_, k_: self._local_train_lm(
                        params, w_, r_, l_, k_, lr,
                        data_axis="data" if n_data > 1 else None, n_data=n_data)
                )(wr, rows, lm, slot_keys)
        else:
            all_x, all_y, all_m, all_lm = data[0], data[1], data[2], data[3]
            if uidx is None:
                xs, ys, sms, lm = all_x, all_y, all_m, all_lm
            else:
                xs, ys, sms, lm = all_x[uidx], all_y[uidx], all_m[uidx], all_lm[uidx]
            n_data = mesh.shape["data"]
            if self._sched_spec.has_deadline:
                total_steps = self.local_epochs * _ceil_div(
                    int(xs.shape[1]), self.batch_size)
                limits = deadline_steps(key, ugid, total_steps,
                                        self._sched_spec.deadline_min_frac)
                trained, ms = jax.vmap(
                    lambda w_, x_, y_, m_, l_, k_, lim_: self._local_train_vision(
                        params, w_, x_, y_, m_, l_, k_, lr,
                        data_axis="data" if n_data > 1 else None,
                        n_data=n_data, step_limit=lim_)
                )(wr, xs, ys, sms, lm, slot_keys, limits)
            else:
                trained, ms = jax.vmap(
                    lambda w_, x_, y_, m_, l_, k_: self._local_train_vision(
                        params, w_, x_, y_, m_, l_, k_, lr,
                        data_axis="data" if n_data > 1 else None, n_data=n_data)
                )(wr, xs, ys, sms, lm, slot_keys)

        if self._poison is not None:
            # chaos NaN poison (ISSUE 15): the matched (round, uid) slots'
            # updates go non-finite BEFORE aggregation -- the adversarial-
            # client model the quarantine gate / watchdog rollback recover
            # from.  Padding slots (uid -1) never match.
            if epoch is None:
                raise ValueError(
                    "chaos_poison needs the round epoch threaded into the "
                    "round core (pass epoch= to train_round)")
            from ..chaos.inject import poison_updates

            trained = poison_updates(trained, self._poison, epoch, user_glob)
        shapes = {k: v.shape for k, v in params.items()}
        cms = jax.vmap(lambda w_, l_, v_: jax.tree_util.tree_map(
            lambda m: m * v_, make_count_masks(shapes, model.specs, model.groups, w_, l_)))(
            wr, lm, valid)
        ok = None
        if self._quarantine.enabled:
            # client-update quarantine (ISSUE 15 tentpole): the gate folds
            # into BOTH the sums and the counts BEFORE the single global
            # psum below -- a quarantined client is a zero-count
            # participant, and the where-select sanitises its (possibly
            # NaN) trained values so NaN * 0-count cannot poison the sum.
            # All-clean rounds are bit-identical: the gate multiplies by
            # 1.0 and the select returns the unchanged value.
            from ..obs.probes import quarantine_gate

            ok = quarantine_gate(trained, params, cms,
                                 self._quarantine.max_norm)
            okf = ok.astype(jnp.float32)
            cms = {k: cms[k] * okf.reshape((-1,) + (1,) * (cms[k].ndim - 1))
                   for k in cms}
            trained = {k: jnp.where(ok.reshape((-1,) + (1,) * (v.ndim - 1)),
                                    v, jnp.zeros((), v.dtype))
                       for k, v in trained.items()}
        summed = {k: jnp.sum(trained[k] * cms[k], axis=0) for k in params}
        counts = {k: jnp.sum(cms[k], axis=0) for k in params}
        codec = self._codec(params)
        if codec is None:
            # ONE psum bind for sums+counts: the round's single global
            # collective (per-leaf addends are identical to two separate
            # psums, so this is bit-compatible; staticcheck audits the
            # exactly-one-psum budget)
            summed, counts = jax.lax.psum((summed, counts), "clients")
            new_resid = None
        else:
            # wire codec (ISSUE 8): quantise this device's partial -> the
            # SAME single psum bind carries the packed payload -> dequantise;
            # the error-feedback residual re-injects the compression error
            # next round.  cmax = this device's slot count (it bounds the
            # partial-sum magnitude, sizing the shared quantisation grid).
            from ..compress.codecs import compressed_psum

            summed, counts, new_resid = compressed_psum(
                codec, "clients", params, summed, counts, resid, key,
                int(user_glob.shape[0]))
        if self._sched_spec.buffered:
            # buffered-async aggregation (ISSUE 9): this cohort's reduction
            # lands NEXT round (staleness-weighted); the previous round's
            # buffered update applies now.  The single-psum wire contract
            # is untouched -- buffering happens after the reduction.
            new_params, new_buf = buffered_combine(
                params, sched_buf, summed, counts, FlatSpec.of(params),
                self._sched_spec.staleness)
        else:
            new_params = combine_counted(params, summed, counts)
            new_buf = None
        if ok is not None:
            # a quarantined client's metric sums may themselves be NaN
            # (its training diverged): select-sanitise, then mask like a
            # failed client -- its rate zeroes too, so the participation
            # probe and the ledger see a zero-count participant.  The
            # clean path (ok all-True) selects the unchanged values.
            okf = ok.astype(jnp.float32)
            ms = {k: jnp.where(ok, v, jnp.zeros((), v.dtype)) * valid
                  for k, v in ms.items()}
            ms["rate"] = rates_abs * valid * okf
            ms["obs_quarantine"] = jnp.reshape(
                jnp.sum(valid * (1.0 - okf)), (1,))
        else:
            ms = {k: v * valid for k, v in ms.items()}
            ms["rate"] = rates_abs * valid
        if self._obs_on:
            # in-program health probes (ISSUE 10): derived from the
            # already-reduced aggregates and the replicated carries --
            # ZERO new collectives (the staticcheck telemetry variants pin
            # the same one-psum budget and the same wire bytes); per-device
            # partials ride the metrics out-spec and finish on the host
            ms = {**ms, **round_probes(self._obs_levels, params, new_params,
                                       summed, counts, ms["rate"],
                                       resid=new_resid, sched_buf=new_buf)}
            if self._obs_hist:
                # cohort histograms (ISSUE 12): fixed-bucket rows over the
                # per-slot metric sums this device already holds -- same
                # zero-collective contract as the scalar probes, same
                # metrics out-spec ride to the host.  total_steps is THE
                # denominator the deadline branches above budgeted against
                # (defined exactly when has_deadline).
                ms = {**ms, **round_hists(
                    self._obs_levels, ms["rate"], ms["loss_sum"], ms["n"],
                    key=key, uids=ugid,
                    total_steps=(total_steps
                                 if self._sched_spec.has_deadline else None),
                    min_frac=(self._sched_spec.deadline_min_frac
                              if self._sched_spec.has_deadline else None),
                    sched_buf=new_buf)}
        return new_params, ms, new_resid, new_buf

    def _data_specs(self) -> Tuple[P, ...]:
        """shard_map in_specs of the ``data`` tuple (incl. the fix-rates
        tail): per-user stacks are device-sharded under ``sharded``
        placement, replicated otherwise."""
        per_user = P("clients") if self.placement == "sharded" else P()
        if self.is_lm:
            data_specs = (per_user, per_user)
        else:
            data_specs = (per_user, per_user, per_user, per_user)
        if self.fix_rates is not None:
            data_specs = data_specs + (P(),)
        return data_specs

    def _ep_kw(self, ep) -> dict:
        """The round core's ``epoch=`` kwarg, present ONLY when a chaos
        poison table is configured: unpoisoned calls keep the exact
        pre-ISSUE-15 signature (the staticcheck/wirecheck seeded-detector
        tests monkeypatch ``_round_core`` with epoch-free stubs)."""
        return {} if self._poison is None else {"epoch": ep}

    def _build_train(self):
        # chaos poison (ISSUE 15): the K=1 program takes the round epoch
        # as one extra replicated scalar ONLY when a poison table is
        # configured -- unpoisoned programs keep their exact argument list
        poisoned = self._poison is not None
        ep_args = (P(),) if poisoned else ()

        def _split_ep(extra):
            return (extra[0] if poisoned else None), \
                (extra[1:] if poisoned else extra)

        if self._codec_name != "dense":
            # compressed round (ISSUE 8): the EF residual is an extra
            # donated carry -- [1, slots, total] per device in, same out
            def body(params, resid, key, lr, *rest):
                ep, rest = _split_ep(rest)
                user_loc, user_glob, *data = rest
                p, ms, r, _ = self._round_core(params, key, lr, user_loc,
                                               user_glob, data,
                                               resid=resid[0],
                                               **self._ep_kw(ep))
                return p, r[None], ms

            fn = _shard_map(
                body, self.mesh,
                in_specs=(P(), P("clients"), P(), P()) + ep_args
                + (P("clients"), P("clients")) + self._data_specs(),
                out_specs=(P(), P("clients"), P("clients")),
            )
            # resid-only donation: donating the params carry alongside the
            # params-sized resid trips the XLA:CPU executable-serialization
            # bug (see _WireCodecCarry) -- both engines pin the same policy
            return jax.jit(fn, donate_argnums=(1,))

        if self._sched_spec.buffered:
            # buffered-async round (ISSUE 9): the staleness buffer is an
            # extra donated carry -- replicated [2, total] in, same out
            def body(params, buf, key, lr, *rest):
                ep, rest = _split_ep(rest)
                user_loc, user_glob, *data = rest
                p, ms, _, nb = self._round_core(params, key, lr, user_loc,
                                                user_glob, data,
                                                sched_buf=buf,
                                                **self._ep_kw(ep))
                return p, nb, ms

            fn = _shard_map(
                body, self.mesh,
                in_specs=(P(), P(), P(), P()) + ep_args
                + (P("clients"), P("clients")) + self._data_specs(),
                out_specs=(P(), P(), P("clients")),
            )
            # buf-only donation: donating the params carry alongside a
            # params-sized buffer output is the trigger pattern of the
            # XLA:CPU executable-serialization bug (see _WireCodecCarry /
            # _SchedBufCarry) -- same policy as the codec programs
            return jax.jit(fn, donate_argnums=(1,))

        def body(params, key, lr, *rest):
            ep, rest = _split_ep(rest)
            user_loc, user_glob, *data = rest
            p, ms, _, _ = self._round_core(params, key, lr, user_loc,
                                           user_glob, data,
                                           **self._ep_kw(ep))
            return p, ms

        fn = _shard_map(
            body, self.mesh,
            in_specs=(P(), P(), P()) + ep_args
            + (P("clients"), P("clients")) + self._data_specs(),
            out_specs=(P(), P("clients")),
        )
        return jax.jit(fn, donate_argnums=(0,))

    def _build_superstep(self, k: int, per_dev: int, in_jit: bool,
                         num_active: int = 0, eval_mask=None, fused_eval=None,
                         lr_arg: bool = False, streaming: bool = False,
                         arms: int = 0):
        """One jitted+donated program for ``k`` federated rounds: the round
        boundary leaves the host (ISSUE 2 tentpole).

        A ``lax.scan`` INSIDE the ``shard_map`` carries ``(params)`` across
        rounds; per-round keys are ``fold_in(base_key, epoch)``, the LR
        schedule is evaluated in-jit from the round index
        (:func:`~..utils.optim.make_traced_lr_fn`), and with ``in_jit``
        sampling (replicated placement) the active clients are drawn from
        :func:`~..fed.core.round_users` inside the scan -- a steady-state
        superstep moves no slot ids at all.  ``in_jit=False`` takes a
        host-packed ``[k, slots]`` schedule as scan xs (sharded placement:
        slot->owner packing is placement bookkeeping).  Per-round per-slot
        metric sums come back stacked ``[k, slots]`` -- one fetch per
        superstep.

        ``eval_mask`` (ISSUE 4 tentpole): a static k-tuple of bools; on
        rounds where it fires, the :class:`~.evaluation.FusedEval` core --
        sBN recalibration + Local/Global eval -- runs INSIDE this program on
        the pre-staged eval operands (appended to the argument list with
        ``fused_eval.specs``), and the eval results come back stacked over
        the superstep's eval points.  The mask compresses to scan groups
        (:func:`superstep_eval_groups`), so ``eval_interval=1`` is one
        (round + eval) scan of length k, not k unrolled blocks.
        ``lr_arg=True`` takes the LR as a staged scalar argument instead of
        the traced schedule (ReduceLROnPlateau: LR is constant within a
        superstep, stepped on eval metrics at superstep boundaries).

        ``streaming=True`` (ISSUE 6): the per-user data stacks are NOT a
        program invariant -- the sampled cohort's shards ride the scan xs as
        ``[k, slots, ...]`` stacks sharded over the slot axis (one slot = one
        device-local cohort row, so the round core indexes identity), and
        only the tiny fix-rates table stays invariant.  Program memory is
        O(k x active_clients), independent of the population."""
        mesh = self.mesh
        n_dev = mesh.shape["clients"]
        slots_total = per_dev * n_dev
        num_users = self.cfg["num_users"]
        lr_fn = self._lr_fn
        sampler = self._sampler  # the in-jit draw's kind (ISSUE 11)
        if streaming:
            n_stream = 2 if self.is_lm else 4
            n_fix = 1 if self.fix_rates is not None else 0
            data_specs = (P(None, "clients"),) * n_stream + (P(),) * n_fix
            sched_specs = (P(None, "clients"),)
        else:
            data_specs = self._data_specs()
            n_data_args = len(data_specs)
            sched_specs = () if in_jit else (P(None, "clients"), P(None, "clients"))
        groups = superstep_eval_groups(eval_mask) if eval_mask else None
        if groups is not None and not any(ev for _, ev, _ in groups):
            groups = None  # an all-False mask is the plain train superstep
        codec = self._codec_name != "dense"
        buffered = self._sched_spec.buffered
        arms_axis = "arms" if (arms and self._arms_mesh) else None
        if groups is not None and arms:
            # arms multiplexer (ISSUE 14): the fused eval phase runs vmapped
            # over the (local) arms axis against the shared committed
            # operands -- one arm per device row under the mesh placement
            fused_eval = _ArmsFusedEval(fused_eval, arms, axis=arms_axis)
        # in-jit availability sampling (ISSUE 9): only the eager replicated
        # path samples inside the scan -- a non-uniform schedule threads its
        # [T, U] trace in as a replicated program argument there; every
        # host-schedule path (sharded/streaming/grouped) consumes the trace
        # through fed.core.superstep_user_schedule instead
        trace_arg = bool(in_jit and not streaming
                         and self._sched_spec.kind != "uniform")

        def sbody(params, *all_rest):
            if codec:
                # wire codec (ISSUE 8): the EF residual joins the scan carry
                resid0, base_key, epoch0, *rest = all_rest
            elif buffered:
                # buffered-async aggregation (ISSUE 9): the staleness buffer
                # joins the scan carry
                buf0, base_key, epoch0, *rest = all_rest
            else:
                base_key, epoch0, *rest = all_rest
            idx = 0
            if trace_arg:
                trace = rest[0]
                idx = 1
            ascales = None
            if lr_arg:
                # under arms this is the staged PER-ARM LR vector [E]
                lr_const = rest[idx]
                idx += 1
            elif arms:
                # per-arm multiplicative scales over the shared schedule
                ascales = rest[idx]
                idx += 1
            if streaming:
                sched_ug = rest[idx]
                idx += 1
                sdata = rest[idx:idx + n_stream]
                idx += n_stream
                fix = rest[idx:idx + n_fix]
                idx += n_fix
                eval_ops = rest[idx:]
            else:
                if not in_jit:
                    sched_ul, sched_ug = rest[idx], rest[idx + 1]
                    idx += 2
                data = rest[idx:idx + n_data_args]
                eval_ops = rest[idx + n_data_args:]

            def step(carry, xs):
                if codec:
                    p, rs, sb = carry[0], carry[1], None
                elif buffered:
                    p, rs, sb = carry[0], None, carry[1]
                else:
                    p, rs, sb = carry, None, None

                def pack(new_p, nr, nb):
                    if codec:
                        return (new_p, nr)
                    if buffered:
                        return (new_p, nb)
                    return new_p

                if arms:
                    # arms multiplexer (ISSUE 14): one round of E arms --
                    # the round core vmapped over the leading arms axis of
                    # the params carry (and EF residual), each arm keyed by
                    # its own stream root (base_key here is the stacked
                    # [E] arm keys) with the population stacks SHARED.
                    # The vmapped psum inside _round_core stays EXACTLY
                    # one bind per fused round (a batched pytree psum);
                    # wire bytes scale linearly in E (staticcheck arms
                    # variants audit both by equality).  In-jit sampling
                    # draws each arm its OWN cohort from its stream -- a
                    # solo run with the same seed replays it bitwise;
                    # host-schedule paths share the packed slots.
                    if in_jit:
                        (t,) = xs
                        ul_s = ug_s = None
                    else:
                        t, ul_s, ug_s = xs
                    scales = lr_const if lr_arg else ascales

                    def arm_core(p_e, akey, sc_e, rs_e):
                        key = jax.random.fold_in(akey, t)
                        lr = sc_e if lr_arg else lr_fn(t) * sc_e
                        if in_jit:
                            if trace_arg:
                                row = jnp.take(trace,
                                               (t - 1) % trace.shape[0],
                                               axis=0)
                                active = round_users(key, num_users,
                                                     num_active, avail=row,
                                                     sampler=sampler)
                            else:
                                active = round_users(key, num_users,
                                                     num_active,
                                                     sampler=sampler)
                            padv = jnp.full((slots_total - num_active,),
                                            -1, jnp.int32)
                            padded = jnp.concatenate([active, padv])
                            d = jax.lax.axis_index("clients")
                            ug_e = jax.lax.dynamic_slice(
                                padded, (d * per_dev,), (per_dev,))
                            ul_e = ug_e
                        else:
                            ul_e, ug_e = ul_s, ug_s
                        new_p, ms, nr, _ = self._round_core(
                            p_e, key, lr, ul_e, ug_e, data, resid=rs_e,
                            **self._ep_kw(t))
                        return new_p, ms, nr

                    if codec:
                        new_p, ms, nr = jax.vmap(arm_core)(
                            p, base_key, scales, rs)
                    else:
                        new_p, ms, nr = jax.vmap(
                            arm_core, in_axes=(0, 0, 0, None))(
                            p, base_key, scales, None)
                    return pack(new_p, nr, None), ms

                if streaming:
                    t, ug, *d = xs
                    key = jax.random.fold_in(base_key, t)
                    lr = lr_const if lr_arg else lr_fn(t)
                    # slot-local cohort rows: user_loc=None = identity gather
                    new_p, ms, nr, nb = self._round_core(
                        p, key, lr, None, ug, tuple(d) + tuple(fix),
                        resid=rs, sched_buf=sb, **self._ep_kw(t))
                    return pack(new_p, nr, nb), ms
                if in_jit:
                    (t,) = xs
                    key = jax.random.fold_in(base_key, t)
                    if trace_arg:
                        # availability-trace sampling (ISSUE 9): round t's
                        # 0/1 row gates the shared sampling stream; slots
                        # the availability cannot fill come back -1
                        # (padding).  (t - 1) % T is the host twin's index
                        # (ScheduleSpec.avail_row), shared by construction.
                        row = jnp.take(trace, (t - 1) % trace.shape[0],
                                       axis=0)
                        active = round_users(key, num_users, num_active,
                                             avail=row, sampler=sampler)
                    else:
                        active = round_users(key, num_users, num_active,
                                             sampler=sampler)
                    pad = jnp.full((slots_total - num_active,), -1, jnp.int32)
                    padded = jnp.concatenate([active, pad])
                    d = jax.lax.axis_index("clients")
                    ug = jax.lax.dynamic_slice(padded, (d * per_dev,), (per_dev,))
                    ul = ug
                else:
                    t, ul, ug = xs
                    key = jax.random.fold_in(base_key, t)
                lr = lr_const if lr_arg else lr_fn(t)
                new_p, ms, nr, nb = self._round_core(p, key, lr, ul, ug,
                                                     data, resid=rs,
                                                     sched_buf=sb,
                                                     **self._ep_kw(t))
                return pack(new_p, nr, nb), ms

            epochs = epoch0 + jnp.arange(k, dtype=jnp.int32)
            if streaming:
                xs = (epochs, sched_ug) + tuple(sdata)
            else:
                xs = (epochs,) if in_jit else (epochs, sched_ul, sched_ug)
            if codec:
                # arms: the per-device residual arrives [E, 1, slots,
                # total] -- drop the device axis behind the arms axis
                carry0 = (params, resid0[:, 0] if arms else resid0[0])
            elif buffered:
                carry0 = (params, buf0)
            else:
                carry0 = params

            def unpack(carry):
                if codec:
                    return carry[0], ((carry[1][:, None] if arms
                                       else carry[1][None]),)
                if buffered:
                    return carry[0], (carry[1],)
                return carry, ()

            if groups is None:
                carry, ms = jax.lax.scan(step, carry0, xs)
                p_out, extra = unpack(carry)
                return (p_out,) + extra + (ms,)
            carry, ms, ev = eval_fused_scan(
                step, carry0, xs, epochs, groups, fused_eval, eval_ops,
                params_of=(lambda c: c[0]) if (codec or buffered) else None)
            p_out, extra = unpack(carry)
            return (p_out,) + extra + (ms, ev)

        # under the mesh placement the stacked [E] leaves -- params carry,
        # arm keys, LR scales, metrics -- shard over the 'arms' axis (one
        # arm per device row group); under vmap they replicate
        arm_lead = P(arms_axis)
        lr_specs = (arm_lead if arms else P(),) if (lr_arg or arms) else ()
        trace_specs = (P(),) if trace_arg else ()
        eval_specs = tuple(fused_eval.specs) if groups else ()
        resid_specs = (self._resid_pspec(),) if codec else ()
        buf_specs = (P(),) if buffered else ()
        carry_specs = resid_specs + buf_specs  # mutually exclusive
        ms_spec = P(None, arms_axis, "clients") if arms \
            else P(None, "clients")
        params_spec = arm_lead if arms else P()
        key_spec = arm_lead if arms else P()
        out_specs = (params_spec,) + carry_specs + (ms_spec,)
        if groups is not None:
            out_specs = out_specs + (fused_eval.out_specs,)
        fn = _shard_map(
            sbody, mesh,
            in_specs=(params_spec,) + carry_specs + (key_spec, P())
            + trace_specs + lr_specs + sched_specs + data_specs
            + eval_specs,
            out_specs=out_specs,
        )
        # codec/buffered programs donate ONLY their extra carry (see
        # _WireCodecCarry: params donation + a params-sized extra output
        # trips an XLA:CPU executable-serialization bug when reloaded from
        # the persistent compile cache; caught by the masked signsgd
        # checkpoint round-trip on a warm cache).  Arms programs (ISSUE
        # 14) donate NOTHING when dense: donating the E-stacked params
        # carry intermittently corrupts single leaves (1e24-magnitude
        # garbage) when the program is DESERIALIZED from the persistent
        # cache -- the same upstream XLA:CPU bug class, reproduced on the
        # multiplexed driver's resume path.  Cost: one extra E x params
        # buffer per dispatch, priced into the staticcheck arms budgets.
        if arms:
            donate = (1,) if (codec or buffered) else ()
        else:
            donate = (1,) if (codec or buffered) else (0,)
        return jax.jit(fn, donate_argnums=donate)

    def stage_cohort(self, store: ClientStore, user_schedule,
                     timer: PhaseTimer = None) -> StagedCohort:
        """Materialise + commit ONE superstep's cohort from a
        :class:`~.staging.ClientStore` (ISSUE 6 tentpole).

        ``user_schedule``: int32 ``[k, A]`` active user ids per round (the
        superstep sampling stream, :func:`~..fed.core.round_users`).  The
        cohort's shards pack into the stager's ring buffers in the masked
        engine's slot layout -- schedule order, ``ceil(A / n_dev)`` slots
        per device, padding slots materialising user 0 exactly like the
        eager gather -- and commit via explicit ``device_put`` + private
        copy, sharded over the slot axis.  Host/device cost is
        O(k x A x shard), independent of the population.  Call it for
        superstep N+1 right after dispatching superstep N: the device_put
        pipeline overlaps with N's compute (prefetch depth 1)."""
        if self._staging is None:
            raise ValueError("stage_cohort needs a mesh-attached engine")
        timer = timer if timer is not None else PhaseTimer()
        with timer.phase("stage"):
            # staticcheck: allow(no-asarray): host schedule normalization;
            # the cohort reaches the mesh via the stager's explicit puts only
            user_schedule = np.asarray(user_schedule, np.int32)
            if user_schedule.ndim != 2:
                raise ValueError(
                    f"user_schedule must be [k, A], got {user_schedule.shape}")
            k, a = user_schedule.shape
            n_dev = self.mesh.shape["clients"]
            per_dev = _ceil_div(a, n_dev)
            slots = per_dev * n_dev
            if self._cohort_stager is None:
                self._cohort_stager = CohortStager(self.mesh,
                                                   depth=self._prefetch_depth)
            st = self._cohort_stager
            n = store.shard_max
            if self.is_lm:
                layouts = [((k, slots), np.int32, -1),
                           ((k, slots) + store.row_shape, store.data.dtype, None),
                           ((k, slots, store.classes_size), np.float32, None)]
            else:
                layouts = [((k, slots), np.int32, -1),
                           ((k, slots, n) + store.data.shape[1:],
                            store.data.dtype, None),
                           ((k, slots, n), store.target.dtype, None),
                           ((k, slots, n), np.float32, None),
                           ((k, slots, store.classes_size), np.float32, None)]
            key = ("masked", k, slots)
            slot_i, bufs = st.buffers(key, layouts)
            sched = bufs[0]
            sched[:, :a] = user_schedule  # trailing slots stay -1 (padding)
            flat = sched.reshape(-1)
            if self.is_lm:
                store.fill_lm(flat, bufs[1].reshape((-1,) + bufs[1].shape[2:]))
                store.fill_labels(flat, bufs[2].reshape(-1, store.classes_size))
            else:
                store.fill_vision(flat,
                                  bufs[1].reshape((-1,) + bufs[1].shape[2:]),
                                  bufs[2].reshape((-1,) + bufs[2].shape[2:]),
                                  bufs[3].reshape(-1, n))
                store.fill_labels(flat, bufs[4].reshape(-1, store.classes_size))
            dev = st.commit(key, slot_i, bufs,
                            (P(None, "clients"),) * len(bufs))
        return StagedCohort(engine="masked", k=k, a=a, per_dev=per_dev,
                            sched=dev[0], data=tuple(dev[1:]))

    def train_superstep(self, params, base_key, epoch0: int, k: int,
                        data: Optional[Tuple[jnp.ndarray, ...]] = None,
                        user_schedule=None,
                        num_active: Optional[int] = None,
                        timer: PhaseTimer = None, eval_mask=None,
                        fused_eval=None, lr: Optional[float] = None,
                        cohort: Optional[StagedCohort] = None):
        """Run ``k`` rounds as ONE compiled program (``superstep_rounds``).

        Per-round keys are ``fold_in(base_key, epoch0 + r)`` -- the driver's
        stream with ``base_key = host_key``.  Under replicated placement
        with ``user_schedule=None`` the per-round active set is sampled
        in-jit from :func:`~..fed.core.round_users` (``num_active`` defaults
        to ``ceil(frac * num_users)``); under sharded placement a host
        ``user_schedule`` int32 ``[k, A]`` drawn from the same stream is
        required, packed here into owner-aligned slot arrays (scan xs).
        Returns ``(new_params, PendingMetrics)`` whose ``fetch()`` yields a
        LIST of k per-round metric dicts -- metrics accumulate on device and
        cross to the host once per superstep.

        ``eval_mask`` + ``fused_eval`` (ISSUE 4): run the fused sBN+eval
        phase in-program on the rounds where the static mask fires; the
        fetch then yields ``{"train": [k dicts], "eval": [per-eval dicts]}``
        with each eval dict carrying ``epoch``/``bn``/``local``/``global``.
        ``lr``: stage a constant LR scalar instead of the traced schedule
        (the ReduceLROnPlateau superstep mode).

        ``cohort`` (ISSUE 6): a :class:`~.staging.StagedCohort` from
        :meth:`stage_cohort` replaces ``data`` entirely -- the cohort's
        shards ride the scan xs and the program never sees the population.
        The slot layout and sampling stream match the in-jit draw, so a
        streamed superstep is bit-identical to the eager one."""
        self._reject_per_level_map()
        eval_mask = normalize_eval_mask(eval_mask, k, fused_eval)
        lr_arg = lr is not None
        if not lr_arg and self._lr_fn is None:
            self._lr_fn = make_traced_lr_fn(self.cfg)
        timer = timer if timer is not None else PhaseTimer()
        aspec = self._arms_spec
        arms = aspec.count if aspec is not None else 0
        if cohort is not None:
            if aspec is not None:
                raise ValueError(
                    "arms need the eager data path: a staged cohort holds "
                    "ONE schedule's shards, and per-arm cohorts would "
                    "multiply the staged bytes by E (a ROADMAP follow-on)")
            if cohort.engine != "masked" or cohort.k != k:
                raise ValueError(
                    f"cohort mismatch: staged for engine={cohort.engine!r} "
                    f"k={cohort.k}, dispatching masked k={k}")
            with timer.phase("stage"):
                a, per_dev = cohort.a, cohort.per_dev
                sched_args = (cohort.sched,)
                args = tuple(cohort.data)
                if self.fix_rates is not None:
                    args = args + self._staging.replicated(
                        "fix_rates", (self.fix_rates,))
                lr_args = (self._staging.scalar(lr),) if lr_arg else ()
                eval_args = tuple(fused_eval.ops) if eval_mask is not None else ()
                epoch0_dev = self._staging.scalar(epoch0, dtype=np.int32)
                params = self._staging.commit(self._pin(params))
                carry_args = self._carry_args(params)
                pkey = (k, per_dev, "stream", a, eval_mask, lr_arg)
                prog = self._superstep_progs.get(pkey)
                if prog is None:
                    prog = self._build_superstep(k, per_dev, False,
                                                 num_active=a,
                                                 eval_mask=eval_mask,
                                                 fused_eval=fused_eval,
                                                 lr_arg=lr_arg, streaming=True)
                    self._superstep_progs[pkey] = prog
            with timer.phase("dispatch"):
                out = prog(params, *carry_args, base_key, epoch0_dev,
                           *lr_args, *sched_args, *args, *eval_args)
            return self._assemble_superstep(out, epoch0, k, eval_mask,
                                            fused_eval)
        if data is None:
            raise ValueError("train_superstep needs data stacks or a cohort")
        with timer.phase("stage"):
            n_dev = self.mesh.shape["clients"]
            sched_args = ()
            if user_schedule is not None:
                # staticcheck: allow(no-asarray): host slot-id normalization;
                # the ids reach the mesh via explicit staging.put only
                user_schedule = np.asarray(user_schedule, np.int32)
                if user_schedule.ndim != 2 or user_schedule.shape[0] != k:
                    raise ValueError(
                        f"user_schedule must be [k={k}, A], got {user_schedule.shape}")
            if self.placement == "sharded":
                if user_schedule is None:
                    raise ValueError(
                        "sharded placement needs a host user_schedule [k, A]: "
                        "slot->owner packing is placement bookkeeping (draw it "
                        "from fed.core.round_users to keep the superstep stream)")
                u_pad = int(data[0].shape[0])
                if u_pad % n_dev:
                    raise ValueError(
                        f"sharded placement needs the user axis ({u_pad}) padded to a "
                        f"multiple of the clients axis ({n_dev}); use shard_client_data")
                per = u_pad // n_dev
                rows = [[user_schedule[r][user_schedule[r] // per == d]
                         for d in range(n_dev)] for r in range(k)]
                # bucket the per-device slot count: the raw max ownership
                # density fluctuates draw to draw, and it keys the K-round
                # program -- unbucketed it recompiles the superstep (K x the
                # flagship compile) whenever the density changes
                per_dev = _bucket_pow2(max(1, max(len(b) for row in rows
                                                  for b in row)))
                ug_buf = self._packer.buffer(("ss_glob", k, n_dev, per_dev),
                                             (k, n_dev, per_dev))
                ul_buf = self._packer.buffer(("ss_loc", k, n_dev, per_dev),
                                             (k, n_dev, per_dev))
                for r in range(k):
                    for d, b in enumerate(rows[r]):
                        ug_buf[r, d, : len(b)] = b
                        ul_buf[r, d, : len(b)] = b - d * per
                ug = self._staging.put(ug_buf.reshape(k, -1), spec=P(None, "clients"))
                ul = self._staging.put(ul_buf.reshape(k, -1), spec=P(None, "clients"))
                sched_args, in_jit, a = (ul, ug), False, 0
                args = tuple(data)
            else:
                if user_schedule is not None:
                    a = user_schedule.shape[1]
                    per_dev = _ceil_div(a, n_dev)
                    buf = self._packer.buffer(("ss_rep", k, per_dev * n_dev),
                                              (k, per_dev * n_dev))
                    buf[:, :a] = user_schedule
                    ug = self._staging.put(buf, spec=P(None, "clients"))
                    sched_args, in_jit = (ug, ug), False
                else:
                    a = int(num_active if num_active is not None
                            else math.ceil(self.cfg["frac"] * self.cfg["num_users"]))
                    per_dev = _ceil_div(a, n_dev)
                    in_jit = True
                args = self._staging.replicated("train_data", data)
            if self.fix_rates is not None:
                args = args + self._staging.replicated("fix_rates", (self.fix_rates,))
            arm_vec_spec = P("arms") if self._arms_mesh else P()
            if lr_arg:
                # arms: the per-arm LR vector [E] (Plateau steps each arm's
                # own state at superstep boundaries); solo: a scalar
                lr_args = ((self._staging.put(
                    np.asarray(lr, np.float32).reshape(arms),  # staticcheck: allow(no-asarray): host LR-vector normalization; reaches the mesh via the explicit staging.put
                    spec=arm_vec_spec),) if arms
                    else (self._staging.scalar(lr),))
            elif arms:
                # per-arm multiplicative LR scales over the shared schedule
                lr_args = (self._staging.put(
                    np.asarray(aspec.lr_scales, np.float32),  # staticcheck: allow(no-asarray): host scale-vector normalization; reaches the mesh via the explicit staging.put
                    spec=arm_vec_spec),)
            else:
                lr_args = ()
            eval_args = tuple(fused_eval.ops) if eval_mask is not None else ()
            epoch0_dev = self._staging.scalar(epoch0, dtype=np.int32)
            # commit the params carry: an uncommitted init tree would
            # specialise this program once and recompile on round 2 when the
            # outputs come back mesh-committed (staticcheck recompile audit);
            # the layout pin rides the same commit (models/layout.py policy).
            # Under the mesh arms placement the stacked axis commits sharded
            # over the 'arms' rows (each arm's params live on its own rows)
            params = self._staging.commit(
                self._pin(params),
                spec=P("arms") if (arms and self._arms_mesh) else P())
            carry_args = self._carry_args(params)
            trace_args = ()
            if in_jit and self._sched_spec.kind != "uniform":
                # the availability trace enters the in-jit sampling program
                # as a committed replicated argument (ISSUE 9); the cached
                # property returns one host array, so this commit is a
                # steady-state identity hit
                trace_args = self._staging.replicated(
                    "sched_trace", (self._sched_spec.trace,))
            # arms (ISSUE 14): the program takes the stacked [E] per-arm
            # key roots in the base-key slot -- THE one stream derivation
            # (fed.core.arm_stream_keys), shared with solo runs; the mesh
            # placement commits them one per arm row group
            if aspec is not None:
                dispatch_key = arm_stream_keys(base_key, aspec.seeds)
                if self._arms_mesh:
                    dispatch_key = self._staging.put(dispatch_key,
                                                     spec=P("arms"))
            else:
                dispatch_key = base_key
            pkey = (k, per_dev, in_jit, a, eval_mask, lr_arg, arms,
                    self._arms_mesh)
            prog = self._superstep_progs.get(pkey)
            if prog is None:
                prog = self._build_superstep(k, per_dev, in_jit, num_active=a,
                                             eval_mask=eval_mask,
                                             fused_eval=fused_eval,
                                             lr_arg=lr_arg, arms=arms)
                self._superstep_progs[pkey] = prog
        with timer.phase("dispatch"):
            out = prog(params, *carry_args, dispatch_key, epoch0_dev,
                       *trace_args, *lr_args, *sched_args, *args, *eval_args)
        return self._assemble_superstep(out, epoch0, k, eval_mask, fused_eval,
                                        arms=arms)

    def _assemble_superstep(self, out, epoch0: int, k: int, eval_mask,
                            fused_eval, arms: int = 0):
        """Package one superstep dispatch's outputs: ``(new_params,
        PendingMetrics)``; shared by the eager and streaming paths.  Under a
        lossy wire codec the second output is the new error-feedback carry;
        under buffered-async aggregation it is the new staleness buffer --
        either way stashed on the engine (read/restored via
        :meth:`wire_resid_host`/:meth:`set_wire_resid` or
        :meth:`~..sched.buffer._SchedBufCarry.sched_buf_host`/
        :meth:`set_sched_buf` at checkpoint boundaries).

        ``arms`` (ISSUE 14): every fetched leaf carries the arms axis right
        behind the round/eval-stack axis; the assemble slices each arm out
        and runs the solo assembly on it, returning ``{"arms": [per-arm
        results]}`` -- each entry exactly what a solo run's fetch yields
        (probe records included), so downstream consumers are per-arm
        unchanged."""
        if self._codec_name != "dense":
            self._resid = out[1]
            out = (out[0],) + out[2:]
        elif self._sched_spec.buffered:
            self._sched_buf = out[1]
            out = (out[0],) + out[2:]
        n_dev = self.mesh.shape["clients"]
        # the quarantine counter rides the metrics pytree as an obs_ probe
        # even under telemetry='off' (ISSUE 15): split whenever either is on
        obs_on = self._obs_on or self._quarantine.enabled

        def _split(host):
            """Probe leaves out of a fetched metrics tree (ISSUE 10):
            telemetry-off trees pass through untouched (None probes)."""
            if obs_on:
                return split_probes(host, n_dev)
            return host, None

        if eval_mask is None:
            new_params, ms = out

            def _assemble_one(host):
                host, probes = _split(host)
                rounds = [{name: v[r] for name, v in host.items()}
                          for r in range(k)]
                if probes is not None:
                    return {"train": rounds, "obs": probes}
                return rounds

            if arms:
                def _assemble(host):
                    return {"arms": [
                        _assemble_one({name: v[:, e]
                                       for name, v in host.items()})
                        for e in range(arms)]}

                return new_params, PendingMetrics(ms, assemble=_assemble)
            return new_params, PendingMetrics(ms, assemble=_assemble_one)

        new_params, ms, ev = out
        eval_epochs = [epoch0 + r for r, m in enumerate(eval_mask) if m]

        def _assemble_eval_one(host):
            ms_h, ev_h = host
            ms_h, probes = _split(ms_h)
            out_d = {"train": [{name: v[r] for name, v in ms_h.items()}
                               for r in range(k)],
                     "eval": fused_eval.assemble(ev_h, eval_epochs)}
            if probes is not None:
                out_d["obs"] = probes
            return out_d

        if arms:
            def _assemble_eval(host):
                ms_h, ev_h = host
                return {"arms": [
                    _assemble_eval_one((
                        {name: v[:, e] for name, v in ms_h.items()},
                        jax.tree_util.tree_map(lambda v: v[:, e], ev_h)))
                    for e in range(arms)]}

            return new_params, PendingMetrics((ms, ev),
                                              assemble=_assemble_eval)
        return new_params, PendingMetrics((ms, ev),
                                          assemble=_assemble_eval_one)

    def program_cache_size(self) -> int:
        """Total compiled specializations across this engine's train
        programs (round + superstep).  bench.py samples the growth per timed
        round to flag fresh-compile rounds and exclude them from the
        steady-state average."""
        progs = ([self._train] if self._train is not None else []) \
            + list(self._superstep_progs.values())
        return sum(p._cache_size() for p in progs)

    def train_round(self, params, key, lr, user_idx, data: Tuple[jnp.ndarray, ...],
                    timer: PhaseTimer = None, epoch: Optional[int] = None):
        """Run one communication round.

        ``user_idx``: int32 [A] active user ids.  ``data``: for vision
        ``(all_x[U,N,H,W,C] uint8, all_y[U,N], all_m[U,N], all_lm[U,classes])``;
        for LM ``(all_rows[U,R,T], all_lm[U,vocab])``.  Under ``sharded``
        placement the per-user arrays must come from :func:`shard_client_data`
        (user axis padded to the clients-axis size and device-sharded); each
        client then trains on the device owning its shard -- no round moves
        any client data.  Under ``replicated`` placement the stacks are
        committed to the mesh once by the placement cache, so steady-state
        rounds move only the slot ids (explicit device_put).  ``timer``
        accounts the stage/dispatch phases.  Returns ``(new_params,
        per-client metric sums)`` with the metric sums still on device.
        """
        self._reject_per_level_map()
        if self._arms_spec is not None:
            raise ValueError(
                "arms need the fused superstep (train_superstep): the K=1 "
                "train_round path is the host-loop reference twin, which "
                "the arms axis would fork per arm -- set superstep_rounds "
                ">= 1 through the superstep API")
        if self._train is None:
            self._train = self._build_train()
        timer = timer if timer is not None else PhaseTimer()
        with timer.phase("stage"):
            n_dev = self.mesh.shape["clients"]
            # staticcheck: allow(no-asarray): host slot-id normalization;
            # the ids reach the mesh via explicit staging.put only
            user_idx = np.asarray(user_idx, np.int32)
            if self.placement == "sharded":
                u_pad = int(data[0].shape[0])
                if u_pad % n_dev:
                    raise ValueError(
                        f"sharded placement needs the user axis ({u_pad}) padded to a "
                        f"multiple of the clients axis ({n_dev}); use shard_client_data")
                per = u_pad // n_dev
                owners = user_idx // per
                by_dev = [user_idx[owners == d] for d in range(n_dev)]
                slots = max(1, max(len(b) for b in by_dev))
                user_glob = self._packer.buffer(("glob", n_dev, slots), (n_dev, slots))
                user_loc = self._packer.buffer(("loc", n_dev, slots), (n_dev, slots))
                for d, b in enumerate(by_dev):
                    user_glob[d, : len(b)] = b
                    user_loc[d, : len(b)] = b - d * per
                user_glob = user_glob.reshape(-1)
                user_loc = user_loc.reshape(-1)
                args = tuple(data)  # committed P('clients') by shard_client_data
            else:
                a = len(user_idx)
                pad = (-a) % n_dev
                user_glob = self._packer.buffer(("rep", a + pad), (a + pad,))
                user_glob[:a] = user_idx
                user_loc = user_glob
                args = self._staging.replicated("train_data", data)
            if self.fix_rates is not None:
                args = args + self._staging.replicated("fix_rates", (self.fix_rates,))
            lr = self._staging.scalar(lr)
            ug = self._staging.put(user_glob, spec=P("clients"))
            ul = ug if user_loc is user_glob else self._staging.put(user_loc, spec=P("clients"))
            # commit params so dispatch 1 and the steady state share ONE
            # program specialization (see train_superstep); layout pinned
            # by the same policy
            params = self._staging.commit(self._pin(params))
            carry_args = self._carry_args(params)
            ep_args = ()
            if self._poison is not None:
                # chaos poison (ISSUE 15): the (round, uid) match needs the
                # round's epoch; the superstep paths thread it from the
                # scan, the K=1 program takes it as a staged scalar
                if epoch is None:
                    raise ValueError(
                        "chaos_poison needs epoch= on train_round (the "
                        "K=1 program matches poisons by (round, uid))")
                ep_args = (self._staging.scalar(epoch, dtype=np.int32),)
        with timer.phase("dispatch"):
            if self._codec_name != "dense":
                new_p, self._resid, ms = self._train(
                    params, *carry_args, key, lr, *ep_args, ul, ug, *args)
                return new_p, ms
            if self._sched_spec.buffered:
                new_p, self._sched_buf, ms = self._train(
                    params, *carry_args, key, lr, *ep_args, ul, ug, *args)
                return new_p, ms
            return self._train(params, key, lr, *ep_args, ul, ug, *args)
