"""Ring attention: exact attention over a sequence-sharded mesh axis.

Net-new capability vs. the reference (SURVEY §5.7: it has no sequence/context
parallelism; bptt is a fixed 64-token window).  For long sequences the
transformer's attention can run with the sequence dimension sharded across a
mesh axis: each device keeps its local queries and rotates K/V blocks around
the ring with ``lax.ppermute`` (ICI neighbour exchanges, never all-gather),
accumulating the softmax online in the numerically stable (m, l, o) form --
the blockwise/flash decomposition.  Memory per device is O(S_local * d) and
the communication per layer is 2 * S * d * (n-1)/n elements.

Usage: inside a ``shard_map`` whose ``seq`` axis shards the S dimension:
``ring_attention(q, k, v, axis_name="seq", temperature=sqrt(d))``.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
from jax import lax


def _online_block(q, k_blk, v_blk, o, m, l, temperature):
    """Fold one K/V block into the (o, m, l) online-softmax accumulator.

    q: [..., Sq, d]; k_blk/v_blk: [..., Sk, d]; o: [..., Sq, d];
    m, l: [..., Sq].
    """
    scores = jnp.einsum("...qd,...kd->...qk", q, k_blk) / temperature
    m_new = jnp.maximum(m, jnp.max(scores, axis=-1))
    alpha = jnp.exp(m - m_new)
    p = jnp.exp(scores - m_new[..., None])
    l_new = l * alpha + jnp.sum(p, axis=-1)
    o_new = o * alpha[..., None] + jnp.einsum("...qk,...kd->...qd", p, v_blk)
    return o_new, m_new, l_new


def ring_attention(q: jnp.ndarray, k: jnp.ndarray, v: jnp.ndarray, *,
                   axis_name: str, axis_size: int, temperature) -> jnp.ndarray:
    """Exact (bidirectional) attention with sequence sharded over ``axis_name``.

    ``q``/``k``/``v``: ``[..., S_local, d]`` per-device blocks; ``axis_size``
    is the static ring length (mesh axis size).  Returns the attention output
    for the local queries -- equivalent (up to float association) to
    softmax(Q K^T / temperature) V over the full sequence.
    """
    m0 = jnp.full(q.shape[:-1], -jnp.inf, q.dtype)
    l0 = jnp.zeros(q.shape[:-1], q.dtype)
    o0 = jnp.zeros_like(q)
    nxt = [(j, (j + 1) % axis_size) for j in range(axis_size)]

    k_blk, v_blk, o, m, l = k, v, o0, m0, l0
    for i in range(axis_size):
        o, m, l = _online_block(q, k_blk, v_blk, o, m, l, temperature)
        if i + 1 < axis_size:  # rotate K/V to the ring neighbour
            k_blk = lax.ppermute(k_blk, axis_name, nxt)
            v_blk = lax.ppermute(v_blk, axis_name, nxt)
    return o / l[..., None]


def dense_attention(q, k, v, temperature):
    """Reference single-device attention (for tests/fallback)."""
    scores = jnp.einsum("...qd,...kd->...qk", q, k) / temperature
    attn = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("...qk,...kd->...qd", attn, v)
