"""Sequence-parallel (long-context) masked-LM training.

Net-new vs. the reference (its sequence handling is a fixed bptt=64 window,
SURVEY §5.7).  The sequence dimension is sharded over a mesh axis: every
device embeds its own positions (``pos_offset``), attention is exact ring
attention (K/V blocks rotate via ``ppermute``; see ring_attention.py), and
encoder layers are rematerialised so activation memory stays O(S_local).

The result: the same HeteroFL transformer scales to sequences ``n_seq`` times
longer than a single device could hold, with only neighbour-exchange
communication per layer.
"""

from __future__ import annotations

from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..config import ceil_width
from ..models.transformer import make_transformer
from ..utils.optim import clip_by_global_norm, make_optimizer
from .ring_attention import ring_attention
from .round_engine import _shard_map
from .staging import PlacementCache


class SeqParallelLM:
    """Jitted forward/train-step programs for a sequence-sharded transformer.

    ``cfg['bptt']`` is the FULL sequence length; it is sharded over the
    ``data`` mesh axis (``bptt % n_seq == 0``).
    """

    def __init__(self, cfg: Dict[str, Any], mesh, model_rate: float = None):
        self.cfg = cfg
        self.mesh = mesh
        self.n_seq = mesh.shape["data"]
        assert cfg["bptt"] % self.n_seq == 0, "bptt must divide the seq axis"
        t = cfg["transformer"]
        rate = model_rate if model_rate is not None else cfg["global_model_rate"]

        def attn(q, k, v, temp):
            return ring_attention(q, k, v, axis_name="data", axis_size=self.n_seq,
                                  temperature=temp)

        from ..models import parse_compute_dtype

        self.model = make_transformer(
            cfg["num_tokens"], ceil_width(t["embedding_size"], rate), t["num_heads"],
            ceil_width(t["hidden_size"], rate), t["num_layers"], t["dropout"],
            cfg["bptt"], cfg["mask_rate"], mask=cfg["mask"],
            compute_dtype=parse_compute_dtype(cfg.get("compute_dtype")),
            attn_impl=attn, remat=True)
        self._opt_init, self._opt_update = make_optimizer(cfg)
        self._fwd = None
        self._step = None
        # LR staged once per value (a per-call jnp.asarray wrap re-uploaded
        # an identical scalar every step; staticcheck's no-asarray rule
        # caught it -- ISSUE 3 satellite)
        self._staging = PlacementCache(mesh)

    def init(self, key):
        return self.model.init(key)

    def init_opt(self, params):
        return self._opt_init(params)

    def _body_common(self, params, labels, w, key, train):
        s_local = labels.shape[1]
        idx = jax.lax.axis_index("data")
        batch = {"label": labels, "pos_offset": idx * s_local}
        out, _ = self.model.apply(params, batch, train=train, sample_weight=w,
                                  rng=jax.random.fold_in(key, idx))
        n_loc = jnp.sum(w)
        return out["loss"] * n_loc, n_loc

    def forward(self, params, labels: jnp.ndarray, key, w=None):
        """Global-mean masked-LM loss over a ``[N, S]`` batch, S sharded."""
        if self._fwd is None:
            def body(params, labels, w, key):
                lsum, n_loc = self._body_common(params, labels, w, key, train=False)
                lsum = jax.lax.psum(lsum, ("clients", "data"))
                n = jax.lax.psum(n_loc, ("clients", "data"))
                return lsum / jnp.maximum(n, 1e-6)

            # staticcheck: allow(jit-needs-donation): inference-only forward;
            # params and batch are caller-owned and reused across calls
            self._fwd = jax.jit(_shard_map(
                body, self.mesh,
                in_specs=(P(), P(None, "data"), P(None, "data"), P()),
                out_specs=P()))
        if w is None:
            w = jnp.ones(labels.shape, jnp.float32)
        return self._fwd(params, labels, w, key)

    def train_step(self, params, opt, labels: jnp.ndarray, key, lr, w=None):
        """One SGD step on a sequence-sharded batch; grads are psum'd."""
        if self._step is None:
            def body(params, opt, labels, w, key, lr):
                def loss_fn(p):
                    lsum, n_loc = self._body_common(p, labels, w, key, train=True)
                    return lsum, n_loc

                (lsum, n_loc), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
                n = jax.lax.psum(n_loc, ("clients", "data"))
                lsum = jax.lax.psum(lsum, ("clients", "data"))
                grads = jax.tree_util.tree_map(
                    lambda g: jax.lax.psum(g, ("clients", "data")) / jnp.maximum(n, 1e-6), grads)
                grads, _ = clip_by_global_norm(grads, 1.0)
                params, opt = self._opt_update(params, grads, opt, lr)
                return params, opt, lsum / jnp.maximum(n, 1e-6)

            # staticcheck: allow(jit-needs-donation): train_step's public
            # contract lets callers keep the previous (params, opt) -- the
            # checkpoint/rollback paths do; donation would delete them
            self._step = jax.jit(_shard_map(
                body, self.mesh,
                in_specs=(P(), P(), P(None, "data"), P(None, "data"), P(), P()),
                out_specs=(P(), P(), P())))
        if w is None:
            w = jnp.ones(labels.shape, jnp.float32)
        return self._step(params, opt, labels, w, key, self._staging.scalar(lr))
