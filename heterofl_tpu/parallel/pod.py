"""Pod-scale probe (ISSUE 17): run the fused grouped-slices superstep on a
REAL multi-process ``jax.distributed`` CPU mesh and prove the pod
contracts without TPU hardware.

The probe is the shared engine behind three consumers:

* ``tests/test_pod.py`` -- the bitwise acceptance gate: a 2-process run
  must produce params AND per-round metrics bit-identical to the
  single-process run of the same program (``slice_align`` pins the same
  host-aligned level partition on both sides), with
  :func:`~..staticcheck.wire.dcn_axes_of` classifying the clients axis as
  DCN from the real process grid and the traced program carrying exactly
  ONE dense reduction per training round, zero reshards.
* ``bench.py BENCH_POD=1`` -- records 2-process rounds/sec and
  per-process checkpoint-write time into ``extra.pod``.
* CI (``tier1.yml``) -- the distributed smoke step drives the same child.

Each child process joins the distributed runtime (coordinator on process
0), builds the (clients, data) mesh over ALL global devices, trains a
K-round fused slices superstep, times a second superstep dispatch, writes
a sharded checkpoint (timed per process), and classifies the traced
program's collectives.  Process 0 persists params/metrics as ``.npz`` for
the bitwise comparison; every process writes its own timing JSON.
"""

from __future__ import annotations

import json
import os
import socket
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional

#: default probe shape: 2 levels so a 2-process mesh hosts one level per
#: process block; 8 users on an 8-row clients axis
PROBE_CONTROL = "1_8_0.5_iid_fix_a1-b1_bn_1_1"
PROBE_USERS = 8


def probe_cfg(control: str = PROBE_CONTROL) -> Dict[str, Any]:
    """The small CPU probe config (mirrors the test suite's ``small_cfg``:
    tiny conv, synthetic MNIST)."""
    from .. import config as C

    cfg = C.default_cfg()
    cfg["control"] = C.parse_control_name(control)
    cfg["data_name"] = "MNIST"
    cfg["model_name"] = "conv"
    cfg = C.process_control(cfg)
    cfg["conv"] = {"hidden_size": [8, 16]}
    cfg["classes_size"] = 10
    return cfg


def probe_data(cfg: Dict[str, Any], users: int = PROBE_USERS):
    """Deterministic synthetic population stacks -- every process builds
    the same host arrays (seed 0), committed to the mesh by staging."""
    import numpy as np

    from ..data import (fetch_dataset, label_split_masks, split_dataset,
                        stack_client_shards)

    ds = fetch_dataset(cfg["data_name"], synthetic=True, seed=0,
                       synthetic_sizes={"train": 400, "test": 100})
    rng = np.random.default_rng(0)  # staticcheck: allow(no-fresh-rng): probe harness data seed, not an engine stream
    split, lsplit = split_dataset(ds, users, cfg["data_split_mode"], rng,
                                  classes_size=10)
    x, y, m = stack_client_shards(ds["train"].data, ds["train"].target,
                                  split["train"], list(range(users)))
    lm = label_split_masks(lsplit, users, 10)
    return x, y, m, lm


def _schedules(cfg, epoch0: int, k: int, num_active: int):
    """Host sampling/rate streams, identical on every process (the same
    folded keys the driver consumes)."""
    import numpy as np

    import jax

    from ..fed.core import round_users

    host_key = jax.random.key(0)
    users = np.stack([
        np.asarray(round_users(jax.random.fold_in(host_key, epoch0 + r),  # staticcheck: allow(no-asarray): host schedule assembly in the probe harness
                               cfg["num_users"], num_active))
        for r in range(k)])
    rates = np.asarray(cfg["model_rate"], np.float32)[users]  # staticcheck: allow(no-asarray): host schedule assembly in the probe harness
    return users, rates


def child_main(out_dir: str, k: int = 4, num_active: int = 4,
               align: int = 0) -> Dict[str, Any]:
    """Runs INSIDE a (possibly distributed) subprocess."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from ..models import make_model
    from ..utils.checkpoint import (dense_from_blocks, is_shard_marker,
                                    load_checkpoint_sharded,
                                    save_checkpoint_sharded)
    from .mesh import initialize_distributed, make_mesh
    from .staging import commit_global, host_fetch
    from ..staticcheck.jaxpr_walk import find_reshards
    from ..staticcheck.wire import dcn_axes_of, program_wire
    from jax.sharding import NamedSharding, PartitionSpec as P

    initialize_distributed()
    pid, n_proc = jax.process_index(), jax.process_count()
    out: Dict[str, Any] = {"process": pid, "processes": n_proc,
                           "devices": len(jax.devices()), "k": k}

    cfg = dict(probe_cfg(), level_placement="slices", strict_placement=True)
    if align:
        cfg["slice_align"] = align
    data_host = probe_data(cfg)
    mesh = make_mesh(len(jax.devices()), 1)
    from .grouped import GroupedRoundEngine

    g = GroupedRoundEngine(cfg, mesh)
    mode, _ = g._fused_layout()
    assert mode == "slices", f"probe needs the slices layout, got {mode}"
    out["slices"] = {str(r): [int(lo), int(hi)]
                     for r, (lo, hi) in g._slices.items()}

    data = tuple(jnp.asarray(a) for a in data_host)  # staticcheck: allow(no-asarray): once-per-run probe staging
    users, rates = _schedules(cfg, 1, k, num_active)
    params = make_model(cfg).init(jax.random.key(0))
    host_key = jax.random.key(0)

    # superstep 1: the probe payload (also the compile warmup)
    p, pend = g.train_superstep(params, host_key, 1, k, users, rates, data)
    ms = pend.fetch()
    # superstep 2: steady-state timing from the updated params
    users2, rates2 = _schedules(cfg, 1 + k, k, num_active)
    t0 = time.perf_counter()  # staticcheck: allow(no-wallclock): probe timing at dispatch boundaries, outside any trace
    p, pend2 = g.train_superstep(p, host_key, 1 + k, k, users2, rates2, data)
    pend2.fetch()
    dt = time.perf_counter() - t0  # staticcheck: allow(no-wallclock): probe timing at dispatch boundaries, outside any trace
    out["rounds_per_sec"] = k / dt
    out["superstep_s"] = dt

    # wire classification against the REAL process grid (aot.py's idiom)
    dcn_axes = dcn_axes_of(mesh)
    out["dcn_axes"] = list(dcn_axes)
    per_dev = None
    for (kk, pd, md, *_rest) in list(g._superstep_progs):
        if kk == k and md == "slices":
            per_dev = pd
    assert per_dev is not None, "slices superstep program not compiled"
    prog = g._superstep_prog(k, per_dev, "slices")
    sched_aval = jax.ShapeDtypeStruct((k, per_dev * mesh.shape["clients"]),
                                      np.int32)
    data_avals = tuple(jax.ShapeDtypeStruct(a.shape, a.dtype) for a in data)
    traced = prog.trace(params, host_key, np.int32(1), sched_aval,
                        *data_avals)
    wire = program_wire(traced.jaxpr, mesh,
                        dcn_axes=dcn_axes if dcn_axes else None)
    reshards = find_reshards(traced.jaxpr)
    out["wire"] = {kk: wire[kk] for kk in
                   ("train_bytes_per_round", "eval_bytes_total",
                    "other_bytes", "dcn_bytes")}
    out["reshards"] = len(reshards)
    out["dcn_one_reduction"] = bool(
        n_proc <= 1 or (wire["dcn_bytes"] == wire["train_bytes_per_round"]
                        and wire["other_bytes"] == 0))

    # per-process checkpoint write: the live blob (replicated params ->
    # header write + barrier) AND a clients-sharded leaf exercising the
    # per-process shard files
    host_params = {n: host_fetch(v) for n, v in p.items()}
    ck = os.path.join(out_dir, "ckpt", "probe.ckpt")
    os.makedirs(os.path.dirname(ck), exist_ok=True)
    t0 = time.perf_counter()  # staticcheck: allow(no-wallclock): probe timing at dispatch boundaries, outside any trace
    save_checkpoint_sharded(ck, {"epoch": k, "params": host_params})
    out["ckpt_write_s"] = time.perf_counter() - t0  # staticcheck: allow(no-wallclock): probe timing at dispatch boundaries, outside any trace

    rng = np.random.default_rng(7)  # staticcheck: allow(no-fresh-rng): synthetic checkpoint payload, not an engine stream
    resid_host = rng.normal(size=(mesh.shape["clients"], 32)).astype(
        np.float32)
    resid = commit_global(resid_host, NamedSharding(mesh, P("clients")))
    cks = os.path.join(out_dir, "ckpt", "probe_sharded.ckpt")
    t0 = time.perf_counter()  # staticcheck: allow(no-wallclock): probe timing at dispatch boundaries, outside any trace
    save_checkpoint_sharded(cks, {"epoch": k, "resid": resid})
    out["ckpt_shard_write_s"] = time.perf_counter() - t0  # staticcheck: allow(no-wallclock): probe timing at dispatch boundaries, outside any trace
    loaded = load_checkpoint_sharded(cks)
    back = loaded["resid"]
    if is_shard_marker(back):
        back = dense_from_blocks(back)
    out["sharded_ckpt_ok"] = bool(np.array_equal(np.asarray(back),  # staticcheck: allow(no-asarray): probe result check
                                                 resid_host))

    if pid == 0:
        np.savez(os.path.join(out_dir, "params.npz"), **host_params)
        flat = {f"r{r}_{name}": np.asarray(v)  # staticcheck: allow(no-asarray): probe result persistence
                for r, md in enumerate(ms) for name, v in md.items()}
        np.savez(os.path.join(out_dir, "metrics.npz"), **flat)
    with open(os.path.join(out_dir, f"pod_result_p{pid}.json"), "w") as f:
        json.dump(out, f, sort_keys=True)
    return out


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_pod_probe(out_dir: str, n_processes: int = 2,
                  local_devices: int = 4, k: int = 4, num_active: int = 4,
                  align: int = 0, timeout_s: int = 900) -> List[Dict[str, Any]]:
    """Spawn ``n_processes`` probe children over a shared coordinator and
    return their result dicts (index = process id).  ``n_processes=1``
    runs the single-process reference (no distributed runtime); pass
    ``align=<pod process count>`` there to pin the SAME host-aligned level
    partition the pod run takes -- the bitwise comparison needs identical
    slice boundaries."""
    os.makedirs(out_dir, exist_ok=True)
    env = dict(os.environ)
    for v in ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
              "AXON_LOOPBACK_RELAY", "AXON_POOL_SVC_OVERRIDE"):
        env.pop(v, None)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + f" --xla_force_host_platform_device_count="
                          f"{local_devices}").strip()
    repo = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    env["PYTHONPATH"] = repo + (os.pathsep + env["PYTHONPATH"]
                                if env.get("PYTHONPATH") else "")
    # the reference n_processes=1 run joins the distributed runtime too:
    # the gloo collectives layer fixes the reduction ASSOCIATION by global
    # device rank, so a 1-process gloo run is bit-identical to the
    # N-process one -- XLA's in-process allreduce associates differently
    # (1-2 f32 ULPs), which is exactly the gap the bitwise gate closes
    env["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{_free_port()}"
    env["JAX_NUM_PROCESSES"] = str(n_processes)
    argv = [sys.executable, "-m", "heterofl_tpu.parallel.pod", out_dir,
            "--k", str(k), "--active", str(num_active)]
    if align:
        argv += ["--align", str(align)]
    procs = []
    for i in range(n_processes):
        e = dict(env)
        e["JAX_PROCESS_ID"] = str(i)
        procs.append(subprocess.Popen(argv, env=e, text=True,
                                      stdout=subprocess.PIPE,
                                      stderr=subprocess.PIPE))
    outs = []
    for i, pr in enumerate(procs):
        try:
            so, se = pr.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            for p2 in procs:
                p2.kill()
            raise RuntimeError(f"pod probe process {i} timed out after "
                               f"{timeout_s}s")
        if pr.returncode != 0:
            raise RuntimeError(f"pod probe process {i} failed "
                               f"(rc={pr.returncode}):\n{se[-3000:]}")
        outs.append((so, se))
    results = []
    for i in range(n_processes):
        with open(os.path.join(out_dir, f"pod_result_p{i}.json")) as f:
            results.append(json.load(f))
    return results


def bitwise_match(dir_a: str, dir_b: str) -> Dict[str, Any]:
    """Compare two probe output dirs' ``params.npz`` + ``metrics.npz``
    bit for bit.  Returns ``{"match": bool, "mismatches": [...]}``."""
    import numpy as np

    mismatches = []
    for fname in ("params.npz", "metrics.npz"):
        a = np.load(os.path.join(dir_a, fname))
        b = np.load(os.path.join(dir_b, fname))
        if sorted(a.files) != sorted(b.files):
            mismatches.append(f"{fname}: key sets differ")
            continue
        for kk in a.files:
            if not np.array_equal(a[kk], b[kk]):
                mismatches.append(f"{fname}:{kk}")
    return {"match": not mismatches, "mismatches": mismatches}


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("out_dir")
    ap.add_argument("--k", type=int, default=4)
    ap.add_argument("--active", type=int, default=4)
    ap.add_argument("--align", type=int, default=0)
    a = ap.parse_args()
    res = child_main(a.out_dir, k=a.k, num_active=a.active, align=a.align)
    print(json.dumps(res, sort_keys=True))
