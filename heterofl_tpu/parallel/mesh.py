"""Device mesh construction.

The communication backend of the framework: clients are laid out along a
``clients`` mesh axis (federated aggregation = ``psum`` over ICI), with an
optional ``data`` axis for intra-client batch / eval-set data parallelism.
This replaces the reference's in-process deepcopy "communication"
(ref src/fed.py:165-178 and SURVEY §2.4) with real XLA collectives.
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh


def make_mesh(n_clients: Optional[int] = None, n_data: int = 1,
              devices: Optional[Sequence[jax.Device]] = None,
              n_arms: int = 1) -> Mesh:
    """Build a ``(clients, data)`` mesh -- or ``(arms, clients, data)``
    with ``n_arms > 1`` (ISSUE 14: the ``experiments`` mesh dimension).

    ``n_clients=None`` uses all devices (divided by ``n_data`` and
    ``n_arms``).  On a single chip this degenerates to a 1x1 mesh and the
    collectives become no-ops -- same program, any scale.  The arms axis
    places each experiment arm's whole federation on its own disjoint
    device rows: the per-arm ``psum`` over ``clients`` reduces within an
    arm's rows only, so E arms execute CONCURRENTLY on a mesh a single
    arm cannot fill (the engines' ``arms_placement='mesh'``).
    """
    devices = list(devices if devices is not None else jax.devices())
    n_arms = max(1, int(n_arms))
    if n_clients is None:
        assert len(devices) % (n_data * n_arms) == 0, \
            "device count not divisible by data x arms axes"
        n_clients = len(devices) // (n_data * n_arms)
    need = n_clients * n_data * n_arms
    if need > len(devices):
        raise ValueError(f"need {need} devices, have {len(devices)}")
    if n_arms > 1:
        arr = np.array(devices[:need]).reshape(n_arms, n_clients, n_data)
        return Mesh(arr, ("arms", "clients", "data"))
    arr = np.array(devices[:need]).reshape(n_clients, n_data)
    return Mesh(arr, ("clients", "data"))


def initialize_distributed() -> bool:
    """Multi-host bring-up: join the JAX distributed runtime when coordinator
    env vars are present, so ``jax.devices()`` spans all hosts and
    :func:`make_mesh` lays the ``clients``/``data`` axes over ICI within a
    slice and DCN across slices (XLA routes collectives accordingly).

    Reads the standard ``JAX_COORDINATOR_ADDRESS`` / ``JAX_NUM_PROCESSES`` /
    ``JAX_PROCESS_ID`` variables (no-op when absent -- single-host runs and
    TPU pod auto-detection need nothing), plus optional
    ``JAX_LOCAL_DEVICE_IDS`` (comma-separated).  Returns True if initialised.
    """
    import os

    addr = os.environ.get("JAX_COORDINATOR_ADDRESS")
    if not addr:
        return False
    import jax as _jax

    # jax.distributed.initialize() only auto-detects num_processes/process_id
    # under a recognised cluster scheduler (SLURM & co.); on a hand-launched
    # pod the documented env vars must be forwarded explicitly -- and must be
    # set *together*: a half-specified pair fails deep inside the runtime with
    # a confusing error, so validate here
    num = os.environ.get("JAX_NUM_PROCESSES")
    pid = os.environ.get("JAX_PROCESS_ID")
    if (num is None) != (pid is None):
        raise RuntimeError(
            "JAX_NUM_PROCESSES and JAX_PROCESS_ID must be set together "
            f"(got JAX_NUM_PROCESSES={num!r}, JAX_PROCESS_ID={pid!r})")
    local = os.environ.get("JAX_LOCAL_DEVICE_IDS")
    # the XLA:CPU backend runs cross-process collectives only through an
    # explicit collectives layer (gloo); without it every multi-process
    # dispatch dies with "Multiprocess computations aren't implemented on
    # the CPU backend" -- select it before the backend initialises (the
    # 2-process CPU probe, tests/test_pod.py; harmless for TPU runs where
    # the platform is not cpu)
    if os.environ.get("JAX_PLATFORMS", "").strip() == "cpu":
        _jax.config.update("jax_cpu_collectives_implementation", "gloo")
    _jax.distributed.initialize(
        coordinator_address=addr,
        num_processes=int(num) if num is not None else None,
        process_id=int(pid) if pid is not None else None,
        local_device_ids=[int(x) for x in local.split(",")] if local else None,
    )
    return True
