"""Sweep front-end (ISSUE 14): grid spec -> arm batches x structural
launches -> multiplexed runs.

``python -m heterofl_tpu.multi.sweep --grid '{"seed": [0,1,2,3], "lr":
[0.1, 0.03], "wire_codec": ["dense", "int8"]}'`` replaces the reference's
process-grid shape (``make.py`` spawning one process per cell): the grid
partitions into

* **arm axes** -- ``seed`` (accepted alias ``init_seed``) and ``lr``:
  trace-compatible knobs that vary per arm INSIDE one fused program
  (per-arm PRNG streams / LR scales over the shared schedule shape); the
  cross product of arm-axis values becomes E arms, chunked at
  ``--max_arms`` per launch;
* **structural axes** -- every other grid key (``wire_codec``,
  ``strategy``, ``superstep_rounds``, ...): knobs that key program
  structure, each combination its own launch with its own compile.

A cell of the reference grid that took one process, one compile and one
under-filled mesh now shares all three with every trace-compatible
sibling.  The data split and staged population are per-launch (structural
by construction -- one committed population serves every arm); per-arm
``seed`` values vary the arms' init/training streams, not the split.

Every launch runs :class:`~..entry.common.ArmsExperiment` (per-arm
checkpoints, per-arm ``{"tag": "arms"}`` log lines, per-arm Plateau
state) under its own ``{output_dir}/launch{i:03d}`` root -- launches
share model tags, so the per-launch subdirectory is what keeps their
checkpoints, logs and resume blobs apart.  ``--dry_run 1`` prints the
partition without running.
"""

from __future__ import annotations

import copy
import itertools
import json
import os
import sys
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import MAX_ARMS

#: grid keys that become per-arm variation inside one program; everything
#: else in the grid is structural (one launch per value combination)
ARM_AXES = ("seed", "init_seed", "lr")


def partition_grid(grid: Dict[str, Sequence[Any]], max_arms: int = 8
                   ) -> List[Tuple[Dict[str, Any], List[Tuple[Optional[int],
                                                              Optional[float]]]]]:
    """Partition a grid spec into ``(structural overrides, arm batch)``
    launches.

    Each arm batch is a list of ``(seed, lr)`` pairs (either element may
    be ``None`` when the grid has no such axis) -- the cross product of
    the arm axes, chunked to ``max_arms``.  Structural launches are the
    cross product of every other key.  Deterministic order (sorted keys,
    given value order), so a sweep is resumable by launch index."""
    if not isinstance(grid, dict) or not grid:
        raise ValueError(f"Not valid grid: {grid!r} (a non-empty dict of "
                         f"cfg-key -> list of values)")
    if not 1 <= max_arms <= MAX_ARMS:
        raise ValueError(f"Not valid max_arms: {max_arms!r} (1..{MAX_ARMS})")
    grid = {k: list(v) for k, v in grid.items()}
    for k, v in grid.items():
        if not v:
            raise ValueError(f"Not valid grid axis {k!r}: empty value list")
    if "seed" in grid and "init_seed" in grid:
        raise ValueError("grid names both 'seed' and 'init_seed' (aliases "
                         "of the same arm axis): pick one")
    seeds = grid.pop("seed", None) or grid.pop("init_seed", None) or [None]
    lrs = grid.pop("lr", [None])
    for s in seeds:
        if s is not None and (not isinstance(s, int) or isinstance(s, bool)
                              or s < 0):
            raise ValueError(f"Not valid grid seed: {s!r} (a non-negative "
                             f"int)")
    for lr in lrs:
        if lr is not None and (not isinstance(lr, (int, float))
                               or isinstance(lr, bool) or not lr > 0):
            raise ValueError(f"Not valid grid lr: {lr!r} (a positive "
                             f"number)")
    arm_combos = [(s, lr) for s in seeds for lr in lrs]
    keys = sorted(grid)
    structural = [dict(zip(keys, vals))
                  for vals in itertools.product(*(grid[k] for k in keys))] \
        if keys else [{}]
    launches = []
    for struct in structural:
        for i in range(0, len(arm_combos), max_arms):
            launches.append((struct, arm_combos[i:i + max_arms]))
    return launches


def launch_cfg(base_cfg: Dict[str, Any], idx: int, struct: Dict[str, Any],
               batch: List[Tuple[Optional[int], Optional[float]]]
               ) -> Dict[str, Any]:
    """The processed cfg of launch ``idx``: structural overrides applied,
    the arm batch resolved, and the launch's OWN output root
    (``{output_dir}/launch{idx:03d}``).  The subdirectory is load-bearing:
    ``make_model_tag`` ignores structural keys, so sibling launches share
    checkpoint/log tags -- a flat output_dir would clobber each other's
    per-arm checkpoints and cross-resume from the wrong launch's blob."""
    from .. import config as C
    cfg = copy.deepcopy(base_cfg)
    for k, v in struct.items():
        cfg[k] = v  # keys validated up front, before any launch ran
    cfg["output_dir"] = os.path.join(base_cfg.get("output_dir") or ".",
                                     f"launch{idx:03d}")
    cfg = C.process_control(cfg)
    cfg["arms"] = arms_cfg_of(cfg, batch)
    return cfg


def arms_cfg_of(cfg: Dict[str, Any],
                batch: List[Tuple[Optional[int], Optional[float]]]
                ) -> Dict[str, Any]:
    """The ``cfg['arms']`` dict of one arm batch AGAINST a processed cfg:
    seeds pass through (``None`` = the identity arm -- a pure-LR sweep
    shares the base stream), LR values become multiplicative scales over
    the launch's resolved ``cfg['lr']`` (the shared schedule shape)."""
    base_lr = float(cfg["lr"])
    return {"count": len(batch),
            "seeds": [s for s, _ in batch],
            "lr_scales": [1.0 if lr is None else float(lr) / base_lr
                          for _, lr in batch]}


def describe_launch(idx: int, struct: Dict[str, Any],
                    batch: List[Tuple[Optional[int], Optional[float]]]) -> str:
    arms = ", ".join(f"(seed={s}, lr={lr})" for s, lr in batch)
    return (f"launch {idx}: structural={struct or '{}'} "
            f"E={len(batch)} arms=[{arms}]")


def main(argv: Optional[List[str]] = None) -> int:
    # import here: the CLI shares the entry layer's flag surface, and the
    # entry chain boots jax -- keep `import heterofl_tpu.multi` jax-free
    from .. import config as C
    from ..entry.common import ArmsExperiment, build_cli, cfg_from_args

    parser = build_cli("HeteroFL experiment-arms sweep: E grid cells per "
                       "fused superstep program (ISSUE 14)")
    parser.add_argument("--grid", default=None, type=str,
                        help="JSON grid spec: {cfg_key: [values, ...]}; "
                             "'seed'/'init_seed' and 'lr' become arms, "
                             "everything else structural launches")
    parser.add_argument("--grid_file", default=None, type=str,
                        help="path to a JSON grid spec (overrides --grid)")
    parser.add_argument("--max_arms", default=8, type=int,
                        help=f"arms per launch (1..{MAX_ARMS})")
    parser.add_argument("--dry_run", default=0, type=int,
                        help="1 = print the partition and exit")
    parser.add_argument("--pivot_metric", default="Global-Accuracy", type=str)
    parser.add_argument("--pivot_mode", default="max", type=str)
    args = parser.parse_args(argv)
    if args.grid_file:
        with open(args.grid_file) as f:
            grid = json.load(f)
    elif args.grid:
        grid = json.loads(args.grid)
    else:
        parser.error("--grid or --grid_file is required")
    base_cfg = cfg_from_args(args)
    # validate structural keys UP FRONT (and under --dry_run): a typo'd
    # key must fail before the first launch burns its compile + run, not
    # mid-sweep after earlier launches already completed
    for k in grid if isinstance(grid, dict) else ():
        if k not in ARM_AXES and k not in C.DEFAULT_CFG:
            raise ValueError(f"Not valid structural grid key: {k!r} "
                             f"(a DEFAULT_CFG key; control-string "
                             f"fields go through --control_name)")
    launches = partition_grid(grid, max_arms=args.max_arms)
    for i, (struct, batch) in enumerate(launches):
        print(describe_launch(i, struct, batch))
    if args.dry_run:
        return 0
    for i, (struct, batch) in enumerate(launches):
        cfg = launch_cfg(base_cfg, i, struct, batch)
        print(f"sweep: running {describe_launch(i, struct, batch)} -> "
              f"{cfg['output_dir']}")
        exp = ArmsExperiment(cfg, cfg["init_seed"])
        exp.run(args.pivot_metric, args.pivot_mode)
    return 0


if __name__ == "__main__":
    sys.exit(main())
