"""Experiment arms multiplexer (ISSUE 14 tentpole): E sweep arms fused
into ONE superstep program.

The reference's top layer (``make.py``) launches sweep grids as separate
processes -- one compile, one dispatch, one under-filled mesh per arm.
This package batches E **experiment arms** into a single fused K-round
superstep: the engines vmap the scan step over a leading ``[E]`` arms
axis, so one XLA program trains E trajectories per dispatch, the batched
counted-average reduction stays EXACTLY one global psum bind per fused
round (a vmapped pytree psum is still one bind; wire bytes and FLOPs
scale linearly in E -- audited by equality in staticcheck's arms
variants), and the per-round metrics come back stacked ``[E, K, ...]``
through the one PendingMetrics fetch.

**Trace-compatible vs structural knobs.**  An arm may vary anything that
enters the compiled program as *data*:

* **seed streams** -- each arm owns a PRNG stream derived by
  ``fold_in(base_key, seed)`` (:func:`~..fed.core.arm_stream_keys`);
  under the masked engine's in-jit draw each arm samples its own cohort,
  rolls its own dynamic rates, its own deadline budgets and failure
  draws from that stream (``seed=None`` is the identity arm: it consumes
  the base stream itself, which is what makes ``arms=1`` bit-identical
  to the unbatched program);
* **LR schedules** -- per-arm multiplicative scales over the shared
  schedule *shape* (``lr_scales``), or per-arm staged LR scalars under
  ReduceLROnPlateau (each arm steps its own plateau state at superstep
  boundaries).

Everything that keys program *structure* -- engine/strategy, placement,
codec choice, schedule kind, K, the model -- stays per-program: a sweep
over a structural knob is a separate launch (:mod:`.sweep` partitions a
grid into trace-compatible arm batches x structural launches).
Unsupported combinations refuse loudly instead of silently degrading:
the sliced strategy, per-level codec maps, buffered-async aggregation,
the streaming client store and grouped-slices placement have carries or
host bookkeeping that do not batch yet (ROADMAP follow-ons).

This module is import-light (no jax): :func:`resolve_arms_cfg` is THE
one validator of ``cfg['arms']`` (the ``sched``/``obs`` convention --
``config.process_control`` applies it and the engines re-apply it); the
jax half (per-arm key derivation) lives in ``fed/core.py`` next to the
other stream definitions.
"""

from __future__ import annotations

from typing import Any, Dict, Optional, Tuple

#: hard ceiling on arms per program: the arms axis multiplies program
#: FLOPs, wire bytes and the params/metrics footprint linearly, and a
#: fatter batch than this is better served by a second structural launch
MAX_ARMS = 64


class ArmsSpec:
    """Resolved arms configuration (one immutable object, the
    ScheduleSpec convention).

    ``count``: E >= 1.  ``seeds``: per-arm stream seeds -- ints folded
    into the superstep base key, or ``None`` for the identity arm that
    consumes the base stream itself (the ``arms=1`` default, which is
    what the E=1 == unbatched bitwise contract rides on).  ``lr_scales``:
    per-arm multiplicative factors over the shared LR schedule."""

    def __init__(self, count: int, seeds: Tuple[Optional[int], ...],
                 lr_scales: Tuple[float, ...]):
        self.count = int(count)
        self.seeds = tuple(seeds)
        self.lr_scales = tuple(float(s) for s in lr_scales)

    def __repr__(self):  # pragma: no cover - debugging aid
        return (f"ArmsSpec(count={self.count}, seeds={self.seeds}, "
                f"lr_scales={self.lr_scales})")

    def __eq__(self, other):
        return (isinstance(other, ArmsSpec) and self.count == other.count
                and self.seeds == other.seeds
                and self.lr_scales == other.lr_scales)

    def __hash__(self):
        return hash((self.count, self.seeds, self.lr_scales))

    def solo(self, i: int) -> "ArmsSpec":
        """The single-arm spec of arm ``i``: the solo run the arm-vs-solo
        equivalence contract compares against."""
        return ArmsSpec(1, (self.seeds[i],), (self.lr_scales[i],))


def default_seeds(count: int) -> Tuple[Optional[int], ...]:
    """Default per-arm stream seeds: arm 0 is the identity arm (the base
    stream, ``None``), arms 1..E-1 fold in their index."""
    return (None,) + tuple(range(1, count))


def resolve_arms_cfg(cfg: Dict[str, Any]) -> Optional[ArmsSpec]:
    """Validate ``cfg['arms']`` and return the :class:`ArmsSpec` (or
    ``None`` when arms are off).

    THE one validator (the PR 6/8/9 convention): malformed counts, seed
    or scale vectors fail loudly at config time, never as a silent
    single-arm fallback mid-run.  Accepted forms::

        "arms": None          # off (default)
        "arms": 4             # E=4, default seeds (None,1,2,3), unit scales
        "arms": {"count": 4,
                 "seeds": [None, 7, 11, 13],     # optional
                 "lr_scales": [1.0, 0.3, 3.0, 1.0]}  # optional

    Cross-field conflicts (strategy/codec/schedule/store) ALSO refuse
    here (ISSUE 18: one validator per axis is the lattice's source of
    truth); the engines and drivers keep their checks as
    defense-in-depth for direct construction."""
    raw = cfg.get("arms")
    if raw is None:
        return None
    if isinstance(raw, bool):
        raise ValueError(f"Not valid arms: {raw!r} (an int count, a dict, "
                         f"or None)")
    if isinstance(raw, int):
        raw = {"count": raw}
    if not isinstance(raw, dict):
        raise ValueError(f"Not valid arms: {raw!r} (an int count, a dict "
                         f"with count/seeds/lr_scales, or None)")
    unknown = set(raw) - {"count", "seeds", "lr_scales"}
    if unknown:
        raise ValueError(f"Not valid arms keys: {sorted(unknown)} "
                         f"(count/seeds/lr_scales)")
    count = raw.get("count")
    if not isinstance(count, int) or isinstance(count, bool) or count < 1:
        raise ValueError(f"Not valid arms count: {count!r} (an int >= 1)")
    if count > MAX_ARMS:
        raise ValueError(f"Not valid arms count: {count} exceeds MAX_ARMS="
                         f"{MAX_ARMS}; split the sweep into several "
                         f"structural launches (multi.sweep does)")
    seeds = raw.get("seeds")
    if seeds is None:
        seeds = default_seeds(count)
    else:
        seeds = tuple(seeds)
        if len(seeds) != count:
            raise ValueError(f"Not valid arms seeds: {len(seeds)} entries "
                             f"for count={count} (one per arm)")
        for s in seeds:
            if s is not None and (not isinstance(s, int)
                                  or isinstance(s, bool) or s < 0):
                raise ValueError(f"Not valid arm seed: {s!r} (a "
                                 f"non-negative int, or None for the "
                                 f"identity arm)")
    scales = raw.get("lr_scales")
    if scales is None:
        scales = (1.0,) * count
    else:
        scales = tuple(scales)
        if len(scales) != count:
            raise ValueError(f"Not valid arms lr_scales: {len(scales)} "
                             f"entries for count={count} (one per arm)")
        for s in scales:
            if not isinstance(s, (int, float)) or isinstance(s, bool) \
                    or not s > 0.0:
                raise ValueError(f"Not valid arm lr_scale: {s!r} (a "
                                 f"positive number)")
    # arms x everything cross-checks (ISSUE 18): promoted from the driver
    # and the engine constructors so an un-batchable arms config refuses
    # at config resolution.  This validator OWNS the arms axis in the
    # staticcheck lattice; each refusal below names the ROADMAP follow-on
    # that would lift it.
    strategy = cfg.get("strategy", "masked") or "masked"
    if strategy == "sliced":
        raise ValueError(
            "Not valid arms with strategy='sliced': the sliced debug twin "
            "replays the reference host loop one trajectory at a time -- "
            "use a mesh-native strategy ('masked' or 'grouped')")
    if (cfg.get("ledger", "off") or "off") == "on":
        raise ValueError(
            "Not valid arms with ledger='on': the O(active) fold consumes "
            "ONE sampling stream's cohort rows, and each arm draws its own "
            "(a ROADMAP follow-on)")
    if cfg.get("trace_dir"):
        raise ValueError(
            "Not valid arms with trace_dir: the multiplexed loop does not "
            "build the TraceRecorder, so the trace would be silently empty "
            "(a ROADMAP follow-on; per-arm probes/watchdog DO run)")
    if ((cfg.get("schedule") or {}).get("aggregation") or "sync") \
            == "buffered":
        raise ValueError(
            "Not valid arms with schedule aggregation='buffered': the "
            "staleness buffer is a replicated carry with its own "
            "donation/checkpoint contract -- batch dense-sync arms or run "
            "buffered solo")
    if (cfg.get("client_store", "eager") or "eager") == "stream":
        raise ValueError(
            "Not valid arms with client_store='stream': the streaming "
            "cohort pipeline stages ONE schedule's shards per superstep "
            "(a ROADMAP follow-on)")
    if strategy == "grouped":
        codec = cfg.get("wire_codec", "dense") or "dense"
        if isinstance(codec, dict) and all(v == "dense"
                                           for v in codec.values()):
            codec = "dense"
        if codec != "dense":
            raise ValueError(
                f"Not valid arms with wire_codec={codec!r} under strategy="
                f"'grouped': the grouped EF-residual carry does not batch "
                f"over the arms axis yet (a ROADMAP follow-on) -- grouped "
                f"arms need the dense wire codec, or use the masked engine "
                f"for codec arms")
        if (cfg.get("telemetry", "off") or "off") != "off":
            raise ValueError(
                "Not valid arms with telemetry on under strategy="
                "'grouped': the span probe rows do not carry the arms "
                "axis yet (a ROADMAP follow-on); the masked engine "
                "supports telemetry x arms")
        if (cfg.get("quarantine", "off") or "off") != "off":
            raise ValueError(
                "Not valid arms with quarantine on under strategy="
                "'grouped': the quarantine counter rides the probe rows, "
                "which do not carry the arms axis yet (a ROADMAP "
                "follow-on); the masked engine supports quarantine x arms")
        if (cfg.get("level_placement", "span") or "span") == "slices":
            raise ValueError(
                "Not valid arms with level_placement='slices': the slices "
                "layout dispatches each level to its own device rows, and "
                "the arms axis would have to batch across disjoint "
                "sub-meshes (a ROADMAP follow-on) -- arms need "
                "level_placement='span'")
    return ArmsSpec(count, seeds, scales)
